"""Per-lane divergent RLE replay: B distinct documents, B distinct ops
per kernel step.

The blocked engines batch IDENTICAL docs in the lane dim (`_lane_scalar`
collapses lanes into one control stream), so divergent small docs — the
config-5 streaming shape — fell back to ``ops.flat``'s one-XLA-dispatch-
per-step scan (r2 verdict weak #4). This engine removes the identical-
lane assumption instead of the batching:

- every document is ONE un-blocked run column (``CAP`` run rows packed
  at the front) — config-5 docs are hundreds of runs, so the in-block
  position scan covers the whole doc and the block machinery (descent,
  splits, windows) disappears;
- every op scalar of the blocked engines (``i_r``, ``off``, splice
  shift, …) becomes a ``[1, B]`` lane VECTOR; the splice shift is ≤2
  rows regardless of text length (the RLE insert property), so per-lane
  dynamic shifts are two static ``pltpu.roll``s blended by per-lane
  masks — the trick that makes divergence free;
- a delete needs NO walk: the whole doc is in view, so one
  flip+boundary-split pass retires any span (`mutations.rs:520-570`);
- state planes are kernel INPUTS as well as outputs — chunk N+1 resumes
  from chunk N's downloaded (or never-downloaded) state, the warm start
  the blocked engines lack (r2 verdict weak #4/#5: "blocked engines only
  cold-start").

Per step the kernel applies B independent ops (one per lane), so wall
per op is ~1/B of a blocked-engine step on the same shapes. Local ops
only (KIND_LOCAL); per-lane REMOTE streams run on the unified
``ops.rle_lanes_mixed`` engine built on these same primitives.
"""
from __future__ import annotations

import dataclasses
import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..common import ROOT_ORDER
from .batch import (
    KIND_LOCAL,
    OpTensors,
    fused_width,
    fused_width_checked,
    merge_fused_origins,
    prefill_logs,
)
from .blocked import _require
from .rle import fused_splice_rows
from .span_arrays import FlatDoc, I32, U32, make_flat_doc


def _vcumsum(x) -> jax.Array:
    """Inclusive cumsum along rows (axis 0) via log2 roll-adds."""
    n = x.shape[0]
    row = lax.broadcasted_iota(jnp.int32, x.shape, 0)
    out = x
    shift = 1
    while shift < n:
        out = out + jnp.where(row >= shift, pltpu.roll(out, shift, axis=0), 0)
        shift *= 2
    return out


def _vrow(arr, r):
    """Per-lane row extraction: ``arr[r[0, b], b]`` as a [1, B] vector."""
    idx = lax.broadcasted_iota(jnp.int32, arr.shape, 0)
    return jnp.sum(jnp.where(idx == r, arr, 0), axis=0, keepdims=True)


def _vshift(x, amt, max_amt: int = 2):
    """Rows shifted down by per-lane ``amt`` in [0, max_amt] ([1, B]):
    one static roll per bit, selected per lane (the down-shift twin of
    ``lane_blocks.vshift_up``).  ``max_amt`` defaults to the plain-
    splice bound (2: new run + split tail); fused W-row splices pass
    their static ``WMAX + 1``."""
    n = x.shape[0]
    out = x
    for bit in range(max(max_amt, 1).bit_length()):
        s = (1 << bit) % n
        if s:
            out = jnp.where((amt >> bit) & 1 != 0,
                            pltpu.roll(out, s, axis=0), out)
    return out


def _live_prefix(bo, bl):
    """(lv, cum): live char counts per run row and their inclusive
    prefix — the most expensive pass of a step (log2(CAP) roll-adds)."""
    lv = jnp.where(bo > 0, bl, 0)
    return lv, _vcumsum(lv)


def _shared_cum_gate(step_has_del, step_has_ins, s_pad: int) -> bool:
    """Hoist one live prefix per step iff it pays: sound only when no
    lane deletes AND inserts in the same step (callers check that
    separately), and worth it only when steps running BOTH branches
    (two cumsums -> one) outnumber steps running NEITHER (zero
    cumsums -> one: remote-only or padding steps)."""
    both = int((step_has_del & step_has_ins).sum())
    neither = int((~(step_has_del | step_has_ins)).sum())
    neither += s_pad - len(step_has_del)  # padded no-op steps
    return both > neither


def _rle_lanes_kernel(
    pos_ref, dlen_ref, ilen_ref, start_ref,     # [CHUNK,B] VMEM op columns
    w_ref,                                      # [CHUNK,B] rows_per_step
    ord0_ref, len0_ref, rows0_ref,              # warm-start state inputs
    ol_ref, or_ref,                             # [CHUNK,B] outputs
    ordp, lenp, rowsv, err_ref,                 # state outputs (working)
    *, CAP: int, CHUNK: int, WMAX: int = 1, SHARED_CUM: bool = False,
):
    B = ordp.shape[1]
    # Grid = (lane blocks, chunks): lanes are independent documents, so
    # wide batches tile the lane axis (a 2048-lane whole-array kernel
    # spills ~105MB of registers and fails to compile); each lane block
    # runs ALL its chunks before the next block starts, preserving the
    # chunk-sequential state contract per lane.
    i = pl.program_id(1)
    idx = lax.broadcasted_iota(jnp.int32, (CAP, B), 0)
    root_u = jnp.uint32(ROOT_ORDER)

    ol_ref[:] = jnp.zeros_like(ol_ref)
    or_ref[:] = jnp.zeros_like(or_ref)

    @pl.when(i == 0)
    def _init():
        ordp[:] = ord0_ref[:]
        lenp[:] = len0_ref[:]
        rowsv[:] = rows0_ref[:]
        err_ref[:] = jnp.zeros_like(err_ref)

    def do_delete(p, d, lv=None, cum=None):
        """Whole-doc single-pass delete, per-lane (active where d > 0).
        ``lv``/``cum`` may be the step-hoisted live prefix (see
        ``op_body``); the delete runs first, so they are always fresh."""
        active = d > 0
        rows = rowsv[:]

        @pl.when(jnp.any(active & (rows + 2 > CAP)))
        def _cap():
            err_ref[0:1, :] = jnp.where(active & (rows + 2 > CAP), 1,
                                        err_ref[0:1, :])

        bo = ordp[:]
        bl = lenp[:]
        if cum is None:
            lv, cum = _live_prefix(bo, bl)
        before = cum - lv
        rem = jnp.where(active, d, 0)
        cs = jnp.clip(p - before, 0, lv)
        ce = jnp.clip(p + rem - before, 0, lv)
        cov = ce - cs
        tot = jnp.sum(cov, axis=0, keepdims=True)

        @pl.when(jnp.any(active & (tot < rem)))
        def _bad():
            err_ref[1:2, :] = jnp.where(active & (tot < rem), 1,
                                        err_ref[1:2, :])

        full = (cov > 0) & (cov == bl)
        part = (cov > 0) & jnp.logical_not(full)
        npart = jnp.sum(part.astype(jnp.int32), axis=0, keepdims=True)
        i1 = jnp.min(jnp.where(part, idx, CAP), axis=0, keepdims=True)
        i2 = jnp.max(jnp.where(part, idx, -1), axis=0, keepdims=True)

        bo = jnp.where(full, -bo, bo)

        def apply_partial(act, i_p, bo, bl):
            o = _vrow(bo, i_p)
            ln = _vrow(bl, i_p)
            cs_i = _vrow(cs, i_p)
            ce_i = _vrow(ce, i_p)
            cov_i = ce_i - cs_i
            has_head = (cs_i > 0) & act
            has_tail = (ce_i < ln) & act
            amt = has_head.astype(jnp.int32) + has_tail.astype(jnp.int32)
            so = _vshift(bo, amt)
            sl = _vshift(bl, amt)
            no = jnp.where(idx <= i_p, bo, so)
            nl = jnp.where(idx <= i_p, bl, sl)
            p0o = jnp.where(has_head, o, -(o + cs_i))
            p0l = jnp.where(has_head, cs_i, cov_i)
            p1o = jnp.where(has_head, -(o + cs_i), o + ce_i)
            p1l = jnp.where(has_head, cov_i, ln - ce_i)
            w0 = act & (idx == i_p)
            no = jnp.where(w0, p0o, no)
            nl = jnp.where(w0, p0l, nl)
            w1 = act & (idx == i_p + 1) & (amt >= 1)
            no = jnp.where(w1, p1o, no)
            nl = jnp.where(w1, p1l, nl)
            w2 = act & (idx == i_p + 2) & (amt == 2)
            no = jnp.where(w2, o + ce_i, no)
            nl = jnp.where(w2, ln - ce_i, nl)
            return no, nl, amt

        bo, bl, a2 = apply_partial(active & (npart >= 1), i2, bo, bl)
        bo, bl, a1 = apply_partial(active & (npart == 2), i1, bo, bl)
        ordp[:] = bo
        lenp[:] = bl
        rowsv[:] = rowsv[:] + jnp.where(active, a1 + a2, 0)

    def do_insert(k, p, il, st, w, lv=None, cum=None):
        """Per-lane insert splice (active where il > 0).

        ``w`` > 1 is a FUSED backwards-burst step (``rows_per_step``):
        W run rows of stride ``L = il // w`` land in ONE shift — row j
        of the spliced window holds orders ``st + il - (j+1)*L`` (patch
        order DESCENDS in document order), the ``ops.rle``
        ``_insert_splice`` contract.  ``w == 1`` is exactly the old
        splice.  The in-place merge stays w==1-only (a burst's first
        patch merging would be un-done by its second patch's split).

        ``lv``/``cum`` may be the step-hoisted PRE-DELETE live prefix:
        valid for this branch's active lanes because the shared-cum
        mode statically guarantees no lane deletes AND inserts in the
        same step, so an insert-active lane's column was untouched by
        the delete branch.  ``bo``/``bl`` are always read FRESH —
        the transform writes whole planes and must preserve the delete
        branch's results on the OTHER lanes."""
        active = il > 0
        rows = rowsv[:]

        @pl.when(jnp.any(active & (rows + w + 1 > CAP)))
        def _cap():
            err_ref[0:1, :] = jnp.where(active & (rows + w + 1 > CAP), 1,
                                        err_ref[0:1, :])

        bo = ordp[:]
        bl = lenp[:]
        if cum is None:
            lv, cum = _live_prefix(bo, bl)
        local = jnp.where(active, p, 0)
        i_r = jnp.sum(((cum < local) & (idx < rows)).astype(jnp.int32),
                      axis=0, keepdims=True)
        o_r = _vrow(bo, i_r)
        l_r = _vrow(bl, i_r)
        off = local - (_vrow(cum, i_r) - _vrow(lv, i_r))

        left = jnp.where(p == 0, root_u,
                         ((o_r - 1) + (off - 1)).astype(jnp.uint32))
        no, nl, amt, mrg, is_split, _lrun = fused_splice_rows(
            bo, bl, idx, p, i_r, o_r, l_r, off, il, st, w, WMAX,
            _vshift, active=active)

        nxt_in_blk = _vrow(bo, i_r + 1)
        first_o = _vrow(bo, 0)
        succ_p0 = jnp.where(rows > 0, first_o, 0)
        succ_after = jnp.where(i_r + 1 < rows, nxt_in_blk, 0)
        succ = jnp.where(p == 0, succ_p0,
                         jnp.where(is_split, o_r + off, succ_after))
        right = jnp.where(succ == 0, root_u,
                          (jnp.abs(succ) - 1).astype(jnp.uint32))
        # Lanes with amt == 0 and no merge keep bo/bl exactly (masks are
        # all False there and _vshift(amt=0) is the identity).
        ordp[:] = no
        lenp[:] = nl
        rowsv[:] = rows + amt

        ol_ref[pl.ds(k, 1), :] = jnp.where(active, left, 0)
        or_ref[pl.ds(k, 1), :] = jnp.where(active, right, 0)

    def op_body(k, _):
        p = pos_ref[pl.ds(k, 1), :]
        d = dlen_ref[pl.ds(k, 1), :]
        il = ilen_ref[pl.ds(k, 1), :]
        st = start_ref[pl.ds(k, 1), :]
        w = jnp.maximum(w_ref[pl.ds(k, 1), :], 1)  # pad rows carry 0

        if SHARED_CUM:
            # One live prefix serves BOTH branches: the builder proved
            # statically that no lane deletes AND inserts in the same
            # step (so the insert branch's active lanes see exactly
            # this pre-delete prefix) AND that both-branch steps
            # outnumber no-op steps (so the unconditional hoist pays).
            lv, cum = _live_prefix(ordp[:], lenp[:])
        else:
            lv = cum = None

        @pl.when(jnp.any(d > 0))
        def _():
            do_delete(p, d, lv, cum)

        @pl.when(jnp.any(il > 0))
        def _():
            do_insert(k, p, il, st, w, lv, cum)

        return 0

    lax.fori_loop(0, CHUNK, op_body, 0)


@dataclasses.dataclass
class LanesResult:
    """Device outputs: per-lane divergent documents."""

    ordp: jax.Array     # i32[CAP, B]
    lenp: jax.Array     # i32[CAP, B]
    rows: jax.Array     # i32[1, B] occupied run rows per lane
    ol: jax.Array       # u32[S, B]
    orr: jax.Array      # u32[S, B]
    err: jax.Array      # i32[8, B]  0: capacity; 1: bad delete (per lane)
    batch: int

    def check(self) -> None:
        err = np.asarray(self.err)
        if err[0].max() != 0:
            raise RuntimeError(
                f"rle_lanes capacity exhausted on lanes "
                f"{np.nonzero(err[0])[0][:8].tolist()}; raise capacity")
        if err[1].max() != 0:
            raise RuntimeError(
                f"delete ran past the end of the document on lanes "
                f"{np.nonzero(err[1])[0][:8].tolist()}")

    def state(self):
        """(ordp, lenp, rows) — feed as ``init`` to the next chunk's
        replayer (stays on device; the warm-start chain)."""
        return self.ordp, self.lenp, self.rows


def _lane_tile(B: int) -> int:
    """Largest lane-block width <= 512 dividing B (full B when small).

    512 lanes x ~1.7k-run planes keeps every per-op temporary a few MB;
    the whole-B alternative spills registers past the VMEM budget at
    2048 lanes (the round-3 config-5 compile failure)."""
    if B <= 512:
        return B
    for t in (512, 384, 256, 128):
        if B % t == 0:
            return t
    return B  # odd widths: no tiling (small-B test shapes)


@functools.lru_cache(maxsize=32)
def _build_call(s_pad: int, B: int, capacity: int, chunk: int,
                interpret: bool, lane_tile: int | None = None,
                shared_cum: bool = False, wmax: int = 1):
    """Shape-keyed cache: streaming chunks share one compiled kernel
    (a per-chunk pallas_call would re-trace and re-compile ~5-30s each —
    the whole point of warm starts is that chunk N+1 is cheap)."""
    T = lane_tile or _lane_tile(B)
    _require(B % T == 0, f"lane_tile {T} must divide batch {B}")
    col = lambda: pl.BlockSpec((chunk, T), lambda lb, i: (i, lb),
                               memory_space=pltpu.VMEM)
    whole = lambda shape: pl.BlockSpec(
        (shape[0], T), lambda lb, i: (0, lb), memory_space=pltpu.VMEM)

    call = pl.pallas_call(
        partial(_rle_lanes_kernel, CAP=capacity, CHUNK=chunk,
                WMAX=wmax, SHARED_CUM=shared_cum),
        grid=(B // T, s_pad // chunk),
        in_specs=[col(), col(), col(), col(), col(),
                  whole((capacity, B)), whole((capacity, B)),
                  whole((1, B))],
        out_specs=[
            col(), col(),
            whole((capacity, B)), whole((capacity, B)),
            whole((1, B)), whole((8, B)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s_pad, B), jnp.uint32),
            jax.ShapeDtypeStruct((s_pad, B), jnp.uint32),
            jax.ShapeDtypeStruct((capacity, B), jnp.int32),
            jax.ShapeDtypeStruct((capacity, B), jnp.int32),
            jax.ShapeDtypeStruct((1, B), jnp.int32),
            jax.ShapeDtypeStruct((8, B), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=110 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return jax.jit(lambda *a: call(*a))


def make_replayer_lanes(
    ops: OpTensors,
    capacity: int,
    chunk: int = 128,
    init=None,
    interpret: bool = False,
    lane_tile: int | None = None,
):
    """Build a jitted per-lane replayer for a BATCHED op stream
    (``stack_ops`` output: every column [S, B]).

    ``capacity`` counts RUN rows per document. ``init`` is an optional
    ``(ordp, lenp, rows)`` triple from a previous ``LanesResult.state()``
    — the warm start; None = empty documents.
    """
    kinds = np.asarray(ops.kind)
    _require(kinds.ndim == 2, "rle_lanes takes stacked per-doc streams "
             "([S, B] columns; see batch.stack_ops)")
    _require(bool((kinds == KIND_LOCAL).all()),
             "rle_lanes replays local streams; per-lane remote "
             "streams -> ops.rle_lanes_mixed")
    S, B = kinds.shape
    _require(capacity >= 8, "capacity must hold a few runs")
    wmax = fused_width(ops)
    _require(wmax + 1 < capacity,
             f"fused rows_per_step {wmax} cannot fit capacity "
             f"{capacity}")
    s_pad = max(((S + chunk - 1) // chunk) * chunk, chunk)

    def staged_col(get):
        a = np.asarray(get(ops), dtype=np.int32)
        return jnp.asarray(np.pad(a, ((0, s_pad - S), (0, 0))))

    staged = (staged_col(lambda o: o.pos),
              staged_col(lambda o: o.del_len),
              staged_col(lambda o: o.ins_len),
              staged_col(lambda o: o.ins_order_start),
              staged_col(lambda o: o.rows_per_step))

    if init is None:
        init = (jnp.zeros((capacity, B), jnp.int32),
                jnp.zeros((capacity, B), jnp.int32),
                jnp.zeros((1, B), jnp.int32))
    else:
        init = _grow_planes(init, capacity, B)

    # One live prefix can serve both branches of a step iff NO lane
    # deletes AND inserts in the same step (a compiled replace patch),
    # and the hoist only pays on streams where mixed-kind steps
    # dominate (see _shared_cum_gate).
    dn = np.asarray(ops.del_len)
    iln = np.asarray(ops.ins_len)
    shared_cum = (not bool(np.any((dn > 0) & (iln > 0)))
                  and _shared_cum_gate((dn > 0).any(axis=1),
                                       (iln > 0).any(axis=1), s_pad))
    jitted = _build_call(s_pad, B, capacity, chunk, interpret, lane_tile,
                         shared_cum, wmax)

    def run(state=None) -> LanesResult:
        ini = init if state is None else _grow_planes(state, capacity, B)
        ol, orr, ordp, lenp, rows, err = jitted(*staged, *ini)
        return LanesResult(ordp=ordp, lenp=lenp, rows=rows,
                           ol=ol[:S], orr=orr[:S], err=err, batch=B)

    return run


def _grow_planes(state, capacity: int, B: int):
    """Zero-pad a prior chunk's (ordp, lenp, rows) up to this chunk's
    row capacity (run rows pack at the front, so padding is free) —
    streaming chunks may GROW capacity as documents accumulate rows
    instead of paying the final capacity from chunk 0."""
    o0, l0, r0 = state
    o0 = jnp.asarray(o0, jnp.int32)
    l0 = jnp.asarray(l0, jnp.int32)
    _require(o0.shape[0] <= capacity and o0.shape[1] == B,
             f"init state shape {o0.shape} incompatible with "
             f"({capacity}, {B})")
    if o0.shape[0] < capacity:
        pad = jnp.zeros((capacity - o0.shape[0], B), jnp.int32)
        o0 = jnp.concatenate([o0, pad], axis=0)
        l0 = jnp.concatenate([l0, pad], axis=0)
    return (o0, l0, jnp.asarray(r0, jnp.int32).reshape(1, B))


def replay_lanes(ops: OpTensors, capacity: int, **kw) -> LanesResult:
    """One-shot convenience wrapper over ``make_replayer_lanes``."""
    return make_replayer_lanes(ops, capacity, **kw)()


# ---------------------------------------------------------------------------
# BLOCKED per-lane engine: ops.rle's K-row block structure carried into
# the divergent-lanes world (ISSUE 2 tentpole).  Runs live in K-row
# physical blocks; per-lane logical tables (blkord/rws/liv + the
# incrementally-maintained inclusive prefix cumliv) order them; a step
# descends over NB block sums and splices ONE gathered K-row block —
# O(NB + K) touched rows instead of log2(CAP) rolls over [CAP, B].
# Full blocks SPLIT into the logical order table (no global rebalance).
# Bit-identical to the un-blocked kernel above: block splits move rows,
# never runs, so the logical run sequence (and every emitted origin) is
# the same at every step.
# ---------------------------------------------------------------------------


def _lanes_blocked_kernel(
    pos_ref, dlen_ref, ilen_ref, start_ref,     # [CHUNK, B] VMEM op columns
    w_ref,                                      # [CHUNK, B] rows_per_step
    ord0_ref, len0_ref, nlog0_ref,              # warm-start state inputs
    blk0_ref, rws0_ref, liv0_ref,
    ol_ref, or_ref,                             # [CHUNK, B] outputs
    ordp, lenp, nlogv, blkord, rws, liv,        # state outputs (working)
    err_ref,
    cumliv,                                     # [NBT, B] scratch prefix
    *, K: int, NB: int, NBT: int, CHUNK: int, WMAX: int = 1,
):
    from .lane_blocks import (
        gather_block,
        gather_head,
        lane_apply_partial,
        scatter_block,
        scatter_block2,
        vshift_up,
    )

    B = ordp.shape[1]
    i = pl.program_id(1)
    kdx = lax.broadcasted_iota(jnp.int32, (K, B), 0)
    tidx = lax.broadcasted_iota(jnp.int32, (NBT, B), 0)
    root_u = jnp.uint32(ROOT_ORDER)

    ol_ref[:] = jnp.zeros_like(ol_ref)
    or_ref[:] = jnp.zeros_like(or_ref)

    @pl.when(i == 0)
    def _init():
        ordp[:] = ord0_ref[:]
        lenp[:] = len0_ref[:]
        # Fresh lanes hold one empty block in logical slot 0.
        nlogv[:] = jnp.maximum(nlog0_ref[:], 1)
        blkord[:] = blk0_ref[:]
        rws[:] = rws0_ref[:]
        liv[:] = liv0_ref[:]
        cumliv[:] = _vcumsum(liv0_ref[:])
        err_ref[:] = jnp.zeros_like(err_ref)

    def trow(tbl, l):
        """Per-lane slot read: ``tbl[l[0, b], b]`` as [1, B]."""
        return jnp.sum(jnp.where(tidx == l, tbl[:], 0), axis=0,
                       keepdims=True)

    def slot_of_live_rank(rank1):
        """Smallest logical slot whose cumulative live count reaches
        ``rank1``, per lane (the `root.rs:54-88` descent over block
        sums; slots >= nlog hold stale prefixes, masked out)."""
        nl = nlogv[:]
        hit = (cumliv[:] < rank1) & (tidx < nl)
        return jnp.minimum(
            jnp.sum(hit.astype(jnp.int32), axis=0, keepdims=True), nl - 1)

    def live_before(l):
        return trow(cumliv, l) - trow(liv, l)

    def split(act, l):
        """Per-lane leaf split (`mutations.rs:623-669`): move the top
        half of slot ``l``'s rows to a fresh physical block spliced
        into the logical order at ``l+1``.  Lanes at table capacity
        raise the error flag and skip (a proceeding split would
        overwrite a live block — the ops.rle advisor-r3 rule)."""
        over = act & (nlogv[:] >= NB)

        @pl.when(jnp.any(over))
        def _cap():
            err_ref[0:1, :] = jnp.where(over, 1, err_ref[0:1, :])

        do = act & (nlogv[:] < NB)

        @pl.when(jnp.any(do))
        def _do():
            b = trow(blkord, l)
            r = trow(rws, l)
            keep = r // 2
            mv = r - keep
            nbv = nlogv[:]  # per-lane fresh physical block id
            ws_o = gather_block(ordp, b, K, NB)
            ws_l = gather_block(lenp, b, K, NB)
            liv_hi = jnp.sum(
                jnp.where((kdx >= keep) & (kdx < r) & (ws_o > 0), ws_l,
                          0), axis=0, keepdims=True)
            up_o = vshift_up(ws_o, keep, K)
            up_l = vshift_up(ws_l, keep, K)
            scatter_block2(
                ordp, b, jnp.where(kdx < keep, ws_o, 0),
                nbv, jnp.where(kdx < mv, up_o, 0), do, K, NB)
            scatter_block2(
                lenp, b, jnp.where(kdx < keep, ws_l, 0),
                nbv, jnp.where(kdx < mv, up_l, 0), do, K, NB)
            # Logical tables: slots > l shift one down; cumliv shifts
            # with them (slot l+1 inherits old c_l — its correct
            # inclusive prefix after the split), slot l loses the
            # moved-out top half.
            for tbl in (blkord, rws, liv, cumliv):
                sh = pltpu.roll(tbl[:], 1, axis=0)
                tbl[:] = jnp.where(do & (tidx > l), sh, tbl[:])
            w_l = do & (tidx == l)
            w_l1 = do & (tidx == l + 1)
            rws[:] = jnp.where(w_l, keep, jnp.where(w_l1, mv, rws[:]))
            liv[:] = jnp.where(w_l, liv[:] - liv_hi,
                               jnp.where(w_l1, liv_hi, liv[:]))
            cumliv[:] = jnp.where(w_l, cumliv[:] - liv_hi, cumliv[:])
            blkord[:] = jnp.where(w_l1, nbv, blkord[:])
            nlogv[:] = nlogv[:] + do.astype(jnp.int32)

    def find_insert_slot(p):
        l = jnp.where(p == 0, 0, slot_of_live_rank(p))
        return l, trow(rws, l)

    def do_insert(k, act, p, il, st, w):
        """Per-lane blocked insert: descend, gather ONE block, splice
        <= w+2 rows, scatter back (`mutations.rs:17-179`).  ``w`` > 1
        is a FUSED backwards-burst step landing W stride-L rows in one
        shift (the ``ops.rle`` ``_insert_splice`` contract; WMAX <=
        K//2 - 1 so the one leaf split below always makes room)."""
        l, r0 = find_insert_slot(p)
        need = act & (r0 + w + 1 > K)

        @pl.when(jnp.any(need))
        def _():
            split(need, l)

        # Re-descend only when a split actually moved slots (pure table
        # reads, so the cond branch is Mosaic-safe).
        l, r0 = lax.cond(jnp.any(need),
                         lambda: find_insert_slot(p), lambda: (l, r0))
        b = trow(blkord, l)
        local = jnp.where(act, p - live_before(l), 0)
        ws_o = gather_block(ordp, b, K, NB)
        ws_l = gather_block(lenp, b, K, NB)
        lv = jnp.where(ws_o > 0, ws_l, 0)
        cum = _vcumsum(lv)
        i_r = jnp.sum(((cum < local) & (kdx < r0)).astype(jnp.int32),
                      axis=0, keepdims=True)
        o_r = _vrow(ws_o, i_r)
        l_r = _vrow(ws_l, i_r)
        off = local - (_vrow(cum, i_r) - _vrow(lv, i_r))

        left = jnp.where(p == 0, root_u,
                         ((o_r - 1) + (off - 1)).astype(jnp.uint32))
        no, nl, amt, mrg, is_split, _lrun = fused_splice_rows(
            ws_o, ws_l, kdx, p, i_r, o_r, l_r, off, il, st, w, WMAX,
            _vshift, active=act)

        # Raw successor (`doc.rs:452`): next row of this block, else the
        # head row of the NEXT logical slot's block.
        nxt_in_blk = _vrow(ws_o, i_r + 1)
        b2 = trow(blkord, jnp.minimum(l + 1, NBT - 1))
        nxt_slot_o = gather_head(ordp, b2, K, NB)
        first_o = gather_head(ordp, trow(blkord, 0), K, NB)
        succ_p0 = jnp.where(trow(rws, 0) > 0, first_o, 0)
        succ_after = jnp.where(i_r + 1 < r0, nxt_in_blk,
                               jnp.where(l + 1 < nlogv[:], nxt_slot_o, 0))
        succ = jnp.where(p == 0, succ_p0,
                         jnp.where(is_split, o_r + off, succ_after))
        right = jnp.where(succ == 0, root_u,
                          (jnp.abs(succ) - 1).astype(jnp.uint32))
        scatter_block(ordp, b, no, act, K, NB)
        scatter_block(lenp, b, nl, act, K, NB)
        w_l = act & (tidx == l)
        rws[:] = jnp.where(w_l, rws[:] + amt, rws[:])
        liv[:] = jnp.where(w_l, liv[:] + il, liv[:])
        cumliv[:] = jnp.where(act & (tidx >= l), cumliv[:] + il,
                              cumliv[:])

        ol_ref[pl.ds(k, 1), :] = jnp.where(act, left, 0)
        or_ref[pl.ds(k, 1), :] = jnp.where(act, right, 0)

    def do_delete(act, p, d):
        """Per-lane blocked delete: per iteration each active lane
        clears its target block's covered span (flip full covers, split
        the <= 2 boundary runs); lanes advance block-to-block through
        the incrementally updated prefix (`mutations.rs:520-570`)."""

        def body(carry):
            rem, iters = carry
            a = act & (rem > 0)
            l = slot_of_live_rank(p + 1)
            need = a & (trow(rws, l) + 2 > K)

            @pl.when(jnp.any(need))
            def _():
                split(need, l)

            l = lax.cond(jnp.any(need),
                         lambda: slot_of_live_rank(p + 1), lambda: l)
            b = trow(blkord, l)
            base = live_before(l)
            ws_o = gather_block(ordp, b, K, NB)
            ws_l = gather_block(lenp, b, K, NB)
            lv = jnp.where(ws_o > 0, ws_l, 0)
            cum = _vcumsum(lv)
            before = base + cum - lv
            remm = jnp.where(a, rem, 0)
            cs = jnp.clip(p - before, 0, lv)
            ce = jnp.clip(p + remm - before, 0, lv)
            cov = ce - cs
            tot = jnp.sum(cov, axis=0, keepdims=True)
            full = (cov > 0) & (cov == ws_l)
            part = (cov > 0) & jnp.logical_not(full)
            npart = jnp.sum(part.astype(jnp.int32), axis=0,
                            keepdims=True)
            i1 = jnp.min(jnp.where(part, kdx, K), axis=0, keepdims=True)
            i2 = jnp.max(jnp.where(part, kdx, -1), axis=0, keepdims=True)
            ws_o = jnp.where(a & full, -ws_o, ws_o)
            ws_o, ws_l, a2 = lane_apply_partial(
                a & (npart >= 1), i2, ws_o, ws_l, cs, ce, kdx)
            ws_o, ws_l, a1 = lane_apply_partial(
                a & (npart == 2), i1, ws_o, ws_l, cs, ce, kdx)
            scatter_block(ordp, b, ws_o, a, K, NB)
            scatter_block(lenp, b, ws_l, a, K, NB)
            w_l = a & (tidx == l)
            rws[:] = jnp.where(w_l, rws[:] + a1 + a2, rws[:])
            liv[:] = jnp.where(w_l, liv[:] - tot, liv[:])
            cumliv[:] = jnp.where(a & (tidx >= l), cumliv[:] - tot,
                                  cumliv[:])
            return rem - jnp.where(a, tot, 0), iters + 1

        # Each iteration clears one block's covered span per lane;
        # > 2*NBT iterations without draining means some lane's delete
        # ran off its document.
        rem, _ = lax.while_loop(
            lambda c: jnp.any(act & (c[0] > 0)) & (c[1] <= 2 * NBT),
            body, (jnp.where(act, d, 0), 0))

        @pl.when(jnp.any(act & (rem > 0)))
        def _bad():
            err_ref[1:2, :] = jnp.where(act & (rem > 0), 1,
                                        err_ref[1:2, :])

    def op_body(k, _):
        p = pos_ref[pl.ds(k, 1), :]
        d = dlen_ref[pl.ds(k, 1), :]
        il = ilen_ref[pl.ds(k, 1), :]
        st = start_ref[pl.ds(k, 1), :]
        w = jnp.maximum(w_ref[pl.ds(k, 1), :], 1)  # pad rows carry 0

        @pl.when(jnp.any(d > 0))
        def _():
            do_delete(d > 0, p, d)

        @pl.when(jnp.any(il > 0))
        def _():
            do_insert(k, il > 0, p, il, st, w)

        return 0

    lax.fori_loop(0, CHUNK, op_body, 0)


@dataclasses.dataclass
class BlockedLanesResult:
    """Device outputs of the BLOCKED per-lane engine: per-lane K-row
    physical blocks + logical block tables."""

    ordp: jax.Array     # i32[CAP, B]  physical K-row blocks
    lenp: jax.Array     # i32[CAP, B]
    nlog: jax.Array     # i32[1, B]    logical blocks in use per lane
    blkord: jax.Array   # i32[NBT, B]  logical slot -> physical block
    rws: jax.Array      # i32[NBT, B]  occupied rows per logical slot
    liv: jax.Array      # i32[NBT, B]  live chars per logical slot
    ol: jax.Array       # u32[S, B]
    orr: jax.Array      # u32[S, B]
    err: jax.Array      # i32[8, B]  0: out of blocks; 1: bad delete
    batch: int
    block_k: int

    def check(self) -> None:
        err = np.asarray(self.err)
        if err[0].max() != 0:
            raise RuntimeError(
                f"blocked rle_lanes out of blocks on lanes "
                f"{np.nonzero(err[0])[0][:8].tolist()}; raise capacity")
        if err[1].max() != 0:
            raise RuntimeError(
                f"delete ran past the end of the document on lanes "
                f"{np.nonzero(err[1])[0][:8].tolist()}")

    def state(self):
        """(ordp, lenp, nlog, blkord, rws, liv) — the next chunk's
        ``init`` (stays on device; the warm-start chain)."""
        return (self.ordp, self.lenp, self.nlog, self.blkord, self.rws,
                self.liv)

    @property
    def rows(self):
        """Total occupied rows per lane (compat with ``LanesResult``)."""
        return jnp.sum(self.rws, axis=0, keepdims=True)


@functools.lru_cache(maxsize=32)
def _build_blocked_call(s_pad: int, B: int, capacity: int, block_k: int,
                        chunk: int, interpret: bool,
                        lane_tile: int | None = None, wmax: int = 1):
    """Shape-keyed cache for the blocked kernel (streaming chunks of one
    geometry share one compiled kernel)."""
    K = block_k
    NB = capacity // K
    NBT = max(8, NB)
    T = lane_tile or _lane_tile(B)
    _require(B % T == 0, f"lane_tile {T} must divide batch {B}")
    col = lambda: pl.BlockSpec((chunk, T), lambda lb, i: (i, lb),
                               memory_space=pltpu.VMEM)
    whole = lambda rows: pl.BlockSpec(
        (rows, T), lambda lb, i: (0, lb), memory_space=pltpu.VMEM)

    call = pl.pallas_call(
        partial(_lanes_blocked_kernel, K=K, NB=NB, NBT=NBT, CHUNK=chunk,
                WMAX=wmax),
        grid=(B // T, s_pad // chunk),
        in_specs=[col(), col(), col(), col(), col(),
                  whole(capacity), whole(capacity), whole(1),
                  whole(NBT), whole(NBT), whole(NBT)],
        out_specs=[
            col(), col(),
            whole(capacity), whole(capacity), whole(1),
            whole(NBT), whole(NBT), whole(NBT),
            whole(8),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s_pad, B), jnp.uint32),
            jax.ShapeDtypeStruct((s_pad, B), jnp.uint32),
            jax.ShapeDtypeStruct((capacity, B), jnp.int32),
            jax.ShapeDtypeStruct((capacity, B), jnp.int32),
            jax.ShapeDtypeStruct((1, B), jnp.int32),
            jax.ShapeDtypeStruct((NBT, B), jnp.int32),
            jax.ShapeDtypeStruct((NBT, B), jnp.int32),
            jax.ShapeDtypeStruct((NBT, B), jnp.int32),
            jax.ShapeDtypeStruct((8, B), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((NBT, T), jnp.int32),    # cumliv
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=110 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return jax.jit(lambda *a: call(*a))


def make_replayer_lanes_blocked(
    ops: OpTensors,
    capacity: int,
    block_k: int = 64,
    chunk: int = 128,
    init=None,
    interpret: bool = False,
    lane_tile: int | None = None,
):
    """Build a jitted BLOCKED per-lane replayer (``stack_ops`` streams,
    local ops only) — bit-identical final state and per-op origins to
    ``make_replayer_lanes``, at O(NB + K) touched rows per step.

    ``capacity`` counts run rows per lane and must be a ``block_k``
    multiple; growing per-chunk capacities grow NB at fixed K.  ``init``
    is a prior ``BlockedLanesResult.state()`` 6-tuple.
    """
    kinds = np.asarray(ops.kind)
    _require(kinds.ndim == 2, "rle_lanes takes stacked per-doc streams "
             "([S, B] columns; see batch.stack_ops)")
    _require(bool((kinds == KIND_LOCAL).all()),
             "rle_lanes replays local streams; per-lane remote "
             "streams -> ops.rle_lanes_mixed")
    S, B = kinds.shape
    _require(block_k >= 8, "block_k must hold a few runs")
    _require(capacity % block_k == 0,
             f"capacity ({capacity}) must be a multiple of block_k "
             f"({block_k})")
    wmax = fused_width_checked([ops], block_k)
    s_pad = max(((S + chunk - 1) // chunk) * chunk, chunk)

    def staged_col(get):
        a = np.asarray(get(ops), dtype=np.int32)
        return jnp.asarray(np.pad(a, ((0, s_pad - S), (0, 0))))

    staged = (staged_col(lambda o: o.pos),
              staged_col(lambda o: o.del_len),
              staged_col(lambda o: o.ins_len),
              staged_col(lambda o: o.ins_order_start),
              staged_col(lambda o: o.rows_per_step))

    NBT = max(8, capacity // block_k)
    if init is None:
        init = _empty_blocked_state(capacity, NBT, B)
    else:
        init = _grow_blocked_state(init, capacity, block_k, B)
    jitted = _build_blocked_call(s_pad, B, capacity, block_k, chunk,
                                 interpret, lane_tile, wmax)

    def run(state=None) -> BlockedLanesResult:
        ini = init if state is None else _grow_blocked_state(
            state, capacity, block_k, B)
        ol, orr, ordp, lenp, nlog, blk, rws, liv, err = jitted(
            *staged, *ini)
        return BlockedLanesResult(
            ordp=ordp, lenp=lenp, nlog=nlog, blkord=blk, rws=rws,
            liv=liv, ol=ol[:S], orr=orr[:S], err=err, batch=B,
            block_k=block_k)

    return run


def _empty_blocked_state(capacity: int, NBT: int, B: int):
    z = lambda r: jnp.zeros((r, B), jnp.int32)
    return (z(capacity), z(capacity), z(1), z(NBT), z(NBT), z(NBT))


def _grow_blocked_state(state, capacity: int, block_k: int, B: int):
    """Pad a prior chunk's blocked 6-tuple up to this chunk's capacity:
    fresh physical blocks append at the end (allocation order == block
    id, so zero-padding is free), logical tables zero-pad past nlog."""
    o0, l0, nlog, blk, rws, liv = state
    o0 = jnp.asarray(o0, jnp.int32)
    l0 = jnp.asarray(l0, jnp.int32)
    _require(o0.shape[0] <= capacity and o0.shape[1] == B,
             f"init state shape {o0.shape} incompatible with "
             f"({capacity}, {B})")
    _require(o0.shape[0] % block_k == 0,
             f"prior capacity {o0.shape[0]} is not a block_k "
             f"({block_k}) multiple — geometry K must not change "
             "between chunks")
    NBT = max(8, capacity // block_k)

    def padp(a):
        a = jnp.asarray(a, jnp.int32)
        if a.shape[0] < capacity:
            a = jnp.concatenate(
                [a, jnp.zeros((capacity - a.shape[0], B), jnp.int32)],
                axis=0)
        return a

    def padt(a):
        a = jnp.asarray(a, jnp.int32)
        _require(a.shape[0] <= NBT,
                 f"table rows {a.shape[0]} exceed {NBT}")
        if a.shape[0] < NBT:
            a = jnp.concatenate(
                [a, jnp.zeros((NBT - a.shape[0], B), jnp.int32)], axis=0)
        return a

    return (padp(o0), padp(l0),
            jnp.asarray(nlog, jnp.int32).reshape(1, B),
            padt(blk), padt(rws), padt(liv))


def expand_lane_blocked(res, doc_index: int) -> np.ndarray:
    """One lane of a blocked result -> per-char ±(order+1) column in doc
    order (walk the logical block table)."""
    res.check()
    K = res.block_k
    ordc = np.asarray(res.ordp[:, doc_index])
    lenc = np.asarray(res.lenp[:, doc_index])
    blk = np.asarray(res.blkord[:, doc_index])
    rows = np.asarray(res.rws[:, doc_index])
    nlog = int(np.asarray(res.nlog[0, doc_index]))
    o_parts, l_parts = [], []
    for l in range(nlog):
        b, r = int(blk[l]), int(rows[l])
        o_parts.append(ordc[b * K: b * K + r])
        l_parts.append(lenc[b * K: b * K + r])
    if not o_parts:
        return np.zeros(0, np.int32)
    o = np.concatenate(o_parts).astype(np.int64)
    ln = np.concatenate(l_parts).astype(np.int64)
    if len(o) == 0:
        return np.zeros(0, np.int32)
    assert (ln > 0).all(), "occupied run with non-positive length"
    total = int(ln.sum())
    base = np.repeat(np.abs(o), ln)
    within = np.arange(total) - np.repeat(np.cumsum(ln) - ln, ln)
    return (np.repeat(np.sign(o), ln) * (base + within)).astype(np.int32)


def expand_lane(res, doc_index: int) -> np.ndarray:
    """One lane's run rows -> per-char ±(order+1) column in doc order
    (dispatches on the blocked-layout results too)."""
    if hasattr(res, "blkord"):
        return expand_lane_blocked(res, doc_index)
    res.check()
    r = int(np.asarray(res.rows)[0, doc_index])
    o = np.asarray(res.ordp)[:r, doc_index].astype(np.int64)
    ln = np.asarray(res.lenp)[:r, doc_index].astype(np.int64)
    if r == 0:
        return np.zeros(0, np.int32)
    assert (ln > 0).all(), "occupied run with non-positive length"
    total = int(ln.sum())
    base = np.repeat(np.abs(o), ln)
    within = np.arange(total) - np.repeat(np.cumsum(ln) - ln, ln)
    return (np.repeat(np.sign(o), ln) * (base + within)).astype(np.int32)


def lanes_to_flat(
    ops: OpTensors,
    res: LanesResult,
    doc_index: int,
    capacity: int | None = None,
    order_capacity: int | None = None,
) -> FlatDoc:
    """One lane -> a standard ``FlatDoc`` (prefill + per-op origins)."""
    flat = expand_lane(res, doc_index)
    n = len(flat)
    if capacity is None:
        capacity = max(2 << max(n - 1, 5).bit_length(), n)
    per_doc = jax.tree.map(lambda a: np.asarray(a)[:, doc_index], ops)
    doc = make_flat_doc(capacity, order_capacity)
    doc = prefill_logs(doc, per_doc)
    ol_log = np.array(doc.ol_log)
    or_log = np.array(doc.or_log)
    ol_np = np.asarray(res.ol)[:, doc_index]
    or_np = np.asarray(res.orr)[:, doc_index]
    merge_fused_origins(ol_log, or_log, per_doc, ol_np, or_np)

    signed_col = np.zeros(capacity, np.int32)
    signed_col[:n] = flat
    advance = int(np.asarray(per_doc.order_advance, dtype=np.int64).sum())
    return dataclasses.replace(
        doc,
        signed=jnp.asarray(signed_col),
        ol_log=jnp.asarray(ol_log),
        or_log=jnp.asarray(or_log),
        n=jnp.asarray(n, I32),
        next_order=jnp.asarray(advance, U32),
    )

"""Block-wise scans for documents larger than device memory.

SURVEY §5's long-context row names two scale regimes beyond the VMEM
engines: sharding one document's runs across chips
(``parallel.sp_runs`` / ``sp_apply``) and *"block-wise scans for >HBM
documents"* — this module. The run planes ``(±(order+1), len)`` stay
HOST-resident (arbitrary length, e.g. memory-mapped), and the read-side
conversions (`README.md:20-26`) stream device-sized tiles through ONE
jitted per-tile reduction each, with the scan carry (live chars before
the tile) riding on host exactly like ``sp_runs`` rides it on the mesh
axis — the B-tree descent (`root.rs:54-88`) with the top levels replaced
by a host-side tile table:

- ``live_total`` / per-tile carries: one pass at construction;
- ``position_of_live_rank``: host-searchsorted over the carry table
  picks the ONE tile that resolves the rank, then a single in-tile
  device lookup finishes (`cursor.rs:147-190`'s inverse);
- ``order_to_position``: tiles stream until the owning run is found
  (`doc.rs:26-29` + `cursor.rs:147-190`); unfound -> -1.

Mutation at this scale goes through ``ops.rle_hbm`` (windowed HBM
planes) or ``parallel.sp_apply`` (sharded); this module is the
read-back path for state bigger than both.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .blocked import _require


@jax.jit
def _tile_rank(o, l, rank1):
    """Resolve 1-based live rank ``rank1`` (known to land in this tile,
    so tile-local arithmetic fits i32) -> (tile-local row, 1-based
    offset within the run)."""
    lv = jnp.where(o > 0, l, 0)
    cum = jnp.cumsum(lv)
    row = jnp.sum((cum < rank1).astype(jnp.int32))
    before = cum[row] - lv[row]
    return row, rank1 - before


@jax.jit
def _tile_order(o, l, order):
    """(found?, tile-local position or -1 when tombstoned).

    A run row covers orders ``[abs(o)-1, abs(o)-1+len)`` (`span.rs:9-13`
    implicit chaining); the position counts live chars strictly before
    the item within this tile (`cursor.rs:147-190` semantics, matching
    ``parallel.sp_runs.order_to_position``)."""
    start = jnp.abs(o) - 1
    hit = (o != 0) & (order >= start) & (order < start + l)
    lv = jnp.where(o > 0, l, 0)
    cum_before = jnp.cumsum(lv) - lv
    row = jnp.argmax(hit)
    found = jnp.any(hit)
    live_run = found & (o[row] > 0)
    pos = jnp.where(live_run,
                    cum_before[row] + (order - start[row]),
                    -1)
    return found, pos


class StreamedRuns:
    """Read-side scans over host-resident run planes of any length.

    ``tile`` rows stream through the device per step; one compile per
    tile shape (all tiles are padded to ``tile``)."""

    def __init__(self, ordp, lenp, tile: int = 1 << 20):
        _require(len(ordp) == len(lenp), "plane length mismatch")
        _require(tile >= 1, "tile must be positive")
        self.tile = int(tile)
        n = len(ordp)
        self.ntiles = max(1, -(-n // self.tile))
        # Keep the caller's arrays as-is (np.asarray over a memmap is
        # zero-copy; a whole-plane np.pad would materialize the full
        # plane in host RAM — the one thing this module must not do).
        # Only the final partial tile pads, inside _tile().
        self.ordp = np.asarray(ordp)
        self.lenp = np.asarray(lenp)
        # Carry table: live chars BEFORE each tile (the host-side analog
        # of sp_runs' all-gathered shard totals) + per-tile order bounds
        # so order lookups skip tiles that cannot contain the order.
        # All computed HOST-side in int64 (no device round-trips, and the
        # device in-tile cumsums are i32, so each tile's live total is
        # required to fit i32 — shrink ``tile`` otherwise).
        totals = np.empty(self.ntiles, np.int64)
        self.omin = np.empty(self.ntiles, np.int64)
        self.omax = np.empty(self.ntiles, np.int64)
        for t in range(self.ntiles):
            s = t * self.tile
            o = np.asarray(self.ordp[s:s + self.tile], np.int64)
            l = np.asarray(self.lenp[s:s + self.tile], np.int64)
            totals[t] = int(np.where(o > 0, l, 0).sum())
            _require(totals[t] < 2 ** 31,
                     f"tile {t} live total {totals[t]} overflows the "
                     "i32 in-tile cumsum; use a smaller tile")
            occ = np.abs(o)
            mask = occ > 0
            self.omin[t] = (occ[mask] - 1).min() if mask.any() else -1
            self.omax[t] = (occ[mask] - 1 + l[mask]).max() \
                if mask.any() else -1
        self.carry = np.concatenate(([0], np.cumsum(totals)))
        self._cached_t = -1
        self._cached = None

    def _tile(self, t: int):
        # One-entry upload cache: repeated lookups overwhelmingly hit
        # the same tile, and a fresh H2D transfer per call would cost
        # tile * 8 bytes each time.
        if self._cached_t != t:
            s = t * self.tile
            o = np.asarray(self.ordp[s:s + self.tile], np.int32)
            l = np.asarray(self.lenp[s:s + self.tile], np.int32)
            if len(o) < self.tile:  # final partial tile only
                pad = self.tile - len(o)
                o = np.pad(o, (0, pad))
                l = np.pad(l, (0, pad))
            self._cached = (jnp.asarray(o), jnp.asarray(l))
            self._cached_t = t
        return self._cached

    def live_total(self) -> int:
        return int(self.carry[-1])

    def position_of_live_rank(self, rank1: int):
        """1-based live rank -> (global run row, 1-based in-run offset);
        (-1, 0) when ``rank1`` exceeds the live total (the documented
        out-of-range sentinel, unlike an ambiguous (0, 0))."""
        if rank1 < 1 or rank1 > self.live_total():
            return -1, 0
        t = int(np.searchsorted(self.carry[1:], rank1, side="left"))
        row, off = _tile_rank(*self._tile(t),
                              rank1 - int(self.carry[t]))
        return t * self.tile + int(row), int(off)

    def order_to_position(self, order: int) -> int:
        """CRDT order -> 0-based content position (live chars strictly
        before it), or -1 when the order is unknown or tombstoned —
        the same contract as ``parallel.sp_runs.order_to_position``."""
        for t in range(self.ntiles):
            # Host-side prune: a tile whose [min, max) order envelope
            # misses ``order`` never uploads (most lookups touch ONE
            # tile; without this, a miss would stream the whole plane).
            if self.omax[t] < 0 or not (self.omin[t] <= order
                                        < self.omax[t]):
                continue
            found, pos = _tile_order(*self._tile(t), order)
            if bool(found):
                p = int(pos)
                return -1 if p < 0 else int(self.carry[t]) + p
        return -1

"""JAX/XLA device kernels — the TPU-native document engines.

All engines share one semantic model (the flattened YjsSpan item layout,
see ``span_arrays``) and cross-check bit-identically in ``tests/``:

- ``flat``      — correctness-first engine: per-item arrays in document
                  order, every op O(capacity) fully-vectorized. Complete
                  op surface (local edits, remote inserts with the YATA
                  integrate scan + name-rank tiebreak, remote delete
                  tombstoning). The device twin of ``models.oracle``.
- ``rle``       — the north-star engine (round 3): state is RLE RUNS
                  (``(start_order, signed_len)`` rows — `span.rs:6-119`'s
                  compression on device), blocked with a logical block
                  order and leaf SPLITS instead of global rebalances
                  (`mutations.rs:623-808`). Consumes the RLE-merged op
                  stream (``batch.merge_patches``). VMEM-resident.
- ``rle_hbm``   — same run algebra with HBM state planes behind a
                  one-block VMEM window: millions of run rows (the kevin
                  prepend worst case, >VMEM documents).
- ``rle_lanes`` — per-lane DIVERGENT documents: B distinct streams, one
                  op per lane per step, warm-startable across compiled
                  chunks (the streaming config-5 engine).
- ``rle_mixed`` — the round-4 unification: the FULL op surface (local +
                  remote YATA integrate + remote delete, `doc.rs:242-348`)
                  on the run representation — runs the config-4 storm on
                  state that is runs, not chars.
- ``rle_lanes_mixed`` — the round-5 unification: the full op surface on
                  PER-LANE DIVERGENT documents (each lane its own remote
                  stream — the production sync shape; config 5's remote
                  variant), with per-lane by-order origin tables and a
                  lane-vectorized YATA scan.
- ``blocked`` / ``blocked_hbm`` — the round-2 per-character block
                  engines (kept as references and for the unmerged-stream
                  path); ``blocked_mixed`` adds the remote-op hot path
                  in-kernel on char rows (superseded by ``rle_mixed``).

``batch`` compiles editing traces into fixed-shape op tensors (the
host-side analog of the reference's bench replay loop,
`benches/yjs.rs:32-49`), RLE-merges patch streams, and owns the agent
name-rank table incl. cross-epoch onboarding (``rank_remap``).

``stream_scan`` is the >HBM read path: host-resident run planes of any
length, scanned tile-by-tile with host-carried prefixes (SURVEY §5's
"block-wise scans for >HBM documents"; mutation at that scale goes
through ``rle_hbm`` or ``parallel.sp_apply``).
"""
from . import _pallas_compat  # noqa: F401  (pltpu API aliasing)

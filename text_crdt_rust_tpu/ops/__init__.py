"""JAX/XLA device kernels — the TPU-native document engines.

Two engines share one semantic model (the flattened YjsSpan item layout,
see ``span_arrays``):

- ``flat``    — correctness-first engine: per-item arrays in document order,
                every op is O(capacity) fully-vectorized work. Supports the
                complete op surface (local edits, remote inserts with the
                YATA integrate scan + name-rank tiebreak, remote delete
                tombstoning — excess-delete *counts* stay in the host-side
                double_deletes log). The device twin of
                ``models.oracle.ListCRDT``.
- ``blocked`` — throughput engine for the north-star trace-replay path:
                the document is a fixed grid of blocks; each op touches one
                block plus an O(num_blocks) index, with periodic all-doc
                rebalance passes replacing the reference B-tree's node splits
                (`range_tree/mutations.rs:623-808`). Variants:
                ``blocked_hbm`` keeps the block grid in HBM behind a DMA'd
                VMEM window (full-trace documents), and ``blocked_mixed``
                adds the remote-op hot path in-kernel (YATA integrate +
                order-range deletes over an order->block index).

``batch`` compiles editing traces into fixed-shape op tensors (the host-side
analog of the reference's bench replay loop, `benches/yjs.rs:32-49`).
"""

"""Host-side op compiler: edit traces / RemoteTxn streams -> device op tensors.

The reference replays edits through per-op O(log n) B-tree walks
(`benches/yjs.rs:41-48` -> `doc.rs:376-469`). The TPU engines instead consume
*pre-compiled, fixed-shape op tensors*: one row per device step, everything
an op needs resolved to dense integers host-side:

- agent names     -> name *ranks* (the Yjs tiebreak is on agent name,
                     `doc.rs:206-209`, so the device compares ranks);
- remote ids      -> orders (`doc.rs:236-240`, via per-agent seq->order RLE
                     maps, `list/mod.rs:33-43`);
- order allocation (`doc.rs:155-165`) — the compiler threads ``next_order``
  through the stream and bakes each insert run's first order into its step;
- remote delete targets are walked in *seq space* and split at the target
  agent's item_orders run boundaries so each step's target range is
  order-contiguous (the fragmentation loop of `doc.rs:311-334`);
- insert runs longer than the static ``lmax`` are split into chained chunks —
  chunk k's origin_left is the last item of chunk k-1, exactly the implicit
  origin chain a split span keeps (`span.rs:24-28,33-45`).

Time-DAG bookkeeping (frontier advance `doc.rs:34-48`, txn spans, causal
order) stays host-side per SURVEY §7; the compiler only asserts txns arrive
causally ready (see ``parallel.causal`` for the buffering layer).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..common import (
    CLIENT_INVALID,
    ROOT_ORDER,
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
    txn_len,
)
from ..utils.rle import KOrderSpan, Rle
from ..utils.testdata import TestData, TestPatch, flatten_patches

# Op kinds (device-side dispatch in ops.flat / ops.blocked).
KIND_LOCAL = 0        # delete del_len live chars at pos, then insert at pos
KIND_REMOTE_INS = 1   # YATA-integrate an insert run at resolved origins
KIND_REMOTE_DEL = 2   # tombstone an order-contiguous target range


@partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "kind", "pos", "del_len", "del_target", "origin_left", "origin_right",
        "ins_len", "ins_order_start", "order_advance", "rank",
        "rows_per_step", "chars",
    ],
    meta_fields=[],
)
@dataclasses.dataclass
class OpTensors:
    """One device step per row; all u32. Batched streams stack a trailing
    doc axis *after* the step axis (time-major for ``lax.scan``)."""

    kind: jax.Array             # u32[S, ...]
    pos: jax.Array              # u32[S, ...]   KIND_LOCAL: content position
    del_len: jax.Array          # u32[S, ...]   local del span / remote target len
    del_target: jax.Array       # u32[S, ...]   KIND_REMOTE_DEL: first target order
    origin_left: jax.Array      # u32[S, ...]   KIND_REMOTE_INS
    origin_right: jax.Array     # u32[S, ...]   KIND_REMOTE_INS
    ins_len: jax.Array          # u32[S, ...]
    ins_order_start: jax.Array  # u32[S, ...]   first order of the insert run
    order_advance: jax.Array    # u32[S, ...]   orders consumed by this step
    rank: jax.Array             # u32[S, ...]   author agent's name rank
    rows_per_step: jax.Array    # u32[S, ...]   W: run rows this step splices
    #   (1 = plain op; W > 1 = a FUSED backwards-contiguous insert burst:
    #   W same-length runs spliced in one step, orders DESCENDING in doc
    #   order with stride L = ins_len/W — the split-batch prepare for the
    #   kevin prepend shape. 0 only on no-op padding rows.)
    chars: jax.Array            # u32[S, ..., LMAX]

    @property
    def num_steps(self) -> int:
        return self.kind.shape[0]

    @property
    def lmax(self) -> int:
        return self.chars.shape[-1]


class AgentTable:
    """Agent name <-> dense id + *name rank* table.

    The device tiebreak compares ranks; ranks are the index of each name in
    the sorted name list, so rank order == name order (`doc.rs:206-209`).
    Within ONE compiled stream the table must not change (the steps bake
    ranks in). ACROSS compiled epochs peers may join freely: agent IDS are
    append-only (``OrderAssigner`` state stays valid), and persisted rank
    logs are re-based through ``rank_remap`` at the epoch boundary — the
    mid-stream onboarding the reference punts on (`doc.rs:66-89` creates
    agents on the fly but has no compiled state to re-base).
    """

    def __init__(self, names: Iterable[str] = ()):
        self.names: List[str] = []
        self._ids: Dict[str, int] = {}
        for n in names:
            self.add(n)

    def add(self, name: str) -> int:
        if name == "ROOT":
            return CLIENT_INVALID
        if name not in self._ids:
            self._ids[name] = len(self.names)
            self.names.append(name)
        return self._ids[name]

    def id_of(self, name: str) -> int:
        if name == "ROOT":
            return CLIENT_INVALID
        return self._ids[name]

    def __contains__(self, name: str) -> bool:
        return name in self._ids

    def rank_of_agent(self) -> np.ndarray:
        """rank_of_agent[dense agent id] -> name rank (u32)."""
        order = sorted(range(len(self.names)), key=lambda i: self.names[i])
        ranks = np.zeros(len(self.names), dtype=np.uint32)
        for r, i in enumerate(order):
            ranks[i] = r
        return ranks

    def rank_of(self, name: str) -> int:
        return int(self.rank_of_agent()[self.id_of(name)])


def rank_remap(old_names: Sequence[str], table: AgentTable) -> np.ndarray:
    """old-epoch rank -> new-epoch rank (u32[len(old_names)]).

    When a peer joins between compiled epochs, the sorted-name ranks of
    existing agents shift; device state that PERSISTED ranks (the by-order
    ``rank_log`` a ``FlatDoc`` carries for the Yjs tiebreak) must be
    re-based before applying steps compiled against the new table. Apply
    with ``span_arrays.remap_rank_log``.
    """
    for n in old_names:
        assert n in table._ids, f"agent {n!r} missing from the new table"
    old_sorted = sorted(old_names)
    out = np.zeros(len(old_names), dtype=np.uint32)
    for old_rank, name in enumerate(old_sorted):
        out[old_rank] = table.rank_of(name)
    return out


class OrderAssigner:
    """Host twin of the order-allocation metadata (`doc.rs:155-165`):
    per-agent seq->order RLE maps (`list/mod.rs:33-43`) + the dense
    ``next_order`` counter. Shared by the compiler and the causal layer."""

    def __init__(self, table: AgentTable):
        self.table = table
        self.item_orders: List[Rle[KOrderSpan]] = [
            Rle() for _ in table.names
        ]
        self.next_order = 0

    @classmethod
    def from_oracle(cls, doc, table: "AgentTable") -> "OrderAssigner":
        """Rebuild the compiler's order metadata from a live (or
        checkpoint-restored) oracle document, so compilation can resume
        mid-history — the serve layer's restore path
        (`serve/residency.py`): a doc evicted to a checkpoint loses its
        in-memory assigner, and the restored oracle's per-agent
        ``item_orders`` are exactly the state to resume from.

        ``table`` must list the oracle's agents in dense-id order (the
        checkpoint meta's ``agents`` list) so agent ids align."""
        assert table.names == [cd.name for cd in doc.client_data], (
            "agent table order must match the oracle's dense agent ids")
        out = cls(table)
        for aid, cd in enumerate(doc.client_data):
            io = out._orders_of(aid)
            for e in cd.item_orders:
                io.append(KOrderSpan(e.seq, e.order, e.length))
        out.next_order = doc.get_next_order()
        return out

    def _orders_of(self, agent_id: int) -> Rle:
        while agent_id >= len(self.item_orders):
            self.item_orders.append(Rle())
        return self.item_orders[agent_id]

    def next_seq(self, agent_id: int) -> int:
        io = self._orders_of(agent_id)
        last = io.last()
        return last.seq + last.length if last is not None else 0

    def assign(self, agent_id: int, seq: int, length: int) -> int:
        """Allocate ``length`` dense orders to (agent, seq..) and return the
        first (`doc.rs:155-165`)."""
        first = self.next_order
        self._orders_of(agent_id).append(KOrderSpan(seq, first, length))
        self.next_order += length
        return first

    def seq_to_order(self, agent_id: int, seq: int) -> int:
        found = self._orders_of(agent_id).find(seq)
        assert found is not None, f"unknown seq {seq} for agent {agent_id}"
        entry, off = found
        return entry.order + off

    def resolve(self, rid: RemoteId) -> int:
        if rid.agent == "ROOT":
            return ROOT_ORDER
        return self.seq_to_order(self.table.id_of(rid.agent), rid.seq)

    def target_runs(self, agent_id: int, seq: int,
                    length: int) -> List[Tuple[int, int]]:
        """Split a (agent, seq, len) delete target into order-contiguous
        (first_order, len) runs (the `doc.rs:311-334` fragmentation walk,
        done in seq space like the oracle)."""
        runs: List[Tuple[int, int]] = []
        io = self._orders_of(agent_id)
        remaining = length
        while remaining > 0:
            found = io.find(seq)
            assert found is not None, f"delete target seq {seq} unknown"
            entry, off = found
            take = min(entry.length - off, remaining)
            runs.append((entry.order + off, take))
            seq += take
            remaining -= take
        return runs


class _Rows:
    """Column accumulator for compiled steps."""

    def __init__(self, lmax: int):
        self.lmax = lmax
        self.cols: Dict[str, list] = {
            f.name: [] for f in dataclasses.fields(OpTensors)
        }

    def emit(self, *, kind=0, pos=0, del_len=0, del_target=0,
             origin_left=ROOT_ORDER, origin_right=ROOT_ORDER, ins_len=0,
             ins_order_start=0, order_advance=0, rank=0, rows=1,
             content="") -> None:
        # ``content``: str, or a uint32 codepoint array (``fuse_steps``
        # re-emits rows it already holds as codepoints — the serve tick
        # hot path — without a utf-32 decode/encode round trip).
        assert ins_len <= self.lmax
        assert rows >= 1 and (rows == 1 or ins_len % rows == 0)
        cps = np.zeros(self.lmax, dtype=np.uint32)
        if len(content):
            assert len(content) == ins_len
            if isinstance(content, str):
                cps[:ins_len] = np.frombuffer(
                    content.encode("utf-32-le"), dtype=np.uint32)
            else:
                cps[:ins_len] = content
        c = self.cols
        c["kind"].append(kind); c["pos"].append(pos)
        c["del_len"].append(del_len); c["del_target"].append(del_target)
        c["origin_left"].append(origin_left)
        c["origin_right"].append(origin_right)
        c["ins_len"].append(ins_len)
        c["ins_order_start"].append(ins_order_start)
        c["order_advance"].append(order_advance)
        c["rank"].append(rank)
        c["rows_per_step"].append(rows)
        c["chars"].append(cps)

    def to_tensors(self) -> OpTensors:
        c = self.cols
        return OpTensors(
            **{k: np.asarray(v, dtype=np.uint32) for k, v in c.items()
               if k != "chars"},
            chars=(np.stack(c["chars"]) if c["chars"]
                   else np.zeros((0, self.lmax), dtype=np.uint32)),
        )


def merge_patches(patches: Sequence[TestPatch]) -> List[TestPatch]:
    """RLE-coalesce adjacent same-kind, position-contiguous patches.

    The op-stream analog of the reference's in-tree merge fast paths
    (`mutations.rs:57-109`): a typing run (each insert continuing at
    ``pos + len(prev)``), a forward-delete run (same ``pos``), or a
    backspace run (next delete ending at the previous ``pos``) collapses
    to ONE op. The merged stream is *semantically identical* to the
    per-keystroke stream — same final state, same per-char orders, same
    origins:

    - insert runs: char k of the merged run gets origin_left = char k-1
      and the shared origin_right, exactly the implicit origin chain a
      span keeps (`span.rs:9-18,24-28`); the unmerged stream's per-patch
      head origins resolve to the same values because nothing intervenes
      between the coalesced patches;
    - delete runs: the same char set is tombstoned and the same number
      of orders is consumed (order totals are preserved patch-for-patch),
      so device state and ``next_order`` are bit-identical.  CAVEAT
      (advisor r3): coalescing a BACKSPACE run into one forward delete
      span reverses the delete-order -> target-char attribution relative
      to the unmerged stream (final state, origins and next_order are
      unchanged, but a per-delete-op version log derived from a merged
      stream would attribute delete orders to the wrong chars — emit
      such logs from the unmerged stream, as ``models.sync`` does);
    - mixed (delete+insert) patches and any position discontinuity break
      the run, so no reordering across unrelated edits ever happens.

    automerge-paper: 259,778 patches -> 10,712 merged ops (24.3x fewer
    device steps). Callers report ops/s against the ORIGINAL patch
    count; the merged stream is an execution strategy, not a workload
    reduction (the native baseline replays the unmerged stream).
    """
    out: List[TestPatch] = []
    for p in patches:
        if out:
            q = out[-1]
            if (q.del_len == 0 and p.del_len == 0 and p.ins_content
                    and q.ins_content
                    and p.pos == q.pos + len(q.ins_content)):
                q.ins_content += p.ins_content
                continue
            if (not q.ins_content and not p.ins_content
                    and q.del_len and p.del_len):
                if p.pos == q.pos:               # forward-delete run
                    q.del_len += p.del_len
                    continue
                if p.pos + p.del_len == q.pos:   # backspace run
                    q.pos = p.pos
                    q.del_len += p.del_len
                    continue
        out.append(TestPatch(p.pos, p.del_len, p.ins_content))
    return out


def fused_width(ops: OpTensors) -> int:
    """Max ``rows_per_step`` of a compiled stream (1 for empty streams).
    Engines without W-row splice support gate on this; the fused
    engines size their shift bound and block headroom from it."""
    r = np.asarray(ops.rows_per_step)
    return max(int(r.max()) if r.size else 1, 1)


def fused_engine_names() -> Tuple[str, ...]:
    """Engines whose insert splice accepts W-row fused steps, from the
    ONE registry (``config.ENGINE_REGISTRY`` ``fused_steps``) — error
    messages and serve gating derive from this instead of hard-coded
    module lists that rot as engines gain the splice."""
    from ..config import ENGINE_REGISTRY

    return tuple(n for n, spec in ENGINE_REGISTRY.items()
                 if spec.get("fused_steps"))


def require_unfused(ops: OpTensors, engine: str) -> None:
    """The ONE reject guard for engines without the W-row splice (every
    engine without a registry ``fused_steps`` flag calls this at build
    time — a fused stream on an unfused engine would silently misapply,
    its row columns read as one wide plain insert)."""
    if fused_width(ops) > 1:
        raise ValueError(
            f"{engine} has no fused multi-row splice; compile with "
            f"fuse_w=1 (fused streams run on the registry fused_steps "
            f"engines: {', '.join(fused_engine_names())})")


def fused_width_checked(streams, block_k: int) -> int:
    """WMAX of a stream set, validated against the fused engines' ONE
    rule: ``WMAX <= K//2 - 1`` — a freshly split block holds up to
    ceil(K/2) rows and must fit W new rows + one split tail, so a
    single amortized-O(1) leaf split always makes room for a fused
    step.  Shared by ops.rle / ops.rle_hbm so the headroom contract
    cannot drift between them."""
    wmax = max(fused_width(st) for st in streams)
    if wmax > 1 and wmax > block_k // 2 - 1:
        raise ValueError(
            f"fused rows_per_step {wmax} exceeds the one-split headroom "
            f"of block_k {block_k} (need WMAX <= K//2 - 1: a freshly "
            f"split block holds up to ceil(K/2) rows and must fit W+1 "
            f"more)")
    return wmax


def _burst_len(patches: Sequence[TestPatch], i: int) -> int:
    """Length of the maximal backwards-contiguous insert burst starting
    at patch ``i``: consecutive insert-only patches at the SAME document
    position with EQUAL insert lengths (the kevin prepend shape — each
    patch's text lands immediately BEFORE the previous patch's, so the
    relative run layout is statically known)."""
    p0 = patches[i]
    if p0.del_len or not p0.ins_content:
        return 1
    L = len(p0.ins_content)
    j = i + 1
    while (j < len(patches) and not patches[j].del_len
           and len(patches[j].ins_content) == L
           and patches[j].pos == p0.pos):
        j += 1
    return j - i


def compile_local_patches(
    patches: Sequence[TestPatch],
    rank: int = 0,
    lmax: int = 16,
    start_order: int = 0,
    dmax: Optional[int] = None,
    fuse_w: int = 1,
    fuse_shapes: str = "burst",
) -> Tuple[OpTensors, int]:
    """Single-author local edit stream -> op tensors.

    Returns ``(ops, next_order)``. Each patch deletes then inserts at
    ``pos`` (`doc.rs:392-464` op order: delete ops take the earlier order
    numbers, then the insert run). ``dmax`` additionally chunks deletes
    (the blocked engine bounds per-step delete spans; the flat engine's
    live-rank window op handles any span, so None = unchunked).

    ``fuse_w > 1`` enables SPLIT-BATCH PREPARE: a backwards-contiguous
    insert burst (``_burst_len``) is compiled into fused multi-row
    steps of up to ``fuse_w`` patches each — ONE device step splicing W
    pre-built run rows (descending orders, stride L) instead of W
    steps.  Semantically identical to the unfused stream: orders,
    chars, and origins are unchanged (patch k's origin_left is the
    shared left neighbour, its origin_right is patch k-1's head — the
    successor at its insert time), and the engines' expanded state is
    bit-identical (a burst never exercises the in-kernel append-merge:
    only the burst's FIRST patch could merge, and the second patch's
    splice would split that merged run at the exact same boundary the
    unfused stream does).  Only the fused engines (``ENGINE_REGISTRY``
    entries with ``fused_steps``) accept W > 1 streams.

    ``fuse_shapes="all"`` additionally runs the GENERALIZED step fuser
    (``fuse_steps``: typing runs, delete sweeps, replace pairs, remote
    runs — ISSUE 6) over the compiled rows before returning; "burst"
    keeps today's behavior (the patch-level kevin detector only).
    """
    assert dmax is None or dmax >= 1, f"dmax must be >= 1, got {dmax}"
    assert fuse_w >= 1, f"fuse_w must be >= 1, got {fuse_w}"
    assert fuse_shapes in ("burst", "all"), fuse_shapes
    rows = _Rows(lmax)
    next_order = start_order
    patches = list(patches)
    i = 0
    while i < len(patches):
        p = patches[i]
        L = len(p.ins_content)
        w_cap = min(fuse_w, lmax // L) if L else 1
        # Scan for a burst only when one could actually fuse — an
        # unfusable shape (w_cap < 2) must not re-walk the remaining
        # run from every index (quadratic on long uniform streams).
        burst = _burst_len(patches, i) if (fuse_w > 1 and w_cap >= 2) \
            else 1
        if burst >= 2 and w_cap >= 2:
            while burst > 0:
                w = min(w_cap, burst)
                group = patches[i:i + w]
                # Chars are ORDER-major (patch k at [k*L, (k+1)*L)); the
                # device splices the rows in reverse patch order.
                rows.emit(
                    kind=KIND_LOCAL, pos=p.pos, ins_len=w * L,
                    ins_order_start=next_order, order_advance=w * L,
                    rank=rank, rows=w,
                    content="".join(g.ins_content for g in group),
                )
                next_order += w * L
                burst -= w
                i += w
            continue
        i += 1
        ins = p.ins_content
        first_chunk = ins[:lmax]
        dfirst = p.del_len if dmax is None else min(p.del_len, dmax)
        # First step: (a chunk of) the delete + the first insert chunk.
        rows.emit(
            kind=KIND_LOCAL, pos=p.pos, del_len=dfirst,
            ins_len=len(first_chunk),
            ins_order_start=next_order + p.del_len,
            order_advance=dfirst + len(first_chunk),
            rank=rank, content=first_chunk,
        )
        next_order += p.del_len + len(first_chunk)
        # Remaining delete chunks run after the first insert chunk landed
        # at pos, so the chars still to delete now sit after it: target
        # pos + len(first_chunk).
        doff = dfirst
        while doff < p.del_len:
            chunk_len = min(p.del_len - doff, dmax)
            rows.emit(
                kind=KIND_LOCAL, pos=p.pos + len(first_chunk),
                del_len=chunk_len, order_advance=chunk_len, rank=rank,
            )
            doff += chunk_len
        off = len(first_chunk)
        while off < len(ins):
            chunk = ins[off:off + lmax]
            rows.emit(
                kind=KIND_LOCAL, pos=p.pos + off, ins_len=len(chunk),
                ins_order_start=next_order, order_advance=len(chunk),
                rank=rank, content=chunk,
            )
            next_order += len(chunk)
            off += len(chunk)
    ops = rows.to_tensors()
    if fuse_shapes == "all":
        ops, _ = fuse_steps(ops, fuse_w=fuse_w, dmax=dmax)
    return ops, next_order


def compile_trace(data: TestData, rank: int = 0, lmax: int = 16
                  ) -> Tuple[OpTensors, int]:
    """Whole-trace convenience wrapper (the `benches/yjs.rs:32-49` replay)."""
    return compile_local_patches(flatten_patches(data), rank=rank, lmax=lmax)


# -- generalized step fusion (ISSUE 6) ---------------------------------------

# Fusable shapes, named for the histogram.  Each entry counts ROWS
# ELIMINATED (ops that piggybacked on an earlier step's row).
FUSE_SHAPES = ("typing", "sweep", "replace", "burst",
               "remote_ins_run", "remote_del_run")


@dataclasses.dataclass
class FuseStats:
    """Per-shape accounting of one ``fuse_steps`` pass.

    ``step_map`` maps each INPUT step index to the OUTPUT (fused) step
    that absorbed it — monotone non-decreasing, length ``steps_in`` —
    so a caller that knows which input rows an op compiled into can
    name the fused super-step the op landed in (the obs/flow per-op
    provenance join).  ``None`` until a ``fuse_steps`` pass fills it;
    ``merge`` drops it (per-stream maps don't concatenate)."""

    steps_in: int = 0
    steps_out: int = 0
    fused: Dict[str, int] = dataclasses.field(
        default_factory=lambda: {s: 0 for s in FUSE_SHAPES})
    step_map: Optional[List[int]] = None

    @property
    def rows_saved(self) -> int:
        return self.steps_in - self.steps_out

    @property
    def reduction_x(self) -> float:
        return self.steps_in / self.steps_out if self.steps_out else 1.0

    def to_dict(self) -> Dict[str, object]:
        return {"steps_in": self.steps_in, "steps_out": self.steps_out,
                "rows_saved": self.rows_saved,
                "reduction_x": round(self.reduction_x, 3),
                "fused": dict(self.fused)}

    def merge(self, other: "FuseStats") -> None:
        self.steps_in += other.steps_in
        self.steps_out += other.steps_out
        self.step_map = None  # per-stream; meaningless across merges
        for k, v in other.fused.items():
            self.fused[k] = self.fused.get(k, 0) + v


class _FRow:
    """One mutable step row while the fuser walks the stream."""

    __slots__ = ("kind", "pos", "del_len", "del_target", "origin_left",
                 "origin_right", "ins_len", "st", "order_advance", "rank",
                 "w", "chars")

    def __init__(self, kind, pos, del_len, del_target, origin_left,
                 origin_right, ins_len, st, order_advance, rank, w,
                 chars):
        self.kind = kind; self.pos = pos; self.del_len = del_len
        self.del_target = del_target; self.origin_left = origin_left
        self.origin_right = origin_right; self.ins_len = ins_len
        self.st = st; self.order_advance = order_advance
        self.rank = rank; self.w = w
        self.chars = chars  # logical content only (ins_len entries)

    @property
    def stride(self) -> int:
        return self.ins_len // self.w if self.w else self.ins_len

    def is_noop(self) -> bool:
        return self.del_len == 0 and self.ins_len == 0


def _try_fuse(cur: _FRow, nxt: _FRow, lmax: int, fuse_w: int,
              dmax=None):
    """Try to fold step ``nxt`` into ``cur`` (adjacent in the stream, so
    nothing intervenes).  Returns the shape name on success (``cur``
    mutated), else None.  Every rule preserves the device-visible state
    bit-exactly (final runs/tombstones, by-order origin/rank/char logs,
    ``next_order``); see the per-rule notes — the correctness burden is
    carried by ``tests/test_rle_fused.py``'s fused-vs-unfused fuzz.

    Cross-agent fusion (YATA commutativity of causally-independent ops,
    PAPERS.md Nicolaescu et al.) is admitted exactly where no insert
    attribution is merged: delete sweeps, remote delete runs, and the
    delete half of a replace carry no rank into device state (ranks are
    only logged for inserted chars), so differing authors fuse safely.
    Insert-bearing rules require equal ranks — a merged run's whole
    span logs ONE rank — and an op whose origin lands inside the other
    op's span can never satisfy the chain/contiguity conditions below,
    so it falls back to its own step (the overlap rejection)."""
    if nxt.w != 1 or nxt.is_noop() or cur.is_noop():
        return None
    loc = KIND_LOCAL
    # ``dmax`` mirrors the compile-time per-step delete-span bound: a
    # stream chunked at dmax (e.g. for an engine with a hard per-step
    # target cap) must not have its delete runs re-merged past it.
    del_fits = (dmax is None
                or cur.del_len + nxt.del_len <= dmax)

    # (a→kevin) backwards-contiguous insert burst -> one W-row step:
    # same position, equal lengths L, ascending orders.  In doc order
    # the burst is W runs with DESCENDING orders (each patch lands
    # before its predecessor); origins: shared left, patch k's right =
    # patch k-1's head (the W-row splice contract, PERF.md §11).
    if (fuse_w > 1 and cur.kind == loc and nxt.kind == loc
            and cur.del_len == 0 and nxt.del_len == 0
            and cur.ins_len > 0 and nxt.ins_len > 0
            and nxt.pos == cur.pos and cur.rank == nxt.rank
            and nxt.ins_len == cur.stride
            and nxt.st == cur.st + cur.ins_len
            and cur.w + 1 <= fuse_w
            and cur.ins_len + nxt.ins_len <= lmax):
        cur.w += 1
        cur.ins_len += nxt.ins_len
        cur.order_advance += nxt.order_advance
        cur.chars = np.concatenate([cur.chars, nxt.chars])
        return "burst"

    if cur.w != 1:
        return None

    # (a) forward typing run -> ONE coalesced row: position- and
    # order-contiguous, same author.  Identical to the host coalescer's
    # merge (``merge_patches`` semantics): the combined run keeps every
    # char's order, the implicit origin chain covers the old run heads
    # (head k's left IS its predecessor char), and the shared raw
    # successor is unchanged because nothing intervenes.  ``cur`` may
    # carry a delete (a replace's insert tail extends the same way).
    if (cur.kind == loc and nxt.kind == loc and nxt.del_len == 0
            and cur.ins_len > 0 and nxt.ins_len > 0
            and cur.rank == nxt.rank
            and nxt.pos == cur.pos + cur.ins_len
            and nxt.st == cur.st + cur.ins_len
            and cur.ins_len + nxt.ins_len <= lmax):
        cur.ins_len += nxt.ins_len
        cur.order_advance += nxt.order_advance
        cur.chars = np.concatenate([cur.chars, nxt.chars])
        return "typing"

    # (b) local delete sweep -> one covered-range walk: forward-delete
    # (same position) or backspace (next range ends where this one
    # starts).  Deletes log no rank, so cross-agent sweeps fuse.
    if (cur.kind == loc and nxt.kind == loc and cur.ins_len == 0
            and nxt.ins_len == 0 and cur.del_len > 0 and nxt.del_len > 0
            and del_fits):
        if nxt.pos == cur.pos:                     # forward-delete run
            cur.del_len += nxt.del_len
            cur.order_advance += nxt.order_advance
            return "sweep"
        if nxt.pos + nxt.del_len == cur.pos:       # backspace run
            cur.pos = nxt.pos
            cur.del_len += nxt.del_len
            cur.order_advance += nxt.order_advance
            return "sweep"
        return None

    # (c) replace fusion: a pure delete followed by a pure insert at
    # the SAME position is exactly the delete+insert pair one compiled
    # KIND_LOCAL row already expresses (every engine fires the delete
    # branch, then the insert branch, with the same arguments the two
    # separate steps would use).  The delete's author logs nothing, so
    # the pair fuses across agents too.
    if (cur.kind == loc and nxt.kind == loc and cur.ins_len == 0
            and cur.del_len > 0 and nxt.del_len == 0 and nxt.ins_len > 0
            and nxt.pos == cur.pos):
        cur.ins_len = nxt.ins_len
        cur.st = nxt.st
        cur.rank = nxt.rank
        cur.order_advance += nxt.order_advance
        cur.chars = nxt.chars
        return "replace"

    # (a-remote) remote insert run: the next run's origin_left chains
    # to this run's tail, shares its origin_right, and continues its
    # orders — the continued-typing shape ``compile_remote_txns`` emits
    # for chunked runs, now fused ACROSS txns.  The combined run
    # integrates at the same cursor: any run the unfused tail-scan
    # would meet has an origin_left strictly left of the tail (a char's
    # left origin precedes it; referencing the tail itself would be
    # causally impossible before this step), so the scan breaks
    # immediately and the tail lands flush after the head either way.
    if (cur.kind == KIND_REMOTE_INS and nxt.kind == KIND_REMOTE_INS
            and cur.ins_len > 0 and nxt.ins_len > 0
            and cur.rank == nxt.rank
            and nxt.origin_left == cur.st + cur.ins_len - 1
            and nxt.origin_right == cur.origin_right
            and nxt.st == cur.st + cur.ins_len
            and cur.ins_len + nxt.ins_len <= lmax):
        cur.ins_len += nxt.ins_len
        cur.order_advance += nxt.order_advance
        cur.chars = np.concatenate([cur.chars, nxt.chars])
        return "remote_ins_run"

    # (b-remote) remote delete run: order-contiguous target ranges
    # (forward sweep or backspace sweep in order space) tombstone one
    # union interval; disjoint adjacent ranges applied back-to-back
    # equal the single interval op, including the dead-run idempotency
    # accounting.  Rank-free -> cross-agent.
    if (cur.kind == KIND_REMOTE_DEL and nxt.kind == KIND_REMOTE_DEL
            and cur.del_len > 0 and nxt.del_len > 0
            and del_fits):
        if nxt.del_target == cur.del_target + cur.del_len:
            cur.del_len += nxt.del_len
            cur.order_advance += nxt.order_advance
            return "remote_del_run"
        if nxt.del_target + nxt.del_len == cur.del_target:
            cur.del_target = nxt.del_target
            cur.del_len += nxt.del_len
            cur.order_advance += nxt.order_advance
            return "remote_del_run"
        return None

    return None


def fuse_steps(ops: OpTensors, lmax: Optional[int] = None,
               fuse_w: int = 1, dmax: Optional[int] = None
               ) -> Tuple[OpTensors, FuseStats]:
    """Generalized step fusion: one greedy adjacent pass over a compiled
    stream, folding the fusable shapes (``FUSE_SHAPES``) into multi-op
    device steps.  The kevin detector (`compile_local_patches(fuse_w)`)
    only sees backwards bursts inside ONE patch list; this pass runs on
    any compiled stream — notably the serve batcher's per-doc tick
    streams, where each event compiles separately and the host
    coalescer never gets a look (ROADMAP item 4's "one device step per
    op" tax on typing runs, backspace sweeps, replaces and same-tick
    cross-agent ops).

    ``fuse_w`` > 1 additionally emits W-row backwards-burst steps
    (``rows_per_step`` > 1) and requires an engine with the registry
    ``fused_steps`` splice; the coalescing shapes emit plain W=1 rows
    every engine accepts.  ``lmax`` caps merged insert lengths (default:
    the stream's chars width); ``dmax`` caps merged delete spans — pass
    the bound the stream was compiled with so fusion never re-merges
    delete runs past an engine's per-step target cap.  Returns
    ``(fused_ops, FuseStats)``;
    orders, origins, ranks and chars are preserved column-for-column, so
    the fused stream is bit-identical in device state to the unfused
    one (the ``tests/test_rle_fused.py`` contract)."""
    kinds = np.asarray(ops.kind)
    assert kinds.ndim == 1, (
        "fuse_steps takes one unbatched [S] stream; fuse per-doc "
        "streams BEFORE stack_ops")
    assert fuse_w >= 1
    lmax = ops.lmax if lmax is None else min(lmax, ops.lmax)
    stats = FuseStats(steps_in=int(kinds.shape[0]))
    if kinds.shape[0] == 0:
        return ops, stats

    cols = {f: np.asarray(getattr(ops, f if f != "st" else
                                  "ins_order_start"))
            for f in ("kind", "pos", "del_len", "del_target",
                      "origin_left", "origin_right", "ins_len", "st",
                      "order_advance", "rank")}
    w_col = np.asarray(ops.rows_per_step)
    chars = np.asarray(ops.chars)

    def row(i) -> _FRow:
        il = int(cols["ins_len"][i])
        return _FRow(*(int(cols[f][i]) for f in
                       ("kind", "pos", "del_len", "del_target",
                        "origin_left", "origin_right", "ins_len", "st",
                        "order_advance", "rank")),
                     max(int(w_col[i]), 1), chars[i, :il].copy())

    out = _Rows(ops.lmax)

    def emit(r: _FRow) -> None:
        content = r.chars if r.ins_len else ""
        out.emit(kind=r.kind, pos=r.pos, del_len=r.del_len,
                 del_target=r.del_target, origin_left=r.origin_left,
                 origin_right=r.origin_right, ins_len=r.ins_len,
                 ins_order_start=r.st, order_advance=r.order_advance,
                 rank=r.rank, rows=r.w, content=content)

    step_map = [0] * stats.steps_in
    cur_inputs = [0]
    emitted_n = 0
    cur = row(0)
    for i in range(1, stats.steps_in):
        nxt = row(i)
        shape = _try_fuse(cur, nxt, lmax, fuse_w, dmax)
        if shape is None:
            for j in cur_inputs:
                step_map[j] = emitted_n
            emitted_n += 1
            emit(cur)
            cur = nxt
            cur_inputs = [i]
        else:
            stats.fused[shape] += 1
            cur_inputs.append(i)
    for j in cur_inputs:
        step_map[j] = emitted_n
    emit(cur)
    stats.step_map = step_map
    fused = out.to_tensors()
    stats.steps_out = fused.num_steps
    assert (int(np.asarray(fused.order_advance, dtype=np.int64).sum())
            == int(np.asarray(ops.order_advance, dtype=np.int64).sum())), \
        "fusion changed the stream's order consumption"
    return fused, stats


def merge_fused_origins(ol_log, or_log, ops: OpTensors,
                        ol_np, or_np) -> None:
    """Merge a replay's per-step kernel origins into the by-order logs
    in place, expanding fused W-row steps (shared by ``rle.rle_to_flat``
    and ``rle_lanes.lanes_to_flat`` so the chain convention lives
    ONCE): a fused step's kernel origins are patch 0's — left is
    SHARED by every sub-run head (orders st + k*L), and rights chain
    statically (patch k's raw successor at insert time is patch k-1's
    head, order st + (k-1)*L)."""
    starts = np.asarray(ops.ins_order_start, dtype=np.int64)
    ilens = np.asarray(ops.ins_len, dtype=np.int64)
    ws = np.maximum(np.asarray(ops.rows_per_step, dtype=np.int64), 1)
    for st, il, w, left, right in zip(starts, ilens, ws, ol_np, or_np):
        if il > 0:
            L = il // w
            for k in range(w):
                ol_log[st + k * L] = left
                or_log[st + k * L: st + (k + 1) * L] = (
                    right if k == 0 else st + (k - 1) * L)


def compile_remote_txns(
    txns: Sequence[RemoteTxn],
    table: AgentTable,
    assigner: Optional[OrderAssigner] = None,
    lmax: int = 16,
    dmax: Optional[int] = None,
) -> Tuple[OpTensors, OrderAssigner]:
    """Causally-ordered RemoteTxn stream -> op tensors (`doc.rs:242-348`).

    The ``assigner`` carries the peer-local order metadata between calls
    (streaming apply); txns must arrive causally ready — buffering
    out-of-order arrivals is ``parallel.causal``'s job. ``dmax`` chunks
    remote delete target runs (the blocked mixed engine bounds per-step
    targets; the flat engine masks whole order ranges, so None there).
    """
    assert dmax is None or dmax >= 1, f"dmax must be >= 1, got {dmax}"
    if assigner is None:
        assigner = OrderAssigner(table)
    ranks = table.rank_of_agent()
    rows = _Rows(lmax)
    for txn in txns:
        agent = table.id_of(txn.id.agent)
        assert assigner.next_seq(agent) == txn.id.seq, (
            f"remote txn out of order: expected seq "
            f"{assigner.next_seq(agent)}, got {txn.id.seq} "
            f"(buffer with parallel.causal.CausalBuffer)"
        )
        length = txn_len(txn)
        assert length > 0, "empty remote txn"
        # Orders for the whole txn are allocated up front (`doc.rs:265-269`)
        # so intra-txn origin references resolve.
        cursor = assigner.assign(agent, txn.id.seq, length)
        for op in txn.ops:
            if isinstance(op, RemoteIns):
                ins = op.ins_content
                if not ins:
                    continue
                origin_left = assigner.resolve(op.origin_left)
                origin_right = assigner.resolve(op.origin_right)
                off = 0
                while off < len(ins):
                    chunk = ins[off:off + lmax]
                    rows.emit(
                        kind=KIND_REMOTE_INS,
                        origin_left=origin_left,
                        origin_right=origin_right,
                        ins_len=len(chunk), ins_order_start=cursor,
                        order_advance=len(chunk),
                        rank=int(ranks[agent]), content=chunk,
                    )
                    origin_left = cursor + len(chunk) - 1
                    cursor += len(chunk)
                    off += len(chunk)
            else:
                assert isinstance(op, RemoteDel)
                target_agent = table.id_of(op.id.agent)
                for first, run_len in assigner.target_runs(
                        target_agent, op.id.seq, op.len):
                    off = 0
                    while off < run_len:
                        take = (run_len - off if dmax is None
                                else min(run_len - off, dmax))
                        rows.emit(
                            kind=KIND_REMOTE_DEL, del_target=first + off,
                            del_len=take, order_advance=take,
                            rank=int(ranks[agent]),
                        )
                        off += take
                    cursor += run_len
    return rows.to_tensors(), assigner


# -- log prefill -------------------------------------------------------------


def _prefill_scatter(ops: OpTensors):
    """The compile-time-known log writes of one unbatched op stream, as
    (positions, values) pairs. See ``prefill_logs``."""
    ins_len = np.asarray(ops.ins_len, dtype=np.int64)
    starts = np.asarray(ops.ins_order_start, dtype=np.int64)
    kinds = np.asarray(ops.kind)
    op_chars = np.asarray(ops.chars)
    ranks = np.asarray(ops.rank)
    ol_ops = np.asarray(ops.origin_left)
    or_ops = np.asarray(ops.origin_right)
    wsteps = np.maximum(np.asarray(ops.rows_per_step, dtype=np.int64), 1)

    sel = ins_len > 0
    if not sel.any():
        return None
    reps = ins_len[sel]
    total = int(reps.sum())
    step_idx = np.repeat(np.nonzero(sel)[0], reps)
    within = np.arange(total) - np.repeat(
        np.cumsum(reps) - reps, reps)
    pos = starts[sel].repeat(reps) + within

    # Within-run implicit origin chain (`span.rs:9-13,24-28`): item k's
    # origin_left is order+k-1. The run head's origins are known at compile
    # time only for remote inserts; local heads are written on device.
    # A FUSED step carries rows_per_step sub-runs of stride L = il/W —
    # the chain breaks at every sub-run head (their origins come from the
    # device/host fused-origin merge, `rle.rle_to_flat`).
    stride = np.repeat(ins_len[sel] // wsteps[sel], reps)
    chain = (within % stride) != 0
    remote = kinds[step_idx] == KIND_REMOTE_INS
    head = ~chain & remote
    return {
        "chars": (pos, op_chars[step_idx, within]),
        "rank": (pos, ranks[step_idx]),
        "ol": (np.concatenate([pos[chain], pos[head]]),
               np.concatenate([(pos[chain] - 1).astype(np.uint32),
                               ol_ops[step_idx[head]]])),
        "or": (pos[remote], or_ops[step_idx[remote]]),
    }


def _apply_scatter(ol, orr, rank, chars, sc) -> None:
    """Apply a scatter to 1-D ``[OCAP]`` or 2-D ``[B, OCAP]`` logs (the
    trailing-axis fancy index broadcasts over the doc axis)."""
    if sc is None:
        return
    chars[..., sc["chars"][0]] = sc["chars"][1]
    rank[..., sc["rank"][0]] = sc["rank"][1]
    ol[..., sc["ol"][0]] = sc["ol"][1]
    orr[..., sc["or"][0]] = sc["or"][1]


def prefill_logs(doc, ops: OpTensors):
    """Fill a ``FlatDoc``'s by-order logs with everything the compiler
    already knows about ``ops``: chars, author ranks, remote origins, and
    every insert run's implicit origin chain. The device then only writes
    the two origins a *local* insert discovers at apply time
    (`doc.rs:447-453`).

    ``ops`` may be unbatched ``[S, ...]`` (doc unbatched, or one stream
    shared by every doc of a batched doc) or batched ``[S, B, ...]`` (doc
    batched ``[B, ...]``). Tiled batches (every doc's column identical,
    the ``tile_ops`` output) are detected and prefilled with one scatter
    broadcast across the doc axis. Returns a new doc; host-side numpy.
    """
    import jax.numpy as jnp

    ops_batched = np.asarray(ops.kind).ndim == 2
    ol = np.array(doc.ol_log)
    orr = np.array(doc.or_log)
    rank = np.array(doc.rank_log)
    chars = np.array(doc.chars_log)
    if ol.ndim == 1:
        assert not ops_batched, "batched ops need a batched doc"
        _apply_scatter(ol, orr, rank, chars, _prefill_scatter(ops))
    elif not ops_batched:
        _apply_scatter(ol, orr, rank, chars, _prefill_scatter(ops))
    else:
        def tiled(a):
            a = np.asarray(a)
            return bool((a == a[:, :1] if a.ndim == 2
                         else a == a[:, :1, ...]).all())

        if all(tiled(np.asarray(c)) for c in
               (ops.kind, ops.ins_len, ops.ins_order_start, ops.rank,
                ops.origin_left, ops.origin_right, ops.chars)):
            one = jax.tree.map(lambda a: np.asarray(a)[:, 0], ops)
            _apply_scatter(ol, orr, rank, chars, _prefill_scatter(one))
        else:
            for b in range(ol.shape[0]):
                per_doc = jax.tree.map(lambda a: np.asarray(a)[:, b], ops)
                _apply_scatter(ol[b], orr[b], rank[b], chars[b],
                               _prefill_scatter(per_doc))
    return dataclasses.replace(
        doc, ol_log=jnp.asarray(ol), or_log=jnp.asarray(orr),
        rank_log=jnp.asarray(rank), chars_log=jnp.asarray(chars))


# -- device-resident prefill (ISSUE 14) --------------------------------------
# The serve tick used to round-trip the four FULL [B, OCAP] by-order
# logs through host numpy every tick (``prefill_logs`` materializes,
# scatters a few hundred compile-time-known values, re-uploads) — an
# O(state) host cost on an O(ops) edit, and a hidden device sync on the
# previous tick's output that eats the pipelined overlap under real
# async dispatch.  ``prefill_delta`` ships ONLY the scatter — fixed-
# shape padded (positions, values) tensors — and ``ops.flat.
# apply_prefill_delta`` applies it on device (``.at[pos].set(...,
# mode="drop")``), so the logs stay device-resident for the life of a
# lane.  Scatter lengths are padded to a small geometric bucket set
# (``scatter_bucket``) so steady-state serving compiles one scatter
# program per bucket, exactly the step-bucket discipline of the tick.

#: Padding position for scatter tensors: positive, out of range for any
#: real order capacity (< 2^31), so ``mode="drop"`` discards it.  The
#: value columns pad with 0 (never read — the position is dropped).
PREFILL_PAD = np.uint32(0x7FFFFFFF)

#: Smallest scatter bucket; buckets grow geometrically (x4) from here,
#: so a serve shape sees at most ~4-5 distinct scatter programs no
#: matter how ragged the per-tick insert volume is.
PREFILL_BUCKET_BASE = 32


def scatter_bucket(n: int) -> int:
    """Smallest bucket (PREFILL_BUCKET_BASE * 4^k) holding ``n`` scatter
    entries — the fixed-shape pad target that keeps the jitted device
    scatter's compile cache bounded (geometric growth: any workload sees
    O(log n) distinct shapes, and a serve tick's scatter is capped at
    S_bucket * lmax entries anyway)."""
    b = PREFILL_BUCKET_BASE
    while b < n:
        b *= 4
    return b


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["ins_pos", "chars_val", "rank_val", "ol_pos", "ol_val",
                 "or_pos", "or_val"],
    meta_fields=["bucket"],
)
@dataclasses.dataclass
class PrefillDelta:
    """The compile-time-known by-order log writes of an op stream as
    fixed-shape padded scatter tensors (``[L]`` for one stream,
    ``[B, L]`` for a stacked batch; ``L = bucket``).

    ``chars_log`` and ``rank_log`` share one position column (every
    inserted char gets both); ``ol``/``or`` carry their own (the chain
    subset / the remote subset).  Padding rows hold ``PREFILL_PAD``
    positions, dropped by the device scatter's ``mode="drop"``.
    ``bucket`` is static metadata (part of the jit cache key) — it is
    drawn from ``scatter_bucket``'s geometric series, so the compiled
    scatter set stays bounded."""

    ins_pos: jax.Array    # u32[..., L] chars/rank write positions
    chars_val: jax.Array  # u32[..., L]
    rank_val: jax.Array   # u32[..., L]
    ol_pos: jax.Array     # u32[..., L] origin_left writes (chain + heads)
    ol_val: jax.Array     # u32[..., L]
    or_pos: jax.Array     # u32[..., L] origin_right writes (remote runs)
    or_val: jax.Array     # u32[..., L]
    bucket: int

    def nbytes(self) -> int:
        """Bytes this delta moves host->device (the whole cost of a
        device-resident prefill; compare 2 * 4 * OCAP * B * 4 for the
        full-log round trip)."""
        return sum(np.asarray(getattr(self, f)).nbytes for f in
                   ("ins_pos", "chars_val", "rank_val", "ol_pos",
                    "ol_val", "or_pos", "or_val"))


def _delta_rows(sc, L: int):
    """One lane's scatter dict -> seven padded length-L u32 rows."""
    ins_pos = np.full(L, PREFILL_PAD, np.uint32)
    chars_val = np.zeros(L, np.uint32)
    rank_val = np.zeros(L, np.uint32)
    ol_pos = np.full(L, PREFILL_PAD, np.uint32)
    ol_val = np.zeros(L, np.uint32)
    or_pos = np.full(L, PREFILL_PAD, np.uint32)
    or_val = np.zeros(L, np.uint32)
    if sc is not None:
        p, v = sc["chars"]
        ins_pos[:len(p)] = p
        chars_val[:len(p)] = v
        rank_val[:len(p)] = sc["rank"][1]
        p, v = sc["ol"]
        ol_pos[:len(p)] = p
        ol_val[:len(p)] = v
        p, v = sc["or"]
        or_pos[:len(p)] = p
        or_val[:len(p)] = v
    return ins_pos, chars_val, rank_val, ol_pos, ol_val, or_pos, or_val


def prefill_delta(ops: OpTensors) -> Optional[PrefillDelta]:
    """``_prefill_scatter`` as fixed-shape padded device tensors: the
    delta-prefill twin of ``prefill_logs`` (ISSUE 14).  ``ops`` may be
    unbatched ``[S, ...]`` or batched ``[S, B, ...]`` (one scatter row
    per lane).  Returns ``None`` when the stream inserts nothing (a
    pure-delete or all-padding tick writes no log values, and skipping
    the scatter call entirely keeps the compile set minimal) — callers
    skip the device scatter in that case.

    Correctness contract (pinned by ``tests/test_device_prefill.py``):
    applying the delta on device (``ops.flat.apply_prefill_delta``) is
    bit-identical to ``prefill_logs`` on every log, for local, remote,
    mixed, fused (``rows_per_step`` > 1) and tiled streams — both paths
    are projections of the SAME ``_prefill_scatter``."""
    batched = np.asarray(ops.kind).ndim == 2
    if not batched:
        scs = [_prefill_scatter(ops)]
    else:
        host = jax.tree.map(np.asarray, ops)
        scs = [_prefill_scatter(jax.tree.map(lambda a: a[:, b], host))
               for b in range(np.asarray(ops.kind).shape[1])]
    if all(sc is None for sc in scs):
        return None
    need = max(len(sc["chars"][0]) for sc in scs if sc is not None)
    L = scatter_bucket(need)
    cols = [np.stack(rows) if batched else rows[0]
            for rows in zip(*(_delta_rows(sc, L) for sc in scs))]
    return PrefillDelta(*cols, bucket=L)


def concat_deltas(deltas) -> Optional[PrefillDelta]:
    """Concatenate T per-tick ``PrefillDelta``s (same batch shape) into
    one padded delta for a tick train (ISSUE 20).  Per-tick scatter
    positions land in disjoint fresh order ranges (orders are allocated
    uniquely and monotonically per lane), so applying the concatenation
    once before the train scan is bit-identical to applying each delta
    before its tick.  ``None`` entries (no-insert ticks) contribute
    nothing; returns ``None`` when every tick was insert-free.  The
    result is re-padded to the ``scatter_bucket`` series, so the train
    path draws from the SAME compiled scatter set as the serial path."""
    live = [d for d in deltas if d is not None]
    if not live:
        return None
    if len(live) == 1:
        return live[0]
    total = sum(d.bucket for d in live)
    L = scatter_bucket(total)
    fields = ("ins_pos", "chars_val", "rank_val", "ol_pos", "ol_val",
              "or_pos", "or_val")
    pads = {"ins_pos": PREFILL_PAD, "ol_pos": PREFILL_PAD,
            "or_pos": PREFILL_PAD}
    cols = []
    for f in fields:
        col = np.concatenate(
            [np.asarray(getattr(d, f)) for d in live], axis=-1)
        if col.shape[-1] < L:
            width = [(0, 0)] * (col.ndim - 1) + [(0, L - col.shape[-1])]
            col = np.pad(col, width,
                         constant_values=pads.get(f, np.uint32(0)))
        cols.append(col)
    return PrefillDelta(*cols, bucket=L)


def row_growth_bound(num_steps: int) -> int:
    """Sound per-lane run-row bound after ``num_steps`` compiled device
    steps: every step splices at most 2 new rows (insert splice / delete
    boundary splits / remote-delete endpoint retires), so a stream of S
    steps can never need more than ``1 + 2*S`` rows.  The growing
    per-chunk capacities of the streaming configs (and the blocked-lanes
    NB-per-chunk sizing) derive from this exact invariant — no sampling
    (PERF.md §7.2/§9)."""
    return 1 + 2 * num_steps


def row_growth_bound_ops(ops: OpTensors) -> int:
    """Fused-aware sound row bound for ONE compiled stream: a plain step
    splices at most 2 new rows (see ``row_growth_bound``); a fused
    W-row step splices at most W + 1 (W new runs + one split tail).
    Equals ``row_growth_bound(num_steps)`` on unfused streams."""
    w = np.maximum(
        np.asarray(ops.rows_per_step, dtype=np.int64).reshape(-1), 1)
    return 1 + int(np.maximum(2, w + 1).sum())


# -- batching ----------------------------------------------------------------


def pad_ops(ops: OpTensors, num_steps: int) -> OpTensors:
    """Pad a step stream with no-ops (KIND_LOCAL, all-zero lengths is an
    exact no-op in both engines)."""
    s = ops.num_steps
    assert s <= num_steps
    if s == num_steps:
        return ops

    def pad(a):
        width = [(0, num_steps - s)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(np.asarray(a), width)

    return jax.tree.map(pad, ops)


def empty_ops(lmax: int) -> OpTensors:
    """A zero-step stream (idle lanes in a serve batch tick)."""
    return _Rows(lmax).to_tensors()


def concat_ops(streams: Sequence[OpTensors]) -> OpTensors:
    """Concatenate step streams along the step axis (equal lmax).

    The serve batcher compiles one stream per drained event and fuses
    them into the doc's tick stream; orders were threaded through one
    assigner, so plain concatenation preserves the compiled invariants.
    """
    streams = [s for s in streams if s.num_steps > 0]
    if not streams:
        return empty_ops(1)
    lmax = streams[0].lmax
    assert all(s.lmax == lmax for s in streams), "mixed lmax streams"
    if len(streams) == 1:
        return streams[0]
    return jax.tree.map(
        lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0),
        *streams)


def stack_ops(streams: Sequence[OpTensors]) -> OpTensors:
    """Ragged per-doc streams -> one time-major [S, B, ...] tensor batch
    (config 3's mixed-corpus batch; shorter docs run no-op tail steps)."""
    s_max = max(o.num_steps for o in streams)
    padded = [pad_ops(o, s_max) for o in streams]
    return jax.tree.map(lambda *xs: np.stack(xs, axis=1), *padded)


def stack_ticks(ticks: Sequence[OpTensors]) -> OpTensors:
    """T equal-shape stacked tick streams ([S, B, ...] each) -> one
    train-major [T, S, B, ...] tensor batch for ``ops.flat.apply_train``
    (ISSUE 20).  The caller re-pads every tick to a common step bucket
    first (``pad_ops``) and pads short trains with all-zero no-op ticks
    — a zero ``OpTensors`` row is an exact no-op in the device step, so
    no-op ticks are exact no-op ticks."""
    return jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs], axis=0),
        *ticks)


def tile_ops(ops: OpTensors, batch: int) -> OpTensors:
    """One stream -> B identical docs (config 2: `random_edits` x 1k docs)."""
    return jax.tree.map(
        lambda a: np.broadcast_to(
            np.asarray(a)[:, None, ...], (a.shape[0], batch) + a.shape[1:]
        ),
        ops,
    )

"""Flattened device state: the document body as TPU-friendly columns.

This is the TPU-native replacement for the reference's pointer B-tree of RLE
``YjsSpan`` runs (`src/range_tree/`, `src/list/span.rs:6-119`). Two ideas:

1. **One mutable per-position column.** Document order lives in ``signed``:
   position ``i`` holds ``±(order+1)`` — magnitude is the item's dense op id
   (`list/mod.rs:29-30`), sign is the tombstone (the reference's signed span
   len, `span.rs:20,110-119`), ``0`` marks an empty slot. Every structural
   edit (splice, tombstone flip) touches only this one i32 column, so the
   apply kernel is pure elementwise/roll work — no TPU-hostile gathers.

2. **By-order append-only logs.** Everything immutable per item is keyed by
   its order, not its position: ``ol_log``/``or_log`` (origins),
   ``rank_log`` (author name rank for the Yjs tiebreak, `doc.rs:206-209`),
   ``chars_log`` (codepoints; the reference drops content with
   ``USE_INNER_ROPE=false``, `doc.rs:14-17` — we keep it so ``to_string``
   works). Orders are dense and assigned up front by the op compiler, so
   the compiler *prefills* all log values it knows (chars, ranks, remote
   origins, the within-run implicit origin chain `span.rs:9-13,24-28`);
   the device writes only the two origins a local insert discovers at apply
   time. Position→content is a host-side ``chars_log[order]`` gather at
   readback.

The per-span origin fix-ups on split/append (`span.rs:33-45,68-85`) are
index arithmetic on these columns, and the cursor total order
(`cursor.rs:274-304`) is integer comparison of positions.

Batched documents stack a leading axis on every field (vmap; sharded over
the mesh's ``dp`` axis by ``parallel.mesh``).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common import ROOT_ORDER

U32 = jnp.uint32
I32 = jnp.int32


@partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "signed", "ol_log", "or_log", "rank_log", "chars_log",
        "n", "next_order",
    ],
    meta_fields=[],
)
@dataclasses.dataclass
class FlatDoc:
    """One (or a batch of) flattened CRDT document bodies."""

    signed: jax.Array      # i32[..., CAP]   ±(order+1) in doc order; 0=empty
    ol_log: jax.Array      # u32[..., OCAP]  origin_left by order
    or_log: jax.Array      # u32[..., OCAP]  origin_right by order
    rank_log: jax.Array    # u32[..., OCAP]  author name rank by order
    chars_log: jax.Array   # u32[..., OCAP]  codepoint by order
    n: jax.Array           # i32[...]        occupied rows (live+tombstone)
    next_order: jax.Array  # u32[...]        next dense op id (`doc.rs:55-58`)

    @property
    def capacity(self) -> int:
        return self.signed.shape[-1]

    @property
    def order_capacity(self) -> int:
        return self.ol_log.shape[-1]


def make_flat_doc(capacity: int, order_capacity: int | None = None) -> FlatDoc:
    """Empty document (`doc.rs:51-64` analog — frontier/logs live host-side,
    SURVEY §7 'Frontier/DAG logic is branchy — keep on host').

    ``order_capacity`` bounds total orders consumed (inserts AND deletes
    take order ids, `doc.rs:155-165`); defaults to ``2 * capacity``.
    """
    if order_capacity is None:
        order_capacity = 2 * capacity
    zeros_o = jnp.zeros(order_capacity, dtype=U32)
    return FlatDoc(
        signed=jnp.zeros(capacity, dtype=I32),
        ol_log=jnp.full(order_capacity, ROOT_ORDER, dtype=U32),
        or_log=jnp.full(order_capacity, ROOT_ORDER, dtype=U32),
        rank_log=zeros_o,
        chars_log=zeros_o,
        n=jnp.asarray(0, dtype=I32),
        next_order=jnp.asarray(0, dtype=U32),
    )


def stack_docs(doc: FlatDoc, batch: int) -> FlatDoc:
    """Replicate a single doc into a batch (leading axis)."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (batch,) + x.shape), doc
    )


# -- host-side readback ------------------------------------------------------


def download(doc: FlatDoc) -> dict:
    """Device -> host: per-item numpy columns in document order.

    Materializes the by-order logs back into positional columns (the
    downloaded arrays are the RLE wire format, SURVEY §2 `Rle` row).
    """
    n = int(doc.n)
    signed = np.asarray(doc.signed[:n]).astype(np.int64)
    order = (np.abs(signed) - 1).astype(np.uint32)
    deleted = signed < 0
    return {
        "order": order,
        "origin_left": np.asarray(doc.ol_log)[order],
        "origin_right": np.asarray(doc.or_log)[order],
        "rank": np.asarray(doc.rank_log)[order],
        "chars": np.asarray(doc.chars_log)[order],
        "deleted": deleted,
        "next_order": int(doc.next_order),
    }


def to_string(doc: FlatDoc) -> str:
    cols = download(doc)
    live = ~cols["deleted"]
    cps = cols["chars"][live]
    return cps.astype("<u4").tobytes().decode("utf-32-le")


def doc_spans(doc: FlatDoc) -> List[Tuple[int, int, int, int]]:
    """Document body as maximally RLE-merged YjsSpan tuples — the canonical
    compacted form every engine reports (predicate `span.rs:47-53`)."""
    from ..utils.rle import merge_yjs_spans

    cols = download(doc)
    return merge_yjs_spans(
        (int(cols["order"][i]), int(cols["origin_left"][i]),
         int(cols["origin_right"][i]), -1 if cols["deleted"][i] else 1)
        for i in range(len(cols["order"]))
    )


def upload_oracle(
    oracle,
    capacity: int,
    rank_of_agent: np.ndarray,
    order_capacity: int | None = None,
) -> FlatDoc:
    """Host oracle document -> device state (resume/warm-start path).

    ``rank_of_agent`` maps the oracle's dense agent ids to name ranks (see
    ``batch.AgentTable``).
    """
    if order_capacity is None:
        order_capacity = 2 * capacity
    n = oracle.n
    next_order = oracle.get_next_order()
    assert n <= capacity, f"doc ({n} rows) exceeds device capacity {capacity}"
    assert next_order <= order_capacity, (
        f"doc ({next_order} orders) exceeds order capacity {order_capacity}")

    order = oracle.order[:n].astype(np.int64)
    signed = np.zeros(capacity, dtype=np.int32)
    signed[:n] = np.where(oracle.deleted[:n], -(order + 1), order + 1)

    def log_from(items, fill):
        out = np.full(order_capacity, fill, dtype=np.uint32)
        out[order] = items[:n]
        return jnp.asarray(out)

    # Per-item author rank: one vectorized searchsorted of item orders
    # against the client_with_order run starts (`list/mod.rs:58-63`).
    run_starts = np.asarray(
        [e.order for e in oracle.client_with_order], dtype=np.int64)
    run_agents = np.asarray(
        [e.agent for e in oracle.client_with_order], dtype=np.int64)
    run_idx = np.searchsorted(run_starts, order, side="right") - 1
    ranks = np.asarray(rank_of_agent)[run_agents[run_idx]].astype(np.uint32)

    return FlatDoc(
        signed=jnp.asarray(signed),
        ol_log=log_from(oracle.origin_left, ROOT_ORDER),
        or_log=log_from(oracle.origin_right, ROOT_ORDER),
        rank_log=log_from(ranks, 0),
        chars_log=log_from(oracle.chars, 0),
        n=jnp.asarray(n, dtype=I32),
        next_order=jnp.asarray(next_order, dtype=U32),
    )


def remap_rank_log(doc: FlatDoc, mapping) -> FlatDoc:
    """Re-base the by-order author-rank log through an old->new rank
    mapping (``batch.rank_remap``) at an agent-onboarding epoch boundary.
    Ranks at or beyond ``len(mapping)`` (never written by the old epoch)
    pass through unchanged."""
    m = jnp.asarray(np.asarray(mapping, dtype=np.uint32))
    old = doc.rank_log
    safe = jnp.minimum(old, m.shape[0] - 1).astype(jnp.int32)
    new = jnp.where(old < m.shape[0], m[safe], old)
    return dataclasses.replace(doc, rank_log=new.astype(jnp.uint32))

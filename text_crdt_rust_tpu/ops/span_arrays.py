"""Flattened device state: the document body as struct-of-arrays columns.

This is the TPU-native replacement for the reference's pointer B-tree of RLE
``YjsSpan`` runs (`src/range_tree/`, `src/list/span.rs:6-119`): one row per
*item* (character), in document order, tombstones in place. The reference's
per-span implicit origin chain (`span.rs:9-18`, `origin_left_at_offset`
`span.rs:24-28`) is materialized per item, so every split/append origin
fix-up (`span.rs:33-45,68-85`) becomes plain index arithmetic, and the
cursor total order (`cursor.rs:274-304`) collapses to integer comparison.

Columns (all capacity-padded to a static shape for XLA):

- ``order``        u32  dense op id of the item (`list/mod.rs:29-30`)
- ``origin_left``  u32  per-item origin (chained within runs)
- ``origin_right`` u32  shared across a run (`span.rs:15-18`)
- ``rank``         u32  author agent's *name rank* — the device stand-in for
                        the Yjs tiebreak on agent name (`doc.rs:206-209`);
                        see ``batch.AgentTable``
- ``chars``        u32  unicode codepoint (the reference drops text content
                        with ``USE_INNER_ROPE=false``, `doc.rs:14-17`; we
                        keep it so ``to_string`` works — column can be fed
                        zeros when benchmarking for parity)
- ``deleted``      bool tombstone flag — the sign bit of the reference's
                        signed span len (`span.rs:110-119`)

plus scalars ``n`` (live+tombstone rows) and ``next_order`` (next dense op
id, `doc.rs:55-58` analog). Batched documents stack a leading axis on every
field (vmap; sharded over the mesh's ``dp`` axis by ``parallel.mesh``).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common import ROOT_ORDER

U32 = jnp.uint32
I32 = jnp.int32


@partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "order", "origin_left", "origin_right", "rank", "chars", "deleted",
        "n", "next_order",
    ],
    meta_fields=[],
)
@dataclasses.dataclass
class FlatDoc:
    """One (or a batch of) flattened CRDT document bodies."""

    order: jax.Array        # u32[..., N]
    origin_left: jax.Array  # u32[..., N]
    origin_right: jax.Array  # u32[..., N]
    rank: jax.Array         # u32[..., N]
    chars: jax.Array        # u32[..., N]
    deleted: jax.Array      # bool[..., N]
    n: jax.Array            # i32[...]
    next_order: jax.Array   # u32[...]

    @property
    def capacity(self) -> int:
        return self.order.shape[-1]


def make_flat_doc(capacity: int) -> FlatDoc:
    """Empty document (`doc.rs:51-64` analog — frontier/logs live host-side,
    SURVEY §7 'Frontier/DAG logic is branchy — keep on host')."""
    full = jnp.full(capacity, ROOT_ORDER, dtype=U32)
    return FlatDoc(
        order=full,
        origin_left=full,
        origin_right=full,
        rank=jnp.zeros(capacity, dtype=U32),
        chars=jnp.zeros(capacity, dtype=U32),
        deleted=jnp.zeros(capacity, dtype=jnp.bool_),
        n=jnp.asarray(0, dtype=I32),
        next_order=jnp.asarray(0, dtype=U32),
    )


def stack_docs(doc: FlatDoc, batch: int) -> FlatDoc:
    """Replicate a single doc into a batch (leading axis)."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (batch,) + x.shape), doc
    )


# -- host-side readback ------------------------------------------------------


def download(doc: FlatDoc) -> dict:
    """Device -> host: numpy columns truncated to the live row count.

    The downloaded arrays *are* the wire format (SURVEY §2 `Rle` row: flat
    sorted span arrays upload/download as-is).
    """
    n = int(doc.n)
    return {
        "order": np.asarray(doc.order[:n]),
        "origin_left": np.asarray(doc.origin_left[:n]),
        "origin_right": np.asarray(doc.origin_right[:n]),
        "rank": np.asarray(doc.rank[:n]),
        "chars": np.asarray(doc.chars[:n]),
        "deleted": np.asarray(doc.deleted[:n]),
        "next_order": int(doc.next_order),
    }


def to_string(doc: FlatDoc) -> str:
    cols = download(doc)
    live = ~cols["deleted"]
    cps = cols["chars"][live]
    return cps.astype("<u4").tobytes().decode("utf-32-le")


def doc_spans(doc: FlatDoc) -> List[Tuple[int, int, int, int]]:
    """Document body as maximally RLE-merged YjsSpan tuples — the canonical
    compacted form every engine reports (predicate `span.rs:47-53`)."""
    from ..utils.rle import merge_yjs_spans

    cols = download(doc)
    return merge_yjs_spans(
        (int(cols["order"][i]), int(cols["origin_left"][i]),
         int(cols["origin_right"][i]), -1 if cols["deleted"][i] else 1)
        for i in range(len(cols["order"]))
    )


def upload_oracle(oracle, capacity: int, rank_of_agent: np.ndarray) -> FlatDoc:
    """Host oracle document -> device state (resume/warm-start path).

    ``rank_of_agent`` maps the oracle's dense agent ids to name ranks (see
    ``batch.AgentTable``).
    """
    n = oracle.n
    assert n <= capacity, f"doc ({n} rows) exceeds device capacity {capacity}"

    def pad_u32(a, fill):
        out = np.full(capacity, fill, dtype=np.uint32)
        out[:n] = a[:n]
        return jnp.asarray(out)

    # Per-item author rank: one vectorized searchsorted of item orders
    # against the client_with_order run starts (`list/mod.rs:58-63`).
    run_starts = np.asarray(
        [e.order for e in oracle.client_with_order], dtype=np.int64)
    run_agents = np.asarray(
        [e.agent for e in oracle.client_with_order], dtype=np.int64)
    run_idx = np.searchsorted(
        run_starts, oracle.order[:n].astype(np.int64), side="right") - 1
    ranks = np.asarray(rank_of_agent)[run_agents[run_idx]].astype(np.uint32)
    return FlatDoc(
        order=pad_u32(oracle.order, ROOT_ORDER),
        origin_left=pad_u32(oracle.origin_left, ROOT_ORDER),
        origin_right=pad_u32(oracle.origin_right, ROOT_ORDER),
        rank=pad_u32(ranks, 0),
        chars=pad_u32(oracle.chars, 0),
        deleted=jnp.asarray(
            np.concatenate([
                oracle.deleted[:n],
                np.zeros(capacity - n, dtype=bool),
            ])
        ),
        n=jnp.asarray(n, dtype=I32),
        next_order=jnp.asarray(oracle.get_next_order(), dtype=U32),
    )

"""jax/pallas version compatibility shared by every device engine.

The kernels target the current pallas API (``pltpu.CompilerParams``);
older jax releases (< 0.5) ship the same dataclass under the
``TPUCompilerParams`` name.  Importing this module (``ops/__init__``
does) aliases the new name onto the module object, which is shared by
every ``from jax.experimental.pallas import tpu as pltpu`` site — no
per-engine shims needed.
"""
from jax.experimental.pallas import tpu as _pltpu

if not hasattr(_pltpu, "CompilerParams"):  # pragma: no cover - new jax
    _pltpu.CompilerParams = _pltpu.TPUCompilerParams

"""Per-lane divergent MIXED replay: B distinct documents, each applying
its OWN local/remote op stream — the production sync shape.

``ops.rle_mixed`` runs the full op surface (KIND_LOCAL/REMOTE_INS/
REMOTE_DEL, `doc.rs:242-348`) but in LOCKSTEP: one shared scalar stream
across identical lanes.  ``ops.rle_lanes`` runs divergent per-lane
streams but refuses remote ops.  This engine is the round-5 unification
(VERDICT r4 missing #2): thousands of *different* documents each
receiving *its own* remote-op stream, one op per lane per kernel step.

Design — rle_lanes' lane-vector layout carried over to the remote paths:

- document state is the un-blocked run column pair ``ordp/lenp``
  [CAP, B] (±(order+1), len) plus ``rows`` [1, B]; every op scalar of
  ``rle_mixed`` becomes a [1, B] lane vector; splices stay <= 3 rows so
  per-lane dynamic shifts are two static ``pltpu.roll``s blended by
  per-lane masks (the rle_lanes trick);
- **per-lane by-order tables** ``oll/orl/rkl`` [OCAP, B] (row = order,
  lane = doc) replace rle_mixed's 128-orders/row packed tables: each
  lane has its own order space, so the packing collapses to one row per
  order and reads/writes are one masked [OCAP, B] pass.  Prefilled
  host-side per lane (`batch._prefill_scatter`), sentinel −2 = unknown;
  unknown entries are never probed (every existing char's entry was
  prefilled or written by the local-insert path at insert time);
- **no order->block hint table**: the lanes layout always works on the
  whole [CAP, B] plane, so order lookup IS the one vectorized
  range-test pass that rle_mixed's ``ordblk`` miss-path falls back to —
  there is nothing to hint, go stale, or self-heal;
- **run-level YATA integrate** (`doc.rs:167-234`) with PER-LANE scan
  state: (cursor, scanning, scan_start, done) are [1, B] vectors; the
  while-loop runs until every lane breaks (conflict-free lanes break on
  the first probe, `doc.rs:192-194`, so iterations = the max conflict
  depth across lanes, not the sum).  The raw prefix sum the scan
  descends on is HOISTED out of the loop — the scan never mutates
  state, so one ``_vcumsum`` serves every probe of the step;
- **one-pass remote delete**: runs are disjoint ORDER intervals, so a
  target range ``[t, t+dlen)`` fully covers every run it touches except
  at most the two holding its endpoints — one interval-clip pass flips
  the full covers and 3-way-splits the <= 2 partial runs, exactly the
  local-delete shape keyed by orders (no fragmentation walk, no dmax
  pre-chunking); covered DEAD runs count toward the idempotency total
  without flipping (`double_delete.rs:6-9`).

State (ordp, lenp, rows, oll, orl) is a kernel input AND output — chunk
N+1 resumes from chunk N on device (the config-5 streaming warm start),
with each chunk's compile-known table entries merged in at step 0 via
the −2 sentinel.  ``rkl`` is read-only (author ranks are compile-time
facts; the host accumulates the full table across chunks).
"""
from __future__ import annotations

import dataclasses
import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .batch import (
    KIND_LOCAL,
    KIND_REMOTE_DEL,
    KIND_REMOTE_INS,
    OpTensors,
    _prefill_scatter,
    fused_width,
    fused_width_checked,
)
from .blocked import _require
from .rle import fused_splice_rows
from .rle_lanes import (
    LanesResult,
    _lane_tile,
    _live_prefix,
    _shared_cum_gate,
    _vcumsum,
    _vrow,
    _vshift,
)

TAB_UNKNOWN = -2  # by-order table sentinel: entry not yet known


def _fused_table_writes(oll, orl, oidx, act, st, il, lrun, left, right):
    """By-order table upkeep for a (possibly fused) local insert —
    shared by the un-blocked and blocked mixed kernels (each binds its
    own ``oll``/``orl``/``oidx`` via ``partial``): every sub-run head
    (orders st + k*L) logs the SHARED left neighbour; sub-run k's span
    logs origin_right = patch k-1's head (k=0 keeps the raw successor)
    — exactly what the unfused per-patch steps would have written, so
    later YATA scans read identical origins.  w == 1 (lrun == il)
    degenerates to the old head write + whole-span right."""
    span = act & (oidx >= st) & (oidx < st + il)
    qoff = oidx - st
    ls = jnp.maximum(lrun, 1)
    oll[:] = jnp.where(span & (qoff % ls == 0), left, oll[:])
    orl[:] = jnp.where(
        span, jnp.where(qoff < ls, right,
                        st + (qoff // ls - 1) * ls), orl[:])


def _mixed_lanes_kernel(
    kind_ref, pos_ref, dlen_ref, dtgt_ref, olop_ref, orop_ref, rk_ref,
    ilen_ref, start_ref,                        # [CHUNK, B] VMEM op columns
    w_ref,                                      # [CHUNK, B] rows_per_step
    ord0_ref, len0_ref, rows0_ref,              # warm-start state inputs
    oll0_ref, orl0_ref,                         # prior table state [OCAP, B]
    olld_ref, orld_ref,                         # this stream's prefill delta
    rkl_ref,                                    # ranks (read-only, full)
    ol_ref, or_ref,                             # [CHUNK, B] origin outputs
    ordp, lenp, rowsv,                          # state outputs (working)
    oll, orl,                                   # table outputs (working)
    err_ref,
    *, CAP: int, OCAP: int, CHUNK: int, WMAX: int = 1,
    SHARED_CUM: bool = False,
):
    B = ordp.shape[1]
    i = pl.program_id(1)
    idx = lax.broadcasted_iota(jnp.int32, (CAP, B), 0)
    oidx = lax.broadcasted_iota(jnp.int32, (OCAP, B), 0)
    root_i = jnp.int32(-1)  # ROOT_ORDER as i32
    root_u = jnp.uint32(0xFFFFFFFF)

    ol_ref[:] = jnp.zeros_like(ol_ref)
    or_ref[:] = jnp.zeros_like(or_ref)

    @pl.when(i == 0)
    def _init():
        ordp[:] = ord0_ref[:]
        lenp[:] = len0_ref[:]
        rowsv[:] = rows0_ref[:]
        # Merge this stream's compile-known entries over the carried
        # tables (chunk N+1's new orders were −2 in chunk N's state).
        oll[:] = jnp.where(olld_ref[:] != TAB_UNKNOWN, olld_ref[:],
                           oll0_ref[:])
        orl[:] = jnp.where(orld_ref[:] != TAB_UNKNOWN, orld_ref[:],
                           orl0_ref[:])
        err_ref[:] = jnp.zeros_like(err_ref)

    # ---- per-lane by-order table ops ------------------------------------

    def t_read(tab, o):
        """tab[o[lane], lane] as [1, B]; o values < 0 read row 0 (callers
        mask ROOT probes before use)."""
        oc = jnp.clip(o, 0, OCAP - 1)
        return jnp.sum(jnp.where(oidx == oc, tab[:], 0), axis=0,
                       keepdims=True)

    def t_write(tab, act, o, v):
        tab[:] = jnp.where(act & (oidx == o), v, tab[:])

    def t_write_run(tab, act, st, ln, v):
        tab[:] = jnp.where(act & (oidx >= st) & (oidx < st + ln), v,
                           tab[:])

    # ---- order -> run / position lookups --------------------------------

    def find_run_of_order(o, need):
        """Per-lane row/run containing order ``o`` ([1, B]): one
        vectorized range test over the whole plane.  Raises the
        missing-order flag for ``need`` lanes with no hit."""
        bo = ordp[:]
        so = jnp.abs(bo) - 1
        hit = (bo != 0) & (so <= o) & (o < so + lenp[:])
        found = jnp.sum(hit.astype(jnp.int32), axis=0, keepdims=True) > 0
        row = jnp.min(jnp.where(hit, idx, CAP), axis=0, keepdims=True)

        @pl.when(jnp.any(need & ~found))
        def _missing():
            err_ref[2:3, :] = jnp.where(need & ~found, 1, err_ref[2:3, :])

        return jnp.where(found, row, 0), found

    def raw_pos_of_order(o, need):
        """Per-lane RAW document position of the char with order ``o``."""
        row, _ = find_run_of_order(o, need)
        raw_before = jnp.sum(jnp.where(idx < row, lenp[:], 0), axis=0,
                             keepdims=True)
        so_hit = jnp.abs(_vrow(ordp[:], row)) - 1
        return raw_before + (o - so_hit)

    def cursor_after(o, need):
        is_root = o == root_i
        # An unknown table entry (sentinel −2) must flag, not silently
        # resolve as order 0 (review r5).
        unknown = need & (o == TAB_UNKNOWN)

        @pl.when(jnp.any(unknown))
        def _unk():
            err_ref[2:3, :] = jnp.where(unknown, 1, err_ref[2:3, :])

        p = raw_pos_of_order(jnp.maximum(o, 0), need & ~is_root)
        return jnp.where(is_root, 0, p + 1)

    # ---- local ops (rle_lanes paths + table upkeep) ---------------------

    def flag_capacity(act, need=2):
        """Flag err row 0 where the lane lacks ``need`` spare rows
        (delete splits need 2; a fused W-row insert needs w + 1)."""
        over = act & (rowsv[:] + need > CAP)

        @pl.when(jnp.any(over))
        def _cap():
            err_ref[0:1, :] = jnp.where(over, 1, err_ref[0:1, :])

    def apply_partial(a, i_p, bo, bl, cs, ce):
        """Split run row ``i_p`` around its covered sub-range
        ``[cs, ce)`` into [head?] [tombstone mid] [tail?] (<= +2 rows),
        per lane where ``a``.  The signed-start fix-up covers LIVE runs
        only (partial coverage of a dead run never reaches here)."""
        o = _vrow(bo, i_p)
        ln = _vrow(bl, i_p)
        cs_i = _vrow(cs, i_p)
        ce_i = _vrow(ce, i_p)
        cov_i = ce_i - cs_i
        has_head = (cs_i > 0) & a
        has_tail = (ce_i < ln) & a
        amt = has_head.astype(jnp.int32) + has_tail.astype(jnp.int32)
        so = _vshift(bo, amt)
        sl = _vshift(bl, amt)
        no = jnp.where(idx <= i_p, bo, so)
        nl = jnp.where(idx <= i_p, bl, sl)
        p0o = jnp.where(has_head, o, -(o + cs_i))
        p0l = jnp.where(has_head, cs_i, cov_i)
        p1o = jnp.where(has_head, -(o + cs_i), o + ce_i)
        p1l = jnp.where(has_head, cov_i, ln - ce_i)
        w0 = a & (idx == i_p)
        no = jnp.where(w0, p0o, no)
        nl = jnp.where(w0, p0l, nl)
        w1 = a & (idx == i_p + 1) & (amt >= 1)
        no = jnp.where(w1, p1o, no)
        nl = jnp.where(w1, p1l, nl)
        w2 = a & (idx == i_p + 2) & (amt == 2)
        no = jnp.where(w2, o + ce_i, no)
        nl = jnp.where(w2, ln - ce_i, nl)
        return no, nl, amt

    def do_local_delete(act, p, d, lv=None, cum=None):
        """Whole-doc single-pass tombstone (rle_lanes.do_delete)."""
        flag_capacity(act)
        bo = ordp[:]
        bl = lenp[:]
        if cum is None:
            lv, cum = _live_prefix(bo, bl)
        before = cum - lv
        rem = jnp.where(act, d, 0)
        cs = jnp.clip(p - before, 0, lv)
        ce = jnp.clip(p + rem - before, 0, lv)
        cov = ce - cs
        tot = jnp.sum(cov, axis=0, keepdims=True)

        @pl.when(jnp.any(act & (tot < rem)))
        def _bad():
            err_ref[1:2, :] = jnp.where(act & (tot < rem), 1,
                                        err_ref[1:2, :])

        full = (cov > 0) & (cov == bl)
        part = (cov > 0) & jnp.logical_not(full)
        npart = jnp.sum(part.astype(jnp.int32), axis=0, keepdims=True)
        i1 = jnp.min(jnp.where(part, idx, CAP), axis=0, keepdims=True)
        i2 = jnp.max(jnp.where(part, idx, -1), axis=0, keepdims=True)
        bo = jnp.where(act & full, -bo, bo)

        bo, bl, a2 = apply_partial(act & (npart >= 1), i2, bo, bl, cs, ce)
        bo, bl, a1 = apply_partial(act & (npart == 2), i1, bo, bl, cs, ce)
        ordp[:] = bo
        lenp[:] = bl
        rowsv[:] = rowsv[:] + jnp.where(act, a1 + a2, 0)

    fused_table_writes = partial(_fused_table_writes, oll, orl, oidx)

    def do_local_insert(act, k, p, il, st, w, lv=None, cum=None):
        """rle_lanes.do_insert + by-order table upkeep (the origins a
        local insert discovers at apply time, `doc.rs:447-453`).
        ``w`` > 1 is a FUSED backwards-burst step: W stride-L rows in
        one shift, the ``ops.rle`` ``_insert_splice`` contract.
        ``lv``/``cum`` may be the step-hoisted PRE-DELETE live prefix
        (valid: shared-cum mode excludes same-lane delete+insert
        steps); ``bo``/``bl`` stay FRESH so the whole-plane writes
        preserve the delete branch's results on other lanes."""
        rows = rowsv[:]
        flag_capacity(act, w + 1)
        bo = ordp[:]
        bl = lenp[:]
        if cum is None:
            lv, cum = _live_prefix(bo, bl)
        local = jnp.where(act, p, 0)
        i_r = jnp.sum(((cum < local) & (idx < rows)).astype(jnp.int32),
                      axis=0, keepdims=True)
        o_r = _vrow(bo, i_r)
        l_r = _vrow(bl, i_r)
        off = local - (_vrow(cum, i_r) - _vrow(lv, i_r))

        left = jnp.where(p == 0, root_i, (o_r - 1) + (off - 1))
        no, nl, amt, mrg, is_split, lrun = fused_splice_rows(
            bo, bl, idx, p, i_r, o_r, l_r, off, il, st, w, WMAX,
            _vshift, active=act)

        nxt_in_blk = _vrow(bo, i_r + 1)
        first_o = _vrow(bo, 0)
        succ_p0 = jnp.where(rows > 0, first_o, 0)
        succ_after = jnp.where(i_r + 1 < rows, nxt_in_blk, 0)
        succ = jnp.where(p == 0, succ_p0,
                         jnp.where(is_split, o_r + off, succ_after))
        right = jnp.where(succ == 0, root_i, jnp.abs(succ) - 1)
        ordp[:] = no
        lenp[:] = nl
        rowsv[:] = rows + amt

        fused_table_writes(act, st, il, lrun, left, right)
        ol_ref[pl.ds(k, 1), :] = jnp.where(
            act, left.astype(jnp.uint32), ol_ref[pl.ds(k, 1), :])
        or_ref[pl.ds(k, 1), :] = jnp.where(
            act, right.astype(jnp.uint32), or_ref[pl.ds(k, 1), :])

    # ---- remote insert (`doc.rs:274-293` -> integrate) ------------------

    def integrate_cursor(act, my_rank, o_left, o_right):
        """Per-lane YATA conflict scan over runs (rle_mixed
        ``integrate_cursor`` with [1, B] scan state).  The raw prefix is
        hoisted: the scan mutates nothing, so one cumsum serves every
        probe of every lane this step."""
        cumraw = _vcumsum(lenp[:])
        n = jnp.sum(lenp[:], axis=0, keepdims=True)
        cursor0 = cursor_after(o_left, act)
        left_cursor = cursor0

        def run_at_raw(c):
            i_r = jnp.sum(((cumraw <= c) & (idx < rowsv[:])).astype(
                jnp.int32), axis=0, keepdims=True)
            o_r = _vrow(ordp[:], i_r)
            l_r = _vrow(lenp[:], i_r)
            off = c - (_vrow(cumraw, i_r) - l_r)
            return o_r, l_r, off

        # Loop-carried lane masks ride as i32 0/1: Mosaic materializes
        # loop-carried [1, T] i1 vectors as i8 and has no i8->i1
        # truncation, so a bool carry fails to compile on real TPU
        # (the cfg5r MosaicError in perf/compile_pin_r5.log).
        def cond(state):
            cursor, scanning_i, scan_start, done_i = state
            return jnp.any((done_i == 0) & (cursor < n))

        def body(state):
            cursor, scanning_i, scan_start, done_i = state
            scanning = scanning_i != 0
            done = done_i != 0
            o_r, l_r, off = run_at_raw(cursor)
            so = jnp.abs(o_r) - 1
            other_order = so + off
            live = ~done & (cursor < n)
            other_left = t_read(oll, other_order)
            other_right = t_read(orl, other_order)
            other_rank = t_read(rkl_ref, other_order)
            olc = cursor_after(other_left, live)
            brk = (other_order == o_right) | (olc < left_cursor)
            eq = ~brk & (olc == left_cursor)
            gt = my_rank > other_rank
            brk = brk | (eq & ~gt & (o_right == other_right))
            starts_scan = eq & ~gt & (o_right != other_right)
            new_scan_start = jnp.where(
                live & starts_scan & ~scanning, cursor, scan_start)
            # i32-VALUED selects: a vector select whose RESULTS are i1
            # makes Mosaic round-trip the mask through i8 (the trunci
            # MosaicError); selecting 0/1 i32 keeps it on the vreg path.
            new_scanning_i = jnp.where(
                live & eq,
                jnp.where(gt, 0,
                          jnp.where(o_right == other_right, scanning_i,
                                    1)),
                scanning_i)
            contains_right = (o_right > other_order) & (o_right < so + l_r)
            step = jnp.where(contains_right, o_right - other_order,
                             l_r - off)
            new_cursor = jnp.where(live & ~brk, cursor + step, cursor)
            new_done_i = jnp.maximum(
                done_i, jnp.where(brk | (cursor >= n), 1, 0))
            return (new_cursor, new_scanning_i, new_scan_start,
                    new_done_i)

        zero = jnp.zeros_like(cursor0)  # [1, B] i32 False
        init = (cursor0, zero, cursor0, (~act).astype(jnp.int32))
        cursor, scanning_i, scan_start, _ = lax.while_loop(
            cond, body, init)
        return jnp.where(scanning_i != 0, scan_start, cursor), cumraw

    def do_remote_insert(act, k, my_rank, o_left, o_right, il, st):
        flag_capacity(act)
        c, cumraw = integrate_cursor(act, my_rank, o_left, o_right)
        rows = rowsv[:]
        bo = ordp[:]
        bl = lenp[:]
        local = jnp.where(act, c, 0)
        i_r = jnp.sum(((cumraw < local) & (idx < rows)).astype(jnp.int32),
                      axis=0, keepdims=True)
        o_r = _vrow(bo, i_r)
        l_r = _vrow(bl, i_r)
        off = local - (_vrow(cumraw, i_r) - l_r)

        # Raw-position splice (`rle_mixed._insert_splice_raw` lane-wise):
        # the split run may be a TOMBSTONE (preserve sign on the tail);
        # the merge fast path additionally requires a live predecessor
        # AND the op's origin_left chaining to the run's last char — the
        # YATA run-skip evaluates only run heads on the premise that
        # non-head chars' origin_left is their own predecessor, so an
        # unchained merge would hide a char the scan must evaluate.
        mrg = act & (c > 0) & (o_r > 0) & (off == l_r) & \
            ((st + 1) == (o_r + l_r)) & (o_left == o_r + l_r - 2)
        is_split = act & (c > 0) & (off < l_r)
        ins_at = jnp.where(c == 0, 0, i_r + 1)
        amt = jnp.where(jnp.logical_not(act) | mrg, 0,
                        jnp.where(is_split, 2, 1))
        so = _vshift(bo, amt)
        sl = _vshift(bl, amt)
        no = jnp.where(idx < ins_at, bo, so)
        nl = jnp.where(idx < ins_at, bl, sl)
        nl = jnp.where(is_split & (idx == i_r), off, nl)
        new_run = act & jnp.logical_not(mrg) & (idx == ins_at)
        no = jnp.where(new_run, st + 1, no)
        nl = jnp.where(new_run, il, nl)
        tail = is_split & (idx == ins_at + 1)
        tail_o = jnp.where(o_r > 0, o_r + off, o_r - off)
        no = jnp.where(tail, tail_o, no)
        nl = jnp.where(tail, l_r - off, nl)
        nl = jnp.where(mrg & (idx == i_r), l_r + il, nl)
        ordp[:] = no
        lenp[:] = nl
        rowsv[:] = rows + amt

        # Remote origins are compile-time facts already prefilled into
        # the tables; only the per-op outputs remain.
        ol_ref[pl.ds(k, 1), :] = jnp.where(
            act, o_left.astype(jnp.uint32), ol_ref[pl.ds(k, 1), :])
        or_ref[pl.ds(k, 1), :] = jnp.where(
            act, o_right.astype(jnp.uint32), or_ref[pl.ds(k, 1), :])

    # ---- remote delete (`doc.rs:295-340`) -------------------------------

    def do_remote_delete(act, t, dlen):
        """Order-interval tombstone in ONE pass (`doc.rs:295-340`
        without the fragmentation walk): runs are disjoint order
        intervals, so at most TWO covered runs are partial — the ones
        holding ``t`` and ``t+dlen-1`` — and every other covered run is
        fully inside ``[t, t+dlen)`` and flips wholesale.  Same shape as
        the local delete, keyed by ORDERS instead of live ranks; covered
        DEAD runs just count toward the idempotency total without
        flipping (`double_delete.rs:6-9`; excess counting is host-side
        per SURVEY).  Any ``dlen`` in one step — no dmax pre-chunking."""
        bo = ordp[:]
        bl = lenp[:]
        so = jnp.abs(bo) - 1
        occ = bo != 0
        cs = jnp.clip(t - so, 0, bl)
        ce = jnp.clip(t + dlen - so, 0, bl)
        cov = jnp.where(act & occ, ce - cs, 0)
        tot = jnp.sum(cov, axis=0, keepdims=True)
        rem = jnp.where(act, dlen, 0)

        @pl.when(jnp.any(act & (tot < rem)))
        def _bad():
            err_ref[1:2, :] = jnp.where(act & (tot < rem), 1,
                                        err_ref[1:2, :])

        live = bo > 0
        full = live & (cov > 0) & (cov == bl)
        part = live & (cov > 0) & jnp.logical_not(cov == bl)
        npart = jnp.sum(part.astype(jnp.int32), axis=0, keepdims=True)
        # Max growth is +2 per op: one run holding both endpoints 3-way
        # splits (+2), or the two endpoint runs each split one-sided
        # (+1 each).  Gate BOTH splits and the full flips so a flagged
        # delete is a clean no-op (review r5: overflow would let
        # pltpu.roll silently wrap the plane).
        tight = act & (npart > 0) & (rowsv[:] + 2 > CAP)

        @pl.when(jnp.any(tight))
        def _cap():
            err_ref[0:1, :] = jnp.where(tight, 1, err_ref[0:1, :])

        a = act & ~tight
        i1 = jnp.min(jnp.where(part, idx, CAP), axis=0, keepdims=True)
        i2 = jnp.max(jnp.where(part, idx, -1), axis=0, keepdims=True)
        bo = jnp.where(a & full, -bo, bo)

        bo, bl, a2 = apply_partial(a & (npart >= 1), i2, bo, bl, cs, ce)
        bo, bl, a1 = apply_partial(a & (npart == 2), i1, bo, bl, cs, ce)
        ordp[:] = bo
        lenp[:] = bl
        rowsv[:] = rowsv[:] + jnp.where(a, a1 + a2, 0)

    # ---- dispatch -------------------------------------------------------

    def op_body(k, _):
        kind = kind_ref[pl.ds(k, 1), :]
        p = pos_ref[pl.ds(k, 1), :]
        d = dlen_ref[pl.ds(k, 1), :]
        il = ilen_ref[pl.ds(k, 1), :]
        st = start_ref[pl.ds(k, 1), :]
        w = jnp.maximum(w_ref[pl.ds(k, 1), :], 1)  # pad rows carry 0

        act_ld = (kind == KIND_LOCAL) & (d > 0)
        act_li = (kind == KIND_LOCAL) & (il > 0)
        act_ri = (kind == KIND_REMOTE_INS) & (il > 0)
        act_rd = (kind == KIND_REMOTE_DEL) & (d > 0)

        if SHARED_CUM:
            # One live prefix serves both LOCAL branches (no lane
            # deletes AND inserts in one step, and both-branch steps
            # outnumber no-local steps — both checked statically).
            lv, cum = _live_prefix(ordp[:], lenp[:])
        else:
            lv = cum = None

        @pl.when(jnp.any(act_ld))
        def _():
            do_local_delete(act_ld, p, d, lv, cum)

        @pl.when(jnp.any(act_li))
        def _():
            do_local_insert(act_li, k, p, il, st, w, lv, cum)

        @pl.when(jnp.any(act_ri))
        def _():
            do_remote_insert(act_ri, k, rk_ref[pl.ds(k, 1), :],
                             olop_ref[pl.ds(k, 1), :],
                             orop_ref[pl.ds(k, 1), :], il, st)

        @pl.when(jnp.any(act_rd))
        def _():
            do_remote_delete(act_rd, dtgt_ref[pl.ds(k, 1), :], d)

        return 0

    lax.fori_loop(0, CHUNK, op_body, 0)


@dataclasses.dataclass
class LanesMixedResult(LanesResult):
    """``LanesResult`` + per-lane by-order table state (the warm-start
    carry) and the missing-order flag (err row 2)."""

    oll: jax.Array = None   # i32[OCAP, B]
    orl: jax.Array = None   # i32[OCAP, B]

    def check(self) -> None:
        super().check()
        err = np.asarray(self.err)
        if err[2].max() != 0:
            raise RuntimeError(
                f"order lookup missed on lanes "
                f"{np.nonzero(err[2])[0][:8].tolist()}: an op referenced "
                f"an order absent from device state")

    def state(self):
        """(ordp, lenp, rows, oll, orl) — the next chunk's ``init``."""
        return self.ordp, self.lenp, self.rows, self.oll, self.orl


def lane_tables(stacked: OpTensors, ocap: int):
    """Per-lane by-order prefill: (oll, orl, rkl) as i32[OCAP, B] numpy,
    sentinel −2 for unknown entries (−1 is ROOT).  Everything the
    compiler knows: remote head origins, within-run chains, author
    ranks (`batch._prefill_scatter` per lane)."""
    kinds = np.asarray(stacked.kind)
    assert kinds.ndim == 2, "lane_tables takes stacked [S, B] streams"
    B = kinds.shape[1]
    oll = np.full((B, ocap), TAB_UNKNOWN, np.int32)
    orl = np.full((B, ocap), TAB_UNKNOWN, np.int32)
    rkl = np.zeros((B, ocap), np.int32)
    for b in range(B):
        per = jax.tree.map(lambda a: np.asarray(a)[:, b], stacked)
        sc = _prefill_scatter(per)
        if sc is None:
            continue
        oll[b, sc["ol"][0]] = sc["ol"][1].astype(np.uint32).astype(
            np.int64).astype(np.int32)  # u32 ROOT -> -1
        orl[b, sc["or"][0]] = sc["or"][1].astype(np.uint32).astype(
            np.int64).astype(np.int32)
        rkl[b, sc["rank"][0]] = sc["rank"][1]
    return (np.ascontiguousarray(oll.T), np.ascontiguousarray(orl.T),
            np.ascontiguousarray(rkl.T))


@functools.lru_cache(maxsize=32)
def _build_call(s_pad: int, B: int, capacity: int, ocap: int, chunk: int,
                interpret: bool, lane_tile: int | None = None,
                shared_cum: bool = False, wmax: int = 1):
    """Shape-keyed cache (streaming chunks share one compiled kernel)."""
    T = lane_tile or _lane_tile(B)
    _require(B % T == 0, f"lane_tile {T} must divide batch {B}")
    col = lambda: pl.BlockSpec((chunk, T), lambda lb, i: (i, lb),
                               memory_space=pltpu.VMEM)
    whole = lambda rows: pl.BlockSpec(
        (rows, T), lambda lb, i: (0, lb), memory_space=pltpu.VMEM)

    call = pl.pallas_call(
        partial(_mixed_lanes_kernel, CAP=capacity, OCAP=ocap,
                CHUNK=chunk, WMAX=wmax, SHARED_CUM=shared_cum),
        grid=(B // T, s_pad // chunk),
        in_specs=[col() for _ in range(10)] + [
            whole(capacity), whole(capacity), whole(1),
            whole(ocap), whole(ocap),           # prior table state
            whole(ocap), whole(ocap),           # prefill delta
            whole(ocap),                        # ranks (read-only)
        ],
        out_specs=[
            col(), col(),
            whole(capacity), whole(capacity), whole(1),
            whole(ocap), whole(ocap),
            whole(8),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s_pad, B), jnp.uint32),
            jax.ShapeDtypeStruct((s_pad, B), jnp.uint32),
            jax.ShapeDtypeStruct((capacity, B), jnp.int32),
            jax.ShapeDtypeStruct((capacity, B), jnp.int32),
            jax.ShapeDtypeStruct((1, B), jnp.int32),
            jax.ShapeDtypeStruct((ocap, B), jnp.int32),
            jax.ShapeDtypeStruct((ocap, B), jnp.int32),
            jax.ShapeDtypeStruct((8, B), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=128 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return jax.jit(lambda *a: call(*a))


def make_replayer_lanes_mixed(
    ops: OpTensors,
    capacity: int,
    order_capacity: int = 0,
    chunk: int = 128,
    init=None,
    rkl=None,
    interpret: bool = False,
    lane_tile: int | None = None,
):
    """Build a jitted per-lane MIXED replayer for stacked per-doc streams
    (``stack_ops`` output: every column [S, B]; kinds may differ per
    lane per step).

    ``capacity`` counts run rows per document; ``order_capacity`` rows
    of by-order table per document (0 = fit this stream: max per-lane
    total orders, +lmax headroom).  ``init`` is a prior result's
    ``state()`` 5-tuple — the streaming warm start; None = empty docs.
    ``rkl`` overrides the rank table (i32[OCAP, B]; pass the host-
    accumulated full table when chunk-chaining so earlier chunks' ranks
    stay visible); None = this stream's prefill.  Remote deletes of any
    length apply in one step (the one-pass interval delete needs no
    dmax pre-chunking).
    """
    kinds = np.asarray(ops.kind)
    _require(kinds.ndim == 2, "rle_lanes_mixed takes stacked per-doc "
             "streams ([S, B] columns; see batch.stack_ops)")
    S, B = kinds.shape
    _require(capacity >= 8, "capacity must hold a few runs")
    wmax = fused_width(ops)
    _require(wmax + 1 < capacity,
             f"fused rows_per_step {wmax} cannot fit capacity "
             f"{capacity}")
    s_pad = max(((S + chunk - 1) // chunk) * chunk, chunk)

    adv = np.asarray(ops.order_advance, dtype=np.int64).sum(axis=0)
    base = 0
    if init is not None and init[3] is not None:
        base = init[3].shape[0]
    ocap = order_capacity or max(
        ((int(adv.max() + ops.lmax) + base + 7) // 8) * 8, 8)
    _require(ocap % 8 == 0, "order_capacity must be a multiple of 8")

    def staged_col(get):
        a = np.asarray(get(ops), dtype=np.uint32).view(np.int32)
        return jnp.asarray(np.pad(a, ((0, s_pad - S), (0, 0))))

    staged = tuple(staged_col(g) for g in (
        lambda o: o.kind, lambda o: o.pos, lambda o: o.del_len,
        lambda o: o.del_target, lambda o: o.origin_left,
        lambda o: o.origin_right, lambda o: o.rank, lambda o: o.ins_len,
        lambda o: o.ins_order_start, lambda o: o.rows_per_step))

    olld, orld, rkl0 = lane_tables(ops, ocap)
    if rkl is None:
        rkl = rkl0
    else:
        rkl = np.asarray(rkl, np.int32)
        _require(rkl.shape == (ocap, B),
                 f"rkl shape {rkl.shape} != ({ocap}, {B})")

    if init is None:
        init = (jnp.zeros((capacity, B), jnp.int32),
                jnp.zeros((capacity, B), jnp.int32),
                jnp.zeros((1, B), jnp.int32),
                jnp.full((ocap, B), TAB_UNKNOWN, jnp.int32),
                jnp.full((ocap, B), TAB_UNKNOWN, jnp.int32))
    else:
        init = _grow_state(init, capacity, ocap, B)

    # Shared live prefix for the local branches: sound only when no
    # lane deletes AND inserts in the same step (a compiled replace
    # patch), and worth it only when steps firing BOTH local branches
    # outnumber steps firing neither — a remote-heavy stream with one
    # stray local op must not pay the hoist on every step (review r5).
    kn, dn, iln = (np.asarray(ops.kind), np.asarray(ops.del_len),
                   np.asarray(ops.ins_len))
    ld = (kn == KIND_LOCAL) & (dn > 0)
    li = (kn == KIND_LOCAL) & (iln > 0)
    shared_cum = (not bool(np.any(ld & li))
                  and _shared_cum_gate(ld.any(axis=1), li.any(axis=1),
                                       s_pad))
    jitted = _build_call(s_pad, B, capacity, ocap, chunk,
                         interpret, lane_tile, shared_cum, wmax)
    deltas = (jnp.asarray(olld), jnp.asarray(orld), jnp.asarray(rkl))

    def run(state=None) -> LanesMixedResult:
        ini = init if state is None else _grow_state(
            state, capacity, ocap, B)
        ol, orr, ordp, lenp, rows, oll, orl, err = jitted(
            *staged, *ini, *deltas)
        return LanesMixedResult(
            ordp=ordp, lenp=lenp, rows=rows, ol=ol[:S], orr=orr[:S],
            err=err, batch=B, oll=oll, orl=orl)

    return run


def _grow_state(state, capacity: int, ocap: int, B: int):
    """Pad a prior chunk's state 5-tuple up to this chunk's row/order
    capacities (rows pack at the front; tables are order-indexed) —
    streaming chunks may GROW both as documents accumulate."""
    from .rle_lanes import _grow_planes

    o0, l0, r0 = _grow_planes(state[:3], capacity, B)
    return (o0, l0, r0,
            _grow_table(state[3], ocap, B),
            _grow_table(state[4], ocap, B))


def _grow_table(t, ocap: int, B: int):
    """Pad a prior chunk's [ocap_old, B] table up to this chunk's ocap
    with the unknown sentinel (order spaces only grow)."""
    t = jnp.asarray(t, jnp.int32)
    _require(t.shape[0] <= ocap and t.shape[1] == B,
             f"table state shape {t.shape} incompatible with "
             f"({ocap}, {B})")
    if t.shape[0] == ocap:
        return t
    pad = jnp.full((ocap - t.shape[0], B), TAB_UNKNOWN, jnp.int32)
    return jnp.concatenate([t, pad], axis=0)


def replay_lanes_mixed(ops: OpTensors, capacity: int,
                       **kw) -> LanesMixedResult:
    """One-shot convenience wrapper over ``make_replayer_lanes_mixed``."""
    return make_replayer_lanes_mixed(ops, capacity, **kw)()


# ---------------------------------------------------------------------------
# BLOCKED per-lane MIXED engine (ISSUE 2 tentpole): the full op surface
# on K-row blocks with per-lane logical tables (blkord/rws/liv/raw +
# incrementally-maintained inclusive prefixes).  Replaces the un-blocked
# kernel's per-step whole-plane cumsum (log2(CAP) rolls over [CAP, B])
# with an NB-row descent + ONE gathered K-row block splice; remote
# cursors descend the raw prefix table the same way.  Bit-identical to
# the un-blocked kernel: block splits move rows, never runs, so the
# logical run sequence, every YATA cursor, and every emitted origin are
# the same at every step.
# ---------------------------------------------------------------------------


def _mixed_lanes_blocked_kernel(
    kind_ref, pos_ref, dlen_ref, dtgt_ref, olop_ref, orop_ref, rk_ref,
    ilen_ref, start_ref,                        # [CHUNK, B] VMEM op columns
    w_ref,                                      # [CHUNK, B] rows_per_step
    ord0_ref, len0_ref, nlog0_ref,              # warm-start state inputs
    blk0_ref, rws0_ref, liv0_ref, raw0_ref,
    oll0_ref, orl0_ref,                         # prior table state
    ordblk0_ref, fwd0_ref,                      # prior hints + split fwd ptrs
    olld_ref, orld_ref,                         # this stream's prefill delta
    rkl_ref,                                    # ranks (read-only)
    ol_ref, or_ref,                             # [CHUNK, B] outputs
    ordp, lenp, nlogv, blkord, rws, liv, raw,   # state outputs (working)
    oll, orl,                                   # table outputs (working)
    ordblk,                                     # [OCAP, B] order->block HINT
    fwd,                                        # [NBT, B] block -> split dest
    err_ref,
    cumliv, cumraw,                             # [NBT, B] scratch prefixes
    *, K: int, NB: int, NBT: int, CAP: int, OCAP: int, CHUNK: int,
    WMAX: int = 1,
):
    from .lane_blocks import (
        gather_block,
        gather_head,
        lane_apply_partial,
        scatter_block,
        scatter_block2,
        vshift_up,
    )

    B = ordp.shape[1]
    i = pl.program_id(1)
    kdx = lax.broadcasted_iota(jnp.int32, (K, B), 0)
    tidx = lax.broadcasted_iota(jnp.int32, (NBT, B), 0)
    idx_cap = lax.broadcasted_iota(jnp.int32, (CAP, B), 0)
    oidx = lax.broadcasted_iota(jnp.int32, (OCAP, B), 0)
    root_i = jnp.int32(-1)

    ol_ref[:] = jnp.zeros_like(ol_ref)
    or_ref[:] = jnp.zeros_like(or_ref)

    @pl.when(i == 0)
    def _init():
        ordp[:] = ord0_ref[:]
        lenp[:] = len0_ref[:]
        nlogv[:] = jnp.maximum(nlog0_ref[:], 1)
        blkord[:] = blk0_ref[:]
        rws[:] = rws0_ref[:]
        liv[:] = liv0_ref[:]
        raw[:] = raw0_ref[:]
        cumliv[:] = _vcumsum(liv0_ref[:])
        cumraw[:] = _vcumsum(raw0_ref[:])
        oll[:] = jnp.where(olld_ref[:] != TAB_UNKNOWN, olld_ref[:],
                           oll0_ref[:])
        orl[:] = jnp.where(orld_ref[:] != TAB_UNKNOWN, orld_ref[:],
                           orl0_ref[:])
        # Order -> physical-block HINT (the per-lane `markers.rs:8` /
        # rle_mixed ``ordblk`` analog): written on insert, left stale by
        # block splits, verified + RUN-healed on every probe, and
        # CARRIED across chunks (a cold table would pay one plane-scan
        # fallback per first probe of every old order each chunk).
        # -1 = unknown.  ``fwd[b]`` = the block b's top half last moved
        # to (split forward pointer; -1 = never split) — the hop that
        # rescues stale hints without a plane scan.
        ordblk[:] = ordblk0_ref[:]
        fwd[:] = fwd0_ref[:]
        err_ref[:] = jnp.zeros_like(err_ref)

    # ---- per-lane by-order table ops (unchanged from un-blocked) --------

    def t_read(tab, o):
        oc = jnp.clip(o, 0, OCAP - 1)
        return jnp.sum(jnp.where(oidx == oc, tab[:], 0), axis=0,
                       keepdims=True)

    def t_write(tab, act, o, v):
        tab[:] = jnp.where(act & (oidx == o), v, tab[:])

    def t_write_run(tab, act, st, ln, v):
        tab[:] = jnp.where(act & (oidx >= st) & (oidx < st + ln), v,
                           tab[:])

    # ---- logical block tables -------------------------------------------

    def trow(tbl, l):
        return jnp.sum(jnp.where(tidx == l, tbl[:], 0), axis=0,
                       keepdims=True)

    def slot_of(cum, rank1, strict):
        """Smallest logical slot whose cumulative count reaches
        ``rank1`` (strict: cum < rank1; else cum <= rank1)."""
        nl = nlogv[:]
        hit = ((cum[:] < rank1) if strict else (cum[:] <= rank1)) \
            & (tidx < nl)
        return jnp.minimum(
            jnp.sum(hit.astype(jnp.int32), axis=0, keepdims=True), nl - 1)

    def live_before(l):
        return trow(cumliv, l) - trow(liv, l)

    def raw_before(l):
        return trow(cumraw, l) - trow(raw, l)

    def split(act, l):
        """Per-lane leaf split with live AND raw table upkeep."""
        over = act & (nlogv[:] >= NB)

        @pl.when(jnp.any(over))
        def _cap():
            err_ref[0:1, :] = jnp.where(over, 1, err_ref[0:1, :])

        do = act & (nlogv[:] < NB)

        @pl.when(jnp.any(do))
        def _do():
            b = trow(blkord, l)
            r = trow(rws, l)
            keep = r // 2
            mv = r - keep
            nbv = nlogv[:]
            ws_o = gather_block(ordp, b, K, NB)
            ws_l = gather_block(lenp, b, K, NB)
            hi = (kdx >= keep) & (kdx < r)
            liv_hi = jnp.sum(jnp.where(hi & (ws_o > 0), ws_l, 0),
                             axis=0, keepdims=True)
            raw_hi = jnp.sum(jnp.where(hi, ws_l, 0), axis=0,
                             keepdims=True)
            up_o = vshift_up(ws_o, keep, K)
            up_l = vshift_up(ws_l, keep, K)
            scatter_block2(
                ordp, b, jnp.where(kdx < keep, ws_o, 0),
                nbv, jnp.where(kdx < mv, up_o, 0), do, K, NB)
            scatter_block2(
                lenp, b, jnp.where(kdx < keep, ws_l, 0),
                nbv, jnp.where(kdx < mv, up_l, 0), do, K, NB)
            for tbl in (blkord, rws, liv, raw, cumliv, cumraw):
                sh = pltpu.roll(tbl[:], 1, axis=0)
                tbl[:] = jnp.where(do & (tidx > l), sh, tbl[:])
            w_l = do & (tidx == l)
            w_l1 = do & (tidx == l + 1)
            rws[:] = jnp.where(w_l, keep, jnp.where(w_l1, mv, rws[:]))
            liv[:] = jnp.where(w_l, liv[:] - liv_hi,
                               jnp.where(w_l1, liv_hi, liv[:]))
            raw[:] = jnp.where(w_l, raw[:] - raw_hi,
                               jnp.where(w_l1, raw_hi, raw[:]))
            cumliv[:] = jnp.where(w_l, cumliv[:] - liv_hi, cumliv[:])
            cumraw[:] = jnp.where(w_l, cumraw[:] - raw_hi, cumraw[:])
            blkord[:] = jnp.where(w_l1, nbv, blkord[:])
            fwd[:] = jnp.where(do & (tidx == b), nbv, fwd[:])
            nlogv[:] = nlogv[:] + do.astype(jnp.int32)

    # ---- order -> run / position lookups --------------------------------

    def _verify_block(b_raw, o):
        """(found, block, in-block row) of order ``o`` within candidate
        block id ``b_raw`` (one K-row range test; out-of-range ids never
        match)."""
        ok = (b_raw >= 0) & (b_raw < NB)
        bc = jnp.where(ok, b_raw, 0)
        ws_o = gather_block(ordp, bc, K, NB)
        ws_l = gather_block(lenp, bc, K, NB)
        so = jnp.abs(ws_o) - 1
        hit = (ws_o != 0) & (so <= o) & (o < so + ws_l)
        f = ok & (jnp.sum(hit.astype(jnp.int32), axis=0,
                          keepdims=True) > 0)
        rowk = jnp.min(jnp.where(hit, kdx, K - 1), axis=0,
                       keepdims=True)
        return f, bc, rowk

    def locate_order(o, want, flag):
        """Per-lane (physical block, in-block row, found) of the run
        containing order ``o`` for ``want`` lanes: read the hint, VERIFY
        by one K-row range test; stale lanes chase the split FORWARD
        POINTERS (a moved run lives in the block its old block's top
        half LAST went to — fwd[b] keeps only the most recent split
        destination, so the two K-row hops cover the common one- and
        two-generation moves; older generations just fall back);
        only then fall back to one vectorized whole-plane scan (under
        ``lax.cond`` so hint-hit steps never pay it).  Hop/fallback
        hits heal the found run's whole hint span.  ``flag`` lanes (may
        be None) raise the order-miss flag when not found."""
        oc = jnp.clip(o, 0, OCAP - 1)
        bh = t_read(ordblk, oc)
        hfound, bhc, rowk_h = _verify_block(bh, o)

        miss1 = want & ~hfound

        def hops():
            b2 = jnp.sum(jnp.where(tidx == bhc, fwd[:], 0), axis=0,
                         keepdims=True)
            f2, b2c, r2 = _verify_block(jnp.where(hfound, -1, b2), o)
            b3 = jnp.sum(jnp.where(tidx == b2c, fwd[:], 0), axis=0,
                         keepdims=True)
            f3, b3c, r3 = _verify_block(jnp.where(f2, -1, b3), o)
            return (f2.astype(jnp.int32), b2c, r2,
                    f3.astype(jnp.int32), b3c, r3)

        z = jnp.zeros_like(bhc)
        f2i, b2, r2, f3i, b3, r3 = lax.cond(
            jnp.any(miss1), hops, lambda: (z, z, z, z, z, z))
        hop2 = miss1 & (f2i != 0)
        hop3 = miss1 & ~hop2 & (f3i != 0)
        miss2 = miss1 & ~hop2 & ~hop3

        def fallback():
            bo = ordp[:]
            sog = jnp.abs(bo) - 1
            ghit = (bo != 0) & (sog <= o) & (o < sog + lenp[:])
            gfound = jnp.sum(ghit.astype(jnp.int32), axis=0,
                             keepdims=True) > 0
            grow = jnp.min(jnp.where(ghit, idx_cap, CAP - 1), axis=0,
                           keepdims=True)
            return (gfound.astype(jnp.int32), grow)

        gfound_i, grow = lax.cond(
            jnp.any(miss2), fallback, lambda: (z, z))
        gfound = miss2 & (gfound_i != 0)
        found = hfound | hop2 | hop3 | gfound
        nb = jnp.where(hfound, bhc,
                       jnp.where(hop2, b2,
                                 jnp.where(hop3, b3, grow // K)))
        rowk = jnp.where(hfound, rowk_h,
                         jnp.where(hop2, r2,
                                   jnp.where(hop3, r3, grow % K)))

        heal = want & ~hfound & found

        @pl.when(jnp.any(heal))
        def _heal():
            # Heal the WHOLE found run's hint span (same one-pass cost
            # as a single entry): a stale run moved wholesale in a
            # block split, so later probes of its other chars would
            # miss too.
            gr = nb * K + rowk
            h_o = _vrow(ordp[:], gr)
            h_l = _vrow(lenp[:], gr)
            h_so = jnp.abs(h_o) - 1
            ordblk[:] = jnp.where(
                heal & (oidx >= h_so) & (oidx < h_so + h_l), nb,
                ordblk[:])

        if flag is not None:
            @pl.when(jnp.any(flag & ~found))
            def _missing():
                err_ref[2:3, :] = jnp.where(flag & ~found, 1,
                                            err_ref[2:3, :])

        return nb, rowk, found

    def slot_of_block(nb):
        """Logical slot holding physical block ``nb`` (NBT-row scan)."""
        lhit = (blkord[:] == nb) & (tidx < nlogv[:])
        return jnp.max(jnp.where(lhit, tidx, 0), axis=0, keepdims=True)

    def locate_order_pure(o):
        """Heal-free, flag-free locate for ``lax.cond`` branches (no
        ref writes; the order is known present)."""
        oc = jnp.clip(o, 0, OCAP - 1)
        bh = t_read(ordblk, oc)
        bh_ok = (bh >= 0) & (bh < NB)
        bhc = jnp.where(bh_ok, bh, 0)
        ws_o = gather_block(ordp, bhc, K, NB)
        ws_l = gather_block(lenp, bhc, K, NB)
        so = jnp.abs(ws_o) - 1
        hit = (ws_o != 0) & (so <= o) & (o < so + ws_l)
        hfound = bh_ok & (jnp.sum(hit.astype(jnp.int32), axis=0,
                                  keepdims=True) > 0)
        rowk_h = jnp.min(jnp.where(hit, kdx, K - 1), axis=0,
                         keepdims=True)
        bo = ordp[:]
        sog = jnp.abs(bo) - 1
        ghit = (bo != 0) & (sog <= o) & (o < sog + lenp[:])
        grow = jnp.min(jnp.where(ghit, idx_cap, CAP - 1), axis=0,
                       keepdims=True)
        return (jnp.where(hfound, bhc, grow // K),
                jnp.where(hfound, rowk_h, grow % K))

    def raw_pos_of_order(o, need):
        """RAW document position of order ``o``: hint-guided block
        locate + slot prefix (tables) + in-block prefix (K rows)."""
        nb, rowk, _ = locate_order(o, need, need)
        l = slot_of_block(nb)
        ws_o = gather_block(ordp, nb, K, NB)
        ws_l = gather_block(lenp, nb, K, NB)
        inblk = jnp.sum(jnp.where(kdx < rowk, ws_l, 0), axis=0,
                        keepdims=True)
        so_hit = jnp.abs(_vrow(ws_o, rowk)) - 1
        return raw_before(l) + inblk + (o - so_hit)

    def cursor_after(o, need):
        is_root = o == root_i
        unknown = need & (o == TAB_UNKNOWN)

        @pl.when(jnp.any(unknown))
        def _unk():
            err_ref[2:3, :] = jnp.where(unknown, 1, err_ref[2:3, :])

        p = raw_pos_of_order(jnp.maximum(o, 0), need & ~is_root)
        return jnp.where(is_root, 0, p + 1)

    def total_raw():
        return trow(cumraw, nlogv[:] - 1)

    # ---- local ops ------------------------------------------------------

    def do_local_delete(act, p, d):
        """Blocked per-lane live-rank tombstone (raw counts unchanged:
        tombstoning never moves raw positions)."""

        def body(carry):
            rem, iters = carry
            a = act & (rem > 0)
            l = slot_of(cumliv, p + 1, strict=True)
            need = a & (trow(rws, l) + 2 > K)

            @pl.when(jnp.any(need))
            def _():
                split(need, l)

            l = lax.cond(
                jnp.any(need),
                lambda: slot_of(cumliv, p + 1, strict=True), lambda: l)
            b = trow(blkord, l)
            base = live_before(l)
            ws_o = gather_block(ordp, b, K, NB)
            ws_l = gather_block(lenp, b, K, NB)
            lv = jnp.where(ws_o > 0, ws_l, 0)
            cum = _vcumsum(lv)
            before = base + cum - lv
            remm = jnp.where(a, rem, 0)
            cs = jnp.clip(p - before, 0, lv)
            ce = jnp.clip(p + remm - before, 0, lv)
            cov = ce - cs
            tot = jnp.sum(cov, axis=0, keepdims=True)
            full = (cov > 0) & (cov == ws_l)
            part = (cov > 0) & jnp.logical_not(full)
            npart = jnp.sum(part.astype(jnp.int32), axis=0,
                            keepdims=True)
            i1 = jnp.min(jnp.where(part, kdx, K), axis=0, keepdims=True)
            i2 = jnp.max(jnp.where(part, kdx, -1), axis=0, keepdims=True)
            ws_o = jnp.where(a & full, -ws_o, ws_o)
            ws_o, ws_l, a2 = lane_apply_partial(
                a & (npart >= 1), i2, ws_o, ws_l, cs, ce, kdx)
            ws_o, ws_l, a1 = lane_apply_partial(
                a & (npart == 2), i1, ws_o, ws_l, cs, ce, kdx)
            scatter_block(ordp, b, ws_o, a, K, NB)
            scatter_block(lenp, b, ws_l, a, K, NB)
            w_l = a & (tidx == l)
            rws[:] = jnp.where(w_l, rws[:] + a1 + a2, rws[:])
            liv[:] = jnp.where(w_l, liv[:] - tot, liv[:])
            # raw counts unchanged: tombstoning moves no raw positions.
            cumliv[:] = jnp.where(a & (tidx >= l), cumliv[:] - tot,
                                  cumliv[:])
            return rem - jnp.where(a, tot, 0), iters + 1

        rem, _ = lax.while_loop(
            lambda c: jnp.any(act & (c[0] > 0)) & (c[1] <= 2 * NBT),
            body, (jnp.where(act, d, 0), 0))

        @pl.when(jnp.any(act & (rem > 0)))
        def _bad():
            err_ref[1:2, :] = jnp.where(act & (rem > 0), 1,
                                        err_ref[1:2, :])

    fused_table_writes = partial(_fused_table_writes, oll, orl, oidx)

    def do_local_insert(act, k, p, il, st, w):
        """Blocked per-lane live-rank insert + by-order table upkeep.
        ``w`` > 1 is a FUSED backwards-burst step: W stride-L rows in
        one shift (the ``ops.rle`` ``_insert_splice`` contract; WMAX
        <= K//2 - 1 so the one leaf split always makes room)."""
        l = jnp.where(p == 0, 0, slot_of(cumliv, p, strict=True))
        need = act & (trow(rws, l) + w + 1 > K)

        @pl.when(jnp.any(need))
        def _():
            split(need, l)

        l = lax.cond(
            jnp.any(need),
            lambda: jnp.where(p == 0, 0,
                              slot_of(cumliv, p, strict=True)),
            lambda: l)
        r0 = trow(rws, l)
        b = trow(blkord, l)
        local = jnp.where(act, p - live_before(l), 0)
        ws_o = gather_block(ordp, b, K, NB)
        ws_l = gather_block(lenp, b, K, NB)
        lv = jnp.where(ws_o > 0, ws_l, 0)
        cum = _vcumsum(lv)
        i_r = jnp.sum(((cum < local) & (kdx < r0)).astype(jnp.int32),
                      axis=0, keepdims=True)
        o_r = _vrow(ws_o, i_r)
        l_r = _vrow(ws_l, i_r)
        off = local - (_vrow(cum, i_r) - _vrow(lv, i_r))

        left = jnp.where(p == 0, root_i, (o_r - 1) + (off - 1))
        no, nl, amt, mrg, is_split, lrun = fused_splice_rows(
            ws_o, ws_l, kdx, p, i_r, o_r, l_r, off, il, st, w, WMAX,
            _vshift, active=act)

        nxt_in_blk = _vrow(ws_o, i_r + 1)
        b2 = trow(blkord, jnp.minimum(l + 1, NBT - 1))
        nxt_slot_o = gather_head(ordp, b2, K, NB)
        first_o = gather_head(ordp, trow(blkord, 0), K, NB)
        succ_p0 = jnp.where(trow(rws, 0) > 0, first_o, 0)
        succ_after = jnp.where(i_r + 1 < r0, nxt_in_blk,
                               jnp.where(l + 1 < nlogv[:], nxt_slot_o, 0))
        succ = jnp.where(p == 0, succ_p0,
                         jnp.where(is_split, o_r + off, succ_after))
        right = jnp.where(succ == 0, root_i, jnp.abs(succ) - 1)
        scatter_block(ordp, b, no, act, K, NB)
        scatter_block(lenp, b, nl, act, K, NB)
        w_l = act & (tidx == l)
        rws[:] = jnp.where(w_l, rws[:] + amt, rws[:])
        liv[:] = jnp.where(w_l, liv[:] + il, liv[:])
        raw[:] = jnp.where(w_l, raw[:] + il, raw[:])
        cumliv[:] = jnp.where(act & (tidx >= l), cumliv[:] + il,
                              cumliv[:])
        cumraw[:] = jnp.where(act & (tidx >= l), cumraw[:] + il,
                              cumraw[:])

        fused_table_writes(act, st, il, lrun, left, right)
        t_write_run(ordblk, act, st, il, b)
        ol_ref[pl.ds(k, 1), :] = jnp.where(
            act, left.astype(jnp.uint32), ol_ref[pl.ds(k, 1), :])
        or_ref[pl.ds(k, 1), :] = jnp.where(
            act, right.astype(jnp.uint32), or_ref[pl.ds(k, 1), :])

    # ---- remote insert (`doc.rs:274-293` -> integrate) ------------------

    def run_at_raw(c):
        """Per-lane (signed start, len, 0-based offset) of the run
        holding RAW position ``c``: slot descent + one block gather."""
        ls = slot_of(cumraw, c, strict=False)
        b = trow(blkord, ls)
        r0 = trow(rws, ls)
        local = c - raw_before(ls)
        ws_o = gather_block(ordp, b, K, NB)
        ws_l = gather_block(lenp, b, K, NB)
        cumb = _vcumsum(ws_l)
        i_r = jnp.sum(((cumb <= local) & (kdx < r0)).astype(jnp.int32),
                      axis=0, keepdims=True)
        o_r = _vrow(ws_o, i_r)
        l_r = _vrow(ws_l, i_r)
        off = local - (_vrow(cumb, i_r) - l_r)
        return o_r, l_r, off

    def integrate_cursor(act, my_rank, o_left, o_right):
        """Per-lane YATA conflict scan — predicates identical to the
        un-blocked kernel (bit-identical cursors); only the probe's
        location machinery changed (table descent + block gather instead
        of a hoisted whole-plane cumsum)."""
        n = total_raw()
        cursor0 = cursor_after(o_left, act)
        left_cursor = cursor0

        def cond(state):
            cursor, scanning_i, scan_start, done_i = state
            return jnp.any((done_i == 0) & (cursor < n))

        def body(state):
            cursor, scanning_i, scan_start, done_i = state
            done = done_i != 0
            o_r, l_r, off = run_at_raw(cursor)
            so = jnp.abs(o_r) - 1
            other_order = so + off
            live = ~done & (cursor < n)
            other_left = t_read(oll, other_order)
            other_right = t_read(orl, other_order)
            other_rank = t_read(rkl_ref, other_order)
            olc = cursor_after(other_left, live)
            brk = (other_order == o_right) | (olc < left_cursor)
            eq = ~brk & (olc == left_cursor)
            gt = my_rank > other_rank
            brk = brk | (eq & ~gt & (o_right == other_right))
            starts_scan = eq & ~gt & (o_right != other_right)
            scanning = scanning_i != 0
            new_scan_start = jnp.where(
                live & starts_scan & ~scanning, cursor, scan_start)
            new_scanning_i = jnp.where(
                live & eq,
                jnp.where(gt, 0,
                          jnp.where(o_right == other_right, scanning_i,
                                    1)),
                scanning_i)
            contains_right = (o_right > other_order) & (o_right < so + l_r)
            step = jnp.where(contains_right, o_right - other_order,
                             l_r - off)
            new_cursor = jnp.where(live & ~brk, cursor + step, cursor)
            new_done_i = jnp.maximum(
                done_i, jnp.where(brk | (cursor >= n), 1, 0))
            return (new_cursor, new_scanning_i, new_scan_start,
                    new_done_i)

        zero = jnp.zeros_like(cursor0)
        init = (cursor0, zero, cursor0, (~act).astype(jnp.int32))
        cursor, scanning_i, scan_start, _ = lax.while_loop(
            cond, body, init)
        return jnp.where(scanning_i != 0, scan_start, cursor)

    def do_remote_insert(act, k, my_rank, o_left, o_right, il, st):
        c = integrate_cursor(act, my_rank, o_left, o_right)
        l = jnp.where(c == 0, 0, slot_of(cumraw, c, strict=True))
        need = act & (trow(rws, l) + 2 > K)

        @pl.when(jnp.any(need))
        def _():
            split(need, l)

        l = lax.cond(
            jnp.any(need),
            lambda: jnp.where(c == 0, 0,
                              slot_of(cumraw, c, strict=True)),
            lambda: l)
        r0 = trow(rws, l)
        b = trow(blkord, l)
        local = jnp.where(act, c - raw_before(l), 0)
        ws_o = gather_block(ordp, b, K, NB)
        ws_l = gather_block(lenp, b, K, NB)
        cumb = _vcumsum(ws_l)
        i_r = jnp.sum(((cumb < local) & (kdx < r0)).astype(jnp.int32),
                      axis=0, keepdims=True)
        o_r = _vrow(ws_o, i_r)
        l_r = _vrow(ws_l, i_r)
        off = local - (_vrow(cumb, i_r) - l_r)

        # Raw splice: the split run may be a TOMBSTONE (sign-preserving
        # tail); merge additionally requires a live predecessor AND the
        # op's origin_left chaining to the run's last char (the YATA
        # run-skip premise — see the un-blocked kernel).
        mrg = act & (c > 0) & (o_r > 0) & (off == l_r) & \
            ((st + 1) == (o_r + l_r)) & (o_left == o_r + l_r - 2)
        is_split = act & (c > 0) & (off < l_r)
        ins_at = jnp.where(c == 0, 0, i_r + 1)
        amt = jnp.where(jnp.logical_not(act) | mrg, 0,
                        jnp.where(is_split, 2, 1))
        so = _vshift(ws_o, amt)
        sl = _vshift(ws_l, amt)
        no = jnp.where(kdx < ins_at, ws_o, so)
        nl = jnp.where(kdx < ins_at, ws_l, sl)
        nl = jnp.where(is_split & (kdx == i_r), off, nl)
        new_run = act & jnp.logical_not(mrg) & (kdx == ins_at)
        no = jnp.where(new_run, st + 1, no)
        nl = jnp.where(new_run, il, nl)
        tail = is_split & (kdx == ins_at + 1)
        tail_o = jnp.where(o_r > 0, o_r + off, o_r - off)
        no = jnp.where(tail, tail_o, no)
        nl = jnp.where(tail, l_r - off, nl)
        nl = jnp.where(mrg & (kdx == i_r), l_r + il, nl)
        scatter_block(ordp, b, no, act, K, NB)
        scatter_block(lenp, b, nl, act, K, NB)
        w_l = act & (tidx == l)
        rws[:] = jnp.where(w_l, rws[:] + amt, rws[:])
        liv[:] = jnp.where(w_l, liv[:] + il, liv[:])
        raw[:] = jnp.where(w_l, raw[:] + il, raw[:])
        cumliv[:] = jnp.where(act & (tidx >= l), cumliv[:] + il,
                              cumliv[:])
        cumraw[:] = jnp.where(act & (tidx >= l), cumraw[:] + il,
                              cumraw[:])

        t_write_run(ordblk, act, st, il, b)
        ol_ref[pl.ds(k, 1), :] = jnp.where(
            act, o_left.astype(jnp.uint32), ol_ref[pl.ds(k, 1), :])
        or_ref[pl.ds(k, 1), :] = jnp.where(
            act, o_right.astype(jnp.uint32), or_ref[pl.ds(k, 1), :])

    # ---- remote delete (`doc.rs:295-340`, covered-run walk) -------------

    def do_remote_delete(act, t, dlen):
        """Order-interval tombstone as a HINT-GUIDED covered-run walk:
        the covered orders ``[t, t+dlen)`` are one contiguous order
        interval, so walking ``o_cur`` run-by-run (hinted locate, flip
        full covers, 3-way-split the <= 2 partial endpoint runs, count
        covered DEAD runs toward the idempotency total without
        flipping, `double_delete.rs:6-9`) touches O(K + NBT) rows per
        covered run instead of the un-blocked engine's whole-plane
        interval clip.  Iterations = covered runs (tiny for the
        config-5r <= 4-char deletes); every iteration makes >= 1 char
        of progress, so the static bound only guards corrupt streams.
        A lane whose target orders are absent flags BAD-DELETE (the
        un-blocked covered-total semantics) and stops cleanly; a lane
        whose endpoint split cannot be housed flags capacity."""
        end = t + jnp.where(act, dlen, 0)

        def body(carry):
            o_cur, rem, iters = carry
            a = act & (rem > 0)
            nb, rowk, found = locate_order(o_cur, a, None)
            miss = a & ~found

            @pl.when(jnp.any(miss))
            def _bad():
                err_ref[1:2, :] = jnp.where(miss, 1, err_ref[1:2, :])

            a = a & found
            ws_o = gather_block(ordp, nb, K, NB)
            ws_l = gather_block(lenp, nb, K, NB)
            o_r = _vrow(ws_o, rowk)
            l_r = _vrow(ws_l, rowk)
            so = jnp.abs(o_r) - 1
            aa = o_cur - so
            ee = jnp.minimum(l_r, end - so)
            live = o_r > 0
            ispartial = live & ((aa > 0) | (ee < l_r))
            l = slot_of_block(nb)
            need = a & ispartial & (trow(rws, l) + 2 > K)

            @pl.when(jnp.any(need))
            def _():
                split(need, l)

            # Re-locate only when a split moved rows, and drop lanes
            # whose split could not be housed (flagged by split();
            # their delete stops cleanly mid-walk).
            nb, rowk = lax.cond(jnp.any(need),
                                lambda: locate_order_pure(o_cur),
                                lambda: (nb, rowk))
            l = slot_of_block(nb)
            housed = jnp.logical_not(ispartial) | (trow(rws, l) + 2 <= K)
            a = a & housed
            ws_o = gather_block(ordp, nb, K, NB)
            ws_l = gather_block(lenp, nb, K, NB)
            o_r = _vrow(ws_o, rowk)
            l_r = _vrow(ws_l, rowk)
            so = jnp.abs(o_r) - 1
            aa = o_cur - so
            ee = jnp.minimum(l_r, end - so)
            cov = ee - aa
            live = o_r > 0
            ispartial = live & ((aa > 0) | (ee < l_r))

            # Full live cover: flip the one row.
            flip = a & live & jnp.logical_not(ispartial)
            ws_o2 = jnp.where(flip & (kdx == rowk), -ws_o, ws_o)
            # Partial live cover: [head?] [tombstone mid] [tail?].
            part = a & ispartial
            has_head = part & (aa > 0)
            has_tail = part & (ee < l_r)
            amt = has_head.astype(jnp.int32) + has_tail.astype(jnp.int32)
            sh_o = _vshift(ws_o2, amt)
            sh_l = _vshift(ws_l, amt)
            no = jnp.where(kdx <= rowk, ws_o2, sh_o)
            nl = jnp.where(kdx <= rowk, ws_l, sh_l)
            p0o = jnp.where(has_head, o_r, -(so + aa + 1))
            p0l = jnp.where(has_head, aa, cov)
            p1o = jnp.where(has_head, -(so + aa + 1), so + ee + 1)
            p1l = jnp.where(has_head, cov, l_r - ee)
            w0 = part & (kdx == rowk)
            no = jnp.where(w0, p0o, no)
            nl = jnp.where(w0, p0l, nl)
            w1 = part & (kdx == rowk + 1) & (amt >= 1)
            no = jnp.where(w1, p1o, no)
            nl = jnp.where(w1, p1l, nl)
            w2 = part & (kdx == rowk + 2) & (amt == 2)
            no = jnp.where(w2, so + ee + 1, no)
            nl = jnp.where(w2, l_r - ee, nl)
            touch = flip | part
            scatter_block(ordp, nb, no, touch, K, NB)
            scatter_block(lenp, nb, nl, touch, K, NB)
            dec = jnp.where(a & live, cov, 0)
            w_l = a & (tidx == l)
            rws[:] = jnp.where(w_l & part, rws[:] + amt, rws[:])
            liv[:] = jnp.where(w_l, liv[:] - dec, liv[:])
            cumliv[:] = jnp.where(a & (tidx >= l), cumliv[:] - dec,
                                  cumliv[:])
            # The new-run hint rows of split pieces stay within block
            # ``nb`` (splits into OTHER blocks already healed above).
            new_rem = jnp.where(miss | jnp.logical_not(housed), 0,
                                rem - jnp.where(a, cov, 0))
            return so + ee, new_rem, iters + 1

        # Every iteration covers >= 1 char, so covered runs bound the
        # trip count; CAP + NBT guards corrupt streams.
        _, rem, _ = lax.while_loop(
            lambda c: jnp.any(c[1] > 0) & (c[2] <= CAP + NBT),
            body, (jnp.where(act, t, 0), jnp.where(act, dlen, 0), 0))

        @pl.when(jnp.any(rem > 0))
        def _leftover():
            err_ref[1:2, :] = jnp.where(rem > 0, 1, err_ref[1:2, :])

    # ---- dispatch -------------------------------------------------------

    def op_body(k, _):
        kind = kind_ref[pl.ds(k, 1), :]
        p = pos_ref[pl.ds(k, 1), :]
        d = dlen_ref[pl.ds(k, 1), :]
        il = ilen_ref[pl.ds(k, 1), :]
        st = start_ref[pl.ds(k, 1), :]
        w = jnp.maximum(w_ref[pl.ds(k, 1), :], 1)  # pad rows carry 0

        act_ld = (kind == KIND_LOCAL) & (d > 0)
        act_li = (kind == KIND_LOCAL) & (il > 0)
        act_ri = (kind == KIND_REMOTE_INS) & (il > 0)
        act_rd = (kind == KIND_REMOTE_DEL) & (d > 0)

        @pl.when(jnp.any(act_ld))
        def _():
            do_local_delete(act_ld, p, d)

        @pl.when(jnp.any(act_li))
        def _():
            do_local_insert(act_li, k, p, il, st, w)

        @pl.when(jnp.any(act_ri))
        def _():
            do_remote_insert(act_ri, k, rk_ref[pl.ds(k, 1), :],
                             olop_ref[pl.ds(k, 1), :],
                             orop_ref[pl.ds(k, 1), :], il, st)

        @pl.when(jnp.any(act_rd))
        def _():
            do_remote_delete(act_rd, dtgt_ref[pl.ds(k, 1), :], d)

        return 0

    lax.fori_loop(0, CHUNK, op_body, 0)


@dataclasses.dataclass
class BlockedLanesMixedResult:
    """Blocked per-lane mixed outputs: block state + by-order tables."""

    ordp: jax.Array     # i32[CAP, B]
    lenp: jax.Array     # i32[CAP, B]
    nlog: jax.Array     # i32[1, B]
    blkord: jax.Array   # i32[NBT, B]
    rws: jax.Array      # i32[NBT, B]
    liv: jax.Array      # i32[NBT, B]
    raw: jax.Array      # i32[NBT, B]
    oll: jax.Array      # i32[OCAP, B]
    orl: jax.Array      # i32[OCAP, B]
    ordblk: jax.Array   # i32[OCAP, B] order->block hint (may be stale)
    fwd: jax.Array      # i32[NBT, B] split forward pointers
    ol: jax.Array       # u32[S, B]
    orr: jax.Array      # u32[S, B]
    err: jax.Array      # i32[8, B] 0: blocks; 1: bad delete; 2: order miss
    batch: int
    block_k: int

    def check(self) -> None:
        err = np.asarray(self.err)
        if err[0].max() != 0:
            raise RuntimeError(
                f"blocked rle_lanes_mixed out of blocks on lanes "
                f"{np.nonzero(err[0])[0][:8].tolist()}; raise capacity")
        if err[1].max() != 0:
            raise RuntimeError(
                f"delete ran past the end of the document on lanes "
                f"{np.nonzero(err[1])[0][:8].tolist()}")
        if err[2].max() != 0:
            raise RuntimeError(
                f"order lookup missed on lanes "
                f"{np.nonzero(err[2])[0][:8].tolist()}: an op referenced "
                f"an order absent from device state")

    def state(self):
        """The next chunk's ``init`` 11-tuple (the hint + forward
        tables ride along so warm-start chunks keep their locality)."""
        return (self.ordp, self.lenp, self.nlog, self.blkord, self.rws,
                self.liv, self.raw, self.oll, self.orl, self.ordblk,
                self.fwd)

    @property
    def rows(self):
        return jnp.sum(self.rws, axis=0, keepdims=True)


@functools.lru_cache(maxsize=32)
def _build_blocked_call(s_pad: int, B: int, capacity: int, block_k: int,
                        ocap: int, chunk: int, interpret: bool,
                        lane_tile: int | None = None, wmax: int = 1):
    """Shape-keyed cache for the blocked mixed kernel."""
    K = block_k
    NB = capacity // K
    NBT = max(8, NB)
    T = lane_tile or _lane_tile(B)
    _require(B % T == 0, f"lane_tile {T} must divide batch {B}")
    col = lambda: pl.BlockSpec((chunk, T), lambda lb, i: (i, lb),
                               memory_space=pltpu.VMEM)
    whole = lambda rows: pl.BlockSpec(
        (rows, T), lambda lb, i: (0, lb), memory_space=pltpu.VMEM)

    call = pl.pallas_call(
        partial(_mixed_lanes_blocked_kernel, K=K, NB=NB, NBT=NBT,
                CAP=capacity, OCAP=ocap, CHUNK=chunk, WMAX=wmax),
        grid=(B // T, s_pad // chunk),
        in_specs=[col() for _ in range(10)] + [
            whole(capacity), whole(capacity), whole(1),
            whole(NBT), whole(NBT), whole(NBT), whole(NBT),
            whole(ocap), whole(ocap), whole(ocap),  # prior table state
            whole(NBT),                         # prior fwd pointers
            whole(ocap), whole(ocap),           # prefill delta
            whole(ocap),                        # ranks (read-only)
        ],
        out_specs=[
            col(), col(),
            whole(capacity), whole(capacity), whole(1),
            whole(NBT), whole(NBT), whole(NBT), whole(NBT),
            whole(ocap), whole(ocap), whole(ocap), whole(NBT),
            whole(8),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s_pad, B), jnp.uint32),
            jax.ShapeDtypeStruct((s_pad, B), jnp.uint32),
            jax.ShapeDtypeStruct((capacity, B), jnp.int32),
            jax.ShapeDtypeStruct((capacity, B), jnp.int32),
            jax.ShapeDtypeStruct((1, B), jnp.int32),
            jax.ShapeDtypeStruct((NBT, B), jnp.int32),
            jax.ShapeDtypeStruct((NBT, B), jnp.int32),
            jax.ShapeDtypeStruct((NBT, B), jnp.int32),
            jax.ShapeDtypeStruct((NBT, B), jnp.int32),
            jax.ShapeDtypeStruct((ocap, B), jnp.int32),
            jax.ShapeDtypeStruct((ocap, B), jnp.int32),
            jax.ShapeDtypeStruct((ocap, B), jnp.int32),
            jax.ShapeDtypeStruct((NBT, B), jnp.int32),
            jax.ShapeDtypeStruct((8, B), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((NBT, T), jnp.int32),    # cumliv
            pltpu.VMEM((NBT, T), jnp.int32),    # cumraw
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=128 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return jax.jit(lambda *a: call(*a))


def make_replayer_lanes_mixed_blocked(
    ops: OpTensors,
    capacity: int,
    block_k: int = 64,
    order_capacity: int = 0,
    chunk: int = 128,
    init=None,
    rkl=None,
    interpret: bool = False,
    lane_tile: int | None = None,
):
    """Build a jitted BLOCKED per-lane MIXED replayer — bit-identical
    final state, YATA cursors, and per-op origins to
    ``make_replayer_lanes_mixed`` at O(NB + K) touched rows per step.

    Same contract as the un-blocked builder; ``capacity`` must be a
    ``block_k`` multiple, ``init`` a prior blocked ``state()`` 11-tuple.
    """
    kinds = np.asarray(ops.kind)
    _require(kinds.ndim == 2, "rle_lanes_mixed takes stacked per-doc "
             "streams ([S, B] columns; see batch.stack_ops)")
    S, B = kinds.shape
    _require(block_k >= 8, "block_k must hold a few runs")
    _require(capacity % block_k == 0,
             f"capacity ({capacity}) must be a multiple of block_k "
             f"({block_k})")
    wmax = fused_width_checked([ops], block_k)
    s_pad = max(((S + chunk - 1) // chunk) * chunk, chunk)

    adv = np.asarray(ops.order_advance, dtype=np.int64).sum(axis=0)
    base = 0
    if init is not None and init[7] is not None:
        base = init[7].shape[0]
    ocap = order_capacity or max(
        ((int(adv.max() + ops.lmax) + base + 7) // 8) * 8, 8)
    _require(ocap % 8 == 0, "order_capacity must be a multiple of 8")

    def staged_col(get):
        a = np.asarray(get(ops), dtype=np.uint32).view(np.int32)
        return jnp.asarray(np.pad(a, ((0, s_pad - S), (0, 0))))

    staged = tuple(staged_col(g) for g in (
        lambda o: o.kind, lambda o: o.pos, lambda o: o.del_len,
        lambda o: o.del_target, lambda o: o.origin_left,
        lambda o: o.origin_right, lambda o: o.rank, lambda o: o.ins_len,
        lambda o: o.ins_order_start, lambda o: o.rows_per_step))

    olld, orld, rkl0 = lane_tables(ops, ocap)
    if rkl is None:
        rkl = rkl0
    else:
        rkl = np.asarray(rkl, np.int32)
        _require(rkl.shape == (ocap, B),
                 f"rkl shape {rkl.shape} != ({ocap}, {B})")

    NBT = max(8, capacity // block_k)
    if init is None:
        init = _empty_mixed_blocked_state(capacity, NBT, ocap, B)
    else:
        init = _grow_mixed_blocked_state(init, capacity, block_k, ocap, B)
    jitted = _build_blocked_call(s_pad, B, capacity, block_k, ocap,
                                 chunk, interpret, lane_tile, wmax)
    deltas = (jnp.asarray(olld), jnp.asarray(orld), jnp.asarray(rkl))

    def run(state=None) -> BlockedLanesMixedResult:
        ini = init if state is None else _grow_mixed_blocked_state(
            state, capacity, block_k, ocap, B)
        (ol, orr, ordp, lenp, nlog, blk, rws, liv, raw, oll, orl,
         ordblk, fwd, err) = jitted(*staged, *ini, *deltas)
        return BlockedLanesMixedResult(
            ordp=ordp, lenp=lenp, nlog=nlog, blkord=blk, rws=rws,
            liv=liv, raw=raw, oll=oll, orl=orl, ordblk=ordblk, fwd=fwd,
            ol=ol[:S], orr=orr[:S], err=err, batch=B, block_k=block_k)

    return run


def _empty_mixed_blocked_state(capacity: int, NBT: int, ocap: int,
                               B: int):
    z = lambda r: jnp.zeros((r, B), jnp.int32)
    unk = lambda r: jnp.full((r, B), -1, jnp.int32)
    tab = lambda r: jnp.full((r, B), TAB_UNKNOWN, jnp.int32)
    return (z(capacity), z(capacity), z(1), z(NBT), z(NBT), z(NBT),
            z(NBT), tab(ocap), tab(ocap), unk(ocap),
            jnp.full((NBT, B), -1, jnp.int32))


def _grow_mixed_blocked_state(state, capacity: int, block_k: int,
                              ocap: int, B: int):
    """Pad a prior chunk's blocked mixed 11-tuple up to this chunk's
    row/order capacities (fixed K; NB and OCAP only grow)."""
    from .rle_lanes import _grow_blocked_state

    o0, l0, nlog, blk, rws, liv = _grow_blocked_state(
        state[:6], capacity, block_k, B)
    NBT = max(8, capacity // block_k)
    rawt = jnp.asarray(state[6], jnp.int32)
    if rawt.shape[0] < NBT:
        rawt = jnp.concatenate(
            [rawt, jnp.zeros((NBT - rawt.shape[0], B), jnp.int32)],
            axis=0)
    hint = jnp.asarray(state[9], jnp.int32)
    if hint.shape[0] < ocap:
        hint = jnp.concatenate(
            [hint, jnp.full((ocap - hint.shape[0], B), -1, jnp.int32)],
            axis=0)
    fwdt = jnp.asarray(state[10], jnp.int32)
    if fwdt.shape[0] < NBT:
        fwdt = jnp.concatenate(
            [fwdt, jnp.full((NBT - fwdt.shape[0], B), -1, jnp.int32)],
            axis=0)
    return (o0, l0, nlog, blk, rws, liv, rawt,
            _grow_table(state[7], ocap, B),
            _grow_table(state[8], ocap, B),
            hint, fwdt)


def replay_lanes_mixed_blocked(ops: OpTensors, capacity: int,
                               **kw) -> BlockedLanesMixedResult:
    """One-shot wrapper over ``make_replayer_lanes_mixed_blocked``."""
    return make_replayer_lanes_mixed_blocked(ops, capacity, **kw)()

"""Per-lane divergent MIXED replay: B distinct documents, each applying
its OWN local/remote op stream — the production sync shape.

``ops.rle_mixed`` runs the full op surface (KIND_LOCAL/REMOTE_INS/
REMOTE_DEL, `doc.rs:242-348`) but in LOCKSTEP: one shared scalar stream
across identical lanes.  ``ops.rle_lanes`` runs divergent per-lane
streams but refuses remote ops.  This engine is the round-5 unification
(VERDICT r4 missing #2): thousands of *different* documents each
receiving *its own* remote-op stream, one op per lane per kernel step.

Design — rle_lanes' lane-vector layout carried over to the remote paths:

- document state is the un-blocked run column pair ``ordp/lenp``
  [CAP, B] (±(order+1), len) plus ``rows`` [1, B]; every op scalar of
  ``rle_mixed`` becomes a [1, B] lane vector; splices stay <= 3 rows so
  per-lane dynamic shifts are two static ``pltpu.roll``s blended by
  per-lane masks (the rle_lanes trick);
- **per-lane by-order tables** ``oll/orl/rkl`` [OCAP, B] (row = order,
  lane = doc) replace rle_mixed's 128-orders/row packed tables: each
  lane has its own order space, so the packing collapses to one row per
  order and reads/writes are one masked [OCAP, B] pass.  Prefilled
  host-side per lane (`batch._prefill_scatter`), sentinel −2 = unknown;
  unknown entries are never probed (every existing char's entry was
  prefilled or written by the local-insert path at insert time);
- **no order->block hint table**: the lanes layout always works on the
  whole [CAP, B] plane, so order lookup IS the one vectorized
  range-test pass that rle_mixed's ``ordblk`` miss-path falls back to —
  there is nothing to hint, go stale, or self-heal;
- **run-level YATA integrate** (`doc.rs:167-234`) with PER-LANE scan
  state: (cursor, scanning, scan_start, done) are [1, B] vectors; the
  while-loop runs until every lane breaks (conflict-free lanes break on
  the first probe, `doc.rs:192-194`, so iterations = the max conflict
  depth across lanes, not the sum).  The raw prefix sum the scan
  descends on is HOISTED out of the loop — the scan never mutates
  state, so one ``_vcumsum`` serves every probe of the step;
- **one-pass remote delete**: runs are disjoint ORDER intervals, so a
  target range ``[t, t+dlen)`` fully covers every run it touches except
  at most the two holding its endpoints — one interval-clip pass flips
  the full covers and 3-way-splits the <= 2 partial runs, exactly the
  local-delete shape keyed by orders (no fragmentation walk, no dmax
  pre-chunking); covered DEAD runs count toward the idempotency total
  without flipping (`double_delete.rs:6-9`).

State (ordp, lenp, rows, oll, orl) is a kernel input AND output — chunk
N+1 resumes from chunk N on device (the config-5 streaming warm start),
with each chunk's compile-known table entries merged in at step 0 via
the −2 sentinel.  ``rkl`` is read-only (author ranks are compile-time
facts; the host accumulates the full table across chunks).
"""
from __future__ import annotations

import dataclasses
import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .batch import (
    KIND_LOCAL,
    KIND_REMOTE_DEL,
    KIND_REMOTE_INS,
    OpTensors,
    _prefill_scatter,
)
from .blocked import _require
from .rle_lanes import (
    LanesResult,
    _lane_tile,
    _live_prefix,
    _shared_cum_gate,
    _vcumsum,
    _vrow,
    _vshift,
)

TAB_UNKNOWN = -2  # by-order table sentinel: entry not yet known


def _mixed_lanes_kernel(
    kind_ref, pos_ref, dlen_ref, dtgt_ref, olop_ref, orop_ref, rk_ref,
    ilen_ref, start_ref,                        # [CHUNK, B] VMEM op columns
    ord0_ref, len0_ref, rows0_ref,              # warm-start state inputs
    oll0_ref, orl0_ref,                         # prior table state [OCAP, B]
    olld_ref, orld_ref,                         # this stream's prefill delta
    rkl_ref,                                    # ranks (read-only, full)
    ol_ref, or_ref,                             # [CHUNK, B] origin outputs
    ordp, lenp, rowsv,                          # state outputs (working)
    oll, orl,                                   # table outputs (working)
    err_ref,
    *, CAP: int, OCAP: int, CHUNK: int, SHARED_CUM: bool = False,
):
    B = ordp.shape[1]
    i = pl.program_id(1)
    idx = lax.broadcasted_iota(jnp.int32, (CAP, B), 0)
    oidx = lax.broadcasted_iota(jnp.int32, (OCAP, B), 0)
    root_i = jnp.int32(-1)  # ROOT_ORDER as i32
    root_u = jnp.uint32(0xFFFFFFFF)

    ol_ref[:] = jnp.zeros_like(ol_ref)
    or_ref[:] = jnp.zeros_like(or_ref)

    @pl.when(i == 0)
    def _init():
        ordp[:] = ord0_ref[:]
        lenp[:] = len0_ref[:]
        rowsv[:] = rows0_ref[:]
        # Merge this stream's compile-known entries over the carried
        # tables (chunk N+1's new orders were −2 in chunk N's state).
        oll[:] = jnp.where(olld_ref[:] != TAB_UNKNOWN, olld_ref[:],
                           oll0_ref[:])
        orl[:] = jnp.where(orld_ref[:] != TAB_UNKNOWN, orld_ref[:],
                           orl0_ref[:])
        err_ref[:] = jnp.zeros_like(err_ref)

    # ---- per-lane by-order table ops ------------------------------------

    def t_read(tab, o):
        """tab[o[lane], lane] as [1, B]; o values < 0 read row 0 (callers
        mask ROOT probes before use)."""
        oc = jnp.clip(o, 0, OCAP - 1)
        return jnp.sum(jnp.where(oidx == oc, tab[:], 0), axis=0,
                       keepdims=True)

    def t_write(tab, act, o, v):
        tab[:] = jnp.where(act & (oidx == o), v, tab[:])

    def t_write_run(tab, act, st, ln, v):
        tab[:] = jnp.where(act & (oidx >= st) & (oidx < st + ln), v,
                           tab[:])

    # ---- order -> run / position lookups --------------------------------

    def find_run_of_order(o, need):
        """Per-lane row/run containing order ``o`` ([1, B]): one
        vectorized range test over the whole plane.  Raises the
        missing-order flag for ``need`` lanes with no hit."""
        bo = ordp[:]
        so = jnp.abs(bo) - 1
        hit = (bo != 0) & (so <= o) & (o < so + lenp[:])
        found = jnp.sum(hit.astype(jnp.int32), axis=0, keepdims=True) > 0
        row = jnp.min(jnp.where(hit, idx, CAP), axis=0, keepdims=True)

        @pl.when(jnp.any(need & ~found))
        def _missing():
            err_ref[2:3, :] = jnp.where(need & ~found, 1, err_ref[2:3, :])

        return jnp.where(found, row, 0), found

    def raw_pos_of_order(o, need):
        """Per-lane RAW document position of the char with order ``o``."""
        row, _ = find_run_of_order(o, need)
        raw_before = jnp.sum(jnp.where(idx < row, lenp[:], 0), axis=0,
                             keepdims=True)
        so_hit = jnp.abs(_vrow(ordp[:], row)) - 1
        return raw_before + (o - so_hit)

    def cursor_after(o, need):
        is_root = o == root_i
        # An unknown table entry (sentinel −2) must flag, not silently
        # resolve as order 0 (review r5).
        unknown = need & (o == TAB_UNKNOWN)

        @pl.when(jnp.any(unknown))
        def _unk():
            err_ref[2:3, :] = jnp.where(unknown, 1, err_ref[2:3, :])

        p = raw_pos_of_order(jnp.maximum(o, 0), need & ~is_root)
        return jnp.where(is_root, 0, p + 1)

    # ---- local ops (rle_lanes paths + table upkeep) ---------------------

    def flag_capacity(act):
        @pl.when(jnp.any(act & (rowsv[:] + 2 > CAP)))
        def _cap():
            err_ref[0:1, :] = jnp.where(act & (rowsv[:] + 2 > CAP), 1,
                                        err_ref[0:1, :])

    def apply_partial(a, i_p, bo, bl, cs, ce):
        """Split run row ``i_p`` around its covered sub-range
        ``[cs, ce)`` into [head?] [tombstone mid] [tail?] (<= +2 rows),
        per lane where ``a``.  The signed-start fix-up covers LIVE runs
        only (partial coverage of a dead run never reaches here)."""
        o = _vrow(bo, i_p)
        ln = _vrow(bl, i_p)
        cs_i = _vrow(cs, i_p)
        ce_i = _vrow(ce, i_p)
        cov_i = ce_i - cs_i
        has_head = (cs_i > 0) & a
        has_tail = (ce_i < ln) & a
        amt = has_head.astype(jnp.int32) + has_tail.astype(jnp.int32)
        so = _vshift(bo, amt)
        sl = _vshift(bl, amt)
        no = jnp.where(idx <= i_p, bo, so)
        nl = jnp.where(idx <= i_p, bl, sl)
        p0o = jnp.where(has_head, o, -(o + cs_i))
        p0l = jnp.where(has_head, cs_i, cov_i)
        p1o = jnp.where(has_head, -(o + cs_i), o + ce_i)
        p1l = jnp.where(has_head, cov_i, ln - ce_i)
        w0 = a & (idx == i_p)
        no = jnp.where(w0, p0o, no)
        nl = jnp.where(w0, p0l, nl)
        w1 = a & (idx == i_p + 1) & (amt >= 1)
        no = jnp.where(w1, p1o, no)
        nl = jnp.where(w1, p1l, nl)
        w2 = a & (idx == i_p + 2) & (amt == 2)
        no = jnp.where(w2, o + ce_i, no)
        nl = jnp.where(w2, ln - ce_i, nl)
        return no, nl, amt

    def do_local_delete(act, p, d, lv=None, cum=None):
        """Whole-doc single-pass tombstone (rle_lanes.do_delete)."""
        flag_capacity(act)
        bo = ordp[:]
        bl = lenp[:]
        if cum is None:
            lv, cum = _live_prefix(bo, bl)
        before = cum - lv
        rem = jnp.where(act, d, 0)
        cs = jnp.clip(p - before, 0, lv)
        ce = jnp.clip(p + rem - before, 0, lv)
        cov = ce - cs
        tot = jnp.sum(cov, axis=0, keepdims=True)

        @pl.when(jnp.any(act & (tot < rem)))
        def _bad():
            err_ref[1:2, :] = jnp.where(act & (tot < rem), 1,
                                        err_ref[1:2, :])

        full = (cov > 0) & (cov == bl)
        part = (cov > 0) & jnp.logical_not(full)
        npart = jnp.sum(part.astype(jnp.int32), axis=0, keepdims=True)
        i1 = jnp.min(jnp.where(part, idx, CAP), axis=0, keepdims=True)
        i2 = jnp.max(jnp.where(part, idx, -1), axis=0, keepdims=True)
        bo = jnp.where(act & full, -bo, bo)

        bo, bl, a2 = apply_partial(act & (npart >= 1), i2, bo, bl, cs, ce)
        bo, bl, a1 = apply_partial(act & (npart == 2), i1, bo, bl, cs, ce)
        ordp[:] = bo
        lenp[:] = bl
        rowsv[:] = rowsv[:] + jnp.where(act, a1 + a2, 0)

    def do_local_insert(act, k, p, il, st, lv=None, cum=None):
        """rle_lanes.do_insert + by-order table upkeep (the origins a
        local insert discovers at apply time, `doc.rs:447-453`).
        ``lv``/``cum`` may be the step-hoisted PRE-DELETE live prefix
        (valid: shared-cum mode excludes same-lane delete+insert
        steps); ``bo``/``bl`` stay FRESH so the whole-plane writes
        preserve the delete branch's results on other lanes."""
        flag_capacity(act)
        rows = rowsv[:]
        bo = ordp[:]
        bl = lenp[:]
        if cum is None:
            lv, cum = _live_prefix(bo, bl)
        local = jnp.where(act, p, 0)
        i_r = jnp.sum(((cum < local) & (idx < rows)).astype(jnp.int32),
                      axis=0, keepdims=True)
        o_r = _vrow(bo, i_r)
        l_r = _vrow(bl, i_r)
        off = local - (_vrow(cum, i_r) - _vrow(lv, i_r))

        left = jnp.where(p == 0, root_i, (o_r - 1) + (off - 1))
        mrg = act & (p > 0) & (off == l_r) & ((st + 1) == (o_r + l_r))
        is_split = act & (p > 0) & (off < l_r)

        nxt_in_blk = _vrow(bo, i_r + 1)
        first_o = _vrow(bo, 0)
        succ_p0 = jnp.where(rows > 0, first_o, 0)
        succ_after = jnp.where(i_r + 1 < rows, nxt_in_blk, 0)
        succ = jnp.where(p == 0, succ_p0,
                         jnp.where(is_split, o_r + off, succ_after))
        right = jnp.where(succ == 0, root_i, jnp.abs(succ) - 1)

        ins_at = jnp.where(p == 0, 0, i_r + 1)
        amt = jnp.where(jnp.logical_not(act) | mrg, 0,
                        jnp.where(is_split, 2, 1))
        so = _vshift(bo, amt)
        sl = _vshift(bl, amt)
        no = jnp.where(idx < ins_at, bo, so)
        nl = jnp.where(idx < ins_at, bl, sl)
        nl = jnp.where(is_split & (idx == i_r), off, nl)
        new_run = act & jnp.logical_not(mrg) & (idx == ins_at)
        no = jnp.where(new_run, st + 1, no)
        nl = jnp.where(new_run, il, nl)
        tail = is_split & (idx == ins_at + 1)
        no = jnp.where(tail, o_r + off, no)
        nl = jnp.where(tail, l_r - off, nl)
        nl = jnp.where(mrg & (idx == i_r), l_r + il, nl)
        ordp[:] = no
        lenp[:] = nl
        rowsv[:] = rows + amt

        t_write(oll, act, st, left)
        t_write_run(orl, act, st, il, right)
        ol_ref[pl.ds(k, 1), :] = jnp.where(
            act, left.astype(jnp.uint32), ol_ref[pl.ds(k, 1), :])
        or_ref[pl.ds(k, 1), :] = jnp.where(
            act, right.astype(jnp.uint32), or_ref[pl.ds(k, 1), :])

    # ---- remote insert (`doc.rs:274-293` -> integrate) ------------------

    def integrate_cursor(act, my_rank, o_left, o_right):
        """Per-lane YATA conflict scan over runs (rle_mixed
        ``integrate_cursor`` with [1, B] scan state).  The raw prefix is
        hoisted: the scan mutates nothing, so one cumsum serves every
        probe of every lane this step."""
        cumraw = _vcumsum(lenp[:])
        n = jnp.sum(lenp[:], axis=0, keepdims=True)
        cursor0 = cursor_after(o_left, act)
        left_cursor = cursor0

        def run_at_raw(c):
            i_r = jnp.sum(((cumraw <= c) & (idx < rowsv[:])).astype(
                jnp.int32), axis=0, keepdims=True)
            o_r = _vrow(ordp[:], i_r)
            l_r = _vrow(lenp[:], i_r)
            off = c - (_vrow(cumraw, i_r) - l_r)
            return o_r, l_r, off

        # Loop-carried lane masks ride as i32 0/1: Mosaic materializes
        # loop-carried [1, T] i1 vectors as i8 and has no i8->i1
        # truncation, so a bool carry fails to compile on real TPU
        # (the cfg5r MosaicError in perf/compile_pin_r5.log).
        def cond(state):
            cursor, scanning_i, scan_start, done_i = state
            return jnp.any((done_i == 0) & (cursor < n))

        def body(state):
            cursor, scanning_i, scan_start, done_i = state
            scanning = scanning_i != 0
            done = done_i != 0
            o_r, l_r, off = run_at_raw(cursor)
            so = jnp.abs(o_r) - 1
            other_order = so + off
            live = ~done & (cursor < n)
            other_left = t_read(oll, other_order)
            other_right = t_read(orl, other_order)
            other_rank = t_read(rkl_ref, other_order)
            olc = cursor_after(other_left, live)
            brk = (other_order == o_right) | (olc < left_cursor)
            eq = ~brk & (olc == left_cursor)
            gt = my_rank > other_rank
            brk = brk | (eq & ~gt & (o_right == other_right))
            starts_scan = eq & ~gt & (o_right != other_right)
            new_scan_start = jnp.where(
                live & starts_scan & ~scanning, cursor, scan_start)
            # i32-VALUED selects: a vector select whose RESULTS are i1
            # makes Mosaic round-trip the mask through i8 (the trunci
            # MosaicError); selecting 0/1 i32 keeps it on the vreg path.
            new_scanning_i = jnp.where(
                live & eq,
                jnp.where(gt, 0,
                          jnp.where(o_right == other_right, scanning_i,
                                    1)),
                scanning_i)
            contains_right = (o_right > other_order) & (o_right < so + l_r)
            step = jnp.where(contains_right, o_right - other_order,
                             l_r - off)
            new_cursor = jnp.where(live & ~brk, cursor + step, cursor)
            new_done_i = jnp.maximum(
                done_i, jnp.where(brk | (cursor >= n), 1, 0))
            return (new_cursor, new_scanning_i, new_scan_start,
                    new_done_i)

        zero = jnp.zeros_like(cursor0)  # [1, B] i32 False
        init = (cursor0, zero, cursor0, (~act).astype(jnp.int32))
        cursor, scanning_i, scan_start, _ = lax.while_loop(
            cond, body, init)
        return jnp.where(scanning_i != 0, scan_start, cursor), cumraw

    def do_remote_insert(act, k, my_rank, o_left, o_right, il, st):
        flag_capacity(act)
        c, cumraw = integrate_cursor(act, my_rank, o_left, o_right)
        rows = rowsv[:]
        bo = ordp[:]
        bl = lenp[:]
        local = jnp.where(act, c, 0)
        i_r = jnp.sum(((cumraw < local) & (idx < rows)).astype(jnp.int32),
                      axis=0, keepdims=True)
        o_r = _vrow(bo, i_r)
        l_r = _vrow(bl, i_r)
        off = local - (_vrow(cumraw, i_r) - l_r)

        # Raw-position splice (`rle_mixed._insert_splice_raw` lane-wise):
        # the split run may be a TOMBSTONE (preserve sign on the tail);
        # the merge fast path additionally requires a live predecessor
        # AND the op's origin_left chaining to the run's last char — the
        # YATA run-skip evaluates only run heads on the premise that
        # non-head chars' origin_left is their own predecessor, so an
        # unchained merge would hide a char the scan must evaluate.
        mrg = act & (c > 0) & (o_r > 0) & (off == l_r) & \
            ((st + 1) == (o_r + l_r)) & (o_left == o_r + l_r - 2)
        is_split = act & (c > 0) & (off < l_r)
        ins_at = jnp.where(c == 0, 0, i_r + 1)
        amt = jnp.where(jnp.logical_not(act) | mrg, 0,
                        jnp.where(is_split, 2, 1))
        so = _vshift(bo, amt)
        sl = _vshift(bl, amt)
        no = jnp.where(idx < ins_at, bo, so)
        nl = jnp.where(idx < ins_at, bl, sl)
        nl = jnp.where(is_split & (idx == i_r), off, nl)
        new_run = act & jnp.logical_not(mrg) & (idx == ins_at)
        no = jnp.where(new_run, st + 1, no)
        nl = jnp.where(new_run, il, nl)
        tail = is_split & (idx == ins_at + 1)
        tail_o = jnp.where(o_r > 0, o_r + off, o_r - off)
        no = jnp.where(tail, tail_o, no)
        nl = jnp.where(tail, l_r - off, nl)
        nl = jnp.where(mrg & (idx == i_r), l_r + il, nl)
        ordp[:] = no
        lenp[:] = nl
        rowsv[:] = rows + amt

        # Remote origins are compile-time facts already prefilled into
        # the tables; only the per-op outputs remain.
        ol_ref[pl.ds(k, 1), :] = jnp.where(
            act, o_left.astype(jnp.uint32), ol_ref[pl.ds(k, 1), :])
        or_ref[pl.ds(k, 1), :] = jnp.where(
            act, o_right.astype(jnp.uint32), or_ref[pl.ds(k, 1), :])

    # ---- remote delete (`doc.rs:295-340`) -------------------------------

    def do_remote_delete(act, t, dlen):
        """Order-interval tombstone in ONE pass (`doc.rs:295-340`
        without the fragmentation walk): runs are disjoint order
        intervals, so at most TWO covered runs are partial — the ones
        holding ``t`` and ``t+dlen-1`` — and every other covered run is
        fully inside ``[t, t+dlen)`` and flips wholesale.  Same shape as
        the local delete, keyed by ORDERS instead of live ranks; covered
        DEAD runs just count toward the idempotency total without
        flipping (`double_delete.rs:6-9`; excess counting is host-side
        per SURVEY).  Any ``dlen`` in one step — no dmax pre-chunking."""
        bo = ordp[:]
        bl = lenp[:]
        so = jnp.abs(bo) - 1
        occ = bo != 0
        cs = jnp.clip(t - so, 0, bl)
        ce = jnp.clip(t + dlen - so, 0, bl)
        cov = jnp.where(act & occ, ce - cs, 0)
        tot = jnp.sum(cov, axis=0, keepdims=True)
        rem = jnp.where(act, dlen, 0)

        @pl.when(jnp.any(act & (tot < rem)))
        def _bad():
            err_ref[1:2, :] = jnp.where(act & (tot < rem), 1,
                                        err_ref[1:2, :])

        live = bo > 0
        full = live & (cov > 0) & (cov == bl)
        part = live & (cov > 0) & jnp.logical_not(cov == bl)
        npart = jnp.sum(part.astype(jnp.int32), axis=0, keepdims=True)
        # Max growth is +2 per op: one run holding both endpoints 3-way
        # splits (+2), or the two endpoint runs each split one-sided
        # (+1 each).  Gate BOTH splits and the full flips so a flagged
        # delete is a clean no-op (review r5: overflow would let
        # pltpu.roll silently wrap the plane).
        tight = act & (npart > 0) & (rowsv[:] + 2 > CAP)

        @pl.when(jnp.any(tight))
        def _cap():
            err_ref[0:1, :] = jnp.where(tight, 1, err_ref[0:1, :])

        a = act & ~tight
        i1 = jnp.min(jnp.where(part, idx, CAP), axis=0, keepdims=True)
        i2 = jnp.max(jnp.where(part, idx, -1), axis=0, keepdims=True)
        bo = jnp.where(a & full, -bo, bo)

        bo, bl, a2 = apply_partial(a & (npart >= 1), i2, bo, bl, cs, ce)
        bo, bl, a1 = apply_partial(a & (npart == 2), i1, bo, bl, cs, ce)
        ordp[:] = bo
        lenp[:] = bl
        rowsv[:] = rowsv[:] + jnp.where(a, a1 + a2, 0)

    # ---- dispatch -------------------------------------------------------

    def op_body(k, _):
        kind = kind_ref[pl.ds(k, 1), :]
        p = pos_ref[pl.ds(k, 1), :]
        d = dlen_ref[pl.ds(k, 1), :]
        il = ilen_ref[pl.ds(k, 1), :]
        st = start_ref[pl.ds(k, 1), :]

        act_ld = (kind == KIND_LOCAL) & (d > 0)
        act_li = (kind == KIND_LOCAL) & (il > 0)
        act_ri = (kind == KIND_REMOTE_INS) & (il > 0)
        act_rd = (kind == KIND_REMOTE_DEL) & (d > 0)

        if SHARED_CUM:
            # One live prefix serves both LOCAL branches (no lane
            # deletes AND inserts in one step, and both-branch steps
            # outnumber no-local steps — both checked statically).
            lv, cum = _live_prefix(ordp[:], lenp[:])
        else:
            lv = cum = None

        @pl.when(jnp.any(act_ld))
        def _():
            do_local_delete(act_ld, p, d, lv, cum)

        @pl.when(jnp.any(act_li))
        def _():
            do_local_insert(act_li, k, p, il, st, lv, cum)

        @pl.when(jnp.any(act_ri))
        def _():
            do_remote_insert(act_ri, k, rk_ref[pl.ds(k, 1), :],
                             olop_ref[pl.ds(k, 1), :],
                             orop_ref[pl.ds(k, 1), :], il, st)

        @pl.when(jnp.any(act_rd))
        def _():
            do_remote_delete(act_rd, dtgt_ref[pl.ds(k, 1), :], d)

        return 0

    lax.fori_loop(0, CHUNK, op_body, 0)


@dataclasses.dataclass
class LanesMixedResult(LanesResult):
    """``LanesResult`` + per-lane by-order table state (the warm-start
    carry) and the missing-order flag (err row 2)."""

    oll: jax.Array = None   # i32[OCAP, B]
    orl: jax.Array = None   # i32[OCAP, B]

    def check(self) -> None:
        super().check()
        err = np.asarray(self.err)
        if err[2].max() != 0:
            raise RuntimeError(
                f"order lookup missed on lanes "
                f"{np.nonzero(err[2])[0][:8].tolist()}: an op referenced "
                f"an order absent from device state")

    def state(self):
        """(ordp, lenp, rows, oll, orl) — the next chunk's ``init``."""
        return self.ordp, self.lenp, self.rows, self.oll, self.orl


def lane_tables(stacked: OpTensors, ocap: int):
    """Per-lane by-order prefill: (oll, orl, rkl) as i32[OCAP, B] numpy,
    sentinel −2 for unknown entries (−1 is ROOT).  Everything the
    compiler knows: remote head origins, within-run chains, author
    ranks (`batch._prefill_scatter` per lane)."""
    kinds = np.asarray(stacked.kind)
    assert kinds.ndim == 2, "lane_tables takes stacked [S, B] streams"
    B = kinds.shape[1]
    oll = np.full((B, ocap), TAB_UNKNOWN, np.int32)
    orl = np.full((B, ocap), TAB_UNKNOWN, np.int32)
    rkl = np.zeros((B, ocap), np.int32)
    for b in range(B):
        per = jax.tree.map(lambda a: np.asarray(a)[:, b], stacked)
        sc = _prefill_scatter(per)
        if sc is None:
            continue
        oll[b, sc["ol"][0]] = sc["ol"][1].astype(np.uint32).astype(
            np.int64).astype(np.int32)  # u32 ROOT -> -1
        orl[b, sc["or"][0]] = sc["or"][1].astype(np.uint32).astype(
            np.int64).astype(np.int32)
        rkl[b, sc["rank"][0]] = sc["rank"][1]
    return (np.ascontiguousarray(oll.T), np.ascontiguousarray(orl.T),
            np.ascontiguousarray(rkl.T))


@functools.lru_cache(maxsize=32)
def _build_call(s_pad: int, B: int, capacity: int, ocap: int, chunk: int,
                interpret: bool, lane_tile: int | None = None,
                shared_cum: bool = False):
    """Shape-keyed cache (streaming chunks share one compiled kernel)."""
    T = lane_tile or _lane_tile(B)
    _require(B % T == 0, f"lane_tile {T} must divide batch {B}")
    col = lambda: pl.BlockSpec((chunk, T), lambda lb, i: (i, lb),
                               memory_space=pltpu.VMEM)
    whole = lambda rows: pl.BlockSpec(
        (rows, T), lambda lb, i: (0, lb), memory_space=pltpu.VMEM)

    call = pl.pallas_call(
        partial(_mixed_lanes_kernel, CAP=capacity, OCAP=ocap,
                CHUNK=chunk, SHARED_CUM=shared_cum),
        grid=(B // T, s_pad // chunk),
        in_specs=[col() for _ in range(9)] + [
            whole(capacity), whole(capacity), whole(1),
            whole(ocap), whole(ocap),           # prior table state
            whole(ocap), whole(ocap),           # prefill delta
            whole(ocap),                        # ranks (read-only)
        ],
        out_specs=[
            col(), col(),
            whole(capacity), whole(capacity), whole(1),
            whole(ocap), whole(ocap),
            whole(8),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s_pad, B), jnp.uint32),
            jax.ShapeDtypeStruct((s_pad, B), jnp.uint32),
            jax.ShapeDtypeStruct((capacity, B), jnp.int32),
            jax.ShapeDtypeStruct((capacity, B), jnp.int32),
            jax.ShapeDtypeStruct((1, B), jnp.int32),
            jax.ShapeDtypeStruct((ocap, B), jnp.int32),
            jax.ShapeDtypeStruct((ocap, B), jnp.int32),
            jax.ShapeDtypeStruct((8, B), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=128 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return jax.jit(lambda *a: call(*a))


def make_replayer_lanes_mixed(
    ops: OpTensors,
    capacity: int,
    order_capacity: int = 0,
    chunk: int = 128,
    init=None,
    rkl=None,
    interpret: bool = False,
    lane_tile: int | None = None,
):
    """Build a jitted per-lane MIXED replayer for stacked per-doc streams
    (``stack_ops`` output: every column [S, B]; kinds may differ per
    lane per step).

    ``capacity`` counts run rows per document; ``order_capacity`` rows
    of by-order table per document (0 = fit this stream: max per-lane
    total orders, +lmax headroom).  ``init`` is a prior result's
    ``state()`` 5-tuple — the streaming warm start; None = empty docs.
    ``rkl`` overrides the rank table (i32[OCAP, B]; pass the host-
    accumulated full table when chunk-chaining so earlier chunks' ranks
    stay visible); None = this stream's prefill.  Remote deletes of any
    length apply in one step (the one-pass interval delete needs no
    dmax pre-chunking).
    """
    kinds = np.asarray(ops.kind)
    _require(kinds.ndim == 2, "rle_lanes_mixed takes stacked per-doc "
             "streams ([S, B] columns; see batch.stack_ops)")
    S, B = kinds.shape
    _require(capacity >= 8, "capacity must hold a few runs")
    s_pad = max(((S + chunk - 1) // chunk) * chunk, chunk)

    adv = np.asarray(ops.order_advance, dtype=np.int64).sum(axis=0)
    base = 0
    if init is not None and init[3] is not None:
        base = init[3].shape[0]
    ocap = order_capacity or max(
        ((int(adv.max() + ops.lmax) + base + 7) // 8) * 8, 8)
    _require(ocap % 8 == 0, "order_capacity must be a multiple of 8")

    def staged_col(get):
        a = np.asarray(get(ops), dtype=np.uint32).view(np.int32)
        return jnp.asarray(np.pad(a, ((0, s_pad - S), (0, 0))))

    staged = tuple(staged_col(g) for g in (
        lambda o: o.kind, lambda o: o.pos, lambda o: o.del_len,
        lambda o: o.del_target, lambda o: o.origin_left,
        lambda o: o.origin_right, lambda o: o.rank, lambda o: o.ins_len,
        lambda o: o.ins_order_start))

    olld, orld, rkl0 = lane_tables(ops, ocap)
    if rkl is None:
        rkl = rkl0
    else:
        rkl = np.asarray(rkl, np.int32)
        _require(rkl.shape == (ocap, B),
                 f"rkl shape {rkl.shape} != ({ocap}, {B})")

    if init is None:
        init = (jnp.zeros((capacity, B), jnp.int32),
                jnp.zeros((capacity, B), jnp.int32),
                jnp.zeros((1, B), jnp.int32),
                jnp.full((ocap, B), TAB_UNKNOWN, jnp.int32),
                jnp.full((ocap, B), TAB_UNKNOWN, jnp.int32))
    else:
        init = _grow_state(init, capacity, ocap, B)

    # Shared live prefix for the local branches: sound only when no
    # lane deletes AND inserts in the same step (a compiled replace
    # patch), and worth it only when steps firing BOTH local branches
    # outnumber steps firing neither — a remote-heavy stream with one
    # stray local op must not pay the hoist on every step (review r5).
    kn, dn, iln = (np.asarray(ops.kind), np.asarray(ops.del_len),
                   np.asarray(ops.ins_len))
    ld = (kn == KIND_LOCAL) & (dn > 0)
    li = (kn == KIND_LOCAL) & (iln > 0)
    shared_cum = (not bool(np.any(ld & li))
                  and _shared_cum_gate(ld.any(axis=1), li.any(axis=1),
                                       s_pad))
    jitted = _build_call(s_pad, B, capacity, ocap, chunk,
                         interpret, lane_tile, shared_cum)
    deltas = (jnp.asarray(olld), jnp.asarray(orld), jnp.asarray(rkl))

    def run(state=None) -> LanesMixedResult:
        ini = init if state is None else _grow_state(
            state, capacity, ocap, B)
        ol, orr, ordp, lenp, rows, oll, orl, err = jitted(
            *staged, *ini, *deltas)
        return LanesMixedResult(
            ordp=ordp, lenp=lenp, rows=rows, ol=ol[:S], orr=orr[:S],
            err=err, batch=B, oll=oll, orl=orl)

    return run


def _grow_state(state, capacity: int, ocap: int, B: int):
    """Pad a prior chunk's state 5-tuple up to this chunk's row/order
    capacities (rows pack at the front; tables are order-indexed) —
    streaming chunks may GROW both as documents accumulate."""
    from .rle_lanes import _grow_planes

    o0, l0, r0 = _grow_planes(state[:3], capacity, B)
    return (o0, l0, r0,
            _grow_table(state[3], ocap, B),
            _grow_table(state[4], ocap, B))


def _grow_table(t, ocap: int, B: int):
    """Pad a prior chunk's [ocap_old, B] table up to this chunk's ocap
    with the unknown sentinel (order spaces only grow)."""
    t = jnp.asarray(t, jnp.int32)
    _require(t.shape[0] <= ocap and t.shape[1] == B,
             f"table state shape {t.shape} incompatible with "
             f"({ocap}, {B})")
    if t.shape[0] == ocap:
        return t
    pad = jnp.full((ocap - t.shape[0], B), TAB_UNKNOWN, jnp.int32)
    return jnp.concatenate([t, pad], axis=0)


def replay_lanes_mixed(ops: OpTensors, capacity: int,
                       **kw) -> LanesMixedResult:
    """One-shot convenience wrapper over ``make_replayer_lanes_mixed``."""
    return make_replayer_lanes_mixed(ops, capacity, **kw)()

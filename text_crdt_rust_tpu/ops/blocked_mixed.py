"""Mixed-stream blocked Pallas engine: remote ops (hot path #2) in-kernel.

``ops.blocked`` replays pure local-edit streams. This engine extends the
same VMEM block layout to the full op surface — KIND_LOCAL,
KIND_REMOTE_INS (YATA integrate, `doc.rs:167-234`), KIND_REMOTE_DEL
(order-range tombstoning, `doc.rs:295-340`) — so an N-peer remote txn
stream (the `BASELINE.json` config-4 concurrent-insert storm) replays on
device in ONE kernel. The pieces the remote paths add:

- **order -> block index** (``ordblk``): the SpaceIndex analog
  (`split_list/mod.rs:440`, device twin of the `markers.rs:8` leaf
  pointers). Maintained O(1) per insert (a run's orders are contiguous);
  a rebalance moves rows between blocks and deliberately leaves the index
  stale — lookups verify against the block and fall back to one
  vectorized full-state search, then self-heal the entry. Amortized: the
  fallback costs one O(capacity) compare, the same work class as a single
  flat-engine step, and only fires on post-rebalance first touches.
- **by-order origin/rank tables** in VMEM (``ol/or/rank``), prefilled
  host-side (`batch.prefill_logs` values, packed 128 orders per row);
  local inserts write the origins they discover at apply time, exactly
  like the flat engine's log writes. The YATA scan reads these tables.
- **remote insert**: cursor_after(origin_left) via the index, then the
  reference's conflict scan as a ``lax.while_loop`` over raw positions
  (zero iterations unless same-origin concurrent items exist,
  `doc.rs:192-194`), then the shared splice.
- **remote delete**: a bitmask walk over the (<= dmax-long) target order
  run — each iteration resolves one not-yet-flipped order to its block,
  flips EVERY in-range row in that block at once, and clears their bits;
  already-deleted rows stay deleted (idempotent concurrent deletes,
  `double_delete.rs:6-9`; excess counting stays host-side per SURVEY).

Same lane batching as ``ops.blocked`` (all docs replay one shared
stream), same result type, same ``blocked_to_flat`` conversion.
"""
from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .batch import (
    KIND_LOCAL,
    KIND_REMOTE_DEL,
    KIND_REMOTE_INS,
    OpTensors,
    prefill_logs,
    require_unfused,
)
from .blocked import (
    BlockedResult,
    _BlockOps,
    _cumsum_rows,
    _lane_scalar,
    _require,
    _shift_rows,
)
from .span_arrays import make_flat_doc

LANES = 128  # orders per by-order table row


def _mixed_kernel(
    kind_ref, pos_ref, dlen_ref, dtgt_ref, olop_ref, orop_ref, rk_ref,
    ilen_ref, start_ref,                        # [CHUNK] SMEM op columns
    oll_in, orl_in, rkl_in,                     # [OT, 128] by-order tables
    ol_ref, or_ref,                             # [CHUNK, B] outputs
    sig_out_ref, rows_out_ref, err_ref,         # final state outputs
    sig, rws, liv, tmp, ordblk, oll, orl,       # VMEM scratch
    *, K: int, NB: int, CHUNK: int, LMAX: int, DMAX: int, OT: int,
):
    B = sig.shape[1]
    CAP = K * NB
    i = pl.program_id(0)
    last = pl.num_programs(0) - 1
    ops_ = _BlockOps(sig, rws, liv, tmp, err_ref, K=K, NB=NB, LMAX=LMAX)
    idx_nb, idx_k = ops_.idx_nb, ops_.idx_k
    idx_cap = lax.broadcasted_iota(jnp.int32, (CAP, B), 0)
    lane = lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    lane2 = lax.broadcasted_iota(jnp.int32, (2, LANES), 1)
    row2 = lax.broadcasted_iota(jnp.int32, (2, LANES), 0)
    root_i = jnp.int32(-1)  # ROOT_ORDER as i32

    ol_ref[:] = jnp.zeros_like(ol_ref)
    or_ref[:] = jnp.zeros_like(or_ref)

    @pl.when(i == 0)
    def _init():
        sig[:] = jnp.zeros_like(sig)
        rws[:] = jnp.zeros_like(rws)
        liv[:] = jnp.zeros_like(liv)
        err_ref[:] = jnp.zeros_like(err_ref)
        ordblk[:] = jnp.zeros_like(ordblk)
        oll[:] = oll_in[:]
        orl[:] = orl_in[:]

    # ---- by-order tables (order o lives at [o // 128, o % 128]) ---------

    def tab_read(tab, o):
        r = tab[pl.ds(o // LANES, 1), :]
        return jnp.sum(jnp.where(lane == o % LANES, r, 0))

    def tab_write(tab, o, v):
        r = tab[pl.ds(o // LANES, 1), :]
        tab[pl.ds(o // LANES, 1), :] = jnp.where(lane == o % LANES, v, r)

    def tab_write_run(tab, start, run_len, v):
        """tab[start : start+run_len] = v; run_len <= LMAX <= 128, so a
        2-row window always covers it (tables have a spare tail row)."""
        r0 = start // LANES
        w = tab[pl.ds(r0, 2), :]
        g = row2 * LANES + lane2 + r0 * LANES
        hit = (g >= start) & (g < start + run_len)
        tab[pl.ds(r0, 2), :] = jnp.where(hit, v, w)

    # ---- position plumbing ---------------------------------------------

    def block_of_raw(c):
        """Smallest block holding raw position c (c <= total raw);
        clamped so an end-of-document cursor maps to the last block."""
        cumraw = _cumsum_rows(jnp.where(idx_nb < NB, rws[:], 0))
        hits = (cumraw <= c) & (idx_nb < NB)
        return jnp.minimum(
            jnp.max(jnp.sum(hits.astype(jnp.int32), axis=0)), NB - 1)

    def item_at_raw(c):
        """Signed row value at raw position c (c < total raw)."""
        b = block_of_raw(c)
        row = c - ops_.raw_before_block(b)
        return _lane_scalar(jnp.where(idx_k == row, sig[pl.ds(b * K, K), :],
                                      0))

    def find_in_block(b, o):
        """(found, row) of order o inside block b."""
        blk = sig[pl.ds(b * K, K), :]
        hit = (blk == o + 1) | (blk == -(o + 1))
        found = _lane_scalar(hit.astype(jnp.int32)) > 0
        row = jnp.max(jnp.min(jnp.where(hit, idx_k, K), axis=0))
        return found, row

    def locate_order(o):
        """(block, row) of the item with order o. ordblk is a HINT — a
        rebalance leaves it stale; verify, fall back to one vectorized
        full-state search, and self-heal the entry."""
        bh = jnp.clip(tab_read(ordblk, o), 0, NB - 1)
        f, row = find_in_block(bh, o)

        def fallback():
            hit = (sig[:] == o + 1) | (sig[:] == -(o + 1))
            g = jnp.max(jnp.min(jnp.where(hit, idx_cap, CAP), axis=0))
            ok = _lane_scalar(hit.astype(jnp.int32)) > 0

            @pl.when(~ok)
            def _missing():
                err_ref[2:3, :] = jnp.ones((1, B), jnp.int32)

            return g // K, g % K

        b, row = lax.cond(f, lambda: (bh, row), fallback)
        tab_write(ordblk, o, b)
        return b, row

    def pos_of_order(o):
        b, row = locate_order(o)
        return ops_.raw_before_block(b) + row

    def cursor_after(o):
        return jnp.where(o == root_i, 0, pos_of_order(o) + 1)

    # ---- shared splice (`mutations.rs:17-179` analog) -------------------
    # Rebalances (ops_.rebalance) leave ordblk stale for every moved row;
    # locate_order self-heals on the next touch.

    def splice_at(b, c, k, il, st, left, right):
        """Insert the run (orders st..st+il) at row c of block b, record
        origins, and maintain the order index + origin tables."""
        shifted = _shift_rows(sig[pl.ds(b * K, K), :], il, LMAX)
        new_vals = st + (idx_k - c) + 1
        blk = sig[pl.ds(b * K, K), :]
        nblk = jnp.where(idx_k < c, blk,
                         jnp.where(idx_k < c + il, new_vals, shifted))
        sig[pl.ds(b * K, K), :] = nblk
        rws[pl.ds(b, 1), :] = rws[pl.ds(b, 1), :] + il
        liv[pl.ds(b, 1), :] = liv[pl.ds(b, 1), :] + il

        tab_write_run(ordblk, st, il, b)
        tab_write(oll, st, left)
        tab_write_run(orl, st, il, right)

        ol_ref[pl.ds(k, 1), :] = jnp.broadcast_to(left.astype(jnp.uint32),
                                                  (1, B))
        or_ref[pl.ds(k, 1), :] = jnp.broadcast_to(right.astype(jnp.uint32),
                                                  (1, B))

    # ---- local ops (shared _BlockOps, + index/table upkeep) -------------

    def do_local_insert(k, p, il, st):
        _, r0 = ops_.local_insert_block(p)

        @pl.when(r0 + il > K)
        def _rb():
            ops_.rebalance()

        b, c, r0, left_signed, succ_signed = ops_.local_insert_target(p)
        left = jnp.where(p == 0, root_i, jnp.abs(left_signed) - 1)
        right = jnp.where(succ_signed == 0, root_i,
                          jnp.abs(succ_signed) - 1)
        splice_at(b, c, k, il, st, left, right)

    # ---- remote insert (`doc.rs:274-293` -> integrate) ------------------

    def integrate_cursor(my_rank, o_left, o_right):
        """The YATA conflict scan (`doc.rs:183-222`), pinned-scan_start
        rule (see tests/test_integrate_divergence.py)."""
        cursor0 = cursor_after(o_left)
        left_cursor = cursor0
        n = _lane_scalar(jnp.where(idx_nb < NB, rws[:], 0))

        def cond(state):
            cursor, scanning, scan_start, done = state
            return ~done & (cursor < n)

        def body(state):
            cursor, scanning, scan_start, done = state
            other_order = jnp.abs(item_at_raw(cursor)) - 1
            other_left = tab_read(oll, other_order)
            other_right = tab_read(orl, other_order)
            other_rank = tab_read(rkl_in, other_order)
            olc = cursor_after(other_left)
            brk = (other_order == o_right) | (olc < left_cursor)
            eq = ~brk & (olc == left_cursor)
            gt = my_rank > other_rank
            brk = brk | (eq & ~gt & (o_right == other_right))
            starts_scan = eq & ~gt & (o_right != other_right)
            new_scan_start = jnp.where(starts_scan & ~scanning, cursor,
                                       scan_start)
            new_scanning = jnp.where(
                eq, jnp.where(gt, False, jnp.where(
                    o_right == other_right, scanning, True)),
                scanning,
            )
            return (jnp.where(brk, cursor, cursor + 1), new_scanning,
                    new_scan_start, brk)

        init = (cursor0, jnp.asarray(False), cursor0, jnp.asarray(False))
        cursor, scanning, scan_start, _ = lax.while_loop(cond, body, init)
        return jnp.where(scanning, scan_start, cursor)

    def do_remote_insert(k, my_rank, o_left, o_right, il, st):
        raw_cursor = integrate_cursor(my_rank, o_left, o_right)

        def target():
            b = block_of_raw(raw_cursor)
            r0 = _lane_scalar(jnp.where(idx_nb == b, rws[:], 0))
            return b, r0

        b, r0 = target()

        @pl.when(r0 + il > K)
        def _rb():
            ops_.rebalance()  # raw_cursor is invariant under a rebalance

        b, r0 = target()
        c = raw_cursor - ops_.raw_before_block(b)
        splice_at(b, c, k, il, st, o_left, o_right)

    # ---- remote delete (`doc.rs:295-340`) -------------------------------

    def do_remote_delete(t, dlen):
        """Tombstone orders [t, t+dlen). A bit in `mask` = a target order
        not yet accounted for; each iteration resolves the lowest one to
        its block and retires every in-range row found there."""
        full = jnp.left_shift(jnp.int32(1), dlen) - 1

        def body(carry):
            mask, iters = carry
            low = mask & (-mask)
            # floor(log2(low)) via scalar shifts — Mosaic has no scalar
            # population-count (it rejected popcount(low - 1) here).
            v = low
            k0 = jnp.int32(0)
            for sh in (16, 8, 4, 2, 1):
                ge = (v >> sh) != 0
                k0 = k0 + jnp.where(ge, sh, 0)
                v = jnp.where(ge, v >> sh, v)
            b, _row = locate_order(t + k0)
            blk = sig[pl.ds(b * K, K), :]
            occ = blk != 0
            orders = jnp.abs(blk) - 1
            diff = orders - t
            in_range = occ & (diff >= 0) & (diff < dlen)
            flip = in_range & (blk > 0)
            sig[pl.ds(b * K, K), :] = jnp.where(flip, -blk, blk)
            liv[pl.ds(b, 1), :] = (liv[pl.ds(b, 1), :]
                                   - jnp.sum(flip.astype(jnp.int32), axis=0,
                                             keepdims=True))
            bits = _lane_scalar(jnp.where(
                in_range,
                jnp.left_shift(jnp.int32(1),
                               jnp.clip(diff, 0, 30)), 0))
            return mask & ~bits, iters + 1

        mask, _ = lax.while_loop(
            lambda c: (c[0] != 0) & (c[1] <= DMAX), body, (full, 0))

        @pl.when(mask != 0)
        def _bad():
            err_ref[1:2, :] = jnp.ones((1, B), jnp.int32)

    # ---- dispatch -------------------------------------------------------

    def op_body(k, _):
        kind = kind_ref[k]
        p = pos_ref[k]
        d = dlen_ref[k]
        il = ilen_ref[k]
        st = start_ref[k]

        @pl.when((kind == KIND_LOCAL) & (d > 0))
        def _():
            ops_.local_delete(p, d)

        @pl.when((kind == KIND_LOCAL) & (il > 0))
        def _():
            do_local_insert(k, p, il, st)

        @pl.when((kind == KIND_REMOTE_INS) & (il > 0))
        def _():
            do_remote_insert(k, rk_ref[k], olop_ref[k], orop_ref[k], il, st)

        @pl.when(kind == KIND_REMOTE_DEL)
        def _():
            do_remote_delete(dtgt_ref[k], d)

        return 0

    lax.fori_loop(0, CHUNK, op_body, 0)

    @pl.when(i == last)
    def _flush():
        sig_out_ref[:] = sig[:]
        rows_out_ref[:] = rws[:]


def make_replayer_mixed(
    ops: OpTensors,
    capacity: int,
    batch: int = 128,
    block_k: int = 256,
    chunk: int = 1024,
    interpret: bool = False,
):
    """Stage a mixed local/remote op stream and build a jitted replayer.

    Same contract as ``blocked.make_replayer`` but accepts every op kind.
    Remote delete runs must be pre-chunked to <= 16 targets per step
    (``compile_remote_txns(..., dmax=16)``).
    """
    kinds = np.asarray(ops.kind)
    _require(kinds.ndim == 1, "blocked engine takes one shared stream")
    require_unfused(ops, "the blocked-mixed engine")
    _require(capacity % block_k == 0,
             f"capacity ({capacity}) must be a multiple of block_k "
             f"({block_k})")
    _require(interpret or chunk % 1024 == 0 or (
        jax.default_backend() != "tpu"),
        "chunk must be a multiple of 1024 on TPU")
    NB = capacity // block_k
    _require(NB >= 2, "need at least two blocks (delete window)")
    NBp = max(8, NB)
    lmax = ops.lmax
    _require(block_k > lmax, (
        f"block_k ({block_k}) must exceed the insert chunk width ({lmax})"))
    dlens = np.asarray(ops.del_len)[kinds == KIND_REMOTE_DEL]
    dmax = 16
    _require(dlens.size == 0 or int(dlens.max()) <= dmax, (
        f"remote delete runs must be <= {dmax} targets per step "
        f"(compile with dmax={dmax})"))
    rows_needed = int(np.asarray(ops.ins_len, dtype=np.int64).sum())
    rows_limit = NB * (block_k - lmax)
    _require(rows_needed <= rows_limit, (
        f"stream inserts {rows_needed} rows but {NB} blocks of "
        f"{block_k} hold at most {rows_limit} at the rebalance fill "
        f"limit (K-lmax); raise capacity"))

    # By-order tables: everything the compiler knows (remote origins,
    # within-run chains, ranks), packed 128 orders per row, i32 (ROOT ->
    # -1 by u32 wraparound). One spare tail row for the 2-row run writes.
    total_orders = int(np.asarray(ops.order_advance, dtype=np.int64).sum())
    ocap = max(total_orders + lmax, LANES)
    OT = (ocap + LANES - 1) // LANES + 1
    OT = ((OT + 7) // 8) * 8
    doc0 = prefill_logs(make_flat_doc(8, OT * LANES), ops)

    def table(x):
        return jnp.asarray(
            np.asarray(x, dtype=np.uint32).view(np.int32).reshape(OT, LANES))

    oll0 = table(doc0.ol_log)
    orl0 = table(doc0.or_log)
    rkl0 = table(doc0.rank_log)

    s = ops.num_steps
    s_pad = max(((s + chunk - 1) // chunk) * chunk, chunk)
    pad = ((0, s_pad - s),)

    def padded(a):
        return jnp.asarray(np.pad(
            np.asarray(a, dtype=np.uint32).view(np.int32), pad))

    staged = tuple(padded(c) for c in (
        ops.kind, ops.pos, ops.del_len, ops.del_target, ops.origin_left,
        ops.origin_right, ops.rank, ops.ins_len, ops.ins_order_start))

    jitted = _build_call(s_pad, batch, capacity, block_k, chunk, lmax,
                         dmax, OT, interpret)
    tables = (oll0, orl0, rkl0)

    def run() -> BlockedResult:
        ol, orr, signed, rows, err = jitted(*staged, *tables)
        return BlockedResult(
            signed=signed, rows=rows, ol=ol[:s], orr=orr[:s], err=err,
            block_k=block_k, num_blocks=NB, batch=batch)

    return run


@functools.lru_cache(maxsize=32)
def _build_call(s_pad: int, batch: int, capacity: int, block_k: int,
                chunk: int, lmax: int, dmax: int, OT: int,
                interpret: bool):
    """Shape-keyed cache (the ``rle_lanes._build_call`` pattern):
    same-shape replays share one traced kernel instead of re-tracing a
    fresh ``jax.jit(lambda ...)`` per build."""
    NB = capacity // block_k
    NBp = max(8, NB)

    smem = lambda: pl.BlockSpec(
        (chunk,), lambda i: (i,), memory_space=pltpu.SMEM)

    def whole(shape):
        return pl.BlockSpec(shape, lambda i: tuple(0 for _ in shape),
                            memory_space=pltpu.VMEM)

    call = pl.pallas_call(
        partial(_mixed_kernel, K=block_k, NB=NB, CHUNK=chunk, LMAX=lmax,
                DMAX=dmax, OT=OT),
        grid=(s_pad // chunk,),
        in_specs=[smem() for _ in range(9)] + [
            whole((OT, LANES)), whole((OT, LANES)), whole((OT, LANES))],
        out_specs=[
            pl.BlockSpec((chunk, batch), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((chunk, batch), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            whole((capacity, batch)),
            whole((NBp, batch)),
            whole((8, batch)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s_pad, batch), jnp.uint32),
            jax.ShapeDtypeStruct((s_pad, batch), jnp.uint32),
            jax.ShapeDtypeStruct((capacity, batch), jnp.int32),
            jax.ShapeDtypeStruct((NBp, batch), jnp.int32),
            jax.ShapeDtypeStruct((8, batch), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((capacity, batch), jnp.int32),
            pltpu.VMEM((NBp, batch), jnp.int32),
            pltpu.VMEM((NBp, batch), jnp.int32),
            pltpu.VMEM((capacity + block_k, batch), jnp.int32),
            pltpu.VMEM((OT, LANES), jnp.int32),   # ordblk
            pltpu.VMEM((OT, LANES), jnp.int32),   # ol table
            pltpu.VMEM((OT, LANES), jnp.int32),   # or table
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return jax.jit(lambda *a: call(*a))


def replay_mixed(ops: OpTensors, capacity: int, **kw) -> BlockedResult:
    """One-shot convenience wrapper over ``make_replayer_mixed``."""
    return make_replayer_mixed(ops, capacity, **kw)()

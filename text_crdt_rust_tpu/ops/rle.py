"""Run-length blocked replay engine: RLE spans on device, VMEM-resident.

The round-2 engines stored ONE ROW PER CHARACTER (524,288 rows for the
automerge-paper trace) and applied one per-keystroke op per sequential
step.  This engine is the blueprint's missing core (SURVEY §7 "flat RLE
span arrays"): device state is the run — the same compression the
reference's `YjsSpan` B-tree entries carry (`src/list/span.rs:6-119`,
16 B/span) — and the op stream is RLE-merged (`ops.batch.merge_patches`),
so the whole automerge-paper trace is 10,712 device steps over ~13k rows
instead of 259,778 steps over 524k rows:

- state is two VMEM planes, ``ordp`` = ±(start_order+1) (sign = live /
  tombstone, 0 = empty slot) and ``lenp`` = run char length; a run row
  encodes `span.rs:9-13`'s implicit order chain — char k of a run has
  order ``start+k`` — so splits are index arithmetic (`span.rs:33-45`);
- rows pack into blocks of ``K`` runs; per-block LIVE-CHAR counts play
  the B-tree's subtree sums (`range_tree/mod.rs:85-93`): position→block
  is a masked scan over ≤``NB`` block sums, position→run one in-block
  cumsum — O(NB + K) per op on runs, not characters;
- an insert touches ≤3 rows (split + new run + tail) NO MATTER HOW LONG
  the inserted text is — the per-op cost is independent of ``ins_len``,
  which is what makes the merged stream pay off;
- a FUSED step (``rows_per_step`` W > 1, compiled by
  ``batch.compile_local_patches(fuse_w=W)`` from backwards-contiguous
  insert bursts — the kevin prepend shape the forward coalescer can't
  touch) splices W descending-order runs in ONE shift: W ops' worth of
  work per sequential device step (PERF.md §11);
- a delete flips sign on covered runs and splits at most the two
  boundary runs (`mutations.rs:520-570` semantics, tombstones =
  sign-flip per `span.rs:110-119`);
- blocks never rebalance globally: a full block SPLITS — the top half
  moves to a fresh physical block spliced into a LOGICAL block-order
  table — the device analog of the reference's leaf split
  (`mutations.rs:623-669`), O(K) per split and amortized O(1) per op.
  This removes the O(capacity)-per-overflow pathology that kept the
  round-2 engines off the pure-prepend worst case (`benches/yjs.rs:51-62`);
- documents batch in the lane dimension (identical-stream lanes), and
  divergent doc GROUPS ride a leading grid dimension exactly like
  ``ops.blocked_hbm`` (config-3 ragged corpus shape).

Origins a local insert discovers (`doc.rs:447-453`) are emitted per op:
``origin_left`` of the run head, with the rest of the run chained
implicitly host-side (`span.rs:24-28`); ``origin_right`` is the raw
successor (tombstones NOT skipped, the `doc.rs:452` behavior the other
engines match).  ``rle_to_flat`` expands the run rows to the standard
per-char ``FlatDoc`` so every downstream consumer (sync, checkpoint,
oracle diff) is unchanged.
"""
from __future__ import annotations

import dataclasses
import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..common import ROOT_ORDER
from .batch import (
    KIND_LOCAL,
    OpTensors,
    fused_width_checked,
    merge_fused_origins,
    prefill_logs,
)
from .blocked import _cumsum_rows, _lane_scalar, _require, _shift_rows
from .span_arrays import FlatDoc, I32, U32, make_flat_doc


def _shift_rows_up(x, amount, max_amount: int) -> jax.Array:
    """Rows shifted toward LOWER indices by dynamic ``amount`` (the
    mirror of ``blocked._shift_rows``): out[j] = x[j + amount]."""
    out = x
    n = x.shape[0]
    for b in range(max(max_amount, 1).bit_length()):
        s = (1 << b) % n
        if s:
            out = jnp.where((amount >> b) & 1 != 0,
                            pltpu.roll(out, n - s, axis=0), out)
    return out



def _row_scalar(arr2d, r, idx_k) -> jax.Array:
    """Row ``r`` of a lane-replicated [K, B] value, as one scalar."""
    return jnp.max(jnp.sum(jnp.where(idx_k == r, arr2d, 0), axis=0))


def _locate_run(bo, bl, idx_k, r0, local):
    """Find the run containing live char #``local`` (1-based) in a block:
    returns ``(i_r, o_r, l_r, off)`` — row index, ±(order+1), length, and
    the 1-based char offset within the run. The hit is a live run by
    construction (tombstone rows don't advance the live cumsum)."""
    lv = jnp.where(bo > 0, bl, 0)
    cum = _cumsum_rows(lv)
    i_r = jnp.max(jnp.sum(
        ((cum < local) & (idx_k < r0)).astype(jnp.int32), axis=0))
    o_r = _row_scalar(bo, i_r, idx_k)
    l_r = _row_scalar(bl, i_r, idx_k)
    off = local - (_row_scalar(cum, i_r, idx_k)
                   - _row_scalar(lv, i_r, idx_k))
    return i_r, o_r, l_r, off


def fused_splice_rows(bo, bl, idx, p, i_r, o_r, l_r, off, il, st, w,
                      wmax: int, shift, active=None):
    """THE W-row fused-splice arithmetic, shared by every fused kernel
    (``rle``/``rle_hbm`` via ``_insert_splice``; both ``rle_lanes`` and
    both ``rle_lanes_mixed`` kernels call it directly with their lane
    mask and shift primitive) — the PR-6 review debt: five drifting
    copies of this block, now one.

    ``w`` run rows of stride ``L = il // w`` land in ONE shift — row j
    of the spliced window holds orders ``st + il - (j+1)*L`` (patch
    order DESCENDS in document order: a same-position burst prepends
    each patch before the previous one).  ``w == 1`` reduces to the
    plain splice exactly (one row, order ``st``, length ``il``).  The
    in-kernel append-merge stays w==1-only: a fused burst's first patch
    merging would be un-done by its second patch's split at the same
    boundary, so skipping it keeps the expanded state bit-identical to
    the unfused stream (see the compile-side proof note).

    ``idx`` is the caller's row-index plane, ``shift`` its row-shift
    primitive (``_shift_rows`` for [K, 1] grids, the lanes kernels'
    ``_vshift`` for [K, B] planes), ``wmax`` the static shift bound,
    and ``active`` an optional lane mask (None = every lane active —
    the single-doc kernels).  Returns ``(no, nl, amt, mrg, is_split,
    lrun)``: new order/length planes, rows added, path flags, and the
    fused stride (the mixed kernels' by-order table writes need it).
    """
    lrun = il // jnp.maximum(w, 1)
    mrg = (w == 1) & (p > 0) & (off == l_r) & ((st + 1) == (o_r + l_r))
    is_split = (p > 0) & (off < l_r)
    if active is None:
        dead = mrg
    else:
        mrg = active & mrg
        is_split = active & is_split
        dead = jnp.logical_not(active) | mrg
    ins_at = jnp.where(p == 0, 0, i_r + 1)
    amt = jnp.where(dead, 0, w + is_split.astype(jnp.int32))
    so = shift(bo, amt, wmax + 1)
    sl = shift(bl, amt, wmax + 1)
    no = jnp.where(idx < ins_at, bo, so)
    nl = jnp.where(idx < ins_at, bl, sl)
    nl = jnp.where(is_split & (idx == i_r), off, nl)
    new_run = (idx >= ins_at) & (idx < ins_at + w) & \
        jnp.logical_not(mrg)
    if active is not None:
        new_run = active & new_run
    no = jnp.where(new_run, st + il - (idx - ins_at + 1) * lrun + 1, no)
    nl = jnp.where(new_run, lrun, nl)
    tail = is_split & (idx == ins_at + w)
    no = jnp.where(tail, o_r + off, no)
    nl = jnp.where(tail, l_r - off, nl)
    nl = jnp.where(mrg & (idx == i_r), l_r + il, nl)
    return no, nl, amt, mrg, is_split, lrun


def _insert_splice(bo, bl, idx_k, p, i_r, o_r, l_r, off, il, st,
                   w=None, wmax: int = 1):
    """In-register insert splice (`mutations.rs:17-179`): ≤3 touched rows
    regardless of ``il``. Returns ``(no, nl, amt, mrg, is_split)`` —
    the new block planes, rows added, and which path was taken.

    ``w``/``wmax`` extend the splice to FUSED multi-row steps
    (``batch.compile_local_patches`` ``fuse_w``); the arithmetic lives
    in ``fused_splice_rows`` (shared with the lanes kernels).

    The in-place merge path is device-state compaction only (an
    order-contiguous live extension of run ``i_r``); YjsSpan merge
    predicates live host-side — this run is raw doc order.
    """
    if w is None:
        w = jnp.int32(1)
    no, nl, amt, mrg, is_split, _lrun = fused_splice_rows(
        bo, bl, idx_k, p, i_r, o_r, l_r, off, il, st, w, wmax,
        _shift_rows)
    return no, nl, amt, mrg, is_split


def _split_piece_aux(aux, idx_k, i_p, amt, w1, w2, so0, s_off, e_off,
                     has_head):
    """Aux-plane transform shared by every 3-way run split ([head?]
    [mid] [tail?] — delete boundaries and remote-delete endpoint
    retires): pieces after the first chain to their own predecessor
    char (`span.rs:24-28` implicit chain survives splits), their
    origin-right is poisoned with -2 (unknowable from the head:
    merge-appended chars keep their own; ops/rle_mixed.py falls back
    to the serial walk if such a piece ever classifies as a sibling),
    and rank is inherited (runs are single-agent).  ``so0`` is the
    run's 0-based start order; pieces begin at ``so0 + s_off`` /
    ``so0 + e_off``.  Returns the three transformed planes."""
    olp_b, orp_b, rkp_b = aux
    t_rk = _row_scalar(rkp_b, i_p, idx_k)
    sent = jnp.int32(-2)
    p1_ol = jnp.where(has_head, so0 + s_off - 1, so0 + e_off - 1)
    p2_ol = so0 + e_off - 1
    out = []
    for a, v1, v2 in ((olp_b, p1_ol, p2_ol), (orp_b, sent, sent),
                      (rkp_b, t_rk, t_rk)):
        na = jnp.where(idx_k <= i_p, a, _shift_rows(a, amt, 2))
        na = jnp.where(w1, v1, na)
        na = jnp.where(w2, v2, na)
        out.append(na)
    return tuple(out)


def _delete_block_math(bo, bl, idx_k, K, base, p, rem, aux=None):
    """One delete iteration over one block (`mutations.rs:520-570`): flip
    fully-covered runs, split at most the two boundary runs. Returns
    ``(no, nl, added_rows, covered)``; caller walks blocks while
    ``covered`` hasn't reached ``rem``.

    ``aux`` (optional) is a tuple of per-run head-metadata planes
    (origin-left, origin-right, rank — the ``rle_mixed`` YATA fast-path
    cache); split pieces inherit their run's origin-right/rank, and a
    non-first piece's head chains to its own predecessor char (the
    `span.rs:24-28` implicit chain survives splits). Returns the
    transformed aux as a 5th element when given."""

    def apply_partial(active, i_p, cs, ce, bo, bl, aux):
        o = _row_scalar(bo, i_p, idx_k)
        ln = _row_scalar(bl, i_p, idx_k)
        cs_i = _row_scalar(cs, i_p, idx_k)
        ce_i = _row_scalar(ce, i_p, idx_k)
        cov_i = ce_i - cs_i
        has_head = (cs_i > 0) & active
        has_tail = (ce_i < ln) & active
        amt = has_head.astype(jnp.int32) + has_tail.astype(jnp.int32)
        so = _shift_rows(bo, amt, 2)
        sl = _shift_rows(bl, amt, 2)
        no = jnp.where(idx_k <= i_p, bo, so)
        nl = jnp.where(idx_k <= i_p, bl, sl)
        # Part layout: [head?] [tombstone mid] [tail?]; the tombstone
        # start encodes as -(o + cs) per the ±(order+1) convention.
        p0o = jnp.where(has_head, o, -(o + cs_i))
        p0l = jnp.where(has_head, cs_i, cov_i)
        p1o = jnp.where(has_head, -(o + cs_i), o + ce_i)
        p1l = jnp.where(has_head, cov_i, ln - ce_i)
        w0 = active & (idx_k == i_p)
        no = jnp.where(w0, p0o, no)
        nl = jnp.where(w0, p0l, nl)
        w1 = active & (idx_k == i_p + 1) & (amt >= 1)
        no = jnp.where(w1, p1o, no)
        nl = jnp.where(w1, p1l, nl)
        w2 = active & (idx_k == i_p + 2) & (amt == 2)
        no = jnp.where(w2, o + ce_i, no)
        nl = jnp.where(w2, ln - ce_i, nl)
        if aux is None:
            return no, nl, amt, None
        # Partial covers only reach LIVE runs: o > 0, start order o-1.
        # Piece 0 keeps the original head (its aux row is untouched).
        return no, nl, amt, _split_piece_aux(
            aux, idx_k, i_p, amt, w1, w2, o - 1, cs_i, ce_i, has_head)

    lv = jnp.where(bo > 0, bl, 0)
    cum = _cumsum_rows(lv)
    before = base + cum - lv
    cs = jnp.clip(p - before, 0, lv)
    ce = jnp.clip(p + rem - before, 0, lv)
    cov = ce - cs
    tot = jnp.max(jnp.sum(cov, axis=0))
    full = (cov > 0) & (cov == bl)
    part = (cov > 0) & jnp.logical_not(full)
    npart = jnp.max(jnp.sum(part.astype(jnp.int32), axis=0))
    i1 = jnp.max(jnp.min(jnp.where(part, idx_k, K), axis=0))
    i2 = jnp.max(jnp.max(jnp.where(part, idx_k, -1), axis=0))

    bo = jnp.where(full, -bo, bo)
    # Higher-index boundary first so i1's row index stays valid.
    bo, bl, a2, aux = apply_partial(npart >= 1, i2, cs, ce, bo, bl, aux)
    bo, bl, a1, aux = apply_partial(npart == 2, i1, cs, ce, bo, bl, aux)
    if aux is None:
        return bo, bl, a1 + a2, tot
    return bo, bl, a1 + a2, tot, aux


def _rle_kernel(
    pos_ref, dlen_ref, ilen_ref, start_ref,     # [CHUNK] SMEM op columns
    w_ref,                                      # [CHUNK] SMEM rows_per_step
    ol_ref, or_ref,                             # [1,CHUNK,B] VMEM outputs
    ordp, lenp,                                 # [CAP,B] state planes (OUT
                                                #   blocks used as working
                                                #   state — halves VMEM)
    blk_out, rows_out, meta_out, err_ref,       # tables + flags
    blkord, rws, liv, cumliv, meta,             # persistent scratch
    *, K: int, NB: int, NBL: int, CHUNK: int, WMAX: int,
):
    B = ordp.shape[1]
    g = pl.program_id(0)
    i = pl.program_id(1)
    last = pl.num_programs(1) - 1
    idx_k = lax.broadcasted_iota(jnp.int32, (K, B), 0)
    idx_l = lax.broadcasted_iota(jnp.int32, rws.shape, 0)
    root_u = jnp.uint32(ROOT_ORDER)

    ol_ref[:] = jnp.zeros_like(ol_ref)
    or_ref[:] = jnp.zeros_like(or_ref)

    @pl.when((g == 0) & (i == 0))
    def _init_err():
        err_ref[:] = jnp.zeros_like(err_ref)

    @pl.when(i == 0)
    def _init():
        # Fresh group: empty document, one empty block in logical slot 0.
        ordp[:] = jnp.zeros_like(ordp)
        lenp[:] = jnp.zeros_like(lenp)
        blkord[:] = jnp.zeros_like(blkord)
        rws[:] = jnp.zeros_like(rws)
        liv[:] = jnp.zeros_like(liv)
        cumliv[:] = jnp.zeros_like(cumliv)
        meta[0] = 1  # blocks in use (logical slots == physical blocks)

    def slot_scalar(tbl, l):
        return _lane_scalar(jnp.where(idx_l == l, tbl[:], 0))

    def live_before_slot(l):
        return slot_scalar(cumliv, l) - slot_scalar(liv, l)

    def slot_of_live_rank(rank1):
        """Smallest logical slot whose cumulative live-char count reaches
        ``rank1`` (the B-tree descent `root.rs:54-88` over block sums).

        ``cumliv`` is the inclusive live prefix per slot, maintained
        INCREMENTALLY (one masked add per op; splits shift it with the
        other tables) instead of recomputed by an 8-roll cumsum on every
        descent — the remaining sequencing-cost lever PERF.md §6 named.
        Slots >= nlog may hold stale values; the mask excludes them."""
        nlog = meta[0]
        hit = (cumliv[:] < rank1) & (idx_l < nlog)
        return jnp.minimum(
            jnp.max(jnp.sum(hit.astype(jnp.int32), axis=0)), nlog - 1)

    def split(l):
        """Leaf split (`mutations.rs:623-669`): move the top half of slot
        ``l``'s rows to a fresh physical block and splice it into the
        logical order at ``l+1``. O(K); never a global rebalance.

        At table capacity the split is a NO-OP with the error flag
        raised (advisor r3: proceeding overwrote an in-use physical
        block and left duplicate blkord entries — silent corruption for
        raw-state readers that skip ``check()``)."""
        nlog = meta[0]

        @pl.when(nlog >= NB)
        def _cap():
            err_ref[0:1, :] = jnp.ones((1, B), jnp.int32)

        @pl.when(nlog < NB)
        def _do():
            b = slot_scalar(blkord, l)
            r = slot_scalar(rws, l)
            keep = r // 2
            mv = r - keep
            nb = nlog  # fresh physical block id
            bo = ordp[pl.ds(b * K, K), :]
            bl = lenp[pl.ds(b * K, K), :]
            liv_hi = _lane_scalar(jnp.where(
                (idx_k >= keep) & (idx_k < r) & (bo > 0), bl, 0))
            liv_lo = slot_scalar(liv, l) - liv_hi

            up_o = _shift_rows_up(bo, keep, K)
            up_l = _shift_rows_up(bl, keep, K)
            new_mask = idx_k < mv
            ordp[pl.ds(nb * K, K), :] = jnp.where(new_mask, up_o, 0)
            lenp[pl.ds(nb * K, K), :] = jnp.where(new_mask, up_l, 0)
            keep_mask = idx_k < keep
            ordp[pl.ds(b * K, K), :] = jnp.where(keep_mask, bo, 0)
            lenp[pl.ds(b * K, K), :] = jnp.where(keep_mask, bl, 0)

            # Splice the new block into the logical order at slot l+1.
            # cumliv shifts with the tables: slots > l take the old
            # predecessor prefix (slot l+1 inherits old c_l, which IS
            # its inclusive prefix after the split); slot l's inclusive
            # prefix loses the moved-out top half.
            for tbl in (blkord, rws, liv, cumliv):
                shifted = _shift_rows(tbl[:], 1, 1)
                tbl[:] = jnp.where(idx_l <= l, tbl[:], shifted)
            rws[pl.ds(l, 1), :] = jnp.broadcast_to(keep, (1, B))
            liv[pl.ds(l, 1), :] = jnp.broadcast_to(liv_lo, (1, B))
            cumliv[pl.ds(l, 1), :] = cumliv[pl.ds(l, 1), :] - liv_hi
            blkord[pl.ds(l + 1, 1), :] = jnp.broadcast_to(nb, (1, B))
            rws[pl.ds(l + 1, 1), :] = jnp.broadcast_to(mv, (1, B))
            liv[pl.ds(l + 1, 1), :] = jnp.broadcast_to(liv_hi, (1, B))
            meta[0] = nlog + 1

    def find_insert_slot(p):
        l = jnp.where(p == 0, 0, slot_of_live_rank(p))
        return l, slot_scalar(rws, l)

    def do_insert(k, p, il, st, w):
        """Insert an ``il``-char run (or, fused, ``w`` runs of stride
        ``il//w``) after live rank ``p`` (`mutations.rs:17-179`):
        ≤ w+2 touched rows regardless of ``il``.  One split always
        makes room: the builder enforces WMAX <= K//2 - 1, so a
        freshly-split slot (≤ ⌈K/2⌉ rows) fits w+1 more."""
        l, r0 = find_insert_slot(p)

        @pl.when(r0 + w + 1 > K)
        def _():
            split(l)

        l, r0 = find_insert_slot(p)
        b = slot_scalar(blkord, l)
        base = live_before_slot(l)
        local = p - base
        bo = ordp[pl.ds(b * K, K), :]
        bl = lenp[pl.ds(b * K, K), :]
        i_r, o_r, l_r, off = _locate_run(bo, bl, idx_k, r0, local)
        no, nl, amt, _mrg, is_split = _insert_splice(
            bo, bl, idx_k, p, i_r, o_r, l_r, off, il, st, w, WMAX)

        left = jnp.where(p == 0, root_u,
                         ((o_r - 1) + (off - 1)).astype(jnp.uint32))
        # Raw successor (`doc.rs:452`: tombstones not skipped); read from
        # the PRE-splice block.
        nxt_in_blk = _row_scalar(bo, i_r + 1, idx_k)  # 0 past the last row
        nlog = meta[0]
        b2 = slot_scalar(blkord, jnp.minimum(l + 1, NBL - 1))
        nxt_slot_o = jnp.max(jnp.sum(jnp.where(
            idx_k == 0, ordp[pl.ds(b2 * K, K), :], 0), axis=0))
        succ_signed = jnp.where(
            i_r + 1 < r0, nxt_in_blk,
            jnp.where(l + 1 < nlog, nxt_slot_o, 0))
        first_o = _row_scalar(bo, 0, idx_k)  # p == 0: the raw doc head
        succ_p0 = jnp.where(r0 > 0, first_o, 0)
        succ = jnp.where(p == 0, succ_p0,
                         jnp.where(is_split, o_r + off, succ_signed))
        right = jnp.where(succ == 0, root_u,
                          (jnp.abs(succ) - 1).astype(jnp.uint32))

        ordp[pl.ds(b * K, K), :] = no
        lenp[pl.ds(b * K, K), :] = nl
        rws[pl.ds(l, 1), :] = rws[pl.ds(l, 1), :] + amt
        liv[pl.ds(l, 1), :] = liv[pl.ds(l, 1), :] + il
        cumliv[:] = jnp.where(idx_l >= l, cumliv[:] + il, cumliv[:])

        ol_ref[:, pl.ds(k, 1), :] = jnp.broadcast_to(left, (1, 1, B))
        or_ref[:, pl.ds(k, 1), :] = jnp.broadcast_to(right, (1, 1, B))

    def do_delete(p, d):
        """Tombstone ``d`` live chars after live rank ``p``: per block,
        flip fully-covered runs and split at most the two boundary runs
        (`mutations.rs:520-570`; `doc.rs:311-334` fragmentation)."""

        def body(carry):
            rem, iters = carry
            l = slot_of_live_rank(p + 1)

            @pl.when(slot_scalar(rws, l) + 2 > K)
            def _():
                split(l)

            l = slot_of_live_rank(p + 1)
            b = slot_scalar(blkord, l)
            base = live_before_slot(l)
            bo = ordp[pl.ds(b * K, K), :]
            bl = lenp[pl.ds(b * K, K), :]
            no, nl, added, tot = _delete_block_math(
                bo, bl, idx_k, K, base, p, rem)
            ordp[pl.ds(b * K, K), :] = no
            lenp[pl.ds(b * K, K), :] = nl
            rws[pl.ds(l, 1), :] = rws[pl.ds(l, 1), :] + added
            liv[pl.ds(l, 1), :] = liv[pl.ds(l, 1), :] - tot
            cumliv[:] = jnp.where(idx_l >= l, cumliv[:] - tot, cumliv[:])
            return rem - tot, iters + 1

        # Each iteration clears one block's covered span; > 2*NBL
        # iterations means the delete ran off the document.
        rem, _ = lax.while_loop(
            lambda c: (c[0] > 0) & (c[1] <= 2 * NBL), body, (d, 0))

        @pl.when(rem > 0)
        def _bad_delete():
            err_ref[1:2, :] = jnp.ones((1, B), jnp.int32)

    def op_body(k, _):
        p = pos_ref[k]
        d = dlen_ref[k]
        il = ilen_ref[k]
        st = start_ref[k]
        w = jnp.maximum(w_ref[k], 1)  # no-op pad rows carry 0

        @pl.when(d > 0)
        def _():
            do_delete(p, d)

        @pl.when(il > 0)
        def _():
            do_insert(k, p, il, st, w)

        return 0

    lax.fori_loop(0, CHUNK, op_body, 0)

    @pl.when(i == last)
    def _flush():
        blk_out[:] = blkord[:][jnp.newaxis]
        rows_out[:] = rws[:][jnp.newaxis]
        row0 = lax.broadcasted_iota(jnp.int32, (1, 8, B), 1) == 0
        meta_out[:] = jnp.where(row0, meta[0], 0)


@dataclasses.dataclass
class RleResult:
    """Device outputs of one RLE replay (one doc group)."""

    ordp: jax.Array     # i32[CAP, B] ±(start_order+1) per run row
    lenp: jax.Array     # i32[CAP, B] run char length
    blkord: jax.Array   # i32[NBLp, B] logical slot -> physical block
    rows: jax.Array     # i32[NBLp, B] occupied rows per logical slot
    meta: jax.Array     # i32[8, B]   row 0: blocks in use
    ol: jax.Array       # u32[S, B]   per-op run-head origin_left
    orr: jax.Array      # u32[S, B]   per-op origin_right
    err: jax.Array      # i32[8, B]   0: block capacity; 1: bad delete
    block_k: int
    num_blocks: int
    batch: int

    def check(self) -> None:
        err = np.asarray(self.err)
        if err[0].max() != 0:
            raise RuntimeError(
                "rle engine out of blocks (every split consumed); raise "
                "capacity")
        if err[1].max() != 0:
            raise RuntimeError(
                "delete ran past the end of the document (invalid op "
                "stream)")


def make_replayer_rle(
    ops,
    capacity: int,
    batch: int = 128,
    block_k: int = 256,
    chunk: int = 1024,
    interpret: bool = False,
):
    """Build a jitted replayer for one local-edit stream (or a SEQUENCE
    of streams — divergent doc groups on a leading grid dimension, the
    ``blocked_hbm`` group contract).

    ``capacity`` counts RUN ROWS, not characters: automerge-paper peaks
    at 13,218 rows (vs 524,288 char rows) — compile the stream with
    ``merge_patches`` first or every keystroke costs a row.
    """
    grouped = isinstance(ops, (list, tuple))
    streams = list(ops) if grouped else [ops]
    G = len(streams)
    _require(G >= 1, "need at least one op stream")
    for st in streams:
        kinds = np.asarray(st.kind)
        _require(kinds.ndim == 1, "rle engine takes per-group shared "
                 "streams (no per-lane batching inside a group)")
        _require(bool((kinds == KIND_LOCAL).all()),
                 "rle engine replays local streams; remote ops -> "
                 "ops.blocked_mixed / ops.flat")
    _require(capacity % block_k == 0,
             f"capacity ({capacity}) must be a multiple of block_k "
             f"({block_k})")
    _require(interpret or chunk % 1024 == 0 or (
        jax.default_backend() != "tpu"),
        "chunk must be a multiple of 1024 on TPU")
    NB = capacity // block_k
    _require(NB >= 1, "need at least one block")
    _require(block_k >= 8, "block_k must hold a few runs")
    NBLp = max(8, NB)
    WMAX = fused_width_checked(streams, block_k)

    lens = [st.num_steps for st in streams]
    s_pad = max(((max(lens) + chunk - 1) // chunk) * chunk, chunk)

    def staged_col(get):
        cols = []
        for st in streams:
            a = np.asarray(get(st), dtype=np.int32)
            cols.append(np.pad(a, ((0, s_pad - len(a)),)))
        # Flat [G*s_pad]: grouped 2-D SMEM blocks are not a legal TPU
        # layout (block second-minor must divide by 8 or equal the
        # array dim); 1-D chunk blocks indexed g*(s_pad//chunk)+i are.
        return jnp.asarray(np.concatenate(cols))

    staged = (staged_col(lambda o: o.pos),
              staged_col(lambda o: o.del_len),
              staged_col(lambda o: o.ins_len),
              staged_col(lambda o: o.ins_order_start),
              staged_col(lambda o: o.rows_per_step))

    jitted = _build_call(G, s_pad, batch, capacity, block_k, chunk,
                         WMAX, interpret)

    def run():
        ol, orr, ordp, lenp, blk, rows, meta, err = jitted(*staged)
        results = [
            RleResult(
                ordp=ordp[gi * capacity:(gi + 1) * capacity],
                lenp=lenp[gi * capacity:(gi + 1) * capacity],
                blkord=blk[gi], rows=rows[gi], meta=meta[gi],
                ol=ol[gi, :lens[gi]], orr=orr[gi, :lens[gi]], err=err,
                block_k=block_k, num_blocks=NB, batch=batch)
            for gi in range(G)
        ]
        return results if grouped else results[0]

    return run


@functools.lru_cache(maxsize=32)
def _build_call(G: int, s_pad: int, batch: int, capacity: int,
                block_k: int, chunk: int, wmax: int, interpret: bool):
    """Shape-keyed cache (the ``rle_lanes._build_call`` pattern): every
    same-shape replay shares one traced kernel — a per-call
    ``jax.jit(lambda ...)`` re-traces the whole interpret program each
    time, which dominates the fixed-shape test suites."""
    NB = capacity // block_k
    NBLp = max(8, NB)
    blocks_per_g = s_pad // chunk
    smem = lambda: pl.BlockSpec(
        (chunk,), lambda g, i: (g * blocks_per_g + i,),
        memory_space=pltpu.SMEM)

    call = pl.pallas_call(
        partial(_rle_kernel, K=block_k, NB=NB, NBL=NBLp, CHUNK=chunk,
                WMAX=wmax),
        grid=(G, s_pad // chunk),
        in_specs=[smem(), smem(), smem(), smem(), smem()],
        out_specs=[
            pl.BlockSpec((1, chunk, batch), lambda g, i: (g, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, batch), lambda g, i: (g, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((capacity, batch), lambda g, i: (g, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((capacity, batch), lambda g, i: (g, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, NBLp, batch), lambda g, i: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, NBLp, batch), lambda g, i: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, batch), lambda g, i: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, batch), lambda g, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((G, s_pad, batch), jnp.uint32),
            jax.ShapeDtypeStruct((G, s_pad, batch), jnp.uint32),
            jax.ShapeDtypeStruct((G * capacity, batch), jnp.int32),
            jax.ShapeDtypeStruct((G * capacity, batch), jnp.int32),
            jax.ShapeDtypeStruct((G, NBLp, batch), jnp.int32),
            jax.ShapeDtypeStruct((G, NBLp, batch), jnp.int32),
            jax.ShapeDtypeStruct((G, 8, batch), jnp.int32),
            jax.ShapeDtypeStruct((8, batch), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((NBLp, batch), jnp.int32),       # blkord
            pltpu.VMEM((NBLp, batch), jnp.int32),       # rws
            pltpu.VMEM((NBLp, batch), jnp.int32),       # liv
            pltpu.VMEM((NBLp, batch), jnp.int32),       # cumliv
            pltpu.SMEM((2,), jnp.int32),                # meta
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=110 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return jax.jit(lambda a, b, c, d, e: call(a, b, c, d, e))


def replay_local_rle(ops, capacity: int, **kw):
    """One-shot convenience wrapper over ``make_replayer_rle``."""
    return make_replayer_rle(ops, capacity, **kw)()


def simulate_run_rows(patches) -> tuple:
    """Host dry-run of the kernel's row algebra over a (merged) patch
    list: returns ``(peak_rows, final_rows)``. Used for capacity planning
    — blocks fragment to ~50% after splits, so size the device capacity
    at ~2.5x the peak. Mirrors the kernel exactly: delete = flip covered
    runs + boundary splits; insert = append-merge / splice / 3-way split.
    """
    runs = []  # (order_start, char_len, live)
    next_order = 0
    peak = 0
    for p in patches:
        if p.del_len:
            rem = p.del_len
            before = 0
            i = 0
            while rem > 0 and i < len(runs):
                o, l, live = runs[i]
                lv = l if live else 0
                cs = min(max(p.pos - before, 0), lv)
                ce = min(max(p.pos + rem - before, 0), lv)
                cov = ce - cs
                if cov > 0:
                    parts = []
                    if cs > 0:
                        parts.append((o, cs, True))
                    parts.append((o + cs, cov, False))
                    if ce < l:
                        parts.append((o + ce, l - ce, True))
                    runs[i:i + 1] = parts
                    i += len(parts)
                    rem -= cov
                else:
                    i += 1
                before += lv - cov
            next_order += p.del_len
        il = len(p.ins_content)
        if il:
            st = next_order
            if p.pos == 0:
                runs.insert(0, (st, il, True))
            else:
                before = 0
                for i, (o, l, live) in enumerate(runs):
                    lv = l if live else 0
                    if before + lv >= p.pos:
                        off = p.pos - before
                        if off == l and live and st == o + l:
                            runs[i] = (o, l + il, True)
                        elif off == lv:
                            runs.insert(i + 1, (st, il, True))
                        else:
                            runs[i:i + 1] = [(o, off, True), (st, il, True),
                                             (o + off, l - off, True)]
                        break
                    before += lv
            next_order += il
        peak = max(peak, len(runs))
    return peak, len(runs)


def expand_runs(res: RleResult, doc_index: int = 0) -> np.ndarray:
    """Run rows -> per-char ±(order+1) column in document order (the
    ``FlatDoc.signed`` layout), host-side numpy."""
    res.check()
    K = res.block_k
    # Slice the lane ON DEVICE before downloading: np.asarray on the
    # whole plane would pull capacity x batch through the host link
    # (10.7 GB at kevin-5M scale) for one lane's worth of data.
    ordc = np.asarray(res.ordp[:, doc_index])
    lenc = np.asarray(res.lenp[:, doc_index])
    blk = np.asarray(res.blkord[:, doc_index])
    rows = np.asarray(res.rows[:, doc_index])
    nlog = int(np.asarray(res.meta[0, doc_index]))
    o_parts, l_parts = [], []
    for l in range(nlog):
        b, r = int(blk[l]), int(rows[l])
        o_parts.append(ordc[b * K: b * K + r])
        l_parts.append(lenc[b * K: b * K + r])
    if not o_parts:
        return np.zeros(0, np.int32)
    o = np.concatenate(o_parts).astype(np.int64)
    ln = np.concatenate(l_parts).astype(np.int64)
    assert (ln > 0).all(), "occupied run with non-positive length"
    reps = ln
    total = int(reps.sum())
    starts = np.abs(o)
    sign = np.sign(o)
    base = np.repeat(starts, reps)
    within = np.arange(total) - np.repeat(np.cumsum(reps) - reps, reps)
    return (np.repeat(sign, reps) * (base + within)).astype(np.int32)


def rle_to_flat(
    ops: OpTensors,
    res: RleResult,
    capacity: int | None = None,
    order_capacity: int | None = None,
    doc_index: int = 0,
) -> FlatDoc:
    """Kernel result -> a standard ``FlatDoc`` (one doc of the batch):
    expand runs to char rows, prefill the by-order logs, then merge the
    kernel's per-op local origins (run heads; the in-run chain is the
    compile-time prefill, `span.rs:24-28`)."""
    flat = expand_runs(res, doc_index)
    n = len(flat)
    if capacity is None:
        capacity = max(2 << max(n - 1, 5).bit_length(), n)
    doc = make_flat_doc(capacity, order_capacity)
    doc = prefill_logs(doc, ops)
    ol_log = np.array(doc.ol_log)
    or_log = np.array(doc.or_log)
    ol_np = np.asarray(res.ol)[:, doc_index]
    or_np = np.asarray(res.orr)[:, doc_index]
    if len(ol_np) < ops.num_steps:
        raise ValueError(
            f"rle_to_flat needs per-op origins for all {ops.num_steps} "
            f"steps but the result carries {len(ol_np)} — was the engine "
            "built with store_origins=False? (zip truncation would "
            "silently skip the origin merges)")
    merge_fused_origins(ol_log, or_log, ops, ol_np, or_np)

    signed_col = np.zeros(capacity, np.int32)
    signed_col[:n] = flat
    advance = int(np.asarray(ops.order_advance, dtype=np.int64).sum())
    return dataclasses.replace(
        doc,
        signed=jnp.asarray(signed_col),
        ol_log=jnp.asarray(ol_log),
        or_log=jnp.asarray(or_log),
        n=jnp.asarray(n, I32),
        next_order=jnp.asarray(advance, U32),
    )

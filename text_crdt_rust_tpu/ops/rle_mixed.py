"""Mixed-stream RLE run engine: remote ops (hot path #2) on RUN rows.

``ops.rle`` is the north-star local-replay engine: device state is the
RLE run (`src/list/span.rs:6-119` semantics, ~40x fewer rows than chars)
but it refuses remote ops.  ``ops.blocked_mixed`` applies remote ops
(YATA integrate, `doc.rs:167-234`) but on ONE ROW PER CHARACTER.  This
engine is the round-4 unification the r3 verdict demanded: the full op
surface — KIND_LOCAL, KIND_REMOTE_INS, KIND_REMOTE_DEL — applied
directly to the run representation, so the `doc.rs:242-348` hot path
(the reference's raison d'etre) runs on state that is runs, not chars.

What the remote paths add on top of ``ops.rle``'s block grid:

- **a RAW per-slot count** (``raw``) next to the live count: remote
  cursors are RAW positions (tombstones not skipped, `doc.rs:452`), so
  the block descent needs the `FullIndex` pair (`index.rs:100-158`) —
  live sums for local edits, raw sums for integrate cursors.
- **order -> physical-block index** (``ordblk``, the `markers.rs:8` /
  `split_list/mod.rs:440` SpaceIndex analog) packed 128 orders/row.
  Maintained per insert; a block split moves rows and deliberately
  leaves entries stale — lookups verify containment against the hinted
  block (runs make that a range test, not an equality test) and fall
  back to ONE vectorized full-plane search, then self-heal.
- **by-order origin/rank tables** (``oll/orl/rkl``) prefilled host-side
  (`batch.prefill_logs`), updated in-kernel by local inserts — the YATA
  scan reads per-ORDER origins, which the prefilled implicit chain
  (`span.rs:24-28`) provides for mid-run chars.
- **run-level YATA integrate**: the reference's conflict scan walks
  items one at a time (`doc.rs:183-222`); on runs, every non-head char
  of a run has ``origin_left == its own predecessor`` so the scan can
  only break mid-run at the op's ``origin_right`` — each loop iteration
  therefore consumes a WHOLE run (or jumps straight to origin_right
  inside it), shrinking the scan by the run factor.
- **one-pass remote delete**: runs are disjoint ORDER intervals, so a
  target range ``[t, t+dlen)`` fully covers every run it touches except
  at most the two holding its endpoints — one plane-wide flip of the
  full covers (block live counts updated off one plane cumsum) plus
  <= 2 by-order endpoint fix-ups (3-way splits).  Already-dead covered
  runs count toward the idempotency total without flipping (idempotent
  concurrent deletes, `double_delete.rs:6-9`; excess counting stays
  host-side per SURVEY).  Any ``dlen`` in one step.

Same lane batching as ``ops.rle`` (all docs replay one shared stream),
same ``RleResult`` / ``rle_to_flat`` result surface.
"""
from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .batch import (
    KIND_LOCAL,
    KIND_REMOTE_DEL,
    KIND_REMOTE_INS,
    OpTensors,
    prefill_logs,
    require_unfused,
)
from .blocked import _cumsum_rows, _lane_scalar, _require, _shift_rows
from .rle import (
    RleResult,
    _delete_block_math,
    _insert_splice,
    _locate_run,
    _row_scalar,
    _shift_rows_up,
    _split_piece_aux,
)
from .span_arrays import make_flat_doc

LANES = 128  # orders per by-order table row


def _locate_run_raw(bo, bl, idx_k, r0, local):
    """Raw-position twin of ``rle._locate_run``: find the run containing
    RAW char #``local`` (1-based, tombstones counted).  Returns
    ``(i_r, o_r, l_r, off)`` with ``off`` the 1-based char offset."""
    cum = _cumsum_rows(bl)
    i_r = jnp.max(jnp.sum(
        ((cum < local) & (idx_k < r0)).astype(jnp.int32), axis=0))
    o_r = _row_scalar(bo, i_r, idx_k)
    l_r = _row_scalar(bl, i_r, idx_k)
    off = local - (_row_scalar(cum, i_r, idx_k) - l_r)
    return i_r, o_r, l_r, off


def _insert_splice_raw(bo, bl, idx_k, c, i_r, o_r, l_r, off, il, st,
                       o_left):
    """Raw-position twin of ``rle._insert_splice``: splice a new LIVE run
    (orders ``st..st+il``) at raw position ``c`` of a block.  Differences
    from the live-rank path: the split run may be a TOMBSTONE (sign must
    be preserved on the tail: a dead run's tail starts at
    ``-(|start|+off)``), and the merge fast path additionally requires
    the preceding run to be live (same-sign append) AND the op's
    ``origin_left`` to chain to the run's last char (`span.rs:47-53`).
    The chain gate is load-bearing for the YATA run-skip: the scan
    evaluates only run HEADS and skips the rest on the premise that
    every non-head char's origin_left is its own predecessor — merging
    an unchained run (e.g. two concurrent root inserts that happen to be
    order-contiguous) would hide a char the scan must evaluate and
    diverge from the oracle (caught round 5: ``amy/zed/mid`` -> ``azm``
    instead of ``amz``)."""
    mrg = ((c > 0) & (o_r > 0) & (off == l_r)
           & ((st + 1) == (o_r + l_r))
           & (o_left == o_r + l_r - 2))
    is_split = (c > 0) & (off < l_r)
    ins_at = jnp.where(c == 0, 0, i_r + 1)
    amt = jnp.where(mrg, 0, jnp.where(is_split, 2, 1))
    so = _shift_rows(bo, amt, 2)
    sl = _shift_rows(bl, amt, 2)
    no = jnp.where(idx_k < ins_at, bo, so)
    nl = jnp.where(idx_k < ins_at, bl, sl)
    nl = jnp.where(is_split & (idx_k == i_r), off, nl)
    new_run = (idx_k == ins_at) & jnp.logical_not(mrg)
    no = jnp.where(new_run, st + 1, no)
    nl = jnp.where(new_run, il, nl)
    tail = is_split & (idx_k == ins_at + 1)
    tail_o = jnp.where(o_r > 0, o_r + off, o_r - off)
    no = jnp.where(tail, tail_o, no)
    nl = jnp.where(tail, l_r - off, nl)
    nl = jnp.where(mrg & (idx_k == i_r), l_r + il, nl)
    return no, nl, amt, mrg, is_split


class RleMixedResult(RleResult):
    """``RleResult`` + the order-index error flag (err row 2)."""

    def check(self) -> None:
        super().check()
        err = np.asarray(self.err)
        if err[2].max() != 0:
            raise RuntimeError(
                "order index lookup missed: an op referenced an order "
                "absent from device state (corrupt stream or engine bug)")


def _mixed_rle_kernel(
    kind_ref, pos_ref, dlen_ref, dtgt_ref, olop_ref, orop_ref, rk_ref,
    ilen_ref, start_ref,                        # [CHUNK] SMEM op columns
    oll_in, orl_in, rkl_in,                     # [OT, 128] by-order tables
    ol_ref, or_ref,                             # [CHUNK, B] outputs
    ordp, lenp,                                 # [CAP, B] run planes (OUT
                                                #   blocks as working state)
    blk_out, rows_out, meta_out, err_ref,       # tables + flags
    blkord, rws, liv, raw, cumliv, cumraw,      # VMEM scratch (cum* =
    ordblk, oll, orl,                           #   incremental inclusive
    olp, orp, rkp, lpp,                         #   prefixes; per-run YATA
    meta,                                       #   aux planes; SMEM scratch
    *, K: int, NB: int, NBL: int, CHUNK: int, OT: int,
    FAST: bool = True,
):
    B = ordp.shape[1]
    CAP = K * NB
    i = pl.program_id(0)
    last = pl.num_programs(0) - 1
    idx_k = lax.broadcasted_iota(jnp.int32, (K, B), 0)
    idx_l = lax.broadcasted_iota(jnp.int32, (NBL, B), 0)
    idx_cap = lax.broadcasted_iota(jnp.int32, (CAP, B), 0)
    lane = lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    lane2 = lax.broadcasted_iota(jnp.int32, (2, LANES), 1)
    row2 = lax.broadcasted_iota(jnp.int32, (2, LANES), 0)
    root_i = jnp.int32(-1)  # ROOT_ORDER as i32
    root_u = jnp.uint32(0xFFFFFFFF)

    ol_ref[:] = jnp.zeros_like(ol_ref)
    or_ref[:] = jnp.zeros_like(or_ref)

    @pl.when(i == 0)
    def _init():
        ordp[:] = jnp.zeros_like(ordp)
        lenp[:] = jnp.zeros_like(lenp)
        blkord[:] = jnp.zeros_like(blkord)
        rws[:] = jnp.zeros_like(rws)
        liv[:] = jnp.zeros_like(liv)
        raw[:] = jnp.zeros_like(raw)
        cumliv[:] = jnp.zeros_like(cumliv)
        cumraw[:] = jnp.zeros_like(cumraw)
        ordblk[:] = jnp.zeros_like(ordblk)
        err_ref[:] = jnp.zeros_like(err_ref)
        oll[:] = oll_in[:]
        orl[:] = orl_in[:]
        # Per-run YATA aux planes (the vectorized conflict scan's
        # gather-free cache): origin-left / origin-right / author rank
        # of each run's HEAD char, plus the logical position of each
        # row's block (the doc-order sort key).  Maintained through
        # every splice; split pieces inherit or/rank and chain ol to
        # their predecessor (`span.rs:24-28` implicit chain).
        olp[:] = jnp.zeros_like(olp)
        orp[:] = jnp.zeros_like(orp)
        rkp[:] = jnp.zeros_like(rkp)
        lpp[:] = jnp.zeros_like(lpp)
        meta[0] = 1  # logical blocks in use

    # ---- by-order tables (order o lives at [o // 128, o % 128]) ---------

    def tab_read(tab, o):
        r = tab[pl.ds(o // LANES, 1), :]
        return jnp.sum(jnp.where(lane == o % LANES, r, 0))

    def tab_write(tab, o, v):
        r = tab[pl.ds(o // LANES, 1), :]
        tab[pl.ds(o // LANES, 1), :] = jnp.where(lane == o % LANES, v, r)

    def tab_write_run(tab, start, run_len, v):
        """tab[start : start+run_len] = v; run_len <= 128, so a 2-row
        window always covers it (tables keep a spare tail row)."""
        r0 = start // LANES
        w = tab[pl.ds(r0, 2), :]
        g = row2 * LANES + lane2 + r0 * LANES
        hit = (g >= start) & (g < start + run_len)
        tab[pl.ds(r0, 2), :] = jnp.where(hit, v, w)

    # ---- slot plumbing (logical block tables) ---------------------------

    def slot_scalar(tbl, l):
        return _lane_scalar(jnp.where(idx_l == l, tbl[:], 0))

    # Descents take a (table, inclusive-prefix) pair; the prefixes are
    # maintained INCREMENTALLY (one masked add per update; splits shift
    # them with the other tables) instead of an 8-roll cumsum per
    # lookup — this kernel descends up to 3x per YATA while-iteration,
    # so the recompute dominated the storm's step cost.
    LIV = (liv, cumliv)
    RAW = (raw, cumraw)

    def sum_before_slot(tblcum, l):
        # One masked reduction; the incremental prefix is only needed
        # by slot_of_cum/total_of (review: cum[l] - tbl[l] would be two
        # lane reductions for the same answer).
        tbl, _ = tblcum
        return _lane_scalar(jnp.where(idx_l < l, tbl[:], 0))

    def total_of(tblcum):
        _, cum = tblcum
        return slot_scalar(cum, meta[0] - 1)

    def slot_of_cum(tblcum, rank1):
        """Smallest logical slot whose cumulative count reaches
        ``rank1`` (the `root.rs:54-88` descent over block sums; LIV for
        content cursors, RAW for raw cursors — `index.rs:100`). Slots
        >= nlog may hold stale prefixes; the mask excludes them."""
        _, cum = tblcum
        nlog = meta[0]
        hit = (cum[:] < rank1) & (idx_l < nlog)
        return jnp.minimum(
            jnp.max(jnp.sum(hit.astype(jnp.int32), axis=0)), nlog - 1)

    def logical_of_physical(b):
        """Slot holding physical block ``b`` (small NBL scan)."""
        nlog = meta[0]
        hit = (blkord[:] == b) & (idx_l < nlog)
        return jnp.max(jnp.where(hit, idx_l, 0))

    def split(l):
        """Leaf split (`mutations.rs:623-669`): move the top half of slot
        ``l``'s rows to a fresh physical block spliced into the logical
        order at ``l+1``.  At table capacity the split is a NO-OP with the
        error flag raised (advisor r3: proceeding overwrote a live block).
        ``ordblk`` entries of moved rows go stale; lookups self-heal."""
        nlog = meta[0]

        @pl.when(nlog >= NB)
        def _cap():
            err_ref[0:1, :] = jnp.ones((1, B), jnp.int32)

        @pl.when(nlog < NB)
        def _do():
            b = slot_scalar(blkord, l)
            r = slot_scalar(rws, l)
            keep = r // 2
            mv = r - keep
            nb = nlog  # fresh physical block id
            bo = ordp[pl.ds(b * K, K), :]
            bl = lenp[pl.ds(b * K, K), :]
            hi_mask = (idx_k >= keep) & (idx_k < r)
            liv_hi = _lane_scalar(jnp.where(hi_mask & (bo > 0), bl, 0))
            raw_hi = _lane_scalar(jnp.where(hi_mask, bl, 0))
            liv_lo = slot_scalar(liv, l) - liv_hi
            raw_lo = slot_scalar(raw, l) - raw_hi

            up_o = _shift_rows_up(bo, keep, K)
            up_l = _shift_rows_up(bl, keep, K)
            new_mask = idx_k < mv
            ordp[pl.ds(nb * K, K), :] = jnp.where(new_mask, up_o, 0)
            lenp[pl.ds(nb * K, K), :] = jnp.where(new_mask, up_l, 0)
            keep_mask = idx_k < keep
            ordp[pl.ds(b * K, K), :] = jnp.where(keep_mask, bo, 0)
            lenp[pl.ds(b * K, K), :] = jnp.where(keep_mask, bl, 0)
            # Aux planes move with their rows (values unchanged: a
            # block split never changes any run's head char).
            for ap in (olp, orp, rkp):
                ax = ap[pl.ds(b * K, K), :]
                ap[pl.ds(nb * K, K), :] = jnp.where(
                    new_mask, _shift_rows_up(ax, keep, K), 0)
                ap[pl.ds(b * K, K), :] = jnp.where(keep_mask, ax, 0)
            # Logical positions: blocks after slot l shift one slot
            # down; the moved-out top half (new physical block nb)
            # lands at slot l + 1.  (Unallocated blocks' rows hold
            # 0, never > l, so the shift cannot touch them.)
            #
            # LOAD-BEARING: unlike olp/orp/rkp above, lpp rows of STALE
            # slots (>= rws, the moved-out top half of block b and the
            # unused tail of nb) are deliberately NOT zeroed. lpp is
            # keyed by PHYSICAL row, whole-block: when a later insert
            # validates one of those rows (rws grows back into them),
            # the row must already hold its block's logical slot — the
            # fast-integrate window keys (`integrate_fast`: key =
            # lpp * K + row) read lpp for every valid row without a
            # per-row freshness check. Zeroing stale rows here would
            # make a later-validated row in block b/nb inherit slot 0
            # and silently corrupt the scan-window bounds.
            lpp[:] = jnp.where(lpp[:] > l, lpp[:] + 1, lpp[:])
            lpp[:] = jnp.where(idx_cap // K == nb, l + 1, lpp[:])

            # cum prefixes shift with the tables; slot l+1 inherits the
            # old inclusive prefix of l (correct), slot l loses the
            # moved-out top half (see ops.rle split).
            for tbl in (blkord, rws, liv, raw, cumliv, cumraw):
                shifted = _shift_rows(tbl[:], 1, 1)
                tbl[:] = jnp.where(idx_l <= l, tbl[:], shifted)
            rws[pl.ds(l, 1), :] = jnp.broadcast_to(keep, (1, B))
            liv[pl.ds(l, 1), :] = jnp.broadcast_to(liv_lo, (1, B))
            raw[pl.ds(l, 1), :] = jnp.broadcast_to(raw_lo, (1, B))
            cumliv[pl.ds(l, 1), :] = cumliv[pl.ds(l, 1), :] - liv_hi
            cumraw[pl.ds(l, 1), :] = cumraw[pl.ds(l, 1), :] - raw_hi
            blkord[pl.ds(l + 1, 1), :] = jnp.broadcast_to(nb, (1, B))
            rws[pl.ds(l + 1, 1), :] = jnp.broadcast_to(mv, (1, B))
            liv[pl.ds(l + 1, 1), :] = jnp.broadcast_to(liv_hi, (1, B))
            raw[pl.ds(l + 1, 1), :] = jnp.broadcast_to(raw_hi, (1, B))
            meta[0] = nlog + 1

    # ---- order -> run lookup (the SpaceIndex, `split_list/mod.rs:440`) --

    def find_in_block(b, o):
        """(found, row) of the run CONTAINING order ``o`` in block ``b``
        (a range test: runs make the index 1-per-run, not 1-per-char)."""
        bo = ordp[pl.ds(b * K, K), :]
        bl = lenp[pl.ds(b * K, K), :]
        so = jnp.abs(bo) - 1
        hit = (bo != 0) & (so <= o) & (o < so + bl)
        found = _lane_scalar(hit.astype(jnp.int32)) > 0
        row = jnp.max(jnp.min(jnp.where(hit, idx_k, K), axis=0))
        return found, row

    def locate_order(o):
        """(physical block, row) of the run containing order ``o``.
        ``ordblk`` is a HINT — splits leave it stale; verify, fall back to
        one vectorized full-plane search, self-heal the entry.

        Callers may pass the ROOT sentinel (-1) from a discarded
        ``jnp.where`` branch (both branches evaluate): the lookup then
        returns in-range garbage without raising the miss flag or
        touching the hint table."""
        oc = jnp.maximum(o, 0)
        bh = jnp.clip(tab_read(ordblk, oc), 0, NB - 1)
        f, row = find_in_block(bh, oc)

        def fallback():
            so = jnp.abs(ordp[:]) - 1
            hit = (ordp[:] != 0) & (so <= oc) & (oc < so + lenp[:])
            g = jnp.max(jnp.min(jnp.where(hit, idx_cap, CAP - 1), axis=0))
            ok = _lane_scalar(hit.astype(jnp.int32)) > 0

            @pl.when(~ok & (o >= 0))
            def _missing():
                err_ref[2:3, :] = jnp.ones((1, B), jnp.int32)

            return g // K, g % K

        b, row = lax.cond(f, lambda: (bh, row), fallback)

        @pl.when(o >= 0)
        def _heal():
            tab_write(ordblk, oc, b)

        return b, row

    def pos_of_order(o):
        """RAW document position of the char with order ``o``."""
        b, row = locate_order(o)
        l = logical_of_physical(b)
        bo = ordp[pl.ds(b * K, K), :]
        bl = lenp[pl.ds(b * K, K), :]
        raw_before = _lane_scalar(jnp.where(idx_k < row, bl, 0))
        so_row = jnp.abs(_row_scalar(bo, row, idx_k)) - 1
        return sum_before_slot(RAW, l) + raw_before + (o - so_row)

    def cursor_after(o):
        return jnp.where(o == root_i, 0, pos_of_order(o) + 1)

    def run_at_raw(c):
        """Signed start order, length, and 0-based char offset of the run
        holding RAW position ``c`` (one shared location routine —
        ``run_at2`` — so the serial walk and the fast scan's window
        bounds can never desynchronize)."""
        _, _, _, o_r, l_r, off = run_at2(c)
        return o_r, l_r, off

    # ---- local ops (the ops.rle paths + raw/index/table upkeep) ---------

    def find_insert_slot(p):
        l = jnp.where(p == 0, 0, slot_of_cum(LIV, p))
        return l, slot_scalar(rws, l)

    def record_insert(k, b, st, il, left, right):
        """Index + origin-table upkeep and per-op origin outputs shared by
        the local and remote insert paths."""
        tab_write_run(ordblk, st, il, b)
        tab_write(oll, st, left)
        tab_write_run(orl, st, il, right)
        ol_ref[pl.ds(k, 1), :] = jnp.broadcast_to(
            left.astype(jnp.uint32), (1, B))
        or_ref[pl.ds(k, 1), :] = jnp.broadcast_to(
            right.astype(jnp.uint32), (1, B))

    def aux_splice(b, i_r, ins_at, amt, mrg, is_split, tail_ol,
                   new_ol, new_or, new_rk):
        """Mirror an insert splice's row motion onto the per-run YATA
        aux planes of block ``b``: rows >= ``ins_at`` shift down by
        ``amt``, the new run takes the op's (origin-left, origin-right,
        rank), and a split tail chains to its own predecessor char
        while inheriting the split run's origin-right/rank."""
        ao = olp[pl.ds(b * K, K), :]
        ar = orp[pl.ds(b * K, K), :]
        ak = rkp[pl.ds(b * K, K), :]
        t_rk = _row_scalar(ak, i_r, idx_k)
        new_run = (idx_k == ins_at) & jnp.logical_not(mrg)
        tail = is_split & (idx_k == ins_at + 1)
        # A split tail's origin-right is NOT the head's (merge-appended
        # chars keep their own) — but the ``orl`` TABLE entry of the
        # tail's head char (order ``tail_ol + 1``) is exact and
        # immutable once written (every existing char's entry was
        # prefilled or recorded at insert time), so read the TRUE value
        # at split time (ADVICE r5 item 3).  The tail then re-qualifies
        # for the ``integrate_fast`` sibling classification instead of
        # poisoning the window with -2 and forcing the serial walk on
        # every later op that scans past it.
        t_or = tab_read(orl, jnp.clip(tail_ol + 1, 0, OT * LANES - 1))
        for ap, a, nv, tv in ((olp, ao, new_ol, tail_ol),
                              (orp, ar, new_or, t_or),
                              (rkp, ak, new_rk, t_rk)):
            na = jnp.where(idx_k < ins_at, a, _shift_rows(a, amt, 2))
            na = jnp.where(new_run, nv, na)
            na = jnp.where(tail, tv, na)
            ap[pl.ds(b * K, K), :] = na

    def do_local_insert(k, p, il, st):
        """Insert an ``il``-char run after LIVE rank ``p``
        (`mutations.rs:17-179`): <= 3 touched rows regardless of ``il``."""
        l, r0 = find_insert_slot(p)

        @pl.when(r0 + 2 > K)
        def _():
            split(l)

        l, r0 = find_insert_slot(p)
        b = slot_scalar(blkord, l)
        base = sum_before_slot(LIV, l)
        local = p - base
        bo = ordp[pl.ds(b * K, K), :]
        bl = lenp[pl.ds(b * K, K), :]
        i_r, o_r, l_r, off = _locate_run(bo, bl, idx_k, r0, local)
        no, nl, amt, _mrg, is_split = _insert_splice(
            bo, bl, idx_k, p, i_r, o_r, l_r, off, il, st)

        left = jnp.where(p == 0, root_i,
                         ((o_r - 1) + (off - 1)).astype(jnp.int32))
        # Raw successor (`doc.rs:452`: tombstones not skipped); read from
        # the PRE-splice block.
        nxt_in_blk = _row_scalar(bo, i_r + 1, idx_k)  # 0 past the last row
        nlog = meta[0]
        b2 = slot_scalar(blkord, jnp.minimum(l + 1, NBL - 1))
        nxt_slot_o = jnp.max(jnp.sum(jnp.where(
            idx_k == 0, ordp[pl.ds(b2 * K, K), :], 0), axis=0))
        succ_signed = jnp.where(
            i_r + 1 < r0, nxt_in_blk,
            jnp.where(l + 1 < nlog, nxt_slot_o, 0))
        first_o = _row_scalar(bo, 0, idx_k)  # p == 0: the raw doc head
        succ_p0 = jnp.where(r0 > 0, first_o, 0)
        succ = jnp.where(p == 0, succ_p0,
                         jnp.where(is_split, o_r + off, succ_signed))
        right = jnp.where(succ == 0, root_i,
                          (jnp.abs(succ) - 1).astype(jnp.int32))

        # Split-head order uses jnp.abs like the remote path (`do_remote
        # _insert`): o_r is signed (tombstone runs are negative), and the
        # split head's order must be the magnitude regardless of liveness.
        aux_splice(b, i_r, jnp.where(p == 0, 0, i_r + 1), amt, _mrg,
                   is_split, (jnp.abs(o_r) - 1) + off - 1, left, right,
                   tab_read(rkl_in, st))
        ordp[pl.ds(b * K, K), :] = no
        lenp[pl.ds(b * K, K), :] = nl
        rws[pl.ds(l, 1), :] = rws[pl.ds(l, 1), :] + amt
        liv[pl.ds(l, 1), :] = liv[pl.ds(l, 1), :] + il
        raw[pl.ds(l, 1), :] = raw[pl.ds(l, 1), :] + il
        cumliv[:] = jnp.where(idx_l >= l, cumliv[:] + il, cumliv[:])
        cumraw[:] = jnp.where(idx_l >= l, cumraw[:] + il, cumraw[:])
        record_insert(k, b, st, il, left, right)

    def do_local_delete(p, d):
        """Tombstone ``d`` live chars after live rank ``p`` (the
        `mutations.rs:520-570` walk; raw counts are unchanged)."""

        def body(carry):
            rem, iters = carry
            l = slot_of_cum(LIV, p + 1)

            @pl.when(slot_scalar(rws, l) + 2 > K)
            def _():
                split(l)

            l = slot_of_cum(LIV, p + 1)
            b = slot_scalar(blkord, l)
            base = sum_before_slot(LIV, l)
            bo = ordp[pl.ds(b * K, K), :]
            bl = lenp[pl.ds(b * K, K), :]
            aux_in = (olp[pl.ds(b * K, K), :],
                      orp[pl.ds(b * K, K), :],
                      rkp[pl.ds(b * K, K), :])
            no, nl, added, tot, aux_out = _delete_block_math(
                bo, bl, idx_k, K, base, p, rem, aux=aux_in)
            for ap, na in zip((olp, orp, rkp), aux_out):
                ap[pl.ds(b * K, K), :] = na
            ordp[pl.ds(b * K, K), :] = no
            lenp[pl.ds(b * K, K), :] = nl
            rws[pl.ds(l, 1), :] = rws[pl.ds(l, 1), :] + added
            liv[pl.ds(l, 1), :] = liv[pl.ds(l, 1), :] - tot
            cumliv[:] = jnp.where(idx_l >= l, cumliv[:] - tot, cumliv[:])
            return rem - tot, iters + 1

        rem, _ = lax.while_loop(
            lambda c: (c[0] > 0) & (c[1] <= 2 * NBL), body, (d, 0))

        @pl.when(rem > 0)
        def _bad_delete():
            err_ref[1:2, :] = jnp.ones((1, B), jnp.int32)

    # ---- remote insert (`doc.rs:274-293` -> integrate) ------------------

    def run_at2(c):
        """``run_at_raw`` + the run's (logical slot, physical block,
        row): everything the fast scan's window bounds need."""
        l = slot_of_cum(RAW, c + 1)
        b = slot_scalar(blkord, l)
        r0 = slot_scalar(rws, l)
        local = c - sum_before_slot(RAW, l)
        bo = ordp[pl.ds(b * K, K), :]
        bl = lenp[pl.ds(b * K, K), :]
        cum = _cumsum_rows(bl)
        i_r = jnp.max(jnp.sum(
            ((cum <= local) & (idx_k < r0)).astype(jnp.int32), axis=0))
        o_r = _row_scalar(bo, i_r, idx_k)
        l_r = _row_scalar(bl, i_r, idx_k)
        off = local - (_row_scalar(cum, i_r, idx_k) - l_r)
        return l, b, i_r, o_r, l_r, off

    BIGK = NBL * K + K  # past any valid doc-order key

    def integrate_fast(cursor0, my_rank, o_left, o_right):
        """Vectorized YATA conflict scan: ONE classification pass over
        all run rows plus three masked reductions replace the serial
        run-walk (whose per-op cost grows with the document and
        dominated the config-4 storm).

        Sound when every run in the scan window is either a direct
        SIBLING (head ``origin_left`` == the op's — order equality, so
        ``olc == left_cursor`` exactly) or a PHYSICALLY-CHAINED piece
        (head chains to its own predecessor char, which sits in the
        previous row of the same block — then ``olc == head position >=
        left_cursor``, the serial walk's plain advance).  Anything else
        — including a split piece whose by-order predecessor was
        spliced away from it — raises ``flag`` and the caller falls
        back to the exact serial loop, so exotic windows lose speed,
        never correctness.  The scanning/scan_start state machine
        (`doc.rs:183-222`, pinned-scan_start rule) reduces to:

          kfb = first sibling that breaks (rank > mine, same o_right)
          kll = last lower-ranked sibling before kfb
          kss = first higher-ranked different-o_right sibling after kll
          cursor = kss if it exists else kfb (else the o_right bound)
        """
        n = total_of(RAW)
        tpos = jnp.where(o_right == root_i, n, pos_of_order(o_right))
        # Window bounds as doc-order keys (logical slot * K + row).
        l0, b0, i0, o_r0, l_r0, off0 = run_at2(cursor0)
        key_lo = l0 * K + i0 - jnp.where(off0 == 0, 1, 0)
        lT, bT, iT, o_rT, l_rT, offT = run_at2(tpos)
        key_hi = jnp.where(tpos >= n, BIGK,
                           lT * K + iT + jnp.where(offT == 0, 0, 1))
        key = lpp[:] * K + idx_cap % K
        valid = ordp[:] != 0
        W = valid & (key > key_lo) & (key < key_hi)
        h = jnp.abs(ordp[:]) - 1
        S = W & (olp[:] == o_left)
        # Chained piece whose predecessor char (order h-1) is literally
        # the previous row's last char: olc = own head position, a
        # plain advance.  Row 0 of a block cannot verify adjacency
        # (its predecessor row lives in another block) -> not safe.
        e_prev = pltpu.roll(h + lenp[:] - 1, 1, axis=0)
        rib = idx_cap % K
        chain = (W & ~S & (h > 0) & (olp[:] == h - 1)
                 & (rib > 0) & (e_prev == h - 1))
        bad = (W & ~S & ~chain) | (S & ((rkp[:] == my_rank)
                                        | (orp[:] == -2)))
        gt_r = rkp[:] > my_rank
        sgo = S & gt_r & (orp[:] == o_right)
        sgn = S & gt_r & (orp[:] != o_right)
        slt = S & ~gt_r
        kfb = jnp.min(jnp.where(sgo, key, BIGK))
        kll = jnp.max(jnp.where(slt & (key < kfb), key, -1))
        kss = jnp.min(jnp.where(sgn & (key > kll) & (key < kfb), key,
                                BIGK))
        flag = jnp.max(jnp.where(bad, 1, 0)) > 0

        # Mid-run window start: the char AT cursor0 chains to the char
        # at cursor0 - 1 == the op's origin_left char, so it is always
        # a direct sibling (the serial walk probes it at off > 0); its
        # key precedes every window key.
        pseudo = (off0 > 0) & (cursor0 < tpos)
        # The pseudo candidate is a MID-RUN char: its origin-right and
        # rank come from the exact by-order tables (the serial walk's
        # source), not the head aux — merge-appended chars keep their
        # own origin-right.
        order0 = jnp.clip(jnp.abs(o_r0) - 1 + off0, 0, OT * LANES - 1)
        p_or = tab_read(orl, order0)
        p_rk = tab_read(rkl_in, order0)
        kP = key_lo  # strictly below every window key
        p_gt = p_rk > my_rank
        flag = flag | (pseudo & (p_rk == my_rank))
        kfb = jnp.where(pseudo & p_gt & (p_or == o_right), kP, kfb)
        kll = jnp.where(pseudo & ~p_gt & (kP < kfb) & (kll < 0), kP, kll)
        kss = jnp.where(pseudo & p_gt & (p_or != o_right)
                        & (kll < kP) & (kP < kfb), kP, kss)

        # kss was reduced against the PRE-pseudo kfb; if the pseudo
        # candidate lowered kfb (it precedes every window key), a stale
        # window kss must lose to it — compare against kfb, not BIGK.
        kwin = jnp.where(kss < kfb, kss, kfb)
        # Winner position: tpos when nothing broke earlier, the window
        # start for the pseudo candidate, else the winning run head's
        # raw position (one block read).
        l_w = jnp.clip(kwin // K, 0, NBL - 1)
        i_w = kwin % K
        b_w = slot_scalar(blkord, l_w)
        bl_w = lenp[pl.ds(b_w * K, K), :]
        hp_w = sum_before_slot(RAW, l_w) + _lane_scalar(
            jnp.where(idx_k < i_w, bl_w, 0))
        c = jnp.where(kwin >= BIGK, tpos,
                      jnp.where(kwin == kP, cursor0, hp_w))
        return c, flag

    def integrate_entry(my_rank, o_left, o_right):
        cursor0 = cursor_after(o_left)
        if not FAST:
            return integrate_cursor(cursor0, my_rank, o_left, o_right)
        c_fast, flag = integrate_fast(cursor0, my_rank, o_left, o_right)
        # Branch via pl.when + an SMEM cell, not lax.cond: a cond whose
        # branch nests the serial while-loop (with its ref writes) sends
        # Mosaic compilation into the weeds (>7 min for the storm
        # kernel vs ~20s with predication).
        meta[1] = c_fast

        @pl.when(flag)
        def _exact():
            meta[1] = integrate_cursor(cursor0, my_rank, o_left, o_right)

        return meta[1]

    def integrate_cursor(cursor0, my_rank, o_left, o_right):
        """The YATA conflict scan (`doc.rs:183-222`) over RUNS: a run's
        non-head chars have ``origin_left == own predecessor`` (olc ==
        own position > left_cursor), so after evaluating a head char the
        scan can only stop inside that run AT ``o_right`` — each
        iteration consumes a whole run or jumps straight there.
        Pinned-scan_start rule (tests/test_integrate_divergence.py).
        The serial exact path: ``integrate_fast`` replaces it whenever
        the window shape allows, falling back here via ``flag``."""
        left_cursor = cursor0
        n = total_of(RAW)

        def cond(state):
            cursor, scanning, scan_start, done = state
            return ~done & (cursor < n)

        def body(state):
            cursor, scanning, scan_start, done = state
            o_r, l_r, off = run_at_raw(cursor)
            so = jnp.abs(o_r) - 1
            other_order = so + off
            other_left = tab_read(oll, other_order)
            other_right = tab_read(orl, other_order)
            other_rank = tab_read(rkl_in, other_order)
            olc = cursor_after(other_left)
            brk = (other_order == o_right) | (olc < left_cursor)
            eq = ~brk & (olc == left_cursor)
            gt = my_rank > other_rank
            brk = brk | (eq & ~gt & (o_right == other_right))
            starts_scan = eq & ~gt & (o_right != other_right)
            new_scan_start = jnp.where(starts_scan & ~scanning, cursor,
                                       scan_start)
            new_scanning = jnp.where(
                eq, jnp.where(gt, False, jnp.where(
                    o_right == other_right, scanning, True)),
                scanning,
            )
            # Run-skip: chars (off+1 .. l_r-1) all have olc == own
            # position > left_cursor (no brk, no eq) — jump past them,
            # stopping only at o_right if this run contains it.
            contains_right = (o_right > other_order) & (o_right < so + l_r)
            step = jnp.where(contains_right, o_right - other_order,
                             l_r - off)
            return (jnp.where(brk, cursor, cursor + step), new_scanning,
                    new_scan_start, brk)

        init = (cursor0, jnp.asarray(False), cursor0, jnp.asarray(False))
        cursor, scanning, scan_start, _ = lax.while_loop(cond, body, init)
        return jnp.where(scanning, scan_start, cursor)

    def do_remote_insert(k, my_rank, o_left, o_right, il, st):
        c = integrate_entry(my_rank, o_left, o_right)
        l = jnp.where(c == 0, 0, slot_of_cum(RAW, c))

        @pl.when(slot_scalar(rws, l) + 2 > K)
        def _():
            split(l)

        l = jnp.where(c == 0, 0, slot_of_cum(RAW, c))
        b = slot_scalar(blkord, l)
        r0 = slot_scalar(rws, l)
        local = c - sum_before_slot(RAW, l)
        bo = ordp[pl.ds(b * K, K), :]
        bl = lenp[pl.ds(b * K, K), :]
        i_r, o_r, l_r, off = _locate_run_raw(bo, bl, idx_k, r0, local)
        no, nl, amt, _mrg, _is_split = _insert_splice_raw(
            bo, bl, idx_k, c, i_r, o_r, l_r, off, il, st, o_left)
        aux_splice(b, i_r, jnp.where(c == 0, 0, i_r + 1), amt, _mrg,
                   _is_split, (jnp.abs(o_r) - 1) + off - 1,
                   o_left, o_right, my_rank)
        ordp[pl.ds(b * K, K), :] = no
        lenp[pl.ds(b * K, K), :] = nl
        rws[pl.ds(l, 1), :] = rws[pl.ds(l, 1), :] + amt
        liv[pl.ds(l, 1), :] = liv[pl.ds(l, 1), :] + il
        raw[pl.ds(l, 1), :] = raw[pl.ds(l, 1), :] + il
        cumliv[:] = jnp.where(idx_l >= l, cumliv[:] + il, cumliv[:])
        cumraw[:] = jnp.where(idx_l >= l, cumraw[:] + il, cumraw[:])
        record_insert(k, b, st, il, o_left, o_right)

    # ---- remote delete (`doc.rs:295-340`) -------------------------------

    def retire_endpoint(t, dlen, o):
        """Split the covered sub-range out of the run containing order
        ``o`` (one former-walk iteration).  No-op unless that run is
        LIVE and PARTIALLY covered — full covers were flipped by the
        caller's plane pass, dead runs are idempotent retires."""
        b, row = locate_order(o)
        l = logical_of_physical(b)

        def run_facts():
            bo = ordp[pl.ds(b * K, K), :]
            bl = lenp[pl.ds(b * K, K), :]
            o_r = _row_scalar(bo, row, idx_k)
            l_r = _row_scalar(bl, row, idx_k)
            so = jnp.abs(o_r) - 1
            a = jnp.maximum(t - so, 0)
            e = jnp.minimum(l_r, t + dlen - so)
            return bo, bl, o_r, l_r, so, a, e

        _, _, o_r, l_r, so, a, e = run_facts()
        partial = (o_r > 0) & ((a > 0) | (e < l_r)) & (e > a)

        @pl.when(partial & (slot_scalar(rws, l) + 2 > K))
        def _():
            split(l)

        @pl.when(partial)
        def _fix():
            b2, row2 = locate_order(o)  # split may have moved the run
            l2 = logical_of_physical(b2)
            bo = ordp[pl.ds(b2 * K, K), :]
            bl = lenp[pl.ds(b2 * K, K), :]
            o_r = _row_scalar(bo, row2, idx_k)
            l_r = _row_scalar(bl, row2, idx_k)
            so = jnp.abs(o_r) - 1
            a = jnp.maximum(t - so, 0)
            e = jnp.minimum(l_r, t + dlen - so)
            cov = e - a
            has_head = a > 0
            has_tail = e < l_r
            amt = has_head.astype(jnp.int32) + has_tail.astype(jnp.int32)
            sh_o = _shift_rows(bo, amt, 2)
            sh_l = _shift_rows(bl, amt, 2)
            no = jnp.where(idx_k <= row2, bo, sh_o)
            nl = jnp.where(idx_k <= row2, bl, sh_l)
            # Part layout: [head?] [tombstone mid] [tail?].
            p0o = jnp.where(has_head, o_r, -(so + a + 1))
            p0l = jnp.where(has_head, a, cov)
            p1o = jnp.where(has_head, -(so + a + 1), so + e + 1)
            p1l = jnp.where(has_head, cov, l_r - e)
            w0 = idx_k == row2
            no = jnp.where(w0, p0o, no)
            nl = jnp.where(w0, p0l, nl)
            w1 = (idx_k == row2 + 1) & (amt >= 1)
            no = jnp.where(w1, p1o, no)
            nl = jnp.where(w1, p1l, nl)
            w2 = (idx_k == row2 + 2) & (amt == 2)
            no = jnp.where(w2, so + e + 1, no)
            nl = jnp.where(w2, l_r - e, nl)
            ordp[pl.ds(b2 * K, K), :] = no
            lenp[pl.ds(b2 * K, K), :] = nl
            # Aux pieces: piece 0 keeps the original head; later pieces
            # chain to their predecessor char (shared 3-way-split
            # transform, see rle._split_piece_aux).
            aux_out = _split_piece_aux(
                (olp[pl.ds(b2 * K, K), :], orp[pl.ds(b2 * K, K), :],
                 rkp[pl.ds(b2 * K, K), :]),
                idx_k, row2, amt, w1, w2, so, a, e, has_head)
            for ap, na in zip((olp, orp, rkp), aux_out):
                ap[pl.ds(b2 * K, K), :] = na
            rws[pl.ds(l2, 1), :] = rws[pl.ds(l2, 1), :] + amt
            liv[pl.ds(l2, 1), :] = liv[pl.ds(l2, 1), :] - cov
            cumliv[:] = jnp.where(idx_l >= l2, cumliv[:] - cov,
                                  cumliv[:])

    def do_remote_delete(t, dlen):
        """One-pass ORDER-interval tombstone (`doc.rs:295-340` without
        the fragmentation walk; see ops.rle_lanes_mixed): runs are
        disjoint order intervals, so [t, t+dlen) fully covers every run
        it touches except at most the two holding its endpoints — flip
        the full covers plane-wide, fix up the <= 2 partial runs by
        order lookup, and count covered DEAD runs toward the
        idempotency total (`double_delete.rs:6-9`).  Any ``dlen`` in
        one step — no dmax pre-chunking."""
        bo = ordp[:]
        bl = lenp[:]
        so = jnp.abs(bo) - 1
        occ = bo != 0
        cs = jnp.clip(t - so, 0, bl)
        ce = jnp.clip(t + dlen - so, 0, bl)
        cov = jnp.where(occ, ce - cs, 0)
        tot = jnp.max(jnp.sum(cov, axis=0))

        @pl.when(tot < dlen)
        def _bad():
            err_ref[1:2, :] = jnp.ones((1, B), jnp.int32)

        live = bo > 0
        full = live & (cov > 0) & (cov == bl)
        # Flip plane-wide; per-slot live counts drop by each block's
        # flipped chars (raw counts are unchanged by tombstoning).
        # Block sums come off ONE plane cumsum via static row reads
        # (Mosaic has no [NB, K, B] reshape), then gather to logical
        # slots through ``blkord`` (NB masked adds on the tiny table).
        ordp[:] = jnp.where(full, -bo, bo)
        cumfull = _cumsum_rows(jnp.where(full, bl, 0))
        g = jnp.zeros((NBL, B), jnp.int32)
        for b_ in range(NB):
            hi = cumfull[(b_ + 1) * K - 1][jnp.newaxis, :]
            lo = (cumfull[b_ * K - 1][jnp.newaxis, :] if b_ > 0
                  else jnp.zeros((1, B), jnp.int32))
            g = g + jnp.where(blkord[:] == b_, hi - lo, 0)
        liv[:] = liv[:] - g
        cumliv[:] = cumliv[:] - _cumsum_rows(g)

        # The <= 2 live partial runs each contain an endpoint; relocate
        # by order (splits move rows) and 3-way split them.
        retire_endpoint(t, dlen, t + dlen - 1)
        retire_endpoint(t, dlen, t)

    # ---- dispatch -------------------------------------------------------

    def op_body(k, _):
        kind = kind_ref[k]
        p = pos_ref[k]
        d = dlen_ref[k]
        il = ilen_ref[k]
        st = start_ref[k]

        @pl.when((kind == KIND_LOCAL) & (d > 0))
        def _():
            do_local_delete(p, d)

        @pl.when((kind == KIND_LOCAL) & (il > 0))
        def _():
            do_local_insert(k, p, il, st)

        @pl.when((kind == KIND_REMOTE_INS) & (il > 0))
        def _():
            do_remote_insert(k, rk_ref[k], olop_ref[k], orop_ref[k], il, st)

        @pl.when(kind == KIND_REMOTE_DEL)
        def _():
            do_remote_delete(dtgt_ref[k], d)

        return 0

    lax.fori_loop(0, CHUNK, op_body, 0)

    @pl.when(i == last)
    def _flush():
        blk_out[:] = blkord[:][jnp.newaxis]
        rows_out[:] = rws[:][jnp.newaxis]
        row0 = lax.broadcasted_iota(jnp.int32, (1, 8, B), 1) == 0
        meta_out[:] = jnp.where(row0, meta[0], 0)


def make_replayer_rle_mixed(
    ops: OpTensors,
    capacity: int,
    batch: int = 128,
    block_k: int = 256,
    chunk: int = 1024,
    interpret: bool = False,
    fast_integrate: bool = True,
):
    """Stage a mixed local/remote op stream on the RUN representation and
    build a jitted replayer.

    ``capacity`` counts RUN rows (`ops.rle` contract).  Remote deletes
    of any length apply in one step (the one-pass interval delete needs
    no dmax pre-chunking); insert chunks must be <= 128 chars (the
    order-table write window).
    """
    kinds = np.asarray(ops.kind)
    _require(kinds.ndim == 1, "rle-mixed engine takes one shared stream")
    require_unfused(ops, "the rle-mixed engine")
    _require(capacity % block_k == 0,
             f"capacity ({capacity}) must be a multiple of block_k "
             f"({block_k})")
    _require(interpret or chunk % 1024 == 0 or (
        jax.default_backend() != "tpu"),
        "chunk must be a multiple of 1024 on TPU")
    NB = capacity // block_k
    _require(NB >= 1, "need at least one block")
    _require(block_k >= 8, "block_k must hold a few runs")
    _require(ops.lmax <= LANES, (
        f"insert chunks must be <= {LANES} chars for the order-table "
        f"window (compile with lmax<={LANES})"))

    # By-order tables: everything the compiler knows (remote origins,
    # within-run chains, ranks), packed 128 orders/row, i32 (ROOT -> -1
    # by u32 wraparound).  One spare tail row for the 2-row run writes.
    total_orders = int(np.asarray(ops.order_advance, dtype=np.int64).sum())
    ocap = max(total_orders + ops.lmax, LANES)
    OT = (ocap + LANES - 1) // LANES + 1
    OT = ((OT + 7) // 8) * 8
    doc0 = prefill_logs(make_flat_doc(8, OT * LANES), ops)

    def table(x):
        return jnp.asarray(
            np.asarray(x, dtype=np.uint32).view(np.int32).reshape(OT, LANES))

    oll0 = table(doc0.ol_log)
    orl0 = table(doc0.or_log)
    rkl0 = table(doc0.rank_log)

    s = ops.num_steps
    s_pad = max(((s + chunk - 1) // chunk) * chunk, chunk)
    pad = ((0, s_pad - s),)

    def padded(a):
        return jnp.asarray(np.pad(
            np.asarray(a, dtype=np.uint32).view(np.int32), pad))

    staged = tuple(padded(c) for c in (
        ops.kind, ops.pos, ops.del_len, ops.del_target, ops.origin_left,
        ops.origin_right, ops.rank, ops.ins_len, ops.ins_order_start))

    jitted = _build_mixed_call(s_pad, batch, capacity, block_k, chunk,
                               OT, interpret, fast_integrate)
    tables = (oll0, orl0, rkl0)

    def run() -> RleMixedResult:
        ol, orr, ordp, lenp, blk, rows, meta, err = jitted(*staged, *tables)
        return RleMixedResult(
            ordp=ordp, lenp=lenp, blkord=blk[0], rows=rows[0], meta=meta[0],
            ol=ol[:s], orr=orr[:s], err=err,
            block_k=block_k, num_blocks=NB, batch=batch)

    return run


@functools.lru_cache(maxsize=32)
def _build_mixed_call(s_pad: int, batch: int, capacity: int,
                      block_k: int, chunk: int, OT: int,
                      interpret: bool, fast_integrate: bool):
    """Shape-keyed cache: streams sharing one geometry share one traced
    kernel (a per-call pallas_call re-traced on every replayer build —
    the cost that capped the differential-fuzz drivers; the lanes
    engines already cached theirs)."""
    NB = capacity // block_k
    NBLp = max(8, NB)
    smem = lambda: pl.BlockSpec(
        (chunk,), lambda i: (i,), memory_space=pltpu.SMEM)

    def whole(shape):
        return pl.BlockSpec(shape, lambda i: tuple(0 for _ in shape),
                            memory_space=pltpu.VMEM)

    call = pl.pallas_call(
        partial(_mixed_rle_kernel, K=block_k, NB=NB, NBL=NBLp, CHUNK=chunk,
                OT=OT, FAST=fast_integrate),
        grid=(s_pad // chunk,),
        in_specs=[smem() for _ in range(9)] + [
            whole((OT, LANES)), whole((OT, LANES)), whole((OT, LANES))],
        out_specs=[
            pl.BlockSpec((chunk, batch), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((chunk, batch), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            whole((capacity, batch)),
            whole((capacity, batch)),
            whole((1, NBLp, batch)),
            whole((1, NBLp, batch)),
            whole((1, 8, batch)),
            whole((8, batch)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s_pad, batch), jnp.uint32),
            jax.ShapeDtypeStruct((s_pad, batch), jnp.uint32),
            jax.ShapeDtypeStruct((capacity, batch), jnp.int32),
            jax.ShapeDtypeStruct((capacity, batch), jnp.int32),
            jax.ShapeDtypeStruct((1, NBLp, batch), jnp.int32),
            jax.ShapeDtypeStruct((1, NBLp, batch), jnp.int32),
            jax.ShapeDtypeStruct((1, 8, batch), jnp.int32),
            jax.ShapeDtypeStruct((8, batch), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((NBLp, batch), jnp.int32),       # blkord
            pltpu.VMEM((NBLp, batch), jnp.int32),       # rws
            pltpu.VMEM((NBLp, batch), jnp.int32),       # liv
            pltpu.VMEM((NBLp, batch), jnp.int32),       # raw
            pltpu.VMEM((NBLp, batch), jnp.int32),       # cumliv
            pltpu.VMEM((NBLp, batch), jnp.int32),       # cumraw
            pltpu.VMEM((OT, LANES), jnp.int32),         # ordblk
            pltpu.VMEM((OT, LANES), jnp.int32),         # ol table
            pltpu.VMEM((OT, LANES), jnp.int32),         # or table
            pltpu.VMEM((capacity, batch), jnp.int32),   # olp (run aux)
            pltpu.VMEM((capacity, batch), jnp.int32),   # orp
            pltpu.VMEM((capacity, batch), jnp.int32),   # rkp
            pltpu.VMEM((capacity, batch), jnp.int32),   # lpp
            pltpu.SMEM((2,), jnp.int32),                # meta
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=110 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return jax.jit(lambda *a: call(*a))


def replay_mixed_rle(ops: OpTensors, capacity: int, **kw) -> RleMixedResult:
    """One-shot convenience wrapper over ``make_replayer_rle_mixed``."""
    return make_replayer_rle_mixed(ops, capacity, **kw)()

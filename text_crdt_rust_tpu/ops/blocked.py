"""Blocked Pallas replay engine: the whole edit stream in ONE kernel.

The flat engine (``ops.flat``) pays two costs per op: it touches the full
capacity and — dominating in practice — it dispatches ~20 XLA kernels per
scanned step (~100us of fixed overhead on the bench chip). This engine is
the TPU-native answer to the reference's B-tree (`src/range_tree/`): one
``pallas_call`` applies the *entire* compiled local-edit stream, holding the
document in VMEM as fixed-size blocks:

- state is ``signed`` rows (same ±(order+1) encoding as ``span_arrays``)
  laid out as ``NB`` blocks of ``K`` rows, occupied rows packed at each
  block's front — the VMEM analog of B-tree leaves (`mod.rs:36-39`);
- per-block live counts replace the internal nodes' subtree sums
  (`mod.rs:85-93`): position→block is a cumsum+compare over ``NB`` scalars,
  position→row a cumsum over one ``K``-row block — O(NB + K) per op
  instead of O(capacity);
- inserts splice one block with static power-of-two rolls (the
  ``ops.flat`` shift trick) — block b's packed slack absorbs them, the
  analog of the reference's leaf-append fast path (`mutations.rs:57-109`);
- deletes flip signs inside a 2-block window walked across the span
  (`mutations.rs:520-570`);
- a block overflow triggers a global *rebalance* — compact all packed rows
  and redeal them evenly — replacing the B-tree's node-split bubbling
  (`mutations.rs:623-808`) with an O(capacity) pass that amortizes to
  nothing (a block absorbs K-fill inserts between rebalances);
- documents batch in the LANE dimension: every vector op processes
  ``batch`` docs at once, all replaying one shared op stream (the
  `BASELINE.json` config-2 shape: N identical docs, `benches/yjs.rs:41-48`
  run batched). Per-doc divergent streams stay on ``ops.flat``.

Origins a local insert discovers (`doc.rs:447-453`) are emitted per step
and merged into the by-order logs host-side, so the kernel's result
converts to a full ``span_arrays.FlatDoc`` — bit-identical to the flat
engine's.
"""
from __future__ import annotations

import dataclasses
import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..common import ROOT_ORDER
from .batch import KIND_LOCAL, OpTensors, prefill_logs, require_unfused
from .flat import _order_of
from .span_arrays import FlatDoc, I32, U32, make_flat_doc


def _require(cond: bool, msg: str) -> None:
    """Config/capacity precheck that must fire even under ``python -O``
    (a violated precondition corrupts device state silently, no crash)."""
    if not cond:
        raise ValueError(msg)


def _lane_scalar(x2d) -> jax.Array:
    """Row-sum then lane-max: collapse a lane-replicated [rows, B] value to
    one scalar. Valid because every doc (lane) replays the same stream, so
    all lanes hold identical control state."""
    return jnp.max(jnp.sum(x2d, axis=0))


def _cumsum_rows(x) -> jax.Array:
    """Inclusive cumsum along the (sublane) row axis via log2 roll-adds."""
    n = x.shape[0]
    row = lax.broadcasted_iota(jnp.int32, x.shape, 0)
    out = x
    shift = 1
    while shift < n:
        out = out + jnp.where(row >= shift, pltpu.roll(out, shift, axis=0), 0)
        shift *= 2
    return out


def _shift_rows(x, amount, max_amount: int) -> jax.Array:
    """Rows shifted toward higher indices by dynamic ``amount``
    (0..max_amount) — one static roll per bit (``flat._shift_right``)."""
    out = x
    for b in range(max(max_amount, 1).bit_length()):
        out = jnp.where((amount >> b) & 1 != 0,
                        pltpu.roll(out, 1 << b, axis=0), out)
    return out


class _BlockOps:
    """The shared VMEM block-grid op set, closed over a kernel's scratch
    refs. Both ``blocked`` and ``blocked_mixed`` build their kernels on
    these — one implementation of the descent, the rebalance (node-split
    analog) and the windowed local delete, so the engines cannot drift.
    """

    def __init__(self, sig, rws, liv, tmp, err_ref, *, K, NB, LMAX):
        self.sig, self.rws, self.liv, self.tmp = sig, rws, liv, tmp
        self.err_ref = err_ref
        self.K, self.NB, self.LMAX = K, NB, LMAX
        self.B = sig.shape[1]
        self.idx_nb = lax.broadcasted_iota(jnp.int32, rws.shape, 0)
        self.idx_k = lax.broadcasted_iota(jnp.int32, (K, self.B), 0)
        self.idx_2k = lax.broadcasted_iota(jnp.int32, (2 * K, self.B), 0)

    def live_before_block(self, b):
        return _lane_scalar(jnp.where(self.idx_nb < b, self.liv[:], 0))

    def raw_before_block(self, b):
        return _lane_scalar(jnp.where(self.idx_nb < b, self.rws[:], 0))

    def block_of_rank(self, rank1):
        """Smallest block whose cumulative live count reaches ``rank1``
        (the B-tree descent `root.rs:54-88` over block sums)."""
        cumlive = _cumsum_rows(
            jnp.where(self.idx_nb < self.NB, self.liv[:], 0))
        hits = (cumlive < rank1) & (self.idx_nb < self.NB)
        return jnp.max(jnp.sum(hits.astype(jnp.int32), axis=0))

    def block_rows(self, b):
        return _lane_scalar(jnp.where(self.idx_nb == b, self.rws[:], 0))

    def total_raw(self):
        return _lane_scalar(jnp.where(self.idx_nb < self.NB, self.rws[:], 0))

    def rebalance(self):
        """Compact all packed rows, redeal evenly (`mutations.rs:623-808`
        analog). O(cap); triggered only on block overflow."""
        K, NB, B = self.K, self.NB, self.B
        sig, rws, liv, tmp = self.sig, self.rws, self.liv, self.tmp
        total = self.total_raw()
        fill = (total + NB - 1) // NB
        err_ref = self.err_ref

        @pl.when(fill > K - self.LMAX)
        def _overflow():
            err_ref[0:1, :] = jnp.ones((1, B), jnp.int32)

        def compact(j, off):
            rows_j = self.block_rows(j)
            tmp[pl.ds(off, K), :] = sig[pl.ds(j * K, K), :]
            return off + rows_j

        lax.fori_loop(0, NB, compact, 0)

        def deal(j, _):
            rows_j = jnp.clip(total - j * fill, 0, fill)
            blk = tmp[pl.ds(j * fill, K), :]
            nblk = jnp.where(self.idx_k < rows_j, blk, 0)
            sig[pl.ds(j * K, K), :] = nblk
            rws[pl.ds(j, 1), :] = jnp.broadcast_to(rows_j, (1, B))
            liv[pl.ds(j, 1), :] = jnp.sum(
                (nblk > 0).astype(jnp.int32), axis=0, keepdims=True)
            return 0

        lax.fori_loop(0, NB, deal, 0)

    def local_delete(self, p, d):
        """Tombstone ``d`` live chars after content pos ``p``
        (`mutations.rs:520-570`); walks 2-block windows across the span."""
        K, NB = self.K, self.NB
        sig, liv = self.sig, self.liv
        err_ref = self.err_ref

        def body(carry):
            rem, iters = carry
            b = jnp.minimum(self.block_of_rank(p + 1), NB - 2)
            base = self.live_before_block(b)
            win = sig[pl.ds(b * K, 2 * K), :]
            wlive = win > 0
            rank = base + _cumsum_rows(wlive.astype(jnp.int32))
            flip = wlive & (rank > p) & (rank <= p + rem)
            sig[pl.ds(b * K, 2 * K), :] = jnp.where(flip, -win, win)
            fcounts = flip.astype(jnp.int32)
            f0 = _lane_scalar(jnp.where(self.idx_2k < K, fcounts, 0))
            f1 = _lane_scalar(jnp.where(self.idx_2k >= K, fcounts, 0))
            liv[pl.ds(b, 1), :] = liv[pl.ds(b, 1), :] - f0
            liv[pl.ds(b + 1, 1), :] = liv[pl.ds(b + 1, 1), :] - f1
            return rem - f0 - f1, iters + 1

        # Iteration bound: each window contains >= 1 target char for a
        # valid stream, so NB+1 windows means the delete ran off the
        # document (invalid op) — flag instead of hanging the chip.
        rem, iters = lax.while_loop(
            lambda c: (c[0] > 0) & (c[1] <= NB), body, (d, 0))

        @pl.when(rem > 0)
        def _bad_delete():
            err_ref[1:2, :] = jnp.ones((1, self.B), jnp.int32)

    def local_insert_block(self, p):
        """(block, occupied rows) an insert at live rank ``p`` targets —
        the cheap pre-check before the overflow rebalance."""
        b = jnp.where(p == 0, 0, self.block_of_rank(p))
        return b, self.block_rows(b)

    def local_insert_target(self, p):
        """(block, row-cursor, block-rows, origins) for a local insert at
        live rank ``p``, with the overflow rebalance already handled.
        Origins per `doc.rs:447-453`: raw successor without skipping
        tombstones."""
        K, NB = self.K, self.NB
        sig, rws = self.sig, self.rws
        idx_k, idx_nb = self.idx_k, self.idx_nb

        b, r0 = self.local_insert_block(p)
        local_rank = p - self.live_before_block(b)
        blk = sig[pl.ds(b * K, K), :]
        bcum = _cumsum_rows((blk > 0).astype(jnp.int32))
        c0 = jnp.max(jnp.sum(
            (bcum < local_rank).astype(jnp.int32), axis=0))
        c = jnp.where(p == 0, 0, c0 + 1)

        left_signed = _lane_scalar(jnp.where(idx_k == c - 1, blk, 0))
        succ_here = _lane_scalar(jnp.where(idx_k == c, blk, 0))
        nb_next = jnp.max(jnp.min(jnp.where(
            (idx_nb > b) & (idx_nb < NB) & (rws[:] > 0), idx_nb, NB),
            axis=0))
        nxt = sig[pl.ds(jnp.minimum(nb_next, NB - 1) * K, K), :]
        succ_next = _lane_scalar(jnp.where(idx_k == 0, nxt, 0))
        succ_signed = jnp.where(c < r0, succ_here,
                                jnp.where(nb_next < NB, succ_next, 0))
        return b, c, r0, left_signed, succ_signed


def _replay_kernel(
    pos_ref, dlen_ref, ilen_ref, start_ref,     # [CHUNK] SMEM op columns
    ol_ref, or_ref,                             # [CHUNK,B] VMEM outputs
    sig_out_ref, rows_out_ref, err_ref,         # final state outputs
    sig, rws, liv, tmp,                         # VMEM scratch
    *, K: int, NB: int, CHUNK: int, LMAX: int,
):
    B = sig.shape[1]
    i = pl.program_id(0)
    last = pl.num_programs(0) - 1
    ops_ = _BlockOps(sig, rws, liv, tmp, err_ref, K=K, NB=NB, LMAX=LMAX)
    idx_k = ops_.idx_k
    root_u = jnp.uint32(ROOT_ORDER)

    # Each grid step owns a fresh [CHUNK, B] origin-output block; rows for
    # steps with ins_len == 0 would otherwise be uninitialized VMEM garbage.
    ol_ref[:] = jnp.zeros_like(ol_ref)
    or_ref[:] = jnp.zeros_like(or_ref)

    @pl.when(i == 0)
    def _init():
        # Cold start: empty document (warm start re-uploads via
        # blocked_to_flat -> flat engine for now).
        sig[:] = jnp.zeros_like(sig)
        rws[:] = jnp.zeros_like(rws)
        liv[:] = jnp.zeros_like(liv)
        err_ref[:] = jnp.zeros_like(err_ref)

    def do_insert(k, p, il, st):
        """Splice ``il`` new items after live rank ``p`` into one block
        (`mutations.rs:17-179`; packed slack instead of node splits)."""
        _, r0 = ops_.local_insert_block(p)

        @pl.when(r0 + il > K)
        def _rb():
            ops_.rebalance()

        b, c, r0, left_signed, succ_signed = ops_.local_insert_target(p)
        left = jnp.where(p == 0, root_u, _order_of(left_signed))
        right = jnp.where(succ_signed == 0, root_u, _order_of(succ_signed))

        blk = sig[pl.ds(b * K, K), :]
        shifted = _shift_rows(blk, il, LMAX)
        new_vals = st + (idx_k - c) + 1
        nblk = jnp.where(idx_k < c, blk,
                         jnp.where(idx_k < c + il, new_vals, shifted))
        sig[pl.ds(b * K, K), :] = nblk
        rws[pl.ds(b, 1), :] = rws[pl.ds(b, 1), :] + il
        liv[pl.ds(b, 1), :] = liv[pl.ds(b, 1), :] + il

        ol_ref[pl.ds(k, 1), :] = jnp.broadcast_to(left, (1, B))
        or_ref[pl.ds(k, 1), :] = jnp.broadcast_to(right, (1, B))

    def op_body(k, _):
        p = pos_ref[k]
        d = dlen_ref[k]
        il = ilen_ref[k]
        st = start_ref[k]

        @pl.when(d > 0)
        def _():
            ops_.local_delete(p, d)

        @pl.when(il > 0)
        def _():
            do_insert(k, p, il, st)

        return 0

    lax.fori_loop(0, CHUNK, op_body, 0)

    @pl.when(i == last)
    def _flush():
        sig_out_ref[:] = sig[:]
        rows_out_ref[:] = rws[:]


@dataclasses.dataclass
class BlockedResult:
    """Device outputs of one ``replay_local`` call.

    Everything stays on device until read; call ``check()`` (or convert
    via ``blocked_to_flat``, which checks) to surface kernel error flags —
    the device↔host round-trip is ~100ms on a tunneled chip, so the
    kernel never syncs eagerly.
    """

    signed: jax.Array   # i32[CAP, B] blocked rows (packed per block)
    rows: jax.Array     # i32[NBp, B] occupied rows per block
    ol: jax.Array       # u32[S, B]  per-step local origin_left
    orr: jax.Array      # u32[S, B]  per-step local origin_right
    err: jax.Array      # i32[8, B]  row 0: capacity exhausted; row 1: bad delete
    block_k: int
    num_blocks: int
    batch: int

    def check(self) -> None:
        # Explicit raises, not assert: these surface device error flags and
        # must fire even under ``python -O``.
        err = np.asarray(self.err)
        if err[0].max() != 0:
            raise RuntimeError(
                "blocked engine capacity exhausted (rebalance found fill > "
                "K-lmax); raise capacity")
        if err[1].max() != 0:
            raise RuntimeError(
                "delete ran past the end of the document (invalid op stream)")
        if err[2].max() != 0:
            raise RuntimeError(
                "remote op referenced an order not present in the document "
                "(bad origin or delete target)")


def make_replayer(
    ops: OpTensors,
    capacity: int,
    batch: int = 128,
    block_k: int = 256,
    chunk: int = 1024,
    interpret: bool = False,
):
    """Stage ``ops`` on device and build a reusable jitted replayer.

    Returns a zero-argument callable producing a ``BlockedResult``; the
    op upload and the pallas trace/compile happen once, so repeated calls
    pay only kernel execution (bench steady state).
    """
    kinds = np.asarray(ops.kind)
    _require(kinds.ndim == 1, "blocked engine takes one shared stream")
    _require(bool((kinds == KIND_LOCAL).all()),
             "blocked engine replays local streams; remote ops -> ops.flat")
    require_unfused(ops, "the blocked engine")
    _require(capacity % block_k == 0,
             f"capacity ({capacity}) must be a multiple of block_k "
             f"({block_k})")
    # Rank-1 i32 arrays tile at T(1024) on TPU; the SMEM op blocks must
    # match that layout (smaller streams fall back to one whole-array
    # block via s_pad == chunk).
    _require(interpret or chunk % 1024 == 0 or (
        jax.default_backend() != "tpu"),
        "chunk must be a multiple of 1024 on TPU")
    NB = capacity // block_k
    _require(NB >= 2, "need at least two blocks (delete window)")
    NBp = max(8, NB)
    lmax = ops.lmax
    _require(block_k > lmax, (
        f"block_k ({block_k}) must exceed the insert chunk width "
        f"({lmax}); a full block could never absorb an insert"))
    rows_needed = int(np.asarray(ops.ins_len, dtype=np.int64).sum())
    rows_limit = NB * (block_k - lmax)
    _require(rows_needed <= rows_limit, (
        f"stream inserts {rows_needed} rows but {NB} blocks of "
        f"{block_k} hold at most {rows_limit} at the rebalance fill "
        f"limit (K-lmax); raise capacity"))

    s = ops.num_steps
    s_pad = max(((s + chunk - 1) // chunk) * chunk, chunk)
    pad = ((0, s_pad - s),)

    def padded(a):
        return jnp.asarray(np.pad(np.asarray(a, dtype=np.int32), pad))

    staged = (padded(ops.pos), padded(ops.del_len), padded(ops.ins_len),
              padded(ops.ins_order_start))

    jitted = _build_call(s_pad, batch, capacity, block_k, chunk, lmax,
                         interpret)

    def run() -> BlockedResult:
        ol, orr, signed, rows, err = jitted(*staged)
        return BlockedResult(
            signed=signed, rows=rows, ol=ol[:s], orr=orr[:s], err=err,
            block_k=block_k, num_blocks=NB, batch=batch)

    return run


@functools.lru_cache(maxsize=32)
def _build_call(s_pad: int, batch: int, capacity: int, block_k: int,
                chunk: int, lmax: int, interpret: bool):
    """Shape-keyed cache (the ``rle_lanes._build_call`` pattern):
    same-shape replays share one traced kernel — a per-call
    ``jax.jit(lambda ...)`` re-traces the whole interpret program each
    time, which dominates the fixed-shape test suites."""
    NB = capacity // block_k
    NBp = max(8, NB)

    smem = lambda: pl.BlockSpec(
        (chunk,), lambda i: (i,), memory_space=pltpu.SMEM)

    def whole(shape):
        return pl.BlockSpec(shape, lambda i: tuple(0 for _ in shape),
                            memory_space=pltpu.VMEM)

    call = pl.pallas_call(
        partial(_replay_kernel, K=block_k, NB=NB, CHUNK=chunk, LMAX=lmax),
        grid=(s_pad // chunk,),
        in_specs=[smem(), smem(), smem(), smem()],
        out_specs=[
            pl.BlockSpec((chunk, batch), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((chunk, batch), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            whole((capacity, batch)),
            whole((NBp, batch)),
            whole((8, batch)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s_pad, batch), jnp.uint32),
            jax.ShapeDtypeStruct((s_pad, batch), jnp.uint32),
            jax.ShapeDtypeStruct((capacity, batch), jnp.int32),
            jax.ShapeDtypeStruct((NBp, batch), jnp.int32),
            jax.ShapeDtypeStruct((8, batch), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((capacity, batch), jnp.int32),
            pltpu.VMEM((NBp, batch), jnp.int32),
            pltpu.VMEM((NBp, batch), jnp.int32),
            pltpu.VMEM((capacity + block_k, batch), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            # The default 16MB scoped-vmem cap rejects big documents; the
            # chip has 128MB of VMEM.
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return jax.jit(lambda a, b, c, d: call(a, b, c, d))


def replay_local(
    ops: OpTensors,
    capacity: int,
    batch: int = 128,
    block_k: int = 256,
    chunk: int = 1024,
    interpret: bool = False,
) -> BlockedResult:
    """One-shot convenience wrapper over ``make_replayer``."""
    return make_replayer(ops, capacity, batch=batch, block_k=block_k,
                         chunk=chunk, interpret=interpret)()


def blocked_to_flat(
    ops: OpTensors,
    res: BlockedResult,
    capacity: int | None = None,
    order_capacity: int | None = None,
    doc_index: int = 0,
) -> FlatDoc:
    """Kernel result -> a standard ``FlatDoc`` (one doc of the batch):
    concatenate each block's packed rows, prefill the by-order logs, then
    merge the kernel's per-step local origins."""
    res.check()
    sig = np.asarray(res.signed)[:, doc_index]
    r = np.asarray(res.rows)[:, doc_index]
    K, NB = res.block_k, res.num_blocks
    parts = [sig[b * K: b * K + r[b]] for b in range(NB)]
    flat = np.concatenate(parts) if parts else np.zeros(0, np.int32)
    n = len(flat)

    if capacity is None:
        capacity = max(res.signed.shape[0], n)
    doc = make_flat_doc(capacity, order_capacity)
    doc = prefill_logs(doc, ops)
    ol_log = np.array(doc.ol_log)
    or_log = np.array(doc.or_log)
    starts = np.asarray(ops.ins_order_start, dtype=np.int64)
    ilens = np.asarray(ops.ins_len, dtype=np.int64)
    ol_np = np.asarray(res.ol)[:, doc_index]
    or_np = np.asarray(res.orr)[:, doc_index]
    for st, il, left, right in zip(starts, ilens, ol_np, or_np):
        if il > 0:
            ol_log[st] = left
            or_log[st: st + il] = right

    signed_col = np.zeros(capacity, np.int32)
    signed_col[:n] = flat
    advance = int(np.asarray(ops.order_advance, dtype=np.int64).sum())
    return dataclasses.replace(
        doc,
        signed=jnp.asarray(signed_col),
        ol_log=jnp.asarray(ol_log),
        or_log=jnp.asarray(or_log),
        n=jnp.asarray(n, I32),
        next_order=jnp.asarray(advance, U32),
    )

"""Per-lane K-row block machinery shared by the BLOCKED streaming
engines (``rle_lanes`` / ``rle_lanes_mixed``).

The un-blocked lanes engines pay a whole-``[CAP, B]`` plane pass (plus a
log2(CAP) roll cumsum) on every step.  The blocked layout is ``ops.rle``'s
structure carried into the per-lane world: runs live in K-row physical
blocks, per-lane logical block tables (`mutations.rs:623-669`'s leaf
locality) order them, and a step touches NB block sums plus ONE K-row
block — O(NB + K) rows instead of O(CAP log CAP).

The per-lane twist vs ``ops.rle``: every block index is a ``[1, B]``
LANE VECTOR, not a scalar, so blocks cannot be addressed with a dynamic
slice.  Gather/scatter instead run an NB-way select chain over static
K-row slices — one plane-read equivalent — and every in-block pass
(cumsum, splice, 3-way split) then costs K rows, which is where the
win lives (K << CAP on the config-5/5r shapes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.pallas import tpu as pltpu


def vshift_up(x, amt, max_amt: int) -> jax.Array:
    """Rows shifted toward LOWER indices by per-lane ``amt`` in
    [0, max_amt] (the lane-vector twin of ``rle._shift_rows_up``):
    out[j, b] = x[j + amt[0, b], b].  Binary decomposition: one static
    roll per bit, selected per lane."""
    n = x.shape[0]
    out = x
    for bit in range(max(max_amt, 1).bit_length()):
        s = (1 << bit) % n
        if s:
            out = jnp.where((amt >> bit) & 1 != 0,
                            pltpu.roll(out, n - s, axis=0), out)
    return out


def gather_block(plane_ref, b, K: int, NB: int) -> jax.Array:
    """Per-lane block gather: ``out[j, lane] = plane[b[0,lane]*K + j,
    lane]`` as one NB-way select chain over static K-row slices."""
    ws = plane_ref[0:K, :]
    for nb in range(1, NB):
        ws = jnp.where(b == nb, plane_ref[nb * K:(nb + 1) * K, :], ws)
    return ws


def gather_head(plane_ref, b, K: int, NB: int) -> jax.Array:
    """Row 0 of per-lane block ``b`` as a [1, B] vector."""
    h = plane_ref[0:1, :]
    for nb in range(1, NB):
        h = jnp.where(b == nb, plane_ref[nb * K: nb * K + 1, :], h)
    return h


def scatter_block(plane_ref, b, ws, act, K: int, NB: int) -> None:
    """Write ``ws`` back to per-lane block ``b`` on ``act`` lanes."""
    for nb in range(NB):
        cur = plane_ref[nb * K:(nb + 1) * K, :]
        plane_ref[nb * K:(nb + 1) * K, :] = jnp.where(
            act & (b == nb), ws, cur)


def scatter_block2(plane_ref, b1, ws1, b2, ws2, act, K: int,
                   NB: int) -> None:
    """Two-block scatter (block split: keep-half to ``b1``, moved half
    to the fresh block ``b2``; b1 != b2 per lane)."""
    for nb in range(NB):
        cur = plane_ref[nb * K:(nb + 1) * K, :]
        v = jnp.where(act & (b1 == nb), ws1, cur)
        plane_ref[nb * K:(nb + 1) * K, :] = jnp.where(
            act & (b2 == nb), ws2, v)


def oracle_runs(oracle):
    """RLE-compress a host oracle body into the lanes engines' run rows:
    ``(signed_starts, lens)`` — ±(order+1) of each run head and its
    length, in document order.

    A char extends the current run only when its order is consecutive,
    its tombstone flag matches, AND its ``origin_left`` chains to its
    predecessor.  The chain condition is load-bearing, not cosmetic:
    the kernels' YATA scan skips whole runs on the premise that every
    non-head char's origin_left is its own predecessor (`doc.rs`
    span-skip; see ``rle_lanes_mixed.do_remote_insert``'s merge
    predicate) — seeding a run across an unchained boundary would let a
    later concurrent-insert scan skip a char it must evaluate and land
    the insert at a diverged cursor."""
    n = oracle.n
    if n == 0:
        return (np.zeros(0, np.int64), np.zeros(0, np.int64))
    order = oracle.order[:n].astype(np.int64)
    deleted = oracle.deleted[:n]
    oleft = oracle.origin_left[:n].astype(np.int64)
    brk = np.ones(n, dtype=bool)
    brk[1:] = ((order[1:] != order[:-1] + 1)
               | (deleted[1:] != deleted[:-1])
               | (oleft[1:] != order[:-1]))
    starts = np.nonzero(brk)[0]
    lens = np.diff(np.append(starts, n)).astype(np.int64)
    sign = np.where(deleted[starts], -1, 1).astype(np.int64)
    return sign * (order[starts] + 1), lens


def pack_lane_blocks(signed_starts, lens, *, K: int, NB: int, NBT: int,
                     capacity: int):
    """Seed ONE lane's blocked state columns from a run list (the
    residency restore/upload path of ``serve.lanes_backend``): pack runs
    into K-row physical blocks at most ``(K-1)//2`` rows each — the same
    half-full occupancy a leaf split leaves, so every seeded block keeps
    the out-of-blocks row bound the serve capacity probe relies on AND
    immediate insert traffic never needs a split to find headroom.

    Returns ``(cols, run_block)``: the numpy state columns
    ``(ordp[capacity], lenp[capacity], nlog[1], blkord[NBT], rws[NBT],
    liv[NBT], raw[NBT])`` with blocks in identity logical order
    (blkord[l] = l), plus the run -> physical-block assignment
    (i64[R]) so hint seeding stays bit-consistent with the packing
    (one occupancy rule, one owner)."""
    R = len(signed_starts)
    per = max(1, (K - 1) // 2)
    nblocks = -(-R // per) if R else 0
    assert nblocks <= NB, (
        f"{R} runs need {nblocks} blocks of {per} rows but only {NB} "
        f"blocks exist (the fits_doc probe should have refused)")
    ordp = np.zeros(capacity, np.int32)
    lenp = np.zeros(capacity, np.int32)
    blkord = np.zeros(NBT, np.int32)
    rws = np.zeros(NBT, np.int32)
    liv = np.zeros(NBT, np.int32)
    raw = np.zeros(NBT, np.int32)
    for b in range(nblocks):
        lo, hi = b * per, min((b + 1) * per, R)
        rows = hi - lo
        ordp[b * K: b * K + rows] = signed_starts[lo:hi]
        lenp[b * K: b * K + rows] = lens[lo:hi]
        blkord[b] = b
        rws[b] = rows
        live = signed_starts[lo:hi] > 0
        liv[b] = int(lens[lo:hi][live].sum())
        raw[b] = int(lens[lo:hi].sum())
    nlog = np.asarray([max(nblocks, 1)], np.int32)
    run_block = np.arange(R, dtype=np.int64) // per
    return (ordp, lenp, nlog, blkord, rws, liv, raw), run_block


def lane_apply_partial(a, i_p, bo, bl, cs, ce, idx):
    """Split run row ``i_p`` around its covered live sub-range
    ``[cs, ce)`` into [head?] [tombstone mid] [tail?] (<= +2 rows), per
    lane where ``a`` — the per-lane 3-way delete split shared by the
    blocked kernels (the in-block twin of the whole-plane transform in
    ``rle_lanes.do_delete`` / ``rle_lanes_mixed.apply_partial``).
    ``idx`` is the row iota of the plane being edited."""
    from .rle_lanes import _vrow, _vshift

    o = _vrow(bo, i_p)
    ln = _vrow(bl, i_p)
    cs_i = _vrow(cs, i_p)
    ce_i = _vrow(ce, i_p)
    cov_i = ce_i - cs_i
    has_head = (cs_i > 0) & a
    has_tail = (ce_i < ln) & a
    amt = has_head.astype(jnp.int32) + has_tail.astype(jnp.int32)
    so = _vshift(bo, amt)
    sl = _vshift(bl, amt)
    no = jnp.where(idx <= i_p, bo, so)
    nl = jnp.where(idx <= i_p, bl, sl)
    p0o = jnp.where(has_head, o, -(o + cs_i))
    p0l = jnp.where(has_head, cs_i, cov_i)
    p1o = jnp.where(has_head, -(o + cs_i), o + ce_i)
    p1l = jnp.where(has_head, cov_i, ln - ce_i)
    w0 = a & (idx == i_p)
    no = jnp.where(w0, p0o, no)
    nl = jnp.where(w0, p0l, nl)
    w1 = a & (idx == i_p + 1) & (amt >= 1)
    no = jnp.where(w1, p1o, no)
    nl = jnp.where(w1, p1l, nl)
    w2 = a & (idx == i_p + 2) & (amt == 2)
    no = jnp.where(w2, o + ce_i, no)
    nl = jnp.where(w2, ln - ce_i, nl)
    return no, nl, amt

"""Per-lane K-row block machinery shared by the BLOCKED streaming
engines (``rle_lanes`` / ``rle_lanes_mixed``).

The un-blocked lanes engines pay a whole-``[CAP, B]`` plane pass (plus a
log2(CAP) roll cumsum) on every step.  The blocked layout is ``ops.rle``'s
structure carried into the per-lane world: runs live in K-row physical
blocks, per-lane logical block tables (`mutations.rs:623-669`'s leaf
locality) order them, and a step touches NB block sums plus ONE K-row
block — O(NB + K) rows instead of O(CAP log CAP).

The per-lane twist vs ``ops.rle``: every block index is a ``[1, B]``
LANE VECTOR, not a scalar, so blocks cannot be addressed with a dynamic
slice.  Gather/scatter instead run an NB-way select chain over static
K-row slices — one plane-read equivalent — and every in-block pass
(cumsum, splice, 3-way split) then costs K rows, which is where the
win lives (K << CAP on the config-5/5r shapes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu


def vshift_up(x, amt, max_amt: int) -> jax.Array:
    """Rows shifted toward LOWER indices by per-lane ``amt`` in
    [0, max_amt] (the lane-vector twin of ``rle._shift_rows_up``):
    out[j, b] = x[j + amt[0, b], b].  Binary decomposition: one static
    roll per bit, selected per lane."""
    n = x.shape[0]
    out = x
    for bit in range(max(max_amt, 1).bit_length()):
        s = (1 << bit) % n
        if s:
            out = jnp.where((amt >> bit) & 1 != 0,
                            pltpu.roll(out, n - s, axis=0), out)
    return out


def gather_block(plane_ref, b, K: int, NB: int) -> jax.Array:
    """Per-lane block gather: ``out[j, lane] = plane[b[0,lane]*K + j,
    lane]`` as one NB-way select chain over static K-row slices."""
    ws = plane_ref[0:K, :]
    for nb in range(1, NB):
        ws = jnp.where(b == nb, plane_ref[nb * K:(nb + 1) * K, :], ws)
    return ws


def gather_head(plane_ref, b, K: int, NB: int) -> jax.Array:
    """Row 0 of per-lane block ``b`` as a [1, B] vector."""
    h = plane_ref[0:1, :]
    for nb in range(1, NB):
        h = jnp.where(b == nb, plane_ref[nb * K: nb * K + 1, :], h)
    return h


def scatter_block(plane_ref, b, ws, act, K: int, NB: int) -> None:
    """Write ``ws`` back to per-lane block ``b`` on ``act`` lanes."""
    for nb in range(NB):
        cur = plane_ref[nb * K:(nb + 1) * K, :]
        plane_ref[nb * K:(nb + 1) * K, :] = jnp.where(
            act & (b == nb), ws, cur)


def scatter_block2(plane_ref, b1, ws1, b2, ws2, act, K: int,
                   NB: int) -> None:
    """Two-block scatter (block split: keep-half to ``b1``, moved half
    to the fresh block ``b2``; b1 != b2 per lane)."""
    for nb in range(NB):
        cur = plane_ref[nb * K:(nb + 1) * K, :]
        v = jnp.where(act & (b1 == nb), ws1, cur)
        plane_ref[nb * K:(nb + 1) * K, :] = jnp.where(
            act & (b2 == nb), ws2, v)


def lane_apply_partial(a, i_p, bo, bl, cs, ce, idx):
    """Split run row ``i_p`` around its covered live sub-range
    ``[cs, ce)`` into [head?] [tombstone mid] [tail?] (<= +2 rows), per
    lane where ``a`` — the per-lane 3-way delete split shared by the
    blocked kernels (the in-block twin of the whole-plane transform in
    ``rle_lanes.do_delete`` / ``rle_lanes_mixed.apply_partial``).
    ``idx`` is the row iota of the plane being edited."""
    from .rle_lanes import _vrow, _vshift

    o = _vrow(bo, i_p)
    ln = _vrow(bl, i_p)
    cs_i = _vrow(cs, i_p)
    ce_i = _vrow(ce, i_p)
    cov_i = ce_i - cs_i
    has_head = (cs_i > 0) & a
    has_tail = (ce_i < ln) & a
    amt = has_head.astype(jnp.int32) + has_tail.astype(jnp.int32)
    so = _vshift(bo, amt)
    sl = _vshift(bl, amt)
    no = jnp.where(idx <= i_p, bo, so)
    nl = jnp.where(idx <= i_p, bl, sl)
    p0o = jnp.where(has_head, o, -(o + cs_i))
    p0l = jnp.where(has_head, cs_i, cov_i)
    p1o = jnp.where(has_head, -(o + cs_i), o + ce_i)
    p1l = jnp.where(has_head, cov_i, ln - ce_i)
    w0 = a & (idx == i_p)
    no = jnp.where(w0, p0o, no)
    nl = jnp.where(w0, p0l, nl)
    w1 = a & (idx == i_p + 1) & (amt >= 1)
    no = jnp.where(w1, p1o, no)
    nl = jnp.where(w1, p1l, nl)
    w2 = a & (idx == i_p + 2) & (amt == 2)
    no = jnp.where(w2, o + ce_i, no)
    nl = jnp.where(w2, ln - ce_i, nl)
    return no, nl, amt

"""Framework configuration layer (SURVEY §5 config row; r2 verdict A6).

The reference's knobs are compile-time consts and cargo features
(`Cargo.toml:39-41`, `range_tree/mod.rs:29-39`, `split_list/mod.rs:12-13`);
a JAX framework needs runtime configuration. One dataclass per surface,
a single source of defaults, and ``from_args`` parsers so every CLI
(bench, examples) shares the same knobs instead of growing private
argparse forests.
"""
from __future__ import annotations

import argparse
import dataclasses
from typing import Optional, Sequence

# The ONE canonical engine registry (advisor r3: bench.py and
# EngineConfig had drifted apart; VERDICT r5 weak #6: rle-lanes-mixed
# was missing from the choices entirely).  Maps the public engine name
# to its implementing module (relative to this package) and the bench
# configs that exercise it.  Every CLI choice list and bench dispatch
# derives from this dict; ``tests/test_engine_registry.py`` asserts the
# README table and bench.py name no engine outside it.
ENGINE_REGISTRY = {
    # ``fused_steps``: the engine's insert splice accepts FUSED multi-row
    # steps (``rows_per_step`` W > 1, the split-batch prepare for the
    # kevin prepend worst case).  Streams compiled with ``fuse_w`` > 1
    # may only run on engines carrying this flag; every other engine
    # rejects them at build time.
    "rle":             {"module": "ops.rle", "configs": ("northstar", "2", "3"),
                        "fused_steps": True},
    "rle-hbm":         {"module": "ops.rle_hbm", "configs": ("northstar", "kevin"),
                        "fused_steps": True},
    "rle-lanes":       {"module": "ops.rle_lanes", "configs": ("5",),
                        "fused_steps": True},
    "rle-mixed":       {"module": "ops.rle_mixed", "configs": ("4",)},
    # The blocked per-lane mixed engine serves two surfaces: the config
    # 5r streaming replay AND the document server's lane backend
    # (serve/lanes_backend.py carries the blocked state across ticks).
    "rle-lanes-mixed": {"module": "ops.rle_lanes_mixed",
                        "configs": ("5r", "serve", "serve-lanes"),
                        "fused_steps": True,
                        "serve_backend":
                            "serve.lanes_backend:LanesMixedLaneBackend"},
    "blocked":         {"module": "ops.blocked", "configs": ("northstar",)},
    "blocked-mixed":   {"module": "ops.blocked_mixed", "configs": ("4",)},
    "hbm":             {"module": "ops.blocked_hbm", "configs": ("northstar",)},
    # The serve batcher's device backends: ``serve_backend`` names the
    # LaneBackend class `serve.batcher.make_lane_backend` constructs —
    # registry-driven dispatch, no hardcoded engine asserts.  The
    # vmapped flat engine is the measured default; rle-lanes-mixed runs
    # the same serve surface at O(NB+K) touched rows/step.
    "flat":            {"module": "ops.flat", "configs": ("serve",),
                        "serve_backend": "serve.batcher:FlatLaneBackend"},
    # One huge doc sharded over the sp axis (bench --config sp).
    "sp-apply":        {"module": "parallel.sp_apply", "configs": ("sp",)},
}
ENGINE_CHOICES = tuple(ENGINE_REGISTRY)

# Bench-row labels that are not registry engine names: variants mapping
# to a registry engine, or host baselines (None) that have no device
# module.  The registry-consistency test walks bench.py and README
# through this map — any NEW label must land here or in the registry.
ENGINE_ROW_ALIASES = {
    "rle-groups": "rle",       # config 3: rle engine, doc-group grid axis
    "rle-hbm-fused": "rle-hbm",  # kevin: fused multi-row prepare steps
    "native-cpp": None,        # host C++ baseline
    "gap-buffer": None,        # text-only rope lower bound
}


def engines_for(config_key: str) -> tuple:
    """Engine names registered as valid for one bench config key —
    bench.py's per-config dispatch derives from the registry instead of
    private literal tuples."""
    return tuple(n for n, spec in ENGINE_REGISTRY.items()
                 if config_key in spec["configs"])


def supports_fused_steps(engine: str) -> bool:
    """True when ``engine`` (registry name or row alias) carries the
    ``fused_steps`` W-row insert splice — the single source the bench
    and compile plumbing consult before compiling with ``fuse_w`` > 1."""
    name = ENGINE_ROW_ALIASES.get(engine, engine)
    if name is None:
        return False
    return bool(ENGINE_REGISTRY.get(name, {}).get("fused_steps", False))


@dataclasses.dataclass
class BatchConfig:
    """Host-side op-compiler knobs (``ops.batch``) — the compile-side
    twin of ``EngineConfig``; CLIs that shape op streams construct one
    (bench's kevin path) so the compile call sites share these
    defaults rather than growing private literal forests.

    ``fuse_w`` is the split-batch prepare width: backwards-contiguous
    insert bursts (the kevin prepend shape) compile into fused
    ``rows_per_step <= fuse_w`` steps — one device step splices the
    whole burst.  Requires a ``fused_steps`` engine and
    ``fuse_w <= block_k // 2 - 1`` (one leaf split must make room for
    a full fused step); 1 disables fusion.
    """

    lmax: int = 16             # insert-chunk width of compiled steps
    dmax: Optional[int] = None  # per-step delete-span bound (None = off)
    fuse_w: int = 1            # fused insert-burst width (1 = unfused)


def lane_block_geometry(capacity: int, block_k: int) -> tuple:
    """Blocked-lanes geometry for a requested per-lane row capacity:
    ``(capacity, NB, NBT)`` with capacity rounded UP to a ``block_k``
    multiple (K is fixed across a stream's chunks; the growing
    per-chunk capacities of configs 5/5r size NB, not K)."""
    cap = ((capacity + block_k - 1) // block_k) * block_k
    nb = cap // block_k
    return cap, nb, max(8, nb)


@dataclasses.dataclass
class EngineConfig:
    """Device-engine knobs shared by the replay engines."""

    engine: str = "rle"        # one of ENGINE_CHOICES
    batch: int = 128           # docs in the lane dim (256 is the measured
    #                            northstar optimum; 512+ exceeds VMEM,
    #                            PERF.md §5)
    block_k: int = 256         # rows per block (rle: RUN rows)
    lanes_block_k: int = 64    # K for the BLOCKED per-lane engines
    #                            (configs 5/5r): small enough that the
    #                            in-block splice is cheap, large enough
    #                            that NB stays a few dozen (PERF.md §9)
    chunk: int = 1024          # ops per grid step (TPU wants %1024)
    capacity: int = 0          # state rows; 0 = per-workload default
    lmax_cap: int = 512        # insert-chunk cap when compiling merged ops
    interpret: bool = False    # pallas interpreter (CPU logic checks)

    def add_args(self, ap: argparse.ArgumentParser) -> None:
        ap.add_argument("--engine", default=self.engine,
                        choices=ENGINE_CHOICES)
        ap.add_argument("--batch", type=int, default=self.batch)
        ap.add_argument("--block-k", type=int, default=self.block_k)
        ap.add_argument("--lanes-block-k", type=int,
                        default=self.lanes_block_k)
        ap.add_argument("--chunk", type=int, default=self.chunk)
        ap.add_argument("--capacity", type=int, default=self.capacity)
        ap.add_argument("--interpret", action="store_true",
                        default=self.interpret)


@dataclasses.dataclass
class MeshConfig:
    """Multi-chip sharding shape (``parallel.make_mesh``)."""

    n_devices: int = 8
    dp: int = 0                # 0 = derive: n_devices // sp
    sp: int = 1                # sequence/span-parallel axis

    def resolved(self) -> tuple:
        dp = self.dp or (self.n_devices // max(self.sp, 1))
        return dp, self.sp


@dataclasses.dataclass
class StreamConfig:
    """Streaming-apply loop (config 5 shape)."""

    resync_every: int = 1      # chunks between host<->device resyncs
    checkpoint_dir: Optional[str] = None


@dataclasses.dataclass
class ServeConfig:
    """The continuous-batching document server (`serve/`).

    ``engine`` must be registered for the ``serve`` bench config in
    ``ENGINE_REGISTRY`` (the batcher's device backend is built per
    engine; ``serve/batcher.make_lane_backend`` validates and raises a
    typed error for names without a serve backend).
    """

    engine: str = "flat"       # registry engine backing the lane batches
    num_shards: int = 2        # device batches (one [B, CAP] doc batch each)
    lanes_per_shard: int = 16  # B — docs resident per shard batch
    lane_capacity: int = 512   # CAP — body rows per lane (flat: chars;
    #                            rle-lanes-mixed: RUN rows)
    order_capacity: int = 1536 # OCAP — by-order log rows per lane
    lanes_block_k: int = 32    # K (rows per block) for the blocked
    #                            rle-lanes-mixed backend; smaller K than
    #                            the config-5/5r replays because serve
    #                            steps are tiny edits and NBT+K is the
    #                            per-step touched-row floor (PERF.md §10).
    #                            32 is the serve-tuned sweep winner
    #                            (perf/serve_k_sweep.json: min touched
    #                            rows/step over K in {8,16,32,64} on the
    #                            loadgen tick trace)
    interpret: Optional[bool] = None  # pallas interpreter for the lanes
    #                            backend (None = auto: on unless on TPU)
    lmax: int = 16             # insert-chunk width of compiled serve
    #                            steps — ALSO the cap on fused typing
    #                            rows (ISSUE 12 satellite, the PR-6
    #                            lever: 8 capped merged typing runs at
    #                            one word).  The pipeline_probe lmax
    #                            sweep on --workload typing: 8 -> 2628
    #                            device steps, 16 -> 2007 (-24%) at
    #                            equal wall, 32 -> 1856 but +25% CPU
    #                            wall from the 4x-wider char columns —
    #                            16 is the shipped winner (PERF.md §17)
    device_prefill: bool = True  # device-resident by-order logs
    #                            (ISSUE 14): the flat backend ships ONLY
    #                            the per-tick prefill scatter as fixed-
    #                            shape padded delta tensors and applies
    #                            it on device (`ops.flat.
    #                            apply_prefill_delta`), instead of
    #                            round-tripping the four full [B, OCAP]
    #                            logs through host numpy every tick
    #                            (`batch.prefill_logs`) — the serve
    #                            tick's last O(state) host cost becomes
    #                            O(ops), and the dispatch edge stops
    #                            reading device state (the hidden sync
    #                            that ate the pipelined overlap under
    #                            real async dispatch).  Logical streams
    #                            and ledger counters are byte-identical
    #                            either way (tests/test_device_prefill
    #                            .py); False = the PR-3 host path
    #                            (loadgen --host-prefill).  Backends
    #                            without device-resident logs (the
    #                            blocked lanes backend prefills only
    #                            ranks, host-side) accept and ignore it
    pipeline_ticks: int = 2    # host/device tick pipelining depth
    #                            (ISSUE 12): 2 = double-buffered — tick
    #                            N+1's drain/fuse/oracle-apply/compile
    #                            (and residency checkpoint I/O) run on
    #                            the host while tick N's device step is
    #                            still in flight, the per-tick
    #                            block_until_ready deferred to ONE
    #                            staged sync point a tick later; 1 =
    #                            the serial PR-3 loop (dispatch ->
    #                            barrier every tick).  Logical streams,
    #                            flow spans and ledger counters are
    #                            byte-identical at any depth — only
    #                            wall time moves (pinned by
    #                            tests/test_serve_pipeline.py).
    #                            Backends opt in via their
    #                            ``max_pipeline_ticks`` (the blocked
    #                            lanes backend trues up exact per-lane
    #                            row counts at its barrier, so it
    #                            stays serial until that true-up is
    #                            pipeline-safe)
    train_ticks: int = 1       # device tick-train length (ISSUE 20):
    #                            T > 1 = the batcher accumulates T
    #                            ticks' fixed-shape op tensors (+ their
    #                            prefill-delta scatters, concatenated)
    #                            and dispatches them as ONE jitted
    #                            lax.scan program (ops.flat.apply_
    #                            train), collapsing T dispatch
    #                            overheads into one; 1 = today's
    #                            one-dispatch-per-tick loop.  Train
    #                            lengths are padded to powers of two
    #                            ({1,2,4,8}) so steady state never
    #                            recompiles, and the compile set stays
    #                            additive (|S buckets| x |T buckets| +
    #                            |scatter buckets|).  Logical streams
    #                            are byte-identical at any length —
    #                            like pipeline_ticks, a pure wall-clock
    #                            knob (pinned by tests/test_serve_
    #                            train.py).  Backends opt in via
    #                            ``max_train_ticks``: the flat backend
    #                            accepts up to 8 on its device-prefill
    #                            path (host prefill needs per-tick host
    #                            log writes, incompatible with
    #                            deferral); the blocked lanes backend
    #                            stays at 1 (barrier true-up)
    sanitize_pipeline: bool = False  # pipeline aliasing sanitizer
    #                            (ISSUE 13): fingerprint (CRC32) the op
    #                            tensors referenced by each in-flight
    #                            tick at dispatch and re-check them at
    #                            the staged sync — a host write racing
    #                            an in-flight device step fails loudly
    #                            naming the tick/shard/array instead of
    #                            corrupting device state (JAX's CPU
    #                            zero-copy conversion can alias the
    #                            host buffers).  Off by default on the
    #                            raw serving path; cheap enough
    #                            (<5% wall, PERF.md §18) to leave on in
    #                            the serve tests and any pipelined
    #                            deployment being debugged
    step_buckets: tuple = (8, 32, 128)  # padded tick step shapes; a tick
    #                            drains at most step_buckets[-1] compiled
    #                            steps per doc so steady-state serving
    #                            cycles a fixed kernel set (no recompiles)
    max_queue_per_doc: int = 256    # admission: pending events per doc
    max_queue_global: int = 8192    # admission: pending events total
    max_txn_len: int = 128          # admission: items per submitted txn —
    #                            must fit step_buckets[-1] so every
    #                            admitted event can apply in one tick
    #                            (DocServer asserts the pair at build)
    rate_capacity: int = 0          # token bucket size per agent (0 = off)
    rate_refill: int = 0            # tokens added per tick per agent
    spool_dir: Optional[str] = None  # eviction checkpoint directory
    journal_dir: Optional[str] = None  # write-ahead op journal (ISSUE
    #                            16): every admitted op is appended to
    #                            per-shard CRC-chained segments here so
    #                            DocServer.recover() can rebuild a
    #                            crashed server byte-identically
    #                            (checkpoint chains + journal-suffix
    #                            replay).  None = journaling off — the
    #                            shipped default for latency benches
    journal_fsync_ticks: int = 1  # fsync cadence on the logical tick
    #                            axis: segments flush every append
    #                            (process-crash durability) and fsync
    #                            at TICK markers every this-many ticks
    #                            (power-loss durability).  1 = every
    #                            tick; the recovery ledger cell prices
    #                            the shipped cadence
    fuse_steps: bool = True    # generalized tick-stream fusion
    #                            (ops.batch.fuse_steps): typing runs /
    #                            sweeps / replaces / remote runs always
    #                            coalesce; W-row bursts additionally on
    #                            fused_steps backends (ISSUE 6)
    fuse_w: int = 8            # burst width cap; effective W is
    #                            min(fuse_w, lanes_block_k // 2 - 1) on
    #                            backends with the W-row splice, 1 on
    #                            the rest (the one-split headroom rule)
    nagle_txns: int = 16       # columnar-wire emission Nagle window
    #                            (ISSUE 12, the §16 latency lever): a
    #                            peer outbox ships once it holds this
    #                            many txns...
    nagle_rounds: int = 4      # ...or has waited this many ticks
    #                            regardless.  The loadgen's flush
    #                            policy reads both (--nagle-txns /
    #                            --nagle-rounds); smaller windows cut
    #                            clean-remote op-age (emission-to-frame
    #                            batching dominates it, PERF.md §16) at
    #                            a bytes/op cost — 16/4 is the
    #                            perf/pipeline_probe.py sweep winner
    #                            (clean-remote p50 13 -> 4 ticks for
    #                            +14% bytes/op at the 200-doc faulted
    #                            shape, PERF.md §17)
    wire_format: str = "columnar"  # TXNS frames the server EMITS
    #                            (request serving): "row" = PR-1 frame
    #                            version 1, "columnar" = the version-2
    #                            per-column delta wire (net/columnar).
    #                            Decode always negotiates on the version
    #                            byte, so mixed-format peers interop.
    ckpt_format: str = "delta"  # eviction checkpoints: "full" = one
    #                            FORMAT_VERSION-3 oracle snapshot per
    #                            evict (O(doc)); "delta" = CRC-chained
    #                            incremental saves (O(ops since last
    #                            save)) with periodic base compaction
    ckpt_compact_ops: int = 4096   # delta chain: fold into a fresh base
    #                            once ops-since-base exceed this
    ckpt_compact_links: int = 16   # ... or the chain grows this long
    # -- observability (ISSUE 8: obs/) --------------------------------------
    trace: bool = True         # logical-clock event tracer (obs/trace):
    #                            default ON — the overhead probe pins it
    #                            <5% of loadgen wall (PERF.md §14)
    trace_ring: int = 512      # flight-recorder ring: last-N events
    trace_path: Optional[str] = None  # stream every event to this JSONL
    #                            file (logical + segregated wall fields)
    trace_rotate_bytes: Optional[int] = None  # size-cap per stream
    #                            segment: the file rolls to <path>.1,
    #                            <path>.2, ... so a long run never grows
    #                            one unbounded JSONL (None = no cap)
    trace_keep: bool = False   # retain the full event list in memory
    #                            (the trace-determinism tests read it
    #                            back via Tracer.logical_bytes)
    flow_sample_mod: int = 16  # per-op provenance spans (ISSUE 11,
    #                            obs/flow): agents whose crc32(name) %
    #                            mod == 0 get END-TO-END flow.* span
    #                            events (emit/frame/reject/buffer/
    #                            ready/apply on the logical tick axis).
    #                            Per-AGENT sampling keeps every sampled
    #                            span complete, so the conservation
    #                            audit is valid at any mod.  1 = track
    #                            everything (audit/ledger runs); 0 =
    #                            off; the 16 default keeps the serve
    #                            path under the PERF.md §14/§16 5% bar
    obs_dir: Optional[str] = None  # post-mortem bundle directory;
    #                            None = $TCR_TRACE_DIR or
    #                            <spool_dir>/obs
    profile_dir: Optional[str] = None  # opt-in jax.profiler capture:
    #                            start a device trace into this dir at
    #                            tick 1, stop after profile_ticks
    profile_ticks: int = 3     # ticks per jax.profiler capture window

    def add_args(self, ap: argparse.ArgumentParser) -> None:
        ap.add_argument("--serve-shards", type=int, default=self.num_shards)
        ap.add_argument("--serve-lanes", type=int,
                        default=self.lanes_per_shard)
        ap.add_argument("--serve-capacity", type=int,
                        default=self.lane_capacity)


@dataclasses.dataclass
class SoakConfig:
    """``examples.soak`` — the `examples/simple.rs:14-49` driver."""

    edits: int = 1_000_000
    seed: int = 7
    oracle_steps: int = 2_000  # per-step differential-oracle prefix
    detailed: bool = False

    @classmethod
    def from_args(cls, argv: Optional[Sequence[str]] = None) -> "SoakConfig":
        d = cls()
        ap = argparse.ArgumentParser(description=__doc__)
        ap.add_argument("--edits", type=int, default=d.edits)
        ap.add_argument("--seed", type=int, default=d.seed)
        ap.add_argument("--oracle", type=int, default=d.oracle_steps,
                        dest="oracle_steps",
                        help="per-step-checked oracle prefix (0 = skip)")
        ap.add_argument("--detailed", action="store_true")
        a = ap.parse_args(argv)
        return cls(edits=a.edits, seed=a.seed,
                   oracle_steps=a.oracle_steps, detailed=a.detailed)


@dataclasses.dataclass
class StatsConfig:
    """``examples.stats`` — the `examples/stats.rs:39-73` driver."""

    trace: str = "automerge-paper"
    engine: str = "native"     # native | oracle
    detailed: bool = False

    @classmethod
    def from_args(cls, argv: Optional[Sequence[str]] = None) -> "StatsConfig":
        d = cls()
        ap = argparse.ArgumentParser(description=__doc__)
        ap.add_argument("--trace", default=d.trace)
        ap.add_argument("--engine", default=d.engine,
                        choices=("native", "oracle"))
        ap.add_argument("--detailed", action="store_true")
        a = ap.parse_args(argv)
        return cls(trace=a.trace, engine=a.engine, detailed=a.detailed)

"""Sequence-parallel RLE runs: one huge document sharded across chips.

The long-context story for the RUN representation (SURVEY §5
"long-context / sequence parallelism": *sharding one huge document's span
array across chips with carry-propagating scans over ICI*). A document
too large for one chip's memory keeps its run rows ``(±(order+1), len)``
sharded over the mesh's ``sp`` axis — shard s holds rows
``[s*R, (s+1)*R)`` in document order — and the two hot conversions
(`README.md:20-26`) become shard-local scans plus ONE small collective:

- ``live_prefix``: per-shard live-char totals are ``psum``-style
  all-gathered (one u32 per shard over ICI) so every shard knows the
  carry entering it — the internal-node subtree sums
  (`range_tree/mod.rs:85-93`) with the tree's top levels replaced by the
  mesh axis;
- ``position_of_live_rank``: content position -> (global row, offset
  within run). Each shard resolves the rank against its carry-adjusted
  local cumsum; exactly one shard hits, and a masked ``psum`` extracts
  the answer;
- ``order_to_position``: CRDT item -> content position (hot path #2's
  read-back, `cursor.rs:147-190`): the owning shard computes live chars
  before the item locally, adds its carry, and a masked ``psum``
  broadcasts it.

All collectives are XLA-emitted (``shard_map`` + ``psum``); nothing here
knows about NCCL/MPI. Tested on the virtual 8-device CPU mesh against a
host reference (``tests/test_sp_runs.py``); the same code compiles for a
real ICI mesh unchanged.
"""
from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from ._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_runs(ordp: np.ndarray, lenp: np.ndarray, mesh: Mesh):
    """Upload run planes ``[CAP]`` sharded over the ``sp`` axis (rows
    padded to a multiple of the axis size; 0 = empty row)."""
    sp = mesh.shape["sp"]
    cap = len(ordp)
    pad = (-cap) % sp
    o = np.pad(np.asarray(ordp, np.int32), (0, pad))
    l = np.pad(np.asarray(lenp, np.int32), (0, pad))
    sharding = NamedSharding(mesh, P("sp"))
    return (jax.device_put(jnp.asarray(o), sharding),
            jax.device_put(jnp.asarray(l), sharding))


def _live_lens(ordp, lenp):
    return jnp.where(ordp > 0, lenp, 0)


@lru_cache(maxsize=16)
def make_sp_ops(mesh: Mesh):
    """Build the sharded lookup ops for ``mesh`` (jitted shard_map fns).

    Returns an object with ``live_prefix``, ``position_of_live_rank`` and
    ``order_to_position`` — each one shard-local compute + one small
    collective over the ``sp`` axis.  lru-cached per mesh: the three
    query jits are built once per geometry, not once per caller (the
    ``_build_call`` pattern, round-17 allowlist burn-down).
    """
    spec = P("sp")
    none = P()

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec),
             out_specs=(spec, none), check_rep=False)
    def live_prefix(ordp, lenp):
        """(per-row global live prefix [CAP], total live chars [])."""
        lv = _live_lens(ordp, lenp)
        local = jnp.cumsum(lv)
        total = local[-1] if local.size else jnp.int32(0)
        # Carry entering this shard: sum of totals of lower sp indices.
        idx = jax.lax.axis_index("sp")
        totals = jax.lax.all_gather(total, "sp")
        carry = jnp.sum(jnp.where(jnp.arange(totals.shape[0]) < idx,
                                  totals, 0))
        return local + carry, jnp.sum(totals)

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, none),
             out_specs=(none, none), check_rep=False)
    def position_of_live_rank(ordp, lenp, rank1):
        """Live rank (1-based) -> (global row index, 1-based offset in
        that run). Exactly one shard owns the hit; psum extracts it.
        Out-of-range ranks (rank1 > total live) return the sentinel
        ``(0, 0)`` — distinguishable from a real hit because a real
        offset is 1-based (``off == 0`` <=> rank out of range)."""
        lv = _live_lens(ordp, lenp)
        local = jnp.cumsum(lv)
        total = local[-1] if local.size else jnp.int32(0)
        idx = jax.lax.axis_index("sp")
        totals = jax.lax.all_gather(total, "sp")
        carry = jnp.sum(jnp.where(jnp.arange(totals.shape[0]) < idx,
                                  totals, 0))
        cum = local + carry
        R = ordp.shape[0]
        rows = jnp.arange(R)
        # First row whose global cumulative live count reaches rank1.
        mine = (carry < rank1) & (rank1 <= cum[-1] if R else False)
        i_local = jnp.sum((cum < rank1).astype(jnp.int32))
        hit = mine & (i_local < R)
        safe = jnp.minimum(i_local, R - 1)
        row_g = jnp.where(hit, idx * R + safe, 0)
        off = jnp.where(
            hit, rank1 - (cum[safe] - lv[safe]), 0)
        del rows
        return (jax.lax.psum(row_g.astype(jnp.int32), "sp"),
                jax.lax.psum(off.astype(jnp.int32), "sp"))

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, none),
             out_specs=none, check_rep=False)
    def order_to_position(ordp, lenp, order):
        """Item order -> content position (live chars strictly before
        it); -1 if the item is a tombstone or unknown."""
        lv = _live_lens(ordp, lenp)
        starts = jnp.abs(ordp) - 1
        occ = ordp != 0
        contains = occ & (starts <= order) & (order < starts + lenp)
        local = jnp.cumsum(lv)
        total = local[-1] if local.size else jnp.int32(0)
        idx = jax.lax.axis_index("sp")
        totals = jax.lax.all_gather(total, "sp")
        carry = jnp.sum(jnp.where(jnp.arange(totals.shape[0]) < idx,
                                  totals, 0))
        i_local = jnp.argmax(contains)
        hit = jnp.any(contains)
        live_run = hit & (ordp[i_local] > 0)
        before = carry + local[i_local] - lv[i_local] \
            + (order - starts[i_local])
        pos = jnp.where(live_run, before, -1)
        found = jnp.where(hit, pos, 0).astype(jnp.int32)
        any_hit = jax.lax.psum(hit.astype(jnp.int32), "sp")
        summed = jax.lax.psum(found, "sp")
        return jnp.where(any_hit > 0, summed, -1)

    class SpOps:
        pass

    ops = SpOps()
    ops.live_prefix = jax.jit(live_prefix)
    ops.position_of_live_rank = jax.jit(position_of_live_rank)
    ops.order_to_position = jax.jit(order_to_position)
    return ops

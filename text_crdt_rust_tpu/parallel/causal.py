"""Causal receive buffer for out-of-order remote transactions.

The reference asserts remote txns arrive in per-agent seq order and leaves a
TODO: "we either need to skip or buffer the transaction" (`doc.rs:246-247`).
This module implements that buffer (SURVEY §5 "Failure detection" row): txns
are held until *causally ready* — every parent known and the author's seq
contiguous — then released in a deterministic causal order. It fronts both
the host oracle (``ListCRDT.apply_remote_txn``) and the device op compiler
(``ops.batch.compile_remote_txns``), which both hard-assert readiness.

Readiness (`doc.rs:242-269` preconditions):
- ``txn.id.seq`` == the author's next expected seq (no gaps in an agent's
  op stream; seqs within a txn advance by its op length, `doc.rs:252-269`);
- every parent id is ROOT or already released (parents are (agent, seq)
  pairs; known iff seq < that agent's released watermark).
"""
from __future__ import annotations

from typing import Dict, Iterable, List

from ..common import RemoteId, RemoteTxn, split_txn_suffix, txn_len


class CausalBuffer:
    """Holds remote txns until causally ready; releases them in order.

    ``add``/``add_all`` return the txns that became ready (possibly
    including earlier-buffered ones), in a valid causal order. Duplicate
    and already-known txns are dropped, mirroring the idempotent re-sync
    behavior peers need (`README.md:33-35` peer model).
    """

    def __init__(self) -> None:
        # Agent name -> next expected seq (the released watermark).
        self._next_seq: Dict[str, int] = {}
        self._pending: List[RemoteTxn] = []

    def _watermark(self, agent: str) -> int:
        return self._next_seq.get(agent, 0)

    def _known(self, rid: RemoteId) -> bool:
        if rid.agent == "ROOT":
            return True
        return rid.seq < self._watermark(rid.agent)

    def _ready(self, txn: RemoteTxn) -> bool:
        if txn.id.seq != self._watermark(txn.id.agent):
            return False
        return all(self._known(p) for p in txn.parents)

    def _trim(self, txn: RemoteTxn) -> RemoteTxn | None:
        """Drop the already-released prefix of ``txn`` (re-sync deliveries
        may cover known seqs — a peer's txns RLE merges linear history, so
        a later export can span an older one, `txn.rs:38-42`). Returns None
        if fully known."""
        wm = self._watermark(txn.id.agent)
        if txn.id.seq + txn_len(txn) <= wm:
            return None  # duplicate / fully released
        if txn.id.seq < wm:
            return split_txn_suffix(txn, wm - txn.id.seq)
        return txn

    def add(self, txn: RemoteTxn) -> List[RemoteTxn]:
        """Offer one txn; return every txn that is now ready, causal order."""
        trimmed = self._trim(txn)
        if trimmed is None:
            return []
        # Re-delivery of a still-blocked txn (peers re-sync while a parent
        # is missing) must not grow the buffer: one entry per (agent, seq),
        # keeping the longer delivery (a merged export supersedes a prefix).
        for i, held in enumerate(self._pending):
            if held.id == trimmed.id:
                if txn_len(trimmed) > txn_len(held):
                    self._pending[i] = trimmed
                    return self._drain()
                return []
        self._pending.append(trimmed)
        return self._drain()

    def add_all(self, txns: Iterable[RemoteTxn]) -> List[RemoteTxn]:
        out: List[RemoteTxn] = []
        for t in txns:
            out.extend(self.add(t))
        return out

    def _drain(self) -> List[RemoteTxn]:
        released: List[RemoteTxn] = []
        progressed = True
        while progressed:
            progressed = False
            for i, txn in enumerate(self._pending):
                if txn.id.seq < self._watermark(txn.id.agent):
                    # Watermark moved while buffered: re-trim (overlapping
                    # delivery) or drop (duplicate).
                    self._pending.pop(i)
                    trimmed = self._trim(txn)
                    if trimmed is not None:
                        self._pending.insert(i, trimmed)
                    progressed = True
                    break
                if self._ready(txn):
                    self._pending.pop(i)
                    self._next_seq[txn.id.agent] = txn.id.seq + txn_len(txn)
                    released.append(txn)
                    progressed = True
                    break
        return released

    @property
    def pending(self) -> int:
        """Buffered txns still waiting on causal dependencies."""
        return len(self._pending)

    def missing(self) -> List[RemoteId]:
        """The frontier of unmet dependencies — the first unreceived
        (agent, seq) per blocking agent, i.e. what to request from peers
        (failure detection: a persistently-missing id marks a lost txn)."""
        out: List[RemoteId] = []
        seen = set()

        def want(agent: str) -> None:
            rid = RemoteId(agent, self._watermark(agent))
            if agent != "ROOT" and rid not in seen:
                seen.add(rid)
                out.append(rid)

        for txn in self._pending:
            if txn.id.seq > self._watermark(txn.id.agent):
                want(txn.id.agent)  # gap in the author's own stream
            for p in txn.parents:
                if not self._known(p):
                    want(p.agent)
        return out

"""Causal receive buffer for out-of-order remote transactions.

The reference asserts remote txns arrive in per-agent seq order and leaves a
TODO: "we either need to skip or buffer the transaction" (`doc.rs:246-247`).
This module implements that buffer (SURVEY §5 "Failure detection" row): txns
are held until *causally ready* — every parent known and the author's seq
contiguous — then released in a deterministic causal order. It fronts both
the host oracle (``ListCRDT.apply_remote_txn``) and the device op compiler
(``ops.batch.compile_remote_txns``), which both hard-assert readiness.

Readiness (`doc.rs:242-269` preconditions):
- ``txn.id.seq`` == the author's next expected seq (no gaps in an agent's
  op stream; seqs within a txn advance by its op length, `doc.rs:252-269`);
- every parent id is ROOT or already released (parents are (agent, seq)
  pairs; known iff seq < that agent's released watermark).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from ..common import RemoteId, RemoteTxn, split_txn_suffix, txn_len


class CausalBuffer:
    """Holds remote txns until causally ready; releases them in order.

    ``add``/``add_all`` return the txns that became ready (possibly
    including earlier-buffered ones), in a valid causal order. Duplicate
    and already-known txns are dropped, mirroring the idempotent re-sync
    behavior peers need (`README.md:33-35` peer model).

    ``max_pending`` bounds the buffer: offering a txn to a full buffer
    evicts the pending txn farthest from readiness (largest seq gap to
    its author's watermark — the one that needs the most missing history
    before it can release) instead of growing without bound. Evictions
    are counted, the watermark is untouched, and the evicted range is
    remembered (until the watermark covers it) so ``missing()`` still
    names the gap even when the evicted txn was the agent's only pending
    entry — the session layer re-requests the range and the peer
    re-delivers; eviction trades memory for a retransmit, never
    correctness (`net/session.py`).

    Introspection for that layer (surfaced via
    ``utils.metrics.causal_buffer_stats``): ``pending``, ``high_water``,
    ``duplicates_dropped``, ``evictions``, ``watermarks()``,
    ``gap_stats()``.
    """

    def __init__(self, max_pending: Optional[int] = None) -> None:
        assert max_pending is None or max_pending >= 1
        # Agent name -> next expected seq (the released watermark).
        self._next_seq: Dict[str, int] = {}
        self._pending: List[RemoteTxn] = []
        self.max_pending = max_pending
        self.high_water = 0        # max simultaneous pending ever seen
        self.duplicates_dropped = 0
        self.evictions = 0
        # Agent -> end seq of the farthest evicted txn: keeps the gap
        # visible to missing() until redelivery covers it.
        self._evicted_ends: Dict[str, int] = {}
        # What happened to the LAST ``add`` offer — "released" (the
        # offered span's watermark advanced past it), "buffered"
        # (held on a causal gap), "dropped" (pressure-evicted within
        # this very offer — it left the buffer, on_drop already saw
        # it), or "dup" (fully known / superseded).  Per-op provenance
        # (obs/flow) reads this right after ``add`` to stamp the
        # span's buffer-vs-ready lifecycle event.
        self.last_offer = "dup"
        # Optional pressure-eviction observer: called with the evicted
        # txn (the span leaves the buffer but NOT the ledger — the gap
        # stays visible to missing() and redelivery brings it back).
        self.on_drop = None

    def _watermark(self, agent: str) -> int:
        return self._next_seq.get(agent, 0)

    def _known(self, rid: RemoteId) -> bool:
        if rid.agent == "ROOT":
            return True
        return rid.seq < self._watermark(rid.agent)

    def _ready(self, txn: RemoteTxn) -> bool:
        if txn.id.seq != self._watermark(txn.id.agent):
            return False
        return all(self._known(p) for p in txn.parents)

    def _trim(self, txn: RemoteTxn) -> RemoteTxn | None:
        """Drop the already-released prefix of ``txn`` (re-sync deliveries
        may cover known seqs — a peer's txns RLE merges linear history, so
        a later export can span an older one, `txn.rs:38-42`). Returns None
        if fully known."""
        wm = self._watermark(txn.id.agent)
        if txn.id.seq + txn_len(txn) <= wm:
            return None  # duplicate / fully released
        if txn.id.seq < wm:
            return split_txn_suffix(txn, wm - txn.id.seq)
        return txn

    def _offer_status(self, trimmed: RemoteTxn) -> str:
        """Post-drain fate of the offered span: released iff the
        author's watermark walked past its start seq (it — or a
        superseding delivery — came out of the drain)."""
        return ("released"
                if self._watermark(trimmed.id.agent) > trimmed.id.seq
                else "buffered")

    def add(self, txn: RemoteTxn) -> List[RemoteTxn]:
        """Offer one txn; return every txn that is now ready, causal order."""
        trimmed = self._trim(txn)
        if trimmed is None:
            self.duplicates_dropped += 1
            self.last_offer = "dup"
            return []
        # Re-delivery of a still-blocked txn (peers re-sync while a parent
        # is missing) must not grow the buffer: one entry per (agent, seq),
        # keeping the longer delivery (a merged export supersedes a prefix).
        for i, held in enumerate(self._pending):
            if held.id == trimmed.id:
                if txn_len(trimmed) > txn_len(held):
                    self._pending[i] = trimmed
                    released = self._drain()
                    self.last_offer = self._offer_status(trimmed)
                    return released
                self.duplicates_dropped += 1
                self.last_offer = "dup"
                return []
        self._pending.append(trimmed)
        self.high_water = max(self.high_water, len(self._pending))
        released = self._drain()
        if (self.max_pending is not None
                and len(self._pending) > self.max_pending):
            self._evict()
        status = self._offer_status(trimmed)
        if status == "buffered" and all(h.id != trimmed.id
                                        for h in self._pending):
            # The eviction above chose the offer itself (it had the
            # farthest watermark gap): it is NOT held — reporting
            # "buffered" would stamp a held event after on_drop
            # already recorded the drop.
            status = "dropped"
        self.last_offer = status
        return released

    def _evict(self) -> None:
        """Drop the pending txn farthest from readiness (largest seq gap
        to its author's watermark). Ties go to the later arrival, so the
        txn most likely to unblock soonest survives."""
        worst_i, worst_gap = 0, -1
        for i, held in enumerate(self._pending):
            gap = held.id.seq - self._watermark(held.id.agent)
            if gap >= worst_gap:
                worst_i, worst_gap = i, gap
        evicted = self._pending.pop(worst_i)
        agent = evicted.id.agent
        end = evicted.id.seq + txn_len(evicted)
        self._evicted_ends[agent] = max(self._evicted_ends.get(agent, 0),
                                        end)
        self.evictions += 1
        if self.on_drop is not None:
            self.on_drop(evicted)

    def add_all(self, txns: Iterable[RemoteTxn]) -> List[RemoteTxn]:
        out: List[RemoteTxn] = []
        for t in txns:
            out.extend(self.add(t))
        return out

    def _drain(self) -> List[RemoteTxn]:
        released: List[RemoteTxn] = []
        progressed = True
        while progressed:
            progressed = False
            for i, txn in enumerate(self._pending):
                if txn.id.seq < self._watermark(txn.id.agent):
                    # Watermark moved while buffered: re-trim (overlapping
                    # delivery) or drop (duplicate).
                    self._pending.pop(i)
                    trimmed = self._trim(txn)
                    if trimmed is not None:
                        self._pending.insert(i, trimmed)
                    progressed = True
                    break
                if self._ready(txn):
                    self._pending.pop(i)
                    self._next_seq[txn.id.agent] = txn.id.seq + txn_len(txn)
                    released.append(txn)
                    progressed = True
                    break
        return released

    @property
    def pending(self) -> int:
        """Buffered txns still waiting on causal dependencies."""
        return len(self._pending)

    def advance_watermark(self, agent: str, seq: int) -> List[RemoteTxn]:
        """Record out-of-band progress for ``agent`` (e.g. the session's
        own local edits, which never flow through the buffer) so echoed
        re-deliveries trim as duplicates and pending txns parented on that
        progress can release. Returns any txns that became ready."""
        return self.advance_watermarks({agent: seq})

    def advance_watermarks(self, marks: Dict[str, int]) -> List[RemoteTxn]:
        """Batch form of ``advance_watermark``: raise EVERY watermark
        first, then drain once. Draining per-agent would be wrong when
        several agents progressed out-of-band (e.g. sessions sharing one
        document, `net/session.py` N-peer mesh): unblocking agent A's
        dependents against agent B's still-stale watermark would release
        a txn the document already applied."""
        changed = False
        for agent, seq in marks.items():
            if seq > self._watermark(agent):
                self._next_seq[agent] = seq
                changed = True
        return self._drain() if changed else []

    def rollback_watermark(self, agent: str, seq: int) -> None:
        """Undo a release that the caller refused to apply (e.g. the
        session's reference validation rejected the txn): lower the
        watermark back to ``seq`` so an honest redelivery of that
        (agent, seq) is accepted instead of trimmed as a duplicate, and
        the gap stays visible to the digest/re-request cycle."""
        if seq < self._watermark(agent):
            self._next_seq[agent] = seq

    def watermarks(self) -> Dict[str, int]:
        """Per-agent released watermark (next expected seq), a copy."""
        return dict(self._next_seq)

    def gap_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-agent watermark gaps for agents with blocked pending txns:
        ``{agent: {next_seq, first_pending, gap, blocked}}`` where ``gap``
        is how many seqs are missing before the earliest pending txn from
        that agent could release."""
        out: Dict[str, Dict[str, int]] = {}
        for txn in self._pending:
            agent = txn.id.agent
            wm = self._watermark(agent)
            slot = out.setdefault(agent, {
                "next_seq": wm, "first_pending": txn.id.seq,
                "gap": txn.id.seq - wm, "blocked": 0,
            })
            slot["blocked"] += 1
            if txn.id.seq < slot["first_pending"]:
                slot["first_pending"] = txn.id.seq
                slot["gap"] = txn.id.seq - wm
        return out

    def missing(self) -> List[RemoteId]:
        """The frontier of unmet dependencies — the first unreceived
        (agent, seq) per blocking agent, i.e. what to request from peers
        (failure detection: a persistently-missing id marks a lost txn)."""
        out: List[RemoteId] = []
        seen = set()

        def want(agent: str) -> None:
            rid = RemoteId(agent, self._watermark(agent))
            if agent != "ROOT" and rid not in seen:
                seen.add(rid)
                out.append(rid)

        for txn in self._pending:
            if txn.id.seq > self._watermark(txn.id.agent):
                want(txn.id.agent)  # gap in the author's own stream
            for p in txn.parents:
                if not self._known(p):
                    want(p.agent)
        # Evicted ranges: the txn is gone but the gap is not — keep
        # naming it until the watermark covers the evicted end.
        for agent in list(self._evicted_ends):
            if self._watermark(agent) >= self._evicted_ends[agent]:
                del self._evicted_ends[agent]
            else:
                want(agent)
        return out

"""Device-mesh sharding for batched CRDT documents.

TPU-native scale-out (SURVEY §2 parallelism inventory, net-new vs the
reference):

- **dp axis** — independent documents. The reference's analog is "run the
  replay loop once per doc" (`benches/yjs.rs:41-48`); here the doc batch
  axis of ``FlatDoc`` is sharded across chips and every step runs SPMD.
- **sp axis** — the capacity (item) axis of *one* document, the
  long-context / sequence-parallel analog (SURVEY §5 "sharding one huge
  document's span array across chips with carry-propagating scans over
  ICI"). The step kernel is pure ``cumsum`` / ``searchsorted`` / masked
  gathers, so the XLA SPMD partitioner inserts the carry collectives
  itself; we only annotate shardings and let it.

No NCCL/MPI translation: collectives are whatever XLA emits for the
annotated shardings, riding ICI inside a pod and DCN across hosts.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.batch import prefill_logs
from ..ops.flat import _check_capacity, step
from ..ops.span_arrays import FlatDoc


def make_mesh(
    n_devices: Optional[int] = None,
    dp: Optional[int] = None,
    sp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A 2-D ``(dp, sp)`` mesh over ``n_devices`` (default: all attached).

    ``dp`` defaults to ``n_devices // sp``. A single-chip mesh (the bench
    machine) is just ``dp=sp=1`` — the same code path compiles unchanged.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = list(devices)[:n_devices]
    if dp is None:
        assert n_devices % sp == 0, (n_devices, sp)
        dp = n_devices // sp
    assert dp * sp == n_devices, f"dp({dp}) * sp({sp}) != {n_devices}"
    grid = np.asarray(devices).reshape(dp, sp)
    return Mesh(grid, axis_names=("dp", "sp"))


def doc_pspecs(batched: bool = True) -> FlatDoc:
    """PartitionSpecs for every ``FlatDoc`` field.

    Batched docs: columns ``[B, N]`` -> ``P('dp', 'sp')``; per-doc scalars
    ``[B]`` -> ``P('dp')``. Unbatched (one huge doc, pure
    sequence-parallel): columns ``[N]`` -> ``P('sp')``, scalars replicated.
    """
    if batched:
        col, scalar = P("dp", "sp"), P("dp")
    else:
        col, scalar = P("sp"), P()
    return FlatDoc(
        signed=col, ol_log=col, or_log=col, rank_log=col,
        chars_log=col, n=scalar, next_order=scalar,
    )


def ops_pspecs(ops, batched: bool = True):
    """PartitionSpecs for an ``OpTensors`` batch: time axis replicated
    (it is scanned), doc axis sharded over ``dp``, the char chunk axis
    replicated."""
    def spec(a):
        if not batched:
            return P()
        extra = (None,) * (a.ndim - 2)
        return P(None, "dp", *extra)

    return jax.tree.map(spec, ops)


def shard_docs(docs: FlatDoc, mesh: Mesh, batched: bool = True) -> FlatDoc:
    """Place a (batch of) document(s) onto the mesh."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        docs, doc_pspecs(batched),
    )


def shard_ops(ops, mesh: Mesh, batched: bool = True):
    """Place a compiled op stream onto the mesh (doc axis over ``dp``)."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        ops, ops_pspecs(ops, batched),
    )


@functools.lru_cache(maxsize=16)
def make_sharded_apply(mesh: Mesh, donate: bool = True,
                       prefill: bool = True):
    """The full multi-chip apply step, jitted over the mesh.

    lru-cached by ``(mesh, donate, prefill)`` — ``jax.sharding.Mesh``
    hashes by (devices, axis names), so re-building for the same mesh
    returns the SAME jitted closure instead of re-tracing (the
    ``_build_call`` pattern, round-17 allowlist burn-down; the old
    grant claimed Mesh was not lru-hashable, which stopped being true
    several jax versions ago).

    Returns ``apply(docs, ops) -> docs`` where docs are sharded
    ``P('dp','sp')`` and the time-major op stream is scanned with the doc
    axis sharded ``P(None,'dp')``. This is the framework's "training step"
    equivalent: the whole op-apply pipeline (position scan, YATA integrate,
    splice, tombstoning) under one pjit.

    ``prefill`` runs ``batch.prefill_logs`` on the docs before each apply
    (host-side; see ``ops.flat.apply_ops``). The device step only writes
    the origins a *local* insert discovers, so a fresh ``make_flat_doc``
    applied without prefilled logs gives silently wrong results (NUL
    chars, wrong tiebreak ranks). Pass ``prefill=False`` only when the
    docs' logs were already prefilled for this op stream.
    """
    vstep = jax.vmap(step)

    def apply(docs: FlatDoc, ops) -> FlatDoc:
        def body(d, op):
            return vstep(d, op), None

        out, _ = jax.lax.scan(body, docs, ops)
        return out

    in_doc_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), doc_pspecs(batched=True))

    jitted = jax.jit(
        apply,
        in_shardings=(in_doc_shardings, None),
        out_shardings=in_doc_shardings,
        donate_argnums=(0,) if donate else (),
    )

    def checked(docs: FlatDoc, ops) -> FlatDoc:
        _check_capacity(docs, ops)
        if prefill:
            docs = shard_docs(prefill_logs(docs, ops), mesh)
        return jitted(docs, ops)

    return checked


@functools.lru_cache(maxsize=16)
def make_sharded_apply_1doc(mesh: Mesh, prefill: bool = True):
    """Sequence-parallel apply for ONE huge document: capacity axis sharded
    ``P('sp')`` across every chip in the mesh (long-context path).

    ``prefill`` as in ``make_sharded_apply`` — required for fresh docs;
    lru-cached per mesh like it too."""
    specs = doc_pspecs(batched=False)
    in_doc_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)

    def apply(doc: FlatDoc, ops) -> FlatDoc:
        def body(d, op):
            return step(d, op), None

        out, _ = jax.lax.scan(body, doc, ops)
        return out

    jitted = jax.jit(
        apply,
        in_shardings=(in_doc_shardings, None),
        out_shardings=in_doc_shardings,
    )

    def checked(doc: FlatDoc, ops) -> FlatDoc:
        _check_capacity(doc, ops)
        if prefill:
            doc = shard_docs(prefill_logs(doc, ops), mesh, batched=False)
        return jitted(doc, ops)

    return checked

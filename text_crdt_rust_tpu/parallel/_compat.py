"""jax version compatibility shared by the parallel modules."""
try:  # jax >= 0.8: top-level shard_map, check_rep -> check_vma
    from jax import shard_map as _jax_shard_map

    def shard_map(f=None, *, check_rep=True, **kw):
        return _jax_shard_map(f, check_vma=check_rep, **kw)
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # noqa: F401

"""Parallel execution layer: device-mesh sharding + causal streaming.

The reference is a single-threaded CPU library (SURVEY §2 "Parallelism
inventory": no DP/TP/PP/SP and no NCCL/MPI anywhere); its only concurrency is
the *logical* concurrency of CRDT editors. This package supplies the
net-new, first-class parallel components the TPU build requires:

- ``mesh``    — document-batch data parallelism (the DP analog) and
                capacity-axis sharding (the SP/long-context analog) over a
                ``jax.sharding.Mesh``, with XLA inserting the collectives.
- ``causal``  — the causal receive buffer for out-of-order remote txns (the
                reference's "we either need to skip or buffer" gap,
                `doc.rs:246-247`).
- ``sp_runs`` — sequence-parallel RLE runs: ONE huge document's run rows
                sharded over the ``sp`` axis, hot-path lookups as
                shard-local scans + one ICI collective (``shard_map`` +
                ``psum``) — the long-context carry-propagating scan of
                SURVEY §5.
- ``sp_apply``— the WRITE side (round 4): sharded insert/delete on the
                same layout — owning-shard splices, fully-parallel
                cross-shard deletes, carry all-gathers over ICI
                (``SpDoc``); state equals the single-device engine.
"""
from .causal import CausalBuffer
from .mesh import (
    make_mesh,
    make_sharded_apply,
    shard_docs,
    shard_ops,
)
from .sp_apply import SpDoc, make_sp_apply
from .sp_runs import make_sp_ops, shard_runs

__all__ = [
    "CausalBuffer",
    "SpDoc",
    "make_mesh",
    "make_sharded_apply",
    "make_sp_apply",
    "make_sp_ops",
    "shard_docs",
    "shard_ops",
    "shard_runs",
]

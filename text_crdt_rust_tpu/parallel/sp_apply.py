"""Sequence-parallel RLE MUTATION: the FULL op surface for one huge doc
sharded over the mesh's ``sp`` axis.

``parallel.sp_runs`` gave the read side (live prefix / rank / order
lookups).  This module is the write side: local edits (r3 missing #4)
AND remote ops (r4 missing #4) — sharded YATA integrate + sharded
remote delete — whose final state equals the single-device engines.

Layout: shard ``s`` owns a PACKED local slice of ``R`` run rows
``(±(order+1), len)`` plus a row count; global document order is the
concatenation of the shards' packed prefixes in ``sp`` order (the mesh
axis plays the B-tree's top levels, `range_tree/mod.rs:85-93`).  The
by-order origin/rank tables (the YATA scan's inputs) are sharded by
ORDER RANGE: shard ``s`` owns orders ``[s*OTS, (s+1)*OTS)``; reads are
one masked local lookup + a psum, writes a masked pass over the owner's
range (an insert run crossing a range boundary writes on both owners).

Per op:

- **local delete** (`mutations.rs:520-570`): every shard clips the
  target live span ``[p, p+d)`` against its own carry-adjusted cumsum
  and flips / boundary-splits INDEPENDENTLY — a delete spanning many
  shards is one fully-parallel pass; the only communication is the
  carry all-gather (one i32 per shard over ICI).
- **local insert** (`mutations.rs:17-179`): exactly one shard owns live
  rank ``p`` (the `root.rs:54-88` descent over shard totals); it
  splices locally (<= 3 touched rows); discovered origins psum-extract
  to every shard, which then records them in its table slice.
- **remote delete** (`doc.rs:295-340`): runs are disjoint ORDER
  intervals, so the target range fully covers every run it touches
  except at most the two holding its endpoints — the same one-pass
  clip as the local delete, keyed by orders; covered DEAD runs count
  toward the idempotency total without flipping
  (`double_delete.rs:6-9`).
- **remote insert** (`doc.rs:167-234`): the YATA conflict scan walks
  raw positions with replicated scan state; each probe resolves its
  char via the owning shard (psum) and its origins via the owning
  table shard (psum).  Conflict-free ops break on the first probe
  (`doc.rs:192-194`), so the while-loop's collective cost is paid per
  CONFLICT, not per op.
- a shard whose slice fills raises the capacity error flag and skips
  the splice; ``SpDoc(auto_reshard=True)`` catches the flag between
  streams, rebalances rows evenly (host-side resharding — the B-tree
  rebuild analog), and retries.

All collectives are XLA-emitted over the ``sp`` axis (shard_map +
all_gather/psum); the same code compiles for a real ICI mesh unchanged.
Tested on the virtual 8-device CPU mesh against ``ops.rle``, the
single-device ``ops.rle_mixed`` storm, and the oracle
(``tests/test_sp_apply.py``); exercised multi-chip by
``__graft_entry__.dryrun_multichip``.
"""
from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common import ROOT_ORDER
from ..ops.batch import (
    KIND_LOCAL,
    KIND_REMOTE_DEL,
    KIND_REMOTE_INS,
    OpTensors,
    require_unfused,
)

ROOT_I = np.int32(np.uint32(ROOT_ORDER))  # -1
TAB_UNKNOWN = -2  # by-order table sentinel: entry not yet known

# Error flag bits (SpDoc.apply_stream decodes).
ERR_CAPACITY = 1
ERR_BAD_DELETE = 2
ERR_NO_OWNER = 4
ERR_ORDER_MISS = 8


def _shift2(x, amt):
    """Rows shifted toward higher indices by traced ``amt`` in {0,1,2}."""
    return jnp.where(amt == 0, x,
                     jnp.where(amt == 1, jnp.roll(x, 1), jnp.roll(x, 2)))


@lru_cache(maxsize=16)
def make_sp_apply(mesh: Mesh, R: int, OTS: int):
    """Build the sharded FULL-SURFACE replayer for ``mesh`` (jitted).

    ``R`` = run-row capacity PER SHARD; ``OTS`` = by-order table rows
    per shard (total order space = nsp*OTS).  Returns ``replay(ordp,
    lenp, rows, oll, orl, rkl, kind, pos, dlen, dtgt, olop, orop, rank,
    ilen, start)`` mapping sharded state + a replicated op stream [S]
    to (new state, per-op origin logs, error flags).

    lru-cached by the full static geometry ``(mesh, R, OTS)`` (Mesh is
    hashable) — two SpDocs with the same geometry share ONE compiled
    replayer instead of re-tracing per doc (the ``_build_call``
    pattern, round-17 allowlist burn-down).
    """
    spec = P("sp")
    none = P()
    nsp = mesh.shape["sp"]

    @partial(shard_map, mesh=mesh,
             in_specs=(spec,) * 6 + (none,) * 9,
             out_specs=(spec,) * 6 + (none, none, none),
             check_rep=False)
    def replay(ordp0, lenp0, rows0, oll0, orl0, rkl0,
               kind, pos, dlen, dtgt, olop, orop, rank, ilen, start):
        idx = jnp.arange(R)
        sidx = lax.axis_index("sp")
        tab_base = sidx * OTS
        tab_g = tab_base + jnp.arange(OTS)  # my slice's global orders

        def gather_carry(lv_total):
            totals = lax.all_gather(lv_total, "sp")
            carry = jnp.sum(jnp.where(jnp.arange(nsp) < sidx, totals, 0))
            return carry, totals

        # ---- by-order table ops (sharded by order range) ---------------

        def tab_read(tab, o):
            """tab[o] (replicated); o < 0 reads 0 — callers mask ROOT."""
            j = jnp.clip(o - tab_base, 0, OTS - 1)
            mine = (o >= tab_base) & (o < tab_base + OTS)
            return lax.psum(jnp.where(mine, tab[j], 0), "sp")

        def tab_write_run(tab, on, st, ln, v):
            """tab[st:st+ln] = v on the owning range shard(s)."""
            hit = on & (tab_g >= st) & (tab_g < st + ln)
            return jnp.where(hit, v, tab)

        def tab_write_chain(tab, on, st, ln, head_val):
            """The insert-run origin_left column: head gets ``head_val``,
            char k > 0 gets its predecessor's order (`span.rs:9-13`)."""
            hit = on & (tab_g >= st) & (tab_g < st + ln)
            return jnp.where(hit,
                             jnp.where(tab_g == st, head_val, tab_g - 1),
                             tab)

        # ---- order -> run / raw-position lookups -----------------------

        def find_order_local(ordp, lenp, o):
            so = jnp.abs(ordp) - 1
            hit = (ordp != 0) & (so <= o) & (o < so + lenp)
            return jnp.any(hit), jnp.argmax(hit)

        def raw_pos_of_order(ordp, lenp, o, need, err):
            """Replicated RAW position of the char with order ``o``."""
            found_l, row = find_order_local(ordp, lenp, o)
            rawcum = jnp.cumsum(lenp)
            raw_before = rawcum[row] - lenp[row]
            off = o - (jnp.abs(ordp[row]) - 1)
            carry, _ = gather_carry(rawcum[-1])
            p = lax.psum(jnp.where(found_l, carry + raw_before + off, 0),
                         "sp")
            found = lax.psum(found_l.astype(jnp.int32), "sp") > 0
            err = err | jnp.where(need & ~found, ERR_ORDER_MISS, 0)
            return p, err

        def cursor_after(ordp, lenp, o, need, err):
            is_root = o == ROOT_I
            # A TAB_UNKNOWN origin (load_tables skipped after a snapshot
            # load) must flag, not silently resolve as order 0 (review
            # r5: jnp.maximum would alias it to an existing char).
            err = err | jnp.where(need & (o == TAB_UNKNOWN),
                                  ERR_ORDER_MISS, 0)
            p, err = raw_pos_of_order(ordp, lenp, jnp.maximum(o, 0),
                                      need & ~is_root, err)
            return jnp.where(is_root, 0, p + 1), err

        def apply_partial(act, i_p, ordp, lenp, cs, ce):
            o = ordp[i_p]
            ln = lenp[i_p]
            cs_i = cs[i_p]
            ce_i = ce[i_p]
            cov_i = ce_i - cs_i
            has_head = (cs_i > 0) & act
            has_tail = (ce_i < ln) & act
            amt = has_head.astype(jnp.int32) + has_tail.astype(jnp.int32)
            so = _shift2(ordp, amt)
            sl = _shift2(lenp, amt)
            no = jnp.where(idx <= i_p, ordp, so)
            nl = jnp.where(idx <= i_p, lenp, sl)
            p0o = jnp.where(has_head, o, -(o + cs_i))
            p0l = jnp.where(has_head, cs_i, cov_i)
            p1o = jnp.where(has_head, -(o + cs_i), o + ce_i)
            p1l = jnp.where(has_head, cov_i, ln - ce_i)
            w0 = act & (idx == i_p)
            no = jnp.where(w0, p0o, no)
            nl = jnp.where(w0, p0l, nl)
            w1 = act & (idx == i_p + 1) & (amt >= 1)
            no = jnp.where(w1, p1o, no)
            nl = jnp.where(w1, p1l, nl)
            w2 = act & (idx == i_p + 2) & (amt == 2)
            no = jnp.where(w2, o + ce_i, no)
            nl = jnp.where(w2, ln - ce_i, nl)
            return no, nl, amt

        def do_delete(ordp, lenp, nrows, err, on, p, d):
            """Every shard retires its intersection of the live span
            [p, p+d) in one clip pass — cross-shard deletes are
            embarrassingly parallel.  No-op (collectives still run,
            keeping the SPMD program unconditional) when ``on`` is
            false."""
            lv = jnp.where(ordp > 0, lenp, 0)
            local = jnp.cumsum(lv)
            carry, _ = gather_carry(local[-1])
            before = carry + local - lv
            rem = jnp.where(on, d, 0)
            cs = jnp.clip(p - before, 0, lv)
            ce = jnp.clip(p + rem - before, 0, lv)
            cov = ce - cs
            covered = lax.psum(jnp.sum(cov), "sp")
            err = err | jnp.where(on & (covered < rem), ERR_BAD_DELETE, 0)

            cap_bad = nrows + 2 > R
            full = (cov > 0) & (cov == lenp)
            part = (cov > 0) & jnp.logical_not(full)
            npart = jnp.sum(part.astype(jnp.int32))
            err = err | jnp.where((npart > 0) & cap_bad, ERR_CAPACITY, 0)
            act = jnp.logical_not(cap_bad)
            i1 = jnp.min(jnp.where(part, idx, R))
            i2 = jnp.max(jnp.where(part, idx, -1))
            # Full-cover flips share the capacity gate: a flagged delete
            # must be a clean no-op, not a half-applied one.
            ordp = jnp.where(full & act, -ordp, ordp)
            ordp, lenp, a2 = apply_partial(
                act & (npart >= 1), i2, ordp, lenp, cs, ce)
            ordp, lenp, a1 = apply_partial(
                act & (npart == 2), i1, ordp, lenp, cs, ce)
            return ordp, lenp, nrows + jnp.where(act, a1 + a2, 0), err

        def do_insert(ordp, lenp, nrows, err, on, p, il, st):
            """One owner shard splices; heads/carries ride two small
            all-gathers; origins psum-extract to every shard.  No-op
            (collectives still run) when ``on`` is false."""
            lv = jnp.where(ordp > 0, lenp, 0)
            local = jnp.cumsum(lv)
            carry, _totals = gather_carry(local[-1])
            owner = on & jnp.where(p == 0, sidx == 0,
                                   (carry < p) & (p <= carry + local[-1]))
            err = err | jnp.where(
                on & (lax.psum(owner.astype(jnp.int32), "sp") == 0),
                ERR_NO_OWNER, 0)
            cap_bad = nrows + 2 > R
            err = err | jnp.where(owner & cap_bad, ERR_CAPACITY, 0)
            active = owner & jnp.logical_not(cap_bad)

            local_rank = p - carry
            i_r = jnp.sum(((local < local_rank) & (idx < nrows))
                          .astype(jnp.int32))
            i_r = jnp.minimum(i_r, R - 1)
            o_r = ordp[i_r]
            l_r = lenp[i_r]
            off = local_rank - (local[i_r] - lv[i_r])

            # Successor across the shard boundary: each shard publishes
            # its head row; the first occupied head PAST this shard is
            # the raw successor when the splice lands at the local end.
            heads = lax.all_gather(jnp.where(nrows > 0, ordp[0], 0), "sp")
            after = (jnp.arange(nsp) > sidx) & (heads != 0)
            nxt_head = jnp.where(jnp.any(after),
                                 heads[jnp.argmax(after)], 0)
            first_head = jnp.where(jnp.any(heads != 0),
                                   heads[jnp.argmax(heads != 0)], 0)

            mrg = (p > 0) & (off == l_r) & ((st + 1) == (o_r + l_r))
            is_split = (p > 0) & (off < l_r)
            left = jnp.where(p == 0, ROOT_I, (o_r - 1) + (off - 1))
            nxt_in_rows = jnp.where(i_r + 1 < nrows,
                                    ordp[jnp.minimum(i_r + 1, R - 1)],
                                    nxt_head)
            succ = jnp.where(p == 0, first_head,
                             jnp.where(is_split, o_r + off, nxt_in_rows))
            right = jnp.where(succ == 0, ROOT_I, jnp.abs(succ) - 1)

            ins_at = jnp.where(p == 0, 0, i_r + 1)
            amt = jnp.where(jnp.logical_not(active) | mrg, 0,
                            jnp.where(is_split, 2, 1))
            so = _shift2(ordp, amt)
            sl = _shift2(lenp, amt)
            no = jnp.where(idx < ins_at, ordp, so)
            nl = jnp.where(idx < ins_at, lenp, sl)
            nl = jnp.where(active & is_split & (idx == i_r), off, nl)
            new_run = active & jnp.logical_not(mrg) & (idx == ins_at)
            no = jnp.where(new_run, st + 1, no)
            nl = jnp.where(new_run, il, nl)
            tail = active & is_split & (idx == ins_at + 1)
            no = jnp.where(tail, o_r + off, no)
            nl = jnp.where(tail, l_r - off, nl)
            nl = jnp.where(active & mrg & (idx == i_r), l_r + il, nl)
            nrows = nrows + amt

            ol = lax.psum(jnp.where(active, left, 0), "sp")
            orr = lax.psum(jnp.where(active, right, 0), "sp")
            any_act = lax.psum(active.astype(jnp.int32), "sp") > 0
            return (no, nl, nrows, err,
                    jnp.where(any_act, ol, 0),
                    jnp.where(any_act, orr, 0), any_act)

        def do_remote_delete(ordp, lenp, nrows, err, on, t, d):
            """One-pass ORDER-interval tombstone (`doc.rs:295-340`):
            runs are disjoint order intervals, so the target range fully
            covers every run it touches except at most the two holding
            its endpoints — the local-delete clip keyed by orders, fully
            parallel across shards.  Covered DEAD runs count toward the
            idempotency total without flipping (`double_delete.rs:6-9`)."""
            so = jnp.abs(ordp) - 1
            occ = ordp != 0
            rem = jnp.where(on, d, 0)
            cs = jnp.clip(t - so, 0, lenp)
            ce = jnp.clip(t + rem - so, 0, lenp)
            cov = jnp.where(occ, ce - cs, 0)
            covered = lax.psum(jnp.sum(cov), "sp")
            err = err | jnp.where(on & (covered < rem), ERR_BAD_DELETE, 0)

            live = ordp > 0
            full = live & (cov > 0) & (cov == lenp)
            part = live & (cov > 0) & jnp.logical_not(cov == lenp)
            npart = jnp.sum(part.astype(jnp.int32))
            # Max growth is +2: one run holding both endpoints 3-way
            # splits (+2), or the two endpoint runs each split one-sided
            # (+1 each) — never +2 per partial (review r5).
            cap_bad = nrows + 2 > R
            err = err | jnp.where(on & (npart > 0) & cap_bad,
                                  ERR_CAPACITY, 0)
            act = on & jnp.logical_not(cap_bad)
            i1 = jnp.min(jnp.where(part, idx, R))
            i2 = jnp.max(jnp.where(part, idx, -1))
            ordp = jnp.where(full & act, -ordp, ordp)
            ordp, lenp, a2 = apply_partial(
                act & (npart >= 1), i2, ordp, lenp, cs, ce)
            ordp, lenp, a1 = apply_partial(
                act & (npart == 2), i1, ordp, lenp, cs, ce)
            return ordp, lenp, nrows + jnp.where(act, a1 + a2, 0), err

        def integrate(ordp, lenp, nrows, oll, orl, rkl, on, my_rank,
                      o_left, o_right, err):
            """The YATA conflict scan (`doc.rs:183-222`) with REPLICATED
            scan state: each probe resolves its char via the owning run
            shard and its origins via the owning table shard (psums).
            Conflict-free ops break on the first probe
            (`doc.rs:192-194`)."""
            rawcum = jnp.cumsum(lenp)
            carry, _ = gather_carry(rawcum[-1])
            n = lax.psum(rawcum[-1], "sp")
            cursor0, err = cursor_after(ordp, lenp, o_left, on, err)
            left_cursor = cursor0

            def cond(state):
                cursor, scanning, scan_start, done, err = state
                return ~done & (cursor < n)

            def body(state):
                cursor, scanning, scan_start, done, err = state
                own = (cursor >= carry) & (cursor < carry + rawcum[-1])
                local = cursor - carry
                i_r = jnp.sum(((rawcum <= local) & (idx < nrows))
                              .astype(jnp.int32))
                i_r = jnp.minimum(i_r, R - 1)
                o_r = lax.psum(jnp.where(own, ordp[i_r], 0), "sp")
                l_r = lax.psum(jnp.where(own, lenp[i_r], 0), "sp")
                off = lax.psum(jnp.where(
                    own, local - (rawcum[i_r] - lenp[i_r]), 0), "sp")
                so = jnp.abs(o_r) - 1
                other_order = so + off
                other_left = tab_read(oll, other_order)
                other_right = tab_read(orl, other_order)
                other_rank = tab_read(rkl, other_order)
                olc, err = cursor_after(ordp, lenp, other_left, ~done,
                                        err)
                brk = (other_order == o_right) | (olc < left_cursor)
                eq = ~brk & (olc == left_cursor)
                gt = my_rank > other_rank
                brk = brk | (eq & ~gt & (o_right == other_right))
                starts_scan = eq & ~gt & (o_right != other_right)
                scan_start = jnp.where(starts_scan & ~scanning, cursor,
                                       scan_start)
                scanning = jnp.where(
                    eq, jnp.where(gt, False,
                                  jnp.where(o_right == other_right,
                                            scanning, True)),
                    scanning)
                contains_right = ((o_right > other_order)
                                  & (o_right < so + l_r))
                stp = jnp.where(contains_right, o_right - other_order,
                                l_r - off)
                cursor = jnp.where(brk, cursor, cursor + stp)
                return cursor, scanning, scan_start, done | brk, err

            f = jnp.asarray(False)
            cursor, scanning, scan_start, _, err = lax.while_loop(
                cond, body, (cursor0, f, cursor0, ~on, err))
            # The scan mutates nothing, so rawcum/carry stay valid for
            # the caller's splice (saves one all-gather per op).
            return (jnp.where(scanning, scan_start, cursor), rawcum,
                    carry, err)

        def do_remote_insert(ordp, lenp, nrows, oll, orl, rkl, err, on,
                             my_rank, o_left, o_right, il, st):
            """`doc.rs:274-293` sharded: integrate to a raw position,
            splice on the owner shard (tombstone-sign-preserving tail;
            merge gated on the origin chain so the YATA run-skip stays
            sound — see ops.rle_lanes_mixed), record origins in the
            order-range tables."""
            c, rawcum, carry, err = integrate(
                ordp, lenp, nrows, oll, orl, rkl, on, my_rank, o_left,
                o_right, err)
            owner = on & jnp.where(c == 0, sidx == 0,
                                   (carry < c) & (c <= carry + rawcum[-1]))
            err = err | jnp.where(
                on & (lax.psum(owner.astype(jnp.int32), "sp") == 0),
                ERR_NO_OWNER, 0)
            cap_bad = nrows + 2 > R
            err = err | jnp.where(owner & cap_bad, ERR_CAPACITY, 0)
            active = owner & jnp.logical_not(cap_bad)

            local = c - carry
            i_r = jnp.sum(((rawcum < local) & (idx < nrows))
                          .astype(jnp.int32))
            i_r = jnp.minimum(i_r, R - 1)
            o_r = ordp[i_r]
            l_r = lenp[i_r]
            off = local - (rawcum[i_r] - lenp[i_r])

            mrg = ((c > 0) & (o_r > 0) & (off == l_r)
                   & ((st + 1) == (o_r + l_r))
                   & (o_left == o_r + l_r - 2))
            is_split = (c > 0) & (off < l_r)
            ins_at = jnp.where(c == 0, 0, i_r + 1)
            amt = jnp.where(jnp.logical_not(active) | mrg, 0,
                            jnp.where(is_split, 2, 1))
            so_s = _shift2(ordp, amt)
            sl_s = _shift2(lenp, amt)
            no = jnp.where(idx < ins_at, ordp, so_s)
            nl = jnp.where(idx < ins_at, lenp, sl_s)
            nl = jnp.where(active & is_split & (idx == i_r), off, nl)
            new_run = active & jnp.logical_not(mrg) & (idx == ins_at)
            no = jnp.where(new_run, st + 1, no)
            nl = jnp.where(new_run, il, nl)
            tail = active & is_split & (idx == ins_at + 1)
            tail_o = jnp.where(o_r > 0, o_r + off, o_r - off)
            no = jnp.where(tail, tail_o, no)
            nl = jnp.where(tail, l_r - off, nl)
            nl = jnp.where(active & mrg & (idx == i_r), l_r + il, nl)
            any_act = lax.psum(active.astype(jnp.int32), "sp") > 0
            return no, nl, nrows + amt, err, any_act

        def step(carry, op):
            ordp, lenp, nrows, oll, orl, rkl, err = carry
            kd, p, d, t, olv, orv, rk, il, st = op
            is_local = kd == KIND_LOCAL
            ri_on = (kd == KIND_REMOTE_INS) & (il > 0)
            ordp, lenp, nrows, err = do_delete(
                ordp, lenp, nrows, err, is_local & (d > 0), p, d)
            ordp, lenp, nrows, err, ol1, or1, li_act = do_insert(
                ordp, lenp, nrows, err, is_local & (il > 0), p, il, st)
            ordp, lenp, nrows, err = do_remote_delete(
                ordp, lenp, nrows, err,
                (kd == KIND_REMOTE_DEL) & (d > 0), t, d)
            ordp, lenp, nrows, err, ri_act = do_remote_insert(
                ordp, lenp, nrows, oll, orl, rkl, err,
                ri_on, rk, olv, orv, il, st)

            # Table upkeep (replicated values, masked to the order-range
            # owners): a local insert records its DISCOVERED origins, a
            # remote insert its given ones; at most one is active per
            # step, and a capacity-blocked splice records nothing.
            ins_on = li_act | ri_act
            head_ol = jnp.where(ri_act, olv, ol1)
            run_or = jnp.where(ri_act, orv, or1)
            oll = tab_write_chain(oll, ins_on, st, il, head_ol)
            orl = tab_write_run(orl, ins_on, st, il, run_or)
            rkl = tab_write_run(rkl, ins_on, st, il, rk)
            ol_out = jnp.where(ri_act, olv, ol1)
            or_out = jnp.where(ri_act, orv, or1)
            return ((ordp, lenp, nrows, oll, orl, rkl, err),
                    (ol_out, or_out))

        nrows0 = rows0[0]
        err0 = jnp.int32(0)
        (ordp, lenp, nrows, oll, orl, rkl, err), (ols, ors) = lax.scan(
            step, (ordp0, lenp0, nrows0, oll0, orl0, rkl0, err0),
            (kind, pos, dlen, dtgt, olop, orop, rank, ilen, start))
        # Bitmask-OR across shards (psum would collide flag bits).
        errs = lax.all_gather(err, "sp")
        err_all = jnp.int32(0)
        for s in range(nsp):
            err_all = err_all | errs[s]
        return (ordp, lenp, nrows[jnp.newaxis], oll, orl, rkl,
                ols.astype(jnp.uint32), ors.astype(jnp.uint32),
                err_all)

    return jax.jit(replay)


class SpDoc:
    """One huge document sharded over the ``sp`` axis: packed per-shard
    run-row slices + counts + order-range table slices, with a host-side
    apply/expand surface for the FULL op stream (local + remote)."""

    def __init__(self, mesh: Mesh, shard_rows: int,
                 order_rows: int = 1024, auto_reshard: bool = False):
        self.mesh = mesh
        self.nsp = mesh.shape["sp"]
        self.R = shard_rows
        self.OTS = order_rows
        self.auto_reshard = auto_reshard
        self._replay = make_sp_apply(mesh, shard_rows, order_rows)
        sharding = NamedSharding(mesh, P("sp"))
        self.ordp = jax.device_put(
            jnp.zeros(self.nsp * shard_rows, jnp.int32), sharding)
        self.lenp = jax.device_put(
            jnp.zeros(self.nsp * shard_rows, jnp.int32), sharding)
        self.rows = jax.device_put(
            jnp.zeros(self.nsp, jnp.int32), sharding)
        self.oll = jax.device_put(
            jnp.full(self.nsp * order_rows, TAB_UNKNOWN, jnp.int32),
            sharding)
        self.orl = jax.device_put(
            jnp.full(self.nsp * order_rows, TAB_UNKNOWN, jnp.int32),
            sharding)
        self.rkl = jax.device_put(
            jnp.zeros(self.nsp * order_rows, jnp.int32), sharding)
        self.ol_log = {}
        self.or_log = {}

    def load(self, ordp: np.ndarray, lenp: np.ndarray) -> None:
        """Reshard an existing document's packed global runs evenly
        across the sp axis (row-balanced).  This is the between-streams
        rebalance: a fresh ``SpDoc`` holds every live rank in shard 0
        (empty shards own no ranks), so long-lived streams load a
        distributed snapshot first and re-load when a shard approaches
        its row budget — the host-side analog of a B-tree rebuild.  The
        by-order tables are keyed by ORDER, not position, so they are
        untouched; a doc loaded from a snapshot must also
        ``load_tables`` before applying REMOTE ops."""
        n = len(ordp)
        assert n <= self.nsp * self.R, (n, self.nsp * self.R)
        per = -(-n // self.nsp)  # ceil: heads get the extra row
        assert per <= self.R
        o2 = np.zeros((self.nsp, self.R), np.int32)
        l2 = np.zeros((self.nsp, self.R), np.int32)
        rows = np.zeros(self.nsp, np.int32)
        at = 0
        for s in range(self.nsp):
            take = min(per, n - at)
            o2[s, :take] = ordp[at:at + take]
            l2[s, :take] = lenp[at:at + take]
            rows[s] = take
            at += take
        sharding = NamedSharding(self.mesh, P("sp"))
        self.ordp = jax.device_put(jnp.asarray(o2.reshape(-1)), sharding)
        self.lenp = jax.device_put(jnp.asarray(l2.reshape(-1)), sharding)
        self.rows = jax.device_put(jnp.asarray(rows), sharding)

    def load_tables(self, oll: np.ndarray, orl: np.ndarray,
                    rkl: np.ndarray) -> None:
        """Load by-order origin/rank tables (1-D [order] arrays, i32,
        ROOT = −1, unknown = −2) — required before REMOTE ops touch
        history that predates this ``SpDoc``."""
        ocap = self.nsp * self.OTS
        sharding = NamedSharding(self.mesh, P("sp"))

        def put(a, fill):
            a = np.asarray(a, np.int32)
            assert len(a) <= ocap, (len(a), ocap)
            out = np.full(ocap, fill, np.int32)
            out[:len(a)] = a
            return jax.device_put(jnp.asarray(out), sharding)

        self.oll = put(oll, TAB_UNKNOWN)
        self.orl = put(orl, TAB_UNKNOWN)
        self.rkl = put(rkl, 0)

    def apply_stream(self, ops: OpTensors) -> None:
        """Apply a compiled op stream (unbatched ``[S]`` columns, any
        kind mix) to the sharded state (one jitted scan; collectives
        over sp).  With ``auto_reshard``, a shard-capacity flag triggers
        one even host-side rebalance + retry (state commits only on a
        clean stream, so the retry replays from the pre-stream state)."""
        kinds = np.asarray(ops.kind)
        assert kinds.ndim == 1, "sp apply takes one unbatched stream"
        require_unfused(ops, "sp apply")
        # Local-only streams may run past the table range (local ops
        # never READ the tables, so SpDoc's local capability stays
        # unbounded); remote ops probe by order, so their order space
        # must fit — out-of-range table writes would silently drop and
        # later probes would mis-resolve.
        if bool((kinds != KIND_LOCAL).any()):
            top_order = int((np.asarray(ops.ins_order_start, np.int64)
                             + np.asarray(ops.ins_len, np.int64)).max(
                                 initial=0))
            assert top_order <= self.nsp * self.OTS, (
                f"order space {top_order} exceeds the table capacity "
                f"{self.nsp * self.OTS}; raise order_rows")
        cols = tuple(
            jnp.asarray(np.asarray(c, dtype=np.uint32).view(np.int32))
            for c in (ops.kind, ops.pos, ops.del_len, ops.del_target,
                      ops.origin_left, ops.origin_right, ops.rank,
                      ops.ins_len, ops.ins_order_start))
        for attempt in (0, 1):
            out = self._replay(self.ordp, self.lenp, self.rows,
                               self.oll, self.orl, self.rkl, *cols)
            ordp, lenp, rows, oll, orl, rkl, ols, ors, err = out
            # Commit state only on a clean stream: a flagged stream is
            # half-applied and the pre-stream state is what recovery
            # (reshard + replay) needs.
            err = int(np.asarray(err).max())
            if not err:
                self.ordp, self.lenp, self.rows = ordp, lenp, rows
                self.oll, self.orl, self.rkl = oll, orl, rkl
                break
            if (err & ERR_CAPACITY) and self.auto_reshard and attempt == 0:
                # Even rebalance, then retry once from pre-stream state.
                self.load(*self.runs())
                continue
            if err & ERR_CAPACITY:
                raise RuntimeError(
                    "sp shard capacity exhausted; reshard with a larger "
                    "per-shard row budget")
            if err & ERR_BAD_DELETE:
                raise RuntimeError(
                    "delete ran past the end of the document")
            if err & ERR_NO_OWNER:
                raise RuntimeError(
                    "insert rank beyond the document length")
            if err & ERR_ORDER_MISS:
                raise RuntimeError(
                    "order lookup missed: an op referenced an order "
                    "absent from device state (load_tables missing?)")
        starts = np.asarray(ops.ins_order_start, np.int64)
        ilens = np.asarray(ops.ins_len, np.int64)
        ol_np = np.asarray(ols)
        or_np = np.asarray(ors)
        for s, (st, il) in enumerate(zip(starts, ilens)):
            if il > 0:
                self.ol_log[int(st)] = int(ol_np[s])
                self.or_log[int(st)] = int(or_np[s])

    def runs(self) -> tuple:
        """Host copy of the global packed runs: (ordp, lenp) 1-D."""
        o = np.asarray(self.ordp).reshape(self.nsp, self.R)
        l = np.asarray(self.lenp).reshape(self.nsp, self.R)
        r = np.asarray(self.rows)
        o_parts = [o[s, :r[s]] for s in range(self.nsp)]
        l_parts = [l[s, :r[s]] for s in range(self.nsp)]
        return (np.concatenate(o_parts) if o_parts else np.zeros(0, np.int32),
                np.concatenate(l_parts) if l_parts else np.zeros(0, np.int32))

    def expand(self) -> np.ndarray:
        """Per-char ±(order+1) column in document order (host)."""
        o, ln = self.runs()
        if len(o) == 0:
            return np.zeros(0, np.int32)
        o = o.astype(np.int64)
        ln = ln.astype(np.int64)
        assert (ln > 0).all(), "occupied run with non-positive length"
        total = int(ln.sum())
        base = np.repeat(np.abs(o), ln)
        within = np.arange(total) - np.repeat(np.cumsum(ln) - ln, ln)
        return (np.repeat(np.sign(o), ln) * (base + within)).astype(np.int32)

    def to_string(self, ops_list) -> str:
        """Materialize content from the expanded orders + op streams'
        char logs (the device state stores orders, not text)."""
        chars = {}
        for ops in ops_list:
            ilens = np.asarray(ops.ins_len)
            starts = np.asarray(ops.ins_order_start, np.int64)
            cps = np.asarray(ops.chars)
            for s in np.nonzero(ilens)[0]:
                for j in range(int(ilens[s])):
                    chars[int(starts[s]) + j] = chr(int(cps[s, j]))
        flat = self.expand()
        return "".join(chars[int(x) - 1] for x in flat if x > 0)

"""Sequence-parallel RLE MUTATION: sharded insert/delete for one huge doc.

``parallel.sp_runs`` gave the read side (live prefix / rank / order
lookups) of a document whose run rows are sharded over the mesh's ``sp``
axis.  This module adds the WRITE side the r3 verdict called missing #4:
a sharded local-edit apply whose final state equals the single-device
engine.

Layout: shard ``s`` owns a PACKED local slice of ``R`` run rows
``(±(order+1), len)`` plus a row count; global document order is the
concatenation of the shards' packed prefixes in ``sp`` order (the mesh
axis plays the B-tree's top levels, `range_tree/mod.rs:85-93`).  Per op:

- **delete** (`mutations.rs:520-570`): every shard clips the target live
  span ``[p, p+d)`` against its own carry-adjusted cumsum and flips /
  boundary-splits INDEPENDENTLY — a delete spanning many shards is one
  fully-parallel pass, no sequential walk.  The only communication is
  the carry all-gather (one i32 per shard over ICI).
- **insert** (`mutations.rs:17-179`): exactly one shard owns live rank
  ``p`` (the `root.rs:54-88` descent over shard totals); it splices
  locally (<= 3 touched rows).  The origin_right successor at a shard's
  end comes from an all-gather of each shard's head row; the discovered
  origins are psum-extracted so every shard logs them (replicated).
- a shard whose slice fills raises the capacity error flag and skips
  the splice (no redistribution mid-stream — the analog of the block
  engines' split-at-capacity no-op; rebalance is a host-side resharding
  between streams).

All collectives are XLA-emitted over the ``sp`` axis (shard_map +
all_gather/psum); the same code compiles for a real ICI mesh unchanged.
Tested on the virtual 8-device CPU mesh against ``ops.rle`` and the
string oracle (``tests/test_sp_apply.py``); exercised multi-chip by
``__graft_entry__.dryrun_multichip``.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common import ROOT_ORDER
from ..ops.batch import KIND_LOCAL, OpTensors

ROOT_I = np.int32(np.uint32(ROOT_ORDER))  # -1


def _shift2(x, amt):
    """Rows shifted toward higher indices by traced ``amt`` in {0,1,2}."""
    return jnp.where(amt == 0, x,
                     jnp.where(amt == 1, jnp.roll(x, 1), jnp.roll(x, 2)))


def make_sp_apply(mesh: Mesh, R: int):
    """Build the sharded local-edit replayer for ``mesh`` (jitted).

    ``R`` = run-row capacity PER SHARD.  Returns ``replay(ordp, lenp,
    rows, pos, dlen, ilen, start)`` mapping sharded state ``[sp*R]``
    planes + ``[sp]`` row counts and a replicated op stream ``[S]`` to
    (new state, per-op origin logs, error flags).
    """
    spec = P("sp")
    none = P()
    nsp = mesh.shape["sp"]

    @partial(shard_map, mesh=mesh,
             in_specs=(spec, spec, spec, none, none, none, none),
             out_specs=(spec, spec, spec, none, none, none),
             check_rep=False)
    def replay(ordp0, lenp0, rows0, pos, dlen, ilen, start):
        idx = jnp.arange(R)
        sidx = lax.axis_index("sp")

        def gather_carry(lv_total):
            totals = lax.all_gather(lv_total, "sp")
            carry = jnp.sum(jnp.where(jnp.arange(nsp) < sidx, totals, 0))
            return carry, totals

        def apply_partial(act, i_p, ordp, lenp, cs, ce):
            o = ordp[i_p]
            ln = lenp[i_p]
            cs_i = cs[i_p]
            ce_i = ce[i_p]
            cov_i = ce_i - cs_i
            has_head = (cs_i > 0) & act
            has_tail = (ce_i < ln) & act
            amt = has_head.astype(jnp.int32) + has_tail.astype(jnp.int32)
            so = _shift2(ordp, amt)
            sl = _shift2(lenp, amt)
            no = jnp.where(idx <= i_p, ordp, so)
            nl = jnp.where(idx <= i_p, lenp, sl)
            p0o = jnp.where(has_head, o, -(o + cs_i))
            p0l = jnp.where(has_head, cs_i, cov_i)
            p1o = jnp.where(has_head, -(o + cs_i), o + ce_i)
            p1l = jnp.where(has_head, cov_i, ln - ce_i)
            w0 = act & (idx == i_p)
            no = jnp.where(w0, p0o, no)
            nl = jnp.where(w0, p0l, nl)
            w1 = act & (idx == i_p + 1) & (amt >= 1)
            no = jnp.where(w1, p1o, no)
            nl = jnp.where(w1, p1l, nl)
            w2 = act & (idx == i_p + 2) & (amt == 2)
            no = jnp.where(w2, o + ce_i, no)
            nl = jnp.where(w2, ln - ce_i, nl)
            return no, nl, amt

        def do_delete(ordp, lenp, nrows, err, p, d):
            """Every shard retires its intersection of the live span
            [p, p+d) in one clip pass — cross-shard deletes are
            embarrassingly parallel.  No-op (collectives still run,
            keeping the SPMD program unconditional) when ``d == 0``."""
            on = d > 0
            lv = jnp.where(ordp > 0, lenp, 0)
            local = jnp.cumsum(lv)
            carry, _ = gather_carry(local[-1])
            before = carry + local - lv
            rem = jnp.where(on, d, 0)
            cs = jnp.clip(p - before, 0, lv)
            ce = jnp.clip(p + rem - before, 0, lv)
            cov = ce - cs
            covered = lax.psum(jnp.sum(cov), "sp")
            err = err | jnp.where(on & (covered < rem), 2, 0)

            cap_bad = nrows + 2 > R
            full = (cov > 0) & (cov == lenp)
            part = (cov > 0) & jnp.logical_not(full)
            npart = jnp.sum(part.astype(jnp.int32))
            err = err | jnp.where((npart > 0) & cap_bad, 1, 0)
            act = jnp.logical_not(cap_bad)
            i1 = jnp.min(jnp.where(part, idx, R))
            i2 = jnp.max(jnp.where(part, idx, -1))
            # Full-cover flips share the capacity gate: a flagged delete
            # must be a clean no-op, not a half-applied one.
            ordp = jnp.where(full & act, -ordp, ordp)
            ordp, lenp, a2 = apply_partial(
                act & (npart >= 1), i2, ordp, lenp, cs, ce)
            ordp, lenp, a1 = apply_partial(
                act & (npart == 2), i1, ordp, lenp, cs, ce)
            return ordp, lenp, nrows + jnp.where(act, a1 + a2, 0), err

        def do_insert(ordp, lenp, nrows, err, p, il, st):
            """One owner shard splices; heads/carries ride two small
            all-gathers; origins psum-extract to every shard.  No-op
            (collectives still run) when ``il == 0``."""
            on = il > 0
            lv = jnp.where(ordp > 0, lenp, 0)
            local = jnp.cumsum(lv)
            carry, _totals = gather_carry(local[-1])
            owner = on & jnp.where(p == 0, sidx == 0,
                                   (carry < p) & (p <= carry + local[-1]))
            err = err | jnp.where(
                on & (lax.psum(owner.astype(jnp.int32), "sp") == 0), 4, 0)
            cap_bad = nrows + 2 > R
            err = err | jnp.where(owner & cap_bad, 1, 0)
            active = owner & jnp.logical_not(cap_bad)

            local_rank = p - carry
            i_r = jnp.sum(((local < local_rank) & (idx < nrows))
                          .astype(jnp.int32))
            i_r = jnp.minimum(i_r, R - 1)
            o_r = ordp[i_r]
            l_r = lenp[i_r]
            off = local_rank - (local[i_r] - lv[i_r])

            # Successor across the shard boundary: each shard publishes
            # its head row; the first occupied head PAST this shard is
            # the raw successor when the splice lands at the local end.
            heads = lax.all_gather(jnp.where(nrows > 0, ordp[0], 0), "sp")
            after = (jnp.arange(nsp) > sidx) & (heads != 0)
            nxt_head = jnp.where(jnp.any(after),
                                 heads[jnp.argmax(after)], 0)
            first_head = jnp.where(jnp.any(heads != 0),
                                   heads[jnp.argmax(heads != 0)], 0)

            mrg = (p > 0) & (off == l_r) & ((st + 1) == (o_r + l_r))
            is_split = (p > 0) & (off < l_r)
            left = jnp.where(p == 0, ROOT_I, (o_r - 1) + (off - 1))
            nxt_in_rows = jnp.where(i_r + 1 < nrows,
                                    ordp[jnp.minimum(i_r + 1, R - 1)],
                                    nxt_head)
            succ = jnp.where(p == 0, first_head,
                             jnp.where(is_split, o_r + off, nxt_in_rows))
            right = jnp.where(succ == 0, ROOT_I, jnp.abs(succ) - 1)

            ins_at = jnp.where(p == 0, 0, i_r + 1)
            amt = jnp.where(jnp.logical_not(active) | mrg, 0,
                            jnp.where(is_split, 2, 1))
            so = _shift2(ordp, amt)
            sl = _shift2(lenp, amt)
            no = jnp.where(idx < ins_at, ordp, so)
            nl = jnp.where(idx < ins_at, lenp, sl)
            nl = jnp.where(active & is_split & (idx == i_r), off, nl)
            new_run = active & jnp.logical_not(mrg) & (idx == ins_at)
            no = jnp.where(new_run, st + 1, no)
            nl = jnp.where(new_run, il, nl)
            tail = active & is_split & (idx == ins_at + 1)
            no = jnp.where(tail, o_r + off, no)
            nl = jnp.where(tail, l_r - off, nl)
            nl = jnp.where(active & mrg & (idx == i_r), l_r + il, nl)
            nrows = nrows + amt

            ol = lax.psum(jnp.where(active, left, 0), "sp")
            orr = lax.psum(jnp.where(active, right, 0), "sp")
            any_act = lax.psum(active.astype(jnp.int32), "sp") > 0
            return (no, nl, nrows, err,
                    jnp.where(any_act, ol, 0),
                    jnp.where(any_act, orr, 0))

        def step(carry, op):
            ordp, lenp, nrows, err = carry
            p, d, il, st = op
            ordp, lenp, nrows, err = do_delete(ordp, lenp, nrows, err, p, d)
            ordp, lenp, nrows, err, ol, orr = do_insert(
                ordp, lenp, nrows, err, p, il, st)
            return (ordp, lenp, nrows, err), (ol, orr)

        nrows0 = rows0[0]
        err0 = jnp.int32(0)
        (ordp, lenp, nrows, err), (ols, ors) = lax.scan(
            step, (ordp0, lenp0, nrows0, err0),
            (pos, dlen, ilen, start))
        # Bitmask-OR across shards (psum would collide flag bits).
        errs = lax.all_gather(err, "sp")
        err_all = jnp.int32(0)
        for s in range(nsp):
            err_all = err_all | errs[s]
        return (ordp, lenp, nrows[jnp.newaxis],
                ols.astype(jnp.uint32), ors.astype(jnp.uint32),
                err_all)

    return jax.jit(replay)


class SpDoc:
    """One huge document sharded over the ``sp`` axis: packed per-shard
    run-row slices + counts, with a host-side apply/expand surface."""

    def __init__(self, mesh: Mesh, shard_rows: int):
        self.mesh = mesh
        self.nsp = mesh.shape["sp"]
        self.R = shard_rows
        self._replay = make_sp_apply(mesh, shard_rows)
        sharding = NamedSharding(mesh, P("sp"))
        self.ordp = jax.device_put(
            jnp.zeros(self.nsp * shard_rows, jnp.int32), sharding)
        self.lenp = jax.device_put(
            jnp.zeros(self.nsp * shard_rows, jnp.int32), sharding)
        self.rows = jax.device_put(
            jnp.zeros(self.nsp, jnp.int32), sharding)
        self.ol_log = {}
        self.or_log = {}

    def load(self, ordp: np.ndarray, lenp: np.ndarray) -> None:
        """Reshard an existing document's packed global runs evenly
        across the sp axis (row-balanced).  This is the between-streams
        rebalance: a fresh ``SpDoc`` holds every live rank in shard 0
        (empty shards own no ranks), so long-lived streams load a
        distributed snapshot first and re-load when a shard approaches
        its row budget — the host-side analog of a B-tree rebuild."""
        n = len(ordp)
        assert n <= self.nsp * self.R, (n, self.nsp * self.R)
        per = -(-n // self.nsp)  # ceil: heads get the extra row
        assert per <= self.R
        o2 = np.zeros((self.nsp, self.R), np.int32)
        l2 = np.zeros((self.nsp, self.R), np.int32)
        rows = np.zeros(self.nsp, np.int32)
        at = 0
        for s in range(self.nsp):
            take = min(per, n - at)
            o2[s, :take] = ordp[at:at + take]
            l2[s, :take] = lenp[at:at + take]
            rows[s] = take
            at += take
        sharding = NamedSharding(self.mesh, P("sp"))
        self.ordp = jax.device_put(jnp.asarray(o2.reshape(-1)), sharding)
        self.lenp = jax.device_put(jnp.asarray(l2.reshape(-1)), sharding)
        self.rows = jax.device_put(jnp.asarray(rows), sharding)

    def apply_stream(self, ops: OpTensors) -> None:
        """Apply a compiled LOCAL op stream (unbatched ``[S]`` columns)
        to the sharded state (one jitted scan; collectives over sp)."""
        kinds = np.asarray(ops.kind)
        assert kinds.ndim == 1, "sp apply takes one unbatched stream"
        assert bool((kinds == KIND_LOCAL).all()), \
            "sp apply replays local streams"
        cols = tuple(
            jnp.asarray(np.asarray(c, dtype=np.uint32).view(np.int32))
            for c in (ops.pos, ops.del_len, ops.ins_len,
                      ops.ins_order_start))
        ordp, lenp, rows, ols, ors, err = self._replay(
            self.ordp, self.lenp, self.rows, *cols)
        # Commit state only on a clean stream: a flagged stream is
        # half-applied and the pre-stream state is what recovery
        # (reshard + replay) needs.
        err = int(np.asarray(err).max())
        if not err:
            self.ordp, self.lenp, self.rows = ordp, lenp, rows
        if err & 1:
            raise RuntimeError("sp shard capacity exhausted; reshard with "
                               "a larger per-shard row budget")
        if err & 2:
            raise RuntimeError("delete ran past the end of the document")
        if err & 4:
            raise RuntimeError("insert rank beyond the document length")
        starts = np.asarray(ops.ins_order_start, np.int64)
        ilens = np.asarray(ops.ins_len, np.int64)
        ol_np = np.asarray(ols)
        or_np = np.asarray(ors)
        for s, (st, il) in enumerate(zip(starts, ilens)):
            if il > 0:
                self.ol_log[int(st)] = int(ol_np[s])
                self.or_log[int(st)] = int(or_np[s])

    def runs(self) -> tuple:
        """Host copy of the global packed runs: (ordp, lenp) 1-D."""
        o = np.asarray(self.ordp).reshape(self.nsp, self.R)
        l = np.asarray(self.lenp).reshape(self.nsp, self.R)
        r = np.asarray(self.rows)
        o_parts = [o[s, :r[s]] for s in range(self.nsp)]
        l_parts = [l[s, :r[s]] for s in range(self.nsp)]
        return (np.concatenate(o_parts) if o_parts else np.zeros(0, np.int32),
                np.concatenate(l_parts) if l_parts else np.zeros(0, np.int32))

    def expand(self) -> np.ndarray:
        """Per-char ±(order+1) column in document order (host)."""
        o, ln = self.runs()
        if len(o) == 0:
            return np.zeros(0, np.int32)
        o = o.astype(np.int64)
        ln = ln.astype(np.int64)
        assert (ln > 0).all(), "occupied run with non-positive length"
        total = int(ln.sum())
        base = np.repeat(np.abs(o), ln)
        within = np.arange(total) - np.repeat(np.cumsum(ln) - ln, ln)
        return (np.repeat(np.sign(o), ln) * (base + within)).astype(np.int32)

    def to_string(self, ops_list) -> str:
        """Materialize content from the expanded orders + op streams'
        char logs (the device state stores orders, not text)."""
        chars = {}
        for ops in ops_list:
            ilens = np.asarray(ops.ins_len)
            starts = np.asarray(ops.ins_order_start, np.int64)
            cps = np.asarray(ops.chars)
            for s in np.nonzero(ilens)[0]:
                for j in range(int(ilens[s])):
                    chars[int(starts[s]) + j] = chr(int(cps[s, j]))
        flat = self.expand()
        return "".join(chars[int(x) - 1] for x in flat if x > 0)

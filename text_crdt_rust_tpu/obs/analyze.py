"""Trace analytics over obs/ JSONL streams and flight-recorder bundles
(ISSUE 10 tentpole, part 3).

The tracer (obs/trace.py) answers "record everything"; this module
answers the questions a regression hunt or a post-mortem actually asks:

- ``phases``     — per-tick phase critical-path breakdown (drain /
  fuse / capacity / device / barrier): where a tick's wall time went,
  aggregated and for the slowest ticks; ``--stall-budget`` appends the
  one-number headline (top phase by total wall, % of tick) the
  pipelined-tick work gates on;
- ``overlap``    — host-vs-device occupancy (ISSUE 12): per tick, host
  wall (drain/fuse/capacity + residency checkpoint I/O) vs dispatch
  wall vs the barrier's idle gap, and the pipelined batcher's
  ``overlap_frac`` (device-sync demand hidden under host work);
- ``hotdocs``    — apply-event volume by doc (who is hot);
- ``fuse``       — fusion efficiency by doc (steps in vs out);
- ``recompiles`` — the ``device.compile`` timeline (steady state must
  stop emitting these — a late entry IS the bug);
- ``diff``       — two-trace same-seed logical diff: strips the
  segregated wall fields and names the FIRST diverging event
  (complementing the flight recorder's item walk, which names the
  first diverging *item* of the end state);
- ``chrome``     — Chrome trace-event export (Perfetto-loadable): the
  segregated wall-clock spans laid over the LOGICAL tick axis, so a
  human can scrub a tick timeline even though the trace backbone is
  causal, not temporal — plus flow-event arrows (``ph: "s"/"t"/"f"``)
  linking each sampled op's ``flow.*`` span across its tick phases;
- ``flow``       — per-op provenance census (obs/flow.py): span
  terminal states, op-age-at-apply distributions per popularity band
  and fault class; ``--audit`` turns conservation into an exit code —
  0 iff every emitted span is terminally accounted, else 1 naming the
  first leaked/double-applied span.

All analysis functions are pure (events in, dict out) so tests can
golden them; the CLI renders text or ``--json``.  Inputs: trace JSONL
files (several = rotated segments, read in order) or flight-recorder
bundle JSONs (their ``events`` list is the same schema).

    python -m text_crdt_rust_tpu.obs.analyze phases trace.jsonl
    python -m text_crdt_rust_tpu.obs.analyze diff good.jsonl bad.jsonl
    python -m text_crdt_rust_tpu.obs.analyze chrome trace.jsonl -o t.json
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from .trace import WALL_KEY

#: The serving-loop phases, in intra-tick order (the batcher emits them
#: in this sequence; ``tick.fuse`` rides inside the drain).
PHASES = ("tick.drain", "tick.fuse", "tick.capacity", "tick.device",
          "tick.barrier")

#: Logical-tick pitch of the chrome export, in trace microseconds: each
#: tick owns a fixed slot on the time axis, and measured wall spans are
#: drawn inside their tick's slot.
CHROME_TICK_US = 1000.0


def load_events(paths: Sequence[str]) -> List[dict]:
    """Events from one or more trace JSONL segments (rotated segments
    concatenate in argument order) or flight-recorder bundle JSONs."""
    events: List[dict] = []
    for path in paths:
        with open(path) as f:
            first = f.readline().strip()
            try:
                head = json.loads(first)
            except json.JSONDecodeError:
                # Not one-object-per-line: a pretty-printed flight-
                # recorder bundle (first line is just the brace).
                f.seek(0)
                events.extend(json.load(f).get("events", []))
                continue
            # A trace stream.  A crash-truncated final line is EXPECTED
            # post-mortem input (the tracer is line-buffered precisely
            # because processes die mid-run): keep the valid prefix and
            # say what was dropped instead of refusing the whole file.
            events.append(head)
            for lineno, line in enumerate(f, start=2):
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    print(f"{path}:{lineno}: truncated/corrupt line — "
                          f"keeping the {len(events)}-event prefix",
                          file=sys.stderr)
                    break
    return events


def logical(ev: dict) -> dict:
    """The event's logical projection (wall fields stripped)."""
    if WALL_KEY in ev:
        return {k: v for k, v in ev.items() if k != WALL_KEY}
    return ev


def _wall_ms(ev: dict) -> float:
    w = ev.get(WALL_KEY)
    return float(w.get("ms", 0.0)) if isinstance(w, dict) else 0.0


# ------------------------------------------------------------- analyses --


def phase_breakdown(events: Sequence[dict], slowest: int = 5) -> dict:
    """Per-tick phase critical path: wall ms per phase per tick (from
    the segregated ``"w"`` fields), aggregated per phase plus the
    ``slowest`` worst ticks in full."""
    per_tick: Dict[int, Dict[str, float]] = {}
    phase_events: Dict[str, int] = {p: 0 for p in PHASES}
    for ev in events:
        k = ev.get("k")
        if k not in PHASES:
            continue
        phase_events[k] += 1
        row = per_tick.setdefault(int(ev["t"]), {p: 0.0 for p in PHASES})
        row[k] += _wall_ms(ev)
    totals = {p: round(sum(r[p] for r in per_tick.values()), 3)
              for p in PHASES}
    wall_total = sum(totals.values())
    tick_rows = [
        {"tick": t, **{p: round(r[p], 3) for p in PHASES},
         "total_ms": round(sum(r.values()), 3)}
        for t, r in sorted(per_tick.items())
    ]
    return {
        "ticks": len(per_tick),
        "events": len(events),
        "wall_ms_total": round(wall_total, 3),
        "phases": {
            p: {
                "events": phase_events[p],
                "wall_ms": totals[p],
                "share_pct": round(totals[p] / wall_total * 100.0, 1)
                if wall_total else 0.0,
            }
            for p in PHASES
        },
        "slowest_ticks": sorted(tick_rows, key=lambda r: -r["total_ms"]
                                )[:slowest],
    }


def stall_budget(breakdown: dict) -> dict:
    """The one-number headline of a phase breakdown: the phase that
    owns the most measured wall and its share of the total — what the
    pipelined-tick refactor (ISSUE 12) must shrink, read before/after
    from any trace."""
    phases = breakdown["phases"]
    top = max(phases, key=lambda p: phases[p]["wall_ms"]) if phases \
        else None
    if top is None or not breakdown["wall_ms_total"]:
        return {"phase": None, "wall_ms": 0.0, "share_pct": 0.0}
    return {"phase": top, "wall_ms": phases[top]["wall_ms"],
            "share_pct": phases[top]["share_pct"]}


#: Host-phase walls the overlap report counts as work the pipelined
#: tick can hide an in-flight device step under; ``tick.device`` is the
#: dispatch (enqueue) wall, ``tick.barrier`` the residual sync stall.
OVERLAP_HOST_KINDS = ("tick.drain", "tick.fuse", "tick.capacity",
                      "residency.evict", "residency.restore")


def overlap_report(events: Sequence[dict], slowest: int = 5) -> dict:
    """Host-vs-device occupancy of the serving loop (ISSUE 12): per
    tick, measured host wall (drain/fuse/capacity + residency
    checkpoint I/O), dispatch wall, and the barrier's idle gap — the
    stall the staged sync still paid ("ms") vs the host window the
    in-flight device step got to hide under ("win", stamped by the
    pipelined batcher).  ``overlap_frac`` = win / (win + stall): 0 in
    the serial loop, -> 1 when host work fully hides device time."""
    per_tick: Dict[int, Dict[str, float]] = {}

    def row(t: int) -> Dict[str, float]:
        return per_tick.setdefault(int(t), {
            "host_ms": 0.0, "dispatch_ms": 0.0, "stall_ms": 0.0,
            "win_ms": 0.0})

    for ev in events:
        k = ev.get("k")
        if k in OVERLAP_HOST_KINDS:
            row(ev["t"])["host_ms"] += _wall_ms(ev)
        elif k == "tick.device":
            row(ev["t"])["dispatch_ms"] += _wall_ms(ev)
        elif k == "tick.barrier":
            r = row(ev["t"])
            r["stall_ms"] += _wall_ms(ev)
            w = ev.get(WALL_KEY)
            if isinstance(w, dict):
                r["win_ms"] += float(w.get("win", 0.0))
    from ..utils.metrics import percentiles

    tot = {key: round(sum(r[key] for r in per_tick.values()), 3)
           for key in ("host_ms", "dispatch_ms", "stall_ms", "win_ms")}
    busy = tot["host_ms"] + tot["dispatch_ms"] + tot["stall_ms"]
    hidden = tot["win_ms"] + tot["stall_ms"]
    stalls = [r["stall_ms"] for r in per_tick.values()]
    gap = {k: round(v, 3)
           for k, v in percentiles(stalls, (50, 99)).items()}
    gap["max"] = round(max(stalls), 3) if stalls else 0.0
    tick_rows = [{"tick": t, **{k: round(v, 3) for k, v in r.items()}}
                 for t, r in sorted(per_tick.items())]
    return {
        "ticks": len(per_tick),
        **tot,
        "overlap_frac": round(tot["win_ms"] / hidden, 4) if hidden
        else 0.0,
        "stall_share_pct": round(tot["stall_ms"] / busy * 100.0, 1)
        if busy else 0.0,
        "idle_gap_ms": gap,
        "worst_ticks": sorted(tick_rows,
                              key=lambda r: -r["stall_ms"])[:slowest],
    }


def hot_docs(events: Sequence[dict], top: int = 10) -> dict:
    """Apply-event volume by doc: events and item-ops, hottest first."""
    per: Dict[str, Dict[str, int]] = {}
    for ev in events:
        if ev.get("k") != "apply":
            continue
        row = per.setdefault(ev["doc"], {"events": 0, "items": 0})
        row["events"] += 1
        row["items"] += int(ev.get("n", 0))
    ranked = sorted(per.items(), key=lambda kv: (-kv[1]["items"], kv[0]))
    return {
        "docs": len(per),
        "apply_events": sum(r["events"] for r in per.values()),
        "item_ops": sum(r["items"] for r in per.values()),
        "top": [{"doc": d, **r} for d, r in ranked[:top]],
    }


def fusion_table(events: Sequence[dict], top: int = 10) -> dict:
    """Fusion efficiency by doc from ``tick.fuse`` events (emitted only
    when a stream actually fused): steps in vs out, rows saved."""
    per: Dict[str, Dict[str, int]] = {}
    for ev in events:
        if ev.get("k") != "tick.fuse":
            continue
        row = per.setdefault(ev["doc"], {"steps_in": 0, "steps_out": 0,
                                         "fused_ticks": 0})
        row["steps_in"] += int(ev["steps_in"])
        row["steps_out"] += int(ev["steps_out"])
        row["fused_ticks"] += 1
    for row in per.values():
        row["rows_saved"] = row["steps_in"] - row["steps_out"]
    ranked = sorted(per.items(),
                    key=lambda kv: (-kv[1]["rows_saved"], kv[0]))
    tin = sum(r["steps_in"] for r in per.values())
    tout = sum(r["steps_out"] for r in per.values())
    return {
        "fused_docs": len(per),
        "steps_in": tin,
        "steps_out": tout,
        "rows_saved": tin - tout,
        "reduction_x": round(tin / tout, 3) if tout else 1.0,
        "top": [{"doc": d, **r} for d, r in ranked[:top]],
    }


def recompile_timeline(events: Sequence[dict]) -> dict:
    """Every ``device.compile`` event in logical order.  Steady-state
    serving must stop emitting these: any entry past the warm-up ticks
    is a fixed-shape-contract violation worth a bisect."""
    compiles = [{"tick": int(ev["t"]), "i": int(ev["i"]),
                 "shard": ev["shard"], "bucket": ev["bucket"]}
                for ev in events if ev.get("k") == "device.compile"]
    last_tick = max((int(ev["t"]) for ev in events), default=0)
    return {
        "compiles": len(compiles),
        "last_compile_tick": compiles[-1]["tick"] if compiles else None,
        "run_last_tick": last_tick,
        "timeline": compiles,
    }


def trace_diff(a: Sequence[dict], b: Sequence[dict]) -> Optional[dict]:
    """Two-trace same-seed LOGICAL diff: the first event whose logical
    projection differs, with the changed field names — ``None`` when
    the logical streams are identical.  This is the cluster-debugging
    primitive (ROADMAP 2): a good and a bad same-seed run localize to
    the first diverging *event*, no re-run needed."""
    n = min(len(a), len(b))
    for idx in range(n):
        ea, eb = logical(a[idx]), logical(b[idx])
        if ea != eb:
            fields = sorted(
                k for k in set(ea) | set(eb) if ea.get(k) != eb.get(k))
            return {"index": idx, "tick": ea.get("t", eb.get("t")),
                    "fields": fields, "a": ea, "b": eb}
    if len(a) != len(b):
        longer, which = (a, "a") if len(a) > len(b) else (b, "b")
        return {"index": n, "tick": logical(longer[n]).get("t"),
                "only_in": which, which: logical(longer[n]),
                "fields": ["<stream length>"],
                "lengths": {"a": len(a), "b": len(b)}}
    return None


def chrome_trace(events: Sequence[dict]) -> dict:
    """Chrome trace-event JSON (load in Perfetto / chrome://tracing):
    the logical tick axis becomes the time axis (one tick =
    ``CHROME_TICK_US`` trace-µs), measured wall spans render as
    duration events inside their tick's slot, and wall-less logical
    events render as instants — so the *causal* trace gets a scrubbable
    timeline without pretending host wall-clock ordered it."""
    out: List[dict] = []
    seen_pids = set()
    tick_idx: Dict[int, int] = {}
    # Flow arrows (ISSUE 11 satellite): each sampled op's flow.* events
    # chain into one Perfetto flow — ph "s" at the first lifecycle
    # event, "t" steps, "f" at the last — so one op's journey is
    # visible ACROSS tick slots alongside the per-tick phase track.
    # Span identity for the arrow id: (doc, agent, seq) for remote
    # spans, (doc, agent, lk) for local ones.
    flow_groups: Dict[tuple, List[int]] = {}
    for idx, ev in enumerate(events):
        if str(ev.get("k", "")).startswith("flow."):
            key = (ev.get("doc"), ev.get("agent"),
                   "lk", ev["lk"]) if "lk" in ev else \
                  (ev.get("doc"), ev.get("agent"), "seq", ev.get("seq"))
            flow_groups.setdefault(key, []).append(idx)
    flow_mark: Dict[int, Tuple[str, str]] = {}
    for key, idxs in flow_groups.items():
        if len(idxs) < 2:
            continue  # an arrow needs two ends
        fid = "/".join(str(p) for p in key)
        flow_mark[idxs[0]] = ("s", fid)
        for j in idxs[1:-1]:
            flow_mark[j] = ("t", fid)
        flow_mark[idxs[-1]] = ("f", fid)
    for idx, ev in enumerate(events):
        kind = ev.get("k", "?")
        pid = int(ev.get("shard", 0)) if isinstance(
            ev.get("shard"), int) else 0
        if pid not in seen_pids:
            seen_pids.add(pid)
            out.append({"name": "process_name", "ph": "M", "pid": pid,
                        "args": {"name": f"shard {pid}"}})
        # Intra-tick ordering comes from a PER-TICK ordinal, clamped
        # inside the tick's slot — the global sequence `i` is unbounded
        # on long runs and would drift events into later ticks' slots.
        t = int(ev.get("t", 0))
        off = tick_idx.get(t, 0)
        tick_idx[t] = off + 1
        ts = t * CHROME_TICK_US + min(off * 1e-3,
                                      CHROME_TICK_US - 1.0)
        args = {k: v for k, v in ev.items() if k != WALL_KEY}
        wall = _wall_ms(ev)
        base = {"name": kind, "cat": kind.split(".")[0], "pid": pid,
                "tid": kind, "ts": round(ts, 3), "args": args}
        is_flow = kind.startswith("flow.")
        if wall > 0.0:
            out.append({**base, "ph": "X",
                        "dur": round(wall * 1e3, 3)})  # ms -> trace-µs
        elif is_flow:
            # Flow lifecycle events render as sub-µs duration slices,
            # not instants: the chrome trace format binds s/t/f flow
            # arrows to an ENCLOSING slice on the same pid/tid/ts — an
            # instant gives the importer nothing to attach to and the
            # arrows would be dropped.
            out.append({**base, "ph": "X", "dur": 0.5})
        else:
            out.append({**base, "ph": "i", "s": "t"})
        mark = flow_mark.get(idx)
        if mark is not None:
            ph, fid = mark
            arrow = {"name": "op-flow", "cat": "flow", "pid": pid,
                     "tid": kind, "ts": round(ts, 3), "ph": ph,
                     "id": fid}
            if ph == "f":
                arrow["bp"] = "e"  # bind to the enclosing slice's end
            out.append(arrow)
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"tick_pitch_us": CHROME_TICK_US,
                          "note": "time axis is the LOGICAL tick axis; "
                                  "dur spans are segregated wall fields"}}


# ------------------------------------------------------------------ CLI --


def _print_phases(d: dict) -> None:
    print(f"{d['ticks']} ticks, {d['events']} events, "
          f"{d['wall_ms_total']:.1f} ms measured wall")
    print(f"{'phase':<16} {'events':>7} {'wall ms':>10} {'share':>7}")
    for p, row in d["phases"].items():
        print(f"{p:<16} {row['events']:>7} {row['wall_ms']:>10.3f} "
              f"{row['share_pct']:>6.1f}%")
    print("slowest ticks:")
    for r in d["slowest_ticks"]:
        parts = " ".join(f"{p.split('.')[1]}={r[p]:.2f}" for p in PHASES)
        print(f"  tick {r['tick']:>4}: {r['total_ms']:.3f} ms ({parts})")


def _print_table(rows: List[dict], cols: List[str]) -> None:
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in cols} if rows else {c: len(c) for c in cols}
    print("  ".join(c.ljust(widths[c]) for c in cols))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in cols))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m text_crdt_rust_tpu.obs.analyze",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("phases", "hotdocs", "fuse", "recompiles", "overlap"):
        p = sub.add_parser(name)
        p.add_argument("trace", nargs="+",
                       help="trace JSONL segment(s) or bundle JSON")
        p.add_argument("--json", action="store_true")
        p.add_argument("--top", type=int, default=10)
        if name == "phases":
            p.add_argument("--stall-budget", action="store_true",
                           help="append the one-line stall budget: the "
                                "phase owning the most wall and its "
                                "share of the measured total")
    p = sub.add_parser("diff")
    p.add_argument("a")
    p.add_argument("b")
    p.add_argument("--json", action="store_true")
    p = sub.add_parser("chrome")
    p.add_argument("trace", nargs="+")
    p.add_argument("-o", "--out", default=None,
                   help="output path (default: stdout)")
    p = sub.add_parser("flow")
    p.add_argument("trace", nargs="+",
                   help="trace JSONL segment(s) or bundle JSON")
    p.add_argument("--json", action="store_true")
    p.add_argument("--audit", action="store_true",
                   help="conservation audit: exit 1 unless every "
                        "emitted span is terminally accounted "
                        "(applied once / rejected / named in-flight "
                        "location), naming the first finding")
    args = ap.parse_args(argv)

    if args.cmd == "diff":
        d = trace_diff(load_events([args.a]), load_events([args.b]))
        if args.json:
            print(json.dumps(d, indent=1, sort_keys=True))
        elif d is None:
            print("logical streams identical")
        else:
            print(f"first divergence at event {d['index']} "
                  f"(tick {d['tick']}): fields {d['fields']}")
            for side in ("a", "b"):
                if side in d:
                    print(f"  {side}: {json.dumps(d[side], sort_keys=True)}")
        return 0 if d is None else 1

    events = load_events(args.trace)
    if args.cmd == "chrome":
        doc = chrome_trace(events)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(doc, f)
            print(f"wrote {len(doc['traceEvents'])} trace events to "
                  f"{args.out}", file=sys.stderr)
        else:
            print(json.dumps(doc))
        return 0

    if args.cmd == "phases":
        d = phase_breakdown(events)
        if args.stall_budget:
            d = {**d, "stall_budget": stall_budget(d)}
        if args.json:
            print(json.dumps(d, indent=1, sort_keys=True))
        else:
            _print_phases(d)
            if args.stall_budget:
                b = d["stall_budget"]
                print(f"stall budget: {b['phase']} owns "
                      f"{b['wall_ms']:.3f} ms = {b['share_pct']:.1f}% "
                      f"of measured tick wall")
    elif args.cmd == "overlap":
        d = overlap_report(events, slowest=args.top)
        if args.json:
            print(json.dumps(d, indent=1, sort_keys=True))
        else:
            print(f"{d['ticks']} ticks: host {d['host_ms']:.1f} ms, "
                  f"dispatch {d['dispatch_ms']:.1f} ms, sync stall "
                  f"{d['stall_ms']:.1f} ms ({d['stall_share_pct']}% of "
                  f"busy wall), overlap window {d['win_ms']:.1f} ms")
            print(f"overlap_frac {d['overlap_frac']} (device-sync "
                  f"demand hidden under host work); idle gap per tick: "
                  f"p50 {d['idle_gap_ms']['p50']} p99 "
                  f"{d['idle_gap_ms']['p99']} max "
                  f"{d['idle_gap_ms']['max']} ms")
            _print_table(d["worst_ticks"],
                         ["tick", "host_ms", "dispatch_ms", "stall_ms",
                          "win_ms"])
    elif args.cmd == "hotdocs":
        d = hot_docs(events, top=args.top)
        if args.json:
            print(json.dumps(d, indent=1, sort_keys=True))
        else:
            print(f"{d['docs']} docs, {d['apply_events']} applies, "
                  f"{d['item_ops']} item-ops")
            _print_table(d["top"], ["doc", "events", "items"])
    elif args.cmd == "fuse":
        d = fusion_table(events, top=args.top)
        if args.json:
            print(json.dumps(d, indent=1, sort_keys=True))
        else:
            print(f"{d['fused_docs']} fused docs: {d['steps_in']} -> "
                  f"{d['steps_out']} steps ({d['rows_saved']} rows "
                  f"saved, {d['reduction_x']}x)")
            _print_table(d["top"], ["doc", "steps_in", "steps_out",
                                    "rows_saved", "fused_ticks"])
    elif args.cmd == "recompiles":
        d = recompile_timeline(events)
        if args.json:
            print(json.dumps(d, indent=1, sort_keys=True))
        else:
            print(f"{d['compiles']} compiles (last at tick "
                  f"{d['last_compile_tick']} of {d['run_last_tick']})")
            _print_table(d["timeline"], ["tick", "i", "shard", "bucket"])
    elif args.cmd == "flow":
        from .flow import flow_report

        d = flow_report(events, expect_terminal=args.audit)
        if args.json:
            print(json.dumps(d, indent=1, sort_keys=True))
        else:
            sp = d["spans"]
            print(f"{sp['emitted']} spans tracked ({d['flow_events']} "
                  f"flow events): {sp['applied']} applied "
                  f"({d['applies']['device']} device / "
                  f"{d['applies']['host']} host), {sp['rejected']} "
                  f"rejected, {sp['in_flight']} in flight")
            a = d["ages_ticks"]
            print(f"op age at apply (ticks): p50 {a['p50']} "
                  f"p99 {a['p99']} max {a['max']} (n={a['count']})")
            for group in ("by_band", "by_class"):
                rows = [{"bucket": k, **v} for k, v in d[group].items()
                        if v["count"]]
                if rows:
                    print(f"{group.replace('_', ' ')}:")
                    _print_table(rows, ["bucket", "count", "p50",
                                        "p99", "max"])
        if args.audit and not d["audit_ok"]:
            f = d["findings"][0]
            print(f"CONSERVATION AUDIT FAILED: {f['kind']} — "
                  f"{f['detail']}", file=sys.stderr)
            return 1
        if args.audit:
            print(f"conservation audit OK: {d['spans']['emitted']} "
                  f"spans terminally accounted", file=sys.stderr)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `analyze ... | head` is a normal usage
        sys.exit(0)

"""Logical-clock-first span/event tracer (ISSUE 8 tentpole, part 1).

The backbone is CAUSALITY, not wall time: every event is stamped with
the server's logical tick and a per-tracer monotonic sequence number,
and every wall-clock measurement lives in a segregated ``"w"`` sub-dict
— so the *logical* projection of a trace is a pure function of the
seeded workload.  Two same-seed loadgen runs emit byte-identical
logical JSONL streams (``tests/test_obs_trace.py`` pins this), which is
exactly the property the serve twin-check's cross-backend bit-identity
proof needs from its observability layer: the trace can be diffed
between a good and a bad run to find the first diverging *event*, not
just the diverged end state.

Like automerge's binary document format (PAPERS.md), the trace is a
versioned, schema-checked artifact: every stream opens with a header
event carrying ``TRACE_SCHEMA_VERSION``, every kind declares its
required logical fields in ``EVENT_SCHEMA``, and ``validate_event``
refuses unknown kinds or missing fields — ad-hoc dict drift (how the
PR-3..7 report dicts grew apart) cannot happen silently here.

Event kinds cover the serving loop end to end:

===================  =======================================================
kind                 emitted by / meaning
===================  =======================================================
``trace.header``     stream start: schema version
``tick.drain``       batcher, per shard: events drained + steps compiled
``tick.fuse``        batcher, per lane doc: pre/post-fusion step counts
``tick.capacity``    batcher, per shard: lane streams probed / degraded
``tick.device``      batcher, per shard: one [S,B] device pass (bucket,
                     lanes, steps; dispatch wall in ``w``)
``tick.barrier``     batcher, per shard: device sync (wall in ``w``)
``device.compile``   batcher: a step-bucket shape compiled for the first
                     time (steady state must stop emitting these)
``apply``            batcher, per applied event: doc, author agent, seq,
                     item count — the event-level audit log the
                     divergence post-mortem joins against
``residency.evict``  residency: doc checkpointed out (kind, bytes)
``residency.restore`` residency: doc restored from its checkpoint
``residency.degrade`` residency: lane-capacity overflow -> host-only
``admission.reject`` admission: typed refusal (reason)
``codec.reject``     router: a frame failed ``net/codec`` validation
``divergence``       router/verifier: equal watermarks, unequal digests
                     (or a twin/lane bit-identity mismatch)
``resync.round``     session/router: anti-entropy round (wants emitted)
``profile``          serve: jax.profiler capture started/stopped
``flow.*``           per-op provenance spans (obs/flow.py): emit /
                     frame / reject / buffer / ready / apply — one
                     ``(agent, seq)`` span's journey through the
                     serving loop, agent-sampled
===================  =======================================================
"""
from __future__ import annotations

import json
import time
from typing import Callable, Dict, List, Optional, Tuple

TRACE_SCHEMA_VERSION = 2  # v2 (ISSUE 16): journal.segment /
#                           journal.refuse / recovery.replay /
#                           chaos.crash event kinds

# kind -> required logical field names (beyond the envelope "i"/"t"/"k").
# Extra fields are allowed — the schema pins the floor, not the ceiling.
EVENT_SCHEMA: Dict[str, Tuple[str, ...]] = {
    "trace.header": ("schema",),
    "tick.drain": ("shard", "events", "steps"),
    "tick.fuse": ("doc", "steps_in", "steps_out"),
    "tick.capacity": ("shard", "probed", "degraded"),
    "tick.device": ("shard", "bucket", "lanes", "steps"),
    "tick.barrier": ("shard",),
    "device.compile": ("shard", "bucket"),
    "apply": ("doc", "ev", "agent", "seq", "n"),
    "residency.evict": ("doc", "ckpt", "bytes"),
    "residency.restore": ("doc",),
    "residency.degrade": ("doc", "reason"),
    "admission.reject": ("reason",),
    "codec.reject": ("err",),
    "divergence": ("doc",),
    "resync.round": ("wants",),
    "profile": ("action",),
    # Per-op provenance spans (ISSUE 11, obs/flow.py): one op's journey
    # on the logical tick axis.  Remote spans carry (agent, seq, n);
    # local edits carry a per-doc ordinal ``lk`` until the oracle
    # realizes their seq at apply.  The floor requires doc+agent — seq
    # vs lk is the span-identity split the flow module owns.
    # Durability + recovery (ISSUE 16, serve/journal.py + chaos.py).
    "journal.segment": ("shard", "seg"),
    "journal.refuse": ("segment", "offset", "reason"),
    # Reopen-time repair: a refused suffix truncated/quarantined to a
    # ``.refused`` sidecar so post-recovery segments survive the next
    # scan (same fields as the refusal it repairs).
    "journal.repair": ("segment", "offset", "reason"),
    "recovery.replay": ("records", "ops", "ticks"),
    "chaos.crash": ("phase",),
    "flow.emit": ("doc", "agent", "n"),
    "flow.frame": ("doc", "agent", "seq", "n", "frame"),
    "flow.reject": ("doc", "agent", "reason"),
    "flow.buffer": ("doc", "agent", "seq", "n", "state"),
    "flow.ready": ("doc", "agent", "seq", "n"),
    "flow.apply": ("doc", "agent", "seq", "n", "mode"),
}

# The one reserved envelope key wall-clock data lives under; stripping
# it is the whole logical projection.
WALL_KEY = "w"
_ENVELOPE = ("i", "t", "k")


def validate_event(ev: dict) -> None:
    """Raise ``ValueError`` unless ``ev`` is a schema-valid trace event:
    known kind, full envelope, every required logical field present, and
    wall-clock data only under the reserved ``"w"`` key."""
    for key in _ENVELOPE:
        if key not in ev:
            raise ValueError(f"trace event missing envelope field {key!r}")
    kind = ev["k"]
    req = EVENT_SCHEMA.get(kind)
    if req is None:
        raise ValueError(f"unknown trace event kind {kind!r}")
    missing = [f for f in req if f not in ev]
    if missing:
        raise ValueError(f"trace event {kind!r} missing fields {missing}")
    wall = ev.get(WALL_KEY)
    if wall is not None and not isinstance(wall, dict):
        raise ValueError(f"wall field {WALL_KEY!r} must be a dict")


def event_line(ev: dict, logical_only: bool = False) -> str:
    """One JSONL line for an event — sorted keys and fixed separators so
    equal logical content is equal bytes."""
    if logical_only and WALL_KEY in ev:
        ev = {k: v for k, v in ev.items() if k != WALL_KEY}
    return json.dumps(ev, sort_keys=True, separators=(",", ":"))


class _Span:
    """Context manager emitting ONE event at exit, with the measured
    wall duration segregated under the ``"w"`` key."""

    __slots__ = ("tracer", "kind", "fields", "t0")

    def __init__(self, tracer: "Tracer", kind: str, fields: dict):
        self.tracer = tracer
        self.kind = kind
        self.fields = fields

    def __enter__(self) -> "_Span":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        ms = (time.perf_counter() - self.t0) * 1e3
        self.tracer.event(self.kind, wall={"ms": round(ms, 3)},
                          **self.fields)
        return False


class Tracer:
    """Bounded event tracer for one server (or one test harness).

    - ``ring`` holds the last N events for the flight recorder;
    - ``keep_all=True`` additionally retains the full stream in memory
      (the determinism tests read it back via ``logical_bytes``);
    - ``path`` streams every event to a JSONL file as it happens;
    - ``rotate_bytes`` caps each stream segment: when the active file
      passes the cap it is closed and the stream continues in
      ``<path>.1``, ``<path>.2``, ... (``segment_paths`` lists them in
      order) — a long loadgen/cluster run no longer grows ONE unbounded
      JSONL, and segments concatenate back to the identical stream
      (``obs.analyze.load_events`` accepts the segment list);
    - ``enabled=False`` turns every entry point into a cheap no-op
      (the overhead-probe baseline arm).

    Events are dicts with a three-field envelope — ``i`` (monotonic
    sequence), ``t`` (logical tick, set via ``set_tick``), ``k`` (kind)
    — plus the kind's logical fields and an optional ``"w"`` wall dict.
    """

    def __init__(self, *, enabled: bool = True, ring: int = 512,
                 keep_all: bool = False, path: Optional[str] = None,
                 rotate_bytes: Optional[int] = None,
                 validate: bool = True):
        from collections import deque

        self.enabled = enabled
        self.ring = deque(maxlen=max(1, ring))
        self.keep_all = keep_all
        self.events: List[dict] = []
        self.validate = validate
        self.seq = 0
        self.tick = 0
        # Line-buffered: the events adjacent to a crash are exactly the
        # ones a flight recorder exists to preserve — they must be on
        # disk, not in a stdio buffer, when the process dies.
        self._path = path
        self.rotate_bytes = rotate_bytes
        self.segment_paths: List[str] = []
        self._segment_bytes = 0
        self._file = None
        if enabled and path:
            self._file = open(path, "w", buffering=1)
            self.segment_paths.append(path)
        self._subscribers: List[Callable[[dict], None]] = []
        if enabled:
            self.event("trace.header", schema=TRACE_SCHEMA_VERSION)

    # -- emit ----------------------------------------------------------------

    def set_tick(self, tick: int) -> None:
        self.tick = tick

    def subscribe(self, fn: Callable[[dict], None]) -> None:
        """Register a callback invoked on every event (the flight
        recorder taps ``apply`` events through this)."""
        self._subscribers.append(fn)

    def event(self, kind: str, wall: Optional[dict] = None,
              **fields) -> Optional[dict]:
        if not self.enabled:
            return None
        ev = {"i": self.seq, "t": self.tick, "k": kind}
        ev.update(fields)
        if wall:
            ev[WALL_KEY] = wall
        if self.validate:
            validate_event(ev)
        self.seq += 1
        self.ring.append(ev)
        if self.keep_all:
            self.events.append(ev)
        if self._file is not None:
            line = event_line(ev) + "\n"
            self._file.write(line)
            self._segment_bytes += len(line)
            # Size-capped segment rollover: rotation happens BETWEEN
            # events (a line is never split), so the concatenated
            # segments are byte-identical to an unrotated stream.
            if (self.rotate_bytes
                    and self._segment_bytes >= self.rotate_bytes):
                self._file.close()
                seg = f"{self._path}.{len(self.segment_paths)}"
                self._file = open(seg, "w", buffering=1)
                self.segment_paths.append(seg)
                self._segment_bytes = 0
        for fn in self._subscribers:
            fn(ev)
        return ev

    def span(self, kind: str, **fields) -> _Span:
        """``with tracer.span("tick.barrier", shard=0): ...`` — one
        event at exit, wall duration under ``"w"``."""
        return _Span(self, kind, fields)

    # -- read back -----------------------------------------------------------

    def last(self, n: int, doc: Optional[str] = None,
             shard: Optional[int] = None) -> List[dict]:
        """Last ``n`` ring events, newest last; ``doc``/``shard`` filter
        to events touching that doc or shard (envelope-level events with
        neither field always pass — they are context)."""
        out = []
        for ev in reversed(self.ring):
            if doc is not None and "doc" in ev and ev["doc"] != doc:
                continue
            if shard is not None and "shard" in ev and ev["shard"] != shard:
                continue
            out.append(ev)
            if len(out) >= n:
                break
        out.reverse()
        return out

    def logical_bytes(self) -> bytes:
        """The retained stream's logical projection as JSONL bytes —
        requires ``keep_all=True``; this is what two same-seed runs must
        agree on byte for byte."""
        assert self.keep_all, "logical_bytes needs Tracer(keep_all=True)"
        return ("\n".join(event_line(ev, logical_only=True)
                          for ev in self.events) + "\n").encode()

    def flush(self) -> None:
        if self._file is not None:
            self._file.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

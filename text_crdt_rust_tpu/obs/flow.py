"""Per-op provenance spans + the conservation audit (ISSUE 11 tentpole).

The paper's whole job is tracking one character's identity
``(agent, seq)`` through every conversion, and YATA's convergence
contract (PAPERS.md) is a *per-op* claim — yet PR 8/9 observability
stops at tick granularity.  This module follows one op's journey end to
end on the logical tick axis:

    emitted -> framed -> admitted/rejected -> buffered/ready ->
    drained+fused -> applied (device or host) [-> survives evictions]

Every lifecycle event is a normal ``obs/trace`` event (``flow.*``
kinds), so flow streams inherit the whole PR-8 discipline for free:
wall-clock segregation, same-seed byte-identity, segment rotation, the
analyze CLI.  Two properties then stop being debugging folklore and
become *gated invariants*:

- **conservation** — at end of run every emitted op span is in exactly
  one terminal state: applied (device or host), rejected with a typed
  reason, or in-flight at shutdown with a NAMED location (network /
  admission / causal-buffer / event-queue).  ``audit_spans`` returns
  findings for anything else: a leaked span, a double-applied span, a
  phantom apply (applied but never emitted), or an evict->restore
  conservation mismatch (a restore replay that re-applied history would
  inflate the doc's item/order counts — the checkpoint chain replay
  must be invisible to the per-op ledger);
- **op age at apply** — ticks from emission to apply, per doc-popularity
  band and per fault class (local / clean / gap-stalled / redelivered),
  all exact logical-tick numbers a cost-ledger cell can pin — the
  before/after latency contract the ROADMAP-7 pipelined tick needs, no
  wall clock involved.

Sampling (``ServeConfig.flow_sample_mod``) is **per agent name**
(``crc32(agent) % mod == 0``), not per event: a sampled agent's spans
are tracked *end to end*, so the audit is valid on the sampled subset
at any mod — trims, merged re-exports and re-deliveries all land on the
same side of the sampling line.  ``mod=1`` tracks everything (the audit
and ledger runs); the serve default keeps the PR-8 "<5% overhead" bar.

Span identity: remote spans are the txn id ``(agent, seq)`` plus item
count (covering seqs ``[seq, seq+n)``); interval arithmetic — not exact
id matching — absorbs the causal buffer's prefix trims and the loadgen's
RLE-merged re-exports (two emits may overlap; the audit unions them).
Local edits have no seq until the oracle applies them, so their
emission is keyed by a per-doc ordinal ``lk`` which the eventual
``flow.apply``/``flow.reject`` closes, realizing the ``(agent, seq)``
span.
"""
from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Tuple

from ..common import RemoteTxn, txn_len
from ..utils.metrics import percentiles

#: The flow lifecycle stages, in journey order (location naming).
FLOW_STAGES = ("emit", "frame", "reject", "buffer", "ready", "apply")

#: Last-stage -> human location for in-flight spans.
STAGE_LOCATION = {
    "emit": "network (emitted, never framed)",
    "frame": "admission (framed, never released)",
    "buffer": "causal-buffer",
    "ready": "event-queue",
    "reject": "rejected (awaiting redelivery)",
}

#: Doc-popularity bands over emitted volume: hottest 10% / next 30% /
#: the Zipf tail.  Computed from the trace itself so the analyzer needs
#: no loadgen-side popularity table.
BANDS = (("hot", 0.10), ("warm", 0.30), ("cold", 1.0))

#: Fault classes an applied span can have experienced, judged purely
#: from its flow shape: ``local`` (no wire), ``clean`` (framed once,
#: never buffered), ``gap-stalled`` (held in the causal buffer),
#: ``redelivered`` (framed more than once — dup fault or pull refetch).
FAULT_CLASSES = ("local", "clean", "gap-stalled", "redelivered")


def agent_sampled(agent: str, mod: int) -> bool:
    """The ONE sampling predicate every emission point shares: stable
    across runs and platforms (crc32 of the utf-8 name), and per-agent
    so a sampled span is complete end to end."""
    if mod <= 0:
        return False
    if mod == 1:
        return True
    return zlib.crc32(agent.encode("utf-8")) % mod == 0


class FlowTracker:
    """Emission helper owned by one ``DocServer``: stamps ``flow.*``
    events through the server's tracer and retains their logical dicts
    (``records``) so the in-process audit/report needs no trace file.
    Every entry point is a cheap no-op when disabled (``mod=0`` or
    tracer off); sampling decisions are cached per agent name.

    Retention is BOUNDED (``max_records``, the PR-8 ring discipline —
    the tracer keeps 512 events, not the run): past the cap the tracker
    keeps *emitting* trace events but stops retaining them, and
    ``report()`` refuses to claim a clean audit over a truncated
    ledger (a named ``records-truncated`` finding).  For runs that
    outgrow the cap, stream the trace to disk and audit offline via
    ``analyze.py flow`` — the archival path."""

    def __init__(self, tracer, sample_mod: int = 1,
                 max_records: int = 1_000_000):
        self.tracer = tracer
        self.sample_mod = max(0, int(sample_mod))
        self.enabled = bool(tracer is not None and tracer.enabled
                            and self.sample_mod > 0)
        self.records: List[dict] = []
        self.max_records = max_records
        self.truncated = False
        self._sample_cache: Dict[str, bool] = {}
        self._local_no: Dict[str, int] = {}
        if self.enabled:
            # Tap the residency conservation checkpoints (evict/restore
            # item+order counts) off the tracer stream so the
            # in-process audit can pair them — the offline path reads
            # the same events from the trace file.
            tracer.subscribe(self._tap)

    def _tap(self, ev: dict) -> None:
        if ev.get("k") in ("residency.evict", "residency.restore") \
                and "n" in ev:
            self._retain(ev)

    def _retain(self, ev: dict) -> None:
        if len(self.records) < self.max_records:
            self.records.append(ev)
        else:
            self.truncated = True

    # -- sampling ------------------------------------------------------------

    def sampled(self, agent: str) -> bool:
        if not self.enabled:
            return False
        hit = self._sample_cache.get(agent)
        if hit is None:
            hit = self._sample_cache[agent] = agent_sampled(
                agent, self.sample_mod)
        return hit

    def _ev(self, kind: str, **fields) -> None:
        ev = self.tracer.event(kind, **fields)
        if ev is not None:
            self._retain(ev)

    # -- lifecycle emission points ------------------------------------------

    def emit_txns(self, doc_id: str, txns: List[RemoteTxn]) -> None:
        """Remote-span emission: the loadgen (or any upstream peer
        harness) records freshly generated txns the moment they exist —
        before the fault channel gets a chance to eat them."""
        if not self.enabled:
            return
        for t in txns:
            if self.sampled(t.id.agent):
                self._ev("flow.emit", doc=doc_id, agent=t.id.agent,
                         seq=t.id.seq, n=txn_len(t))

    def emit_local(self, doc_id: str, agent: str, n: int) -> Optional[int]:
        """Local-edit emission at submit time; returns the per-doc
        ordinal ``lk`` that keys the span until the oracle realizes its
        ``(agent, seq)`` at apply (or ``None`` when unsampled)."""
        if not self.sampled(agent):
            return None
        lk = self._local_no.get(doc_id, 0)
        self._local_no[doc_id] = lk + 1
        self._ev("flow.emit", doc=doc_id, agent=agent, n=n, lk=lk)
        return lk

    def framed(self, doc_id: str, txns: List[RemoteTxn],
               frame: int) -> None:
        """Decoded off the wire inside frame ``frame`` (the frame's
        stored CRC32C — content-derived, so same-seed runs agree)."""
        if not self.enabled:
            return
        for t in txns:
            if self.sampled(t.id.agent):
                self._ev("flow.frame", doc=doc_id, agent=t.id.agent,
                         seq=t.id.seq, n=txn_len(t), frame=frame)

    def rejected(self, doc_id: str, agent: str, reason: str,
                 seq: Optional[int] = None, n: Optional[int] = None,
                 lk: Optional[int] = None) -> None:
        if not self.sampled(agent):
            return
        fields = {"doc": doc_id, "agent": agent, "reason": reason}
        if lk is not None:
            fields["lk"] = lk
        if seq is not None:
            fields["seq"] = seq
            fields["n"] = n if n is not None else 1
        self._ev("flow.reject", **fields)

    def buffered(self, doc_id: str, txn: RemoteTxn,
                 state: str = "held") -> None:
        """Held in the causal buffer (``held``) or pressure-evicted from
        it (``drop`` — the gap stays visible to ``missing()``; the span
        comes back via re-request)."""
        if self.sampled(txn.id.agent):
            self._ev("flow.buffer", doc=doc_id, agent=txn.id.agent,
                     seq=txn.id.seq, n=txn_len(txn), state=state)

    def ready(self, doc_id: str, txn: RemoteTxn) -> None:
        """Causally released into the doc's FIFO event queue."""
        if self.sampled(txn.id.agent):
            self._ev("flow.ready", doc=doc_id, agent=txn.id.agent,
                     seq=txn.id.seq, n=txn_len(txn))

    def applied(self, doc_id: str, agent: str, seq: int, n: int,
                mode: str, lk: Optional[int] = None,
                fstep: Optional[int] = None,
                fn_steps: Optional[int] = None) -> None:
        """Terminal apply: ``mode`` is ``device`` (the span rode a lane
        batch this tick) or ``host`` (host-only / degraded oracle
        apply).  ``fstep`` names the fused super-step that absorbed the
        span's first compiled row, ``fn_steps`` how many fused output
        steps its rows span."""
        if not self.sampled(agent):
            return
        fields = {"doc": doc_id, "agent": agent, "seq": seq, "n": n,
                  "mode": mode}
        if lk is not None:
            fields["lk"] = lk
        if fstep is not None:
            fields["fstep"] = fstep
            fields["fn"] = fn_steps if fn_steps is not None else 1
        self._ev("flow.apply", **fields)

    # -- in-process report ---------------------------------------------------

    def report(self, expect_terminal: bool = False) -> dict:
        out = flow_report(self.records, expect_terminal=expect_terminal)
        out["sample_mod"] = self.sample_mod
        if self.truncated:
            # A truncated ledger cannot certify conservation — refuse
            # the claim and point at the offline (trace-file) path.
            out["audit_ok"] = False
            out["findings"] = [{
                "kind": "records-truncated", "doc": None,
                "detail": f"in-process flow retention hit max_records="
                          f"{self.max_records}; audit the streamed "
                          f"trace with analyze.py flow --audit instead",
            }] + out["findings"][:7]
        return out


# -- interval arithmetic ------------------------------------------------------


def _merge(intervals: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """Union of half-open [start, end) intervals, sorted."""
    out: List[Tuple[int, int]] = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _subtract(a: List[Tuple[int, int]],
              b: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """``a`` minus ``b``; both merged-sorted."""
    out: List[Tuple[int, int]] = []
    bi = 0
    for s, e in a:
        cur = s
        while bi < len(b) and b[bi][1] <= cur:
            bi += 1
        j = bi
        while cur < e:
            if j >= len(b) or b[j][0] >= e:
                out.append((cur, e))
                break
            bs, be = b[j]
            if bs > cur:
                out.append((cur, bs))
            cur = max(cur, be)
            j += 1
    return out


def _covered(intervals: List[Tuple[int, int]], s: int, e: int) -> bool:
    return not _subtract([(s, e)], intervals)


def _overlap_pairs(spans: List[Tuple[int, int, int]]
                   ) -> List[Tuple[int, int]]:
    """Overlapping [start, end) ranges among a MULTISET of spans
    (tagged with their record index) — the double-apply detector.
    Returns (record_index_a, record_index_b) pairs."""
    out = []
    ordered = sorted(spans)
    if not ordered:
        return out
    run_end, run_idx = ordered[0][1], ordered[0][2]
    for cs, ce, cidx in ordered[1:]:
        if cs < run_end:
            out.append((run_idx, cidx))
        if ce > run_end:
            run_end, run_idx = ce, cidx
    return out


# -- span table ---------------------------------------------------------------


class _AgentFlow:
    """All flow records for one (doc, agent) pair."""

    __slots__ = ("emits", "frames", "buffers", "readys", "rejects",
                 "applies")

    def __init__(self):
        self.emits: List[dict] = []
        self.frames: List[dict] = []
        self.buffers: List[dict] = []
        self.readys: List[dict] = []
        self.rejects: List[dict] = []
        self.applies: List[dict] = []


class FlowTable:
    """Flow events regrouped per (doc, agent) + per local ordinal +
    per-doc residency conservation checkpoints."""

    def __init__(self):
        self.agents: Dict[Tuple[str, str], _AgentFlow] = {}
        # (doc, lk) -> {"emit": ev, "reject": ev|None, "applies": [ev]}
        self.locals: Dict[Tuple[str, int], dict] = {}
        # doc -> ordered [(kind, n, orders)] residency checkpoints
        self.residency: Dict[str, List[Tuple[str, int, int]]] = {}

    def agent(self, doc: str, agent: str) -> _AgentFlow:
        key = (doc, agent)
        af = self.agents.get(key)
        if af is None:
            af = self.agents[key] = _AgentFlow()
        return af


def spans_from_events(events) -> FlowTable:
    """Build the span table from any event iterable — the tracker's
    retained records, a loaded JSONL stream, or rotated segments
    concatenated by ``analyze.load_events`` (a span whose lifecycle
    straddles a rotation boundary reassembles here)."""
    table = FlowTable()
    for ev in events:
        k = ev.get("k", "")
        if k.startswith("flow."):
            stage = k[len("flow."):]
            doc = ev["doc"]
            lk = ev.get("lk")
            if lk is not None:
                slot = table.locals.setdefault((doc, lk), {
                    "emit": None, "reject": None, "applies": []})
                if stage == "emit":
                    slot["emit"] = ev
                elif stage == "reject":
                    slot["reject"] = ev
                elif stage == "apply":
                    slot["applies"].append(ev)
                    table.agent(doc, ev["agent"]).applies.append(ev)
                continue
            af = table.agent(doc, ev["agent"])
            if stage == "emit":
                af.emits.append(ev)
            elif stage == "frame":
                af.frames.append(ev)
            elif stage == "buffer":
                af.buffers.append(ev)
            elif stage == "ready":
                af.readys.append(ev)
            elif stage == "reject":
                af.rejects.append(ev)
            elif stage == "apply":
                af.applies.append(ev)
        elif k in ("residency.evict", "residency.restore") \
                and "n" in ev and "error" not in ev:
            table.residency.setdefault(ev["doc"], []).append(
                (k.split(".")[1], int(ev["n"]), int(ev["orders"])))
    return table


def _span(ev: dict) -> Tuple[int, int]:
    return int(ev["seq"]), int(ev["seq"]) + max(int(ev.get("n", 1)), 1)


def _last_stage(af: _AgentFlow, s: int, e: int) -> str:
    """The journey-latest stage any record overlapping [s, e) reached —
    the named location of an in-flight span."""
    best = "emit"
    order = {st: i for i, st in enumerate(FLOW_STAGES)}
    for stage, recs in (("frame", af.frames), ("reject", af.rejects),
                        ("buffer", af.buffers), ("ready", af.readys)):
        for ev in recs:
            rs, re_ = _span(ev)
            if rs < e and re_ > s and order[stage] > order[best]:
                best = stage
    return best


# -- the audit ----------------------------------------------------------------


def audit_spans(table: FlowTable,
                expect_terminal: bool = True) -> List[dict]:
    """Conservation findings, worst first.  Empty list = every tracked
    span is terminally accounted: applied exactly once (interval-wise),
    rejected with a reason, or — when ``expect_terminal`` is False —
    in-flight at a named location.  Finding kinds:

    - ``duplicate-apply``: two apply records overlap in seq space for
      one (doc, agent) — the must-never-happen YATA violation;
    - ``phantom-apply``: applied seqs nothing ever emitted;
    - ``leak``: an emitted range with no terminal disposition (named
      last-known location; only with ``expect_terminal``);
    - ``local-leak`` / ``local-duplicate``: the lk-keyed local analogs;
    - ``evict-restore-mismatch``: a restore whose (items, orders) do
      not equal the preceding evict's — replay re-application or state
      loss across the checkpoint boundary.
    """
    findings: List[dict] = []

    def finding(kind: str, doc: str, agent: Optional[str], detail: str,
                seq: Optional[int] = None, end: Optional[int] = None):
        f = {"kind": kind, "doc": doc, "detail": detail}
        if agent is not None:
            f["agent"] = agent
        if seq is not None:
            f["seq"] = seq
            f["end"] = end
        findings.append(f)

    dups: List[dict] = []
    phantoms: List[dict] = []
    leaks: List[dict] = []
    for (doc, agent), af in sorted(table.agents.items()):
        applies = [(*_span(ev), i) for i, ev in enumerate(af.applies)]
        for ia, ib in _overlap_pairs(applies):
            ea, eb = af.applies[ia], af.applies[ib]
            s = max(_span(ea)[0], _span(eb)[0])
            e = min(_span(ea)[1], _span(eb)[1])
            dups.append({
                "kind": "duplicate-apply", "doc": doc, "agent": agent,
                "seq": s, "end": e,
                "detail": f"span ({agent!r}, {s}..{e}) applied twice: "
                          f"tick {ea['t']} ({ea['mode']}) and tick "
                          f"{eb['t']} ({eb['mode']})"})
        emitted = _merge([_span(ev) for ev in af.emits]
                         + [_span(ev) for ev in af.applies
                            if ev.get("lk") is not None])
        applied = _merge([_span(ev) for ev in af.applies])
        for s, e in _subtract(applied, emitted):
            phantoms.append({
                "kind": "phantom-apply", "doc": doc, "agent": agent,
                "seq": s, "end": e,
                "detail": f"span ({agent!r}, {s}..{e}) applied but "
                          f"never emitted"})
        rejected = _merge([_span(ev) for ev in af.rejects
                           if "seq" in ev])
        open_ranges = _subtract(_subtract(emitted, applied), rejected)
        if expect_terminal:
            for s, e in open_ranges:
                loc = STAGE_LOCATION[_last_stage(af, s, e)]
                leaks.append({
                    "kind": "leak", "doc": doc, "agent": agent,
                    "seq": s, "end": e,
                    "detail": f"span ({agent!r}, {s}..{e}) leaked: "
                              f"last seen at {loc}"})

    for (doc, lk), slot in sorted(table.locals.items()):
        if len(slot["applies"]) > 1:
            ev = slot["applies"][1]
            finding("local-duplicate", doc, ev.get("agent"),
                    f"local edit lk={lk} applied "
                    f"{len(slot['applies'])} times")
        elif not slot["applies"] and slot["reject"] is None \
                and expect_terminal:
            em = slot["emit"] or {}
            finding("local-leak", doc, em.get("agent"),
                    f"local edit lk={lk} (agent {em.get('agent')!r}, "
                    f"{em.get('n')} items) leaked: submitted at tick "
                    f"{em.get('t')}, never applied or rejected")

    for doc, steps in sorted(table.residency.items()):
        last_evict: Optional[Tuple[int, int]] = None
        for kind, n, orders in steps:
            if kind == "evict":
                last_evict = (n, orders)
            elif kind == "restore" and last_evict is not None:
                if (n, orders) != last_evict:
                    finding("evict-restore-mismatch", doc, None,
                            f"doc {doc!r} restored with {n} items / "
                            f"{orders} orders but was evicted with "
                            f"{last_evict[0]} items / {last_evict[1]} "
                            f"orders — the checkpoint replay must "
                            f"re-create state, never re-apply it")
                last_evict = None
    return dups + phantoms + leaks + findings


# -- the crash-boundary audit -------------------------------------------------


def audit_crash_spans(pre_events, post_events,
                      expect_terminal: bool = False) -> dict:
    """Conservation audit ACROSS a crash/recovery boundary (ISSUE 16).

    ``pre_events`` is the crashed process's flow ledger, ``post_events``
    the recovered one's.  Recovery re-executes the journal, so a span
    applied before the crash applies again in the new process — that is
    *replayed*, not a duplicate; the plain ``audit_spans`` semantics
    would misread the join.  The crash-aware invariants:

    - ``crash-leak``: a span applied before the crash with no covering
      apply after recovery — the journal lost it (this is the finding
      the journal-record-drop injection proves loud, BEFORE resumed
      anti-entropy can quietly heal the hole);
    - ``duplicate-apply`` / ``local-duplicate``: within the recovered
      process only (replay must re-apply exactly once);
    - ``phantom-apply``: applied after recovery yet emitted in neither
      epoch;
    - ``leak`` (only with ``expect_terminal``, i.e. after the resumed
      run fully drains): an emitted span with no terminal disposition
      in the joined ledger;
    - ``crash-local-leak``: a pre-crash local edit whose ordinal the
      replay never re-submitted (the deterministic re-execution assigns
      the same per-doc ``lk`` order, so the keys join exactly).

    Returns ``{"audit_ok", "findings", "replayed_spans",
    "replayed_locals"}``."""
    pre = spans_from_events(pre_events)
    post = spans_from_events(post_events)
    findings: List[dict] = []
    replayed = 0
    empty = _AgentFlow()
    for key in sorted(set(pre.agents) | set(post.agents)):
        doc, agent = key
        af_pre = pre.agents.get(key, empty)
        af_post = post.agents.get(key, empty)
        applied_pre = _merge([_span(ev) for ev in af_pre.applies])
        applied_post = _merge([_span(ev) for ev in af_post.applies])
        for s, e in applied_pre:
            if _covered(applied_post, s, e):
                replayed += 1
        for s, e in _subtract(applied_pre, applied_post):
            findings.append({
                "kind": "crash-leak", "doc": doc, "agent": agent,
                "seq": s, "end": e,
                "detail": f"span ({agent!r}, {s}..{e}) was applied "
                          f"before the crash but has no covering apply "
                          f"after recovery — journal replay lost it"})
        applies_post = [(*_span(ev), i)
                        for i, ev in enumerate(af_post.applies)]
        for ia, ib in _overlap_pairs(applies_post):
            ea, eb = af_post.applies[ia], af_post.applies[ib]
            s = max(_span(ea)[0], _span(eb)[0])
            e = min(_span(ea)[1], _span(eb)[1])
            findings.append({
                "kind": "duplicate-apply", "doc": doc, "agent": agent,
                "seq": s, "end": e,
                "detail": f"span ({agent!r}, {s}..{e}) applied twice "
                          f"inside the recovered process: tick "
                          f"{ea['t']} and tick {eb['t']}"})
        emitted = _merge(
            [_span(ev) for ev in af_pre.emits]
            + [_span(ev) for ev in af_post.emits]
            + [_span(ev) for ev in af_pre.applies
               if ev.get("lk") is not None]
            + [_span(ev) for ev in af_post.applies
               if ev.get("lk") is not None])
        for s, e in _subtract(applied_post, emitted):
            findings.append({
                "kind": "phantom-apply", "doc": doc, "agent": agent,
                "seq": s, "end": e,
                "detail": f"span ({agent!r}, {s}..{e}) applied after "
                          f"recovery but emitted in neither epoch"})
        if expect_terminal:
            rejected = _merge(
                [_span(ev) for ev in af_pre.rejects if "seq" in ev]
                + [_span(ev) for ev in af_post.rejects if "seq" in ev])
            open_ranges = _subtract(_subtract(emitted, applied_post),
                                    rejected)
            for s, e in open_ranges:
                loc = STAGE_LOCATION[_last_stage(af_post, s, e)]
                findings.append({
                    "kind": "leak", "doc": doc, "agent": agent,
                    "seq": s, "end": e,
                    "detail": f"span ({agent!r}, {s}..{e}) leaked "
                              f"across the crash boundary: last seen "
                              f"at {loc}"})

    replayed_locals = 0
    for (doc, lk), slot_pre in sorted(pre.locals.items()):
        slot_post = post.locals.get((doc, lk))
        pre_applied = bool(slot_pre["applies"])
        if slot_post is None:
            if pre_applied or expect_terminal:
                findings.append({
                    "kind": "crash-local-leak", "doc": doc,
                    "detail": f"local edit lk={lk} existed before the "
                              f"crash but replay never re-submitted "
                              f"it"})
            continue
        if pre_applied and slot_post["applies"]:
            replayed_locals += 1
        if pre_applied and not slot_post["applies"] \
                and slot_post["reject"] is None:
            findings.append({
                "kind": "crash-local-leak", "doc": doc,
                "detail": f"local edit lk={lk} was applied before the "
                          f"crash but is neither applied nor rejected "
                          f"after recovery"})
    for (doc, lk), slot_post in sorted(post.locals.items()):
        if len(slot_post["applies"]) > 1:
            findings.append({
                "kind": "local-duplicate", "doc": doc,
                "detail": f"local edit lk={lk} applied "
                          f"{len(slot_post['applies'])} times inside "
                          f"the recovered process"})
        elif (doc, lk) not in pre.locals and expect_terminal \
                and not slot_post["applies"] \
                and slot_post["reject"] is None:
            findings.append({
                "kind": "local-leak", "doc": doc,
                "detail": f"local edit lk={lk} submitted after "
                          f"recovery, never applied or rejected"})
    return {
        "audit_ok": not findings,
        "findings": findings[:16],
        "total_findings": len(findings),
        "replayed_spans": replayed,
        "replayed_locals": replayed_locals,
    }


# -- ages ---------------------------------------------------------------------


def _tick_stats(ages: List[int]) -> dict:
    """Exact logical-tick distribution stats — the repo's ONE
    nearest-rank percentile definition (``utils.metrics.percentiles``)
    cast back to the integers ticks are, so flow-age p99 can never
    silently mean something different from latency p99."""
    if not ages:
        return {"count": 0, "p50": 0, "p99": 0, "max": 0}
    pct = percentiles(ages, (50, 99))
    return {"count": len(ages), "p50": int(pct["p50"]),
            "p99": int(pct["p99"]), "max": max(ages)}


def _fault_class(af: _AgentFlow, ev: dict) -> str:
    if ev.get("lk") is not None:
        return "local"
    s, e = _span(ev)
    frames = sum(1 for f in af.frames
                 if _span(f)[0] < e and _span(f)[1] > s)
    if frames > 1:
        return "redelivered"
    held = any(_span(b)[0] < e and _span(b)[1] > s
               for b in af.buffers)
    return "gap-stalled" if held else "clean"


def age_stats(table: FlowTable) -> dict:
    """Op-age-at-apply (ticks from emission to apply) distributions:
    overall, per apply mode, per doc-popularity band (emitted-volume
    deciles computed from the trace itself), per fault class."""
    # Emission tick per (doc, agent, seq): earliest emit covering it.
    doc_volume: Dict[str, int] = {}
    ages: List[int] = []
    by_mode: Dict[str, List[int]] = {"device": [], "host": []}
    by_class: Dict[str, List[int]] = {c: [] for c in FAULT_CLASSES}
    per_doc_ages: Dict[str, List[int]] = {}

    for (doc, agent), af in table.agents.items():
        vol = sum(max(int(ev.get("n", 1)), 1) for ev in af.emits)
        doc_volume[doc] = doc_volume.get(doc, 0) + vol
        emits = sorted((_span(ev)[0], _span(ev)[1], int(ev["t"]))
                       for ev in af.emits)
        for ev in af.applies:
            lk = ev.get("lk")
            s, _e = _span(ev)
            if lk is not None:
                slot = table.locals.get((doc, lk))
                emit_tick = (int(slot["emit"]["t"])
                             if slot and slot["emit"] else int(ev["t"]))
            else:
                emit_tick = None
                for es, ee, et in emits:
                    if es <= s < ee:
                        emit_tick = et
                        break
                if emit_tick is None:
                    continue  # phantom — the audit names it
            age = max(0, int(ev["t"]) - emit_tick)
            ages.append(age)
            by_mode.setdefault(ev.get("mode", "host"), []).append(age)
            by_class[_fault_class(af, ev)].append(age)
            per_doc_ages.setdefault(doc, []).append(age)
    for (doc, lk), slot in table.locals.items():
        em = slot["emit"]
        if em is not None:
            doc_volume[doc] = (doc_volume.get(doc, 0)
                               + max(int(em.get("n", 1)), 1))

    # Popularity bands from emitted volume (ties broken by doc id so
    # the banding is deterministic).
    ranked = sorted(doc_volume, key=lambda d: (-doc_volume[d], d))
    by_band: Dict[str, List[int]] = {name: [] for name, _ in BANDS}
    n_docs = len(ranked)
    for i, doc in enumerate(ranked):
        frac = (i + 1) / n_docs if n_docs else 1.0
        for name, ceil_frac in BANDS:
            if frac <= ceil_frac or name == BANDS[-1][0]:
                by_band[name].extend(per_doc_ages.get(doc, []))
                break
    return {
        "ages_ticks": _tick_stats(ages),
        "by_mode": {m: _tick_stats(v) for m, v in sorted(by_mode.items())},
        "by_band": {b: _tick_stats(by_band[b]) for b, _ in BANDS},
        "by_class": {c: _tick_stats(by_class[c]) for c in FAULT_CLASSES},
    }


# -- report -------------------------------------------------------------------


def flow_report(events, expect_terminal: bool = False) -> dict:
    """The full flow analysis over an event stream: span terminal-state
    census, audit findings, age distributions.  Pure (events in, dict
    out) so tests can golden it and the ledger can pin it."""
    table = spans_from_events(events)
    findings = audit_spans(table, expect_terminal=True)
    hard = [f for f in findings if f["kind"] != "leak"
            and f["kind"] != "local-leak"]
    leaks = [f for f in findings if f["kind"] in ("leak", "local-leak")]

    spans_emitted = spans_applied = spans_rejected = spans_inflight = 0
    applied_device = applied_host = 0
    flow_events = 0
    for (doc, agent), af in table.agents.items():
        flow_events += (len(af.emits) + len(af.frames) + len(af.buffers)
                        + len(af.readys) + len(af.rejects)
                        + len(af.applies))
        applied = _merge([_span(ev) for ev in af.applies])
        rejected = _merge([_span(ev) for ev in af.rejects
                           if "seq" in ev])
        for ev in af.emits:
            spans_emitted += 1
            s, e = _span(ev)
            if _covered(applied, s, e):
                spans_applied += 1
            elif _covered(_merge(applied + rejected), s, e):
                spans_rejected += 1
            else:
                spans_inflight += 1
        for ev in af.applies:
            if ev.get("mode") == "device":
                applied_device += 1
            else:
                applied_host += 1
    for (doc, lk), slot in table.locals.items():
        spans_emitted += 1
        # Count the lk-keyed emit and reject here; the span's applies
        # were already counted in the agent loop above (an lk apply is
        # indexed BOTH ways — by ordinal to close the emission and by
        # realized seq for the interval audit).
        flow_events += 1
        if slot["applies"]:
            spans_applied += 1
        elif slot["reject"] is not None:
            spans_rejected += 1
            flow_events += 1
        else:
            spans_inflight += 1

    audit_findings = findings if expect_terminal else hard
    out = {
        "flow_events": flow_events,
        "spans": {
            "emitted": spans_emitted,
            "applied": spans_applied,
            "rejected": spans_rejected,
            "in_flight": spans_inflight,
        },
        "applies": {"device": applied_device, "host": applied_host},
        "audit_ok": not audit_findings,
        "findings": audit_findings[:8],
        "leaks": len(leaks),
        "duplicates": sum(1 for f in hard
                          if "duplicate" in f["kind"]),
    }
    out.update(age_stats(table))
    return out

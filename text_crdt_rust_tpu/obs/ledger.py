"""Deterministic cost ledger (ISSUE 10 tentpole, part 1).

Every device-side perf claim in this repo is CPU-proven and
silicon-pending, and until now the evidence lived in one-shot
``perf/*_r*.json`` probe files nothing re-checks: a refactor could
silently regress touched rows, fused-step counts, wire bytes/op or
steady-state recompiles and tier-1 would stay green.  The ledger turns
those numbers into a *committed, diffable cost contract*:

- the same logical-first discipline that makes two same-seed loadgen
  runs emit byte-identical traces (PERF.md §14) makes every logical
  cost metric — device steps, fused rows, touched rows/step, wire and
  checkpoint bytes, admission/codec rejects, compile counts — EXACTLY
  reproducible on CPU, so a perf regression gate needs no wall clock
  and no TPU;
- static compiled-HLO costs (collectives/step, flops, bytes accessed
  via ``jit(...).lower(...).compile().cost_analysis()``) are
  reproducible up to compiler version, so they carry a tolerance band
  instead of an exact pin.

``perf/cost_ledger_probe.py`` derives the cells at small pinned
deterministic shapes and commits them as ``perf/COST_LEDGER.json``;
``bench.py --check-ledger`` re-derives every CPU cell and fails with a
named per-metric diff on drift (a tier-1 test runs the gate, so CPU CI
guards TPU-relevant cost invariants on every PR).

Ledger shape::

    {"schema_version": 1,
     "recorded": {...provenance note...},
     "cells": {
       "<cell>": {
         "kind": "cpu" | "device",      # the gate re-derives cpu cells
         "workload": {...pinned shape description...},
         "metrics": {
           "<metric>": {"v": <number>, "family": "<family>",
                        "tol": <relative band, 0.0 = exact>}}}}}

Wall-clock data NEVER enters a cpu cell: the ledger is a logical cost
contract, and wall histograms belong to the ``device`` cells the
silicon re-record (``perf/when_up_r11.sh``) appends.
"""
from __future__ import annotations

import json
from typing import Dict, List, Tuple

LEDGER_SCHEMA_VERSION = 2  # v2 (ISSUE 16): "recovery" metric family
#                            (journal/replay durability counters)

#: Default committed-artifact location (repo-root relative).
LEDGER_PATH = "perf/COST_LEDGER.json"

#: Known metric families — every metric must claim one, so the
#: committed artifact stays groupable and the coverage floor
#: (>= 6 families, ISSUE 10 acceptance) is checkable.
METRIC_FAMILIES = (
    "steps",        # device steps, pre-fusion steps, fused rows saved
    "compile",      # device_compiles (steady state must stay fixed)
    "wire",         # replication bytes by lane + bytes/op
    "ckpt",         # checkpoint bytes per evict kind, evictions/restores
    "admission",    # admission/codec rejects, admitted counts
    "trace",        # trace event volume, post-mortem bundle counts
    "touched-rows", # blocked-lanes cost-model replay of the tick trace
    "fuse",         # generalized step-fusion accounting
    "hlo",          # static compiled-HLO costs (collectives/flops/bytes)
    "wall",         # device-cell wall histograms (silicon re-record only)
    "flow",         # per-op provenance: span terminal states + op-age-
    #                 at-apply in logical ticks (obs/flow, ISSUE 11) —
    #                 the ROADMAP-7 pipelined-tick latency contract
    "recovery",     # durability (ISSUE 16): journal bytes/op, replayed
    #                 records/ops/ticks-to-recover of the pinned crash
    #                 scenario, byte-identity + crash-audit asserted
    #                 green before pinning
)

CELL_KINDS = ("cpu", "device")


def metric(value, family: str, tol: float = 0.0) -> dict:
    """One ledger metric entry. ``tol`` is a RELATIVE band: 0.0 pins the
    value exactly (logical counters), ``0.5`` accepts ±50% (HLO costs,
    which drift with compiler versions without a logic change)."""
    assert family in METRIC_FAMILIES, family
    assert tol >= 0.0
    v = float(value)
    out = {"v": int(v) if v == int(v) and tol == 0.0 else round(v, 6),
           "family": family}
    if tol:
        out["tol"] = tol
    return out


def validate_ledger(ledger: dict) -> None:
    """Raise ``ValueError`` naming every schema violation — the same
    write-time strictness as ``bench.validate_row``: a drifted artifact
    must refuse loudly, not mis-compare quietly."""
    problems: List[str] = []
    if ledger.get("schema_version") != LEDGER_SCHEMA_VERSION:
        problems.append(
            f"schema_version {ledger.get('schema_version')!r} != "
            f"{LEDGER_SCHEMA_VERSION} (re-record through "
            f"perf/cost_ledger_probe.py)")
    cells = ledger.get("cells")
    if not isinstance(cells, dict) or not cells:
        problems.append("ledger carries no cells")
        cells = {}
    for name, cell in cells.items():
        if cell.get("kind") not in CELL_KINDS:
            problems.append(f"cell {name!r}: unknown kind "
                            f"{cell.get('kind')!r}")
        if not isinstance(cell.get("workload"), dict):
            problems.append(f"cell {name!r}: missing workload pin")
        metrics = cell.get("metrics")
        if not isinstance(metrics, dict) or not metrics:
            problems.append(f"cell {name!r}: no metrics")
            continue
        for mname, m in metrics.items():
            if not isinstance(m, dict) or "v" not in m:
                problems.append(f"metric {name}.{mname}: no value")
                continue
            if not isinstance(m["v"], (int, float)):
                problems.append(f"metric {name}.{mname}: non-numeric "
                                f"value {m['v']!r}")
            if m.get("family") not in METRIC_FAMILIES:
                problems.append(f"metric {name}.{mname}: unknown family "
                                f"{m.get('family')!r}")
            if m.get("tol") is not None and (
                    not isinstance(m["tol"], (int, float))
                    or m["tol"] < 0):
                problems.append(f"metric {name}.{mname}: bad tol "
                                f"{m.get('tol')!r}")
    if problems:
        raise ValueError("cost ledger violates the schema: "
                         + "; ".join(problems))


def families_covered(ledger: dict) -> set:
    return {m.get("family")
            for cell in ledger.get("cells", {}).values()
            for m in cell.get("metrics", {}).values()}


def diff_cell(name: str, committed: dict, fresh: dict) -> List[str]:
    """Named per-metric diffs between one committed cell and its fresh
    re-derivation.  Drift in EITHER direction is a finding: a value
    outside its band, a committed metric the code no longer produces,
    or a new metric the ledger never recorded (schema growth that needs
    a deliberate re-record, not a silent pass)."""
    out: List[str] = []
    cm = committed.get("metrics", {})
    fm = fresh.get("metrics", {})
    for mname in sorted(cm):
        if mname not in fm:
            out.append(f"{name}.{mname}: committed "
                       f"{cm[mname]['v']} but the probe no longer "
                       f"derives it (re-record the ledger if deliberate)")
            continue
        want, got = cm[mname]["v"], fm[mname]["v"]
        tol = cm[mname].get("tol", 0.0)
        if tol:
            band = abs(want) * tol
            if abs(got - want) > band:
                out.append(
                    f"{name}.{mname} [{cm[mname]['family']}]: "
                    f"{got} outside {want} ±{tol * 100:.0f}% "
                    f"(band ±{band:.6g})")
        elif got != want:
            out.append(
                f"{name}.{mname} [{cm[mname]['family']}]: "
                f"{got} != committed {want} (exact logical counter)")
    for mname in sorted(set(fm) - set(cm)):
        out.append(f"{name}.{mname}: derived {fm[mname]['v']} but the "
                   f"committed ledger never recorded it (re-record to "
                   f"adopt the new metric)")
    return out


def diff_ledger(committed: dict, fresh_cells: Dict[str, dict]
                ) -> Tuple[bool, List[str]]:
    """Compare committed cells against freshly derived ones; only cells
    present in ``fresh_cells`` are judged (the gate derives the cpu
    cells; device cells wait for silicon).  Returns (ok, named diffs).
    """
    diffs: List[str] = []
    cells = committed.get("cells", {})
    for name in sorted(fresh_cells):
        if name not in cells:
            diffs.append(f"{name}: derived a cell the committed ledger "
                         f"does not carry (re-record to adopt it)")
            continue
        diffs.extend(diff_cell(name, cells[name], fresh_cells[name]))
    return not diffs, diffs


def cpu_cell_names(ledger: dict) -> List[str]:
    """The cells the wall-clock-free gate can re-derive on any box."""
    return sorted(n for n, c in ledger.get("cells", {}).items()
                  if c.get("kind") == "cpu")


def load_ledger(path: str = LEDGER_PATH) -> dict:
    with open(path) as f:
        return json.load(f)

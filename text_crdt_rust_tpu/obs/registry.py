"""One metrics registry for counters, gauges and bounded histograms
(ISSUE 8 tentpole, part 3).

``utils.metrics.Counters`` grew organically across PRs 1–7: monotonic
counts, high-water gauges, and mean-only ``sample`` gauges — and the
means hid real distributions (the PR-6 ``ops_per_step`` skew was
invisible until the per-shape histogram landed).  ``MetricsRegistry``
extends ``Counters`` (every existing ``incr``/``hiwater``/``sample``
call site keeps working, min/max now ride along) with:

- ``histo(name, value)`` — **bounded** histograms: count/sum/min/max
  are always exact; percentiles come from a bounded sample buffer that
  decimates deterministically (keep-every-k-th with k doubling) when
  full, feeding the one shared ``utils.metrics.percentiles``
  definition so p99 can't mean different things in different reports;
- ``gauge(name, value)`` — last-value gauges;
- exporters: ``summary()`` (flat dict — what ``DocServer.stats()``,
  the loadgen report and the bench rows consume), ``to_jsonl()``
  (versioned one-metric-per-line JSONL) and ``prometheus_text()``
  (the text exposition format, counters + summary quantiles).

The registry is deliberately deterministic: no wall-clock anywhere,
decimation depends only on the sample sequence — so registry state is
part of the same-seed reproducibility contract the tracer pins.
"""
from __future__ import annotations

import json
import re
from typing import Dict, List

from ..utils.metrics import Counters, percentiles

REGISTRY_SCHEMA_VERSION = 1

# Default bounded-buffer size: percentile error from decimation is
# negligible far below this; memory is bounded at cap floats/histogram.
_DEFAULT_CAP = 1024

_PROM_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str, prefix: str = "tcr") -> str:
    """A conformant Prometheus metric name: invalid characters collapse
    to ``_``, and the result may not start with a digit
    (``[a-zA-Z_:][a-zA-Z0-9_:]*`` per the exposition format spec)."""
    s = _PROM_SANITIZE.sub("_", name)
    full = f"{prefix}_{s}" if prefix else s
    if not full or full[0].isdigit():
        full = "_" + full
    return full


def prom_escape_label(value) -> str:
    """Label-VALUE escaping per the text exposition format: backslash,
    double-quote and newline must be escaped inside the quotes."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_escape_help(text: str) -> str:
    """# HELP text escaping: backslash and newline only."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


class Histogram:
    """Bounded histogram with deterministic decimation.

    Exact ``count``/``total``/``min``/``max``; a bounded sample buffer
    for percentiles.  When the buffer fills, every other retained
    sample is dropped and the keep-stride doubles — so the buffer holds
    an evenly-spaced subsample of the whole series (not just its
    prefix or suffix), and two identical series always decimate
    identically.
    """

    __slots__ = ("cap", "samples", "stride", "_phase", "count", "total",
                 "vmin", "vmax")

    def __init__(self, cap: int = _DEFAULT_CAP):
        assert cap >= 2
        self.cap = cap
        self.samples: List[float] = []
        self.stride = 1
        self._phase = 0
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")

    def add(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        self._phase += 1
        if self._phase >= self.stride:
            self._phase = 0
            self.samples.append(v)
            if len(self.samples) > self.cap:
                self.samples = self.samples[::2]
                self.stride *= 2

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def quantiles(self, points=(50, 99)) -> Dict[str, float]:
        return percentiles(self.samples, points)

    def summary(self) -> Dict[str, float]:
        if not self.count:
            return {"count": 0, "mean": 0.0, "min": 0.0, "max": 0.0,
                    "p50": 0.0, "p99": 0.0}
        out = {"count": self.count, "mean": round(self.mean, 6),
               "min": self.vmin, "max": self.vmax}
        out.update(self.quantiles())
        return out


class MetricsRegistry(Counters):
    """``Counters`` + gauges + bounded histograms + exporters — the ONE
    sink every serve/net/bench metric flows through (ISSUE 8)."""

    def __init__(self) -> None:
        super().__init__()
        self._gauges: Dict[str, float] = {}
        self._histos: Dict[str, Histogram] = {}

    # -- new instrument surface ---------------------------------------------

    def gauge(self, name: str, value: float) -> None:
        self._gauges[name] = value

    def histo(self, name: str, value: float, cap: int = _DEFAULT_CAP) -> None:
        h = self._histos.get(name)
        if h is None:
            h = self._histos[name] = Histogram(cap)
        h.add(value)

    def histogram(self, name: str) -> Histogram:
        """The named histogram (created empty if absent) — for callers
        that want the exact count/percentile surface, not the flat
        summary keys."""
        h = self._histos.get(name)
        if h is None:
            h = self._histos[name] = Histogram()
        return h

    # -- exporters -----------------------------------------------------------

    def summary(self) -> Dict[str, float]:
        out = super().summary()
        out.update(self._gauges)
        for name, h in self._histos.items():
            for k, v in h.summary().items():
                out[f"{name}_{k}"] = v
        return out

    def to_jsonl(self) -> str:
        """Versioned one-metric-per-line JSONL: a ``meta`` header line,
        then ``{"name", "type", ...}`` per metric — the machine-readable
        export the bench rows and dashboards ingest."""
        lines = [json.dumps({"meta": "metrics",
                             "schema": REGISTRY_SCHEMA_VERSION},
                            sort_keys=True, separators=(",", ":"))]
        for name in sorted(self._counts):
            lines.append(json.dumps(
                {"name": name, "type": "counter",
                 "value": self._counts[name]},
                sort_keys=True, separators=(",", ":")))
        for name in sorted(self._hiwater):
            lines.append(json.dumps(
                {"name": name, "type": "hiwater",
                 "value": self._hiwater[name]},
                sort_keys=True, separators=(",", ":")))
        for name in sorted(self._gauges):
            lines.append(json.dumps(
                {"name": name, "type": "gauge",
                 "value": self._gauges[name]},
                sort_keys=True, separators=(",", ":")))
        for name in sorted(self._samples):
            total, count, vmin, vmax = self._sample_stats(name)
            lines.append(json.dumps(
                {"name": name, "type": "sample", "count": count,
                 "mean": round(total / count, 6) if count else 0.0,
                 "min": vmin, "max": vmax},
                sort_keys=True, separators=(",", ":")))
        for name in sorted(self._histos):
            row = {"name": name, "type": "histogram"}
            row.update(self._histos[name].summary())
            lines.append(json.dumps(row, sort_keys=True,
                                    separators=(",", ":")))
        return "\n".join(lines) + "\n"

    def prometheus_text(self, prefix: str = "tcr") -> str:
        """Prometheus text exposition: counters as ``counter``, hiwater
        and gauges as ``gauge``, samples and histograms as ``summary``
        (quantiles + _sum + _count).  Conformance (ISSUE 10 satellite):
        sanitized names with the leading-digit rule, escaped label
        values, one ``# HELP``/``# TYPE`` pair per metric, and sanitize
        collisions disambiguated (two raw names collapsing to one
        exposition name — or one raw name reused across metric kinds —
        would otherwise emit a duplicate ``# TYPE``, invalid per the
        format spec).  The suffix is a per-base ordinal, so a colliding
        metric's exposed name stays stable as unrelated metrics appear
        between scrapes."""
        seen: dict = {}  # exposition base -> times already emitted

        def _n(name: str) -> str:
            full = prom_name(name, prefix)
            k = seen.get(full, 0)
            seen[full] = k + 1
            return full if k == 0 else f"{full}_{k}"

        out: List[str] = []

        def _head(n: str, raw: str, mtype: str, what: str) -> None:
            out.append(f"# HELP {n} "
                       f"{_prom_escape_help(f'{what} {raw!r}')}")
            out.append(f"# TYPE {n} {mtype}")

        for name in sorted(self._counts):
            n = _n(name)
            _head(n, name, "counter", "monotonic counter")
            out.append(f"{n} {self._counts[name]}")
        for name in sorted(self._hiwater):
            n = _n(name)
            _head(n, name, "gauge", "high-water gauge")
            out.append(f"{n} {self._hiwater[name]}")
        for name in sorted(self._gauges):
            n = _n(name)
            _head(n, name, "gauge", "last-value gauge")
            out.append(f"{n} {self._gauges[name]}")
        for name in sorted(self._samples):
            total, count, _vmin, _vmax = self._sample_stats(name)
            n = _n(name)
            _head(n, name, "summary", "mean-gauge sample")
            out.append(f"{n}_sum {total}")
            out.append(f"{n}_count {count}")
        for name in sorted(self._histos):
            h = self._histos[name]
            n = _n(name)
            _head(n, name, "summary", "bounded histogram")
            for p, v in h.quantiles().items():
                q = float(p[1:]) / 100.0
                out.append(f'{n}{{quantile="{prom_escape_label(q)}"}} {v}')
            out.append(f"{n}_sum {h.total}")
            out.append(f"{n}_count {h.count}")
        return "\n".join(out) + "\n"


def observe(counters, name: str, value: float) -> None:
    """Record ``value`` into ``counters``' histogram ``name`` when the
    sink supports histograms (a ``MetricsRegistry``), else fall back to
    the mean-gauge ``sample`` — so serve components instrument
    unconditionally and plain-``Counters`` call sites keep working."""
    h = getattr(counters, "histo", None)
    if h is not None:
        h(name, value)
    else:
        counters.sample(name, value)

"""Structured observability for the serving stack (ISSUEs 8 + 10).

Five layers, each consumable on its own:

- ``obs.trace``    — a logical-clock-first span/event tracer emitting
                     versioned JSONL (size-capped segment rotation for
                     long runs) with wall-clock fields segregated, so
                     two same-seed runs produce byte-identical
                     *logical* traces (the determinism oracle);
- ``obs.registry`` — one metrics registry (counters + gauges + bounded
                     histograms) with JSONL and Prometheus-text
                     exporters, unifying what used to be scattered
                     across ``Counters``, ``tick_summary`` and ad-hoc
                     report dicts;
- ``obs.recorder`` — a bounded flight recorder that, on any typed
                     failure or twin/lane bit-identity mismatch, dumps
                     a post-mortem bundle (last-N events, counters,
                     doc stats, the offending tick's compiled-step
                     metadata, and a first-divergence walk);
- ``obs.ledger``   — the deterministic cost ledger: logical cost
                     metrics per config cell, committed as
                     ``perf/COST_LEDGER.json`` and re-derived by
                     ``bench.py --check-ledger`` — the wall-clock-free
                     perf regression gate;
- ``obs.analyze``  — trace analytics CLI: per-tick phase breakdown,
                     hot-doc and fusion tables, recompile timeline,
                     two-trace logical diff, Chrome trace-event export,
                     per-op flow census + conservation audit;
- ``obs.flow``     — per-op provenance spans (ISSUE 11): every sampled
                     op's ``(agent, seq)`` journey emitted as
                     ``flow.*`` trace events, with a conservation
                     audit (leaked/double-applied spans are named
                     findings) and op-age-at-apply distributions on
                     the logical tick axis.
"""
from .flow import FlowTracker, audit_spans, flow_report  # noqa: F401
from .ledger import (  # noqa: F401
    LEDGER_SCHEMA_VERSION,
    diff_ledger,
    load_ledger,
    validate_ledger,
)
from .recorder import FlightRecorder  # noqa: F401
from .registry import Histogram, MetricsRegistry, observe  # noqa: F401
from .trace import TRACE_SCHEMA_VERSION, Tracer, validate_event  # noqa: F401

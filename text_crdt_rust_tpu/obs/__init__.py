"""Structured observability for the serving stack (ISSUE 8).

Three layers, each consumable on its own:

- ``obs.trace``    — a logical-clock-first span/event tracer emitting
                     versioned JSONL with wall-clock fields segregated,
                     so two same-seed runs produce byte-identical
                     *logical* traces (the determinism oracle);
- ``obs.registry`` — one metrics registry (counters + gauges + bounded
                     histograms) with JSONL and Prometheus-text
                     exporters, unifying what used to be scattered
                     across ``Counters``, ``tick_summary`` and ad-hoc
                     report dicts;
- ``obs.recorder`` — a bounded flight recorder that, on any typed
                     failure or twin/lane bit-identity mismatch, dumps
                     a post-mortem bundle (last-N events, counters,
                     doc stats, the offending tick's compiled-step
                     metadata, and a first-divergence walk).
"""
from .recorder import FlightRecorder  # noqa: F401
from .registry import Histogram, MetricsRegistry, observe  # noqa: F401
from .trace import TRACE_SCHEMA_VERSION, Tracer, validate_event  # noqa: F401

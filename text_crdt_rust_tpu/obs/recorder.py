"""Flight recorder + divergence post-mortems (ISSUE 8 tentpole, part 2).

Both real bugs the serve stack has caught so far (PR 4's epoch-boundary
straddle, PR 7's zipfile flag-bit refusal) were flushed out by
bit-identity oracles that only say *that* state diverged — finding
*when* meant re-running the seed under print statements.  The flight
recorder closes that gap: it rides along every run at ring-buffer cost,
and on any typed failure or twin/lane mismatch dumps a post-mortem
bundle — a versioned JSON artifact carrying:

- the last-N trace events (filtered to the offending doc/shard when
  one is named) from the tracer's bounded ring;
- a full metrics snapshot (``Counters``/``MetricsRegistry`` summary);
- ``doc_stats`` of the offending doc's oracle when it is resident;
- the offending doc's last compiled-step metadata (tick, step counts,
  bucket) — what the device was actually asked to run;
- the CRCs + lengths of the most recent wire frames (what came off
  the network right before the failure);
- for divergence failures, a **first-divergence walk**: the two states
  compared item by item in document order, the first differing item
  named as peer-portable ``(agent, seq)``, and — joined against the
  recorder's per-doc apply log — the exact logical tick and trace
  event that introduced it; with per-op provenance on (obs/flow), the
  diverged span's FULL flow path (emit -> frame -> buffer -> ready ->
  apply) rides along so the bundle names the op's whole journey.

Trigger classes (``REASONS``): ``codec`` (`net/codec.CodecError`),
``causal-gap`` (`net/session.CausalGapError`), ``checkpoint``
(`utils/checkpoint.CheckpointError`), ``degrade`` (lane-capacity
overflow), ``divergence`` (digest mismatch or twin/lane bit-identity
mismatch), ``journal`` (`serve/journal.JournalError` — a refused
journal segment at recovery), ``pipeline-flush`` (the emergency
in-flight sync while a tick unwinds failed too).  Bundles are BOUNDED: the first failure of each reason
class dumps, later ones are counted (``bundles_suppressed``) — a 10%
fault-injection loadgen run must not write thousands of bundles.
"""
from __future__ import annotations

import itertools
import json
import os
from collections import deque
from typing import Dict, List, Optional, Tuple

BUNDLE_SCHEMA_VERSION = 1

REASON_CODEC = "codec"
REASON_CAUSAL_GAP = "causal-gap"
REASON_CHECKPOINT = "checkpoint"
REASON_DEGRADE = "degrade"
REASON_DIVERGENCE = "divergence"
REASON_JOURNAL = "journal"
REASON_PIPELINE_FLUSH = "pipeline-flush"
REASONS = (REASON_CODEC, REASON_CAUSAL_GAP, REASON_CHECKPOINT,
           REASON_DEGRADE, REASON_DIVERGENCE, REASON_JOURNAL,
           REASON_PIPELINE_FLUSH)

# Per-process recorder ids: several servers (or a flat-twin pair in one
# probe) may share one out_dir — e.g. the conftest TCR_TRACE_DIR
# workflow — and their bundles must not overwrite each other.  The pid
# disambiguates across processes sharing the dir.
_RECORDER_IDS = itertools.count()


def item_key(doc, i: int) -> Tuple[str, int, int, bool]:
    """Item ``i`` of an oracle as a peer-portable comparison key:
    (author agent name, seq, codepoint, deleted) — local orders never
    appear, so the walk is valid across peers that interleaved the same
    history differently (the ``state_digest`` argument)."""
    agent, seq = doc.loc_of_order(int(doc.order[i]))
    return (doc.get_agent_name(agent), seq, int(doc.chars[i]),
            bool(doc.deleted[i]))


def first_divergence(a, b) -> Optional[dict]:
    """Walk two oracles in document order; the first differing item (or
    the length difference) as a dict, ``None`` when bit-identical. Runs
    only on the failure path — O(n) python is fine there."""
    n = min(a.n, b.n)
    for i in range(n):
        ka, kb = item_key(a, i), item_key(b, i)
        if ka != kb:
            return {"item_index": i,
                    "server": {"agent": ka[0], "seq": ka[1],
                               "char": ka[2], "deleted": ka[3]},
                    "twin": {"agent": kb[0], "seq": kb[1],
                             "char": kb[2], "deleted": kb[3]},
                    # The item whose introduction diverged: the server
                    # side's author id is what joins the apply log.
                    "agent": ka[0], "seq": ka[1]}
    if a.n != b.n:
        longer, which = (a, "server") if a.n > b.n else (b, "twin")
        ka = item_key(longer, n)
        return {"item_index": n, "only_in": which,
                "agent": ka[0], "seq": ka[1],
                which: {"agent": ka[0], "seq": ka[1],
                        "char": ka[2], "deleted": ka[3]}}
    return None


class FlightRecorder:
    """Bounded post-mortem recorder for one server.

    Subscribes to the tracer to maintain a per-doc apply log (bounded
    deque of ``(agent, seq, n, tick, event_seq)``) and a bounded recent
    wire-frame log; on a trigger, writes one JSON bundle per reason
    class into ``out_dir`` and counts the rest.
    """

    def __init__(self, tracer, counters, out_dir: str, *,
                 ring_events: int = 256, apply_ring: int = 256,
                 frame_ring: int = 64, max_bundles_per_reason: int = 1):
        self.tracer = tracer
        self.counters = counters
        self.out_dir = out_dir
        self.ring_events = ring_events
        self.apply_ring = apply_ring
        self.max_bundles_per_reason = max_bundles_per_reason
        self.bundle_paths: List[str] = []
        self._dumped: Dict[str, int] = {}
        self._applies: Dict[str, deque] = {}
        # Per-doc ring of flow.* provenance events (ISSUE 11): the
        # divergence bundle joins the diverged span's FULL path —
        # emit/frame/buffer/ready/apply — not just its apply record.
        self._flows: Dict[str, deque] = {}
        self._frames: deque = deque(maxlen=max(1, frame_ring))
        # Last compiled-step metadata per doc (the batcher records it
        # right before the device pass).
        self._streams: Dict[str, dict] = {}
        self._n = 0
        self._tag = f"{os.getpid()}_{next(_RECORDER_IDS)}"
        if tracer is not None:
            tracer.subscribe(self._on_event)

    # -- feeds ---------------------------------------------------------------

    def _on_event(self, ev: dict) -> None:
        kind = ev.get("k")
        if isinstance(kind, str) and kind.startswith("flow."):
            doc = ev.get("doc")
            ring = self._flows.get(doc)
            if ring is None:
                ring = self._flows[doc] = deque(maxlen=self.apply_ring)
            ring.append(ev)
            return
        if kind != "apply":
            return
        doc = ev["doc"]
        ring = self._applies.get(doc)
        if ring is None:
            ring = self._applies[doc] = deque(maxlen=self.apply_ring)
        ring.append((ev["agent"], ev["seq"], ev["n"], ev["t"], ev["i"]))

    def note_frame(self, doc_id: Optional[str], data: bytes) -> None:
        """Log one received wire frame's length + trailing CRC bytes
        (the codec's outer CRC32C) — cheap enough for every frame."""
        crc = data[-4:].hex() if len(data) >= 4 else data.hex()
        self._frames.append({"doc": doc_id, "len": len(data), "crc": crc})

    def record_stream(self, doc_id: str, meta: dict) -> None:
        """The doc's latest compiled tick stream metadata (one dict,
        overwritten per tick) — 'what was the device asked to run'."""
        self._streams[doc_id] = meta

    def find_apply(self, doc_id: str, agent: str,
                   seq: int) -> Optional[dict]:
        """The apply-log record whose (agent, seq..seq+n) span covers
        the given id — names the tick + trace event that introduced an
        item. ``None`` when it rotated out of the bounded log."""
        for a, s, n, tick, ev_seq in self._applies.get(doc_id, ()):
            if a == agent and s <= seq < s + max(n, 1):
                return {"agent": a, "seq": s, "n": n, "tick": tick,
                        "event": ev_seq}
        return None

    # -- triggers ------------------------------------------------------------

    def on_failure(self, reason: str, detail: str, *,
                   doc_id: Optional[str] = None,
                   shard: Optional[int] = None,
                   tick: Optional[int] = None,
                   oracle=None, extra: Optional[dict] = None
                   ) -> Optional[str]:
        """Dump a post-mortem bundle for one typed failure; returns the
        bundle path, or ``None`` when this reason class already hit its
        bundle budget (the suppression is counted)."""
        assert reason in REASONS, reason
        self.counters.incr(f"obs_failures_{reason.replace('-', '_')}")
        seen = self._dumped.get(reason, 0)
        if seen >= self.max_bundles_per_reason:
            self.counters.incr("bundles_suppressed")
            return None
        self._dumped[reason] = seen + 1
        bundle = self._bundle(reason, detail, doc_id=doc_id, shard=shard,
                              tick=tick, oracle=oracle, extra=extra)
        return self._write(bundle)

    def flow_path(self, doc_id: str, agent: str,
                  seq: int) -> List[dict]:
        """Every retained flow.* event whose span covers ``(agent,
        seq)`` for this doc, in emission order — the op's full journey
        (emit -> frame -> buffer/ready -> apply) as far as the bounded
        ring still holds it.  Local spans have no seq until apply, so
        a covering apply's ``lk`` pulls in the span's ordinal-keyed
        records (the emission, any invalid-position reject) too."""
        ring = list(self._flows.get(doc_id, ()))
        out = []
        lks = set()
        for ev in ring:
            if ev.get("agent") != agent or "seq" not in ev:
                continue
            s = int(ev["seq"])
            if s <= seq < s + max(int(ev.get("n", 1)), 1):
                out.append(ev)
                if "lk" in ev:
                    lks.add(ev["lk"])
        if lks:
            out.extend(ev for ev in ring
                       if "seq" not in ev and ev.get("lk") in lks
                       and ev.get("agent") == agent)
            out.sort(key=lambda ev: ev["i"])
        return out

    def on_divergence(self, doc_id: str, server_oracle, twin_oracle, *,
                      detail: str = "twin-check bit-identity mismatch",
                      tick: Optional[int] = None) -> Optional[str]:
        """The divergence post-mortem: first-divergence walk + apply-log
        join, then a bundle.  This is the artifact that answers *when*
        — the exact logical tick, doc, and event where the twin first
        diverged (ISSUE 8 acceptance).  When per-op provenance was on
        (obs/flow), the bundle also carries the diverged span's FULL
        flow path (ISSUE 11 satellite) — not just the apply that
        introduced the item, but its whole journey into the server."""
        fd = first_divergence(server_oracle, twin_oracle)
        extra = {"first_divergence": fd}
        if fd is not None:
            extra["apply_event"] = self.find_apply(doc_id, fd["agent"],
                                                   fd["seq"])
            extra["flow_path"] = self.flow_path(doc_id, fd["agent"],
                                                fd["seq"])
        return self.on_failure(REASON_DIVERGENCE, detail, doc_id=doc_id,
                               tick=tick, oracle=server_oracle,
                               extra=extra)

    # -- bundle assembly -----------------------------------------------------

    def _bundle(self, reason: str, detail: str, *, doc_id, shard, tick,
                oracle, extra) -> dict:
        bundle = {
            "schema_version": BUNDLE_SCHEMA_VERSION,
            "reason": reason,
            "detail": detail,
            "doc": doc_id,
            "shard": shard,
            "tick": (tick if tick is not None
                     else (self.tracer.tick if self.tracer else None)),
            "events": (self.tracer.last(self.ring_events, doc=doc_id,
                                        shard=shard)
                       if self.tracer is not None else []),
            "counters": self.counters.summary(),
            "recent_frames": list(self._frames),
            "compiled_step_meta": (self._streams.get(doc_id)
                                   if doc_id else None),
        }
        if oracle is not None:
            from ..utils.metrics import doc_stats

            try:
                bundle["doc_stats"] = doc_stats(oracle)
            except Exception as e:  # stats must never mask the failure
                bundle["doc_stats"] = {"error": f"{type(e).__name__}: {e}"}
        if extra:
            bundle.update(extra)
        return bundle

    def _write(self, bundle: dict) -> str:
        os.makedirs(self.out_dir, exist_ok=True)
        name = f"bundle_{self._tag}_{self._n:03d}_{bundle['reason']}.json"
        self._n += 1
        path = os.path.join(self.out_dir, name)
        with open(path, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        self.bundle_paths.append(path)
        self.counters.incr("bundles_written")
        return path

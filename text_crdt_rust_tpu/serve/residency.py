"""Doc lifecycle: admit -> resident-in-lane -> evicted-to-checkpoint.

An LRU policy over the shards' device lanes. The working set of hot
documents lives in lanes (device-accelerated); colder documents fall
back in two graceful stages, neither of which is ever an assert:

- **host-only** — oracle in memory, no lane (all lanes hotter, or the
  doc outgrew the lane capacity and is permanently ``degraded``). Ticks
  still apply its events to the oracle; the next lane acquisition
  re-seeds device state wholesale via ``upload_lane`` (the flat
  backend's ``span_arrays.upload_oracle`` warm-start path).
- **evicted** — the oracle is serialized through ``utils/checkpoint.py``
  (FORMAT_VERSION 3, CRC-guarded: a restore is bit-perfect or refuses)
  and dropped from memory. ``ckpt_format="delta"`` (the default via
  ``ServeConfig``) writes a ``CheckpointChain`` link — the
  columnar-encoded ops since the last save, O(new ops) instead of
  O(doc), ~6.4x smaller per warm evict on the loadgen (PERF.md §13) —
  with periodic base compaction; ``"full"`` keeps the one-snapshot-
  per-evict PR-3 behavior. The doc's ``CausalBuffer`` and event queue
  stay live, so peer traffic keeps accumulating causally while the doc
  is out. A later touch restores: ``load_doc`` (or the chain's
  base + replay) rebuilds the oracle, ``OrderAssigner.from_oracle``
  rebuilds the compiler state, and the queued events replay through
  the normal tick path — the edited-by-peers-while-out invariant
  ``tests/test_serve_residency.py`` pins against an always-resident
  twin.

Eviction preference: least-recently-touched lane doc without pending
events; a victim touched in the current tick is never stolen (the
restored doc serves host-only for a tick instead — bounded, no
livelock). The analog of paged-out KV cache + prompt re-upload in LLM
serving: restore costs O(doc), correctness costs nothing.
"""
from __future__ import annotations

import os
import tempfile
import time
from typing import Dict, List, Optional

from ..ops import batch as B
from ..utils import checkpoint
from ..utils.metrics import Counters
from .batcher import oracle_signed
from .router import DocState, ShardRouter


class LaneResidency:
    """Lane ownership + the evict/restore state machine."""

    def __init__(self, backends: List, router: ShardRouter, *,
                 spool_dir: Optional[str] = None,
                 counters: Optional[Counters] = None,
                 ckpt_format: str = "full",
                 ckpt_compact_ops: int = 4096,
                 ckpt_compact_links: int = 16,
                 tracer=None):
        assert ckpt_format in ("full", "delta"), ckpt_format
        self.backends = backends
        self.router = router
        self.counters = counters if counters is not None else Counters()
        self.tracer = tracer
        self.recorder = None  # set by DocServer after construction
        self.spool_dir = spool_dir or tempfile.mkdtemp(prefix="tcr_serve_")
        os.makedirs(self.spool_dir, exist_ok=True)
        # "full" = one O(doc) snapshot per evict (the PR-3 behavior);
        # "delta" = CRC-chained incremental saves, O(ops since the last
        # save) for a warm evict, with periodic base compaction.
        self.ckpt_format = ckpt_format
        self.ckpt_compact_ops = ckpt_compact_ops
        self.ckpt_compact_links = ckpt_compact_links
        self._chains: Dict[str, checkpoint.CheckpointChain] = {}
        # lane_owner[shard][lane] -> doc_id | None
        self.lane_owner: List[List[Optional[str]]] = [
            [None] * b.lanes for b in backends
        ]
        self._ckpt_ids: Dict[str, int] = {}
        # Monotonic file-number allocator, NOT len(_ckpt_ids): after a
        # crash recovery that refused a corrupt checkpoint, the refused
        # file's number must stay burned or a new doc would collide
        # with a surviving doc's files.
        self._next_ckpt_id = 0

    # -- introspection -------------------------------------------------------

    def resident_counts(self) -> Dict[str, int]:
        in_lane = sum(1 for s in self.lane_owner for d in s if d)
        docs = self.router.docs.values()
        return {
            "docs_total": len(self.router.docs),
            "docs_in_lane": in_lane,
            "docs_host_only": sum(1 for d in docs
                                  if d.resident and not d.in_lane),
            "docs_evicted": sum(1 for d in docs if d.evicted),
            "docs_degraded": sum(1 for d in docs if d.degraded),
        }

    def _ckpt_path(self, doc_id: str) -> str:
        # Stable, filesystem-safe name per doc (ids are arbitrary strings).
        if doc_id not in self._ckpt_ids:
            self._ckpt_ids[doc_id] = self._next_ckpt_id
            self._next_ckpt_id += 1
        return os.path.join(self.spool_dir,
                            f"doc_{self._ckpt_ids[doc_id]:06d}.npz")

    # -- lane allocation -----------------------------------------------------

    def _free_lane(self, shard: int) -> Optional[int]:
        for lane, owner in enumerate(self.lane_owner[shard]):
            if owner is None:
                return lane
        return None

    def _lru_victim(self, shard: int, tick_no: int) -> Optional[DocState]:
        """Least-recently-touched lane doc of ``shard`` that is safe to
        steal from: prefer docs with no pending events; never one
        touched this tick."""
        docs = [self.router.docs[d] for d in self.lane_owner[shard] if d]
        docs = [d for d in docs if d.last_touch_tick < tick_no]
        if not docs:
            return None
        idle = [d for d in docs if not d.events]
        pool = idle or docs
        return min(pool, key=lambda d: d.last_touch_tick)

    def try_assign_lane(self, doc: DocState, tick_no: int) -> bool:
        """Find ``doc`` a lane on its shard (evicting the LRU victim if
        none is free). False = stay host-only this tick (every lane is
        hotter) — a deferral, not a failure."""
        assert doc.resident and not doc.in_lane
        backend = self.backends[doc.shard]
        # fits_doc is the backend's EXACT occupancy probe (chars for the
        # flat engine, RLE run rows for the blocked lanes engine).
        if not backend.fits_doc(doc.oracle):
            self.degrade(doc, f"doc ({doc.oracle.n} rows, "
                              f"{doc.oracle.get_next_order()} orders) "
                              f"exceeds lane capacity "
                              f"{backend.capacity}/{backend.order_capacity}")
            return False
        lane = self._free_lane(doc.shard)
        if lane is None:
            victim = self._lru_victim(doc.shard, tick_no)
            if victim is None:
                self.counters.incr("lane_acquire_deferred")
                return False
            self.evict(victim)
            lane = self._free_lane(doc.shard)
            assert lane is not None
        doc.lane = lane
        # Granting a lane IS a touch: without the stamp, every doc's
        # last_touch_tick predates this tick (submissions happen between
        # ticks) and the LRU's touched-this-tick guard would be vacuous
        # — a doc restored early in the residency pass could be stolen
        # again later in the SAME pass, stalling its queued events.
        doc.last_touch_tick = tick_no
        self.lane_owner[doc.shard][lane] = doc.doc_id
        backend.upload_lane(lane, doc.oracle, doc.table.rank_of_agent())
        self.counters.incr("lane_uploads")
        return True

    def release_lane(self, doc: DocState) -> None:
        if not doc.in_lane:
            return
        self.backends[doc.shard].clear_lane(doc.lane)
        self.lane_owner[doc.shard][doc.lane] = None
        doc.lane = None

    def degrade(self, doc: DocState, reason: str) -> None:
        """Capacity overflow: host-oracle-only from here on (the
        ``DeviceMirror`` degrade contract — never an assert)."""
        self.release_lane(doc)
        doc.degraded = True
        doc.degrade_reason = reason
        self.counters.incr("lane_overflow_degraded")
        if self.tracer is not None:
            self.tracer.event("residency.degrade", doc=doc.doc_id,
                              reason=reason)
        if self.recorder is not None:
            self.recorder.on_failure("degrade", reason, doc_id=doc.doc_id,
                                     shard=doc.shard, oracle=doc.oracle)

    # -- evict / restore -----------------------------------------------------

    def evict(self, doc: DocState) -> str:
        """Serialize the oracle to its CRC-guarded checkpoint, drop the
        in-memory state, free the lane. The causal buffer and event
        queue survive in memory (peers keep editing the doc while it is
        out). Returns the checkpoint path."""
        assert doc.resident, "evicting an already-evicted doc"
        path = self._ckpt_path(doc.doc_id)
        # Snapshot the oracle's per-agent watermarks first: REQUEST
        # emission must keep seeing the persisted history's extent
        # (router.poll_request_frame reads known_marks).
        doc.absorb_oracle_marks()
        t_io = time.perf_counter()
        # Extra meta rides every save (ISSUE 16): the doc id maps files
        # back to docs when recovery rediscovers checkpoints from disk
        # (``_ckpt_ids`` died with the process), and ``local_applied``
        # is an audit stamp written atomically with the oracle state it
        # describes — reserved for future incremental recovery; today's
        # replay re-executes from genesis and never reads it back.
        extra = {"doc_id": doc.doc_id,
                 "local_applied": doc.local_applied}
        if self.ckpt_format == "delta":
            chain = self._chains.get(doc.doc_id)
            if chain is None:
                chain = self._chains[doc.doc_id] = checkpoint.CheckpointChain(
                    path[:-len(".npz")],
                    compact_ops=self.ckpt_compact_ops,
                    compact_links=self.ckpt_compact_links)
            info = chain.save(doc.oracle, extra_meta=extra)
            path = chain.base_path
        else:
            info = checkpoint.save_doc(doc.oracle, path, extra_meta=extra)
            info = {"kind": "full", "bytes": info["bytes"]}
        io_ms = (time.perf_counter() - t_io) * 1e3
        self.counters.incr(f"ckpt_saves_{info['kind']}")
        if info["kind"] != "noop":
            # "noop" = the chain tip already covers this state (zero
            # new ops since the last save) — nothing written, and a
            # 0-byte sample would flatter the per-evict means.
            self.counters.incr("ckpt_bytes_written", info["bytes"])
            self.counters.incr(f"ckpt_bytes_{info['kind']}", info["bytes"])
            self.counters.sample("ckpt_bytes_per_evict", info["bytes"])
            # Split by kind: the warm-evict claim compares the mean
            # DELTA link against the mean FULL snapshot, not the
            # blended mean.
            self.counters.sample(f"ckpt_{info['kind']}_bytes_per_evict",
                                 info["bytes"])
        # Conservation checkpoint (ISSUE 11): the doc's item/order
        # counts at the eviction boundary.  The flow audit pairs these
        # with the restore's — a checkpoint replay that re-APPLIED
        # history (instead of re-creating state) would inflate them.
        n_items = doc.oracle.n
        n_orders = doc.oracle.get_next_order()
        doc.ckpt_path = path
        doc.oracle = None
        doc.table = None
        doc.assigner = None
        doc.evicted = True
        self.release_lane(doc)
        self.counters.incr("evictions")
        if self.tracer is not None:
            # The checkpoint-write wall rides the event (segregated
            # under "w"): residency evictions run in the tick's host
            # phase, so with the pipelined tick this I/O overlaps the
            # previous tick's in-flight device step — analyze.py
            # overlap counts it as hidden host work.
            self.tracer.event("residency.evict", doc=doc.doc_id,
                              ckpt=info["kind"], bytes=info.get("bytes", 0),
                              n=n_items, orders=n_orders,
                              wall={"ms": round(io_ms, 3)})
        return path

    def restore(self, doc: DocState, tick_no: Optional[int] = None) -> None:
        """Rebuild the full in-memory state from the checkpoint. Raises
        ``CheckpointError`` on a corrupt file (refusing beats silently
        serving garbage); queued events then replay via the normal tick
        path, so 'restored + replayed' is bit-identical to
        never-evicted. ``tick_no`` stamps the touch so the same tick's
        LRU pass cannot immediately re-evict the doc it just restored."""
        assert doc.evicted and doc.ckpt_path
        t_io = time.perf_counter()
        try:
            if self.ckpt_format == "delta":
                oracle = self._chains[doc.doc_id].load()
            else:
                oracle = checkpoint.load_doc(doc.ckpt_path)
        except checkpoint.CheckpointError as e:
            # Refusal is the contract (bit-perfect or nothing) — but it
            # must leave a post-mortem behind: WHICH doc, which tick,
            # what the server did to that checkpoint beforehand.
            if self.tracer is not None:
                self.tracer.event("residency.restore", doc=doc.doc_id,
                                  error=str(e))
            if self.recorder is not None:
                self.recorder.on_failure("checkpoint", str(e),
                                         doc_id=doc.doc_id,
                                         shard=doc.shard, tick=tick_no)
            raise
        doc.oracle = oracle
        doc.table = B.AgentTable([cd.name for cd in oracle.client_data])
        doc.assigner = B.OrderAssigner.from_oracle(oracle, doc.table)
        doc.evicted = False
        if tick_no is not None:
            doc.last_touch_tick = tick_no
        self.counters.incr("restores")
        if self.tracer is not None:
            # The restore side of the conservation pair: queued events
            # replay AFTER this through normal ticks, so these counts
            # must equal the eviction snapshot's exactly.  The I/O wall
            # rides the event only for IN-LOOP restores (tick_no set):
            # end-of-run verification restores happen outside any tick,
            # and counting their wall would inflate the overlap
            # report's final-tick host occupancy with work no pipeline
            # could ever hide.
            wall = None
            if tick_no is not None:
                wall = {"ms": round(
                    (time.perf_counter() - t_io) * 1e3, 3)}
            self.tracer.event("residency.restore", doc=doc.doc_id,
                              n=oracle.n,
                              orders=oracle.get_next_order(),
                              wall=wall)

    # -- crash recovery (ISSUE 16) ------------------------------------------

    def rediscover(self) -> Dict[str, dict]:
        """Audit the spool directory after a crash and advance the
        checkpoint-file allocator past everything on disk.

        Returns ``doc_id -> {"path", "local_applied"}`` for every doc
        with a LOADABLE checkpoint (chains validated link by link: a
        corrupt tail link truncates its chain, a corrupt BASE or full
        snapshot refuses the whole doc's checkpoint — each counted,
        traced, recorded).  Nothing is REGISTERED: recovery re-executes
        the journal from genesis, so replayed evictions lay down fresh
        checkpoint files — registering a crash-time chain here would
        hand a replayed (earlier-order) evict a tip from its own
        future.  (``local_applied`` is surfaced for that future
        incremental path; genesis replay does not read it.)  Pre-crash
        files survive untouched for forensics; the
        advanced ``_next_ckpt_id`` keeps fresh files clear of them,
        refused numbers included."""
        found: Dict[str, dict] = {}
        names = sorted(os.listdir(self.spool_dir))
        for name in names:
            if not (name.startswith("doc_") and name.endswith(".npz")):
                continue
            is_base = name.endswith(".base.npz")
            if self.ckpt_format == "delta":
                if not is_base:
                    continue  # delta links walk with their base
                file_no = int(name[len("doc_"):-len(".base.npz")])
                stem = os.path.join(self.spool_dir,
                                    name[:-len(".base.npz")])
                path = stem + ".base.npz"
            else:
                if is_base or ".d" in name:
                    continue  # stale delta files under full format
                file_no = int(name[len("doc_"):-len(".npz")])
                path = os.path.join(self.spool_dir, name)
            self._next_ckpt_id = max(self._next_ckpt_id, file_no + 1)
            try:
                if self.ckpt_format == "delta":
                    _chain, dropped, tip_meta = \
                        checkpoint.CheckpointChain.from_disk(
                            stem, compact_ops=self.ckpt_compact_ops,
                            compact_links=self.ckpt_compact_links)
                    for link_path in dropped:
                        self.counters.incr("recovery_ckpt_links_refused")
                        if self.tracer is not None:
                            self.tracer.event(
                                "residency.restore", doc=None,
                                error=f"refused chain link {link_path}")
                else:
                    tip_meta, _ = checkpoint._load_npz(
                        path, expect_kind="oracle")
            except checkpoint.CheckpointError as e:
                self.counters.incr("recovery_ckpt_refused")
                if self.tracer is not None:
                    self.tracer.event("residency.restore", doc=None,
                                      error=str(e))
                if self.recorder is not None:
                    self.recorder.on_failure("checkpoint", str(e),
                                             doc_id=None)
                continue
            doc_id = tip_meta.get("doc_id")
            if not isinstance(doc_id, str):
                # Pre-durability checkpoint without the doc-id meta:
                # unmappable, refuse it loudly rather than guess.
                self.counters.incr("recovery_ckpt_refused")
                if self.tracer is not None:
                    self.tracer.event(
                        "residency.restore", doc=None,
                        error=f"checkpoint {path} carries no doc_id meta")
                continue
            found[doc_id] = {
                "path": path,
                "local_applied": int(tip_meta.get("local_applied", 0)),
            }
        return found

    # -- verification --------------------------------------------------------

    def verify_lane(self, doc: DocState) -> bool:
        """Device lane state bit-identical to the host oracle: the same
        ±(order+1) body column, row for row."""
        if not doc.in_lane:
            return True
        import numpy as np

        got = self.backends[doc.shard].lane_signed(doc.lane)
        want = oracle_signed(doc.oracle)
        ok = got.shape == want.shape and bool(np.array_equal(got, want))
        if not ok:
            if self.tracer is not None:
                self.tracer.event("divergence", doc=doc.doc_id,
                                  via="lane")
            if self.recorder is not None:
                self.recorder.on_failure(
                    "divergence",
                    f"device lane {doc.shard}/{doc.lane} != host oracle "
                    f"({got.shape} vs {want.shape} rows)",
                    doc_id=doc.doc_id, shard=doc.shard, oracle=doc.oracle)
        return ok

"""Serve lane backend over the BLOCKED streaming-lanes mixed engine
(`ops/rle_lanes_mixed.make_replayer_lanes_mixed_blocked`) — the whole
`serve/` stack on O(NB+K) touched rows per step instead of the flat
engine's O(CAP) (ROADMAP open item #5; the continuous-batching analogue
of paged/incremental KV state in LLM inference serving: fixed-shape
device steps whose per-step cost tracks the *edit*, not the *document*).

Three things make this a backend rather than a replay driver:

1. **Persistent per-tick state.** ``make_replayer_lanes_mixed_blocked``
   was built for chunked replays; here its 11-tuple ``state()`` (block
   planes, logical tables, by-order origin tables, the order->block
   hint + split forward pointers) is carried ACROSS ticks as the lanes'
   device state, with each tick's stacked ``[S, B]`` stream applied as
   one warm-started chunk.  Tick step counts are already padded to the
   batcher's static buckets, so the shape-keyed kernel cache compiles
   one program per bucket and steady state never recompiles
   (``shapes_seen`` stays bounded exactly as the flat backend asserts).
   Author ranks are a read-only kernel input, so the backend accumulates
   the full by-order rank table host-side across ticks (chunk-chaining
   contract of ``make_replayer_lanes_mixed``'s ``rkl``) — which is also
   what agent-onboarding rank remaps rewrite.

2. **Per-lane residency writes.** ``upload_lane`` synthesizes one
   lane's columns from a restored oracle — runs via
   ``lane_blocks.oracle_runs``, half-full K-row blocks via
   ``lane_blocks.pack_lane_blocks``, by-order origin/rank tables and the
   order->block hint directly from the oracle's columns — and writes
   them into the carried state with every other lane untouched
   (``.at[:, b].set``); ``clear_lane`` writes the empty column.

3. **Run-row capacity semantics.** The blocked planes hold RUN rows,
   not chars, and leaf splits need free blocks, so ``fits`` cannot be
   the flat backend's char-count probe.  The backend tracks per-lane
   run-row occupancy host-side (upper-bounded by +2 rows per ACTIVE op
   branch — a compiled local replace step fires both the delete and
   the insert branch) and bounds it by ``row_budget``: every
   split-born or seeded block holds at least ``(K-1)//2`` rows, so
   running out of blocks requires at least ``(NB-1) * (K-1)//2``
   occupied rows — staying strictly below that makes the kernel's
   capacity flag unreachable.  Overflow therefore degrades host-side
   (``tick_fits``/``fits_doc`` refuse, residency frees the lane)
   before the device could ever flag, same contract as the flat
   backend, different unit.

   **Pipeline-safe true-up (ISSUE 14, ROADMAP 7a).**  The bound used
   to be trued up to the device's exact per-lane row counts at every
   barrier — which forced the barrier to materialize the tick's
   output before the next capacity probe could run, clamping this
   backend to a serial pipeline (``max_pipeline_ticks`` 1).  The
   true-up is now a HOST-MIRRORED model on a fixed logical schedule:
   ``apply(t)`` re-bases ``_lane_rows`` from tick t-1's exact device
   counts (whose staged sync has already completed at every depth; the
   batcher's dispatch-edge sync guarantees it) plus tick t's
   conservative growth — so the value every capacity probe reads is a
   pure function of the tick index, byte-identical at pipeline depth 1
   and 2 (``tests/test_serve_pipeline.py``), and at most ONE tick's
   conservative over-estimate above exact.  Lanes touched by residency
   writes since the previous apply keep their (exact) residency-seeded
   counts instead of the stale device value.  ``max_pipeline_ticks``
   is therefore 2: the serve tick overlaps on BOTH backends.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np

from ..ops import batch as B
from ..ops import rle_lanes_mixed as RLM
from ..ops.lane_blocks import oracle_runs, pack_lane_blocks


class LanesMixedLaneBackend:
    """The blocked per-lane MIXED engine as a serve lane backend: one
    persistent blocked state 11-tuple per shard, applied with one
    warm-started kernel call per ``[S, B]`` tick.

    Implements the full surface ``serve.batcher.FlatLaneBackend``
    documents (``apply`` / ``clear_lane`` / ``upload_lane`` /
    ``remap_lane_ranks`` / ``lane_signed`` / ``fits`` / ``fits_doc`` /
    ``tick_fits`` / ``barrier``).  ``capacity`` counts RUN rows per lane
    (rounded up to a ``block_k`` multiple); ``order_capacity`` rows of
    by-order table per lane (rounded up to a multiple of 8)."""

    engine = "rle-lanes-mixed"
    # Pipeline-safe since ISSUE 14: the run-row bound is host-mirrored
    # on a fixed logical schedule (see the module header), so the
    # barrier no longer trues up state the next probe reads and the
    # tick's device pass may stay in flight through the next host tick.
    # Depth 2 is what the dispatch-edge sync guarantees cheap true-up
    # reads for; deeper pipelines would partially serialize there.
    max_pipeline_ticks = 2
    # Tick trains (ISSUE 20) stay off: this backend host-prefills rank
    # state per tick and trues up run-row bounds at the dispatch edge,
    # both incompatible with deferring ticks into a device-side train.
    # The batcher's ``effective_train_ticks`` clamp reads this.
    max_train_ticks = 1
    train_ticks = 1

    def __init__(self, lanes: int, capacity: int, order_capacity: int,
                 lmax: int, block_k: int = 64,
                 interpret: Optional[bool] = None, fuse_w: int = 1,
                 device_prefill: bool = True):
        from ..config import lane_block_geometry

        self.lanes = lanes
        self.lmax = lmax
        self.block_k = max(8, min(block_k, capacity))
        self.capacity, self.NB, self.NBT = lane_block_geometry(
            capacity, self.block_k)
        # Widest fused burst step this backend admits (the batcher's
        # generalized tick fusion asks): clamped by the one-split
        # headroom rule WMAX <= K//2 - 1 (``batch.fused_width_checked``).
        self.max_fuse_w = max(1, min(fuse_w, self.block_k // 2 - 1))
        self.order_capacity = ((order_capacity + 7) // 8) * 8
        # Pallas needs the interpreter off-TPU; on silicon run compiled.
        self.interpret = (jax.default_backend() != "tpu"
                          if interpret is None else interpret)
        self._state = RLM._empty_mixed_blocked_state(
            self.capacity, self.NBT, self.order_capacity, lanes)
        # One cached empty column for clear_lane (an eviction-path
        # hot spot: hundreds of clears per loadgen run).
        self._empty_cols = tuple(
            e[:, 0] for e in RLM._empty_mixed_blocked_state(
                self.capacity, self.NBT, self.order_capacity, 1))
        # Host-accumulated full by-order rank table (the kernel's rkl is
        # a read-only input; see make_replayer_lanes_mixed's rkl doc).
        self._rkl = np.zeros((self.order_capacity, lanes), np.int32)
        # device_prefill is flat-backend surface (this backend's
        # by-order tables are device-resident already; only the rank
        # prefill is host-merged) — accepted and ignored.
        del device_prefill
        # Host-mirrored per-lane run-row bound (see the module header):
        # exact as of the LAST-BUT-ONE applied tick plus the newest
        # tick's conservative growth; residency writes reset a lane to
        # its exact seeded count.  Pairing is lint-enforced (ISSUE 15):
        # the class is registered in analysis/checks_mirror.
        # MIRROR_CONTRACTS (device: _state; mirrors: _lane_rows/_rkl/
        # _resident_fresh) — a new device-write method without a mirror
        # update fails tier-1 as TCR-M001.
        self._lane_rows = np.zeros(lanes, np.int64)
        self._prev_res = None      # last apply's result (true-up source)
        self._prev_checked = False  # its kernel flags already verified
        # Lanes written by clear/upload since the last apply: their
        # _lane_rows value is fresher than _prev_res's device counts.
        self._resident_fresh = np.zeros(lanes, bool)
        self.shapes_seen: set = set()   # compiled (S,) tick shapes

    # -- capacity probes ----------------------------------------------------

    @property
    def row_budget(self) -> int:
        """Max run rows a lane may hold such that the kernel can never
        run out of blocks: out-of-blocks requires every block allocated,
        and all but one block (seeded or split-born) holds at least
        ``(K - WMAX) // 2`` rows — a fused W-row splice fires its leaf
        split at ``r0 + W + 1 > K``, so the kept half of a split block
        can be as small as ``(K - WMAX) // 2`` (WMAX = 1 recovers the
        unfused ``(K-1)//2`` fill).  Staying below
        ``(NB-1) * (K-WMAX)//2`` rows (minus 2 rows of slack; the
        probes bound each stream's FULL growth before it applies) keeps
        the device capacity flag unreachable."""
        min_fill = (self.block_k - self.max_fuse_w) // 2
        return max(0, (self.NB - 1) * min_fill - 2)

    def _orders_fit(self, next_order: int) -> bool:
        return next_order <= self.order_capacity - self.lmax

    def fits(self, n: int, next_order: int) -> bool:
        """Shape-only probe: ``n`` body rows taken as the (worst-case)
        run count.  Callers holding the oracle get the exact answer from
        ``fits_doc``."""
        return n + 2 <= self.row_budget and self._orders_fit(next_order)

    def fits_doc(self, oracle) -> bool:
        """Exact upload-path probe: the oracle's true run count (what
        ``upload_lane`` will seed) against the row budget."""
        runs = len(oracle_runs(oracle)[0])
        return (runs + 2 <= self.row_budget
                and self._orders_fit(oracle.get_next_order()))

    @staticmethod
    def _stream_growth(del_len, ins_len, rows_per_step=None) -> np.ndarray:
        """Sound run-row growth bound of a stream, per trailing lane
        axis: each ACTIVE branch of a step splices at most +2 rows (a
        3-way delete split, or an insert split), and a compiled local
        REPLACE step fires both branches — so the bound is 2 rows per
        active branch, NOT 2 per step (a 2/step bound is reachable by
        ``submit_local(..., del_len=k, ins_content=...)``, and crossing
        it would make the kernel's out-of-blocks flag reachable).  A
        FUSED insert branch (``rows_per_step`` W > 1) splices up to
        W + 1 rows (W new runs + one split tail); W = 1 keeps the old
        +2 (new run + split tail)."""
        d = np.asarray(del_len) > 0
        i = np.asarray(ins_len) > 0
        w = (np.maximum(np.asarray(rows_per_step, dtype=np.int64), 1)
             if rows_per_step is not None else np.int64(1))
        ins_growth = np.maximum(w + 1, 2) * i.astype(np.int64)
        return (2 * d.astype(np.int64) + ins_growth).sum(axis=0)

    def tick_fits(self, b: int, oracle, stream) -> bool:
        """Pre-apply probe for lane ``b``'s compiled tick stream: the
        lane's tracked run rows plus the stream's sound growth bound
        must stay inside the budget."""
        growth = int(self._stream_growth(stream.del_len, stream.ins_len,
                                         stream.rows_per_step))
        return (int(self._lane_rows[b]) + growth <= self.row_budget
                and self._orders_fit(oracle.get_next_order()))

    # -- residency writes ---------------------------------------------------

    def clear_lane(self, b: int) -> None:
        self._state = tuple(
            s.at[:, b].set(e)
            for s, e in zip(self._state, self._empty_cols))
        self._rkl[:, b] = 0
        self._lane_rows[b] = 0
        self._resident_fresh[b] = True

    def upload_lane(self, b: int, oracle, rank_of_agent) -> None:
        """Seed lane ``b`` wholesale from a (restored) oracle: packed
        half-full blocks, by-order origin tables, author ranks, and a
        fully-warm order->block hint — other lanes' carried state is
        untouched."""
        starts, lens = oracle_runs(oracle)
        packed, run_block = pack_lane_blocks(
            starts, lens, K=self.block_k, NB=self.NB, NBT=self.NBT,
            capacity=self.capacity)
        cols = list(packed)
        ocap = self.order_capacity
        n = oracle.n
        order = oracle.order[:n].astype(np.int64)
        assert oracle.get_next_order() <= ocap, (
            f"doc ({oracle.get_next_order()} orders) exceeds order "
            f"capacity {ocap}")

        def table_from(items):
            # u32 view -> i32 turns ROOT (0xFFFFFFFF) into the kernels'
            # -1 root sentinel; absent orders stay TAB_UNKNOWN.
            out = np.full(ocap, RLM.TAB_UNKNOWN, np.int32)
            out[order] = items[:n].astype(np.uint32).view(np.int32)
            return out

        oll = table_from(oracle.origin_left)
        orl = table_from(oracle.origin_right)
        # order -> physical block hint: run r's whole span points at the
        # block pack_lane_blocks placed it in (the packer owns the
        # occupancy rule; this just expands its assignment per order).
        ordblk = np.full(ocap, -1, np.int32)
        if len(starts):
            ordblk[np.repeat(np.abs(starts) - 1, lens)
                   + _within(lens)] = np.repeat(run_block, lens)
        fwd = np.full(self.NBT, -1, np.int32)
        cols.extend([oll, orl, ordblk, fwd])
        self._state = tuple(
            s.at[:, b].set(np.asarray(c))
            for s, c in zip(self._state, cols))

        # Per-item author rank by order (`span_arrays.upload_oracle`'s
        # searchsorted over the client_with_order runs).
        rkl = np.zeros(ocap, np.int32)
        if n:
            run_starts = np.asarray(
                [e.order for e in oracle.client_with_order], np.int64)
            run_agents = np.asarray(
                [e.agent for e in oracle.client_with_order], np.int64)
            run_idx = np.searchsorted(run_starts, order,
                                      side="right") - 1
            rkl[order] = np.asarray(rank_of_agent)[
                run_agents[run_idx]].astype(np.int32)
        self._rkl[:, b] = rkl
        self._lane_rows[b] = len(starts)
        self._resident_fresh[b] = True

    def remap_lane_ranks(self, b: int, mapping: np.ndarray) -> None:
        """Agent-onboarding epoch re-base: rewrite lane ``b``'s column
        of the accumulated rank table through the old->new rank map
        (entries at or past ``len(mapping)`` — never written by the old
        epoch — pass through, as `span_arrays.remap_rank_log`)."""
        m = np.asarray(mapping, dtype=np.int64)
        col = self._rkl[:, b].astype(np.int64)
        safe = np.minimum(col, len(m) - 1)
        self._rkl[:, b] = np.where(col < len(m), m[safe],
                                   col).astype(np.int32)

    # -- the tick -----------------------------------------------------------

    def apply(self, stacked: B.OpTensors) -> None:
        """One [S, B] tick as a warm-started blocked-kernel chunk.  The
        batcher pads S to a static bucket, so ``chunk=S`` makes the
        shape-keyed kernel cache hold exactly one compiled program per
        bucket.

        Run-row true-up rides the FIXED logical schedule the module
        header documents: re-base from the PREVIOUS tick's exact device
        counts (already synced — the batcher blocks this shard's
        in-flight work at the dispatch edge, ``dispatch_reads_device``),
        then add this tick's conservative growth.  The probes between
        two applies therefore read exact(t-1) + growth(t) at EVERY
        pipeline depth — the depth-invariance the byte-identity
        contract needs — and the previous tick's kernel flags are
        verified here, one tick late, still before any state built on
        them is read back."""
        growth = self._stream_growth(stacked.del_len, stacked.ins_len,
                                     stacked.rows_per_step)
        if self._prev_res is not None:
            # Cheap: the dispatch-edge sync already materialized the
            # previous tick's outputs on every pipeline depth.
            exact = np.asarray(self._prev_res.rows)[0].astype(np.int64)
            if not self._prev_checked:
                self._prev_res.check()
            base = np.where(self._resident_fresh, self._lane_rows, exact)
        else:
            base = self._lane_rows
        self._lane_rows = base + growth
        self._resident_fresh[:] = False
        S = int(stacked.num_steps)
        self._merge_rank_prefill(stacked)
        run = RLM.make_replayer_lanes_mixed_blocked(
            stacked, self.capacity, block_k=self.block_k,
            order_capacity=self.order_capacity, chunk=S,
            init=self._state, rkl=self._rkl, interpret=self.interpret)
        res = run()
        self.shapes_seen.add(S)
        self._state = res.state()
        self._prev_res = res
        self._prev_checked = False

    def _merge_rank_prefill(self, stacked: B.OpTensors) -> None:
        """Fold this tick's compile-known author ranks into the
        host-accumulated full table (earlier ticks' ranks must stay
        visible to later YATA tiebreaks — the chunk-chaining rkl
        contract).  One host materialization of the batch, then
        per-lane column slices (not one transfer per lane)."""
        host = jax.tree.map(np.asarray, stacked)
        for b in range(self.lanes):
            per = jax.tree.map(lambda a: a[:, b], host)
            sc = B._prefill_scatter(per)
            if sc is not None:
                self._rkl[sc["rank"][0], b] = sc["rank"][1].astype(
                    np.int32)

    def barrier(self) -> None:
        """Materialize the newest tick's outputs and surface any kernel
        flag loudly (the host-side probes make every flag unreachable,
        so a raise here is a backend bug, not load).  Deliberately NO
        row true-up: the run-row bound follows the fixed logical
        schedule in ``apply`` so capacity decisions cannot depend on
        WHEN a barrier ran (the pipeline-depth byte-identity
        contract)."""
        if self._prev_res is not None and not self._prev_checked:
            self._prev_res.check()
            self._prev_checked = True

    def sync_token(self):
        """Device-completion handle for everything enqueued so far: the
        newest result's per-lane row sums (tiny [1, B]) — blocking on
        it waits for this backend's work through the current tick
        without serializing later dispatches (the staged-sync contract
        of ``max_pipeline_ticks`` > 1).  None before the first apply
        (the batcher then falls back to ``barrier``, a no-op)."""
        return self._prev_res.rows if self._prev_res is not None else None

    # -- readback -----------------------------------------------------------

    def lane_signed(self, b: int) -> np.ndarray:
        """±(order+1) body column of lane ``b`` in document order (walk
        the logical block table; the bit-identity comparison target).
        Readback implies a device sync, so the newest tick's kernel
        flags are verified here too (the end-of-run path — at depth 2
        no barrier ever runs, and the last tick's flags must still be
        checked before its state is trusted)."""
        self.barrier()
        ordp = np.asarray(self._state[0])[:, b]
        lenp = np.asarray(self._state[1])[:, b]
        nlog = int(np.asarray(self._state[2])[0, b])
        blkord = np.asarray(self._state[3])[:, b]
        rws = np.asarray(self._state[4])[:, b]
        K = self.block_k
        o_parts: List[np.ndarray] = []
        l_parts: List[np.ndarray] = []
        for sl in range(nlog):
            blk, r = int(blkord[sl]), int(rws[sl])
            o_parts.append(ordp[blk * K: blk * K + r])
            l_parts.append(lenp[blk * K: blk * K + r])
        o = (np.concatenate(o_parts) if o_parts
             else np.zeros(0, np.int32)).astype(np.int64)
        ln = (np.concatenate(l_parts) if l_parts
              else np.zeros(0, np.int32)).astype(np.int64)
        if len(o) == 0:
            return np.zeros(0, np.int32)
        base = np.repeat(np.abs(o), ln)
        return (np.repeat(np.sign(o), ln)
                * (base + _within(ln))).astype(np.int32)


def _within(lens: np.ndarray) -> np.ndarray:
    """0..len-1 counters concatenated across runs."""
    total = int(lens.sum())
    return np.arange(total) - np.repeat(np.cumsum(lens) - lens, lens)

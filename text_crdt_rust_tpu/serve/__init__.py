"""serve/ — the continuous-batching document server (ISSUE 3).

Turns the library (engines, wire codec, causal buffering, checkpoints)
into a single-process server that multiplexes thousands of live
documents onto B-lane device batches:

- ``admission``  — typed backpressure (bounded queues, token buckets);
- ``router``     — doc_id -> (shard, lane) + frames -> causal queues;
- ``batcher``    — per-tick drain -> bucketed [S, B] device pass;
- ``lanes_backend`` — the blocked O(NB+K) lane backend
  (``rle-lanes-mixed``; the flat backend lives in ``batcher``);
- ``residency``  — LRU lanes, checkpoint evict / restore;
- ``server``     — the ``DocServer`` facade;
- ``loadgen``    — deterministic closed-loop load generator + checker.
"""
from .admission import (  # noqa: F401
    AdmissionControl,
    AdmissionError,
    TokenBucket,
)
from .batcher import ContinuousBatcher, make_lane_backend  # noqa: F401
# NOTE: serve.lanes_backend is deliberately NOT re-exported here — it
# pulls in the pallas blocked kernels at import time, and
# make_lane_backend resolves it lazily through the registry's
# serve_backend entry only when the engine is actually selected.
from .residency import LaneResidency  # noqa: F401
from .router import DocState, ShardRouter  # noqa: F401
from .server import DocServer  # noqa: F401

"""serve/ — the continuous-batching document server (ISSUE 3).

Turns the library (engines, wire codec, causal buffering, checkpoints)
into a single-process server that multiplexes thousands of live
documents onto B-lane device batches:

- ``admission``  — typed backpressure (bounded queues, token buckets);
- ``router``     — doc_id -> (shard, lane) + frames -> causal queues;
- ``batcher``    — per-tick drain -> bucketed [S, B] device pass;
- ``residency``  — LRU lanes, checkpoint evict / restore;
- ``server``     — the ``DocServer`` facade;
- ``loadgen``    — deterministic closed-loop load generator + checker.
"""
from .admission import (  # noqa: F401
    AdmissionControl,
    AdmissionError,
    TokenBucket,
)
from .batcher import ContinuousBatcher, make_lane_backend  # noqa: F401
from .residency import LaneResidency  # noqa: F401
from .router import DocState, ShardRouter  # noqa: F401
from .server import DocServer  # noqa: F401

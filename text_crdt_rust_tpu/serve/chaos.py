"""Deterministic crash-injection harness (ISSUE 16 tentpole, part 3).

The durability claim of `serve/journal` is only worth what its failure
modes are tested against.  This module kills a journaled loadgen run at
seeded, named phases of the tick loop, recovers a fresh ``DocServer``
from the surviving journal + checkpoint spool, resumes the SAME
generation state (worlds, rng, fault channels — real clients survive a
server death), and compares the post-recovery logical streams against
an uncrashed same-seed twin.  The oracle is byte-identity: recovery is
re-execution of the input log, so every doc's content AND state digest
must match the twin exactly — "close" is a bug.

Kill phases (``PHASES``):

- ``post-admit``     — the crash tick's submissions are journaled but
                       its ``server.tick()`` never runs (no TICK
                       marker): recovery must re-derive the tick from
                       the bare op records.
- ``post-dispatch``  — the crash tick completes, including pipelined
                       dispatch; the server dies before the NEXT tick
                       would sync it.  Recovery replays through the
                       marker and the staged syncs re-derive.
- ``mid-ckpt``       — post-admit, plus the newest eviction checkpoint
                       file in the spool is truncated mid-write.
                       ``rediscover`` must refuse it loudly; replay
                       re-derives the doc from genesis anyway.
- ``mid-journal``    — post-dispatch, plus shard 0's final record is
                       torn mid-bytes (a power cut inside ``write``).
                       The torn tail is dropped with a typed refusal;
                       the TICK marker is duplicated to every shard so
                       the tick still replays (and even with one shard
                       the resume loop below re-runs it live).

Loudness proof: ``drop_journal_record`` rewrites a segment WITHOUT one
op record, re-chaining the CRCs so the drop is undetectable to the
scanner — the at-recovery conservation audit
(`obs.flow.audit_crash_spans`) must then report a crash-leak.  That
audit, not the digest (anti-entropy would heal the content), is the
detector the acceptance bar demands.
"""
from __future__ import annotations

import hashlib
import os
import shutil
import tempfile
import time
from typing import Dict, Optional, Tuple

from ..config import ServeConfig
from ..obs.flow import audit_crash_spans
from ..utils.integrity import crc32c
from . import journal as J
from .loadgen import ServeLoadGen
from .server import DocServer

PHASES = ("post-admit", "post-dispatch", "mid-ckpt", "mid-journal")


class CrashSignal(BaseException):
    """The injected kill.  Deliberately a ``BaseException``: a real
    crash (SIGKILL, power cut) is not an ``Exception`` the tick loop's
    typed-error handling may catch and absorb — the batcher's
    ``flush_pipeline`` path must trigger on it and nothing else."""


def logical_stream_digest(server: DocServer) -> str:
    """One hash over every doc's logical stream: content + CRDT state
    digest, in doc-id order.  Two servers with equal digests hold
    byte-identical documents."""
    h = hashlib.sha256()
    for doc_id in sorted(server.router.docs):
        server.ensure_resident(doc_id)
        h.update(doc_id.encode("utf-8"))
        h.update(b"\x00")
        h.update(server.doc_string(doc_id).encode("utf-8"))
        h.update(b"\x00")
        h.update(str(server.doc_digest(doc_id)).encode("utf-8"))
        h.update(b"\x00")
    return h.hexdigest()


# -- fault injectors ---------------------------------------------------------


def truncate_newest_checkpoint(spool_dir: str) -> Optional[str]:
    """Simulate a crash mid-checkpoint-write: cut the newest spool file
    (highest allocation number) in half.  ``rediscover`` must refuse it
    with a typed error, not crash or silently load garbage."""
    cands = [n for n in sorted(os.listdir(spool_dir))
             if n.startswith("doc_") and n.endswith(".npz")]
    if not cands:
        return None
    path = os.path.join(spool_dir, max(cands))
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(max(1, size // 2))
    return path


def tear_last_record(journal_dir: str, shard: int = 0) -> Optional[str]:
    """Simulate a power cut mid-append: truncate the given shard's
    newest segment in the middle of its final record.  The scanner must
    keep the valid prefix and refuse the torn tail by name."""
    records, _ = J.scan(journal_dir)
    mine = [r for r in records if r.shard == shard]
    if not mine:
        return None
    # Segment names embed a zero-padded index, so the lexicographic max
    # is the newest; within it the final record extends to EOF.
    last = max(mine, key=lambda r: (r.segment, r.offset))
    size = os.path.getsize(last.segment)
    cut = last.offset + max(1, (size - last.offset) // 2)
    with open(last.segment, "r+b") as fh:
        fh.truncate(cut)
    return last.segment


def drop_journal_record(journal_dir: str, kind: int = J.REC_TXNS,
                        nth: int = 0) -> Optional[int]:
    """Rewrite a segment WITHOUT its ``nth`` record of ``kind``,
    re-chaining the CRCs so the scanner cannot tell.  This is the
    loudness injection: a journal that silently loses an acked op must
    be caught by the crash-boundary conservation audit, because nothing
    at the storage layer can.  Returns the dropped record's global seq,
    or None if no such record exists."""
    records, _ = J.scan(journal_dir)
    victims = [r for r in records if r.kind == kind]
    if nth >= len(victims):
        return None
    victim = victims[nth]
    keep = sorted((r for r in records
                   if r.segment == victim.segment and r.seq != victim.seq),
                  key=lambda r: r.offset)
    out = bytearray(J._segment_header(victim.shard))
    crc = 0
    for r in keep:
        rec = bytearray()
        J._write_varint(rec, r.seq)
        rec.append(r.kind)
        J._write_varint(rec, len(r.body))
        rec += r.body
        crc = crc32c(bytes(rec), crc)
        rec += crc.to_bytes(4, "little")
        out += rec
    with open(victim.segment, "wb") as fh:
        fh.write(bytes(out))
    return victim.seq


# -- the scenario ------------------------------------------------------------


def run_crash_scenario(phase: str, crash_tick: int, *,
                       ticks: int = 12, docs: int = 16,
                       agents_per_doc: int = 2, events_per_tick: int = 12,
                       seed: int = 7, fault_rate: float = 0.10,
                       num_shards: int = 2, lanes_per_shard: int = 2,
                       ckpt_format: str = "delta", fsync_ticks: int = 1,
                       byzantine: float = 0.0,
                       flash_crowd: Optional[Tuple[int, int]] = None,
                       drop_record_kind: Optional[int] = None,
                       workdir: Optional[str] = None,
                       run_twin: bool = True,
                       twin_digest: Optional[str] = None,
                       train_ticks: int = 1,
                       recover_train_ticks: Optional[int] = None
                       ) -> Dict[str, object]:
    """One kill-and-recover cycle at ``phase`` during loadgen tick
    ``crash_tick`` (0-based), resumed to ``ticks``, checked against an
    uncrashed same-seed twin.  Returns the scenario report; asserts
    nothing itself so tests and the ledger probe can pin their own
    expectations (``identical``, audits, recovery stats)."""
    assert phase in PHASES, f"unknown crash phase {phase!r}"
    assert 0 < crash_tick < ticks - 1, \
        "crash_tick must leave room to resume (0 < crash_tick < ticks-1)"
    own_workdir = workdir is None
    if own_workdir:
        workdir = tempfile.mkdtemp(prefix="tcr-chaos-")
    dirs = {name: os.path.join(workdir, name)
            for name in ("journal", "spool", "twin-journal", "twin-spool")}
    # Tick trains (ISSUE 20): the victim and twin run at ``train_
    # ticks``; recovery replays the journal at ``recover_train_ticks``
    # (default: same) — the journal's per-tick markers make train
    # length a pure wall-clock knob, so a journal written at one length
    # must recover sha-identical at ANY other.
    base_cfg = dict(num_shards=num_shards, lanes_per_shard=lanes_per_shard,
                    ckpt_format=ckpt_format, journal_fsync_ticks=fsync_ticks,
                    flow_sample_mod=1, train_ticks=train_ticks)
    gen_kwargs = dict(docs=docs, agents_per_doc=agents_per_doc, ticks=ticks,
                      events_per_tick=events_per_tick, seed=seed,
                      fault_rate=fault_rate, byzantine=byzantine,
                      flash_crowd=flash_crowd)

    try:
        # -- the victim run, up to the kill point ------------------------
        cfg = ServeConfig(journal_dir=dirs["journal"],
                          spool_dir=dirs["spool"], **base_cfg)
        gen = ServeLoadGen(cfg=cfg, **gen_kwargs)
        gen.start()
        gen.run_ticks(0, crash_tick)

        if phase in ("post-admit", "mid-ckpt"):
            # Die INSIDE the crash tick: its submissions hit the journal
            # but the device tick (and the TICK marker) never happen.
            def _killed_tick():
                raise CrashSignal(phase)
            gen.server.tick = _killed_tick
            try:
                gen.run_tick(crash_tick)
            except CrashSignal:
                pass
            else:
                raise AssertionError("kill point was never reached")
        else:
            # Die AFTER the crash tick completed (dispatch done, marker
            # written) but before anything else syncs the pipeline.
            stats = gen.run_tick(crash_tick)
            gen._applied += stats["ops_applied"]
            gen._steps += stats["steps"]
        gen.server.tracer.event("chaos.crash", phase=phase)
        # The crash: abandon the server object — no flush, no close, no
        # drain.  In-flight pipelined ticks die dispatched-but-unsynced;
        # the journal keeps only what its per-append flush pushed out.
        pre_flow = list(gen.server.flow.records)
        dead_counters = {
            "journal_bytes": gen.server.counters.get("journal_bytes"),
            "journal_ops": gen.server.counters.get("journal_ops"),
        }

        if phase == "mid-ckpt":
            torn = truncate_newest_checkpoint(dirs["spool"])
        elif phase == "mid-journal":
            torn = tear_last_record(dirs["journal"], shard=0)
        else:
            torn = None
        dropped_seq = None
        if drop_record_kind is not None:
            dropped_seq = drop_journal_record(dirs["journal"],
                                              kind=drop_record_kind)

        # -- recovery ----------------------------------------------------
        cfg2_kw = dict(base_cfg)
        if recover_train_ticks is not None:
            cfg2_kw["train_ticks"] = recover_train_ticks
        cfg2 = ServeConfig(journal_dir=dirs["journal"],
                           spool_dir=dirs["spool"], **cfg2_kw)
        server2 = DocServer(cfg2)
        t0 = time.perf_counter()
        rstats = server2.recover()
        gen.server = server2
        while server2.tick_no < crash_tick + 1:
            # Recovery's last step: the crash tick's device work never
            # ran or left no surviving marker (post-admit, mid-ckpt, a
            # one-shard run whose only TICK record was torn) — its ops
            # ARE journaled and queued, so re-derive the tick live.
            stats = server2.tick()
            gen._applied += stats["ops_applied"]
            gen._steps += stats["steps"]
        recover_wall_s = time.perf_counter() - t0
        # At-recovery loudness gate: every span applied before the crash
        # must be covered by a replayed apply NOW — before any client
        # resumes and the anti-entropy cycle gets a chance to quietly
        # heal a journal hole.  A dropped op record shows up here: no
        # re-derived tick can apply an op that never reached a queue.
        at_recovery = audit_crash_spans(pre_flow, server2.flow.records)

        # -- resume the surviving clients against the recovered server ---
        gen.run_ticks(crash_tick + 1, ticks)
        report = gen.finalize()
        final_audit = audit_crash_spans(pre_flow, server2.flow.records,
                                        expect_terminal=True)
        digest = logical_stream_digest(server2)

        # -- the uncrashed same-seed twin --------------------------------
        # The twin is phase-independent (same seed, no crash), so the
        # crash matrix computes it ONCE per fault rate and passes its
        # digest in instead of re-running it for every kill phase.
        twin_converged = None
        if run_twin and twin_digest is None:
            cfg_t = ServeConfig(journal_dir=dirs["twin-journal"],
                                spool_dir=dirs["twin-spool"], **base_cfg)
            twin = ServeLoadGen(cfg=cfg_t, **gen_kwargs)
            twin.start()
            twin.run_ticks(0, ticks)
            twin_report = twin.finalize()
            twin_digest = logical_stream_digest(twin.server)
            twin_converged = bool(twin_report["converged"])

        journal_bytes = dead_counters["journal_bytes"]
        journal_ops = dead_counters["journal_ops"]
        return {
            "phase": phase,
            "crash_tick": crash_tick,
            "ticks": ticks,
            "fault_rate": fault_rate,
            "identical": (digest == twin_digest) if run_twin else None,
            "digest": digest,
            "twin_digest": twin_digest,
            "converged": bool(report["converged"]),
            "twin_converged": twin_converged,
            "recover": dict(rstats),
            "recover_wall_s": round(recover_wall_s, 4),
            "at_recovery_audit": at_recovery,
            "final_audit": final_audit,
            "torn": torn,
            "dropped_seq": dropped_seq,
            "journal_bytes": journal_bytes,
            "journal_ops": journal_ops,
            "journal_bytes_per_op": round(
                journal_bytes / max(1, journal_ops), 2),
            "report": report,
        }
    finally:
        if own_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


def uncrashed_twin_digest(*, ticks, docs, agents_per_doc,
                          events_per_tick, seed, fault_rate,
                          num_shards, lanes_per_shard,
                          ckpt_format: str = "delta",
                          fsync_ticks: int = 1) -> str:
    """The logical-stream digest of a full uncrashed run at the given
    shape — the oracle every crash cell at that shape compares to."""
    workdir = tempfile.mkdtemp(prefix="tcr-twin-")
    try:
        cfg = ServeConfig(journal_dir=os.path.join(workdir, "journal"),
                          spool_dir=os.path.join(workdir, "spool"),
                          num_shards=num_shards,
                          lanes_per_shard=lanes_per_shard,
                          ckpt_format=ckpt_format,
                          journal_fsync_ticks=fsync_ticks,
                          flow_sample_mod=1)
        gen = ServeLoadGen(cfg=cfg, docs=docs,
                           agents_per_doc=agents_per_doc, ticks=ticks,
                           events_per_tick=events_per_tick, seed=seed,
                           fault_rate=fault_rate)
        rep = gen.run()
        assert rep["converged"], rep["mismatches"][:4]
        return logical_stream_digest(gen.server)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def run_crash_matrix(*, phases=PHASES, fault_rates=(0.0, 0.10),
                     crash_tick: int = 4, ticks: int = 10,
                     docs: int = 16, agents_per_doc: int = 2,
                     events_per_tick: int = 12, seed: int = 7,
                     num_shards: int = 2, lanes_per_shard: int = 2,
                     ckpt_format: str = "delta",
                     verbose: bool = False) -> Dict[str, object]:
    """Every kill phase x fault rate; a cell is green when the
    recovered server's logical streams are byte-identical to the twin,
    the run converged, and both crash-boundary audits pass.  The twin
    is computed once per fault rate (it is phase-independent)."""
    shape = dict(ticks=ticks, docs=docs, agents_per_doc=agents_per_doc,
                 events_per_tick=events_per_tick, seed=seed,
                 num_shards=num_shards, lanes_per_shard=lanes_per_shard,
                 ckpt_format=ckpt_format)
    cells: Dict[str, dict] = {}
    ok = True
    for rate in fault_rates:
        twin = uncrashed_twin_digest(fault_rate=rate, **shape)
        for phase in phases:
            cell = run_crash_scenario(
                phase, crash_tick, fault_rate=rate, twin_digest=twin,
                **shape)
            green = (bool(cell["identical"]) and cell["converged"]
                     and cell["at_recovery_audit"]["audit_ok"]
                     and cell["final_audit"]["audit_ok"])
            cells[f"{phase}@{rate}"] = {
                "green": green,
                "identical": cell["identical"],
                "converged": cell["converged"],
                "at_recovery_ok": cell["at_recovery_audit"]["audit_ok"],
                "final_audit_ok": cell["final_audit"]["audit_ok"],
                "replayed_ops": cell["recover"]["ops"],
                "replayed_records": cell["recover"]["records"],
                "replayed_ticks": cell["recover"]["ticks"],
                "refusals": cell["recover"]["refusals"],
                "readmissions": cell["recover"]["readmissions"],
                "recover_wall_s": cell["recover_wall_s"],
                "journal_bytes": cell["journal_bytes"],
                "journal_ops": cell["journal_ops"],
                "journal_bytes_per_op": cell["journal_bytes_per_op"],
                "torn": cell["torn"],
            }
            ok = ok and green
    return {"ok": ok, "cells": cells}

"""The continuous batcher: per-doc causal queues -> one device step/tick.

Each tick the batcher drains causally-ready events across every
lane-resident document of a shard, applies them to the per-doc host
oracles (the source of truth), compiles them into the fixed-shape
columnar op tensors ``ops/batch.py`` defines, stacks them time-major
``[S, B]`` across the shard's B lanes, and applies the whole shard in
ONE vmapped device pass of the registry-selected lane engine — the
continuous-batching shape of LLM inference serving (ragged requests
coalesced into fixed-shape device steps), with YATA's delivery-order
freedom (PAPERS.md, Nicolaescu et al.) guaranteeing that any causally
valid drain order converges bit-identically.

Fixed shapes are what keep steady-state serving compile-free: tick step
counts are padded up to a small static set of **step buckets** (the
`perf/fuzz_mixed_fast.py` shape-bucketing idea), the lane count B and
per-lane capacities are static, and the device call always runs the
``local_only=False`` kernel variant — so after the buckets are warm the
server cycles a fixed set of compiled programs (asserted by
``tests/test_serve_batcher.py`` via ``LaneBackend.shapes_seen``).

Per-event cost is bounded before compilation (``estimate_steps`` walks
the same run boundaries the compiler will) so one oversized edit can
never blow the tick's bucket; admission's ``max_txn_len`` makes the
bound a protocol guarantee. Capacity overflow inside a lane *degrades
the doc to the host oracle* (lane freed, truth preserved) the way
`net/session.py`'s ``DeviceMirror`` does — never an assert on the
serving path.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ..common import LocalOp, RemoteDel, RemoteIns, RemoteTxn
from ..net.session import txn_refs_known
from ..models.sync import agent_watermarks
from ..obs.registry import observe
from ..ops import batch as B
from ..ops import flat as F
from ..ops import span_arrays as SA
from ..utils.metrics import Counters
from ..utils.testdata import TestPatch
from .router import EV_LOCAL, DocState, Event, ShardRouter


class PipelineAliasingError(RuntimeError):
    """A host write raced an in-flight device step (ISSUE 13): an op
    tensor referenced by a dispatched-but-unsynced tick changed between
    dispatch and its staged sync.  On CPU, JAX's zero-copy conversion
    can alias the host numpy buffers the compiled step reads — this is
    the loud version of silent device-state corruption, naming the
    tick, shard and array so the post-mortem starts at the writer."""


def _op_fingerprints(stacked: "B.OpTensors") -> Dict[str, int]:
    """CRC32 per op-tensor column — the dispatch-time fingerprint the
    staged sync re-checks.  Columns are small host arrays ([S, B] u32
    plus the [S, B, LMAX] char block), so this is tens-of-µs cheap at
    serve shapes."""
    import dataclasses
    import zlib

    out: Dict[str, int] = {}
    for f in dataclasses.fields(stacked):
        arr = np.ascontiguousarray(np.asarray(getattr(stacked, f.name)))
        out[f.name] = zlib.crc32(arr.tobytes())
    return out


class FlatLaneBackend:
    """The flat engine (`ops/flat.py`) as a serve lane backend: one
    batched ``FlatDoc`` ``[B, CAP]`` per shard, applied with the vmapped
    step under ``lax.scan`` — the north-star kernel shape, incremental
    per tick.

    Surface the batcher/residency layers consume (any future blocked
    lanes backend implements the same):

    - ``apply(stacked)``    — one device pass for a ``[S, B]`` tick;
    - ``clear_lane(b)`` / ``upload_lane(b, oracle, ranks)`` — residency
      writes (restore re-seeds a lane from the restored oracle);
    - ``remap_lane_ranks(b, mapping)`` — agent-onboarding epoch re-base
      (`ops.batch.rank_remap`) for one lane;
    - ``lane_signed(b)`` / ``fits(...)`` — readback + capacity probe.
    """

    engine = "flat"
    # The flat engine has no W-row splice (``require_unfused``): the
    # batcher's tick fusion still coalesces shapes into plain W=1 rows
    # for it, but never emits multi-row burst steps.
    max_fuse_w = 1
    # Pipeline-safe (ISSUE 12): every host-side probe (``fits`` /
    # ``fits_doc`` / ``tick_fits``) reads the HOST oracle, never device
    # state, and ``barrier`` performs no true-up — so a tick's device
    # pass may stay in flight through the whole next host tick, synced
    # only at the staged sync point via ``sync_token``.  Backends whose
    # barrier trues up probe state (the blocked lanes backend's exact
    # per-lane row counts) leave this at the default 1 and stay serial.
    max_pipeline_ticks = 8

    def __init__(self, lanes: int, capacity: int, order_capacity: int,
                 lmax: int, block_k: Optional[int] = None,
                 interpret: Optional[bool] = None, fuse_w: int = 1,
                 device_prefill: bool = True):
        import jax.numpy as jnp

        # block_k / interpret / fuse_w are lane-backend-constructor
        # surface (the blocked backend consumes them); the flat engine
        # has no blocks and no W-row splice, so they are accepted and
        # ignored.
        self.lanes = lanes
        self.capacity = capacity
        self.order_capacity = order_capacity
        self.lmax = lmax
        base = SA.make_flat_doc(capacity, order_capacity)
        # Materialize the broadcast so lane writes (.at[b].set) behave
        # like independent columns from the start.
        self.docs = jax.tree.map(jnp.array, SA.stack_docs(base, lanes))
        self._empty = base
        self.shapes_seen: set = set()   # compiled (S,) tick shapes
        # Device-resident prefill (ISSUE 14): ship only the per-tick
        # scatter delta, keep the [B, OCAP] logs on device, and check
        # capacity against HOST-MIRRORED per-lane counts — the dispatch
        # edge then reads no device state at all (``dispatch_reads_
        # device``), so the batcher skips its forced pre-dispatch sync
        # and the in-flight step overlaps the whole next host tick.
        # The device/mirror pairing is a LINT contract (ISSUE 15): this
        # class is registered in analysis/checks_mirror.MIRROR_CONTRACTS
        # (device: docs; mirrors: _n_host/_next_order_host), so a new
        # method that writes device state without updating a mirror —
        # or without a justified allowlist grant, like the rank-only
        # remap_lane_ranks — fails tier-1 as TCR-M001.
        self.device_prefill = device_prefill
        self.dispatch_reads_device = not device_prefill
        self.scatter_shapes_seen: set = set()  # compiled scatter buckets
        # Host mirrors of the flat docs' n/next_order (exact: a tick
        # advances them by its column sums, residency writes set them).
        self._n_host = np.zeros(lanes, np.int64)
        self._next_order_host = np.zeros(lanes, np.int64)
        # Prefill cost accounting (the ledger/probe surface): bytes the
        # chosen path moved vs what the full-log round trip would move,
        # and the un-padded scatter volume.  All logical (seed-
        # deterministic) — wall-free by the §15 cpu-cell rule.
        self.prefill_stats = {"ticks": 0, "moved_bytes": 0,
                              "full_bytes_equiv": 0, "scatter_len": 0}
        # Tick trains (ISSUE 20): with train_ticks > 1 ``apply`` buffers
        # ticks host-side (op tensors + prefill deltas, both already
        # fixed-shape) and ``_dispatch_train`` replays T of them as ONE
        # device program (``ops.flat.apply_train``), the concatenated
        # scatter staying a separate dispatch so the compile set stays
        # additive.  Only the device-prefill path can defer: host
        # prefill writes the [B, OCAP] logs per tick from host numpy,
        # which would race the deferred scan.  The host mirrors advance
        # at the TRAIN boundary by the buffered column sums
        # (``_pending_n``/``_pending_o``) — the TCR-M003 train_sync
        # contract: device write and mirror true-up in ONE method.
        self.max_train_ticks = 8 if device_prefill else 1
        self.train_ticks = 1
        self._train_buf: list = []      # [(stacked, delta), ...]
        self._pending_n = np.zeros(lanes, np.int64)
        self._pending_o = np.zeros(lanes, np.int64)
        # Which lanes have real (non-padding) steps buffered: lanes are
        # independent under the vmapped step, so a single-lane
        # residency write (upload / rank remap) only forces a flush
        # when THAT lane has buffered work — without this gate,
        # mid-stream agent onboarding (``_grow_table`` rank remaps)
        # would flush nearly every train at length 1.
        self._pending_active = np.zeros(lanes, bool)
        self._train_flags: list = []    # in-flight device overflow flags
        self.train_shapes_seen: set = set()  # compiled (T, S) train keys
        self.train_stats = {"trains": 0, "ticks_sum": 0,
                            "dispatches": 0, "serial_equiv": 0}

    def set_train_ticks(self, t: int) -> None:
        """Clamp-and-set the effective train length (the batcher calls
        this at construction with ``ServeConfig.train_ticks``; backends
        cap it at their ``max_train_ticks``)."""
        self.train_ticks = max(1, min(int(t), self.max_train_ticks))

    def fits(self, n: int, next_order: int) -> bool:
        """Would a doc of ``n`` rows / ``next_order`` orders fit a lane
        (with the engine's lmax log-write headroom)?"""
        return (n <= self.capacity
                and next_order <= self.order_capacity - self.lmax)

    def fits_doc(self, oracle) -> bool:
        """Residency-path probe (upload/restore): for the flat engine
        the doc's char count IS its device occupancy, so this is
        ``fits`` verbatim.  Backends with a different state unit (run
        rows for the blocked lanes engine) override with an exact
        answer derived from the oracle."""
        return self.fits(oracle.n, oracle.get_next_order())

    def tick_fits(self, b: int, oracle, stream) -> bool:
        """Pre-apply probe for lane ``b``'s compiled tick ``stream``.
        The oracle already applied (it is truth), so for the flat
        engine its post-apply counts are exactly the post-tick device
        occupancy — lane and stream don't matter.  Run-row backends
        bound the stream's splice growth per active op branch."""
        return self.fits(oracle.n, oracle.get_next_order())

    def _flush_train_for_lane(self, b: int) -> None:
        """Flush the open train iff lane ``b`` has buffered steps:
        lanes are independent columns of the vmapped step, so a
        residency write to a lane with NO buffered work commutes with
        the rest of the train (its delta columns are all padding, its
        step rows all no-ops) — serial order is preserved per lane,
        which is the only order the logical stream observes."""
        if self._pending_active[b]:
            self.flush_train()

    def _remap_buffered_lane(self, b: int, m: np.ndarray) -> None:
        """Re-base lane ``b``'s buffered rank values through the
        old->new rank map (same guard as the device rewrite: values
        outside the map — padding, sentinels — pass through).  Copies
        the touched arrays: the originals may be CRC-fingerprinted by
        the pipeline sanitizer, and in-place writes would read as
        aliasing."""
        if not self._pending_active[b]:
            return
        import dataclasses
        mlen = m.shape[0]

        def rebase(col):
            safe = np.minimum(col, mlen - 1)
            return np.where(col < mlen, m[safe], col)

        for i, (stacked, delta) in enumerate(self._train_buf):
            r = np.asarray(stacked.rank).copy()
            r[:, b] = rebase(r[:, b])
            stacked = dataclasses.replace(stacked, rank=r)
            if delta is not None:
                rv = np.asarray(delta.rank_val).copy()
                rv[b] = rebase(rv[b])
                delta = dataclasses.replace(delta, rank_val=rv)
            self._train_buf[i] = (stacked, delta)

    def _cancel_buffered_lane(self, b: int) -> None:
        """Erase lane ``b``'s columns from every buffered tick: zero op
        rows (an exact no-op step) and padding delta positions (dropped
        by the scatter).  Used by ``clear_lane``: eviction checkpoints
        from the ORACLE, so the serial loop's apply-then-wipe of the
        device lane and the train path's never-apply are
        indistinguishable — nothing reads the lane in between."""
        if not self._pending_active[b]:
            return
        import dataclasses
        for i, (stacked, delta) in enumerate(self._train_buf):
            cols = {}
            for f in ("kind", "pos", "del_len", "del_target",
                      "origin_left", "origin_right", "ins_len",
                      "ins_order_start", "order_advance", "rank",
                      "rows_per_step", "chars"):
                a = np.asarray(getattr(stacked, f)).copy()
                a[:, b] = 0
                cols[f] = a
            stacked = dataclasses.replace(stacked, **cols)
            if delta is not None:
                dcols = {}
                for f in ("ins_pos", "ol_pos", "or_pos"):
                    a = np.asarray(getattr(delta, f)).copy()
                    a[b] = B.PREFILL_PAD
                    dcols[f] = a
                for f in ("chars_val", "rank_val", "ol_val", "or_val"):
                    a = np.asarray(getattr(delta, f)).copy()
                    a[b] = 0
                    dcols[f] = a
                delta = dataclasses.replace(delta, **dcols)
            self._train_buf[i] = (stacked, delta)
        self._pending_n[b] = 0
        self._pending_o[b] = 0
        self._pending_active[b] = False

    def clear_lane(self, b: int) -> None:
        self._cancel_buffered_lane(b)
        self.docs = jax.tree.map(
            lambda batched, one: batched.at[b].set(one),
            self.docs, self._empty)
        self._n_host[b] = 0
        self._next_order_host[b] = 0

    def upload_lane(self, b: int, oracle, rank_of_agent) -> None:
        self._flush_train_for_lane(b)
        flat = SA.upload_oracle(oracle, self.capacity, rank_of_agent,
                                self.order_capacity)
        self.docs = jax.tree.map(
            lambda batched, one: batched.at[b].set(one), self.docs, flat)
        self._n_host[b] = oracle.n
        self._next_order_host[b] = oracle.get_next_order()

    def remap_lane_ranks(self, b: int, mapping: np.ndarray) -> None:
        # Buffered work for THIS lane carries rank values baked with
        # the PRE-remap table (the op tensors' author ``rank`` column
        # and the prefill deltas' ``rank_val``); re-base them through
        # the same old->new map instead of flushing.  The map is
        # strictly monotone on old ranks (sorted-name order is stable
        # under growth), so every tiebreak comparison the buffered
        # steps will make is preserved — mid-stream onboarding would
        # otherwise flush nearly every train (``_grow_table`` fires on
        # each doc's late-arriving agents).
        self._remap_buffered_lane(b, np.asarray(mapping, dtype=np.uint32))
        import dataclasses

        import jax.numpy as jnp

        m = jnp.asarray(np.asarray(mapping, dtype=np.uint32))
        lane = self.docs.rank_log[b]
        safe = jnp.minimum(lane, m.shape[0] - 1).astype(jnp.int32)
        new = jnp.where(lane < m.shape[0], m[safe], lane)
        self.docs = dataclasses.replace(
            self.docs, rank_log=self.docs.rank_log.at[b].set(new))

    def _check_capacity_host(self, ops: B.OpTensors) -> None:
        """The ONE flat capacity contract (``flat.check_capacity_
        counts``) against the HOST-MIRRORED lane counts — same bounds,
        same per-lane pairing, zero device reads (the mirrors are
        exact: every accepted tick advances n by its ins_len column
        sum and next_order by its order_advance sum, residency writes
        reset them from the oracle).  With ticks buffered in an open
        train, the not-yet-trued-up pending sums count too — the check
        gates against the post-TRAIN occupancy, so a train can never
        carry a tick the serial loop would have refused."""
        F.check_capacity_counts(self._n_host + self._pending_n,
                                self._next_order_host + self._pending_o,
                                self.capacity, self.order_capacity, ops)

    def apply(self, stacked: B.OpTensors) -> None:
        """One [S, B] tick: prefill the by-order logs — on device from
        the scatter delta (``device_prefill``, the shipped default) or
        host-side via ``batch.prefill_logs`` — then a single jitted
        vmapped scan. Always the full (local+remote) kernel variant so
        the tick mix can't flip compiled programs.

        The two paths are bit-identical in device state and logical
        counters (tests/test_device_prefill.py); they differ only in
        bytes moved (full-log round trip vs scatter-len delta,
        ``prefill_stats``) and in whether the dispatch edge touches
        device state at all."""
        st = self.prefill_stats
        st["ticks"] += 1
        # What the full-log round trip would move for this tick: the
        # four [B, OCAP] u32 logs, host-materialized AND re-uploaded.
        st["full_bytes_equiv"] += 2 * 4 * self.lanes \
            * self.order_capacity * 4
        st["scatter_len"] += int(np.asarray(
            stacked.ins_len, dtype=np.int64).sum())
        self.shapes_seen.add(int(stacked.num_steps))
        if self.train_ticks > 1:
            # Tick-train path (ISSUE 20; device_prefill guaranteed —
            # ``max_train_ticks`` clamps host-prefill backends to 1):
            # gate against the pending-aware host mirrors NOW (serial
            # admission semantics), buffer the tick, and dispatch ONE
            # ``apply_train`` program once train_ticks are queued.
            self._check_capacity_host(stacked)
            delta = B.prefill_delta(stacked)
            self._train_buf.append((stacked, delta))
            self._pending_n += np.asarray(
                stacked.ins_len, dtype=np.int64).sum(axis=0)
            self._pending_o += np.asarray(
                stacked.order_advance, dtype=np.int64).sum(axis=0)
            self._pending_active |= (np.asarray(
                stacked.rows_per_step, dtype=np.int64).sum(axis=0) > 0)
            self.train_stats["serial_equiv"] += 1 + (delta is not None)
            self._drain_train_flags()
            if len(self._train_buf) >= self.train_ticks:
                self._dispatch_train()
            return
        n_disp = 1
        if self.device_prefill:
            self._check_capacity_host(stacked)
            delta = B.prefill_delta(stacked)
            docs = self.docs
            if delta is not None:
                self.scatter_shapes_seen.add(delta.bucket)
                st["moved_bytes"] += delta.nbytes()
                docs = F.apply_prefill_delta(docs, delta)
                n_disp = 2
        else:
            F._check_capacity(self.docs, stacked)
            docs = B.prefill_logs(self.docs, stacked)
            st["moved_bytes"] += 2 * 4 * self.lanes \
                * self.order_capacity * 4
        self.docs = F._apply_ops_batch(docs, stacked, local_only=False)
        self._n_host += np.asarray(
            stacked.ins_len, dtype=np.int64).sum(axis=0)
        self._next_order_host += np.asarray(
            stacked.order_advance, dtype=np.int64).sum(axis=0)
        ts = self.train_stats
        ts["trains"] += 1
        ts["ticks_sum"] += 1
        ts["dispatches"] += n_disp
        ts["serial_equiv"] += n_disp

    @staticmethod
    def _train_bucket(t: int) -> int:
        """Smallest power of two >= ``t`` — the train-length pad series
        ({1, 2, 4, 8}): partial trains (flushes) re-use a bucketed
        program instead of compiling per ragged length."""
        b = 1
        while b < t:
            b *= 2
        return b

    def _dispatch_train(self) -> None:
        """Replay the buffered ticks as ONE device train: (1) the
        concatenated prefill scatter (separate dispatch — keeping it
        out of the scan keeps the compile set additive, |S|x|T| + |L|),
        (2) one ``apply_train`` scan over the [T, S, B] stack.  Per-tick
        scatters land in disjoint fresh order ranges, so hoisting the
        concatenation before the scan is bit-identical to the serial
        interleaving.  Host mirrors true up by the buffered column sums
        HERE, in the same method as the device write — the TCR-M003
        ``train_sync`` atomicity contract."""
        buf, self._train_buf = self._train_buf, []
        if not buf:
            return
        st = self.prefill_stats
        ts = self.train_stats
        t_bkt = self._train_bucket(len(buf))
        s_max = max(s.num_steps for s, _ in buf)
        ticks = [B.pad_ops(s, s_max) for s, _ in buf]
        if len(ticks) < t_bkt:
            noop = jax.tree.map(
                lambda a: np.zeros_like(np.asarray(a)), ticks[0])
            ticks = ticks + [noop] * (t_bkt - len(ticks))
        train = B.stack_ticks(ticks)
        delta = B.concat_deltas([d for _, d in buf])
        docs = self.docs
        n_disp = 1
        if delta is not None:
            self.scatter_shapes_seen.add(delta.bucket)
            st["moved_bytes"] += delta.nbytes()
            docs = F.apply_prefill_delta(docs, delta)
            n_disp = 2
        docs, flag = F.apply_train(docs, train)
        self.docs = docs
        self._train_flags.append(flag)
        self.train_shapes_seen.add((t_bkt, s_max))
        ts["trains"] += 1
        ts["ticks_sum"] += len(buf)
        ts["dispatches"] += n_disp
        self._n_host = self._n_host + self._pending_n
        self._next_order_host = self._next_order_host + self._pending_o
        self._pending_n = np.zeros(self.lanes, np.int64)
        self._pending_o = np.zeros(self.lanes, np.int64)
        self._pending_active = np.zeros(self.lanes, bool)

    def _drain_train_flags(self, block: bool = False) -> None:
        """Check completed trains' device overflow flags.  Non-blocking
        by default (opportunistic, at enqueue); ``block=True`` forces
        every in-flight train to completion (barrier / flush).  A set
        flag means a tick exceeded the static capacities mid-train —
        unreachable through the serve path (the pending-aware host
        check refuses such ticks at enqueue), so it raises instead of
        degrading: the docs are corrupt, not merely full."""
        keep = []
        for flag in self._train_flags:
            if not block and hasattr(flag, "is_ready") \
                    and not flag.is_ready():
                keep.append(flag)
                continue
            if bool(np.asarray(flag)):
                raise RuntimeError(
                    "tick-train overflow flag set: a train exceeded the "
                    "lane capacity/order budget on device; the host-"
                    "mirror capacity check should have refused it at "
                    "enqueue")
        self._train_flags = keep

    def flush_train(self) -> None:
        """Dispatch any partial train and settle its overflow flags —
        the pre-read / pre-residency-write sync point (``lane_doc``,
        ``clear_lane``/``upload_lane``/``remap_lane_ranks``, pipeline
        flush).  NOT called from ``barrier``: at pipeline depth 1 the
        batcher barriers every tick, which would stop trains from ever
        forming."""
        if self._train_buf:
            self._dispatch_train()
        self._drain_train_flags(block=True)

    def train_summary(self) -> Dict[str, float]:
        """Per-train dispatch economy (logical, seed-deterministic):
        how many device dispatches the tick stream cost vs what the
        serial loop would have issued, and the train program compile
        count (report-only — never traced, so the logical stream stays
        train-length-invariant)."""
        ts = self.train_stats
        return {
            "train_ticks": self.train_ticks,
            "device_dispatches": ts["dispatches"],
            "dispatch_serial_equiv": ts["serial_equiv"],
            "dispatch_cut_x": round(
                ts["serial_equiv"] / max(ts["dispatches"], 1), 2),
            "train_len": round(
                ts["ticks_sum"] / max(ts["trains"], 1), 2),
            "train_compiles": len(self.train_shapes_seen),
        }

    def prefill_summary(self) -> Dict[str, float]:
        """Per-tick prefill byte economy (logical, seed-deterministic):
        what moved host<->device for log prefill vs the full-log
        baseline, the un-padded scatter volume, and the scatter
        program's compile count."""
        st = self.prefill_stats
        ticks = max(st["ticks"], 1)
        return {
            "device_prefill": self.device_prefill,
            "prefill_bytes_per_tick": round(st["moved_bytes"] / ticks, 1),
            "prefill_bytes_full_per_tick": round(
                st["full_bytes_equiv"] / ticks, 1),
            "prefill_bytes_cut_x": round(
                st["full_bytes_equiv"] / max(st["moved_bytes"], 1), 2),
            "prefill_scatter_len": st["scatter_len"],
            "prefill_scatter_compiles": len(self.scatter_shapes_seen),
        }

    def barrier(self) -> None:
        # Blocks DISPATCHED work only (and settles its overflow flags).
        # Deliberately does NOT flush an open train: at pipeline depth 1
        # the batcher barriers every tick, and flushing here would pin
        # the train length to 1.  Reads of device state go through
        # ``lane_doc``/``flush_train``, which do flush.
        self._drain_train_flags(block=True)
        np.asarray(self.docs.n)

    def sync_token(self):
        """The device-completion handle for everything enqueued so far:
        blocking on THIS array waits for work through this tick without
        serializing against anything dispatched after the capture (the
        staged-sync contract of ``max_pipeline_ticks`` > 1)."""
        return self.docs.n

    def lane_doc(self, b: int):
        self.flush_train()
        return jax.tree.map(lambda x: x[b], self.docs)

    def lane_signed(self, b: int) -> np.ndarray:
        """±(order+1) body column of lane ``b`` (occupied rows only)."""
        lane = self.lane_doc(b)
        n = int(lane.n)
        return np.asarray(lane.signed)[:n]

    def lane_to_string(self, b: int) -> str:
        return SA.to_string(self.lane_doc(b))


def make_lane_backend(engine: str, *, lanes: int, capacity: int,
                      order_capacity: int, lmax: int,
                      block_k: int = 32,
                      interpret: Optional[bool] = None,
                      fuse_w: int = 1,
                      device_prefill: bool = True):
    """Registry-driven lane-backend construction: ``engine`` must be
    registered for the ``serve`` config in ``config.ENGINE_REGISTRY``
    AND carry a ``serve_backend`` entry naming its backend class —
    unknown or serve-less engines raise a precise ``ValueError`` at
    construction time (config-time strictness — runtime failures
    degrade, construction failures explain)."""
    import importlib

    from ..config import ENGINE_REGISTRY, engines_for

    serve_engines = engines_for("serve")
    if engine not in ENGINE_REGISTRY:
        raise ValueError(
            f"unknown engine {engine!r} (registry: "
            f"{tuple(ENGINE_REGISTRY)})")
    target = ENGINE_REGISTRY[engine].get("serve_backend")
    if engine not in serve_engines or not target:
        raise ValueError(
            f"engine {engine!r} has no serve lane backend; registered "
            f"serve engines: {serve_engines}")
    mod_path, cls_name = target.split(":")
    cls = getattr(importlib.import_module(
        f"text_crdt_rust_tpu.{mod_path}"), cls_name)
    return cls(lanes=lanes, capacity=capacity,
               order_capacity=order_capacity, lmax=lmax,
               block_k=block_k, interpret=interpret, fuse_w=fuse_w,
               device_prefill=device_prefill)


def oracle_signed(oracle) -> np.ndarray:
    """The oracle body as the device's ±(order+1) encoding — the
    bit-identity comparison target for a lane."""
    n = oracle.n
    order = oracle.order[:n].astype(np.int64)
    sign = np.where(oracle.deleted[:n], -1, 1)
    return (sign * (order + 1)).astype(np.int32)


def estimate_steps(doc: DocState, event: Event, lmax: int) -> int:
    """Compiled step count of ``event`` against the doc's CURRENT
    assigner state (events estimate in FIFO order, so every earlier
    event's orders are already assigned). Mirrors the compiler's
    chunking: insert runs split at ``lmax``; remote delete targets split
    at the target agent's order-run boundaries (``dmax=None``).

    A delete targeting this txn's OWN fresh inserts (seqs at or past the
    agent's watermark) costs one step: the compiler allocates the whole
    txn as one contiguous order span before walking its ops. An unknown
    target agent costs one step too — that txn fails the apply-time
    reference validation and is dropped without compiling."""
    if event.kind == EV_LOCAL:
        _agent, _pos, _del_len, ins = event.payload
        return max(1, -(-len(ins) // lmax))
    steps = 0
    txn: RemoteTxn = event.payload
    for op in txn.ops:
        if isinstance(op, RemoteIns):
            steps += -(-len(op.ins_content) // lmax)
        else:
            assert isinstance(op, RemoteDel)
            if op.id.agent not in doc.table:
                steps += 1  # rejected at apply (refs unknown)
                continue
            aid = doc.table.id_of(op.id.agent)
            known = doc.assigner.next_seq(aid)
            end = op.id.seq + op.len
            if op.id.seq >= known:
                steps += 1  # entirely in-txn fresh range: one span
                continue
            steps += len(doc.assigner.target_runs(
                aid, op.id.seq, min(end, known) - op.id.seq))
            if end > known:
                steps += 1  # tail lands in the txn's own fresh span
    return max(steps, 1)


class ContinuousBatcher:
    """Drains per-doc event queues into one bucketed device pass per
    shard per tick. Owns nothing long-lived but the backends' jit
    caches; doc state lives in the router, lane ownership in residency.
    """

    def __init__(self, router: ShardRouter, residency, *,
                 step_buckets: Tuple[int, ...], lmax: int,
                 counters: Optional[Counters] = None,
                 fuse_steps: bool = False, fuse_w: int = 1,
                 tracer=None, recorder=None, flow=None,
                 pipeline_ticks: int = 1,
                 sanitize_pipeline: bool = False,
                 train_ticks: int = 1):
        assert tuple(sorted(step_buckets)) == tuple(step_buckets)
        self.router = router
        self.residency = residency
        self.step_buckets = tuple(step_buckets)
        self.lmax = lmax
        self.counters = counters if counters is not None else Counters()
        self.tracer = tracer
        self.recorder = recorder
        self.flow = flow  # obs/flow.FlowTracker (None = provenance off)
        # Generalized tick-stream fusion (``ops.batch.fuse_steps``,
        # ISSUE 6): each lane doc's drained tick stream is fused before
        # the capacity probe and stacking — typing runs / sweeps /
        # replaces / remote runs coalesce into plain rows every backend
        # accepts; backwards bursts additionally fuse into W-row steps
        # up to the backend's ``max_fuse_w`` (1 on backends without the
        # W-row splice).  Fewer steps per doc-tick -> more docs fit a
        # fixed [S, B] bucket, the batching win ``fuse_stats`` tracks.
        self.fuse_steps = fuse_steps
        self.fuse_w = max(1, fuse_w)
        self.fuse_stats = B.FuseStats()
        self.latency_samples: List[float] = []
        self.tick_wall_samples: List[float] = []  # per-tick wall seconds
        # Pipelined tick (ISSUE 12): with depth D, up to D-1 ticks'
        # device passes stay in flight while the host stages the next
        # tick (jax async dispatch returns before completion — the
        # per-tick barrier was OURS, not XLA's).  Each tick appends one
        # entry carrying the per-shard sync tokens and the tick's
        # applied events; the staged sync pops entries past the depth
        # at the barrier slot — the SAME logical stream position in
        # every mode, so pipelining moves only wall time (the
        # cross-mode byte-identity contract of
        # tests/test_serve_pipeline.py).  The effective depth is capped
        # by the backends' ``max_pipeline_ticks`` (1 = a barrier-time
        # true-up makes deferral unsafe — the blocked lanes backend).
        self.pipeline_ticks = max(1, pipeline_ticks)
        # Pipeline aliasing sanitizer (ISSUE 13): when on, every
        # dispatched tick's stacked op tensors are CRC-fingerprinted at
        # the dispatch edge and re-checked at that entry's staged sync;
        # any host write that raced the in-flight device step raises
        # PipelineAliasingError naming tick/shard/array.  Detection
        # only — it emits no trace events, so sanitized runs stay
        # byte-identical on the logical stream.
        self.sanitize_pipeline = sanitize_pipeline
        # Tick trains (ISSUE 20): with T > 1, backends that opt in
        # (``max_train_ticks`` > 1 — the flat backend's device-prefill
        # path) buffer T ticks' op tensors + prefill deltas and replay
        # them as ONE device ``lax.scan`` program, collapsing T
        # dispatch overheads into one.  Like pipeline depth, a pure
        # wall-clock knob: trace events, counters and the journal all
        # land at their per-tick logical positions, so logical streams
        # are byte-identical at any train length.
        self.train_ticks = max(1, train_ticks)
        for b in residency.backends:
            if hasattr(b, "set_train_ticks"):
                b.set_train_ticks(self.train_ticks)
        self._inflight: List[dict] = []
        # Per-shard stall/win not yet attributed to a trace event: a
        # deferred entry's sync may pay stall for a shard that has no
        # device work — hence no tick.barrier event — THIS tick; the
        # wall carries to that shard's next emitted barrier event so
        # the trace totals match the in-memory accounting (end-of-run
        # flush leftovers stay in-memory only).
        self._pending_stall: Dict[int, float] = {}
        self._pending_win: Dict[int, float] = {}
        # End of the last staged sync: a later entry's overlap window
        # opens at max(its dispatch, this) — time spent BLOCKING on an
        # older entry is not window the host earned for the next one
        # (same-shard device work is queued behind the older tick's
        # anyway), and without the clamp that stall would double-count
        # as both stall and win, flooring overlap_frac near 0.5 on a
        # fully device-bound run.
        self._last_sync_end = 0.0
        # Overlap accounting: window = host wall an in-flight device
        # step had to hide under (dispatch -> staged sync start), stall
        # = what blocking still cost at the sync.  overlap_frac =
        # window / (window + stall) — 0 in the serial loop, -> 1 as
        # the pipeline fully hides device time.
        self.overlap_window_s = 0.0
        self.sync_stall_s = 0.0
        # Optional per-doc compiled-stream tap: called as
        # (doc_id, OpTensors) for every lane doc's tick stream BEFORE
        # padding/stacking — how perf/blocked_lanes_sim.py replays the
        # loadgen tick trace through its step-cost models.
        self.step_trace = None

    def bucket(self, steps: int) -> int:
        for b in self.step_buckets:
            if steps <= b:
                return b
        raise AssertionError(
            f"tick stream of {steps} steps exceeds the largest bucket "
            f"{self.step_buckets[-1]} (drain budget bug)")

    # -- pipelined staged sync ----------------------------------------------

    def effective_pipeline_ticks(self) -> int:
        """Configured depth capped by every backend's opt-in: one
        backend that trues up probe state at its barrier serializes the
        whole server (backends are homogeneous per server, so in
        practice this is all-or-nothing)."""
        return min([self.pipeline_ticks]
                   + [getattr(b, "max_pipeline_ticks", 1)
                      for b in self.residency.backends])

    def effective_train_ticks(self) -> int:
        """Configured train length capped by every backend's opt-in
        (``max_train_ticks``; 1 on backends without a deferrable
        dispatch path — host-prefill flat, the blocked lanes backend)."""
        return min([self.train_ticks]
                   + [getattr(b, "train_ticks", 1)
                      for b in self.residency.backends])

    def _sync_entry(self, entry: dict) -> None:
        """Block until one in-flight tick's device work is done: the
        per-shard sync tokens when the entry was deferred (pipelined —
        a token blocks through ITS tick's work without serializing
        against later dispatches), ``backend.barrier()`` otherwise.
        Stamps the entry's applied events' admission->applied latency
        (device completion included, exactly as the serial loop's
        post-barrier stamp did); per-token window/stall accounting
        lives in ``_block_token``."""
        for tok in entry["tokens"]:
            self._block_token(entry, tok)
        now = time.perf_counter()
        for event in entry["events"]:
            self.latency_samples.append(now - event.t_submit)

    def _block_token(self, entry: dict, tok: dict) -> None:
        """Block one shard's device work for one in-flight entry and
        account it.  Window = host wall since the entry's dispatch (or
        since the last block — time already spent BLOCKING is not
        overlap the host earned: same-shard device work queues behind
        what we were waiting for, and without the clamp a device-bound
        pipelined run would floor near frac 0.5).  Only DEFERRED
        entries (real sync tokens) accrue window: the serial loop's
        immediate sync accrues stall only, so its µs-scale bookkeeping
        gaps can't manufacture overlap and the documented contract —
        frac == 0.0 at depth 1 — holds."""
        if tok["done"]:
            return
        shard = tok["shard"]
        t0 = time.perf_counter()
        win = 0.0
        if tok["token"] is not None:
            win = max(0.0, t0 - max(entry["t_dispatched"],
                                    self._last_sync_end))
            np.asarray(tok["token"])
        else:
            self.residency.backends[shard].barrier()
        stall = time.perf_counter() - t0
        tok["done"] = True
        self._check_guards(entry, shard)
        self._last_sync_end = time.perf_counter()
        self.overlap_window_s += win
        self.sync_stall_s += stall
        self._pending_stall[shard] = (
            self._pending_stall.get(shard, 0.0) + stall)
        self._pending_win[shard] = (
            self._pending_win.get(shard, 0.0) + win)

    def _check_guards(self, entry: dict, shard: int) -> None:
        """Sanitizer re-check at the staged sync: the op tensors this
        shard's in-flight tick dispatched must CRC-match their
        dispatch-edge fingerprints — a mismatch means host code wrote
        into arrays the device step may have been reading (the ISSUE-13
        hazard class the double-buffered tick opened)."""
        for guard in entry.get("guards", ()):
            if guard["shard"] != shard:
                continue
            self.counters.incr("sanitize_checks")
            fresh = _op_fingerprints(guard["arrays"])
            for name, crc in guard["crcs"].items():
                if fresh[name] != crc:
                    raise PipelineAliasingError(
                        f"pipeline aliasing: tick {entry['tick']} shard "
                        f"{shard} array {name!r} changed between "
                        f"dispatch and its staged sync (crc "
                        f"{crc:#010x} -> {fresh[name]:#010x}) — host "
                        f"code wrote into an op tensor an in-flight "
                        f"device step reads")

    def _sync_shard_inflight(self, shard: int) -> None:
        """Complete SHARD's older in-flight device work right before a
        new dispatch to it.  The flat backend's dispatch path reads
        device state host-side (``_check_capacity``/``prefill_logs``),
        which would otherwise block on the previous tick's work INSIDE
        the dispatch-wall measurement — hiding any device time the
        host window failed to cover from the stall accounting (a
        metric blind spot, not a correctness issue: the read blocks
        either way).  Syncing here keeps the dispatch wall
        enqueue-only and charges un-hidden device time to the pipeline
        stall it actually is — on any platform, TPU included."""
        for entry in self._inflight:
            for tok in entry["tokens"]:
                if tok["shard"] == shard:
                    self._block_token(entry, tok)

    def flush_pipeline(self) -> None:
        """Drain every in-flight tick (end of run / before reading
        latency percentiles).  Emits no trace events, so a flushed
        pipelined stream stays byte-identical to the serial one;
        idempotent and a no-op in the serial loop (depth 1 never leaves
        an entry behind).  Open tick trains dispatch first: their device
        work must be enqueued before the entries' sync tokens can cover
        it."""
        for b in self.residency.backends:
            if hasattr(b, "flush_train"):
                b.flush_train()
        while self._inflight:
            self._sync_entry(self._inflight.pop(0))

    def pipeline_overlap_frac(self) -> float:
        """Fraction of the measured device-sync demand the pipeline hid
        under host work: window / (window + stall).  0.0 in the serial
        loop (no window), -> 1.0 when the staged sync never blocks."""
        denom = self.overlap_window_s + self.sync_stall_s
        return self.overlap_window_s / denom if denom > 0 else 0.0

    # -- per-event processing ----------------------------------------------

    def _grow_table(self, doc: DocState, names) -> None:
        """Register new agent names; if the doc holds a lane, re-base its
        persisted rank log through the old->new rank map (the epoch
        boundary of ``ops.batch.rank_remap`` — mid-stream onboarding)."""
        new = [n for n in names if n != "ROOT" and n not in doc.table]
        if not new:
            return
        old_names = list(doc.table.names)
        for n in new:
            doc.table.add(n)
        if doc.in_lane and old_names:
            mapping = B.rank_remap(old_names, doc.table)
            backend = self.residency.backends[doc.shard]
            backend.remap_lane_ranks(doc.lane, mapping)
            self.counters.incr("lane_rank_remaps")

    def _apply_local(self, doc: DocState, event: Event,
                     compile_device: bool):
        """Oracle-apply (+ compile when the doc serves from a lane) one
        local edit. Returns (applied, ops-or-None); an invalid position
        is counted and dropped — (False, None)."""
        agent, pos, del_len, ins = event.payload
        oracle = doc.oracle
        live = len(oracle)
        if pos > live or pos + del_len > live:
            self.counters.incr("events_invalid")
            if self.flow is not None and event.lk is not None:
                # Terminal typed refusal for the span: the edit raced a
                # position the server never reached (deterministically
                # dropped — the loadgen's twin-sourced positions).
                self.flow.rejected(doc.doc_id, agent, "invalid-position",
                                   lk=event.lk)
            return False, None
        self._grow_table(doc, [agent])
        aid = oracle.get_or_create_agent_id(agent)
        seq0 = oracle.client_data[aid].get_next_seq()
        o0 = oracle.get_next_order()
        oracle.apply_local_txn(aid, [LocalOp(pos=pos, ins_content=ins,
                                             del_span=del_len)])
        doc.assigner.assign(doc.table.id_of(agent), seq0, event.items)
        # Realize the span for the tick's terminal flow.apply stamp
        # (mode — device vs host — is only known after the lane-
        # capacity probe, so the batcher stamps it there).
        event.span = (agent, seq0, event.items)
        if self.tracer is not None:
            # The event-level audit log the divergence post-mortem
            # joins against: WHICH (agent, seq) span landed on WHICH
            # logical tick.
            self.tracer.event("apply", doc=doc.doc_id, ev="local",
                              agent=agent, seq=seq0, n=event.items)
        if not compile_device:
            return True, None
        ops, next_o = B.compile_local_patches(
            [TestPatch(pos, del_len, ins)], rank=doc.table.rank_of(agent),
            lmax=self.lmax, start_order=o0, dmax=None)
        assert next_o == oracle.get_next_order()
        return True, ops

    def _apply_txn(self, doc: DocState, event: Event,
                   compile_device: bool):
        """Oracle-apply (+ compile) one released remote txn. A txn whose
        references don't resolve (buggy/malicious peer beyond what the
        causal buffer can see) is rejected typed-and-counted and the
        buffer watermark rolled back so an honest redelivery lands."""
        txn: RemoteTxn = event.payload
        if not txn_refs_known(doc.oracle, txn):
            self.counters.incr("txns_rejected")
            doc.buffer.rollback_watermark(txn.id.agent, txn.id.seq)
            if self.flow is not None:
                # Non-terminal when honest redelivery lands later (the
                # rollback re-opens the watermark for it); terminal for
                # a genuinely bogus peer txn.
                self.flow.rejected(doc.doc_id, txn.id.agent,
                                   "refs-unknown", seq=txn.id.seq,
                                   n=event.items)
            return False, None
        self._grow_table(doc, ShardRouter.txn_agent_names(txn))
        doc.oracle.apply_remote_txn(txn)
        event.span = (txn.id.agent, txn.id.seq, event.items)
        if self.tracer is not None:
            self.tracer.event("apply", doc=doc.doc_id, ev="txn",
                              agent=txn.id.agent, seq=txn.id.seq,
                              n=event.items)
        if not compile_device:
            # Host-only doc: advance the compiler's order metadata the
            # exact way compile_remote_txns would (whole-txn span) but
            # skip the tensor emission nothing will consume — with most
            # docs host-only under lane pressure this is the bulk of a
            # tick's host work.
            agent = doc.table.id_of(txn.id.agent)
            assert doc.assigner.next_seq(agent) == txn.id.seq
            doc.assigner.assign(agent, txn.id.seq, event.items)
            return True, None
        ops, doc.assigner = B.compile_remote_txns(
            [txn], doc.table, assigner=doc.assigner, lmax=self.lmax,
            dmax=None)
        return True, ops

    @staticmethod
    def _new_agent_names(doc: DocState, event: Event) -> List[str]:
        """Agent names this event would onboard into the doc's table."""
        if event.kind == EV_LOCAL:
            agent = event.payload[0]
            return [] if (agent == "ROOT" or agent in doc.table) \
                else [agent]
        return [n for n in ShardRouter.txn_agent_names(event.payload)
                if n not in doc.table]

    def _drain_doc(self, doc: DocState, budget: int, compile_device: bool
                   ) -> Tuple[Optional[B.OpTensors], List[Event], int,
                              List[Optional[Tuple[int, int]]]]:
        """Drain up to ``budget`` compiled steps of FIFO events from one
        doc: oracle-apply each, compile each (lane docs only), concat.
        Returns (tick stream or None, APPLIED events, steps, per-event
        compiled step ranges) — rejected or invalid events are dequeued
        but excluded from ``applied`` so they feed neither the
        ops-applied stats nor latency samples.  ``ranges[i]`` is applied
        event i's [s0, s1) row span in the concatenated tick stream
        (None for host-only drains), the pre-fusion coordinates the
        fuser's ``step_map`` translates to fused super-steps."""
        streams: List[B.OpTensors] = []
        applied: List[Event] = []
        ranges: List[Optional[Tuple[int, int]]] = []
        steps = 0
        while doc.events:
            event = doc.events[0]
            est = estimate_steps(doc, event, self.lmax)
            if steps + est > budget:
                break
            if applied and self._new_agent_names(doc, event):
                # Agent onboarding is an EPOCH BOUNDARY: the rank remap
                # rewrites the lane's persisted by-order ranks, but the
                # steps already compiled this tick baked the OLD ranks
                # in — applying both in one stream would prefill stale
                # ranks over the re-based log and diverge later
                # same-origin tiebreaks.  Defer the onboarding event to
                # the next tick so every compiled tick stream is
                # single-epoch (FIFO preserved; one tick of latency).
                # Gated on APPLIED (not compiled streams): host-only
                # docs must defer on the same schedule, or the apply
                # timing — and with it the interleaving of tick-end
                # causal releases vs later local edits — would depend
                # on the doc's lane status, which differs across lane
                # backends (degradation thresholds differ) and would
                # break the cross-backend bit-identity contract.  For
                # lane docs the two conditions coincide (every applied
                # event compiles >= 1 step).
                self.counters.incr("epoch_boundary_deferrals")
                break
            doc.events.popleft()
            self.router.admission.dequeued()
            ok, ops = (self._apply_local(doc, event, compile_device)
                       if event.kind == EV_LOCAL
                       else self._apply_txn(doc, event, compile_device))
            if event.kind == EV_LOCAL and event.ordinal is not None:
                # The local-edit durability watermark advances on
                # PROCESSING, not success: a validity-dropped local
                # consumed its ordinal too (ISSUE 16).  Checkpointed as
                # an audit stamp reserved for future incremental
                # recovery — today's replay is from genesis and checks
                # ordinals against ``local_seen`` (``local_gaps``); it
                # does not skip on this watermark.
                doc.local_applied = max(doc.local_applied,
                                        event.ordinal + 1)
            if not ok:
                continue
            applied.append(event)
            if compile_device and ops is not None and ops.num_steps > 0:
                ranges.append((steps, steps + ops.num_steps))
                streams.append(ops)
                steps += ops.num_steps
            else:
                ranges.append(None)
                if not compile_device:
                    steps += est  # budget proxy: bounds host drain too
        if not streams:
            return None, applied, steps if compile_device else 0, ranges
        return B.concat_ops(streams), applied, steps, ranges

    def _flow_applies(self, doc: DocState, applied: List[Event],
                      ranges, fs, device: bool) -> None:
        """Stamp the terminal ``flow.apply`` for every span a doc's
        tick drain applied: realized ``(agent, seq, n)`` from the
        event, device-vs-host mode from the probe outcome, and — when
        the tick stream fused — the fused super-step that absorbed the
        span's compiled rows (``FuseStats.step_map`` translated through
        the event's pre-fusion row range)."""
        mode = "device" if device else "host"
        fmap = fs.step_map if fs is not None else None
        for event, rng in zip(applied, ranges):
            if event.span is None:
                continue
            agent, seq, n = event.span
            fstep = fn = None
            if fmap is not None and rng is not None:
                fstep = fmap[rng[0]]
                fn = fmap[rng[1] - 1] - fstep + 1
            self.flow.applied(doc.doc_id, agent, seq, n, mode,
                              lk=event.lk, fstep=fstep, fn_steps=fn)

    # -- the tick -----------------------------------------------------------

    def tick(self, tick_no: int) -> Dict[str, float]:
        """One serving tick across all shards; returns tick stats.

        A typed error escaping mid-tick (aliasing sanitizer, capacity
        assert, an injected fault) must not strand dispatched-but-
        unsynced pipeline entries: their staged syncs would never run,
        leaking device work, latency stamps and flow spans — and a
        later ``flush_pipeline`` after partial host mutations could
        sync against torn state.  So the in-flight queue is drained
        before the error propagates (ISSUE 16 bugfix; the regression
        test injects a fault at depth 2 and asserts the flow audit
        stays green)."""
        try:
            return self._tick_inner(tick_no)
        except BaseException as tick_exc:
            try:
                self.flush_pipeline()
            except Exception as flush_exc:
                # The original error is the story; the flush failure is
                # recorded, not raised over it.
                if self.recorder is not None:
                    self.recorder.on_failure(
                        "pipeline-flush",
                        f"flush_pipeline failed while unwinding "
                        f"tick {tick_no}: {flush_exc} "
                        f"(original: {tick_exc})")
            raise

    def _tick_inner(self, tick_no: int) -> Dict[str, float]:
        t0 = time.perf_counter()
        tr = self.tracer
        if tr is not None:
            tr.set_tick(tick_no)
        stats = {"ops_applied": 0, "events_applied": 0, "steps": 0,
                 "lanes_active": 0}

        # 1. Residency: restore evicted docs with traffic, find lanes
        #    for host-only docs (both may LRU-evict colder docs; both
        #    stamp the doc's touch tick so a doc granted residency this
        #    tick is never stolen later in the same pass).
        for doc in self.router.docs.values():
            if doc.events and not doc.resident:
                self.residency.restore(doc, tick_no)
            if (doc.events and doc.resident and not doc.in_lane
                    and not doc.degraded):
                self.residency.try_assign_lane(doc, tick_no)

        # 2. Drain + compile per shard, apply in one device pass each.
        #    Host-only docs drain without tensor emission (nothing would
        #    consume the streams — the oracle apply is the whole serve).
        applied_events: List[Event] = []
        tick_guards: List[dict] = []
        active_shards: set = set()
        for shard, backend in enumerate(self.residency.backends):
            t_drain = time.perf_counter()
            lane_streams: Dict[int, B.OpTensors] = {}
            host_only_applies = 0
            shard_events = 0
            shard_steps = 0
            probed = degraded = 0
            budget = self.step_buckets[-1]
            for doc in self.router.docs.values():
                if doc.shard != shard or not doc.events:
                    continue
                if not doc.resident:
                    continue  # restore deferred (no lane, no memory)
                stream, applied, steps, ev_ranges = self._drain_doc(
                    doc, budget, compile_device=doc.in_lane)
                applied_events.extend(applied)
                stats["events_applied"] += len(applied)
                stats["ops_applied"] += sum(e.items for e in applied)
                shard_events += len(applied)
                shard_steps += steps
                fs = None
                if (self.fuse_steps and doc.in_lane
                        and stream is not None):
                    if stream.num_steps > 1:
                        # Fuse the doc's tick stream BEFORE the
                        # capacity probe and stacking: per-event
                        # compilation never sees adjacent events, so
                        # this is where typing runs / sweeps / replaces
                        # / same-tick remote runs collapse
                        # (bit-identical stream, fewer rows).
                        stream, fs = B.fuse_steps(
                            stream,
                            fuse_w=min(self.fuse_w,
                                       getattr(backend, "max_fuse_w",
                                               1)))
                    else:
                        # Single-step streams can't fuse but ARE device
                        # steps: count them so steps_total/ops_per_step
                        # measure the whole run, not the fused subset.
                        fs = B.FuseStats(steps_in=stream.num_steps,
                                         steps_out=stream.num_steps)
                scheduled = False
                if doc.in_lane and stream is not None:
                    # Lane-capacity probe AFTER the oracle applied (the
                    # oracle is truth): overflow degrades to host-only,
                    # frees the lane, skips the device — never asserts.
                    # Backends define their own unit (chars for flat,
                    # run rows + split headroom for the blocked lanes).
                    probed += 1
                    if backend.tick_fits(doc.lane, doc.oracle, stream):
                        scheduled = True
                        if self.step_trace is not None:
                            self.step_trace(doc.doc_id, stream)
                        lane_streams[doc.lane] = stream
                        stats["steps"] += stream.num_steps
                        if fs is not None:
                            # Count fusion only for streams that WILL
                            # run as device steps: a probe failure
                            # degrades to host-only, and its rows must
                            # not inflate the exported device-step
                            # counters.
                            self.fuse_stats.merge(fs)
                            if tr is not None and fs.rows_saved > 0:
                                tr.event("tick.fuse", doc=doc.doc_id,
                                         steps_in=fs.steps_in,
                                         steps_out=fs.steps_out)
                            observe(self.counters, "ops_per_step",
                                    fs.reduction_x)
                            observe(self.counters, "fused_rows_saved",
                                    fs.rows_saved)
                        if self.recorder is not None:
                            self.recorder.record_stream(doc.doc_id, {
                                "tick": tick_no,
                                "num_steps": int(stream.num_steps),
                                "steps_prefuse": (fs.steps_in if fs
                                                  else int(stream.num_steps)),
                            })
                    else:
                        degraded += 1
                        self.residency.degrade(
                            doc, f"lane capacity overflow: {doc.oracle.n} "
                                 f"rows / {doc.oracle.get_next_order()} "
                                 f"orders vs {backend.capacity}/"
                                 f"{backend.order_capacity}")
                elif not doc.in_lane and applied:
                    host_only_applies += 1
                if self.flow is not None and applied:
                    # Terminal flow.apply per span, stamped AFTER the
                    # capacity probe so the mode is truthful: a probe
                    # failure means the oracle applied but no device
                    # step ran — "host", exactly like host-only docs.
                    self._flow_applies(doc, applied, ev_ranges,
                                       fs if scheduled else None,
                                       scheduled)

            if tr is not None and (shard_events or shard_steps):
                # Drain wall = the whole host-side doc loop (oracle
                # apply + compile + fuse + capacity probes) — the phase
                # the pipelined tick overlaps with the previous tick's
                # in-flight device step (analyze.py overlap reads it).
                tr.event("tick.drain", shard=shard, events=shard_events,
                         steps=shard_steps,
                         wall={"ms": round((time.perf_counter()
                                            - t_drain) * 1e3, 3)})
            if tr is not None and probed:
                tr.event("tick.capacity", shard=shard, probed=probed,
                         degraded=degraded)
            if lane_streams:
                active_shards.add(shard)
                s_max = max(s.num_steps for s in lane_streams.values())
                s_bkt = self.bucket(s_max)
                # Recompile tracking promoted from the backend's
                # ``shapes_seen`` assert to a first-class trace event
                # (ISSUE 8): steady state must stop emitting these.
                seen = getattr(backend, "shapes_seen", None)
                fresh_shape = seen is not None and s_bkt not in seen
                per_lane = [
                    B.pad_ops(lane_streams.get(b, B.empty_ops(self.lmax)),
                              s_bkt)
                    for b in range(backend.lanes)
                ]
                stacked = B.stack_ops(per_lane)
                # Finish this shard's older in-flight work FIRST (the
                # staged sync, pulled forward to the dispatch edge):
                # apply()'s host-side device reads would block on it
                # anyway, but inside the dispatch-wall window — this
                # keeps disp_ms enqueue-only and charges un-hidden
                # device time to the pipeline stall accounting.
                # Backends whose dispatch path reads NO device state
                # (the flat backend with device_prefill: delta scatter
                # + host-mirrored capacity counts, ISSUE 14) skip the
                # forced sync entirely — the dispatch is pure enqueue
                # and the in-flight step overlaps through to its staged
                # sync (wall-only; the logical stream cannot tell).
                if getattr(backend, "dispatch_reads_device", True):
                    self._sync_shard_inflight(shard)
                t_dev = time.perf_counter()
                backend.apply(stacked)
                disp_ms = (time.perf_counter() - t_dev) * 1e3
                if self.sanitize_pipeline:
                    # Dispatch-edge fingerprint: these exact array
                    # objects are what the in-flight device step may
                    # still read (CPU zero-copy aliasing); the staged
                    # sync re-checks them.
                    tick_guards.append({
                        "shard": shard, "arrays": stacked,
                        "crcs": _op_fingerprints(stacked)})
                real = sum(s.num_steps for s in lane_streams.values())
                if fresh_shape:
                    self.counters.incr("device_compiles")
                    if tr is not None:
                        tr.event("device.compile", shard=shard,
                                 bucket=s_bkt)
                if tr is not None:
                    # Dispatch wall (host prefill + enqueue; the device
                    # sync lands in tick.barrier) — segregated under
                    # "w" so the logical stream stays seed-determined.
                    tr.event("tick.device", shard=shard, bucket=s_bkt,
                             lanes=len(lane_streams), steps=real,
                             wall={"ms": round(disp_ms, 3)})
                observe(self.counters, f"device_step_wall_ms_b{s_bkt}",
                        disp_ms)
                stats["lanes_active"] += len(lane_streams)
                self.counters.sample(
                    "batch_fill_ratio",
                    real / float(s_bkt * backend.lanes))
                self.counters.incr("device_ticks")
                self.counters.incr("device_steps", s_bkt)
            self.counters.incr("host_only_applies", host_only_applies)

        # 3. The barrier slot.  The per-shard ``tick.barrier`` events
        #    are emitted at the SAME logical stream position in every
        #    mode (the pipelined-vs-serial byte-identity contract), but
        #    the actual block_until_ready is staged behind the pipeline
        #    depth: with depth D this tick's device pass stays in
        #    flight while the next D-1 host ticks (drain, compile,
        #    oracle applies, residency checkpoint I/O) run, and the
        #    stall paid here is only the device time that host work
        #    could not hide.  Admission->applied latency stamps ride
        #    the staged sync, so they still include device completion.
        depth = self.effective_pipeline_ticks()
        tokens = []
        for shard, backend in enumerate(self.residency.backends):
            tok = backend.sync_token() if depth > 1 else None
            tokens.append({"shard": shard, "token": tok, "done": False})
        self._inflight.append({"tick": tick_no, "tokens": tokens,
                               "t_dispatched": time.perf_counter(),
                               "events": applied_events,
                               "guards": tick_guards})
        while len(self._inflight) > depth - 1:
            self._sync_entry(self._inflight.pop(0))
        if tr is not None:
            for shard in sorted(active_shards):
                # Wall names the shard's accumulated unreported sync
                # cost: the residual stall ("ms") and the host window
                # the in-flight step got to hide under ("win").  Once
                # the pipeline is primed the sync paid here belongs to
                # the PREVIOUS tick's entry — and a shard with no
                # device work this tick gets no event, so its numbers
                # carry to its next emitted barrier (the trace totals
                # stay equal to the in-memory accounting).  Logical
                # content is mode-invariant; only wall numbers move.
                tr.event("tick.barrier", shard=shard, wall={
                    "ms": round(
                        self._pending_stall.pop(shard, 0.0) * 1e3, 3),
                    "win": round(
                        self._pending_win.pop(shard, 0.0) * 1e3, 3)})
        now = time.perf_counter()
        for doc in self.router.docs.values():
            if doc.resident:
                released = doc.buffer.advance_watermarks(
                    agent_watermarks(doc.oracle))
                if released:
                    self.router.enqueue_released(doc, released)
        stats["tick_wall_s"] = now - t0
        self.tick_wall_samples.append(stats["tick_wall_s"])
        observe(self.counters, "tick_wall_ms", stats["tick_wall_s"] * 1e3)
        return stats

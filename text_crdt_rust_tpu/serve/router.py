"""Doc-shard routing: wire frames and local edits -> per-doc causal queues.

The router owns the ``doc_id -> (shard, lane)`` table for the server's
B-lane device batches and the per-document host state behind it. One
``DocState`` per admitted document:

- a host **oracle** (`models.oracle.ListCRDT`) — the source of truth the
  device lanes mirror, and what eviction serializes (``None`` while the
  doc is evicted to its checkpoint);
- the op **compiler state** (`ops.batch.AgentTable` + ``OrderAssigner``)
  kept aligned with the oracle so tick-time compilation resumes
  mid-history (rebuilt via ``OrderAssigner.from_oracle`` on restore);
- a bounded ``parallel.causal.CausalBuffer`` fronting all remote
  traffic, so the server inherits PR 1's gap/duplicate/out-of-order
  handling for free — frames from a lossy network release in causal
  order or wait, and ``missing()`` feeds the REQUEST frames the server
  emits to pull lost ranges;
- a FIFO **event queue** of causally-ready work (released remote txns +
  local edits) the batcher drains at tick time. FIFO per doc preserves
  the release order, so every apply is causally valid.

Frames arrive as bytes and are decoded through ``net/codec.py``; any
``CodecError`` becomes a counted, typed admission refusal — corrupt
input can never crash the serving loop (`net/faults.py` is the test
model). The doc id itself is connection metadata (the wire frame format
is doc-agnostic), so the submit surface is ``(doc_id, frame_bytes)``.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..common import RemoteIns, RemoteTxn, txn_len
from ..models.oracle import ListCRDT
from ..models.sync import (
    agent_watermarks,
    export_txns_for_wants,
    export_txns_since,
    state_digest,
)
from ..net import codec
from ..net.codec import CodecError
from ..ops import batch as B
from ..parallel.causal import CausalBuffer
from ..utils.metrics import Counters
from .admission import AdmissionControl, AdmissionError

# Event kinds in a doc's FIFO queue.
EV_TXN = "txn"      # payload: a causally-ready RemoteTxn
EV_LOCAL = "local"  # payload: (agent_name, pos, del_len, ins_content)


class Event:
    """One unit of causally-ready work. ``t_submit`` is the ADMISSION
    time (callers pass the stamp recorded when the txn entered the
    server, so a txn's causal-buffer wait — the fault-induced tail the
    latency metric exists to expose — is inside admission->applied).

    ``lk`` is the flow-provenance ordinal of a sampled LOCAL edit
    (obs/flow: the span has no ``(agent, seq)`` until the oracle
    applies it); ``span`` is filled by the batcher at apply time with
    the realized ``(agent, seq, n)`` so the tick can stamp the span's
    terminal ``flow.apply`` after the lane-capacity probe decides
    device vs host.

    ``ordinal`` is a LOCAL edit's per-doc durability ordinal (ISSUE
    16): assigned densely at admission, advanced into
    ``DocState.local_applied`` when the batcher processes the event.
    Replay is exactly-once because recovery re-executes the journal
    from genesis; the recorded ordinal is its audit (``local_gaps``
    checks it against the rebuilt ``local_seen``), and
    ``local_applied`` is the checkpointed stamp a future INCREMENTAL
    recovery would skip below (a validity-dropped local leaves no
    oracle state, so no oracle-derived watermark could cover it)."""

    __slots__ = ("kind", "payload", "items", "t_submit", "tick_submit",
                 "lk", "span", "ordinal")

    def __init__(self, kind: str, payload, items: int, tick: int,
                 t_submit: Optional[float] = None,
                 lk: Optional[int] = None,
                 ordinal: Optional[int] = None):
        self.kind = kind
        self.payload = payload
        self.items = items
        self.t_submit = (time.perf_counter() if t_submit is None
                         else t_submit)
        self.tick_submit = tick
        self.lk = lk
        self.span = None
        self.ordinal = ordinal


class DocState:
    """Everything the server holds for one document."""

    def __init__(self, doc_id: str, shard: int,
                 max_pending: Optional[int] = None):
        self.doc_id = doc_id
        self.shard = shard
        self.lane: Optional[int] = None
        self.oracle: Optional[ListCRDT] = ListCRDT()
        self.table: Optional[B.AgentTable] = B.AgentTable()
        self.assigner: Optional[B.OrderAssigner] = B.OrderAssigner(self.table)
        self.buffer = CausalBuffer(max_pending=max_pending)
        self.events: Deque[Event] = deque()
        self.evicted = False
        self.ckpt_path: Optional[str] = None
        # (agent, seq) -> admission perf_counter stamp for txns still in
        # the causal buffer, so their eventual Event carries the TRUE
        # admission time (first delivery wins; trims look up the nearest
        # covering stamp). Pruned against the buffer watermark.
        self.submit_stamps: Dict[Tuple[str, int], float] = {}
        # Latest per-agent watermarks any peer DIGEST advertised: the
        # gossip that reveals gaps the causal buffer cannot see (every
        # frame from an agent dropped), exactly as in `net/session.py`.
        self.peer_marks: Dict[str, int] = {}
        # High-water of the ORACLE's own per-agent watermarks, kept
        # fresh while resident and surviving eviction (the checkpoint
        # holds that history): REQUEST emission reads these so an
        # evicted doc never re-requests ranges it already persisted —
        # and so the owed-wants computation is independent of residency
        # timing (the loadgen's cross-backend determinism relies on it).
        self.known_marks: Dict[str, int] = {}
        self.degraded = False          # lane overflow: host-only forever
        self.degrade_reason = ""
        self.last_touch_tick = 0
        self.divergence_detected = False
        # Local-edit durability watermarks (ISSUE 16): ``local_seen`` is
        # the next ordinal to assign at submit; ``local_applied`` counts
        # ordinals the batcher has PROCESSED (applied or
        # validity-dropped).  ``local_applied`` rides checkpoint extra
        # meta as an audit stamp reserved for future incremental
        # (checkpoint-anchored) recovery; today's replay re-executes
        # from genesis and audits ordinals against ``local_seen``.
        self.local_seen = 0
        self.local_applied = 0

    def absorb_oracle_marks(self) -> None:
        """Fold the resident oracle's per-agent watermarks into
        ``known_marks`` (max-merge).  Called wherever the oracle's
        history extent must survive the oracle's absence — REQUEST
        emission while resident, and the eviction snapshot."""
        if self.oracle is None:
            return
        for agent, wm in agent_watermarks(self.oracle).items():
            if wm > self.known_marks.get(agent, 0):
                self.known_marks[agent] = wm

    @property
    def resident(self) -> bool:
        """Oracle in memory (lane-backed or host-only)."""
        return self.oracle is not None

    @property
    def in_lane(self) -> bool:
        return self.lane is not None

    def pending(self) -> int:
        return len(self.events) + self.buffer.pending


class ShardRouter:
    """doc_id -> (shard, lane) table + the frame/edit submit surface.

    Shard assignment is least-loaded-at-admit and stable for the doc's
    lifetime (a doc's lane may come and go with residency, its shard
    never does — evicting to a different shard would orphan its device
    state). Lane assignment belongs to ``serve/residency.py``.
    """

    def __init__(self, num_shards: int, *, admission: AdmissionControl,
                 counters: Optional[Counters] = None,
                 buffer_max_pending: Optional[int] = 512,
                 wire_format: str = "row", tracer=None, flow=None):
        assert num_shards >= 1
        self.num_shards = num_shards
        self.admission = admission
        self.counters = counters if counters is not None else Counters()
        self.tracer = tracer
        self.flow = flow  # obs/flow.FlowTracker (None = provenance off)
        self.recorder = None  # set by DocServer after construction
        self.journal = None   # serve/journal.Journal (set by DocServer;
        #                       None = durability off)
        self.buffer_max_pending = buffer_max_pending
        # TXNS frames the router EMITS (serving REQUEST pulls); decode
        # always negotiates on the version byte, so what peers send is
        # their choice. The columnar wire amortizes per-frame overhead
        # across much bigger batches.
        self.wire_format = wire_format
        self._encode_txns = codec.txns_encoder(wire_format)
        self._txns_per_frame = 8 if wire_format == "row" else 512
        self.docs: Dict[str, DocState] = {}
        self._shard_docs = [0] * num_shards
        self._tick = 0

    # -- doc lifecycle surface (driven by the server facade) ----------------

    def set_tick(self, tick: int) -> None:
        self._tick = tick

    def admit_doc(self, doc_id: str) -> DocState:
        """Register a new empty document; idempotent on the same id."""
        doc = self.docs.get(doc_id)
        if doc is not None:
            return doc
        shard = min(range(self.num_shards), key=lambda s: self._shard_docs[s])
        doc = DocState(doc_id, shard, max_pending=self.buffer_max_pending)
        doc.last_touch_tick = self._tick
        if self.flow is not None and self.flow.enabled:
            # A pressure-evicted buffer txn leaves the process but not
            # the ledger: stamp the drop so the span's location stays
            # named until redelivery brings it back.
            doc.buffer.on_drop = (
                lambda txn, d=doc_id: self.flow.buffered(d, txn, "drop"))
        self.docs[doc_id] = doc
        self._shard_docs[shard] += 1
        self.counters.incr("docs_admitted")
        if self.journal is not None:
            # Admission ORDER is durable state: replaying admits in
            # sequence reproduces both the least-loaded shard choice
            # and the docs-dict iteration order the drain loop walks.
            self.journal.admit(shard, doc_id)
        return doc

    def doc(self, doc_id: str) -> DocState:
        doc = self.docs.get(doc_id)
        if doc is None:
            raise self.admission.reject_unknown_doc(doc_id)
        return doc

    def shard_lane(self, doc_id: str) -> Tuple[int, Optional[int]]:
        doc = self.doc(doc_id)
        return doc.shard, doc.lane

    # -- submit surface -----------------------------------------------------

    def _enqueue(self, doc: DocState, event: Event) -> None:
        doc.events.append(event)
        self.admission.enqueued()
        doc.last_touch_tick = self._tick

    def _pop_stamp(self, doc: DocState, txn: RemoteTxn) -> Optional[float]:
        """Admission stamp for a released txn: exact (agent, seq) hit,
        else the nearest earlier same-agent stamp (the buffer trims
        already-known prefixes, shifting the released seq forward)."""
        key = (txn.id.agent, txn.id.seq)
        t = doc.submit_stamps.pop(key, None)
        if t is not None:
            return t
        best = None
        for (agent, seq), stamp in doc.submit_stamps.items():
            if agent == txn.id.agent and seq <= txn.id.seq:
                if best is None or seq > best[0]:
                    best = (seq, stamp)
        if best is not None:
            doc.submit_stamps.pop((txn.id.agent, best[0]), None)
            return best[1]
        return None

    def _prune_stamps(self, doc: DocState) -> None:
        """Stamps whose seqs the buffer watermark already covers belong
        to duplicates that will never release — drop them (bounds the
        dict against duplicate-heavy re-deliveries)."""
        if len(doc.submit_stamps) <= 1024:
            return
        marks = doc.buffer.watermarks()
        for key in [k for k in doc.submit_stamps
                    if k[1] < marks.get(k[0], 0)]:
            del doc.submit_stamps[key]

    def enqueue_released(self, doc: DocState,
                         released: List[RemoteTxn]) -> None:
        """Queue causally-released txns as events carrying their
        ADMISSION stamps (a release must never be refused — refusing it
        would desync the buffer watermark)."""
        for txn in released:
            if self.flow is not None:
                # The ONE choke point every causal release crosses
                # (submit-time drains AND tick-end watermark advances):
                # the span's buffered->ready crossing.
                self.flow.ready(doc.doc_id, txn)
            self._enqueue(doc, Event(EV_TXN, txn, txn_len(txn), self._tick,
                                     t_submit=self._pop_stamp(doc, txn)))

    def submit_txn(self, doc_id: str, txn: RemoteTxn) -> None:
        """Admit one remote txn (already decoded) into the doc's causal
        queue. Raises ``AdmissionError``; on success the txn is either
        released into the event FIFO or held in the causal buffer."""
        doc = self.doc(doc_id)
        try:
            self.admission.admit(doc_id, txn.id.agent, txn_len(txn),
                                 doc.pending(), self._tick,
                                 seq=txn.id.seq)
        except AdmissionError as e:
            self._flow_reject_txns(doc_id, [txn], e.reason)
            raise
        if self._ingest_txn(doc, txn) and self.journal is not None:
            self.journal.txns(doc.shard, doc_id, [txn])

    def _flow_reject_txns(self, doc_id: Optional[str],
                          txns: List[RemoteTxn], reason: str) -> None:
        """Stamp ``flow.reject`` for every sampled span an admission
        refusal bounced (all-or-nothing per frame/group, so the whole
        batch shares the reason).  Non-terminal if a redelivery later
        lands — the audit's precedence gives applied the last word."""
        if self.flow is None:
            return
        for t in txns:
            self.flow.rejected(doc_id, t.id.agent, reason,
                               seq=t.id.seq, n=txn_len(t))

    def _ingest_txn(self, doc: DocState, txn: RemoteTxn) -> bool:
        """Offer one admitted txn to the doc's causal buffer; returns
        True when the buffer took it as FRESH (anything but a full
        duplicate) — the predicate the journal records on (dup
        deliveries are no-ops on buffer state, so replay skipping them
        reproduces the same trajectory for a fraction of the bytes)."""
        doc.submit_stamps.setdefault((txn.id.agent, txn.id.seq),
                                     time.perf_counter())
        self._prune_stamps(doc)
        released = doc.buffer.add(txn)
        if (self.flow is not None
                and doc.buffer.last_offer == "buffered"):
            self.flow.buffered(doc.doc_id, txn, "held")
        doc.last_touch_tick = self._tick
        self.enqueue_released(doc, released)
        return doc.buffer.last_offer != "dup"

    def submit_local(self, doc_id: str, agent: str, pos: int,
                     del_len: int = 0, ins_content: str = "") -> None:
        """Admit one local edit (the server is the authoring peer)."""
        items = del_len + len(ins_content)
        if items <= 0:
            return
        doc = self.doc(doc_id)
        # Emission precedes admission: a refused local edit is still an
        # emitted span — its terminal state is the typed rejection.
        lk = (self.flow.emit_local(doc_id, agent, items)
              if self.flow is not None else None)
        try:
            self.admission.admit(doc_id, agent, items, doc.pending(),
                                 self._tick)
        except AdmissionError as e:
            if lk is not None:
                self.flow.rejected(doc_id, agent, e.reason, lk=lk)
            raise
        ordinal = doc.local_seen
        doc.local_seen += 1
        if self.journal is not None:
            self.journal.local(doc.shard, doc_id, agent, pos, del_len,
                               ins_content, ordinal)
        self._enqueue(doc, Event(EV_LOCAL, (agent, pos, del_len,
                                            ins_content), items,
                                 self._tick, lk=lk, ordinal=ordinal))

    def submit_frame(self, doc_id: str, data: bytes) -> List[bytes]:
        """Ingest one wire frame for ``doc_id``; returns response frames
        (served REQUESTs). Corrupt bytes raise a typed, counted
        ``AdmissionError`` — never an uncaught decode error."""
        doc = self.doc(doc_id)
        self.counters.incr("wire_bytes_in", len(data))
        if self.recorder is not None:
            self.recorder.note_frame(doc_id, data)
        try:
            kind, value, _, finfo = codec.decode_frame_ex(data)
        except CodecError as e:
            self._trace_codec_reject(doc_id, e)
            raise self.admission.reject_frame(
                str(e), doc=doc_id, agent=e.agent, seq=e.seq,
                n=e.n) from None
        self.counters.incr("frames_received")
        if (self.journal is not None
                and kind not in (codec.KIND_TXNS, codec.KIND_TXNS_MUX)):
            # Control frames steer trajectory-relevant state (REQUEST
            # touches the residency LRU clock, DIGEST advances
            # peer_marks) — the input log carries them verbatim so
            # recovery's re-execution stays exact.  TXNS frames are
            # journaled dedup'd below instead; a mux frame on this
            # lane is refused before it can mutate anything.
            self.journal.frame(doc.shard, doc_id, data)

        if kind == codec.KIND_TXNS:
            if self.flow is not None:
                # The framed crossing, stamped with the frame's stored
                # CRC32C as frame id (content-derived, so same-seed
                # runs — and dup deliveries — agree on it).
                self.flow.framed(doc_id, value, finfo.crc)
            # Two-phase: admission-CHECK every txn in the frame first,
            # then ingest — a mid-frame refusal must not leave a prefix
            # enqueued behind a raised AdmissionError (all-or-nothing
            # per frame; checked-prefix rate tokens are consumed).
            try:
                for i, txn in enumerate(value):
                    self.admission.check(doc_id, txn.id.agent,
                                         txn_len(txn),
                                         doc.pending() + i, self._tick,
                                         seq=txn.id.seq)
            except AdmissionError as e:
                self._flow_reject_txns(doc_id, value, e.reason)
                raise
            fresh = []
            for txn in value:
                self.admission.count_admitted(txn_len(txn))
                if self._ingest_txn(doc, txn):
                    fresh.append(txn)
            if fresh and self.journal is not None:
                self.journal.txns(doc.shard, doc_id, fresh)
            return []

        if kind == codec.KIND_REQUEST:
            # Serve the pull from the oracle when it is in memory; an
            # evicted doc registers the touch (restore happens at the
            # next tick) and the peer re-asks — a retry, not an error.
            doc.last_touch_tick = self._tick
            if not doc.resident:
                self.counters.incr("requests_deferred_evicted")
                return []
            txns = export_txns_for_wants(doc.oracle, value)
            out = []
            for i in range(0, len(txns), self._txns_per_frame):
                frame = self._encode_txns(txns[i:i + self._txns_per_frame])
                out.append(frame)
                self.counters.incr("wire_txn_bytes_out", len(frame))
            self.counters.incr("requests_served")
            return out

        if kind == codec.KIND_TXNS_MUX:
            # A multiplexed frame on the per-doc lane would need doc
            # routing this entry point does not do — a silent drop here
            # would ack-then-lose the txns. Typed refusal, like every
            # other wrong-shape input.
            raise self.admission.reject_frame(
                "TXNS_MUX frame on the per-doc lane "
                "(use submit_mux_frame)")

        # KIND_DIGEST: watermark gossip (reveals agents whose frames were
        # ALL lost — the causal buffer alone can't see those gaps; the
        # next ``poll_request_frame`` pulls them) + divergence detection
        # (equal watermarks, unequal digests = the must-never-happen
        # CRDT failure, surfaced loudly).
        marks, digest = value
        for agent, wm in marks.items():
            if wm > doc.peer_marks.get(agent, 0):
                doc.peer_marks[agent] = wm
        if doc.resident and not doc.events:
            mine = agent_watermarks(doc.oracle)
            if marks == mine and digest != state_digest(doc.oracle):
                doc.divergence_detected = True
                self.counters.incr("divergence_detected")
                if self.tracer is not None:
                    self.tracer.event("divergence", doc=doc_id,
                                      via="digest")
                if self.recorder is not None:
                    self.recorder.on_failure(
                        "divergence",
                        "peer digest mismatch at equal watermarks",
                        doc_id=doc_id, oracle=doc.oracle)
        return []

    def _trace_codec_reject(self, doc_id: Optional[str],
                            err: CodecError) -> None:
        """One trace event + (bounded) post-mortem bundle per codec
        rejection — the 'what came off the wire right before' record.
        When the decoder could name the offending span (txn-level
        validation failures), its ``(agent, seq)`` range rides the
        event (ISSUE 11 satellite)."""
        if self.tracer is not None:
            span = {}
            if err.agent is not None:
                span = {"agent": err.agent, "seq": err.seq, "n": err.n}
            self.tracer.event("codec.reject", doc=doc_id, err=str(err),
                              **span)
        if self.recorder is not None:
            self.recorder.on_failure("codec", str(err), doc_id=doc_id)

    def submit_mux_frame(self, data: bytes) -> List[Tuple[str, str]]:
        """Ingest one doc-multiplexed TXNS frame (``net/columnar``
        TXNS_MUX) — the connection-level replication lane, where one
        frame carries many documents' batches.

        Admission is all-or-nothing per DOC GROUP, not per frame: one
        overloaded or unknown document must not reject every other
        document sharing the connection. Returns the per-group
        rejections as ``(doc_id, reason)`` pairs (the frame itself
        failing to decode still raises, as in ``submit_frame``)."""
        self.counters.incr("wire_bytes_in", len(data))
        if self.recorder is not None:
            self.recorder.note_frame(None, data)
        try:
            kind, groups, _, finfo = codec.decode_frame_ex(data)
        except CodecError as e:
            self._trace_codec_reject(None, e)
            raise self.admission.reject_frame(
                str(e), agent=e.agent, seq=e.seq, n=e.n) from None
        if kind != codec.KIND_TXNS_MUX:
            raise self.admission.reject_frame(
                f"frame kind {kind} on the mux lane")
        self.counters.incr("frames_received")
        rejected: List[Tuple[str, str]] = []
        for doc_id, txns in groups:
            if self.flow is not None:
                self.flow.framed(doc_id, txns, finfo.crc)
            try:
                doc = self.doc(doc_id)
                for i, txn in enumerate(txns):
                    self.admission.check(doc_id, txn.id.agent, txn_len(txn),
                                         doc.pending() + i, self._tick,
                                         seq=txn.id.seq)
            except AdmissionError as e:
                self._flow_reject_txns(doc_id, txns, e.reason)
                rejected.append((doc_id, str(e)))
                continue
            fresh = []
            for txn in txns:
                self.admission.count_admitted(txn_len(txn))
                if self._ingest_txn(doc, txn):
                    fresh.append(txn)
            if fresh and self.journal is not None:
                self.journal.txns(doc.shard, doc_id, fresh)
        return rejected

    # -- pull / export surface ---------------------------------------------

    def poll_request_frame(self, doc_id: str) -> Optional[bytes]:
        """The REQUEST frame this doc currently owes its peers: the
        causal buffer's missing-range frontier (gaps from dropped or
        corrupted frames) PLUS gaps only peer digests reveal (an agent
        whose every frame was lost). None when nothing is missing."""
        doc = self.doc(doc_id)
        if self.journal is not None:
            # A poll is an input, not a pure read: absorb_oracle_marks
            # below folds the oracle's watermarks into known_marks,
            # which narrows every later REQUEST the doc emits.
            self.journal.poll(doc.shard, doc_id)
        wants: Dict[str, int] = {}
        for rid in doc.buffer.missing():
            wants[rid.agent] = min(wants.get(rid.agent, rid.seq), rid.seq)
        marks = dict(doc.buffer.watermarks())
        doc.absorb_oracle_marks()
        for agent, wm in doc.known_marks.items():
            marks[agent] = max(marks.get(agent, 0), wm)
        for agent, peer_wm in doc.peer_marks.items():
            mine = marks.get(agent, 0)
            if peer_wm > mine:
                wants[agent] = min(wants.get(agent, mine), mine)
        if not wants:
            return None
        self.counters.incr("range_requests")
        if self.tracer is not None:
            self.tracer.event("resync.round", doc=doc_id, wants=len(wants))
        return codec.encode_request(wants)

    def export_since(self, doc_id: str, start_order: int
                     ) -> List[RemoteTxn]:
        """History with order >= start_order — how downstream replicas
        (and the loadgen's twins) observe server-authored edits."""
        doc = self.doc(doc_id)
        assert doc.resident, "export from an evicted doc (restore first)"
        return export_txns_since(doc.oracle, start_order)

    @staticmethod
    def txn_agent_names(txn: RemoteTxn) -> set:
        """Every agent name a txn references (author, parents, origins,
        delete targets) — what must exist in the doc's AgentTable before
        the txn compiles."""
        names = {txn.id.agent}
        for p in txn.parents:
            names.add(p.agent)
        for op in txn.ops:
            if isinstance(op, RemoteIns):
                names.add(op.origin_left.agent)
                names.add(op.origin_right.agent)
            else:
                names.add(op.id.agent)
        names.discard("ROOT")
        return names

"""Deterministic closed-loop load generator + convergence checker.

N documents x M agents: every agent holds a real oracle replica of its
document, edits it locally, gossips with its sibling agents, and ships
its history to the server as binary TXNS frames through a seeded
`net/faults.py` channel (drops / dups / reorders / truncations /
bit-flips). A seeded Zipf popularity skew concentrates traffic on hot
documents so the cold tail actually evicts. Local server-side edits mix
in with probability ``local_prob`` (they also *touch* evicted docs,
driving the restore path).

Ground truth: one always-resident **twin** oracle per doc consumes the
exact same txn set over a clean channel (plus the server's own edits,
observed via ``export_since``). The run converges iff, after the lossy
phase plus the server-driven REQUEST/re-delivery cycle, every document
is bit-identical to its twin (string AND portable state digest) and
every device lane is bit-identical to its host oracle — the ISSUE-3
acceptance bar, CLI-runnable:

    python -m text_crdt_rust_tpu.serve.loadgen --docs 200 --agents 3 \\
        --ticks 60 --fault-rate 0.10 --seed 7
"""
from __future__ import annotations

import argparse
import os
import random
import time
from typing import Dict, List, Optional, Set, Tuple

from ..common import RemoteTxn, txn_len
from ..config import ServeConfig
from ..models.oracle import ListCRDT
from ..models.sync import agent_watermarks, export_txns_since, state_digest
from ..net import codec, columnar
from ..net.faults import FaultSpec, FaultyChannel
from ..obs.trace import TRACE_SCHEMA_VERSION
from ..parallel.causal import CausalBuffer
from .admission import AdmissionError
from .server import DocServer

TXNS_PER_FRAME = 4
# Mux frames cap below the codec's 4096-txn limit: one frame is one
# loss unit on the fault channel — a dropped whole-window frame turns
# into a multi-doc backfill pull.
MUX_TXNS_PER_FRAME = 1024
# The Nagle-style push policy (columnar wire) lives in ServeConfig
# (``nagle_txns`` / ``nagle_rounds``, CLI ``--nagle-txns`` /
# ``--nagle-rounds``): a doc's outbox ships once it holds nagle_txns
# txns, or after nagle_rounds TICKS regardless (the flush check runs
# every tick — emission-to-frame batching dominates clean-remote op
# age, PERF.md §16, so the window is the serve loop's first-order
# latency lever; perf/pipeline_probe.py sweeps it).
# Pull chunking: a REQUEST want carries only a from-seq (the v1 control
# frame), so the owed range is the WHOLE history suffix even when the
# hole is one dropped frame. A faulty-phase pull ships a bounded chunk
# per round — the causal buffer's watermark walks forward and the next
# want narrows — instead of re-shipping the suffix every window. The
# clean final drain chunks too, at the admission queue's scale: an
# UNCHUNKED pull of a hot doc's long-stalled suffix (> max_queue_per_doc
# txns) is rejected queue-full as one all-or-nothing group — and
# re-offered identically every round, a zero-progress livelock the
# ISSUE-12 Nagle sweep exposed at mid-size windows.  A bounded clean
# chunk is always admissible once the inter-round tick drains the
# queue, so the watermark advances every round and the want narrows.
PULL_CHUNK_TXNS = 48
PULL_CHUNK_TXNS_CLEAN = 128

# The typing workload's deterministic vocabulary (real-text shape so
# DEFLATE sees real-text statistics, not a uniform-random alphabet).
WORDS = ("the quick brown fox jumps over a lazy dog while some text "
         "gets typed into this doc one word at a time and then edited "
         "again with small corrections near the cursor").split()


class _DocWorld:
    """Generation-side state for one document: agent replicas, their
    fault channels, the global txn log (generation order == a causal
    order), and the clean twin."""

    def __init__(self, doc_id: str, agents: List[str], seed: int,
                 spec: FaultSpec):
        self.doc_id = doc_id
        self.agents = agents
        self.replicas: Dict[str, ListCRDT] = {}
        self.replica_ids: Dict[str, int] = {}
        self.marks: Dict[str, int] = {a: 0 for a in agents}
        self.applied: Dict[str, Set[Tuple[str, int]]] = {
            a: set() for a in agents}
        self.channels: Dict[str, FaultyChannel] = {}
        for i, a in enumerate(agents):
            doc = ListCRDT()
            self.replicas[a] = doc
            self.replica_ids[a] = doc.get_or_create_agent_id(a)
            self.channels[a] = FaultyChannel(
                spec=spec, seed=seed * 10007 + i)
        self.txns: List[RemoteTxn] = []   # generation order, deduped
        self.txn_keys: Set[Tuple[str, int]] = set()
        self.twin = ListCRDT()
        self.twin_buffer = CausalBuffer()
        self.server_mark = 0
        # Columnar wire: fresh txns accumulate here between windowed
        # flushes instead of shipping per event.  ``outbox_age`` counts
        # TICKS the outbox has waited (the Nagle-style policy: ship
        # when big enough OR old enough — tiny per-doc batches are
        # where column chains and DEFLATE can't win; the window knobs
        # live in ServeConfig.nagle_txns/nagle_rounds).
        self.outbox: List[RemoteTxn] = []
        self.outbox_age = 0
        # Typing workload: per-agent cursor into the agent's replica.
        self.cursor: Dict[str, int] = {a: 0 for a in agents}

    def record(self, txns: List[RemoteTxn]) -> List[RemoteTxn]:
        fresh = []
        for t in txns:
            key = (t.id.agent, t.id.seq)
            if key not in self.txn_keys:
                self.txn_keys.add(key)
                self.txns.append(t)
                fresh.append(t)
        return fresh

    def feed_twin(self, txns: List[RemoteTxn]) -> None:
        for t in self.twin_buffer.add_all(txns):
            self.twin.apply_remote_txn(t)

    def gossip(self, rng: random.Random, agent: str) -> None:
        """The agent merges a random prefix of the doc's foreign
        history (generation order is causal, so any prefix is safe —
        the `perf/fuzz_mixed_fast.py` gen_stream recipe)."""
        doc = self.replicas[agent]
        seen = self.applied[agent]
        upto = rng.randint(0, len(self.txns))
        for t in self.txns[:upto]:
            key = (t.id.agent, t.id.seq)
            if t.id.agent != agent and key not in seen:
                seen.add(key)
                doc.apply_remote_txn(t)

    def agent_edit(self, rng: random.Random, agent: str, edits: int,
                   workload: str = "scatter") -> List[RemoteTxn]:
        """A burst of local edits on the agent's replica; returns the
        NEW txns exported since the agent's last export mark.

        ``scatter`` (default, the PR-3 shape) edits uniform-random
        positions; ``typing`` keeps a per-agent cursor and mostly types
        forward word by word with occasional backspaces and cursor
        jumps — the real-editing-trace shape (ROADMAP item 4), which
        both the step fuser and the columnar wire's delta chains are
        built for. Every position comes from the agent's OWN replica,
        so traffic stays server-state-independent either way."""
        doc = self.replicas[agent]
        aid = self.replica_ids[agent]
        for _ in range(edits):
            n = len(doc)
            if workload == "typing":
                cur = min(self.cursor[agent], n)
                r = rng.random()
                if n == 0 or r < 0.75:
                    word = rng.choice(WORDS) + " "
                    doc.local_insert(aid, cur, word)
                    self.cursor[agent] = cur + len(word)
                elif r < 0.87 and cur > 0:
                    k = min(rng.randint(1, 4), cur)
                    doc.local_delete(aid, cur - k, k)
                    self.cursor[agent] = cur - k
                else:
                    self.cursor[agent] = rng.randint(0, n)
            elif n == 0 or rng.random() < 0.55:
                pos = rng.randint(0, n)
                doc.local_insert(aid, pos, "".join(
                    rng.choice("abcdefgh") for _ in range(rng.randint(1, 4))))
            else:
                pos = rng.randint(0, n - 1)
                doc.local_delete(aid, pos, min(rng.randint(1, 4), n - pos))
        out = export_txns_since(doc, self.marks[agent])
        self.marks[agent] = doc.get_next_order()
        return out


class ServeLoadGen:
    """Seeded closed loop against one ``DocServer``."""

    def __init__(self, *, docs: int = 200, agents_per_doc: int = 3,
                 ticks: int = 60, events_per_tick: int = 48,
                 zipf_alpha: float = 1.1, fault_rate: float = 0.10,
                 local_prob: float = 0.25, seed: int = 7,
                 cfg: Optional[ServeConfig] = None,
                 resync_every: int = 4, verbose: bool = False,
                 workload: str = "scatter", byzantine: float = 0.0,
                 flash_crowd: Optional[Tuple[int, int]] = None):
        self.rng = random.Random(seed)
        self.cfg = cfg or ServeConfig()
        self.server = DocServer(self.cfg)
        self.ticks = ticks
        self.events_per_tick = events_per_tick
        self.local_prob = local_prob
        self.resync_every = max(1, resync_every)
        self.verbose = verbose
        assert workload in ("scatter", "typing"), workload
        self.workload = workload
        # The replication protocol generation, from ServeConfig: "row" =
        # the PR-1 shape (per-event frames of <= 4 txns, each agent
        # re-shipping its merged export); "columnar" = the v2 shape
        # (deduplicated per-world outboxes flushed each resync window as
        # doc-multiplexed columnar frames on one connection, pull
        # re-delivery as columnar streams).
        self.wire = self.cfg.wire_format
        spec = FaultSpec.all(fault_rate)
        self.worlds: List[_DocWorld] = []
        for d in range(docs):
            doc_id = f"doc{d:04d}"
            names = [f"d{d:04d}.a{i}" for i in range(agents_per_doc)]
            self.worlds.append(_DocWorld(doc_id, names,
                                         seed * 131 + d, spec))
            self.server.admit_doc(doc_id)
        # The mux lane's own fault channel (one connection for the
        # whole window flush; drops cost a window, anti-entropy pulls
        # it back).
        self.mux_channel = FaultyChannel(spec=spec, seed=seed * 7919 + 1)
        # Zipf popularity over docs (rank 0 hottest).
        self.weights = [1.0 / (i + 1) ** zipf_alpha for i in range(docs)]
        # Byzantine agent class (ISSUE 16 satellite): rate of hostile
        # frames per tick relative to events_per_tick.  Every hostile
        # frame must be refused TYPED (or absorbed as a dup) — any
        # other exception escaping the submit surface is a panic, and
        # the seeded test treats it as a failure.
        self.byzantine = max(0.0, float(byzantine))
        self.byz_rng = random.Random(seed * 104729 + 13)
        self.byz_sent = 0
        self.byz_rejected = 0
        self.byz_absorbed = 0
        # Flash-crowd scenario (ISSUE 16 satellite): from tick T on,
        # the pick distribution collapses onto one hot doc — lane
        # overflow + residency thrash on a single key.
        self.flash_crowd = flash_crowd
        self.rejections = 0
        self.ops_offered = 0
        # Wire accounting: bytes handed to the transport (pre-fault,
        # the sender's cost) on the txn lane vs the control lane, and
        # the deduplicated item-ops they carried.
        self.wire_txn_bytes = 0
        self.wire_push_bytes = 0   # event/flush lane
        self.wire_pull_bytes = 0   # REQUEST-answer (backfill) lane
        self.wire_ctrl_bytes = 0
        self.ops_replicated = 0

    # -- traffic -------------------------------------------------------------

    def _ship(self, world: _DocWorld, agent: str,
              txns: List[RemoteTxn], faulty: bool = True,
              lane: str = "push") -> None:
        """Encode txns into ROW frames and deliver them to the server,
        optionally through the agent's fault channel. (The v1 lane
        only: all columnar traffic goes through ``_ship_mux``.)"""
        assert self.wire == "row", "columnar traffic ships via _ship_mux"
        if not txns:
            return
        frames = [codec.encode_txns(txns[i:i + TXNS_PER_FRAME])
                  for i in range(0, len(txns), TXNS_PER_FRAME)]
        nbytes = sum(len(f) for f in frames)
        self.wire_txn_bytes += nbytes
        if lane == "push":
            self.wire_push_bytes += nbytes
        else:
            self.wire_pull_bytes += nbytes
        if faulty:
            ch = world.channels[agent]
            for f in frames:
                ch.send(f)
            frames = ch.drain()
        for f in frames:
            try:
                self.server.submit_frame(world.doc_id, f)
            except AdmissionError:
                self.rejections += 1

    def _flush_mux(self, faulty: bool = True, final: bool = False) -> None:
        """Columnar wire: ship deduplicated outboxes as doc-multiplexed
        frames on one connection (each doc's batch agent-sorted — the
        causal buffer re-orders on parents, and sorted columns are what
        the delta chains predict well).

        Nagle-style policy per doc: flush when the outbox reached
        ``cfg.nagle_txns`` or waited ``cfg.nagle_rounds`` ticks (column
        chains and frame DEFLATE only pay on batches; the anti-entropy
        pull covers anything a deferral or a dropped frame delays).
        The check runs EVERY tick — the window itself, not the resync
        cadence, decides when a batch ships."""
        batches: List[Tuple[str, List[RemoteTxn]]] = []
        for world in self.worlds:
            if not world.outbox:
                continue
            world.outbox_age += 1
            if not (final or len(world.outbox) >= self.cfg.nagle_txns
                    or world.outbox_age >= self.cfg.nagle_rounds):
                continue
            batches.append((world.doc_id,
                            sorted(world.outbox,
                                   key=lambda t: (t.id.agent, t.id.seq))))
            world.outbox = []
            world.outbox_age = 0
        self._ship_mux(batches, faulty=faulty)

    def _ship_mux(self, batches: List[Tuple[str, List[RemoteTxn]]],
                  faulty: bool = True, lane: str = "push") -> None:
        flat: List[Tuple[str, RemoteTxn]] = [
            (doc_id, t) for doc_id, txns in batches for t in txns]
        if not flat:
            return
        frames: List[bytes] = []
        for i in range(0, len(flat), MUX_TXNS_PER_FRAME):
            frames.append(columnar.encode_mux(
                columnar.group_consecutive(flat[i:i + MUX_TXNS_PER_FRAME])))
        nbytes = sum(len(f) for f in frames)
        self.wire_txn_bytes += nbytes
        if lane == "push":
            self.wire_push_bytes += nbytes
        else:
            self.wire_pull_bytes += nbytes
        if faulty:
            for f in frames:
                self.mux_channel.send(f)
            frames = self.mux_channel.drain()
        for f in frames:
            try:
                self.rejections += len(self.server.submit_mux_frame(f))
            except AdmissionError:
                self.rejections += 1

    def _gossip_digests(self, faulty: bool) -> None:
        """Every agent advertises its replica's watermarks + portable
        state digest — the anti-entropy signal that lets the server see
        gaps whose every frame was dropped (a peer it has literally
        never heard from)."""
        for world in self.worlds:
            for agent in world.agents:
                replica = world.replicas[agent]
                frame = codec.encode_digest(agent_watermarks(replica),
                                            state_digest(replica))
                self.wire_ctrl_bytes += len(frame)
                if faulty:
                    ch = world.channels[agent]
                    ch.send(frame)
                    frames = ch.drain()
                else:
                    frames = [frame]
                for f in frames:
                    try:
                        self.server.submit_frame(world.doc_id, f)
                    except AdmissionError:
                        self.rejections += 1

    def _resync(self, faulty: bool) -> int:
        """Answer the server's owed REQUEST frames from the generation
        log; returns how many docs still had wants."""
        wanting = 0
        owed_batches: List[Tuple[str, List[RemoteTxn]]] = []
        for world in self.worlds:
            req = self.server.poll_request_frame(world.doc_id)
            if req is None:
                continue
            wanting += 1
            self.wire_ctrl_bytes += len(req)
            kind, wants, _ = codec.decode_frame(req)
            assert kind == codec.KIND_REQUEST
            owed = [t for t in world.txns
                    if t.id.agent in wants
                    and t.id.seq + txn_len(t) > wants[t.id.agent]]
            if self.wire == "columnar":
                # A want that names txns still sitting in the world's
                # outbox is the push deferral showing through the
                # digest gossip, not a loss — the scheduled flush
                # delivers them. Pulling them too would double-ship
                # every deferred window.
                deferred = {(t.id.agent, t.id.seq) for t in world.outbox}
                owed = [t for t in owed
                        if (t.id.agent, t.id.seq) not in deferred]
                owed = owed[:PULL_CHUNK_TXNS if faulty
                            else PULL_CHUNK_TXNS_CLEAN]
            if self.wire == "columnar":
                # The pull lane is a backfill: ship ALL docs' owed
                # ranges as one multiplexed columnar stream — per-doc
                # frames would hand the overhead right back.
                if owed:
                    owed_batches.append((world.doc_id, sorted(
                        owed, key=lambda t: (t.id.agent, t.id.seq))))
            else:
                # Deliver via the hottest agent's channel (any path
                # works; the server dedups) — clean in the final drain.
                self._ship(world, world.agents[0], owed, faulty=faulty,
                           lane="pull")
        self._ship_mux(owed_batches, faulty=faulty, lane="pull")
        return wanting

    def _ship_byzantine(self, tick_index: int) -> None:
        """The byzantine agent class: a seeded stream of hostile frames
        — garbage bytes, bit-flipped frames, truncations, replays of
        already-delivered history, unknown-doc and wrong-lane
        submissions.  The server contract under attack: every hostile
        frame is either refused with a TYPED ``AdmissionError`` (counted
        below) or absorbed as a no-op duplicate — nothing panics the
        tick loop, nothing corrupts convergence.  Runs off its own rng
        so enabling the attacker never shifts the legitimate traffic
        stream (the crash-twin comparisons depend on that)."""
        rng = self.byz_rng
        n = max(1, round(self.events_per_tick * self.byzantine))
        for _ in range(n):
            attack = rng.choice(("garbage", "bitflip", "truncate",
                                 "replay", "unknown-doc", "wrong-lane"))
            world = self.worlds[rng.randrange(len(self.worlds))]
            doc_id = world.doc_id
            data: Optional[bytes] = None
            if attack == "garbage":
                data = bytes(rng.randrange(256)
                             for _ in range(rng.randint(1, 40)))
            elif attack in ("bitflip", "truncate", "replay"):
                if not world.txns:
                    continue  # nothing delivered yet to mangle/replay
                upto = rng.randint(1, min(4, len(world.txns)))
                frame = bytearray(codec.encode_txns(world.txns[:upto]))
                if attack == "bitflip":
                    frame[rng.randrange(len(frame))] ^= \
                        1 << rng.randrange(8)
                elif attack == "truncate":
                    del frame[rng.randint(1, len(frame) - 1):]
                data = bytes(frame)
            elif attack == "unknown-doc":
                doc_id = f"byz-doc-{rng.randrange(1 << 16):04x}"
                data = codec.encode_txns(world.txns[:1]) \
                    if world.txns else b"\x00"
            else:  # wrong-lane: a mux frame on the per-doc lane
                if not world.txns:
                    continue
                data = columnar.encode_mux([(doc_id, world.txns[:1])])
            self.byz_sent += 1
            try:
                self.server.submit_frame(doc_id, data)
            except AdmissionError:
                self.byz_rejected += 1
            else:
                # Replays (and garbage that happened to parse as a
                # benign frame) land here: absorbed, state untouched.
                self.byz_absorbed += 1

    def _observe_server_edits(self) -> None:
        """Feed the twins whatever new history the server produced
        (its own local edits, interleaved with merges)."""
        for world in self.worlds:
            doc = self.server.doc_state(world.doc_id)
            if not doc.resident:
                continue
            nxt = doc.oracle.get_next_order()
            if nxt > world.server_mark:
                txns = self.server.export_since(world.doc_id,
                                                world.server_mark)
                world.server_mark = nxt
                world.feed_twin(txns)

    def run_tick(self, tick_index: int) -> Dict[str, float]:
        picks = self.rng.choices(range(len(self.worlds)),
                                 weights=self.weights,
                                 k=self.events_per_tick)
        if (self.flash_crowd is not None
                and tick_index >= self.flash_crowd[0]):
            # Flash crowd: 90% of this tick's events slam one doc.  The
            # remap consumes its own rng draws AFTER the base picks so
            # pre-flash ticks are byte-identical to the plain run.
            hot = self.flash_crowd[1] % len(self.worlds)
            picks = [hot if self.rng.random() < 0.90 else p
                     for p in picks]
        for d in picks:
            world = self.worlds[d]
            if self.rng.random() < self.local_prob:
                # A server-side edit; position bounded by the doc's
                # TWIN length — a server-state-independent source, so
                # one seed generates byte-identical traffic on every
                # lane backend (the cross-backend bit-identity twin
                # runs of ISSUE 4 depend on it; a position the server
                # hasn't caught up to yet is validity-checked at apply
                # time and dropped, deterministically). The edit still
                # *touches* evicted docs, driving the restore path.
                live = len(world.twin)
                pos = self.rng.randint(0, live)
                ins = "".join(self.rng.choice("xyzw")
                              for _ in range(self.rng.randint(1, 3)))
                try:
                    self.server.submit_local(world.doc_id, "server-editor",
                                             pos, 0, ins)
                    self.ops_offered += len(ins)
                except AdmissionError:
                    self.rejections += 1
            else:
                agent = self.rng.choice(world.agents)
                world.gossip(self.rng, agent)
                txns = world.agent_edit(self.rng, agent,
                                        self.rng.randint(1, 3),
                                        workload=self.workload)
                fresh = world.record(txns)
                # Per-op provenance (ISSUE 11): a span is EMITTED the
                # moment it exists — before the fault channel gets to
                # eat its frames — so the conservation audit covers
                # lost-and-repulled ops, not just delivered ones.
                self.server.flow.emit_txns(world.doc_id, fresh)
                world.feed_twin(fresh)
                ops = sum(txn_len(t) for t in fresh)
                self.ops_offered += ops
                self.ops_replicated += ops
                if self.wire == "columnar":
                    # v2 protocol: dedup into the world's outbox; the
                    # windowed mux flush ships it (re-shipping every
                    # agent's merged export per event is most of the v1
                    # byte bill).
                    world.outbox.extend(fresh)
                else:
                    self._ship(world, agent, txns, faulty=True)
        if self.byzantine > 0.0:
            self._ship_byzantine(tick_index)
        if self.wire == "columnar":
            # The Nagle window is checked every tick (ISSUE 12): the
            # flush cadence is the window's own, decoupled from the
            # resync/anti-entropy cadence below — at the old
            # once-per-resync-window cadence the effective emission
            # latency floor was resync_every ticks no matter how small
            # the window was set.
            self._flush_mux(faulty=True)
        if (tick_index + 1) % self.resync_every == 0:
            self._gossip_digests(faulty=True)
            self._resync(faulty=True)
        # Server-authored history reaches the twins in the final
        # observation pass, NOT per tick: per-tick observation is gated
        # on residency, which differs across lane backends — it would
        # leak backend state into the twin lengths that seed the next
        # tick's traffic (see run_tick's position source).
        return self.server.tick()

    # -- the full run --------------------------------------------------------

    def run(self) -> Dict[str, object]:
        self.start()
        self.run_ticks(0, self.ticks)
        return self.finalize()

    def start(self) -> None:
        """Arm the run clock and accumulators.  ``run()`` calls this;
        the chaos harness calls it once, then drives ``run_ticks`` in
        pieces around the injected crash."""
        self._t0 = time.perf_counter()
        self._applied = 0
        self._steps = 0

    def run_ticks(self, start: int, stop: int) -> None:
        """Drive ticks ``start..stop`` (half-open).  Resumable: the
        crash harness runs ``[0, k)``, kills and recovers the server,
        then runs ``[k+1, ticks)`` against the recovered instance —
        generation state (worlds, rng, fault channels) lives here and
        survives the server's death, exactly like real clients would."""
        for i in range(start, stop):
            stats = self.run_tick(i)
            self._applied += stats["ops_applied"]
            self._steps += stats["steps"]
            if self.verbose and (i + 1) % 10 == 0:
                rc = self.server.residency.resident_counts()
                print(f"tick {i + 1}/{self.ticks}: applied "
                      f"{self._applied} item-ops, {rc['docs_in_lane']} "
                      f"in-lane / {rc['docs_evicted']} evicted",
                      flush=True)

    def finalize(self) -> Dict[str, object]:
        """The run tail: flush the pipeline, drain the anti-entropy
        cycle clean, verify every doc against its twin, and assemble
        the report."""
        applied = self._applied
        # The timed loop is not done until its device work is: flush
        # the pipeline BEFORE the wall capture, so serial and pipelined
        # arms account identical work (a depth-D run would otherwise
        # push its last D-1 ticks' sync cost outside the loop wall and
        # bias the probe's regression gate in its own favor).
        self.server.flush_pipeline()
        loop_wall = time.perf_counter() - self._t0

        # Final drain: clean digests + re-delivery until the server owes
        # no REQUESTs and every queue is empty — the anti-entropy cycle
        # that recovers everything the fault channels mangled.
        drain_rounds = 0
        if self.wire == "columnar":
            self._flush_mux(faulty=False, final=True)
        self._gossip_digests(faulty=False)
        for drain_rounds in range(1, 64):
            wanting = self._resync(faulty=False)
            self.server.tick()
            busy = any(d.events for d in self.server.router.docs.values())
            if not wanting and not busy:
                break
        self.server.drain()
        self._observe_server_edits()

        converged, mismatches = self.verify()
        wall = time.perf_counter() - self._t0
        stats = self.server.stats()
        tick_sum = self.server.tick_summary()
        report = {
            "converged": converged,
            "mismatches": mismatches[:8],
            "docs": len(self.worlds),
            "item_ops_applied": int(applied),
            "device_ticks_wall_s": round(loop_wall, 3),
            "ops_per_sec": round(applied / loop_wall, 1) if loop_wall else 0,
            "drain_rounds": drain_rounds,
            "wall_s": round(wall, 3),
            "rejected_submissions": self.rejections,
            "byzantine": {
                "rate": self.byzantine,
                "sent": self.byz_sent,
                "rejected": self.byz_rejected,
                "absorbed": self.byz_absorbed,
            },
            "latency_us": self.server.latency_summary(),
            "tick_ms": tick_sum,
            "engine": self.cfg.engine,
            # Pipelined tick (ISSUE 12): effective depth, how much of
            # the device-sync demand the staged sync hid under host
            # work, and the residual stall.
            "pipeline": {
                "sanitize": self.cfg.sanitize_pipeline,
                "sanitize_checks": stats.get("sanitize_checks", 0),
                "ticks": tick_sum.get("pipeline_ticks", 1),
                "overlap_frac": tick_sum.get("pipeline_overlap_frac",
                                             0.0),
                "stall_ms_total": tick_sum.get("pipeline_stall_ms_total",
                                               0.0),
            },
            # Device-resident prefill (ISSUE 14): the per-tick log-
            # prefill byte economy — delta scatter vs full-log round
            # trip.  All logical (seed-deterministic); the flat backend
            # is the only producer today.
            "prefill": {
                # Default False: a backend fleet that exposes no
                # prefill surface (the lanes backend's tables are
                # device-resident already) moves no prefill bytes.
                "device_prefill": tick_sum.get("device_prefill", False),
                "bytes_per_tick": tick_sum.get(
                    "prefill_bytes_per_tick", 0.0),
                "bytes_full_per_tick": tick_sum.get(
                    "prefill_bytes_full_per_tick", 0.0),
                "bytes_cut_x": tick_sum.get("prefill_bytes_cut_x", 0.0),
                "scatter_len": tick_sum.get("prefill_scatter_len", 0),
                "scatter_compiles": tick_sum.get(
                    "prefill_scatter_compiles", 0),
            },
            # Tick trains (ISSUE 20): the device-dispatch economy — how
            # many device programs the run issued vs what the serial
            # per-tick loop would have, the realized mean train length,
            # and the (T, S) train-program compile count.
            "train": {
                "ticks": tick_sum.get("train_ticks", 1),
                "device_dispatches": tick_sum.get(
                    "device_dispatches", 0),
                "dispatches_per_tick": tick_sum.get(
                    "device_dispatches_per_tick", 0.0),
                "dispatch_cut_x": tick_sum.get("dispatch_cut_x", 1.0),
                "train_len": tick_sum.get("train_len", 1.0),
                "train_compiles": tick_sum.get("train_compiles", 0),
            },
            "wire": {
                "format": self.wire,
                "workload": self.workload,
                "nagle_txns": self.cfg.nagle_txns,
                "nagle_rounds": self.cfg.nagle_rounds,
                "txn_bytes": self.wire_txn_bytes,
                "push_bytes": self.wire_push_bytes,
                "pull_bytes": self.wire_pull_bytes,
                "ctrl_bytes": self.wire_ctrl_bytes,
                "ops_replicated": self.ops_replicated,
                "bytes_per_op": round(
                    self.wire_txn_bytes / max(1, self.ops_replicated), 3),
            },
            "ckpt": {
                "format": self.cfg.ckpt_format,
                "bytes_written": stats.get("ckpt_bytes_written", 0),
                "saves_full": stats.get("ckpt_saves_full", 0),
                "saves_delta": stats.get("ckpt_saves_delta", 0),
                "bytes_per_evict": stats.get("ckpt_bytes_per_evict_mean", 0),
                "bytes_per_evict_min": stats.get(
                    "ckpt_bytes_per_evict_min", 0),
                "bytes_per_evict_max": stats.get(
                    "ckpt_bytes_per_evict_max", 0),
            },
            # Observability block (ISSUE 8): everything below flows
            # from the ONE metrics registry + tracer the server owns.
            # Per-op provenance (ISSUE 11): span census, conservation
            # audit over the sampled spans (end-of-run mode: every
            # span must be terminal — the drain above finished), and
            # op-age-at-apply distributions in logical ticks.
            "flow": self.server.flow_summary(expect_terminal=True),
            "obs": {
                "trace_enabled": self.cfg.trace,
                "trace_schema": TRACE_SCHEMA_VERSION,
                "trace_events": self.server.tracer.seq,
                "device_compiles": stats.get("device_compiles", 0),
                "bundles_written": stats.get("bundles_written", 0),
                "bundles_suppressed": stats.get("bundles_suppressed", 0),
                # The recorder's own written-file count (must agree
                # with the counter — asserted in test_obs_recorder).
                "bundle_count": len(self.server.recorder.bundle_paths),
                "bundles": list(self.server.recorder.bundle_paths),
            },
            "server": stats,
        }
        # Finalize obs: stop a still-open profiler capture, flush+close
        # the trace stream (the report above already read everything).
        self.server.close_obs()
        return report

    def verify(self) -> Tuple[bool, List[str]]:
        """Every doc bit-identical to its twin; every lane bit-identical
        to its oracle. Returns (ok, mismatch descriptions)."""
        bad: List[str] = []
        for world in self.worlds:
            # Docs evicted at run end: restore, then feed the twin any
            # server-authored history it hasn't observed yet (the doc
            # may have been checkpointed right after its last edit).
            self.server.ensure_resident(world.doc_id)
        self._observe_server_edits()
        for world in self.worlds:
            # The twin must itself have fully converged (a generation
            # bug otherwise — every generated txn was fed cleanly).
            if world.twin_buffer.pending:
                bad.append(f"{world.doc_id}: twin buffer still holds "
                           f"{world.twin_buffer.pending} txns")
                continue
            got = self.server.doc_string(world.doc_id)
            want = world.twin.to_string()
            if got != want:
                bundle = self._postmortem(world, "content diverged")
                bad.append(f"{world.doc_id}: content diverged "
                           f"({len(got)} vs {len(want)} chars; "
                           f"post-mortem: {bundle})")
                continue
            doc = self.server.doc_state(world.doc_id)
            if state_digest(doc.oracle) != state_digest(world.twin):
                bundle = self._postmortem(world, "state digest diverged")
                bad.append(f"{world.doc_id}: state digest diverged "
                           f"(post-mortem: {bundle})")
                continue
            if not self.server.verify_doc(world.doc_id):
                # verify_lane already dumped its own divergence bundle
                # (or the run's one divergence bundle was spent earlier
                # — point at that one, never at an unrelated class).
                bundle = next(
                    (p for p in reversed(self.server.recorder.bundle_paths)
                     if "divergence" in os.path.basename(p)), None)
                bad.append(f"{world.doc_id}: device lane != host oracle"
                           + (f" (post-mortem: {bundle})" if bundle else ""))
        return not bad, bad

    def _postmortem(self, world: _DocWorld, detail: str):
        """Dump the twin-divergence flight-recorder bundle (ISSUE 8):
        the first-divergence walk against the twin names the exact
        logical tick, doc, and apply event where the histories parted."""
        doc = self.server.doc_state(world.doc_id)
        path = self.server.recorder.on_divergence(
            world.doc_id, doc.oracle, world.twin,
            detail=f"twin check: {detail}")
        # Budget already spent on an earlier divergence this run: point
        # at the bundle that WAS written instead of printing None.
        return path or next(
            (p for p in self.server.recorder.bundle_paths
             if "divergence" in p), None)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--docs", type=int, default=200)
    ap.add_argument("--agents", type=int, default=3)
    ap.add_argument("--ticks", type=int, default=60)
    ap.add_argument("--events-per-tick", type=int, default=48)
    ap.add_argument("--zipf", type=float, default=1.1)
    ap.add_argument("--fault-rate", type=float, default=0.10)
    ap.add_argument("--local-prob", type=float, default=0.25)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--lanes", type=int, default=16)
    ap.add_argument("--engine", default="flat",
                    help="registry engine backing the lane batches "
                         "(any engine with a serve backend: flat, "
                         "rle-lanes-mixed)")
    ap.add_argument("--device", action="store_true",
                    help="run on the default jax backend (TPU when the "
                         "tunnel is up) instead of forcing CPU — the "
                         "perf/when_up_r7.sh on-silicon serve smoke")
    d = ServeConfig()
    ap.add_argument("--wire", default=d.wire_format,
                    choices=("row", "columnar"),
                    help="replication protocol generation: per-event "
                         "row frames (v1) or windowed doc-multiplexed "
                         "columnar frames (v2)")
    ap.add_argument("--ckpt", default=d.ckpt_format,
                    choices=("full", "delta"),
                    help="eviction checkpoints: full O(doc) snapshots "
                         "or CRC-chained O(new ops) deltas")
    ap.add_argument("--workload", default="scatter",
                    choices=("scatter", "typing"),
                    help="agent edit shape: uniform-random positions "
                         "or cursor-based typing runs")
    ap.add_argument("--pipeline-ticks", type=int, default=d.pipeline_ticks,
                    help="host/device tick pipelining depth: 2 = "
                         "double-buffered (stage the next tick's host "
                         "work while the device step is in flight), "
                         "1 = the serial loop; logical streams are "
                         "byte-identical at any depth")
    ap.add_argument("--train-ticks", type=int, default=d.train_ticks,
                    help="device tick-train length: T > 1 buffers T "
                         "ticks' op tensors + prefill scatters and "
                         "replays them as ONE jitted lax.scan program "
                         "(flat engine, device prefill only; lengths "
                         "pad to powers of two so steady state never "
                         "recompiles); logical streams are "
                         "byte-identical at any length")
    ap.add_argument("--host-prefill", action="store_true",
                    help="disable device-resident prefill: round-trip "
                         "the full by-order logs through host numpy "
                         "every tick (the pre-ISSUE-14 path; logical "
                         "streams are byte-identical either way — this "
                         "is the probe's baseline arm)")
    ap.add_argument("--sanitize-pipeline", action="store_true",
                    help="pipeline aliasing sanitizer: CRC-fingerprint "
                         "each in-flight tick's op tensors at dispatch "
                         "and re-check at the staged sync — a host "
                         "write racing the device step fails naming "
                         "tick/shard/array (PERF.md §18)")
    ap.add_argument("--nagle-txns", type=int, default=d.nagle_txns,
                    help="columnar-wire Nagle window: flush a doc's "
                         "outbox once it holds this many txns")
    ap.add_argument("--nagle-rounds", type=int, default=d.nagle_rounds,
                    help="...or once it has waited this many ticks "
                         "(smaller = lower op age, more frame "
                         "overhead; see perf/pipeline_probe.py sweep)")
    ap.add_argument("--lmax", type=int, default=d.lmax,
                    help="insert-chunk width of compiled serve steps "
                         "(the typing-workload fusion lever: larger "
                         "lmax folds longer typing runs per step)")
    ap.add_argument("--no-trace", action="store_true",
                    help="disable the obs/ event tracer (the overhead "
                         "probe's baseline arm)")
    ap.add_argument("--trace-path", default=None,
                    help="stream trace events to this JSONL file")
    ap.add_argument("--trace-rotate-bytes", type=int, default=None,
                    help="size-cap per trace segment; the stream rolls "
                         "to <path>.1, <path>.2, ... at the cap")
    ap.add_argument("--flow-sample-mod", type=int,
                    default=d.flow_sample_mod,
                    help="per-op provenance sampling: agents with "
                         "crc32(name) %% mod == 0 get end-to-end "
                         "flow.* span events (1 = every span, the "
                         "conservation-audit mode; 0 = off)")
    ap.add_argument("--profile-dir", default=None,
                    help="opt-in jax.profiler capture directory "
                         "(ticks 1..profile_ticks)")
    ap.add_argument("--journal-dir", default=None,
                    help="write-ahead op journal directory (ISSUE 16): "
                         "every admitted input is logged before it can "
                         "mutate state; a crashed server recovers by "
                         "re-executing the log")
    ap.add_argument("--journal-fsync-ticks", type=int,
                    default=d.journal_fsync_ticks,
                    help="fsync the journal every N logical ticks "
                         "(1 = every tick boundary)")
    ap.add_argument("--byzantine", type=float, default=0.0,
                    metavar="RATE",
                    help="byzantine agent class: ship this many "
                         "malformed/corrupt/replayed frames per tick "
                         "(fraction of events-per-tick); every one "
                         "must be refused typed or absorbed as a dup, "
                         "never panic the tick loop")
    ap.add_argument("--flash-crowd", default=None, metavar="TICK:DOC",
                    help="from tick TICK on, remap 90%% of each tick's "
                         "events onto doc index DOC — lane overflow + "
                         "residency thrash on one hot doc")
    ap.add_argument("--crash-at", default=None, metavar="PHASE:TICK",
                    help="crash-injection harness (serve/chaos): kill "
                         "the server at the named phase of loadgen "
                         "tick TICK, recover from the journal, resume, "
                         "and compare logical streams against an "
                         "uncrashed same-seed twin. Phases: post-admit, "
                         "post-dispatch, mid-ckpt, mid-journal")
    ap.add_argument("--verbose", action="store_true")
    a = ap.parse_args(argv)

    flash_crowd = None
    if a.flash_crowd is not None:
        tick_s, _, doc_s = a.flash_crowd.partition(":")
        flash_crowd = (int(tick_s), int(doc_s))

    import jax

    if not a.device:
        jax.config.update("jax_platforms", "cpu")

    if a.crash_at is not None:
        # The chaos harness owns the whole run (victim, recovery,
        # resume, twin); it needs a journal, and allocates its own
        # workdir when --journal-dir is not given.
        from .chaos import PHASES, run_crash_scenario
        phase, _, tick_s = a.crash_at.partition(":")
        if phase not in PHASES or not tick_s:
            raise SystemExit(f"--crash-at wants PHASE:TICK with PHASE in "
                             f"{PHASES}, got {a.crash_at!r}")
        cell = run_crash_scenario(
            phase, int(tick_s), ticks=a.ticks, docs=a.docs,
            agents_per_doc=a.agents, events_per_tick=a.events_per_tick,
            seed=a.seed, fault_rate=a.fault_rate, num_shards=a.shards,
            lanes_per_shard=a.lanes, ckpt_format=a.ckpt,
            fsync_ticks=a.journal_fsync_ticks, byzantine=a.byzantine,
            flash_crowd=flash_crowd, train_ticks=a.train_ticks)
        import json

        cell.pop("report")
        print(json.dumps(cell, indent=1, default=str))
        ok = (cell["identical"] and cell["converged"]
              and cell["at_recovery_audit"]["audit_ok"]
              and cell["final_audit"]["audit_ok"])
        raise SystemExit(0 if ok else 1)

    cfg = ServeConfig(engine=a.engine, num_shards=a.shards,
                      lanes_per_shard=a.lanes,
                      wire_format=a.wire, ckpt_format=a.ckpt,
                      pipeline_ticks=a.pipeline_ticks,
                      train_ticks=a.train_ticks,
                      device_prefill=not a.host_prefill,
                      sanitize_pipeline=a.sanitize_pipeline,
                      nagle_txns=a.nagle_txns,
                      nagle_rounds=a.nagle_rounds, lmax=a.lmax,
                      trace=not a.no_trace, trace_path=a.trace_path,
                      trace_rotate_bytes=a.trace_rotate_bytes,
                      flow_sample_mod=a.flow_sample_mod,
                      profile_dir=a.profile_dir,
                      journal_dir=a.journal_dir,
                      journal_fsync_ticks=a.journal_fsync_ticks)
    gen = ServeLoadGen(docs=a.docs, agents_per_doc=a.agents, ticks=a.ticks,
                       events_per_tick=a.events_per_tick, zipf_alpha=a.zipf,
                       fault_rate=a.fault_rate, local_prob=a.local_prob,
                       seed=a.seed, cfg=cfg, verbose=a.verbose,
                       workload=a.workload, byzantine=a.byzantine,
                       flash_crowd=flash_crowd)
    report = gen.run()
    import json

    print(json.dumps(report, indent=1, default=str))
    if not report["converged"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()

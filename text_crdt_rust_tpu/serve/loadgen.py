"""Deterministic closed-loop load generator + convergence checker.

N documents x M agents: every agent holds a real oracle replica of its
document, edits it locally, gossips with its sibling agents, and ships
its history to the server as binary TXNS frames through a seeded
`net/faults.py` channel (drops / dups / reorders / truncations /
bit-flips). A seeded Zipf popularity skew concentrates traffic on hot
documents so the cold tail actually evicts. Local server-side edits mix
in with probability ``local_prob`` (they also *touch* evicted docs,
driving the restore path).

Ground truth: one always-resident **twin** oracle per doc consumes the
exact same txn set over a clean channel (plus the server's own edits,
observed via ``export_since``). The run converges iff, after the lossy
phase plus the server-driven REQUEST/re-delivery cycle, every document
is bit-identical to its twin (string AND portable state digest) and
every device lane is bit-identical to its host oracle — the ISSUE-3
acceptance bar, CLI-runnable:

    python -m text_crdt_rust_tpu.serve.loadgen --docs 200 --agents 3 \\
        --ticks 60 --fault-rate 0.10 --seed 7
"""
from __future__ import annotations

import argparse
import random
import time
from typing import Dict, List, Optional, Set, Tuple

from ..common import RemoteTxn, txn_len
from ..config import ServeConfig
from ..models.oracle import ListCRDT
from ..models.sync import agent_watermarks, export_txns_since, state_digest
from ..net import codec
from ..net.faults import FaultSpec, FaultyChannel
from ..parallel.causal import CausalBuffer
from .admission import AdmissionError
from .server import DocServer

TXNS_PER_FRAME = 4


class _DocWorld:
    """Generation-side state for one document: agent replicas, their
    fault channels, the global txn log (generation order == a causal
    order), and the clean twin."""

    def __init__(self, doc_id: str, agents: List[str], seed: int,
                 spec: FaultSpec):
        self.doc_id = doc_id
        self.agents = agents
        self.replicas: Dict[str, ListCRDT] = {}
        self.replica_ids: Dict[str, int] = {}
        self.marks: Dict[str, int] = {a: 0 for a in agents}
        self.applied: Dict[str, Set[Tuple[str, int]]] = {
            a: set() for a in agents}
        self.channels: Dict[str, FaultyChannel] = {}
        for i, a in enumerate(agents):
            doc = ListCRDT()
            self.replicas[a] = doc
            self.replica_ids[a] = doc.get_or_create_agent_id(a)
            self.channels[a] = FaultyChannel(
                spec=spec, seed=seed * 10007 + i)
        self.txns: List[RemoteTxn] = []   # generation order, deduped
        self.txn_keys: Set[Tuple[str, int]] = set()
        self.twin = ListCRDT()
        self.twin_buffer = CausalBuffer()
        self.server_mark = 0

    def record(self, txns: List[RemoteTxn]) -> List[RemoteTxn]:
        fresh = []
        for t in txns:
            key = (t.id.agent, t.id.seq)
            if key not in self.txn_keys:
                self.txn_keys.add(key)
                self.txns.append(t)
                fresh.append(t)
        return fresh

    def feed_twin(self, txns: List[RemoteTxn]) -> None:
        for t in self.twin_buffer.add_all(txns):
            self.twin.apply_remote_txn(t)

    def gossip(self, rng: random.Random, agent: str) -> None:
        """The agent merges a random prefix of the doc's foreign
        history (generation order is causal, so any prefix is safe —
        the `perf/fuzz_mixed_fast.py` gen_stream recipe)."""
        doc = self.replicas[agent]
        seen = self.applied[agent]
        upto = rng.randint(0, len(self.txns))
        for t in self.txns[:upto]:
            key = (t.id.agent, t.id.seq)
            if t.id.agent != agent and key not in seen:
                seen.add(key)
                doc.apply_remote_txn(t)

    def agent_edit(self, rng: random.Random, agent: str,
                   edits: int) -> List[RemoteTxn]:
        """A burst of local edits on the agent's replica; returns the
        NEW txns exported since the agent's last export mark."""
        doc = self.replicas[agent]
        aid = self.replica_ids[agent]
        for _ in range(edits):
            n = len(doc)
            if n == 0 or rng.random() < 0.55:
                pos = rng.randint(0, n)
                doc.local_insert(aid, pos, "".join(
                    rng.choice("abcdefgh") for _ in range(rng.randint(1, 4))))
            else:
                pos = rng.randint(0, n - 1)
                doc.local_delete(aid, pos, min(rng.randint(1, 4), n - pos))
        out = export_txns_since(doc, self.marks[agent])
        self.marks[agent] = doc.get_next_order()
        return out


class ServeLoadGen:
    """Seeded closed loop against one ``DocServer``."""

    def __init__(self, *, docs: int = 200, agents_per_doc: int = 3,
                 ticks: int = 60, events_per_tick: int = 48,
                 zipf_alpha: float = 1.1, fault_rate: float = 0.10,
                 local_prob: float = 0.25, seed: int = 7,
                 cfg: Optional[ServeConfig] = None,
                 resync_every: int = 4, verbose: bool = False):
        self.rng = random.Random(seed)
        self.cfg = cfg or ServeConfig()
        self.server = DocServer(self.cfg)
        self.ticks = ticks
        self.events_per_tick = events_per_tick
        self.local_prob = local_prob
        self.resync_every = max(1, resync_every)
        self.verbose = verbose
        spec = FaultSpec.all(fault_rate)
        self.worlds: List[_DocWorld] = []
        for d in range(docs):
            doc_id = f"doc{d:04d}"
            names = [f"d{d:04d}.a{i}" for i in range(agents_per_doc)]
            self.worlds.append(_DocWorld(doc_id, names,
                                         seed * 131 + d, spec))
            self.server.admit_doc(doc_id)
        # Zipf popularity over docs (rank 0 hottest).
        self.weights = [1.0 / (i + 1) ** zipf_alpha for i in range(docs)]
        self.rejections = 0
        self.ops_offered = 0

    # -- traffic -------------------------------------------------------------

    def _ship(self, world: _DocWorld, agent: str,
              txns: List[RemoteTxn], faulty: bool = True) -> None:
        """Encode txns into frames and deliver them to the server,
        optionally through the agent's fault channel."""
        if not txns:
            return
        frames = [codec.encode_txns(txns[i:i + TXNS_PER_FRAME])
                  for i in range(0, len(txns), TXNS_PER_FRAME)]
        if faulty:
            ch = world.channels[agent]
            for f in frames:
                ch.send(f)
            frames = ch.drain()
        for f in frames:
            try:
                self.server.submit_frame(world.doc_id, f)
            except AdmissionError:
                self.rejections += 1

    def _gossip_digests(self, faulty: bool) -> None:
        """Every agent advertises its replica's watermarks + portable
        state digest — the anti-entropy signal that lets the server see
        gaps whose every frame was dropped (a peer it has literally
        never heard from)."""
        for world in self.worlds:
            for agent in world.agents:
                replica = world.replicas[agent]
                frame = codec.encode_digest(agent_watermarks(replica),
                                            state_digest(replica))
                if faulty:
                    ch = world.channels[agent]
                    ch.send(frame)
                    frames = ch.drain()
                else:
                    frames = [frame]
                for f in frames:
                    try:
                        self.server.submit_frame(world.doc_id, f)
                    except AdmissionError:
                        self.rejections += 1

    def _resync(self, faulty: bool) -> int:
        """Answer the server's owed REQUEST frames from the generation
        log; returns how many docs still had wants."""
        wanting = 0
        for world in self.worlds:
            req = self.server.poll_request_frame(world.doc_id)
            if req is None:
                continue
            wanting += 1
            kind, wants, _ = codec.decode_frame(req)
            assert kind == codec.KIND_REQUEST
            owed = [t for t in world.txns
                    if t.id.agent in wants
                    and t.id.seq + txn_len(t) > wants[t.id.agent]]
            # Deliver via the hottest agent's channel (any path works;
            # the server dedups) — clean in the final drain.
            self._ship(world, world.agents[0], owed, faulty=faulty)
        return wanting

    def _observe_server_edits(self) -> None:
        """Feed the twins whatever new history the server produced
        (its own local edits, interleaved with merges)."""
        for world in self.worlds:
            doc = self.server.doc_state(world.doc_id)
            if not doc.resident:
                continue
            nxt = doc.oracle.get_next_order()
            if nxt > world.server_mark:
                txns = self.server.export_since(world.doc_id,
                                                world.server_mark)
                world.server_mark = nxt
                world.feed_twin(txns)

    def run_tick(self, tick_index: int) -> Dict[str, float]:
        picks = self.rng.choices(range(len(self.worlds)),
                                 weights=self.weights,
                                 k=self.events_per_tick)
        for d in picks:
            world = self.worlds[d]
            if self.rng.random() < self.local_prob:
                # A server-side edit; position bounded by the doc's
                # TWIN length — a server-state-independent source, so
                # one seed generates byte-identical traffic on every
                # lane backend (the cross-backend bit-identity twin
                # runs of ISSUE 4 depend on it; a position the server
                # hasn't caught up to yet is validity-checked at apply
                # time and dropped, deterministically). The edit still
                # *touches* evicted docs, driving the restore path.
                live = len(world.twin)
                pos = self.rng.randint(0, live)
                ins = "".join(self.rng.choice("xyzw")
                              for _ in range(self.rng.randint(1, 3)))
                try:
                    self.server.submit_local(world.doc_id, "server-editor",
                                             pos, 0, ins)
                    self.ops_offered += len(ins)
                except AdmissionError:
                    self.rejections += 1
            else:
                agent = self.rng.choice(world.agents)
                world.gossip(self.rng, agent)
                txns = world.agent_edit(self.rng, agent,
                                        self.rng.randint(1, 3))
                fresh = world.record(txns)
                world.feed_twin(fresh)
                self.ops_offered += sum(txn_len(t) for t in fresh)
                self._ship(world, agent, txns, faulty=True)
        if (tick_index + 1) % self.resync_every == 0:
            self._gossip_digests(faulty=True)
            self._resync(faulty=True)
        # Server-authored history reaches the twins in the final
        # observation pass, NOT per tick: per-tick observation is gated
        # on residency, which differs across lane backends — it would
        # leak backend state into the twin lengths that seed the next
        # tick's traffic (see run_tick's position source).
        return self.server.tick()

    # -- the full run --------------------------------------------------------

    def run(self) -> Dict[str, object]:
        t0 = time.perf_counter()
        applied = 0
        steps = 0
        for i in range(self.ticks):
            stats = self.run_tick(i)
            applied += stats["ops_applied"]
            steps += stats["steps"]
            if self.verbose and (i + 1) % 10 == 0:
                rc = self.server.residency.resident_counts()
                print(f"tick {i + 1}/{self.ticks}: applied {applied} "
                      f"item-ops, {rc['docs_in_lane']} in-lane / "
                      f"{rc['docs_evicted']} evicted", flush=True)
        loop_wall = time.perf_counter() - t0

        # Final drain: clean digests + re-delivery until the server owes
        # no REQUESTs and every queue is empty — the anti-entropy cycle
        # that recovers everything the fault channels mangled.
        drain_rounds = 0
        self._gossip_digests(faulty=False)
        for drain_rounds in range(1, 64):
            wanting = self._resync(faulty=False)
            self.server.tick()
            busy = any(d.events for d in self.server.router.docs.values())
            if not wanting and not busy:
                break
        self.server.drain()
        self._observe_server_edits()

        converged, mismatches = self.verify()
        wall = time.perf_counter() - t0
        stats = self.server.stats()
        report = {
            "converged": converged,
            "mismatches": mismatches[:8],
            "docs": len(self.worlds),
            "item_ops_applied": int(applied),
            "device_ticks_wall_s": round(loop_wall, 3),
            "ops_per_sec": round(applied / loop_wall, 1) if loop_wall else 0,
            "drain_rounds": drain_rounds,
            "wall_s": round(wall, 3),
            "rejected_submissions": self.rejections,
            "latency_us": self.server.latency_summary(),
            "tick_ms": self.server.tick_summary(),
            "engine": self.cfg.engine,
            "server": stats,
        }
        return report

    def verify(self) -> Tuple[bool, List[str]]:
        """Every doc bit-identical to its twin; every lane bit-identical
        to its oracle. Returns (ok, mismatch descriptions)."""
        bad: List[str] = []
        for world in self.worlds:
            # Docs evicted at run end: restore, then feed the twin any
            # server-authored history it hasn't observed yet (the doc
            # may have been checkpointed right after its last edit).
            self.server.ensure_resident(world.doc_id)
        self._observe_server_edits()
        for world in self.worlds:
            # The twin must itself have fully converged (a generation
            # bug otherwise — every generated txn was fed cleanly).
            if world.twin_buffer.pending:
                bad.append(f"{world.doc_id}: twin buffer still holds "
                           f"{world.twin_buffer.pending} txns")
                continue
            got = self.server.doc_string(world.doc_id)
            want = world.twin.to_string()
            if got != want:
                bad.append(f"{world.doc_id}: content diverged "
                           f"({len(got)} vs {len(want)} chars)")
                continue
            doc = self.server.doc_state(world.doc_id)
            if state_digest(doc.oracle) != state_digest(world.twin):
                bad.append(f"{world.doc_id}: state digest diverged")
                continue
            if not self.server.verify_doc(world.doc_id):
                bad.append(f"{world.doc_id}: device lane != host oracle")
        return not bad, bad


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--docs", type=int, default=200)
    ap.add_argument("--agents", type=int, default=3)
    ap.add_argument("--ticks", type=int, default=60)
    ap.add_argument("--events-per-tick", type=int, default=48)
    ap.add_argument("--zipf", type=float, default=1.1)
    ap.add_argument("--fault-rate", type=float, default=0.10)
    ap.add_argument("--local-prob", type=float, default=0.25)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--lanes", type=int, default=16)
    ap.add_argument("--engine", default="flat",
                    help="registry engine backing the lane batches "
                         "(any engine with a serve backend: flat, "
                         "rle-lanes-mixed)")
    ap.add_argument("--device", action="store_true",
                    help="run on the default jax backend (TPU when the "
                         "tunnel is up) instead of forcing CPU — the "
                         "perf/when_up_r7.sh on-silicon serve smoke")
    ap.add_argument("--verbose", action="store_true")
    a = ap.parse_args(argv)

    import jax

    if not a.device:
        jax.config.update("jax_platforms", "cpu")
    cfg = ServeConfig(engine=a.engine, num_shards=a.shards,
                      lanes_per_shard=a.lanes)
    gen = ServeLoadGen(docs=a.docs, agents_per_doc=a.agents, ticks=a.ticks,
                       events_per_tick=a.events_per_tick, zipf_alpha=a.zipf,
                       fault_rate=a.fault_rate, local_prob=a.local_prob,
                       seed=a.seed, cfg=cfg, verbose=a.verbose)
    report = gen.run()
    import json

    print(json.dumps(report, indent=1, default=str))
    if not report["converged"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()

"""Admission control for the document server: typed backpressure.

The serving contract (ISSUE 3, mirroring `net/`'s failure philosophy):
overload and bad input are *protocol outcomes*, never crashes. Every
refusal is an ``AdmissionError`` with a machine-readable ``reason`` the
caller can branch on and the server counts:

- ``doc-unknown``     — traffic for a doc id the server never admitted;
- ``queue-full``      — the per-doc or global pending-event bound hit
                        (the caller backs off and retries; nothing was
                        enqueued);
- ``frame-rejected``  — undecodable wire bytes (wraps ``CodecError``)
                        or a structurally-oversized op (``max_txn_len``,
                        which bounds the compiled steps one event can
                        cost a batch tick);
- ``rate-limited``    — the submitting agent's token bucket is dry
                        (one hot client must not starve a lane batch).

Token buckets run on the server's logical tick clock — deterministic
under test, like `net/session.py`'s backoff (no wall-clock anywhere in
the admission decision).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from ..utils.metrics import Counters

REASON_DOC_UNKNOWN = "doc-unknown"
REASON_QUEUE_FULL = "queue-full"
REASON_FRAME_REJECTED = "frame-rejected"
REASON_RATE_LIMITED = "rate-limited"

_REASONS = (REASON_DOC_UNKNOWN, REASON_QUEUE_FULL,
            REASON_FRAME_REJECTED, REASON_RATE_LIMITED)


class AdmissionError(RuntimeError):
    """A submission was refused; ``reason`` is one of the module's
    ``REASON_*`` constants. Recoverable by construction: a refused call
    enqueues NOTHING (multi-txn frames are checked whole before any txn
    enters — all-or-nothing); the only state a refusal may have touched
    is rate-bucket tokens consumed by the checked prefix."""

    def __init__(self, reason: str, detail: str):
        assert reason in _REASONS, reason
        self.reason = reason
        super().__init__(f"{reason}: {detail}")


@dataclass
class TokenBucket:
    """Per-agent rate limiter on the logical tick clock.

    ``capacity`` tokens maximum, ``refill`` added per elapsed tick,
    lazily credited at ``take`` time (no per-tick sweep over agents).
    One token pays for one item (char inserted / item deleted), so cost
    tracks the device work a submission implies, not its frame count.
    """

    capacity: int
    refill: int
    tokens: float = 0.0
    last_tick: int = 0

    def __post_init__(self) -> None:
        self.tokens = float(self.capacity)

    def take(self, cost: int, tick: int) -> bool:
        if tick > self.last_tick:
            self.tokens = min(float(self.capacity),
                              self.tokens + self.refill
                              * (tick - self.last_tick))
            self.last_tick = tick
        if cost > self.tokens:
            return False
        self.tokens -= cost
        return True


class AdmissionControl:
    """Bounded queues + per-agent token buckets for one server.

    The router consults this before any state changes; a refusal
    therefore never leaves a half-enqueued event. Counters:
    ``admitted`` (events), ``admitted_items`` (chars/targets), and one
    ``rejected_<reason>`` per refusal class.
    """

    def __init__(self, *, max_queue_per_doc: int, max_queue_global: int,
                 max_txn_len: int, rate_capacity: int = 0,
                 rate_refill: int = 0,
                 counters: Optional[Counters] = None,
                 tracer=None):
        assert max_queue_per_doc >= 1 and max_queue_global >= 1
        self.max_queue_per_doc = max_queue_per_doc
        self.max_queue_global = max_queue_global
        self.max_txn_len = max_txn_len
        self.rate_capacity = rate_capacity
        self.rate_refill = rate_refill
        self.counters = counters if counters is not None else Counters()
        self.tracer = tracer
        self.global_pending = 0
        self._buckets: Dict[str, TokenBucket] = {}

    def _reject(self, reason: str, detail: str, *,
                doc: Optional[str] = None, agent: Optional[str] = None,
                seq: Optional[int] = None,
                n: Optional[int] = None) -> AdmissionError:
        self.counters.incr(f"rejected_{reason.replace('-', '_')}")
        if self.tracer is not None:
            # The offending (agent, seq) range rides the reject event
            # (ISSUE 11 satellite) — today's triage gets the op's
            # identity, not just the reason class.  Absent for refusals
            # with no decodable span (corrupt frames, unknown docs).
            span = {k: v for k, v in (("doc", doc), ("agent", agent),
                                      ("seq", seq), ("n", n))
                    if v is not None}
            self.tracer.event("admission.reject", reason=reason, **span)
        return AdmissionError(reason, detail)

    def reject_frame(self, detail: str, *, doc: Optional[str] = None,
                     agent: Optional[str] = None,
                     seq: Optional[int] = None,
                     n: Optional[int] = None) -> AdmissionError:
        """Typed refusal for undecodable wire bytes (the router calls
        this from its ``CodecError`` handler so the count lives here);
        span kwargs carry the offending op when the decoder could name
        one (txn-level validation failures)."""
        return self._reject(REASON_FRAME_REJECTED, detail, doc=doc,
                            agent=agent, seq=seq, n=n)

    def reject_unknown_doc(self, doc_id: str) -> AdmissionError:
        return self._reject(REASON_DOC_UNKNOWN,
                            f"doc {doc_id!r} was never admitted")

    def admit(self, doc_id: str, agent: str, items: int,
              doc_pending: int, tick: int,
              seq: Optional[int] = None) -> None:
        """Gate AND count one event. Single-event submission path."""
        self.check(doc_id, agent, items, doc_pending, tick, seq=seq)
        self.count_admitted(items)

    def count_admitted(self, items: int) -> None:
        self.counters.incr("admitted")
        self.counters.incr("admitted_items", items)

    def check(self, doc_id: str, agent: str, items: int,
              doc_pending: int, tick: int,
              seq: Optional[int] = None) -> None:
        """Gate one event (``items`` = its item count) WITHOUT counting
        it admitted — multi-event frames check every event first, then
        count+enqueue, so a mid-frame refusal enqueues nothing (rate
        tokens of the checked prefix are consumed; queue/count state is
        untouched). Raises a typed ``AdmissionError``.  ``seq`` (the
        span start for remote txns) rides the reject trace event."""
        span = dict(doc=doc_id, agent=agent, seq=seq, n=items)
        if items > self.max_txn_len:
            raise self._reject(
                REASON_FRAME_REJECTED,
                f"event of {items} items exceeds max_txn_len "
                f"{self.max_txn_len} (split the edit)", **span)
        if doc_pending >= self.max_queue_per_doc:
            raise self._reject(
                REASON_QUEUE_FULL,
                f"doc {doc_id!r} has {doc_pending} pending events "
                f"(bound {self.max_queue_per_doc})", **span)
        if self.global_pending >= self.max_queue_global:
            raise self._reject(
                REASON_QUEUE_FULL,
                f"{self.global_pending} events pending server-wide "
                f"(bound {self.max_queue_global})", **span)
        if self.rate_capacity > 0:
            bucket = self._buckets.get(agent)
            if bucket is None:
                bucket = self._buckets[agent] = TokenBucket(
                    self.rate_capacity, self.rate_refill)
            if not bucket.take(items, tick):
                raise self._reject(
                    REASON_RATE_LIMITED,
                    f"agent {agent!r} exhausted its token bucket "
                    f"({self.rate_capacity} cap, {self.rate_refill}"
                    f"/tick)", **span)

    def enqueued(self) -> None:
        self.global_pending += 1
        self.counters.hiwater("queue_high_water", self.global_pending)

    def dequeued(self, n: int = 1) -> None:
        assert self.global_pending >= n
        self.global_pending -= n

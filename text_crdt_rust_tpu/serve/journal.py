"""Write-ahead op journal for the serve tier (ISSUE 16 tentpole,
part 1).

A ``DocServer`` is an in-memory process: between checkpoints, a crash
loses every resident doc and every admitted-but-unapplied op.  The
journal closes that window as a **full input log**: every
state-mutating call that crosses the admission edge is recorded, and
``DocServer.recover()`` re-drives the normal admission → buffer →
batcher path with the same inputs in the same order.  The server is a
deterministic state machine, so re-execution reproduces the crashed
process byte-for-byte — including residency trajectory, local-edit
position resolution, and the in-flight pipelined ticks that were
dispatched but never synced (their inputs are in the log; the replayed
ticks re-derive them).  Record kinds:

- ``ADMIT``  — a doc was admitted (body: doc id).  Replaying admits in
  order reproduces the router's least-loaded shard assignment and its
  dict iteration order, which the batcher's drain loop depends on.
- ``TXNS``   — fresh remote txns accepted for one doc.  The body is a
  complete ``net/columnar`` ``TXNS_MUX`` frame (self-CRC'd, deflated
  when that wins) — the same bytes the wire speaks, so the journal
  format inherits the codec's torn/corrupt taxonomy for free.
  Duplicate deliveries are NOT journaled: a dup is a no-op on buffer
  state, so skipping it preserves the exact state trajectory at a
  fraction of the bytes.
- ``LOCAL``  — a server-side local edit with its per-doc submission
  ordinal (an exactly-once audit stamp: replay asserts the rebuilt
  ``DocState.local_seen`` agrees with every record's ordinal).
- ``TICK``   — a logical tick boundary; the fsync point, and the
  replay pacing marker (recovery calls ``server.tick()`` here so the
  apply cadence — and therefore local-edit interleaving — reproduces).
- ``FRAME``  — a control frame on the per-doc lane, raw bytes.
  REQUEST frames touch the residency LRU clock and DIGEST frames
  advance ``peer_marks``; both steer later traffic, so the input log
  must carry them for the re-execution to stay exact.
- ``POLL``   — a ``poll_request_frame`` call (body: doc id).  Polling
  folds oracle watermarks into ``known_marks``, which narrows future
  REQUEST wants — a mutation, so it is an input.

Records are framed per segment as::

    varint(global_seq) | kind:1 | varint(len(body)) | body | crc32c:4LE

with the CRC chained record-to-record (each record's CRC seeds the
next) so a bit-flip anywhere poisons the whole suffix, exactly like
``utils.checkpoint``'s chain CRCs.  Segments are per shard
(``shard<k>.<seg:06d>.tcrj``) with a magic header; the chain restarts
at each segment.  Appends are flushed immediately (process-crash
durability); ``os.fsync`` runs at TICK markers every
``fsync_ticks`` ticks (power-loss durability), which is the knob the
``recovery`` ledger cell prices.

``scan`` is the reader: it keeps the valid prefix of each shard's
stream (the ``obs.load_events`` discipline) and reports every refused
suffix as a typed ``JournalError`` naming segment and byte offset —
corruption is never silent.  Records from all shards merge into one
total order on the global sequence number, which is what
``DocServer.recover()`` replays.

``repair`` makes the disk agree with the scan: every refused suffix is
truncated/quarantined (to ``<segment>.refused`` sidecars — forensic
bytes are moved, never destroyed) so a reopened journal's NEW segments
can never be dropped behind a stale torn segment on the next scan.
``Journal.__init__`` runs it on every reopen, which is what makes a
crash → recover → crash → recover sequence lossless for the records
journaled between the crashes.
"""
from __future__ import annotations

import os
from typing import IO, Dict, List, NamedTuple, Optional, Sequence, Tuple

from ..utils.integrity import crc32c

JOURNAL_MAGIC = b"TCRJ"
JOURNAL_VERSION = 1

# Record kinds (one byte on the wire).
REC_ADMIT = 1
REC_TXNS = 2
REC_LOCAL = 3
REC_TICK = 4
REC_FRAME = 5
REC_POLL = 6

_KIND_NAMES = {REC_ADMIT: "admit", REC_TXNS: "txns",
               REC_LOCAL: "local", REC_TICK: "tick",
               REC_FRAME: "frame", REC_POLL: "poll"}

# Rotate a shard's segment once it crosses this many bytes.  Rotation
# bounds the blast radius of a corrupt record (only one segment's
# suffix is lost) and keeps recovery's read buffers small.
DEFAULT_ROTATE_BYTES = 1 << 20


class JournalError(Exception):
    """Typed refusal of a journal segment suffix.  Carries the segment
    path, the byte offset of the first refused record, and the reason —
    so a torn tail is distinguishable from a bit-flip in tests and in
    the flight recorder."""

    def __init__(self, segment: str, offset: int, reason: str):
        super().__init__(f"{segment} @ {offset}: {reason}")
        self.segment = segment
        self.offset = offset
        self.reason = reason


class JournalRecord(NamedTuple):
    seq: int          # global monotonic sequence number
    shard: int        # shard whose segment held the record
    kind: int         # REC_* constant
    body: bytes       # kind-specific payload
    segment: str      # segment path (diagnostics)
    offset: int       # byte offset of the record in its segment


def _write_varint(out: bytearray, value: int) -> None:
    assert value >= 0
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, cur: int, end: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if cur >= end:
            raise ValueError("varint truncated")
        b = buf[cur]
        cur += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, cur
        shift += 7
        if shift > 63:
            raise ValueError("varint overflow")


def _pack_str(out: bytearray, s: str) -> None:
    data = s.encode("utf-8")
    _write_varint(out, len(data))
    out += data


def _unpack_str(buf: bytes, cur: int, end: int) -> Tuple[str, int]:
    n, cur = _read_varint(buf, cur, end)
    if cur + n > end:
        raise ValueError("string truncated")
    return buf[cur:cur + n].decode("utf-8"), cur + n


def encode_local_body(doc_id: str, agent: str, pos: int, del_len: int,
                      ins_content: str, ordinal: int) -> bytes:
    out = bytearray()
    _pack_str(out, doc_id)
    _pack_str(out, agent)
    _write_varint(out, pos)
    _write_varint(out, del_len)
    _pack_str(out, ins_content)
    _write_varint(out, ordinal)
    return bytes(out)


def decode_local_body(body: bytes) -> Tuple[str, str, int, int, str, int]:
    end = len(body)
    doc_id, cur = _unpack_str(body, 0, end)
    agent, cur = _unpack_str(body, cur, end)
    pos, cur = _read_varint(body, cur, end)
    del_len, cur = _read_varint(body, cur, end)
    ins, cur = _unpack_str(body, cur, end)
    ordinal, cur = _read_varint(body, cur, end)
    return doc_id, agent, pos, del_len, ins, ordinal


def decode_frame_body(body: bytes) -> Tuple[str, bytes]:
    doc_id, cur = _unpack_str(body, 0, len(body))
    return doc_id, body[cur:]


def _segment_name(shard: int, index: int) -> str:
    return f"shard{shard}.{index:06d}.tcrj"


def _segment_header(shard: int) -> bytes:
    out = bytearray(JOURNAL_MAGIC)
    out.append(JOURNAL_VERSION)
    _write_varint(out, shard)
    return bytes(out)


class _ShardLog:
    """One shard's open segment: file handle, CRC chain state, and the
    rotation counter."""

    __slots__ = ("shard", "index", "path", "fh", "crc", "size")

    def __init__(self, shard: int):
        self.shard = shard
        self.index = 0
        self.path: Optional[str] = None
        self.fh: Optional[IO[bytes]] = None
        self.crc = 0
        self.size = 0


class Journal:
    """Append-side of the write-ahead journal.  One instance per
    ``DocServer``; ``None`` when ``ServeConfig.journal_dir`` is unset
    (journaling off — the shipped default for latency benches)."""

    def __init__(self, journal_dir: str, num_shards: int, *,
                 fsync_ticks: int = 1,
                 rotate_bytes: int = DEFAULT_ROTATE_BYTES,
                 counters=None, tracer=None):
        assert num_shards >= 1
        assert fsync_ticks >= 1
        self.dir = journal_dir
        self.num_shards = num_shards
        self.fsync_ticks = fsync_ticks
        self.rotate_bytes = rotate_bytes
        self.counters = counters
        self.tracer = tracer
        self._seq = 0
        self._suspended = 0
        self._closed = False
        os.makedirs(journal_dir, exist_ok=True)
        # Repair before anything else: truncate/quarantine any refused
        # suffix a crash left behind, so (a) the global sequence
        # continues past the last RECOVERABLE record (never reusing
        # sequence numbers) and (b) segments this reopen appends are
        # never dropped behind a stale torn segment on the next scan —
        # the double-crash data-loss hole.  The refusals stay loud:
        # counted and traced here, and ``DocServer.recover()`` folds
        # ``self.repair_errors`` into its replay stats and the flight
        # recorder.
        existing, self.repair_errors = repair(journal_dir)
        for err in self.repair_errors:
            self._count("journal_refusals")
            if self.tracer is not None:
                self.tracer.event("journal.repair", segment=err.segment,
                                  offset=err.offset, reason=err.reason)
        if existing:
            self._seq = existing[-1].seq + 1
        self._shards = [_ShardLog(s) for s in range(num_shards)]
        for log in self._shards:
            log.index = self._next_segment_index(log.shard)

    # -- plumbing ----------------------------------------------------

    def _next_segment_index(self, shard: int) -> int:
        prefix = f"shard{shard}."
        top = -1
        for name in sorted(os.listdir(self.dir)):
            if name.startswith(prefix) and name.endswith(".tcrj"):
                try:
                    top = max(top, int(name[len(prefix):-5]))
                except ValueError:
                    continue
        return top + 1

    def _open_segment(self, log: _ShardLog) -> None:
        log.path = os.path.join(self.dir, _segment_name(log.shard,
                                                        log.index))
        log.fh = open(log.path, "wb")
        header = _segment_header(log.shard)
        log.fh.write(header)
        log.crc = 0
        log.size = len(header)
        self._count("journal_bytes", len(header))
        if self.tracer is not None:
            self.tracer.event("journal.segment", shard=log.shard,
                              seg=log.index, path=log.path)

    def _count(self, name: str, n: int = 1) -> None:
        if self.counters is not None:
            self.counters.incr(name, n)

    def _append(self, shard: int, kind: int, body: bytes) -> None:
        if self._suspended or self._closed:
            return
        log = self._shards[shard]
        if log.fh is None:
            self._open_segment(log)
        rec = bytearray()
        _write_varint(rec, self._seq)
        rec.append(kind)
        _write_varint(rec, len(body))
        rec += body
        crc = crc32c(bytes(rec), log.crc)
        rec += crc.to_bytes(4, "little")
        log.fh.write(rec)
        # Flush every append: the OS page cache survives a process
        # crash, which is the failure mode the chaos harness models.
        # fsync (power loss) is paced separately by TICK markers.
        log.fh.flush()
        log.crc = crc
        log.size += len(rec)
        self._seq += 1
        self._count("journal_records")
        self._count("journal_bytes", len(rec))
        if kind == REC_TICK and log.size >= self.rotate_bytes:
            # fsync before the handle goes away: ``tick()``'s cadenced
            # fsync loop only sees OPEN handles, so a rotated-out
            # segment's tail would otherwise never be fsynced — a
            # power-loss hole at exactly the rotating tick.
            os.fsync(log.fh.fileno())
            self._count("journal_fsyncs")
            log.fh.close()
            log.fh = None
            log.index += 1

    # -- append API --------------------------------------------------

    def admit(self, shard: int, doc_id: str) -> None:
        self._append(shard, REC_ADMIT, doc_id.encode("utf-8"))

    def txns(self, shard: int, doc_id: str, txns: Sequence) -> None:
        """Record fresh (non-duplicate) remote txns accepted for one
        doc, as one mux frame."""
        if not txns:
            return
        from ..common import txn_len
        from ..net import columnar
        body = columnar.encode_mux([(doc_id, list(txns))])
        self._append(shard, REC_TXNS, body)
        self._count("journal_ops", sum(txn_len(t) for t in txns))

    def local(self, shard: int, doc_id: str, agent: str, pos: int,
              del_len: int, ins_content: str, ordinal: int) -> None:
        self._append(shard, REC_LOCAL,
                     encode_local_body(doc_id, agent, pos, del_len,
                                       ins_content, ordinal))
        self._count("journal_ops", del_len + len(ins_content))

    def frame(self, shard: int, doc_id: str, data: bytes) -> None:
        """Record a control frame (REQUEST/DIGEST) verbatim: replay
        re-submits the same bytes through ``submit_frame``."""
        out = bytearray()
        _pack_str(out, doc_id)
        out += data
        self._append(shard, REC_FRAME, bytes(out))

    def poll(self, shard: int, doc_id: str) -> None:
        self._append(shard, REC_POLL, doc_id.encode("utf-8"))

    def tick(self, tick_no: int) -> None:
        """Mark a tick boundary on every shard's stream, then fsync at
        the configured cadence."""
        if self._suspended or self._closed:
            return
        body = bytearray()
        _write_varint(body, tick_no)
        for log in self._shards:
            self._append(log.shard, REC_TICK, bytes(body))
        if tick_no % self.fsync_ticks == 0:
            for log in self._shards:
                if log.fh is not None:
                    os.fsync(log.fh.fileno())
            self._count("journal_fsyncs")

    # -- lifecycle ---------------------------------------------------

    def suspend(self):
        """Context manager: appends no-op inside (used while recovery
        replays the journal through the normal submit path — replayed
        ops must not re-journal themselves)."""
        journal = self

        class _Suspend:
            def __enter__(self):
                journal._suspended += 1
                return journal

            def __exit__(self, *exc):
                journal._suspended -= 1
                return False

        return _Suspend()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for log in self._shards:
            if log.fh is not None:
                log.fh.flush()
                os.fsync(log.fh.fileno())
                log.fh.close()
                log.fh = None


# -- scan side -------------------------------------------------------

def _scan_segment(path: str, shard: int
                  ) -> Tuple[List[JournalRecord], Optional[JournalError]]:
    """Read one segment, returning the valid record prefix and the
    typed error that ended the read (``None`` on a clean EOF)."""
    with open(path, "rb") as fh:
        buf = fh.read()
    end = len(buf)
    header = _segment_header(shard)
    if buf[:len(JOURNAL_MAGIC)] != JOURNAL_MAGIC:
        return [], JournalError(path, 0, "bad magic")
    if end < len(header) or buf[:len(header)] != header:
        return [], JournalError(path, 0, "bad segment header")
    records: List[JournalRecord] = []
    cur = len(header)
    crc = 0
    while cur < end:
        start = cur
        try:
            seq, cur = _read_varint(buf, cur, end)
            if cur >= end:
                raise ValueError("record truncated at kind")
            kind = buf[cur]
            cur += 1
            if kind not in _KIND_NAMES:
                return records, JournalError(
                    path, start, f"unknown record kind {kind}")
            blen, cur = _read_varint(buf, cur, end)
            if cur + blen + 4 > end:
                raise ValueError("record body truncated")
            body = buf[cur:cur + blen]
            cur += blen
            want = crc32c(buf[start:cur], crc)
            got = int.from_bytes(buf[cur:cur + 4], "little")
            cur += 4
            if want != got:
                return records, JournalError(
                    path, start,
                    f"crc mismatch (want {want:08x}, got {got:08x})")
        except ValueError as exc:
            # Torn tail: the record was cut mid-write.  The prefix up
            # to ``start`` is intact (chain-CRC'd), keep it.
            return records, JournalError(path, start, f"torn record: {exc}")
        crc = want
        records.append(JournalRecord(seq, shard, kind, body, path, start))
    return records, None


def scan(journal_dir: str
         ) -> Tuple[List[JournalRecord], List[JournalError]]:
    """Read every shard's segments under ``journal_dir`` and merge the
    valid records into global-sequence order.

    Per shard, segments are read in index order; the first refused
    record ends that shard's stream — later segments of the same shard
    are dropped too and reported (within one append epoch their bytes
    were written after the refused ones, so keeping them would admit
    records whose prefix is gone).  ``repair`` — run at every
    ``Journal`` reopen — truncates/quarantines refused suffixes
    precisely so segments from a LATER epoch (post-recovery appends)
    are never dropped behind them.  The returned error list is the
    loud part: callers count and trace every entry."""
    records: List[JournalRecord] = []
    errors: List[JournalError] = []
    if not os.path.isdir(journal_dir):
        return records, errors
    by_shard: Dict[int, List[Tuple[int, str]]] = {}
    for name in sorted(os.listdir(journal_dir)):
        if not (name.startswith("shard") and name.endswith(".tcrj")):
            continue
        stem = name[len("shard"):-len(".tcrj")]
        try:
            shard_s, idx_s = stem.split(".", 1)
            shard, idx = int(shard_s), int(idx_s)
        except ValueError:
            continue
        by_shard.setdefault(shard, []).append(
            (idx, os.path.join(journal_dir, name)))
    for shard in sorted(by_shard):
        segs = sorted(by_shard[shard])
        broken = False
        for idx, path in segs:
            if broken:
                errors.append(JournalError(
                    path, 0, "dropped: earlier segment refused"))
                continue
            recs, err = _scan_segment(path, shard)
            records.extend(recs)
            if err is not None:
                errors.append(err)
                broken = True
    records.sort(key=lambda r: r.seq)
    return records, errors


def repair(journal_dir: str
           ) -> Tuple[List[JournalRecord], List[JournalError]]:
    """Scan, then make the disk AGREE with the scan: after repair, a
    fresh ``scan`` returns exactly the records this call returned and
    no errors.

    Without this, a reopened journal appends post-recovery records to
    NEW segments of a shard whose torn segment is still on disk — and
    the next scan, refusing the stale torn record first, would drop
    those fully durable later segments ("earlier segment refused").
    Recovery already discarded the refused suffix, so records written
    after it are causally independent of it and must survive a second
    crash.  ``Journal.__init__`` calls this on every reopen.

    Refused bytes are moved, never destroyed: a refused record suffix
    is cut from its segment into a ``<segment>.refused`` sidecar; a
    segment refused whole (bad header, or dropped behind an earlier
    refused segment of its shard — recovery never replayed it) is
    renamed to ``<segment>.refused``.  The ``.refused`` namespace is
    invisible to ``scan`` and to the segment-index allocator.

    Returns the same ``(records, errors)`` as the pre-repair scan."""
    records, errors = scan(journal_dir)
    for err in errors:
        if not os.path.exists(err.segment):
            continue
        if err.offset == 0:
            # Nothing in the segment was recovered (bad magic/header)
            # or nothing in it was replayed (dropped behind a refused
            # earlier segment): quarantine the whole file.
            os.replace(err.segment, err.segment + ".refused")
            continue
        with open(err.segment, "r+b") as fh:
            fh.seek(err.offset)
            tail = fh.read()
            with open(err.segment + ".refused", "wb") as side:
                side.write(tail)
            fh.truncate(err.offset)
            fh.flush()
            os.fsync(fh.fileno())
    return records, errors

"""The single-process document server: router + batcher + residency +
admission wired behind one facade.

Usage shape (see ``serve/loadgen.py`` for the closed-loop driver):

    server = DocServer(ServeConfig(num_shards=2, lanes_per_shard=16))
    server.admit_doc("doc-7")
    server.submit_frame("doc-7", frame_bytes)       # remote peer traffic
    server.submit_local("doc-7", "editor", pos=0, ins_content="hi")
    server.tick()                                    # one batched step
    server.poll_request_frame("doc-7")               # owed REQUESTs

Everything user-facing is total: overload and malformed input raise
typed ``AdmissionError``s, capacity overflow degrades to the host
oracle, eviction/restore is CRC-guarded — the invariant under all of it
being YATA convergence: after any interleaving of ticks, evictions,
restores, faults and re-requests, every doc is bit-identical to a
replica that saw the same ops cleanly.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..common import RemoteTxn
from ..config import ServeConfig
from ..models.sync import state_digest
from ..utils.metrics import Counters, percentiles
from .admission import AdmissionControl
from .batcher import ContinuousBatcher, make_lane_backend
from .residency import LaneResidency
from .router import DocState, ShardRouter


class DocServer:
    """One process, ``num_shards`` device batches, thousands of docs."""

    def __init__(self, cfg: Optional[ServeConfig] = None,
                 counters: Optional[Counters] = None):
        self.cfg = cfg = cfg or ServeConfig()
        assert cfg.max_txn_len <= cfg.step_buckets[-1], (
            f"max_txn_len {cfg.max_txn_len} exceeds the largest step "
            f"bucket {cfg.step_buckets[-1]}: an admitted event could "
            f"never fit a tick")
        self.counters = counters if counters is not None else Counters()
        self.admission = AdmissionControl(
            max_queue_per_doc=cfg.max_queue_per_doc,
            max_queue_global=cfg.max_queue_global,
            max_txn_len=cfg.max_txn_len,
            rate_capacity=cfg.rate_capacity,
            rate_refill=cfg.rate_refill,
            counters=self.counters)
        self.router = ShardRouter(cfg.num_shards, admission=self.admission,
                                  counters=self.counters,
                                  wire_format=cfg.wire_format)
        backends = [
            make_lane_backend(cfg.engine, lanes=cfg.lanes_per_shard,
                              capacity=cfg.lane_capacity,
                              order_capacity=cfg.order_capacity,
                              lmax=cfg.lmax, block_k=cfg.lanes_block_k,
                              interpret=cfg.interpret,
                              fuse_w=cfg.fuse_w if cfg.fuse_steps else 1)
            for _ in range(cfg.num_shards)
        ]
        self.residency = LaneResidency(backends, self.router,
                                       spool_dir=cfg.spool_dir,
                                       counters=self.counters,
                                       ckpt_format=cfg.ckpt_format,
                                       ckpt_compact_ops=cfg.ckpt_compact_ops,
                                       ckpt_compact_links=cfg.ckpt_compact_links)
        self.batcher = ContinuousBatcher(self.router, self.residency,
                                         step_buckets=cfg.step_buckets,
                                         lmax=cfg.lmax,
                                         counters=self.counters,
                                         fuse_steps=cfg.fuse_steps,
                                         fuse_w=cfg.fuse_w)
        self.tick_no = 0

    # -- traffic surface ----------------------------------------------------

    def admit_doc(self, doc_id: str) -> None:
        self.router.admit_doc(doc_id)

    def submit_frame(self, doc_id: str, data: bytes) -> List[bytes]:
        return self.router.submit_frame(doc_id, data)

    def submit_mux_frame(self, data: bytes):
        """One doc-multiplexed TXNS frame (the connection-level
        replication lane); returns per-doc-group rejections."""
        return self.router.submit_mux_frame(data)

    def submit_txn(self, doc_id: str, txn: RemoteTxn) -> None:
        self.router.submit_txn(doc_id, txn)

    def submit_local(self, doc_id: str, agent: str, pos: int,
                     del_len: int = 0, ins_content: str = "") -> None:
        self.router.submit_local(doc_id, agent, pos, del_len, ins_content)

    def poll_request_frame(self, doc_id: str) -> Optional[bytes]:
        return self.router.poll_request_frame(doc_id)

    def export_since(self, doc_id: str, start_order: int):
        return self.router.export_since(doc_id, start_order)

    # -- the serving loop ---------------------------------------------------

    def tick(self) -> Dict[str, float]:
        self.tick_no += 1
        self.router.set_tick(self.tick_no)
        return self.batcher.tick(self.tick_no)

    def drain(self, max_ticks: int = 64) -> int:
        """Tick until every queue is empty (or the budget runs out);
        returns ticks spent. Pending = undrained events only — txns
        blocked in causal buffers need peer re-delivery, not ticks."""
        for i in range(max_ticks):
            if not any(d.events for d in self.router.docs.values()):
                return i
            self.tick()
        return max_ticks

    # -- inspection / verification ------------------------------------------

    def doc_state(self, doc_id: str) -> DocState:
        return self.router.doc(doc_id)

    def ensure_resident(self, doc_id: str) -> DocState:
        doc = self.router.doc(doc_id)
        if not doc.resident:
            self.residency.restore(doc)
        return doc

    def doc_string(self, doc_id: str) -> str:
        return self.ensure_resident(doc_id).oracle.to_string()

    def doc_digest(self, doc_id: str) -> int:
        return state_digest(self.ensure_resident(doc_id).oracle)

    def verify_doc(self, doc_id: str) -> bool:
        """Lane (if any) bit-identical to the host oracle."""
        doc = self.router.doc(doc_id)
        if not doc.resident:
            return True
        return self.residency.verify_lane(doc)

    def latency_summary(self) -> Dict[str, float]:
        """Admission->applied latency percentiles in microseconds."""
        us = [s * 1e6 for s in self.batcher.latency_samples]
        out = {k: round(v, 1)
               for k, v in percentiles(us, (50, 99)).items()}
        out["samples"] = len(us)
        return out

    def tick_summary(self) -> Dict[str, float]:
        """Serve tick wall-latency percentiles in milliseconds (one
        sample per ``tick()`` — the fixed-shape device pass plus the
        host drain around it), plus the generalized step-fusion
        counters (ISSUE 6): how many compiled rows the per-doc tick
        fusion eliminated (= bucket occupancy gained) and the
        per-shape histogram."""
        ms = [s * 1e3 for s in self.batcher.tick_wall_samples]
        out = {k: round(v, 3)
               for k, v in percentiles(ms, (50, 99)).items()}
        out["samples"] = len(ms)
        fs = self.batcher.fuse_stats
        out["steps_total"] = fs.steps_out
        out["steps_prefuse"] = fs.steps_in
        out["fused_rows_saved"] = fs.rows_saved
        # ops/step: compiled op rows landed per device step row (each
        # pre-fusion row is one op's step).
        out["ops_per_step"] = round(fs.reduction_x, 3)
        for shape, n in fs.fused.items():
            if n:
                out[f"fuse_{shape}"] = n
        # Bytes-on-wire + checkpoint-bytes (ISSUE 7): what the columnar
        # wire and delta checkpoints are cutting, by lane.
        c = self.counters.summary()
        for key in ("wire_bytes_in", "wire_txn_bytes_out",
                    "ckpt_bytes_written", "ckpt_saves_full",
                    "ckpt_saves_delta", "ckpt_bytes_per_evict_mean"):
            if key in c:
                out[key] = c[key]
        return out

    def stats(self) -> Dict[str, float]:
        out = dict(self.counters.summary())
        out.update(self.residency.resident_counts())
        out.update({f"latency_us_{k}": v
                    for k, v in self.latency_summary().items()})
        out.update({f"tick_ms_{k}": v
                    for k, v in self.tick_summary().items()})
        return out

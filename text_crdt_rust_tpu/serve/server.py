"""The single-process document server: router + batcher + residency +
admission wired behind one facade.

Usage shape (see ``serve/loadgen.py`` for the closed-loop driver):

    server = DocServer(ServeConfig(num_shards=2, lanes_per_shard=16))
    server.admit_doc("doc-7")
    server.submit_frame("doc-7", frame_bytes)       # remote peer traffic
    server.submit_local("doc-7", "editor", pos=0, ins_content="hi")
    server.tick()                                    # one batched step
    server.poll_request_frame("doc-7")               # owed REQUESTs

Everything user-facing is total: overload and malformed input raise
typed ``AdmissionError``s, capacity overflow degrades to the host
oracle, eviction/restore is CRC-guarded — the invariant under all of it
being YATA convergence: after any interleaving of ticks, evictions,
restores, faults and re-requests, every doc is bit-identical to a
replica that saw the same ops cleanly.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..common import RemoteTxn
from ..config import ServeConfig
from ..models.sync import state_digest
from ..obs.flow import FlowTracker
from ..obs.recorder import FlightRecorder
from ..obs.registry import MetricsRegistry
from ..obs.trace import Tracer
from ..utils.metrics import Counters, percentiles
from .admission import AdmissionControl
from .batcher import ContinuousBatcher, make_lane_backend
from .residency import LaneResidency
from .router import DocState, ShardRouter


class DocServer:
    """One process, ``num_shards`` device batches, thousands of docs."""

    def __init__(self, cfg: Optional[ServeConfig] = None,
                 counters: Optional[Counters] = None):
        self.cfg = cfg = cfg or ServeConfig()
        assert cfg.max_txn_len <= cfg.step_buckets[-1], (
            f"max_txn_len {cfg.max_txn_len} exceeds the largest step "
            f"bucket {cfg.step_buckets[-1]}: an admitted event could "
            f"never fit a tick")
        # One metrics registry for the whole server (counters + gauges +
        # bounded histograms, ISSUE 8) — a caller-supplied plain
        # Counters still works (histograms degrade to mean gauges).
        self.counters = (counters if counters is not None
                         else MetricsRegistry())
        self.tracer = Tracer(enabled=cfg.trace, ring=cfg.trace_ring,
                             keep_all=cfg.trace_keep, path=cfg.trace_path,
                             rotate_bytes=cfg.trace_rotate_bytes)
        # Per-op provenance (ISSUE 11): one FlowTracker shared by every
        # layer an op crosses, agent-sampled (flow_sample_mod).
        self.flow = FlowTracker(self.tracer,
                                sample_mod=cfg.flow_sample_mod)
        self.admission = AdmissionControl(
            max_queue_per_doc=cfg.max_queue_per_doc,
            max_queue_global=cfg.max_queue_global,
            max_txn_len=cfg.max_txn_len,
            rate_capacity=cfg.rate_capacity,
            rate_refill=cfg.rate_refill,
            counters=self.counters,
            tracer=self.tracer)
        self.router = ShardRouter(cfg.num_shards, admission=self.admission,
                                  counters=self.counters,
                                  wire_format=cfg.wire_format,
                                  tracer=self.tracer, flow=self.flow)
        backends = [
            make_lane_backend(cfg.engine, lanes=cfg.lanes_per_shard,
                              capacity=cfg.lane_capacity,
                              order_capacity=cfg.order_capacity,
                              lmax=cfg.lmax, block_k=cfg.lanes_block_k,
                              interpret=cfg.interpret,
                              fuse_w=cfg.fuse_w if cfg.fuse_steps else 1,
                              device_prefill=cfg.device_prefill)
            for _ in range(cfg.num_shards)
        ]
        self.residency = LaneResidency(backends, self.router,
                                       spool_dir=cfg.spool_dir,
                                       counters=self.counters,
                                       ckpt_format=cfg.ckpt_format,
                                       ckpt_compact_ops=cfg.ckpt_compact_ops,
                                       ckpt_compact_links=cfg.ckpt_compact_links,
                                       tracer=self.tracer)
        # Write-ahead op journal (ISSUE 16): admission-edge durability.
        # None (the default) = off; the loadgen/chaos drivers pin a
        # directory so DocServer.recover() can rebuild this server.
        self.journal = None
        if cfg.journal_dir:
            from .journal import Journal
            self.journal = Journal(cfg.journal_dir, cfg.num_shards,
                                   fsync_ticks=cfg.journal_fsync_ticks,
                                   counters=self.counters,
                                   tracer=self.tracer)
            self.router.journal = self.journal
        # Flight recorder: bundles land in cfg.obs_dir, else the
        # TCR_TRACE_DIR env knob (how a failing tier-1 serve test
        # attaches its post-mortem to the pytest report — conftest),
        # else next to the eviction spool.
        obs_dir = (cfg.obs_dir or os.environ.get("TCR_TRACE_DIR")
                   or os.path.join(self.residency.spool_dir, "obs"))
        self.recorder = FlightRecorder(self.tracer, self.counters, obs_dir,
                                       ring_events=cfg.trace_ring)
        self.router.recorder = self.recorder
        self.residency.recorder = self.recorder
        self.batcher = ContinuousBatcher(self.router, self.residency,
                                         step_buckets=cfg.step_buckets,
                                         lmax=cfg.lmax,
                                         counters=self.counters,
                                         fuse_steps=cfg.fuse_steps,
                                         fuse_w=cfg.fuse_w,
                                         tracer=self.tracer,
                                         recorder=self.recorder,
                                         flow=self.flow,
                                         pipeline_ticks=cfg.pipeline_ticks,
                                         sanitize_pipeline=cfg.sanitize_pipeline,
                                         train_ticks=cfg.train_ticks)
        self.tick_no = 0
        self._profiling = False

    # -- traffic surface ----------------------------------------------------

    def admit_doc(self, doc_id: str) -> None:
        self.router.admit_doc(doc_id)

    def submit_frame(self, doc_id: str, data: bytes) -> List[bytes]:
        return self.router.submit_frame(doc_id, data)

    def submit_mux_frame(self, data: bytes):
        """One doc-multiplexed TXNS frame (the connection-level
        replication lane); returns per-doc-group rejections."""
        return self.router.submit_mux_frame(data)

    def submit_txn(self, doc_id: str, txn: RemoteTxn) -> None:
        self.router.submit_txn(doc_id, txn)

    def submit_local(self, doc_id: str, agent: str, pos: int,
                     del_len: int = 0, ins_content: str = "") -> None:
        self.router.submit_local(doc_id, agent, pos, del_len, ins_content)

    def poll_request_frame(self, doc_id: str) -> Optional[bytes]:
        return self.router.poll_request_frame(doc_id)

    def export_since(self, doc_id: str, start_order: int):
        return self.router.export_since(doc_id, start_order)

    # -- the serving loop ---------------------------------------------------

    def tick(self) -> Dict[str, float]:
        self.tick_no += 1
        self.router.set_tick(self.tick_no)
        self._profile_hook()
        stats = self.batcher.tick(self.tick_no)
        if self.journal is not None:
            # The tick boundary is the journal's fsync point AND the
            # replay pacing marker: recovery re-runs ``tick()`` here so
            # the apply cadence (and with it the local-vs-remote
            # interleaving) reproduces exactly.
            self.journal.tick(self.tick_no)
        return stats

    def flush_pipeline(self) -> None:
        """Sync every in-flight pipelined tick (no-op in the serial
        loop).  Latency percentiles and end-of-run verification call
        this so the last tick's device completion is stamped; emits no
        trace events, so the logical stream stays mode-invariant."""
        self.batcher.flush_pipeline()

    def close_obs(self) -> None:
        """Finalize observability at end of run: stop a still-running
        profiler capture (a run shorter than ``profile_ticks`` would
        otherwise never write its trace — and leave the process-global
        profiler running into the next server) and close the trace
        file. Idempotent; drivers (loadgen, probes) call it on
        teardown."""
        self.flush_pipeline()
        if self._profiling:
            import jax

            try:
                jax.profiler.stop_trace()
                self.tracer.event("profile", action="stop",
                                  dir=self.cfg.profile_dir)
            except Exception as e:
                self.counters.incr("profile_errors")
                self.tracer.event("profile", action="error",
                                  err=f"{type(e).__name__}: {e}")
            self._profiling = False
        if self.journal is not None:
            self.journal.close()
        self.tracer.close()

    def _profile_hook(self) -> None:
        """Opt-in ``jax.profiler`` capture (ISSUE 8 device hooks): trace
        ticks 1..profile_ticks into ``cfg.profile_dir``. Failure to
        start a profiler (unsupported backend) is counted, never
        raised — profiling must not take the serving loop down."""
        if not self.cfg.profile_dir:
            return
        import jax

        try:
            if self.tick_no == 1 and not self._profiling:
                jax.profiler.start_trace(self.cfg.profile_dir)
                self._profiling = True
                self.tracer.event("profile", action="start",
                                  dir=self.cfg.profile_dir)
            elif (self._profiling
                  and self.tick_no > self.cfg.profile_ticks):
                jax.profiler.stop_trace()
                self._profiling = False
                self.tracer.event("profile", action="stop",
                                  dir=self.cfg.profile_dir)
        except Exception as e:
            self._profiling = False
            self.counters.incr("profile_errors")
            self.tracer.event("profile", action="error",
                              err=f"{type(e).__name__}: {e}")

    def recover(self) -> Dict[str, int]:
        """Rebuild a crashed server by re-executing its input log
        (ISSUE 16 tentpole, part 2).  Call on a FRESH server
        constructed with the dead server's ``spool_dir``/``journal_dir``.

        The server is a deterministic state machine, so recovery is
        re-execution: scan the journal (valid prefix per shard; typed
        refusals counted + traced, their suffixes already repaired
        away at Journal reopen so post-recovery appends survive a
        second crash), audit the checkpoint spool
        (corruption reported, file allocator advanced past the crashed
        process's files), then replay the merged record stream through
        the NORMAL admission -> buffer -> batcher path with journaling
        suspended.  ADMIT records reproduce shard assignment and drain
        order; TXNS/LOCAL/FRAME/POLL records re-submit the same inputs;
        TICK markers re-run ``tick()`` so the apply cadence — residency
        trajectory, local-edit position resolution, and the in-flight
        pipelined ticks that were dispatched but never synced at crash
        time — re-derives exactly.  Replayed evictions lay the
        checkpoint chains down again (fresh files; the crashed
        process's spool stays untouched for forensics), and replayed
        restores read them back — the checkpoint path exercises itself.
        Returns replay stats."""
        from ..net import codec
        from . import journal as J
        from .admission import AdmissionError

        assert self.journal is not None, \
            "recover() needs cfg.journal_dir (durability was off)"
        assert not self.router.docs, \
            "recover() must run on a fresh server, before any traffic"
        records, fresh_errors = J.scan(self.cfg.journal_dir)
        # Refusals were detected — and the refused suffixes repaired
        # (truncated/quarantined, so post-recovery segments can never
        # be dropped behind them on the NEXT crash's scan) — when this
        # server's Journal reopened the directory.  Those were counted
        # at reopen; report them through the recovery channel too.
        # ``fresh_errors`` (disk mutated between reopen and recover)
        # should be empty, but if not, count them like any refusal.
        for err in fresh_errors:
            self.counters.incr("journal_refusals")
        errors = list(self.journal.repair_errors) + fresh_errors
        for err in errors:
            self.tracer.event("journal.refuse", segment=err.segment,
                              offset=err.offset, reason=err.reason)
            if self.recorder is not None:
                self.recorder.on_failure("journal", str(err))
        found = self.residency.rediscover()
        stats = {"records": len(records), "refusals": len(errors),
                 "docs": 0, "ckpts_found": len(found), "ops": 0,
                 "txns_replayed": 0, "locals_replayed": 0,
                 "frames_replayed": 0, "polls_replayed": 0,
                 "ticks": 0, "readmissions": 0, "shard_mismatches": 0,
                 "local_gaps": 0}
        with self.journal.suspend():
            for rec in records:
                if rec.kind == J.REC_ADMIT:
                    doc_id = rec.body.decode("utf-8")
                    doc = self.router.admit_doc(doc_id)
                    stats["docs"] += 1
                    if doc.shard != rec.shard:
                        # Replayed least-loaded choice disagreeing with
                        # the recorded one would reorder every later
                        # drain — loud, never silent.
                        stats["shard_mismatches"] += 1
                        self.counters.incr("recovery_shard_mismatches")
                elif rec.kind == J.REC_TXNS:
                    try:
                        kind, groups, _, _ = codec.decode_frame_ex(
                            bytes(rec.body))
                    except codec.CodecError as e:
                        # CRC-chained records should never decode dirty;
                        # if one does, refuse it loudly and keep going.
                        self.counters.incr("journal_refusals")
                        self.tracer.event(
                            "journal.refuse", segment=rec.segment,
                            offset=rec.offset,
                            reason=f"undecodable TXNS body: {e}")
                        continue
                    if kind != codec.KIND_TXNS_MUX:
                        # Same taxonomy as an undecodable body: a TXNS
                        # record carrying a non-mux frame is a typed
                        # per-record refusal, never a replay abort.
                        self.counters.incr("journal_refusals")
                        self.tracer.event(
                            "journal.refuse", segment=rec.segment,
                            offset=rec.offset,
                            reason=f"TXNS body kind {kind} is not "
                                   f"TXNS_MUX")
                        continue
                    for doc_id, txns in groups:
                        for txn in txns:
                            try:
                                self.router.submit_txn(doc_id, txn)
                            except AdmissionError:
                                stats["readmissions"] += 1
                                continue
                            stats["txns_replayed"] += 1
                elif rec.kind == J.REC_LOCAL:
                    (doc_id, agent, pos, del_len, ins,
                     ordinal) = J.decode_local_body(rec.body)
                    doc = self.router.doc(doc_id)
                    if ordinal != doc.local_seen:
                        # Exactly-once audit: the rebuilt ordinal
                        # counter must agree with the recorded one.
                        stats["local_gaps"] += 1
                        self.counters.incr("recovery_local_gaps")
                    try:
                        self.router.submit_local(doc_id, agent, pos,
                                                 del_len, ins)
                    except AdmissionError:
                        stats["readmissions"] += 1
                        continue
                    stats["locals_replayed"] += 1
                elif rec.kind == J.REC_FRAME:
                    doc_id, data = J.decode_frame_body(rec.body)
                    try:
                        self.router.submit_frame(doc_id, data)
                    except AdmissionError:
                        stats["readmissions"] += 1
                        continue
                    stats["frames_replayed"] += 1
                elif rec.kind == J.REC_POLL:
                    doc_id = rec.body.decode("utf-8")
                    try:
                        self.router.poll_request_frame(doc_id)
                    except AdmissionError:
                        stats["readmissions"] += 1
                        continue
                    stats["polls_replayed"] += 1
                elif rec.kind == J.REC_TICK:
                    tick_no, _ = J._read_varint(rec.body, 0,
                                                len(rec.body))
                    if tick_no <= self.tick_no:
                        continue  # one marker per shard: replay once
                    self.tick_no = tick_no - 1
                    self.tick()
                    stats["ticks"] += 1
        stats["ops"] = stats["txns_replayed"] + stats["locals_replayed"]
        self.counters.incr("recovery_ops_replayed", stats["ops"])
        self.counters.incr("recovery_ticks", stats["ticks"])
        self.tracer.event("recovery.replay", records=stats["records"],
                          ops=stats["ops"], ticks=stats["ticks"],
                          docs=stats["docs"],
                          ckpts=stats["ckpts_found"],
                          refusals=stats["refusals"])
        return stats

    def drain(self, max_ticks: int = 64) -> int:
        """Tick until every queue is empty (or the budget runs out);
        returns ticks spent. Pending = undrained events only — txns
        blocked in causal buffers need peer re-delivery, not ticks."""
        for i in range(max_ticks):
            if not any(d.events for d in self.router.docs.values()):
                self.flush_pipeline()
                return i
            self.tick()
        self.flush_pipeline()
        return max_ticks

    # -- inspection / verification ------------------------------------------

    def doc_state(self, doc_id: str) -> DocState:
        return self.router.doc(doc_id)

    def ensure_resident(self, doc_id: str) -> DocState:
        doc = self.router.doc(doc_id)
        if not doc.resident:
            self.residency.restore(doc)
        return doc

    def doc_string(self, doc_id: str) -> str:
        return self.ensure_resident(doc_id).oracle.to_string()

    def doc_digest(self, doc_id: str) -> int:
        return state_digest(self.ensure_resident(doc_id).oracle)

    def verify_doc(self, doc_id: str) -> bool:
        """Lane (if any) bit-identical to the host oracle."""
        doc = self.router.doc(doc_id)
        if not doc.resident:
            return True
        return self.residency.verify_lane(doc)

    def flow_summary(self, expect_terminal: bool = False) -> Dict[str, object]:
        """Per-op provenance census + conservation audit over the
        tracked (sampled) spans: terminal-state counts, findings, and
        op-age-at-apply distributions in logical ticks.  With
        ``expect_terminal`` every still-in-flight span is a named
        finding — the end-of-run audit mode."""
        return self.flow.report(expect_terminal=expect_terminal)

    def latency_summary(self) -> Dict[str, float]:
        """Admission->applied latency percentiles in microseconds.
        Flushes the pipeline first: an in-flight tick's events are not
        stamped until their device work completes.  With pipelining on,
        a tick's events are stamped at its STAGED sync (the next tick's
        barrier slot) — an upper bound that can run up to one tick of
        host wall past true device completion (JAX exposes no per-array
        completion time); results are not observable to readers before
        that sync either way."""
        self.flush_pipeline()
        us = [s * 1e6 for s in self.batcher.latency_samples]
        out = {k: round(v, 1)
               for k, v in percentiles(us, (50, 99)).items()}
        out["samples"] = len(us)
        return out

    def tick_summary(self) -> Dict[str, float]:
        """Serve tick wall-latency percentiles in milliseconds (one
        sample per ``tick()`` — the fixed-shape device pass plus the
        host drain around it), plus the generalized step-fusion
        counters (ISSUE 6): how many compiled rows the per-doc tick
        fusion eliminated (= bucket occupancy gained) and the
        per-shape histogram."""
        # Flush like latency_summary does: an in-flight tick's stall/
        # window is not accounted until its staged sync, and the two
        # summaries must not disagree about the same run.
        self.flush_pipeline()
        ms = [s * 1e3 for s in self.batcher.tick_wall_samples]
        out = {k: round(v, 3)
               for k, v in percentiles(ms, (50, 99)).items()}
        out["samples"] = len(ms)
        fs = self.batcher.fuse_stats
        out["steps_total"] = fs.steps_out
        out["steps_prefuse"] = fs.steps_in
        out["fused_rows_saved"] = fs.rows_saved
        # ops/step: compiled op rows landed per device step row (each
        # pre-fusion row is one op's step).
        out["ops_per_step"] = round(fs.reduction_x, 3)
        for shape, n in fs.fused.items():
            if n:
                out[f"fuse_{shape}"] = n
        # Bytes-on-wire + checkpoint-bytes (ISSUE 7): what the columnar
        # wire and delta checkpoints are cutting, by lane.  Plus the
        # ISSUE-8 distribution keys: per-stream ops_per_step and
        # fused_rows_saved histograms (the mean alone hid the PR-6
        # skew) and per-bucket device-step wall percentiles, all from
        # the one metrics registry.
        c = self.counters.summary()
        for key in ("wire_bytes_in", "wire_txn_bytes_out",
                    "ckpt_bytes_written", "ckpt_saves_full",
                    "ckpt_saves_delta", "ckpt_bytes_per_evict_mean"):
            if key in c:
                out[key] = c[key]
        for key in c:
            if (key.startswith(("ops_per_step_", "fused_rows_saved_",
                                "device_step_wall_ms_"))
                    and key.rsplit("_", 1)[-1] in
                    ("min", "max", "p50", "p99", "count")):
                out[key] = c[key]
        out["device_compiles"] = c.get("device_compiles", 0)
        # Pipelined tick (ISSUE 12): how much of the measured device-
        # sync demand the staged sync hid under host work (0.0 in the
        # serial loop), the configured-vs-effective depth, and the
        # residual stall the overlap could not absorb.
        out["pipeline_ticks"] = self.batcher.effective_pipeline_ticks()
        out["pipeline_overlap_frac"] = round(
            self.batcher.pipeline_overlap_frac(), 4)
        out["pipeline_stall_ms_total"] = round(
            self.batcher.sync_stall_s * 1e3, 3)
        # Device-resident prefill (ISSUE 14): the per-tick log-prefill
        # byte economy — what moved host<->device vs the full-log
        # round trip, the scatter volume, and the scatter program's
        # compile count.  Backends without the surface (the blocked
        # lanes backend prefills only ranks, host-side) contribute
        # nothing; the summed stats stay seed-deterministic.
        pf = [b.prefill_summary() for b in self.residency.backends
              if hasattr(b, "prefill_summary")]
        if pf:
            out["device_prefill"] = all(p["device_prefill"] for p in pf)
            out["prefill_bytes_per_tick"] = round(
                sum(p["prefill_bytes_per_tick"] for p in pf), 1)
            out["prefill_bytes_full_per_tick"] = round(
                sum(p["prefill_bytes_full_per_tick"] for p in pf), 1)
            # max(.., 1): same floor as the backend's per-backend cut —
            # a run that moved zero prefill bytes (no-insert streams)
            # reports the full-log baseline as its cut, not a 1e9
            # division artifact.
            out["prefill_bytes_cut_x"] = round(
                out["prefill_bytes_full_per_tick"]
                / max(out["prefill_bytes_per_tick"], 1.0), 2)
            out["prefill_scatter_len"] = sum(
                p["prefill_scatter_len"] for p in pf)
            out["prefill_scatter_compiles"] = sum(
                p["prefill_scatter_compiles"] for p in pf)
        # Tick trains (ISSUE 20): the per-tick device-dispatch economy.
        # ``device_dispatches`` counts actual device programs issued
        # (train scans + prefill scatters); ``dispatch_serial_equiv`` is
        # what the serial loop would have issued for the same stream,
        # so ``dispatch_cut_x`` ~= train length x (scatter and scan both
        # amortize).  ``train_len`` is the realized mean (flushes make
        # partial trains); ``train_compiles`` counts distinct (T, S)
        # train programs — report-only, never traced, so the logical
        # stream stays train-length-invariant.
        tn = [b.train_summary() for b in self.residency.backends
              if hasattr(b, "train_summary")]
        if tn:
            out["train_ticks"] = self.batcher.effective_train_ticks()
            out["device_dispatches"] = sum(
                t["device_dispatches"] for t in tn)
            out["device_dispatches_per_tick"] = round(
                out["device_dispatches"]
                / max(c.get("device_ticks", 0), 1), 3)
            out["dispatch_serial_equiv"] = sum(
                t["dispatch_serial_equiv"] for t in tn)
            out["dispatch_cut_x"] = round(
                out["dispatch_serial_equiv"]
                / max(out["device_dispatches"], 1), 2)
            out["train_len"] = round(sum(
                t["train_len"] for t in tn) / len(tn), 2)
            out["train_compiles"] = sum(t["train_compiles"] for t in tn)
        # Flight-recorder visibility (ISSUE 10 satellite): how many
        # post-mortem bundles this run wrote and how many same-reason
        # repeats were suppressed — a nonzero suppressed count in a
        # summary is the "this run failed the same way many times"
        # signal without grepping the obs dir.
        out["bundles_written"] = c.get("bundles_written", 0)
        out["bundles_suppressed"] = c.get("bundles_suppressed", 0)
        return out

    def stats(self) -> Dict[str, float]:
        out = dict(self.counters.summary())
        out.update(self.residency.resident_counts())
        out.update({f"latency_us_{k}": v
                    for k, v in self.latency_summary().items()})
        out.update({f"tick_ms_{k}": v
                    for k, v in self.tick_summary().items()})
        return out

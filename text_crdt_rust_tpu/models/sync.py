"""Peer synchronization: export local history as ``RemoteTxn``s and merge.

The reference defines the peer-portable structs (`external_txn.rs:5-30`) but
implements no serializer or sync ("wire encoding is out of scope",
SURVEY §2 L4). This module completes the layer: any engine exposing the
oracle's log surface (client_with_order / item_orders / deletes / txns /
per-item origins) can export its history since an order watermark and merge
another peer's history, skipping already-known (agent, seq) ranges and
splitting partially-known spans.

All ids cross this boundary as (agent-name string, seq) pairs because
numeric agent ids and orders are peer-local (`README.md:33-35`,
`doc.rs:236-240`).
"""
from __future__ import annotations

import struct
import zlib
from typing import Dict, List, Set

from ..common import (
    CLIENT_INVALID,
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
    split_txn_suffix,
    txn_len,
)
from .oracle import ListCRDT


def export_txns_since(doc: ListCRDT, start_order: int = 0) -> List[RemoteTxn]:
    """All history with order >= ``start_order`` as RemoteTxns, in order.

    Txn spans are split on agent boundaries (the txns RLE can merge linear
    history across agents, `txn.rs:38-42`) and re-derive per-run ops from the
    logs: a delete-op order range is found in the deletes log
    (`list/mod.rs:82-84`); anything else is an insert run whose implicit
    origin chain (`span.rs:9-18`) bounds the run.
    """
    out: List[RemoteTxn] = []
    end_order = doc.get_next_order()
    if start_order >= end_order:
        # Idle hot path: a session polls this every tick — don't build
        # the O(n) index when there is nothing to export.
        return out
    # One pass over the body: order -> raw index (avoids a per-char scan).
    idx_of = {int(doc.order[i]): i for i in range(doc.n)}
    o = start_order
    while o < end_order:
        txn_found = doc.txns.find(o)
        assert txn_found is not None, f"no txn covering order {o}"
        txn_entry, txn_off = txn_found
        txn_end = txn_entry.order + txn_entry.length
        # Split on agent span boundaries too.
        cwo_found = doc.client_with_order.find(o)
        assert cwo_found is not None
        cwo_entry, cwo_off = cwo_found
        cwo_end = cwo_entry.order + cwo_entry.length
        sub_end = min(txn_end, cwo_end)

        agent_name = doc.get_agent_name(cwo_entry.agent)
        seq0 = cwo_entry.seq + cwo_off
        if txn_off == 0:
            parents = [doc.order_to_remote_id(p) for p in txn_entry.parents]
        else:
            # Interior of a merged linear span: parent is the previous op.
            parents = [doc.order_to_remote_id(o - 1)]

        ops: List = []
        pos = o
        while pos < sub_end:
            del_found = doc.deletes.find(pos)
            if del_found is not None:
                de, de_off = del_found
                take = min(de.op_order + de.length, sub_end) - pos
                # Split the target run at our client_with_order span
                # boundaries: a (agent, seq) range is only portable as one
                # RemoteDel if it was assigned orders as one run (the
                # reference's implicit "contiguous from a single client"
                # constraint, `list/mod.rs` OpExternal comment).
                t0 = de.target + de_off
                t_found = doc.client_with_order.find(t0)
                assert t_found is not None
                t_entry, t_off = t_found
                take = min(take, t_entry.length - t_off)
                ops.append(RemoteDel(
                    id=doc.order_to_remote_id(t0),
                    len=take,
                ))
                pos += take
            else:
                # Insert run: orders pos.. while the implicit origin chain
                # holds and items exist in the body.
                i0 = idx_of.get(pos)
                assert i0 is not None, f"order {pos} neither delete nor insert"
                origin_left = int(doc.origin_left[i0])
                origin_right = int(doc.origin_right[i0])
                run_idx = [i0]
                p = pos + 1
                while p < sub_end:
                    ii = idx_of.get(p)
                    if ii is None:
                        break
                    if int(doc.origin_left[ii]) != p - 1:
                        break
                    if int(doc.origin_right[ii]) != origin_right:
                        break
                    run_idx.append(ii)
                    p += 1
                chars = [chr(int(doc.chars[iq])) for iq in run_idx]
                ops.append(RemoteIns(
                    origin_left=doc.order_to_remote_id(origin_left),
                    origin_right=doc.order_to_remote_id(origin_right),
                    ins_content="".join(chars),
                ))
                pos = p

        out.append(RemoteTxn(
            id=RemoteId(agent_name, seq0),
            parents=parents,
            ops=ops,
        ))
        o = sub_end
    return out


def merge_into(dst: ListCRDT, src: ListCRDT) -> int:
    """Apply everything ``dst`` is missing from ``src``'s history.

    Returns the number of RemoteTxns applied. Applying in source order is
    causally safe: parents always have smaller source order than their txn.
    """
    applied = 0
    for txn in export_txns_since(src, 0):
        agent = dst.get_or_create_agent_id(txn.id.agent)
        next_seq = dst.client_data[agent].get_next_seq()
        if txn.id.seq + txn_len(txn) <= next_seq:
            continue  # fully known
        if txn.id.seq < next_seq:
            txn = split_txn_suffix(txn, next_seq - txn.id.seq)
        dst.apply_remote_txn(txn)
        applied += 1
    return applied


def remote_frontier(doc: ListCRDT) -> Set[RemoteId]:
    """Frontier as peer-portable ids (orders are peer-local)."""
    return {doc.order_to_remote_id(o) for o in doc.frontier}


def agent_watermarks(doc: ListCRDT) -> Dict[str, int]:
    """Per-agent next expected seq — the peer-portable progress vector a
    DIGEST frame advertises (`net/session.py`). Orders are peer-local;
    (agent name, seq) watermarks are the only comparable progress."""
    return {cd.name: cd.get_next_seq() for cd in doc.client_data}


def state_digest(doc: ListCRDT) -> int:
    """Order-independent 32-bit digest of the *converged* state.

    Hashes the document body in document order as peer-portable
    (agent name, seq, deleted) triples plus the sorted remote frontier —
    never local orders, which differ across peers that interleaved the
    same history differently. Two peers that have applied the same op set
    converge to the same YATA document order (PAPER.md §1), so equal
    history ⇒ equal digest; equal watermarks with UNEQUAL digests is the
    divergence signal the resync session trips on.
    """
    h = 0
    # u32 length prefix: agent names are unbounded strings (the codec
    # caps them at 4 KiB, but the digest must never be the crash site).
    for i in range(doc.n):
        agent, seq = doc.loc_of_order(int(doc.order[i]))
        name = doc.get_agent_name(agent).encode("utf-8")
        h = zlib.crc32(struct.pack("<I", len(name)) + name, h)
        h = zlib.crc32(
            struct.pack("<IB", seq, 1 if doc.deleted[i] else 0), h)
    frontier = sorted(((r.agent, r.seq) for r in remote_frontier(doc)))
    for name_s, seq in frontier:
        name = name_s.encode("utf-8")
        h = zlib.crc32(struct.pack("<I", len(name)) + name, h)
        h = zlib.crc32(struct.pack("<I", seq), h)
    return h & 0xFFFF_FFFF


def export_txns_for_wants(doc: ListCRDT,
                          wants: Dict[str, int]) -> List[RemoteTxn]:
    """Serve a REQUEST frame: history covering every requested
    (agent, from_seq..) range this doc knows about.

    Exports since the *minimum* local order covering any requested id —
    possibly a superset of the ask (linear history interleaves agents),
    which is safe: the receiver's ``CausalBuffer`` trims known prefixes
    and drops duplicates idempotently. Unknown agents and already-covered
    watermarks are skipped; returns ``[]`` when nothing is owed.
    """
    start = None
    for name, from_seq in wants.items():
        aid = doc.get_agent_id(name)
        if aid is None or aid == CLIENT_INVALID:
            continue
        cd = doc.client_data[aid]
        if from_seq >= cd.get_next_seq():
            continue
        o = cd.seq_to_order(from_seq)
        start = o if start is None else min(start, o)
    if start is None:
        return []
    return export_txns_since(doc, start)

"""ctypes wrapper for the C++ native document engine.

Same Python-facing API shape as ``models.oracle.ListCRDT`` for the subset
used by benchmarks and differential tests. The native engine is the CPU
baseline (`BASELINE.md` row 1) and the host-side reference path mandated by
SURVEY §2's "TPU-build mapping" column.

Remote txns are pre-resolved here (agent names -> local ids, remote ids ->
orders for insert origins; delete targets stay (agent, seq) pairs so the
engine can walk them in seq space) and handed to the C ABI as flat arrays.
"""
from __future__ import annotations

import ctypes as ct
from typing import List, Optional, Tuple

import numpy as np

from ..common import (
    CLIENT_INVALID,
    LocalOp,
    ROOT_ORDER,
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
)
from ..native.build import build

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = ct.CDLL(build())
    u32 = ct.c_uint32
    p32 = ct.POINTER(ct.c_uint32)
    pi32 = ct.POINTER(ct.c_int32)
    lib.tcr_new.restype = ct.c_void_p
    lib.tcr_free.argtypes = [ct.c_void_p]
    lib.tcr_last_error.restype = ct.c_char_p
    lib.tcr_last_error.argtypes = [ct.c_void_p]
    lib.tcr_get_or_create_agent.restype = u32
    lib.tcr_get_or_create_agent.argtypes = [ct.c_void_p, ct.c_char_p]
    lib.tcr_agent_id.restype = ct.c_int
    lib.tcr_agent_id.argtypes = [ct.c_void_p, ct.c_char_p]
    for name in ("tcr_len", "tcr_raw_len", "tcr_next_order", "tcr_num_spans"):
        fn = getattr(lib, name)
        fn.restype = u32
        fn.argtypes = [ct.c_void_p]
    lib.tcr_apply_local_txn.restype = ct.c_int
    lib.tcr_apply_local_txn.argtypes = [ct.c_void_p, u32, u32, p32, p32, p32, p32]
    lib.tcr_apply_remote_txn.restype = ct.c_int
    lib.tcr_apply_remote_txn.argtypes = [
        ct.c_void_p, u32, u32, p32, u32, u32, p32, p32, p32, p32, p32]
    lib.tcr_seq_to_order.restype = u32
    lib.tcr_seq_to_order.argtypes = [ct.c_void_p, u32, u32]
    lib.tcr_get_spans.restype = u32
    lib.tcr_get_spans.argtypes = [ct.c_void_p, p32, p32, p32, pi32, u32]
    lib.tcr_get_frontier.restype = u32
    lib.tcr_get_frontier.argtypes = [ct.c_void_p, p32, u32]
    lib.tcr_get_deletes.restype = u32
    lib.tcr_get_deletes.argtypes = [ct.c_void_p, p32, p32, p32, u32]
    lib.tcr_get_double_deletes.restype = u32
    lib.tcr_get_double_deletes.argtypes = [ct.c_void_p, p32, p32, p32, u32]
    lib.tcr_text_utf8.restype = u32
    lib.tcr_text_utf8.argtypes = [ct.c_void_p, ct.c_char_p, u32]
    lib.tcr_replay_trace.restype = ct.c_int
    lib.tcr_replay_trace.argtypes = [ct.c_void_p, u32, u32, p32, p32, p32, p32]
    lib.tcr_rope_replay.restype = ct.c_longlong
    lib.tcr_rope_replay.argtypes = [u32, p32, p32, p32, p32, p32, u32]
    lib.tcr_memory_bytes.restype = ct.c_ulonglong
    lib.tcr_memory_bytes.argtypes = [ct.c_void_p]
    _lib = lib
    return lib


def _u32arr(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.uint32))


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ct.POINTER(ct.c_uint32))


def _cps(s: str) -> np.ndarray:
    if not s:
        return np.zeros(0, dtype=np.uint32)
    return np.frombuffer(s.encode("utf-32-le"), dtype=np.uint32)


class NativeListCRDT:
    """Native-engine document with the oracle's API subset."""

    def __init__(self):
        self._lib = _load()
        self._doc = self._lib.tcr_new()

    def __del__(self):
        try:
            if getattr(self, "_doc", None):
                self._lib.tcr_free(self._doc)
                self._doc = None
        except Exception:
            pass

    def _check(self, rc: int) -> None:
        if rc != 0:
            msg = self._lib.tcr_last_error(self._doc).decode()
            raise AssertionError(f"native engine error: {msg}")

    # -- agents ---------------------------------------------------------

    def get_or_create_agent_id(self, name: str) -> int:
        aid = self._lib.tcr_get_or_create_agent(self._doc, name.encode())
        return CLIENT_INVALID if aid == 0xFFFFFFFF else aid

    def get_agent_id(self, name: str) -> Optional[int]:
        aid = self._lib.tcr_agent_id(self._doc, name.encode())
        if aid == -2:
            return None
        return CLIENT_INVALID if aid == -1 else aid

    # -- edits ----------------------------------------------------------

    def apply_local_txn(self, agent: int, local_ops: List[LocalOp]) -> None:
        pos = _u32arr([op.pos for op in local_ops])
        dels = _u32arr([op.del_span for op in local_ops])
        ins_lens = _u32arr([len(op.ins_content) for op in local_ops])
        cps = np.concatenate([_cps(op.ins_content) for op in local_ops]) \
            if local_ops else np.zeros(0, dtype=np.uint32)
        cps = _u32arr(cps)
        self._check(self._lib.tcr_apply_local_txn(
            self._doc, agent, len(local_ops), _ptr(pos), _ptr(dels),
            _ptr(ins_lens), _ptr(cps)))

    def local_insert(self, agent: int, pos: int, content: str) -> None:
        self.apply_local_txn(agent, [LocalOp(pos=pos, ins_content=content)])

    def local_delete(self, agent: int, pos: int, del_span: int) -> None:
        self.apply_local_txn(agent, [LocalOp(pos=pos, del_span=del_span)])

    def _rid_to_order(self, rid: RemoteId) -> int:
        aid = self.get_agent_id(rid.agent)
        assert aid is not None, f"unknown agent {rid.agent!r}"
        if aid == CLIENT_INVALID:
            return ROOT_ORDER
        o = self._lib.tcr_seq_to_order(self._doc, aid, rid.seq)
        assert o != ROOT_ORDER, f"unknown seq {rid.seq} for {rid.agent!r}"
        return o

    def apply_remote_txn(self, txn: RemoteTxn) -> None:
        agent = self.get_or_create_agent_id(txn.id.agent)
        assert agent != CLIENT_INVALID, "ROOT cannot author txns"
        txn_len = sum(len(op.ins_content) if isinstance(op, RemoteIns)
                      else op.len for op in txn.ops)
        first_order = self.get_next_order()

        def rid_order(rid: RemoteId) -> int:
            if rid.agent == "ROOT":
                return ROOT_ORDER
            # Intra-txn forward reference: the engine assigns this txn's
            # order range on entry (`doc.rs:265-269`), so seqs inside
            # [txn.id.seq, txn.id.seq + txn_len) map to
            # first_order + (seq - txn.id.seq) before the C call runs.
            if rid.agent == txn.id.agent and \
                    txn.id.seq <= rid.seq < txn.id.seq + txn_len:
                return first_order + (rid.seq - txn.id.seq)
            return self._rid_to_order(rid)

        parents = _u32arr([rid_order(p) for p in txn.parents])
        kinds, A, B, L = [], [], [], []
        cps_list = []
        for op in txn.ops:
            if isinstance(op, RemoteIns):
                kinds.append(0)
                A.append(rid_order(op.origin_left))
                B.append(rid_order(op.origin_right))
                L.append(len(op.ins_content))
                cps_list.append(_cps(op.ins_content))
            else:
                assert isinstance(op, RemoteDel)
                t_aid = self.get_agent_id(op.id.agent)
                assert t_aid is not None and t_aid != CLIENT_INVALID
                kinds.append(1)
                A.append(t_aid)
                B.append(op.id.seq)
                L.append(op.len)
        cps = np.concatenate(cps_list) if cps_list else np.zeros(0, np.uint32)
        self._check(self._lib.tcr_apply_remote_txn(
            self._doc, agent, txn.id.seq, _ptr(parents), len(parents),
            len(kinds), _ptr(_u32arr(kinds)), _ptr(_u32arr(A)),
            _ptr(_u32arr(B)), _ptr(_u32arr(L)), _ptr(_u32arr(cps))))

    def replay_trace(self, agent: int, pos, dels, ins_lens, cps) -> None:
        """Replay a pre-flattened local-edit trace in one native call
        (the `benches/yjs.rs:32-49` workload)."""
        pos, dels, ins_lens, cps = map(_u32arr, (pos, dels, ins_lens, cps))
        rc = self._lib.tcr_replay_trace(
            self._doc, agent, len(pos), _ptr(pos), _ptr(dels), _ptr(ins_lens),
            _ptr(cps))
        self._check(0 if rc == 0 else -1)

    # -- read-back ------------------------------------------------------

    def __len__(self) -> int:
        return self._lib.tcr_len(self._doc)

    def raw_len(self) -> int:
        return self._lib.tcr_raw_len(self._doc)

    def num_spans(self) -> int:
        return self._lib.tcr_num_spans(self._doc)

    def memory_bytes(self) -> int:
        """Actual allocation of the document (the `alloc.rs:40-50`
        TracingAlloc role): every live vector/map buffer."""
        return self._lib.tcr_memory_bytes(self._doc)

    def get_next_order(self) -> int:
        return self._lib.tcr_next_order(self._doc)

    def to_string(self) -> str:
        n = self._lib.tcr_text_utf8(self._doc, None, 0)
        buf = ct.create_string_buffer(n)
        self._lib.tcr_text_utf8(self._doc, buf, n)
        return buf.raw[:n].decode("utf-8")

    def doc_spans(self) -> List[Tuple[int, int, int, int]]:
        """Document body as maximally RLE-merged YjsSpan tuples (canonical
        form — same as oracle.doc_spans; merge predicate `span.rs:47-53`)."""
        n = self._lib.tcr_get_spans(self._doc, None, None, None, None, 0)
        order = np.zeros(n, np.uint32)
        ol = np.zeros(n, np.uint32)
        orr = np.zeros(n, np.uint32)
        ln = np.zeros(n, np.int32)
        self._lib.tcr_get_spans(
            self._doc, _ptr(order), _ptr(ol), _ptr(orr),
            ln.ctypes.data_as(ct.POINTER(ct.c_int32)), n)
        from ..utils.rle import merge_yjs_spans
        return merge_yjs_spans(
            (int(order[i]), int(ol[i]), int(orr[i]), int(ln[i]))
            for i in range(n)
        )

    @property
    def frontier(self) -> List[int]:
        n = self._lib.tcr_get_frontier(self._doc, None, 0)
        buf = np.zeros(n, np.uint32)
        self._lib.tcr_get_frontier(self._doc, _ptr(buf), n)
        return [int(x) for x in buf]

    def deletes_entries(self) -> List[Tuple[int, int, int]]:
        n = self._lib.tcr_get_deletes(self._doc, None, None, None, 0)
        a, b, c = (np.zeros(n, np.uint32) for _ in range(3))
        self._lib.tcr_get_deletes(self._doc, _ptr(a), _ptr(b), _ptr(c), n)
        return [(int(a[i]), int(b[i]), int(c[i])) for i in range(n)]

    def double_deletes_entries(self) -> List[Tuple[int, int, int]]:
        n = self._lib.tcr_get_double_deletes(self._doc, None, None, None, 0)
        a, b, c = (np.zeros(n, np.uint32) for _ in range(3))
        self._lib.tcr_get_double_deletes(self._doc, _ptr(a), _ptr(b), _ptr(c), n)
        return [(int(a[i]), int(b[i]), int(c[i])) for i in range(n)]


def rope_replay(pos, dels, ins_lens, cps, want_content: bool = True):
    """Text-only gap-buffer replay (`benches/ropey.rs:12-38` analog): the
    lower bound the CRDT numbers are judged against — same edit stream,
    zero CRDT metadata. Returns ``(final_len, content_or_None)``."""
    lib = _load()
    pos, dels, ins_lens, cps = map(_u32arr, (pos, dels, ins_lens, cps))
    cap = int(cps.size) + 16
    out = np.zeros(cap, np.uint32) if want_content else None
    n = lib.tcr_rope_replay(len(pos), _ptr(pos), _ptr(dels), _ptr(ins_lens),
                            _ptr(cps), _ptr(out) if want_content else None,
                            cap if want_content else 0)
    if n < 0:
        raise RuntimeError("rope replay: patch out of range")
    content = None
    if want_content:
        content = out[:n].tobytes().decode("utf-32-le")
    return int(n), content

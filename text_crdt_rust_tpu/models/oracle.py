"""Host-side reference document engine — the correctness oracle.

A faithful, item-granular rebuild of the reference's ``ListCRDT``
(`src/list/doc.rs:19-511`, state at `src/list/mod.rs:52-99`). Where the
reference stores the document as a pointer B-tree of RLE ``YjsSpan`` runs
(`range_tree/`), the oracle stores **one row per character** in
struct-of-arrays numpy columns — the same flattened layout the TPU engine
uses, minus RLE compaction. This is deliberately the simplest obviously
correct representation; the C++ engine and the device engine are both
validated against it.

Semantic invariants preserved bit-exactly (SURVEY §7):

- per-item implicit origin chaining: item ``k`` of an inserted run has
  origin_left ``order+k-1`` and the run's shared origin_right
  (`list/span.rs:9-18`, `origin_left_at_offset` `span.rs:24-28`);
- tombstones are sign-flips, never removals (`span.rs:110-119`) — here a
  ``deleted`` byte column;
- the Yjs/YATA integrate scan with name-based tiebreak and the
  scanning/scan_start backtrack (`doc.rs:167-234`);
- origin_right is the item *immediately after* origin_left in raw order,
  even if deleted (`doc.rs:452-453` keeps that known quirk);
- deletes log keyed by the delete op's order; double-delete interval
  increments (`doc.rs:295-340`);
- frontier advance + txn shadow computation (`doc.rs:34-48`, `:350-374`).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..common import (
    CLIENT_INVALID,
    LocalOp,
    ROOT_ORDER,
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
)
from ..utils.rle import (
    KCRDTSpan,
    KDeleteEntry,
    KDoubleDelete,
    KOrderSpan,
    Rle,
    TxnSpan,
    increment_delete_range,
)


class ClientData:
    """Per-agent name + (seq -> order) RLE map (`list/mod.rs:33-43`)."""

    def __init__(self, name: str):
        self.name = name
        self.item_orders: Rle[KOrderSpan] = Rle()

    def get_next_seq(self) -> int:
        last = self.item_orders.last()
        return last.seq + last.length if last is not None else 0

    def seq_to_order(self, seq: int) -> int:
        found = self.item_orders.find(seq)
        assert found is not None, f"unknown seq {seq} for agent {self.name}"
        entry, offset = found
        return entry.order + offset


class ListCRDT:
    """Python oracle document (`src/list/doc.rs`)."""

    def __init__(self, capacity: int = 64):
        # Document body: one row per character, document order, tombstones
        # in place. SoA columns sized `capacity`, `n` rows live.
        self.order = np.full(capacity, ROOT_ORDER, dtype=np.uint32)
        self.origin_left = np.full(capacity, ROOT_ORDER, dtype=np.uint32)
        self.origin_right = np.full(capacity, ROOT_ORDER, dtype=np.uint32)
        self.deleted = np.zeros(capacity, dtype=bool)
        self.chars = np.zeros(capacity, dtype=np.uint32)  # unicode codepoints
        self.n = 0
        # order -> raw body index, maintained through every splice — the
        # oracle's SpaceIndex (`markers.rs:8`): ``raw_index_of_order`` is
        # one array read instead of a full-body np.nonzero scan (the
        # per-probed-char cost that capped differential-fuzz throughput).
        # -1 = order not in the body.
        self._raw_index = np.full(capacity, -1, dtype=np.int64)

        # Frontier starts at ROOT (`doc.rs:54`).
        self.frontier: List[int] = [ROOT_ORDER]
        # order -> (agent, seq) (`list/mod.rs:58-63`).
        self.client_with_order: Rle[KCRDTSpan] = Rle()
        self.client_data: List[ClientData] = []
        # Logs (`list/mod.rs:82-95`).
        self.deletes: Rle[KDeleteEntry] = Rle()
        self.double_deletes: Rle[KDoubleDelete] = Rle()
        self.txns: Rle[TxnSpan] = Rle()

    # -- agents ------------------------------------------------------------

    def get_or_create_agent_id(self, name: str) -> int:
        if name == "ROOT":
            return CLIENT_INVALID
        aid = self.get_agent_id(name)
        if aid is not None:
            return aid
        self.client_data.append(ClientData(name))
        return len(self.client_data) - 1

    def get_agent_id(self, name: str) -> Optional[int]:
        if name == "ROOT":
            return CLIENT_INVALID
        for i, cd in enumerate(self.client_data):
            if cd.name == name:
                return i
        return None

    def get_agent_name(self, agent: int) -> str:
        if agent == CLIENT_INVALID:
            return "ROOT"
        return self.client_data[agent].name

    # -- order bookkeeping -------------------------------------------------

    def get_next_order(self) -> int:
        last = self.client_with_order.last()
        return last.order + last.length if last is not None else 0

    def assign_order_to_client(self, agent: int, seq: int, order: int,
                               length: int) -> None:
        """(`doc.rs:155-165`)"""
        self.client_with_order.append(KCRDTSpan(order, agent, seq, length))
        self.client_data[agent].item_orders.append(KOrderSpan(seq, order, length))

    def agent_of_order(self, order: int) -> int:
        found = self.client_with_order.find(order)
        assert found is not None
        return found[0].agent

    def loc_of_order(self, order: int) -> Tuple[int, int]:
        """order -> (agent, seq)."""
        found = self.client_with_order.find(order)
        assert found is not None
        entry, offset = found
        return entry.agent, entry.seq + offset

    def remote_id_to_order(self, rid: RemoteId) -> int:
        """(`doc.rs:236-240`)"""
        agent = self.get_agent_id(rid.agent)
        assert agent is not None, f"unknown agent {rid.agent!r}"
        if agent == CLIENT_INVALID:
            return ROOT_ORDER
        return self.client_data[agent].seq_to_order(rid.seq)

    def order_to_remote_id(self, order: int) -> RemoteId:
        if order == ROOT_ORDER:
            return RemoteId("ROOT", 0xFFFF_FFFF)
        agent, seq = self.loc_of_order(order)
        return RemoteId(self.get_agent_name(agent), seq)

    # -- document body helpers --------------------------------------------

    def _grow(self, need: int) -> None:
        cap = len(self.order)
        if self.n + need <= cap:
            return
        new_cap = max(cap * 2, self.n + need)
        for name in ("order", "origin_left", "origin_right", "deleted", "chars"):
            old = getattr(self, name)
            fill = ROOT_ORDER if old.dtype == np.uint32 and name != "chars" else 0
            new = np.full(new_cap, fill, dtype=old.dtype)
            new[: self.n] = old[: self.n]
            setattr(self, name, new)

    def rebuild_raw_index(self) -> None:
        """Recompute the order->raw-index map from the body — for
        restore paths that set the body columns directly instead of
        splicing (``utils.checkpoint._rebuild_oracle``)."""
        n = self.n
        orders = self.order[:n].astype(np.int64)
        top = int(orders.max(initial=0)) + 1
        if top > len(self._raw_index):
            self._raw_index = np.full(top, -1, dtype=np.int64)
        else:
            self._raw_index[:] = -1
        self._raw_index[orders] = np.arange(n)

    def raw_index_of_order(self, order: int) -> int:
        """Raw (tombstones included) document index of an item — the
        oracle's stand-in for the order->leaf SpaceIndex (`doc.rs:101-107`).
        One indexed read off the splice-maintained map (``check()``
        verifies the map against the body wholesale)."""
        i = int(self._raw_index[order]) if order < len(self._raw_index) else -1
        assert 0 <= i < self.n and int(self.order[i]) == order, (
            f"order {order} not found in doc body"
        )
        return i

    def raw_index_of_live(self, content_pos: int) -> int:
        """Raw index of the ``content_pos``-th live item (0-based)."""
        live = ~self.deleted[: self.n]
        cum = np.cumsum(live)
        idx = int(np.searchsorted(cum, content_pos + 1, side="left"))
        assert idx < self.n, f"content pos {content_pos} out of range"
        return idx

    def _cursor_after(self, origin: int) -> int:
        """Raw cursor just after item ``origin`` (`doc.rs:121-136`)."""
        if origin == ROOT_ORDER:
            return 0
        return self.raw_index_of_order(origin) + 1

    # -- integrate (the YATA core) ----------------------------------------

    def _integrate(self, agent: int, first_order: int, origin_left: int,
                   origin_right: int, length: int, content: str,
                   raw_cursor: Optional[int] = None) -> int:
        """Yjs/YATA concurrent-insert conflict resolution (`doc.rs:167-234`).

        Returns the raw index the run was inserted at. Cursors are plain raw
        indices here: the reference's cursor total order (`cursor.rs:274-304`)
        collapses to integer comparison in the flat layout (SURVEY §2
        `Cursor` row).
        """
        if raw_cursor is None:
            raw_cursor = self._cursor_after(origin_left)
        cursor = raw_cursor
        left_cursor = raw_cursor
        scan_start = raw_cursor
        scanning = False

        while cursor < self.n:
            other_order = int(self.order[cursor])
            if other_order == origin_right:
                break
            other_left = int(self.origin_left[cursor])
            other_left_cursor = self._cursor_after(other_left)
            if other_left_cursor < left_cursor:
                break
            elif other_left_cursor == left_cursor:
                # Possibly-concurrent items: Yjs name tiebreak
                # (`doc.rs:204-217`) — on *agent name*, not agent id.
                my_name = self.get_agent_name(agent)
                other_name = self.get_agent_name(self.agent_of_order(other_order))
                if my_name > other_name:
                    scanning = False
                elif origin_right == int(self.origin_right[cursor]):
                    break
                else:
                    # Deliberate fix vs the reference: `doc.rs:214-216`
                    # re-pins scan_start on *every* scanning iteration, which
                    # diverges from Yjs (Item.integrate keeps `left` pinned
                    # unless o.client < this.client) and breaks N-peer
                    # convergence — e.g. merging an (origin ROOT, right ROOT)
                    # item into three chained same-origin items. Pin only on
                    # the false→true transition.
                    if not scanning:
                        scan_start = cursor
                    scanning = True
            cursor += 1
        if scanning:
            cursor = scan_start

        self._splice_in(cursor, first_order, origin_left, origin_right,
                        length, content)
        return cursor

    def _splice_in(self, at: int, first_order: int, origin_left: int,
                   origin_right: int, length: int, content: str) -> None:
        assert length > 0, "zero-length splice would corrupt neighbour origins"
        self._grow(length)
        n = self.n
        # Index upkeep costs O(moved), the same as the splice itself:
        # shifted items move +length, the new run maps to at..at+length.
        if first_order + length > len(self._raw_index):
            new = np.full(max(2 * len(self._raw_index),
                              first_order + length), -1, dtype=np.int64)
            new[: len(self._raw_index)] = self._raw_index
            self._raw_index = new
        self._raw_index[self.order[at:n].astype(np.int64)] += length
        self._raw_index[first_order: first_order + length] = np.arange(
            at, at + length)
        for name in ("order", "origin_left", "origin_right", "deleted", "chars"):
            arr = getattr(self, name)
            arr[at + length: n + length] = arr[at: n]
        orders = np.arange(first_order, first_order + length, dtype=np.uint32)
        self.order[at: at + length] = orders
        # Implicit origin chaining within the run (`span.rs:9-13,24-28`).
        self.origin_left[at] = np.uint32(origin_left)
        if length > 1:
            self.origin_left[at + 1: at + length] = orders[:-1]
        self.origin_right[at: at + length] = np.uint32(origin_right)
        self.deleted[at: at + length] = False
        if content:
            assert len(content) == length
            self.chars[at: at + length] = np.frombuffer(
                content.encode("utf-32-le"), dtype=np.uint32
            )
        self.n += length

    # -- local edits -------------------------------------------------------

    def apply_local_txn(self, agent: int, local_ops: List[LocalOp]) -> None:
        """(`doc.rs:376-469`)"""
        first_order = self.get_next_order()
        next_order = first_order

        txn_span = sum(op.del_span + len(op.ins_content) for op in local_ops)
        self.assign_order_to_client(
            agent, self.client_data[agent].get_next_seq(), first_order, txn_span
        )

        for op in local_ops:
            pos = op.pos
            if op.del_span > 0:
                next_order = self._local_deactivate(pos, op.del_span, next_order)
            if op.ins_content:
                ins_len = len(op.ins_content)
                order = next_order
                next_order += ins_len
                if pos == 0:
                    origin_left, cursor = ROOT_ORDER, 0
                else:
                    li = self.raw_index_of_live(pos - 1)
                    origin_left = int(self.order[li])
                    cursor = li + 1
                # Known reference quirk kept: origin_right does NOT skip
                # deleted items (`doc.rs:452-453`).
                origin_right = (
                    int(self.order[cursor]) if cursor < self.n else ROOT_ORDER
                )
                self._integrate(agent, order, origin_left, origin_right,
                                ins_len, op.ins_content, raw_cursor=cursor)

        self._insert_txn(None, first_order, next_order - first_order)
        assert next_order == self.get_next_order()

    def _local_deactivate(self, pos: int, del_span: int, next_order: int) -> int:
        """Tombstone ``del_span`` live items from content pos ``pos``
        (`range_tree/mutations.rs:520-570` + `doc.rs:392-433`)."""
        i = self.raw_index_of_live(pos)
        runs: List[Tuple[int, int]] = []  # (target_order_start, len), RLE-merged
        remaining = del_span
        while remaining > 0:
            assert i < self.n, "local delete past end of document"
            if self.deleted[i]:
                i += 1
                continue
            o = int(self.order[i])
            self.deleted[i] = True
            if runs and runs[-1][0] + runs[-1][1] == o:
                runs[-1] = (runs[-1][0], runs[-1][1] + 1)
            else:
                runs.append((o, 1))
            remaining -= 1
            i += 1
        for target, length in runs:
            self.deletes.append(KDeleteEntry(next_order, target, length))
            next_order += length
        return next_order

    def local_insert(self, agent: int, pos: int, content: str) -> None:
        self.apply_local_txn(agent, [LocalOp(pos=pos, ins_content=content)])

    def local_delete(self, agent: int, pos: int, del_span: int) -> None:
        self.apply_local_txn(agent, [LocalOp(pos=pos, del_span=del_span)])

    # -- remote edits ------------------------------------------------------

    def apply_remote_txn(self, txn: RemoteTxn) -> None:
        """(`doc.rs:242-348`)"""
        agent = self.get_or_create_agent_id(txn.id.agent)
        next_seq = self.client_data[agent].get_next_seq()
        # Out-of-order txns must be buffered by the caller (the reference
        # asserts here too, `doc.rs:246-247`; see parallel/causal.py).
        assert next_seq == txn.id.seq, (
            f"remote txn out of order: expected seq {next_seq}, got {txn.id.seq}"
        )

        first_order = self.get_next_order()
        next_order = first_order

        txn_len = 0
        for op in txn.ops:
            if isinstance(op, RemoteIns):
                txn_len += len(op.ins_content)
            else:
                assert op.len > 0, "zero-length RemoteDel"
                txn_len += op.len
        # Zero-length txns would create zero-length RLE log entries and break
        # frontier arithmetic (first_order + len - 1).
        assert txn_len > 0, "empty remote txn"

        self.assign_order_to_client(agent, txn.id.seq, first_order, txn_len)

        for op in txn.ops:
            if isinstance(op, RemoteIns):
                ins_len = len(op.ins_content)
                if ins_len == 0:
                    continue
                order = next_order
                next_order += ins_len
                origin_left = self.remote_id_to_order(op.origin_left)
                origin_right = self.remote_id_to_order(op.origin_right)
                self._integrate(agent, order, origin_left, origin_right,
                                ins_len, op.ins_content, raw_cursor=None)
            else:
                assert isinstance(op, RemoteDel)
                order = next_order
                next_order += op.len
                # The reference maps the target id to a local order once and
                # walks `len` *local* orders (`doc.rs:301-311`) — which
                # silently assumes the target seq range is order-contiguous
                # on every peer. It isn't in general (peers interleave txns
                # differently), so we walk the target range in *seq space*,
                # chunked through our own item_orders runs; each chunk is
                # order-contiguous locally by construction. When the
                # reference's implicit assumption holds, the deletes-log
                # entries RLE-merge back into the identical single entry.
                target_agent = self.get_agent_id(op.id.agent)
                assert target_agent is not None and target_agent != CLIENT_INVALID
                item_orders = self.client_data[target_agent].item_orders
                seq = op.id.seq
                remaining = op.len
                consumed = 0
                dd_run: Optional[Tuple[int, int]] = None  # (start, len)
                while remaining > 0:
                    found = item_orders.find(seq)
                    assert found is not None, (
                        f"delete target ({op.id.agent},{seq}) unknown"
                    )
                    entry, off = found
                    run_len = min(entry.length - off, remaining)
                    target = entry.order + off
                    # Log the delete keyed by the delete op's order
                    # (`doc.rs:305-308`).
                    self.deletes.append(
                        KDeleteEntry(order + consumed, target, run_len)
                    )
                    # Deleted items may be fragmented in doc order
                    # (`doc.rs:310-334`); double-deleted runs are counted
                    # (`mutations.rs:579-615`, `double_delete.rs:41-106`).
                    for k in range(run_len):
                        t = target + k
                        i = self.raw_index_of_order(t)
                        if self.deleted[i]:
                            if dd_run is not None and dd_run[0] + dd_run[1] == t:
                                dd_run = (dd_run[0], dd_run[1] + 1)
                            else:
                                if dd_run is not None:
                                    increment_delete_range(
                                        self.double_deletes, dd_run[0], dd_run[1])
                                dd_run = (t, 1)
                        else:
                            self.deleted[i] = True
                    seq += run_len
                    consumed += run_len
                    remaining -= run_len
                if dd_run is not None:
                    increment_delete_range(self.double_deletes,
                                           dd_run[0], dd_run[1])

        parents = [self.remote_id_to_order(p) for p in txn.parents]
        self._insert_txn(parents, first_order, txn_len)

    # -- time DAG ----------------------------------------------------------

    def _advance_branch_by(self, txn_parents: List[int], first_order: int,
                           length: int) -> None:
        """(`doc.rs:34-48`)"""
        assert first_order not in self.frontier
        self.frontier = [o for o in self.frontier if o not in txn_parents]
        self.frontier.append(first_order + length - 1)

    def _insert_txn(self, txn_parents: Optional[List[int]], first_order: int,
                    length: int) -> None:
        """(`doc.rs:350-374`)"""
        last_order = first_order + length - 1
        if txn_parents is not None:
            self._advance_branch_by(txn_parents, first_order, length)
        else:
            txn_parents = self.frontier
            self.frontier = [last_order]

        shadow = first_order
        while shadow >= 1 and (shadow - 1) in txn_parents:
            found = self.txns.find(shadow - 1)
            assert found is not None
            shadow = found[0].shadow

        self.txns.append(TxnSpan(first_order, length, shadow, list(txn_parents)))

    # -- read-back ---------------------------------------------------------

    def __len__(self) -> int:
        """Live character count (`doc.rs:484-486`)."""
        return int(np.count_nonzero(~self.deleted[: self.n]))

    def to_string(self) -> str:
        live = ~self.deleted[: self.n]
        cps = self.chars[: self.n][live]
        return cps.astype("<u4").tobytes().decode("utf-32-le")

    def doc_spans(self) -> List[Tuple[int, int, int, int]]:
        """Document body as maximally RLE-merged YjsSpan tuples
        (order, origin_left, origin_right, signed_len) — the canonical
        compacted form used to compare engines."""
        from ..utils.rle import merge_yjs_spans
        return merge_yjs_spans(
            (int(self.order[i]), int(self.origin_left[i]),
             int(self.origin_right[i]), -1 if self.deleted[i] else 1)
            for i in range(self.n)
        )

    def position_of_order(self, order: int) -> int:
        """Content position of a live item (inverse lookup, `cursor.rs:147-190`)."""
        i = self.raw_index_of_order(order)
        return int(np.count_nonzero(~self.deleted[:i]))

    def check(self) -> None:
        """Structure invariants (`root.rs:242-253` ethos)."""
        n = self.n
        orders = self.order[:n]
        assert len(np.unique(orders)) == n, "duplicate orders in doc body"
        # The order->raw-index map must agree with the body everywhere.
        assert bool((self._raw_index[orders.astype(np.int64)]
                     == np.arange(n)).all()), "order index diverged from body"
        self.client_with_order.check()
        self.deletes.check()
        self.double_deletes.check()
        for cd in self.client_data:
            cd.item_orders.check()
        # Every assigned insert order appears in the body exactly once:
        # body orders == all orders minus delete-op orders.
        total = self.get_next_order()
        del_ops = sum(e.length for e in self.deletes)
        assert n == total - del_ops, (
            f"body has {n} items, expected {total - del_ops}"
        )

    def print_stats(self, detailed: bool = False) -> None:
        """(`doc.rs:492-498` analog)"""
        spans = self.doc_spans()
        print(f"oracle doc: {self.n} items, {len(self)} live, "
              f"{len(spans)} merged spans "
              f"(compaction {self.n / max(1, len(spans)):.1f}x)")
        print(f"  deletes: {self.deletes.num_entries()} entries; "
              f"double_deletes: {self.double_deletes.num_entries()}; "
              f"txns: {self.txns.num_entries()}")

from .oracle import ClientData, ListCRDT

__all__ = ["ClientData", "ListCRDT"]

// tcr_engine.cpp — native host-side list-CRDT document engine.
//
// C++ rebuild of the reference ListCRDT (`/root/reference/src/list/doc.rs`)
// with a different core container: instead of the reference's pointer-based
// RLE B-tree with subtree sums (`src/range_tree/`), the document body is an
// order-statistic *treap of RLE YjsSpan runs* with two augmentations per
// subtree — raw item count and live (content) count — which gives the same
// O(log n) position<->item conversions (`README.md:20-26`) with split/merge
// instead of node-splitting B-tree mutations (`range_tree/mutations.rs`).
//
// Semantics preserved from the reference:
//  * YjsSpan origin fix-ups on split (`list/span.rs:33-45,68-85`) and the
//    append merge predicate (`span.rs:47-53`);
//  * tombstones are len sign-flips (`span.rs:110-119`);
//  * Yjs/YATA integrate with name tiebreak (`doc.rs:167-234`), including the
//    scan_start pinning fix documented in models/oracle.py;
//  * deletes keyed by the delete op's order (`list/mod.rs:82-84`), remote
//    delete targets walked in seq space (see models/oracle.py rationale),
//    double-delete interval increments (`double_delete.rs:41-106`);
//  * frontier advance + txn shadow (`doc.rs:34-48`, `:350-374`).
//
// Exposed as a C ABI for ctypes (models/native.py).

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <string>
#include <vector>
#include <map>
#include <algorithm>

typedef uint32_t u32;
typedef int32_t i32;
typedef uint64_t u64;

static const u32 ROOT_ORDER = 0xFFFFFFFFu;
static const u32 AGENT_ROOT = 0xFFFFFFFFu;
static const int NIL = -1;

// ---------------------------------------------------------------- treap ----

struct Node {
    u32 order;        // first order of the span
    u32 ol;           // origin_left of the first item (`span.rs:9-13`)
    u32 orr;          // origin_right shared by all items (`span.rs:15-18`)
    i32 len;          // signed; negative = deleted (`span.rs:20`)
    u32 pri;          // treap priority
    int l, r, p;      // children + parent
    u32 sum_raw;      // subtree sum of |len|
    u32 sum_content;  // subtree sum of max(len, 0)
};

static inline u32 uabs(i32 x) { return (u32)(x < 0 ? -x : x); }

struct Rng {
    u64 s;
    explicit Rng(u64 seed) : s(seed) {}
    u32 next() {
        // xorshift64* — deterministic priorities.
        s ^= s >> 12; s ^= s << 25; s ^= s >> 27;
        return (u32)((s * 0x2545F4914F6CDD1DULL) >> 32);
    }
};

// ------------------------------------------------------------ RLE logs ----

struct CwoEntry { u32 order, agent, seq, len; };       // client_with_order
struct IoEntry  { u32 seq, order, len; };              // item_orders (per agent)
struct DelEntry { u32 op_order, target, len; };        // deletes log
struct DDEntry  { u32 target, len, excess; };          // double_deletes
struct TxnEntry {
    u32 order, len, shadow;
    std::vector<u32> parents;
};

struct ClientData {
    std::string name;
    std::vector<IoEntry> item_orders;
    u32 next_seq() const {
        if (item_orders.empty()) return 0;
        const IoEntry& e = item_orders.back();
        return e.seq + e.len;
    }
    // seq -> order (`doc.rs:26-29`); returns false if unknown.
    bool seq_to_order(u32 seq, u32* out) const {
        size_t lo = 0, hi = item_orders.size();
        while (lo < hi) {
            size_t mid = (lo + hi) / 2;
            if (item_orders[mid].seq <= seq) lo = mid + 1; else hi = mid;
        }
        if (lo == 0) return false;
        const IoEntry& e = item_orders[lo - 1];
        if (seq >= e.seq + e.len) return false;
        *out = e.order + (seq - e.seq);
        return true;
    }
    // Find the run containing seq: returns index or -1.
    int find_run(u32 seq) const {
        size_t lo = 0, hi = item_orders.size();
        while (lo < hi) {
            size_t mid = (lo + hi) / 2;
            if (item_orders[mid].seq <= seq) lo = mid + 1; else hi = mid;
        }
        if (lo == 0) return -1;
        const IoEntry& e = item_orders[lo - 1];
        if (seq >= e.seq + e.len) return -1;
        return (int)(lo - 1);
    }
};

// ---------------------------------------------------------------- doc ----

struct Doc {
    std::vector<Node> nodes;
    int root = NIL;
    Rng rng;
    // span start order -> node id. Splits only create new right halves;
    // the one op that changes an existing start is the prepend-merge fast
    // path, which re-keys its entry in place.
    std::map<u32, int> order_index;
    std::vector<u32> chars;  // codepoint per *insert* order (delete ops: gaps)
    std::vector<int> free_nodes;  // slots freed by the tombstone merge
    u32 n_spans = 0;              // live span count (nodes minus freed)

    std::vector<CwoEntry> client_with_order;
    std::vector<ClientData> clients;
    std::vector<DelEntry> deletes;
    std::vector<DDEntry> double_deletes;
    std::vector<TxnEntry> txns;
    std::vector<u32> frontier;

    std::string last_error;

    Doc() : rng(0x9E3779B97F4A7C15ULL) { frontier.push_back(ROOT_ORDER); }

    // ---- treap plumbing ----

    inline u32 raw(int t) const { return t == NIL ? 0 : nodes[t].sum_raw; }
    inline u32 content(int t) const { return t == NIL ? 0 : nodes[t].sum_content; }

    inline void pull(int t) {
        Node& n = nodes[t];
        n.sum_raw = uabs(n.len) + raw(n.l) + raw(n.r);
        n.sum_content = (u32)std::max(n.len, 0) + content(n.l) + content(n.r);
        if (n.l != NIL) nodes[n.l].p = t;
        if (n.r != NIL) nodes[n.r].p = t;
    }

    int new_node(u32 order, u32 ol, u32 orr, i32 len) {
        Node n;
        n.order = order; n.ol = ol; n.orr = orr; n.len = len;
        n.pri = rng.next();
        n.l = n.r = n.p = NIL;
        n.sum_raw = uabs(len);
        n.sum_content = (u32)std::max(len, 0);
        int id;
        if (!free_nodes.empty()) {
            id = free_nodes.back();
            free_nodes.pop_back();
            nodes[id] = n;
        } else {
            nodes.push_back(n);
            id = (int)nodes.size() - 1;
        }
        order_index[order] = id;
        n_spans++;
        return id;
    }

    // Detach a node the tombstone merge absorbed into a neighbor: its
    // orders are now covered by the neighbor's order_index entry. The
    // caller settles the order_index keys itself (the absorbed start key
    // is erased on an append-merge but RE-POINTED on a prepend-merge).
    void discard_node(int id) {
        free_nodes.push_back(id);
        n_spans--;
    }

    // Split at measure k: a = minimal prefix whose measure is k, b = rest.
    // BY_CONTENT=false splits by raw item count; BY_CONTENT=true splits by
    // live-char count (boundary tombstones, measure 0, go to b — "minimal
    // prefix"). The in-span cut keeps the `span.rs:33-45` origin fix-up:
    // right half gets order+off, origin_left = order+off-1. A content cut
    // can only land inside a live span, where raw and live offsets
    // coincide, so one inner branch serves both.
    // NB: `nodes` may reallocate inside new_node(); never hold a Node&
    // across it.
    template <bool BY_CONTENT>
    void split_impl(int t, u32 k, int* a, int* b) {
        if (t == NIL) { *a = *b = NIL; return; }
        u32 lm = BY_CONTENT ? content(nodes[t].l) : raw(nodes[t].l);
        u32 sl = BY_CONTENT ? (u32)std::max(nodes[t].len, 0)
                            : uabs(nodes[t].len);
        if (k <= lm) {
            int nl;
            split_impl<BY_CONTENT>(nodes[t].l, k, a, &nl);
            nodes[t].l = nl;
            *b = t;
            nodes[t].p = NIL;
            pull(t);
        } else if (k >= lm + sl) {
            int nr;
            split_impl<BY_CONTENT>(nodes[t].r, k - lm - sl, &nr, b);
            nodes[t].r = nr;
            *a = t;
            nodes[t].p = NIL;
            pull(t);
        } else {
            u32 off = k - lm;
            i32 sign = nodes[t].len < 0 ? -1 : 1;  // BY_CONTENT: always +1
            u32 o = nodes[t].order;
            u32 orr_ = nodes[t].orr;
            i32 rest_len = nodes[t].len - sign * (i32)off;
            int old_r = nodes[t].r;
            int rid = new_node(o + off, o + off - 1, orr_, rest_len);
            nodes[t].len = sign * (i32)off;
            // rid takes t's right subtree — it must inherit t's priority to
            // keep the heap invariant over that subtree.
            nodes[rid].pri = nodes[t].pri;
            nodes[rid].r = old_r;
            nodes[t].r = NIL;
            pull(rid);
            pull(t);
            *a = t; nodes[t].p = NIL;
            *b = rid; nodes[rid].p = NIL;
        }
    }

    void split(int t, u32 k, int* a, int* b) {
        split_impl<false>(t, k, a, b);
    }

    void split_content(int t, u32 k, int* a, int* b) {
        split_impl<true>(t, k, a, b);
    }

    int merge(int a, int b) {
        if (a == NIL) { if (b != NIL) nodes[b].p = NIL; return b; }
        if (b == NIL) { nodes[a].p = NIL; return a; }
        if (nodes[a].pri > nodes[b].pri) {
            int m = merge(nodes[a].r, b);
            nodes[a].r = m;
            pull(a);
            nodes[a].p = NIL;
            return a;
        } else {
            int m = merge(a, nodes[b].l);
            nodes[b].l = m;
            pull(b);
            nodes[b].p = NIL;
            return b;
        }
    }

    u32 n_raw() const { return raw(root); }
    u32 n_content() const { return content(root); }

    // Raw index of the item at content position p, rolling forward past
    // tombstones (cursor_at_content_pos(pos, false), `root.rs:406`).
    // p == content total -> n_raw() (end cursor).
    u32 raw_of_content(u32 p) const {
        int t = root;
        u32 base = 0;
        while (t != NIL) {
            const Node& n = nodes[t];
            u32 lc = content(n.l);
            if (p < lc) { t = n.l; continue; }
            p -= lc;
            u32 lr = raw(n.l);
            u32 c = (u32)std::max(n.len, 0);
            if (p < c) return base + lr + p;
            p -= c;
            base += lr + uabs(n.len);
            t = n.r;
        }
        return base;
    }

    // Content position count of live items strictly before raw index k.
    u32 content_before_raw(u32 k) const {
        int t = root;
        u32 acc = 0;
        while (t != NIL) {
            const Node& n = nodes[t];
            u32 lr = raw(n.l);
            u32 sl = uabs(n.len);
            if (k <= lr) { t = n.l; continue; }
            acc += content(n.l);
            if (k < lr + sl) {
                if (n.len > 0) acc += k - lr;
                return acc;
            }
            acc += (u32)std::max(n.len, 0);
            k -= lr + sl;
            t = n.r;
        }
        return acc;
    }

    // (node, offset) at raw index k; false at end.
    bool item_at_raw(u32 k, int* nid, u32* off) const {
        int t = root;
        while (t != NIL) {
            const Node& n = nodes[t];
            u32 lr = raw(n.l);
            u32 sl = uabs(n.len);
            if (k < lr) { t = n.l; continue; }
            if (k < lr + sl) { *nid = t; *off = k - lr; return true; }
            k -= lr + sl;
            t = n.r;
        }
        return false;
    }

    // Raw position of (node, offset) by walking parents — the analog of
    // `cursor.count_pos()` (`cursor.rs:147-190`), but in raw coordinates.
    u32 raw_position_of(int nid, u32 off) const {
        const Node& n = nodes[nid];
        u32 pos = raw(n.l) + off;
        int cur = nid;
        int par = n.p;
        while (par != NIL) {
            const Node& pn = nodes[par];
            if (pn.r == cur) pos += raw(pn.l) + uabs(pn.len);
            cur = par;
            par = pn.p;
        }
        return pos;
    }

    // Find the span node containing an item order (SpaceIndex analog,
    // `doc.rs:101-107`): the order_index map plays the role of the
    // order->leaf-pointer SplitList.
    bool node_of_order(u32 order, int* nid, u32* off) const {
        auto it = order_index.upper_bound(order);
        if (it == order_index.begin()) return false;
        --it;
        int t = it->second;
        const Node& n = nodes[t];
        if (order < n.order || order >= n.order + uabs(n.len)) return false;
        *nid = t; *off = order - n.order;
        return true;
    }

    // Raw cursor just after item `origin` (`doc.rs:121-136`).
    bool cursor_after(u32 origin, u32* out) const {
        if (origin == ROOT_ORDER) { *out = 0; return true; }
        int nid; u32 off;
        if (!node_of_order(origin, &nid, &off)) return false;
        *out = raw_position_of(nid, off) + 1;
        return true;
    }

    // ---- agents / orders ----

    int get_agent_id(const char* name) const {
        if (strcmp(name, "ROOT") == 0) return (int)AGENT_ROOT;
        for (size_t i = 0; i < clients.size(); i++)
            if (clients[i].name == name) return (int)i;
        return -2;  // unknown
    }

    u32 get_or_create_agent(const char* name) {
        int a = get_agent_id(name);
        if (a != -2) return (u32)a;
        ClientData cd; cd.name = name;
        clients.push_back(cd);
        return (u32)(clients.size() - 1);
    }

    u32 next_order() const {
        if (client_with_order.empty()) return 0;
        const CwoEntry& e = client_with_order.back();
        return e.order + e.len;
    }

    void assign_order_to_client(u32 agent, u32 seq, u32 order, u32 len) {
        // (`doc.rs:155-165`) with KVPair-style RLE merging.
        if (!client_with_order.empty()) {
            CwoEntry& e = client_with_order.back();
            if (e.order + e.len == order && e.agent == agent &&
                e.seq + e.len == seq) {
                e.len += len;
            } else {
                client_with_order.push_back({order, agent, seq, len});
            }
        } else {
            client_with_order.push_back({order, agent, seq, len});
        }
        ClientData& cd = clients[agent];
        if (!cd.item_orders.empty()) {
            IoEntry& e = cd.item_orders.back();
            if (e.seq + e.len == seq && e.order + e.len == order) {
                e.len += len;
                return;
            }
        }
        cd.item_orders.push_back({seq, order, len});
    }

    bool agent_of_order(u32 order, u32* agent) const {
        size_t lo = 0, hi = client_with_order.size();
        while (lo < hi) {
            size_t mid = (lo + hi) / 2;
            if (client_with_order[mid].order <= order) lo = mid + 1; else hi = mid;
        }
        if (lo == 0) return false;
        const CwoEntry& e = client_with_order[lo - 1];
        if (order >= e.order + e.len) return false;
        *agent = e.agent;
        return true;
    }

    bool loc_of_order(u32 order, u32* agent, u32* seq) const {
        size_t lo = 0, hi = client_with_order.size();
        while (lo < hi) {
            size_t mid = (lo + hi) / 2;
            if (client_with_order[mid].order <= order) lo = mid + 1; else hi = mid;
        }
        if (lo == 0) return false;
        const CwoEntry& e = client_with_order[lo - 1];
        if (order >= e.order + e.len) return false;
        *agent = e.agent;
        *seq = e.seq + (order - e.order);
        return true;
    }

    // ---- logs ----

    void deletes_append(u32 op_order, u32 target, u32 len) {
        if (!deletes.empty()) {
            DelEntry& e = deletes.back();
            if (e.op_order + e.len == op_order && e.target + e.len == target) {
                e.len += len;
                return;
            }
        }
        deletes.push_back({op_order, target, len});
    }

    // Gap-aware interval increment (`double_delete.rs:41-106`).
    void increment_delete_range(u32 base, u32 len) {
        // Find first entry with key > base, step back.
        size_t idx;
        {
            size_t lo = 0, hi = double_deletes.size();
            while (lo < hi) {
                size_t mid = (lo + hi) / 2;
                if (double_deletes[mid].target <= base) lo = mid + 1; else hi = mid;
            }
            if (lo > 0 && base < double_deletes[lo - 1].target +
                                  double_deletes[lo - 1].len)
                idx = lo - 1;
            else
                idx = lo;
        }
        u32 nb = base, nl = len;
        while (true) {
            if (idx == double_deletes.size() || double_deletes[idx].target > nb) {
                u32 this_len = nl;
                bool done = true;
                if (idx < double_deletes.size() &&
                    nb + nl > double_deletes[idx].target) {
                    this_len = double_deletes[idx].target - nb;
                    done = false;
                }
                if (idx >= 1 && double_deletes[idx - 1].target +
                                double_deletes[idx - 1].len == nb &&
                    double_deletes[idx - 1].excess == 1) {
                    double_deletes[idx - 1].len += this_len;
                } else {
                    double_deletes.insert(double_deletes.begin() + idx,
                                          {nb, this_len, 1});
                    idx++;
                }
                if (done) break;
                nb += this_len; nl -= this_len;
            }
            DDEntry& e = double_deletes[idx];
            if (e.target < nb) {
                u32 at = nb - e.target;
                DDEntry rest = {nb, e.len - at, e.excess};
                e.len = at;
                idx++;
                double_deletes.insert(double_deletes.begin() + idx, rest);
            }
            DDEntry& e2 = double_deletes[idx];
            if (e2.len <= nl) {
                e2.excess += 1;
                nb += e2.len; nl -= e2.len;
                if (nl == 0) break;
                idx++;
            } else {
                DDEntry rest = {nb + nl, e2.len - nl, e2.excess};
                e2.len = nl;
                e2.excess += 1;
                double_deletes.insert(double_deletes.begin() + idx + 1, rest);
                break;
            }
        }
    }

    // ---- time DAG (`doc.rs:34-48`, `:350-374`) ----

    bool branch_contains(const std::vector<u32>& b, u32 o) const {
        return std::find(b.begin(), b.end(), o) != b.end();
    }

    u32 txn_shadow_of(u32 order) const {
        size_t lo = 0, hi = txns.size();
        while (lo < hi) {
            size_t mid = (lo + hi) / 2;
            if (txns[mid].order <= order) lo = mid + 1; else hi = mid;
        }
        return txns[lo - 1].shadow;
    }

    void insert_txn(bool has_parents, std::vector<u32> parents,
                    u32 first_order, u32 len) {
        u32 last_order = first_order + len - 1;
        if (has_parents) {
            std::vector<u32> nf;
            for (u32 o : frontier)
                if (!branch_contains(parents, o)) nf.push_back(o);
            nf.push_back(last_order);
            frontier = nf;
        } else {
            parents = frontier;
            frontier.clear();
            frontier.push_back(last_order);
        }
        u32 shadow = first_order;
        while (shadow >= 1 && branch_contains(parents, shadow - 1))
            shadow = txn_shadow_of(shadow - 1);

        if (!txns.empty()) {
            TxnEntry& e = txns.back();
            if (parents.size() == 1 && parents[0] == e.order + e.len - 1 &&
                shadow == e.shadow) {
                e.len += len;
                return;
            }
        }
        TxnEntry t; t.order = first_order; t.len = len; t.shadow = shadow;
        t.parents = parents;
        txns.push_back(t);
    }

    // ---- integrate (`doc.rs:167-234`) ----

    // Bump every sum on the path node -> root by (draw, dcontent). The
    // in-place fast paths use this instead of full pull()s: one add per
    // level, no child re-reads.
    inline void bump_sums(int nid, i32 draw, i32 dcontent) {
        for (int c = nid; c != NIL; c = nodes[c].p) {
            nodes[c].sum_raw = (u32)((i32)nodes[c].sum_raw + draw);
            nodes[c].sum_content = (u32)((i32)nodes[c].sum_content + dcontent);
        }
    }

    // Insert a run at raw position `cursor`, merging into the predecessor
    // span when the YjsSpan append predicate allows (`span.rs:47-53`).
    // (No prepend case here: orders are allocated monotonically and
    // integrated immediately, so a fresh run can never precede an existing
    // span in order space. The reference's prepend optimization
    // `mutations.rs:84-109` is about *tombstones* — see local_deactivate.)
    void insert_run_at(u32 cursor, u32 order, u32 ol, u32 orr, u32 len) {
        // Fast path 1 (the typing hot path): the item just before the
        // cursor is the END of a live span the run appends to. Extend the
        // span in place — no split/merge node churn, just a sum walk.
        if (cursor > 0 && ol != ROOT_ORDER) {
            int nid; u32 off;
            if (item_at_raw(cursor - 1, &nid, &off)) {
                Node& pn = nodes[nid];
                if (pn.len > 0 && off == (u32)pn.len - 1 &&
                    order == pn.order + (u32)pn.len &&
                    ol == order - 1 && orr == pn.orr) {
                    pn.len += (i32)len;
                    bump_sums(nid, (i32)len, (i32)len);
                    return;
                }
            }
        }
        int a, b;
        split(root, cursor, &a, &b);
        int nn = new_node(order, ol, orr, (i32)len);
        root = merge(merge(a, nn), b);
    }

    bool integrate(u32 agent, u32 order, u32 ol, u32 orr, u32 len,
                   bool have_cursor, u32 cursor_in) {
        u32 cursor;
        if (have_cursor) cursor = cursor_in;
        else if (!cursor_after(ol, &cursor)) return fail("unknown origin_left");

        u32 left_cursor = cursor;
        u32 scan_start = cursor;
        bool scanning = false;
        u32 n = n_raw();

        while (cursor < n) {
            int nid; u32 off;
            if (!item_at_raw(cursor, &nid, &off)) break;
            const Node& on = nodes[nid];
            u32 other_order = on.order + off;
            if (other_order == orr) break;
            u32 other_left = (off == 0) ? on.ol : other_order - 1;
            u32 olc;
            if (!cursor_after(other_left, &olc))
                return fail("unknown other origin_left");
            if (olc < left_cursor) break;
            if (olc == left_cursor) {
                u32 other_agent;
                if (!agent_of_order(on.order, &other_agent))
                    return fail("unknown agent of span");
                const std::string& my_name = clients[agent].name;
                const std::string& other_name = clients[other_agent].name;
                if (my_name > other_name) {
                    scanning = false;
                } else if (orr == on.orr) {
                    break;
                } else {
                    // Pin on the first conflicting item only — see
                    // models/oracle.py on the reference's `doc.rs:214-216`.
                    if (!scanning) scan_start = cursor;
                    scanning = true;
                }
            }
            cursor++;
        }
        if (scanning) cursor = scan_start;
        insert_run_at(cursor, order, ol, orr, len);
        return true;
    }

    // ---- local edits (`doc.rs:376-469`) ----

    bool fail(const char* msg) { last_error = msg; return false; }

    // Tombstone del_span live items from content pos (`mutations.rs:520-570`).
    // Appends delete-log entries using op orders starting at *next_order_io.
    bool local_deactivate(u32 pos, u32 del_span, u32* next_order_io) {
        if (pos + del_span > n_content()) return fail("delete past end");
        u32 i = raw_of_content(pos);
        int a, m, c, rest;
        split(root, i, &a, &rest);
        // Content split keeps boundary tombstones out of m, so flip_live
        // walks exactly the spans covering the del_span live chars.
        split_content(rest, del_span, &m, &c);
        // Flip all live spans in m (in-order), collecting delete runs.
        std::vector<std::pair<u32, u32>> runs;
        flip_live(m, runs);
        // Tombstone boundary merge — the real analog of the reference's
        // prepend optimization (`mutations.rs:84-109`, "improves
        // performance when the user hits backspace... merging all the
        // deleted elements together"): when the freshly flipped span is a
        // single node, try to absorb it into an order-adjacent tombstone
        // neighbor (the span.rs:47-53 predicate, both signs negative).
        // Backspace runs merge rightward; forward-delete runs leftward.
        if (m != NIL && nodes[m].l == NIL && nodes[m].r == NIL) {
            const Node& mn = nodes[m];
            if (a != NIL) {   // append m after a's rightmost span
                int t = a;
                while (nodes[t].r != NIL) t = nodes[t].r;
                const Node& ra = nodes[t];
                if (ra.len < 0 && mn.order == ra.order + uabs(ra.len) &&
                    mn.ol == mn.order - 1 && mn.orr == ra.orr) {
                    u32 grow = uabs(mn.len);
                    order_index.erase(mn.order);
                    nodes[t].len -= (i32)grow;   // more negative
                    for (int w = a; ; w = nodes[w].r) {
                        nodes[w].sum_raw += grow;
                        if (w == t) break;
                    }
                    discard_node(m);
                    root = merge(a, c);
                    return finish_deactivate(runs, next_order_io);
                }
            }
            if (c != NIL) {   // prepend m before c's leftmost span
                int t = c;
                while (nodes[t].l != NIL) t = nodes[t].l;
                const Node& cl = nodes[t];
                if (cl.len < 0 && cl.order == mn.order + uabs(mn.len) &&
                    cl.ol == cl.order - 1 && cl.orr == mn.orr) {
                    u32 grow = uabs(mn.len);
                    order_index.erase(cl.order);
                    nodes[t].order = mn.order;
                    nodes[t].ol = mn.ol;
                    nodes[t].len -= (i32)grow;
                    order_index[mn.order] = t;  // re-points m's old entry
                    for (int w = c; ; w = nodes[w].l) {
                        nodes[w].sum_raw += grow;
                        if (w == t) break;
                    }
                    discard_node(m);
                    root = merge(a, c);
                    return finish_deactivate(runs, next_order_io);
                }
            }
        }
        root = merge(merge(a, m), c);
        return finish_deactivate(runs, next_order_io);
    }

    bool finish_deactivate(const std::vector<std::pair<u32, u32>>& runs,
                           u32* next_order_io) {
        u32 nord = *next_order_io;
        for (auto& rn : runs) {
            deletes_append(nord, rn.first, rn.second);
            nord += rn.second;
        }
        *next_order_io = nord;
        return true;
    }

    void flip_live(int t, std::vector<std::pair<u32, u32>>& runs) {
        if (t == NIL) return;
        Node& n = nodes[t];
        flip_live(n.l, runs);
        if (n.len > 0) {
            // extend_delete RLE merge on consecutive target orders
            // (`root.rs:9-17`).
            if (!runs.empty() &&
                runs.back().first + runs.back().second == n.order)
                runs.back().second += (u32)n.len;
            else
                runs.push_back({n.order, (u32)n.len});
            n.len = -n.len;
        }
        flip_live(n.r, runs);
        pull(t);
    }

    bool local_insert_op(u32 agent, u32 pos, const u32* cps, u32 ins_len,
                         u32 order) {
        u32 origin_left, cursor;
        if (pos == 0) {
            origin_left = ROOT_ORDER;
            cursor = 0;
        } else {
            if (pos > n_content()) return fail("insert pos out of range");
            u32 li = raw_of_content(pos - 1);
            int nid; u32 off;
            if (!item_at_raw(li, &nid, &off)) return fail("bad content pos");
            origin_left = nodes[nid].order + off;
            cursor = li + 1;
        }
        // origin_right: next item in raw order even if deleted
        // (`doc.rs:452-453` quirk kept).
        u32 origin_right = ROOT_ORDER;
        {
            int nid; u32 off;
            if (item_at_raw(cursor, &nid, &off))
                origin_right = nodes[nid].order + off;
        }
        for (u32 k = 0; k < ins_len; k++) chars_set(order + k, cps[k]);
        return integrate(agent, order, origin_left, origin_right, ins_len,
                         true, cursor);
    }

    void chars_set(u32 order, u32 cp) {
        if (chars.size() <= order) chars.resize(order + 1, 0);
        chars[order] = cp;
    }

    bool apply_local_txn(u32 agent, u32 n_ops, const u32* pos_arr,
                         const u32* del_arr, const u32* ins_len_arr,
                         const u32* ins_cps /* concatenated */) {
        u32 first_order = next_order();
        u32 next = first_order;
        u32 txn_span = 0;
        for (u32 i = 0; i < n_ops; i++)
            txn_span += del_arr[i] + ins_len_arr[i];
        if (txn_span == 0) return fail("empty txn");
        assign_order_to_client(agent, clients[agent].next_seq(), first_order,
                               txn_span);
        const u32* cp = ins_cps;
        for (u32 i = 0; i < n_ops; i++) {
            if (del_arr[i] > 0) {
                if (!local_deactivate(pos_arr[i], del_arr[i], &next))
                    return false;
            }
            if (ins_len_arr[i] > 0) {
                u32 order = next;
                next += ins_len_arr[i];
                if (!local_insert_op(agent, pos_arr[i], cp, ins_len_arr[i],
                                     order))
                    return false;
                cp += ins_len_arr[i];
            }
        }
        insert_txn(false, {}, first_order, txn_span);
        return true;
    }

    // ---- remote edits (`doc.rs:242-348`) ----

    bool remote_deactivate_chunk(u32 target, u32 chunk_len, u32* dd_base,
                                 u32* dd_len) {
        // Deactivate chunk_len order-consecutive items starting at `target`;
        // they may be fragmented in doc order (`doc.rs:310-334`).
        u32 remaining = chunk_len;
        while (remaining > 0) {
            int nid; u32 off;
            if (!node_of_order(target, &nid, &off))
                return fail("unknown delete target");
            u32 span_rest = uabs(nodes[nid].len) - off;
            u32 m = std::min(span_rest, remaining);
            bool was_deleted = nodes[nid].len < 0;
            if (was_deleted) {
                // Already deleted by another peer: count double deletes
                // (`mutations.rs:579-615` negative return path).
                if (*dd_len > 0 && *dd_base + *dd_len == target) {
                    *dd_len += m;
                } else {
                    if (*dd_len > 0) increment_delete_range(*dd_base, *dd_len);
                    *dd_base = target; *dd_len = m;
                }
            } else {
                u32 k = raw_position_of(nid, off);
                int a, mm, c;
                split(root, k + m, &a, &c);
                split(a, k, &a, &mm);
                // mm is exactly one span of m live items.
                nodes[mm].len = -nodes[mm].len;
                pull(mm);
                root = merge(merge(a, mm), c);
            }
            target += m;
            remaining -= m;
        }
        return true;
    }

    bool apply_remote_ins(u32 agent, u32 order, u32 ol, u32 orr,
                          const u32* cps, u32 len) {
        for (u32 k = 0; k < len; k++) chars_set(order + k, cps[k]);
        return integrate(agent, order, ol, orr, len, false, 0);
    }

    bool apply_remote_del(u32 target_agent, u32 seq, u32 total_len,
                          u32 op_order) {
        // Walk targets in seq space chunked through our item_orders runs
        // (see models/oracle.py rationale).
        ClientData& cd = clients[target_agent];
        u32 remaining = total_len, consumed = 0;
        u32 dd_base = 0, dd_len = 0;
        while (remaining > 0) {
            int ri = cd.find_run(seq);
            if (ri < 0) return fail("unknown delete target seq");
            const IoEntry& e = cd.item_orders[ri];
            u32 off = seq - e.seq;
            u32 run_len = std::min(e.len - off, remaining);
            u32 target = e.order + off;
            deletes_append(op_order + consumed, target, run_len);
            if (!remote_deactivate_chunk(target, run_len, &dd_base, &dd_len))
                return false;
            seq += run_len; consumed += run_len; remaining -= run_len;
        }
        if (dd_len > 0) increment_delete_range(dd_base, dd_len);
        return true;
    }

    // ---- read-back ----

    void collect_spans(int t, std::vector<Node>& out) const {
        if (t == NIL) return;
        collect_spans(nodes[t].l, out);
        out.push_back(nodes[t]);
        collect_spans(nodes[t].r, out);
    }

    std::string to_string_utf8() const {
        std::vector<Node> spans;
        collect_spans(root, spans);
        std::string out;
        out.reserve(n_content() * 2);
        for (const Node& s : spans) {
            if (s.len <= 0) continue;
            for (i32 k = 0; k < s.len; k++) {
                u32 cp = (s.order + (u32)k) < chars.size()
                             ? chars[s.order + (u32)k] : 0;
                // UTF-8 encode.
                if (cp < 0x80) out.push_back((char)cp);
                else if (cp < 0x800) {
                    out.push_back((char)(0xC0 | (cp >> 6)));
                    out.push_back((char)(0x80 | (cp & 0x3F)));
                } else if (cp < 0x10000) {
                    out.push_back((char)(0xE0 | (cp >> 12)));
                    out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
                    out.push_back((char)(0x80 | (cp & 0x3F)));
                } else {
                    out.push_back((char)(0xF0 | (cp >> 18)));
                    out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
                    out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
                    out.push_back((char)(0x80 | (cp & 0x3F)));
                }
            }
        }
        return out;
    }
};

// ------------------------------------------------------------- C ABI ----

extern "C" {

void* tcr_new() { return new Doc(); }
void tcr_free(void* d) { delete (Doc*)d; }

const char* tcr_last_error(void* d) { return ((Doc*)d)->last_error.c_str(); }

u32 tcr_get_or_create_agent(void* d, const char* name) {
    return ((Doc*)d)->get_or_create_agent(name);
}

u32 tcr_len(void* d) { return ((Doc*)d)->n_content(); }
u32 tcr_raw_len(void* d) { return ((Doc*)d)->n_raw(); }
u32 tcr_next_order(void* d) { return ((Doc*)d)->next_order(); }
u32 tcr_num_spans(void* d) { return ((Doc*)d)->n_spans; }

// Actual allocation of the document (the `alloc.rs:40-50` role): every
// live vector/map buffer, in bytes.
unsigned long long tcr_memory_bytes(void* dv) {
    Doc* d = (Doc*)dv;
    unsigned long long b = 0;
    b += d->nodes.capacity() * sizeof(Node);
    b += d->order_index.size() * (sizeof(u32) + sizeof(int) + 48);  // map node
    b += d->chars.capacity() * sizeof(u32);
    b += d->free_nodes.capacity() * sizeof(int);
    b += d->client_with_order.capacity() * sizeof(CwoEntry);
    b += d->deletes.capacity() * sizeof(DelEntry);
    b += d->double_deletes.capacity() * sizeof(DDEntry);
    b += d->txns.capacity() * sizeof(TxnEntry);
    b += d->frontier.capacity() * sizeof(u32);
    for (auto& c : d->clients) {
        b += sizeof(ClientData) + c.name.size();
        b += c.item_orders.capacity() * sizeof(IoEntry);
    }
    return b;
}

int tcr_apply_local_txn(void* dv, u32 agent, u32 n_ops, const u32* pos,
                        const u32* dels, const u32* ins_lens,
                        const u32* ins_cps) {
    Doc* d = (Doc*)dv;
    if (agent >= d->clients.size()) {
        d->last_error = "invalid agent id";
        return -1;
    }
    return d->apply_local_txn(agent, n_ops, pos, dels, ins_lens, ins_cps)
               ? 0 : -1;
}

int tcr_local_insert(void* dv, u32 agent, u32 pos, const u32* cps, u32 len) {
    u32 zero = 0;
    return tcr_apply_local_txn(dv, agent, 1, &pos, &zero, &len, cps);
}

int tcr_local_delete(void* dv, u32 agent, u32 pos, u32 del_span) {
    u32 zero = 0;
    return tcr_apply_local_txn(dv, agent, 1, &pos, &del_span, &zero, nullptr);
}

// Remote txn, pre-resolved by the Python wrapper into numeric form:
//   agent: local agent id (created by caller)
//   seq:   txn start seq
//   parents: array of orders (already remote_id_to_order-mapped), len n_parents
//   ops encoded as flat arrays: kinds[i] (0=ins, 1=del),
//     A[i]: ins -> origin_left order; del -> target agent id
//     B[i]: ins -> origin_right order; del -> target seq
//     L[i]: op length
//   cps: concatenated insert codepoints.
int tcr_apply_remote_txn(void* dv, u32 agent, u32 seq, const u32* parents,
                         u32 n_parents, u32 n_ops, const u32* kinds,
                         const u32* A, const u32* B, const u32* L,
                         const u32* cps) {
    Doc* d = (Doc*)dv;
    if (agent >= d->clients.size()) {
        d->last_error = "invalid agent id (ROOT cannot author txns)";
        return -1;
    }
    for (u32 i = 0; i < n_ops; i++) {
        if (kinds[i] == 1 && A[i] >= d->clients.size()) {
            d->last_error = "invalid delete target agent";
            return -1;
        }
    }
    if (d->clients[agent].next_seq() != seq) {
        d->last_error = "remote txn out of order";
        return -1;
    }
    u32 first_order = d->next_order();
    u32 txn_len = 0;
    for (u32 i = 0; i < n_ops; i++) txn_len += L[i];
    if (txn_len == 0) { d->last_error = "empty txn"; return -1; }
    d->assign_order_to_client(agent, seq, first_order, txn_len);
    u32 next = first_order;
    const u32* cp = cps;
    for (u32 i = 0; i < n_ops; i++) {
        if (kinds[i] == 0) {
            if (L[i] == 0) continue;
            u32 order = next; next += L[i];
            if (!d->apply_remote_ins(agent, order, A[i], B[i], cp, L[i]))
                return -1;
            cp += L[i];
        } else {
            u32 order = next; next += L[i];
            if (!d->apply_remote_del(A[i], B[i], L[i], order)) return -1;
        }
    }
    std::vector<u32> ps(parents, parents + n_parents);
    d->insert_txn(true, ps, first_order, txn_len);
    return 0;
}

u32 tcr_seq_to_order(void* dv, u32 agent, u32 seq) {
    Doc* d = (Doc*)dv;
    if (agent == AGENT_ROOT) return ROOT_ORDER;
    u32 out;
    if (!d->clients[agent].seq_to_order(seq, &out)) return ROOT_ORDER;
    return out;
}

int tcr_agent_id(void* dv, const char* name) {
    return ((Doc*)dv)->get_agent_id(name);
}

// Dump the document body spans in doc order. Returns span count
// (call with cap=0 to size). Arrays: order, origin_left, origin_right,
// signed len.
u32 tcr_get_spans(void* dv, u32* order, u32* ol, u32* orr, i32* len, u32 cap) {
    Doc* d = (Doc*)dv;
    std::vector<Node> spans;
    d->collect_spans(d->root, spans);
    u32 n = (u32)spans.size();
    if (cap >= n && order) {
        for (u32 i = 0; i < n; i++) {
            order[i] = spans[i].order;
            ol[i] = spans[i].ol;
            orr[i] = spans[i].orr;
            len[i] = spans[i].len;
        }
    }
    return n;
}

u32 tcr_get_frontier(void* dv, u32* out, u32 cap) {
    Doc* d = (Doc*)dv;
    u32 n = (u32)d->frontier.size();
    if (cap >= n && out)
        for (u32 i = 0; i < n; i++) out[i] = d->frontier[i];
    return n;
}

u32 tcr_get_deletes(void* dv, u32* op_order, u32* target, u32* len, u32 cap) {
    Doc* d = (Doc*)dv;
    u32 n = (u32)d->deletes.size();
    if (cap >= n && op_order)
        for (u32 i = 0; i < n; i++) {
            op_order[i] = d->deletes[i].op_order;
            target[i] = d->deletes[i].target;
            len[i] = d->deletes[i].len;
        }
    return n;
}

u32 tcr_get_double_deletes(void* dv, u32* target, u32* len, u32* excess,
                           u32 cap) {
    Doc* d = (Doc*)dv;
    u32 n = (u32)d->double_deletes.size();
    if (cap >= n && target)
        for (u32 i = 0; i < n; i++) {
            target[i] = d->double_deletes[i].target;
            len[i] = d->double_deletes[i].len;
            excess[i] = d->double_deletes[i].excess;
        }
    return n;
}

u32 tcr_text_utf8(void* dv, char* buf, u32 cap) {
    std::string s = ((Doc*)dv)->to_string_utf8();
    u32 n = (u32)s.size();
    if (cap >= n && buf) memcpy(buf, s.data(), n);
    return n;
}

// Replay a whole pre-flattened local-edit trace in one call (the CPU
// baseline path, mirroring `benches/yjs.rs:32-49`). Patches arrays:
// pos[i], del[i], ins_len[i]; cps = concatenated insert codepoints.
// One txn per patch. Returns 0 or -1.
int tcr_replay_trace(void* dv, u32 agent, u32 n_patches, const u32* pos,
                     const u32* dels, const u32* ins_lens, const u32* cps) {
    Doc* d = (Doc*)dv;
    if (agent >= d->clients.size()) {
        d->last_error = "invalid agent id";
        return -1;
    }
    const u32* cp = cps;
    for (u32 i = 0; i < n_patches; i++) {
        if (!d->apply_local_txn(agent, 1, &pos[i], &dels[i], &ins_lens[i], cp))
            return -1;  // failing patch context is in last_error
        cp += ins_lens[i];
    }
    return 0;
}

// Text-only replay baseline (`benches/ropey.rs:12-38` analog): a gap
// buffer of u32 codepoints — the rope stand-in that measures what the
// same edit stream costs with NO CRDT metadata at all, the lower bound
// CRDT numbers are judged against. Returns the final length; if `out`
// is non-null and holds >= that many u32s, the final content is copied.
long long tcr_rope_replay(u32 n_patches, const u32* pos, const u32* dels,
                          const u32* ins_lens, const u32* cps,
                          u32* out, u32 out_cap) {
    std::vector<u32> buf(4096);
    size_t gap_at = 0, gap_len = buf.size();  // [gap_at, gap_at+gap_len)
    const u32* cp = cps;
    for (u32 i = 0; i < n_patches; i++) {
        size_t n = buf.size() - gap_len;
        size_t p = pos[i], d = dels[i], il = ins_lens[i];
        if (p > n || p + d > n) return -1;
        // Move the gap to p (the rope's cursor locality: consecutive
        // edits at nearby positions cost near-zero moves).
        if (p < gap_at) {
            std::memmove(buf.data() + p + gap_len, buf.data() + p,
                         (gap_at - p) * sizeof(u32));
            gap_at = p;
        } else if (p > gap_at) {
            std::memmove(buf.data() + gap_at, buf.data() + gap_at + gap_len,
                         (p - gap_at) * sizeof(u32));
            gap_at = p;
        }
        gap_len += d;  // delete = widen the gap over the removed chars
        if (il > gap_len) {  // grow: double until the insert fits
            size_t live = buf.size() - gap_len;  // post-delete live count
            size_t tail = buf.size() - (gap_at + gap_len);
            size_t need = buf.size();
            while (need - live < il) need *= 2;
            std::vector<u32> nb(need);
            std::memcpy(nb.data(), buf.data(), gap_at * sizeof(u32));
            std::memcpy(nb.data() + need - tail,
                        buf.data() + buf.size() - tail, tail * sizeof(u32));
            gap_len = need - live;
            buf.swap(nb);
        }
        std::memcpy(buf.data() + gap_at, cp, il * sizeof(u32));
        cp += il;
        gap_at += il;
        gap_len -= il;
    }
    size_t n = buf.size() - gap_len;
    if (out && out_cap >= n) {
        std::memcpy(out, buf.data(), gap_at * sizeof(u32));
        std::memcpy(out + gap_at, buf.data() + gap_at + gap_len,
                    (n - gap_at) * sizeof(u32));
    }
    return (long long)n;
}

}  // extern "C"

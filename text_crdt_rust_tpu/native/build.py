"""Build the native engine shared library with g++ (no pip deps).

Usage: ``python -m text_crdt_rust_tpu.native.build`` or just import
``text_crdt_rust_tpu.models.native`` (builds on demand, cached by source
hash).
"""
from __future__ import annotations

import hashlib
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "tcr_engine.cpp")
BUILD_DIR = os.path.join(HERE, "_build")


def _src_hash() -> str:
    with open(SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def lib_path() -> str:
    return os.path.join(BUILD_DIR, f"libtcr_{_src_hash()}.so")


def build(verbose: bool = False) -> str:
    """Compile (if needed) and return the shared-library path."""
    out = lib_path()
    if os.path.exists(out):
        return out
    os.makedirs(BUILD_DIR, exist_ok=True)
    # Compile to a temp path and rename into place so a concurrent builder
    # can never dlopen a partially written library.
    tmp = f"{out}.tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-std=c++17", "-fPIC", "-shared",
        "-march=native", "-fno-exceptions", "-fno-rtti",
        SRC, "-o", tmp,
    ]
    if verbose:
        print(" ".join(cmd), file=sys.stderr)
    subprocess.run(cmd, check=True)
    os.rename(tmp, out)
    return out


if __name__ == "__main__":
    print(build(verbose=True))

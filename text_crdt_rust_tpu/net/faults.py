"""Deterministic fault injection for the replication wire.

A ``FaultyChannel`` sits between two peers and mangles frames the way a
real network does — drops, duplicates, reorders, truncations, bit-flips —
under a seeded RNG so every fuzz failure replays exactly. The convergence
contract under test (PAPER.md §1, Yjs/YATA model): whatever this channel
does, the receiving peer must either converge bit-identically after
resync or reject the frame with a typed error. Zero uncaught exceptions.

Faults are rolled independently per frame at ``send`` time (so one frame
can be both duplicated and bit-flipped); ``reorder`` is applied at
``drain`` time by moving a marked frame to a random later position in the
delivery batch. Counters record every injected fault for assertion
against the session metrics.
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class FaultSpec:
    """Per-frame fault probabilities (independent rolls)."""

    drop: float = 0.0
    duplicate: float = 0.0
    reorder: float = 0.0
    truncate: float = 0.0
    bitflip: float = 0.0

    @classmethod
    def all(cls, p: float) -> "FaultSpec":
        return cls(drop=p, duplicate=p, reorder=p, truncate=p, bitflip=p)


@dataclass
class FaultyChannel:
    """One-directional frame pipe with seeded fault injection."""

    spec: FaultSpec = field(default_factory=FaultSpec)
    seed: int = 0

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)
        # (frame, reorder_marked) pending delivery.
        self._queue: List[tuple] = []
        self.counters: Dict[str, int] = {
            "sent": 0, "dropped": 0, "duplicated": 0, "reordered": 0,
            "truncated": 0, "bitflipped": 0, "delivered": 0,
        }

    # -- fault transforms ---------------------------------------------------

    def _truncate(self, frame: bytes) -> bytes:
        if len(frame) <= 1:
            return b""
        return frame[: self._rng.randrange(0, len(frame))]

    def _bitflip(self, frame: bytes) -> bytes:
        if not frame:
            return frame
        i = self._rng.randrange(len(frame))
        bit = 1 << self._rng.randrange(8)
        out = bytearray(frame)
        out[i] ^= bit
        return bytes(out)

    # -- pipe ---------------------------------------------------------------

    def send(self, frame: bytes) -> None:
        rng = self._rng
        self.counters["sent"] += 1
        if rng.random() < self.spec.drop:
            self.counters["dropped"] += 1
            return
        copies = 1
        if rng.random() < self.spec.duplicate:
            self.counters["duplicated"] += 1
            copies = 2
        for _ in range(copies):
            f = frame
            if rng.random() < self.spec.truncate:
                self.counters["truncated"] += 1
                f = self._truncate(f)
            if rng.random() < self.spec.bitflip:
                self.counters["bitflipped"] += 1
                f = self._bitflip(f)
            marked = rng.random() < self.spec.reorder
            self._queue.append((f, marked))

    def drain(self) -> List[bytes]:
        """Deliver everything queued, applying reorders, and reset."""
        batch = self._queue
        self._queue = []
        out: List[bytes] = []
        deferred: List[bytes] = []
        for frame, marked in batch:
            if marked:
                deferred.append(frame)
            else:
                out.append(frame)
        for frame in deferred:
            pos = self._rng.randrange(len(out) + 1)
            if pos != len(out):
                self.counters["reordered"] += 1
            out.insert(pos, frame)
        self.counters["delivered"] += len(out)
        return out

    @property
    def pending(self) -> int:
        return len(self._queue)

"""Binary wire codec for peer replication frames.

The reference's ``RemoteTxn``/``RemoteOp``/``RemoteId`` structs are the
only peer-portable history representation (`external_txn.rs:5-30`), but it
never serializes them. This codec puts them on an actual wire, following
automerge's columnar-binary playbook in spirit (compact varints, string
table, checksummed chunks — see PAPERS.md) while keeping the frame layout
simple enough to audit byte-by-byte:

``frame := MAGIC(1B) VERSION(1B) varint(payload_len) payload CRC32C(4B LE)``

- the CRC32C (Castagnoli) covers *everything* before it — magic, version,
  the length varint and the payload — so any truncation or single-byte
  corruption anywhere in the frame is detected (CRC32 detects all burst
  errors up to 32 bits);
- agent names appear once per frame in a string table; every id in the
  body is a (table index, seq) varint pair (`README.md:33-35`: only the
  name strings are peer-portable — numeric ids and orders are peer-local);
- ``payload := kind(1B) body``: kind 0 carries a ``RemoteTxn`` batch,
  kinds 1/2 are the session layer's control messages (range REQUEST and
  watermark+state DIGEST, `net/session.py`).

Every malformed input raises ``CodecError`` with a precise message —
never an ``IndexError``/``UnicodeDecodeError``/assertion. Decoding is
hardened against adversarial lengths: varints are width-capped, declared
lengths are bounds-checked against the buffer before any allocation, and
the payload cursor must land exactly on the declared end.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Sequence, Tuple, Union

from ..common import (
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
    txn_len,
    validate_remote_txn,
)
from ..utils.integrity import crc32c

MAGIC = 0xC7
FRAME_VERSION = 1            # row-oriented body (this module)
FRAME_VERSION_COLUMNAR = 2   # columnar body (net/columnar.py)
_FRAME_VERSIONS = (FRAME_VERSION, FRAME_VERSION_COLUMNAR)

# Wire-format names (ServeConfig.wire_format / session knobs) -> TXNS
# encoder.  Decoding needs no selection: ``decode_frame`` negotiates on
# the version byte, so peers on different formats interoperate.
WIRE_FORMATS = ("row", "columnar")


def txns_encoder(wire: str):
    """The ``encode_txns`` implementation for a wire-format name."""
    if wire == "row":
        return encode_txns
    if wire == "columnar":
        from . import columnar
        return columnar.encode_txns
    raise ValueError(f"unknown wire format {wire!r}; one of {WIRE_FORMATS}")

# Frame kinds (first payload byte).
KIND_TXNS = 0      # batch of RemoteTxns
KIND_REQUEST = 1   # per-agent "send me seqs >= from_seq" wants
KIND_DIGEST = 2    # per-agent watermarks + portable state digest
KIND_TXNS_MUX = 3  # v2 only: many docs' txn batches on one connection

_MAX_PAYLOAD = 1 << 28   # 256 MiB: reject absurd declared lengths early
_MAX_NAME_BYTES = 4096   # agent names are human-scale identifiers
_MAX_VARINT_BYTES = 10   # 64-bit LEB128
_U32_MAX = 0xFFFF_FFFF


class CodecError(ValueError):
    """A frame failed validation (framing, CRC, version, or body shape).

    The recoverable rejection path: the session layer counts it and
    re-requests the range; it must never surface as a crash.

    When the failure is txn-level (the frame decoded but a txn failed
    ``validate_remote_txn``), ``agent``/``seq``/``n`` name the offending
    span so the reject trace event can carry the op's identity (ISSUE 11
    satellite) — structurally-undecodable frames leave them ``None``
    (there is no span to name)."""

    def __init__(self, message: str, *, agent=None, seq=None, n=None):
        super().__init__(message)
        self.agent = agent
        self.seq = seq
        self.n = n


# -- varints -----------------------------------------------------------------

def _write_varint(out: bytearray, value: int) -> None:
    assert value >= 0
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, cur: int, end: int) -> Tuple[int, int]:
    value = 0
    shift = 0
    for _ in range(_MAX_VARINT_BYTES):
        if cur >= end:
            raise CodecError("truncated varint")
        b = buf[cur]
        cur += 1
        value |= (b & 0x7F) << shift
        if not b & 0x80:
            return value, cur
        shift += 7
    raise CodecError("varint too long")


# -- string table ------------------------------------------------------------

class _NameTable:
    """First-seen-order agent-name table for one frame."""

    def __init__(self) -> None:
        self.names: List[str] = []
        self._ids: Dict[str, int] = {}

    def idx(self, name: str) -> int:
        i = self._ids.get(name)
        if i is None:
            i = self._ids[name] = len(self.names)
            self.names.append(name)
        return i


def _collect_names(txns: Sequence[RemoteTxn]) -> _NameTable:
    table = _NameTable()
    for txn in txns:
        table.idx(txn.id.agent)
        for p in txn.parents:
            table.idx(p.agent)
        for op in txn.ops:
            if isinstance(op, RemoteIns):
                table.idx(op.origin_left.agent)
                table.idx(op.origin_right.agent)
            else:
                table.idx(op.id.agent)
    return table


def _write_str(out: bytearray, s: str) -> None:
    raw = s.encode("utf-8")
    _write_varint(out, len(raw))
    out += raw


def _read_str(buf: bytes, cur: int, end: int, what: str,
              max_bytes: int = _MAX_PAYLOAD) -> Tuple[str, int]:
    """One length-prefixed UTF-8 string, bounds-checked; the single
    hardening point for every string the wire carries."""
    ln, cur = _read_varint(buf, cur, end)
    if ln > max_bytes:
        raise CodecError(f"{what} of {ln} bytes exceeds cap {max_bytes}")
    if ln > end - cur:
        raise CodecError(f"truncated {what}")
    try:
        s = buf[cur:cur + ln].decode("utf-8")
    except UnicodeDecodeError as e:
        raise CodecError(f"{what} not utf-8: {e}") from None
    return s, cur + ln


def _check_name(name: str) -> str:
    """Encode-side twin of the decoder's name cap: emitting an oversized
    name would produce frames every compliant peer rejects — fail fast at
    the source instead of poisoning the re-request cycle."""
    if len(name.encode("utf-8")) > _MAX_NAME_BYTES:
        raise CodecError(
            f"agent name of {len(name.encode('utf-8'))} bytes exceeds "
            f"cap {_MAX_NAME_BYTES}")
    return name


def _write_names(out: bytearray, names: Sequence[str]) -> None:
    _write_varint(out, len(names))
    for name in names:
        _write_str(out, _check_name(name))


def _read_names(buf: bytes, cur: int, end: int) -> Tuple[List[str], int]:
    count, cur = _read_varint(buf, cur, end)
    if count > end - cur:  # each name costs >= 1 byte
        raise CodecError("name table longer than payload")
    names: List[str] = []
    for _ in range(count):
        name, cur = _read_str(buf, cur, end, "agent name",
                              max_bytes=_MAX_NAME_BYTES)
        names.append(name)
    return names, cur


def _write_rid(out: bytearray, table: _NameTable, rid: RemoteId) -> None:
    _write_varint(out, table.idx(rid.agent))
    _write_varint(out, rid.seq)


def _read_rid(buf: bytes, cur: int, end: int,
              names: Sequence[str]) -> Tuple[RemoteId, int]:
    idx, cur = _read_varint(buf, cur, end)
    if idx >= len(names):
        raise CodecError(f"agent index {idx} out of table range {len(names)}")
    seq, cur = _read_varint(buf, cur, end)
    if seq > _U32_MAX:
        raise CodecError(f"seq {seq} exceeds u32")
    return RemoteId(names[idx], seq), cur


# -- framing -----------------------------------------------------------------

def _frame(payload: bytes, version: int = FRAME_VERSION) -> bytes:
    out = bytearray([MAGIC, version])
    _write_varint(out, len(payload))
    out += payload
    out += struct.pack("<I", crc32c(bytes(out)))
    return bytes(out)


class FrameInfo:
    """Frame-layer metadata ``decode_frame_ex`` plumbs through to the
    receiver (ISSUE 11): the stored CRC32C doubles as a content-derived
    **frame id** — deterministic across same-seed runs, identical for a
    dup-delivered frame — so per-op flow events can name WHICH frame
    carried a span without any wire-format change."""

    __slots__ = ("version", "crc", "length")

    def __init__(self, version: int, crc: int, length: int):
        self.version = version
        self.crc = crc
        self.length = length


def _unframe(buf: bytes, offset: int) -> Tuple[int, bytes, int]:
    """Validate one frame at ``offset``; return
    ``(version, payload, next_offset)``."""
    total = len(buf)
    if offset >= total:
        raise CodecError("empty input")
    if buf[offset] != MAGIC:
        raise CodecError(f"bad magic byte 0x{buf[offset]:02x}")
    if offset + 2 > total:
        raise CodecError("truncated header")
    ln, cur = _read_varint(buf, offset + 2, total)
    if ln > _MAX_PAYLOAD:
        raise CodecError(f"declared payload length {ln} too large")
    payload_end = cur + ln
    if payload_end + 4 > total:
        raise CodecError("frame truncated (payload or CRC missing)")
    stored = struct.unpack_from("<I", buf, payload_end)[0]
    computed = crc32c(bytes(buf[offset:payload_end]))
    if stored != computed:
        raise CodecError(
            f"CRC mismatch: stored {stored:#010x} != computed {computed:#010x}")
    # Version is checked after the CRC: a corrupted version byte reports as
    # a CRC failure; a *valid* frame from a future format reports here.
    if buf[offset + 1] not in _FRAME_VERSIONS:
        raise CodecError(f"unsupported frame version {buf[offset + 1]}")
    return buf[offset + 1], bytes(buf[cur:payload_end]), payload_end + 4


# -- KIND_TXNS ---------------------------------------------------------------

def encode_txns(txns: Sequence[RemoteTxn]) -> bytes:
    """One frame carrying a ``RemoteTxn`` batch."""
    for txn in txns:
        validate_remote_txn(txn)
    table = _collect_names(txns)
    body = bytearray([KIND_TXNS])
    _write_names(body, table.names)
    _write_varint(body, len(txns))
    for txn in txns:
        _write_rid(body, table, txn.id)
        _write_varint(body, len(txn.parents))
        for p in txn.parents:
            _write_rid(body, table, p)
        _write_varint(body, len(txn.ops))
        for op in txn.ops:
            if isinstance(op, RemoteIns):
                body.append(0)
                _write_rid(body, table, op.origin_left)
                _write_rid(body, table, op.origin_right)
                _write_str(body, op.ins_content)
            else:
                body.append(1)
                _write_rid(body, table, op.id)
                _write_varint(body, op.len)
    return _frame(bytes(body))


def _decode_txns(buf: bytes, cur: int, end: int) -> List[RemoteTxn]:
    names, cur = _read_names(buf, cur, end)
    count, cur = _read_varint(buf, cur, end)
    if count > end - cur:  # each txn costs >= 1 byte
        raise CodecError("txn count longer than payload")
    txns: List[RemoteTxn] = []
    for _ in range(count):
        tid, cur = _read_rid(buf, cur, end, names)
        n_parents, cur = _read_varint(buf, cur, end)
        if n_parents > end - cur:
            raise CodecError("parent count longer than payload")
        parents: List[RemoteId] = []
        for _ in range(n_parents):
            p, cur = _read_rid(buf, cur, end, names)
            parents.append(p)
        n_ops, cur = _read_varint(buf, cur, end)
        if n_ops > end - cur:
            raise CodecError("op count longer than payload")
        ops: List[Union[RemoteIns, RemoteDel]] = []
        for _ in range(n_ops):
            if cur >= end:
                raise CodecError("truncated op tag")
            tag = buf[cur]
            cur += 1
            if tag == 0:
                ol, cur = _read_rid(buf, cur, end, names)
                orr, cur = _read_rid(buf, cur, end, names)
                content, cur = _read_str(buf, cur, end, "insert content")
                ops.append(RemoteIns(ol, orr, content))
            elif tag == 1:
                rid, cur = _read_rid(buf, cur, end, names)
                ln, cur = _read_varint(buf, cur, end)
                # Cap like seqs: an unchecked huge length would poison the
                # receiver's per-agent watermark (seq + len) forever.
                if ln > _U32_MAX or rid.seq + ln > _U32_MAX + 1:
                    raise CodecError(f"delete length {ln} exceeds u32 range")
                ops.append(RemoteDel(rid, ln))
            else:
                raise CodecError(f"unknown op tag {tag}")
        txn = RemoteTxn(tid, parents, ops)
        try:
            validate_remote_txn(txn)
        except ValueError as e:
            # Name the offending span: the frame's bytes were sound, so
            # the op's identity is known and the reject can carry it.
            raise CodecError(f"invalid txn: {e}", agent=tid.agent,
                             seq=tid.seq, n=txn_len(txn)) from None
        txns.append(txn)
    if cur != end:
        raise CodecError(f"{end - cur} trailing bytes after txn batch")
    return txns


# -- KIND_REQUEST / KIND_DIGEST ----------------------------------------------

def _write_name_map(body: bytearray, mapping: Dict[str, int]) -> None:
    _write_varint(body, len(mapping))
    for name in sorted(mapping):
        _write_str(body, _check_name(name))
        _write_varint(body, mapping[name])


def encode_request(wants: Dict[str, int]) -> bytes:
    """REQUEST frame: for each agent name, "send me seqs >= from_seq"."""
    body = bytearray([KIND_REQUEST])
    _write_name_map(body, wants)
    return _frame(bytes(body))


def encode_digest(watermarks: Dict[str, int], digest: int) -> bytes:
    """DIGEST frame: per-agent next-seq watermarks + portable state digest
    (``models.sync.state_digest``)."""
    body = bytearray([KIND_DIGEST])
    _write_name_map(body, watermarks)
    body += struct.pack("<I", digest & _U32_MAX)
    return _frame(bytes(body))


def _decode_name_map(buf: bytes, cur: int, end: int
                     ) -> Tuple[Dict[str, int], int]:
    count, cur = _read_varint(buf, cur, end)
    if count > end - cur:
        raise CodecError("map longer than payload")
    out: Dict[str, int] = {}
    for _ in range(count):
        name, cur = _read_str(buf, cur, end, "agent name",
                              max_bytes=_MAX_NAME_BYTES)
        seq, cur = _read_varint(buf, cur, end)
        if seq > _U32_MAX:
            raise CodecError(f"seq {seq} exceeds u32")
        out[name] = seq
    return out, cur


# -- public decode -----------------------------------------------------------

def decode_frame(buf: bytes, offset: int = 0):
    """Decode ONE frame at ``offset``.

    Returns ``(kind, value, next_offset)`` where ``value`` is a txn list
    (KIND_TXNS), a wants dict (KIND_REQUEST), or a ``(watermarks, digest)``
    pair (KIND_DIGEST). Raises ``CodecError`` on any malformed input.
    """
    kind, value, next_offset, _info = decode_frame_ex(buf, offset)
    return kind, value, next_offset


def decode_frame_ex(buf: bytes, offset: int = 0):
    """``decode_frame`` plus a ``FrameInfo`` fourth element: the
    receiver-side frame-id plumb-through (the stored CRC32C, already
    verified by ``_unframe``) for flow provenance and audit logs."""
    version, payload, next_offset = _unframe(buf, offset)
    info = FrameInfo(version,
                     struct.unpack_from("<I", buf, next_offset - 4)[0],
                     next_offset - offset)
    if not payload:
        raise CodecError("empty payload")
    kind = payload[0]
    cur, end = 1, len(payload)
    if version == FRAME_VERSION_COLUMNAR:
        # Version 2 defines only the columnar TXNS bodies; control
        # frames (REQUEST/DIGEST) stay version 1 — they are name maps
        # with no columnar gear to gain.
        from . import columnar
        if kind == KIND_TXNS:
            return KIND_TXNS, columnar.decode_txns(payload, cur, end), \
                next_offset, info
        if kind == KIND_TXNS_MUX:
            return KIND_TXNS_MUX, \
                columnar.decode_txns_mux(payload, cur, end), \
                next_offset, info
        raise CodecError(f"frame kind {kind} not defined for version 2")
    if kind == KIND_TXNS:
        return KIND_TXNS, _decode_txns(payload, cur, end), \
            next_offset, info
    if kind == KIND_REQUEST:
        wants, cur = _decode_name_map(payload, cur, end)
        if cur != end:
            raise CodecError("trailing bytes after request")
        return KIND_REQUEST, wants, next_offset, info
    if kind == KIND_DIGEST:
        marks, cur = _decode_name_map(payload, cur, end)
        if cur + 4 != end:
            raise CodecError("bad digest trailer")
        digest = struct.unpack_from("<I", payload, cur)[0]
        return KIND_DIGEST, (marks, digest), next_offset, info
    raise CodecError(f"unknown frame kind {kind}")


def decode_frames(buf: bytes) -> List[Tuple[int, object]]:
    """Decode a back-to-back frame stream; ``[(kind, value), ...]``."""
    out: List[Tuple[int, object]] = []
    offset = 0
    while offset < len(buf):
        kind, value, offset = decode_frame(buf, offset)
        out.append((kind, value))
    return out

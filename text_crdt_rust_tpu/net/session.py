"""Anti-entropy resync sessions over the binary wire.

One ``ResyncSession`` is a peer endpoint: a host oracle document, a
bounded ``CausalBuffer``, and the codec. Peers exchange three frame
kinds (``net/codec.py``):

- TXNS    — new history, broadcast since the last poll;
- DIGEST  — per-agent watermarks + portable state digest
  (`models.sync.state_digest`), the gossip that detects both *gaps*
  (peer's watermark ahead of mine — maybe every frame from an agent was
  dropped, so the causal buffer alone can't see it) and *divergence*
  (equal watermarks, unequal digests — the "must never happen" CRDT
  failure, surfaced instead of silently served);
- REQUEST — per-agent "send me seqs >= from_seq" pulls for missing
  ranges, paced by capped exponential backoff on a logical tick clock
  (deterministic under test; no wall-clock in the protocol).

Failure handling is total: corrupt frames are counted and dropped
(``CodecError`` — the digest/request cycle re-covers the loss), buffer
overflow evicts-and-re-requests instead of growing unboundedly, a gap
that outlives ``retry_limit`` re-requests raises ``CausalGapError`` (a
peer is gone or the range is unrecoverable — the caller's cue to find
another replica), and a device-engine mirror that would overflow its
fixed capacity *degrades to the host oracle* rather than asserting.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..common import CLIENT_INVALID, RemoteIns, RemoteTxn, txn_len
from ..models.oracle import ListCRDT
from ..models.sync import (
    agent_watermarks,
    export_txns_for_wants,
    export_txns_since,
    state_digest,
)
from ..parallel.causal import CausalBuffer
from ..utils.metrics import Counters
from . import codec
from .codec import CodecError

# Txns per TXNS frame: small enough that one lost frame costs little,
# large enough to amortize the string table.
TXNS_PER_FRAME = 8


class CausalGapError(RuntimeError):
    """A missing range outlived the re-request budget.

    Raised by ``ResyncSession.poll`` when a gap has been re-requested
    ``retry_limit`` times without the watermark moving — the sending
    replica is gone or never had the range. Carries what was missing so
    the caller can redirect the pull at another replica.
    """

    def __init__(self, missing: Dict[str, int], attempts: int):
        self.missing = dict(missing)
        self.attempts = attempts
        super().__init__(
            f"unrecoverable causal gap after {attempts} re-requests: "
            f"{self.missing}")


class DeviceMirror:
    """Device-engine shadow of a *receive-only* session's document.

    Released remote txns are compiled (`ops.batch.compile_remote_txns`)
    and applied to a ``FlatDoc`` alongside the oracle. The mirror is an
    accelerator, not the source of truth: any condition it cannot handle
    — capacity or order-log overflow, an agent not pre-registered in its
    rank table (rank re-basing is an epoch-boundary operation,
    `ops.batch.rank_remap`) — flips ``degraded`` and the session keeps
    serving from the oracle. Never an assert on the serving path.

    ``agents`` must pre-register every peer name that will appear in the
    stream (ranks bake into compiled steps). Local edits do not flow
    through ``apply``; use mirrors on receive-only replicas.
    """

    def __init__(self, capacity: int, order_capacity: Optional[int] = None,
                 agents: tuple = (), lmax: int = 8):
        from ..ops import batch as B
        from ..ops import span_arrays as SA

        self.table = B.AgentTable(agents)
        self.assigner = None
        self.doc = SA.make_flat_doc(capacity, order_capacity)
        self.lmax = lmax
        self.degraded = False
        self.degrade_reason = ""

    def _degrade(self, reason: str, counters: Counters) -> None:
        self.degraded = True
        self.degrade_reason = reason
        counters.incr("device_degraded")

    def apply(self, txns: List[RemoteTxn], counters: Counters) -> None:
        from ..ops import batch as B
        from ..ops import flat as F

        if self.degraded or not txns:
            return
        names = set()
        for t in txns:
            names.add(t.id.agent)
            for p in t.parents:
                names.add(p.agent)
            for op in t.ops:
                if isinstance(op, RemoteIns):
                    names.update((op.origin_left.agent,
                                  op.origin_right.agent))
                else:
                    names.add(op.id.agent)
        unknown = {n for n in names if n != "ROOT" and n not in self.table}
        if unknown:
            self._degrade(f"unregistered agents {sorted(unknown)}", counters)
            return
        ins_chars = sum(len(op.ins_content) for t in txns for op in t.ops
                        if isinstance(op, RemoteIns))
        orders = sum(txn_len(t) for t in txns)
        if (int(self.doc.n) + ins_chars > self.doc.capacity
                or int(self.doc.next_order) + orders
                > self.doc.order_capacity):
            self._degrade(
                f"capacity overflow: n {int(self.doc.n)}+{ins_chars} "
                f"vs {self.doc.capacity}, orders {int(self.doc.next_order)}"
                f"+{orders} vs {self.doc.order_capacity}", counters)
            return
        ops, self.assigner = B.compile_remote_txns(
            txns, self.table, assigner=self.assigner, lmax=self.lmax)
        self.doc = F.apply_ops(self.doc, ops)
        counters.incr("device_txns_applied", len(txns))


def span_is_items(doc: ListCRDT, agent_name: str, seq: int,
                  span: int) -> bool:
    """Every (agent, seq .. seq+span) names an existing document ITEM
    — an inserted char, live or tombstoned — not a delete-op's
    consumed seq (which maps to an order but to no body row).

    An assigned order is an item iff it is not a delete-op order, so
    after ``item_orders`` proves the seqs exist this is an O(log n)
    interval-overlap test against the deletes log per chunk — no
    body scan."""
    aid = doc.get_agent_id(agent_name)
    if aid is None or aid == CLIENT_INVALID:
        return False
    io = doc.client_data[aid].item_orders
    del_log = doc.deletes
    remaining, s = span, seq
    while remaining > 0:
        found = io.find(s)
        if found is None:
            return False
        entry, off = found
        take = min(entry.length - off, remaining)
        o = entry.order + off
        ok, idx = del_log.search(o)
        if ok:
            return False  # chunk starts inside a delete-op range
        ents = del_log.entries
        if idx < len(ents) and ents[idx].key < o + take:
            return False  # a delete-op range starts inside the chunk
        s += take
        remaining -= take
    return True


def txn_refs_known(doc: ListCRDT, txn: RemoteTxn) -> bool:
    """Every id a released txn references must resolve at apply time.
    The causal buffer only checks *parents*; a well-formed frame from
    a buggy or malicious peer can still be out of order (after an
    earlier same-agent rejection rolled the watermark back), or
    reference unknown origins, forward/self seqs, or delete-op seqs —
    all of which the oracle hard-asserts on. Callers (the resync
    session's pump loop, the serve batcher's tick) reject
    typed-and-counted instead of crashing.

    Three tiers of reference:
    - the txn itself must be seq-in-order against the DOC watermark;
    - parents are txn ids: they need a seq->order *mapping*
      (seq < watermark) but not a body row (a txn's last op may be a
      delete op);
    - origins and delete targets must name *items*: validated against
      the document body for known history, or against the
      inserted-char intervals of STRICTLY EARLIER ops of this txn."""
    marks = agent_watermarks(doc)
    if txn.id.seq != marks.get(txn.id.agent, 0):
        return False
    own_ins: List = []  # (start, end) insert seq intervals so far

    def parent_known(rid) -> bool:
        if rid.agent == "ROOT":
            return True
        return rid.seq < marks.get(rid.agent, 0)

    def item_known(rid, span=1) -> bool:
        if rid.agent == "ROOT":
            return True
        end = rid.seq + span
        cur = rid.seq
        wm = marks.get(rid.agent, 0)
        if cur < wm:
            lo = min(end, wm) - cur
            if not span_is_items(doc, rid.agent, cur, lo):
                return False
            cur += lo
        if rid.agent != txn.id.agent:
            return cur >= end
        # Remainder must be chars this txn already inserted
        # (intervals ascend and are disjoint by construction).
        for s, e in own_ins:
            if cur >= end:
                break
            if s <= cur < e:
                cur = min(e, end)
        return cur >= end

    if not all(parent_known(p) for p in txn.parents):
        return False
    cursor = txn.id.seq
    for op in txn.ops:
        if isinstance(op, RemoteIns):
            if not (item_known(op.origin_left)
                    and item_known(op.origin_right)):
                return False
            nxt = cursor + len(op.ins_content)
            own_ins.append((cursor, nxt))
            cursor = nxt
        else:
            if not item_known(op.id, op.len):
                return False
            cursor += op.len
    return True


class ResyncSession:
    """One peer endpoint of the resync protocol.

    Drive it with a pump loop: ``poll()`` returns frames to send
    (new history + digest + due re-requests), ``receive(frame)`` ingests
    one delivered frame and returns any response frames (served
    REQUESTs). Both are safe against arbitrary bytes: every rejection is
    typed, counted in ``counters``, and recovered by the digest cycle.
    """

    def __init__(self, doc: ListCRDT, *,
                 max_pending: Optional[int] = None,
                 retry_limit: int = 32,
                 backoff_base: int = 1,
                 backoff_cap: int = 8,
                 digest_every: int = 1,
                 mirror: Optional[DeviceMirror] = None,
                 counters: Optional[Counters] = None,
                 wire: str = "row", tracer=None, recorder=None):
        self.doc = doc
        self.wire = wire
        # Optional ``obs.trace.Tracer``: one ``resync.round`` event per
        # poll that re-requests ranges (logical-tick-stamped, so mesh
        # anti-entropy behavior is reconstructible post-hoc).  The
        # optional ``obs.recorder.FlightRecorder`` dumps a post-mortem
        # when a gap outlives the retry budget (``CausalGapError``).
        self.tracer = tracer
        self.recorder = recorder
        self._encode_txns = codec.txns_encoder(wire)
        # The columnar wire amortizes its name table + column headers
        # across the batch, so it ships far bigger frames; the row wire
        # keeps the PR-1 loss-granularity default.
        self._txns_per_frame = TXNS_PER_FRAME if wire == "row" else 512
        self.buffer = CausalBuffer(max_pending=max_pending)
        self.mirror = mirror
        self.counters = counters if counters is not None else Counters()
        self.retry_limit = retry_limit
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.digest_every = max(1, digest_every)
        self.divergence_detected = False
        self.protocol_error = False
        self._tick = 0
        self._bcast_order = 0
        self._digest_cache = (None, 0)
        # agent -> [attempts, next_due_tick, last_from_seq] for
        # outstanding gap pulls.
        self._requests: Dict[str, List[int]] = {}
        # Latest watermark vector each digest advertised.
        self._peer_marks: Dict[str, int] = {}
        self._sync_watermarks()

    # -- internals ----------------------------------------------------------

    def _apply_released(self, released: List[RemoteTxn]) -> None:
        applied = []
        for txn in released:
            if not txn_refs_known(self.doc, txn):
                self.counters.incr("txns_rejected")
                self.protocol_error = True
                # The release advanced the buffer watermark; undo it so
                # an honest redelivery of this (agent, seq) is accepted
                # rather than trimmed as a duplicate, and the gap stays
                # visible to the digest/re-request cycle. Dependents
                # later in this batch fail the same validation (their
                # refs/parents now read as unknown) and roll back too.
                self.buffer.rollback_watermark(txn.id.agent, txn.id.seq)
                continue
            self.doc.apply_remote_txn(txn)
            applied.append(txn)
        if applied:
            self.counters.incr("txns_applied", len(applied))
            if self.mirror is not None:
                self.mirror.apply(applied, self.counters)

    def _sync_watermarks(self) -> None:
        """Align the buffer with out-of-band document progress (our own
        local edits, or sibling sessions sharing this doc in an N-peer
        mesh) so echoed deliveries dedup and dependents release."""
        self._apply_released(
            self.buffer.advance_watermarks(agent_watermarks(self.doc)))

    def _my_watermark(self, agent: str) -> int:
        return max(self.buffer.watermarks().get(agent, 0),
                   agent_watermarks(self.doc).get(agent, 0))

    def _wanted(self) -> Dict[str, int]:
        """Every (agent -> from_seq) range we currently lack: gaps the
        causal buffer can *see* (blocked pending txns) plus gaps only the
        peer's digest reveals (all frames from an agent dropped)."""
        wants: Dict[str, int] = {}
        for rid in self.buffer.missing():
            wants[rid.agent] = min(wants.get(rid.agent, rid.seq), rid.seq)
        for agent, peer_wm in self._peer_marks.items():
            mine = self._my_watermark(agent)
            if peer_wm > mine:
                wants[agent] = min(wants.get(agent, mine), mine)
        return wants

    # -- protocol pump ------------------------------------------------------

    def _state_digest(self) -> int:
        """``models.sync.state_digest`` cached on (n, next_order): every
        apply/local edit advances next_order, so the O(n) portable hash
        only recomputes when the document actually changed."""
        key = (self.doc.n, self.doc.get_next_order())
        if self._digest_cache[0] != key:
            self._digest_cache = (key, state_digest(self.doc))
        return self._digest_cache[1]

    def poll(self) -> List[bytes]:
        """Advance the logical clock; emit frames owed to the peer."""
        self._tick += 1
        self._sync_watermarks()

        # Gap pulls FIRST: this section can raise CausalGapError, and it
        # must do so before _bcast_order advances — otherwise the history
        # batch built this tick would be skipped forever on the
        # caught-and-redirected recovery path.
        wanted = self._wanted()
        # Gap closed -> retire its backoff schedule.
        for agent in [a for a in self._requests if a not in wanted]:
            del self._requests[agent]
        # Exhaustion pre-scan with NO state mutation: raising mid-loop
        # would burn other agents' attempt counters on requests that are
        # never sent (the frames list is discarded by the raise).
        for agent, from_seq in sorted(wanted.items()):
            entry = self._requests.get(agent)
            if entry is None or from_seq > entry[2]:
                continue  # first ask / new gap: budget (re)starts fresh
            if self._tick >= entry[1] and entry[0] + 1 > self.retry_limit:
                err = CausalGapError(wanted, entry[0])
                if self.recorder is not None:
                    self.recorder.on_failure(
                        "causal-gap", str(err),
                        tick=self._tick,
                        extra={"wanted": dict(wanted),
                               "attempts": entry[0]})
                raise err
        due: Dict[str, int] = {}
        for agent, from_seq in sorted(wanted.items()):
            entry = self._requests.setdefault(
                agent, [0, self._tick, from_seq])
            if from_seq > entry[2]:
                # The watermark moved since the last ask: the peer IS
                # feeding us (a long lossy backfill), this is a new gap —
                # reset the attempt budget AND the backoff deadline so
                # the fresh gap's first ask goes out this tick instead of
                # waiting out the previous gap's capped delay.
                entry[0] = 0
                entry[1] = self._tick
                entry[2] = from_seq
            if self._tick < entry[1]:
                continue
            entry[0] += 1
            delay = min(self.backoff_cap,
                        self.backoff_base * (1 << (entry[0] - 1)))
            entry[1] = self._tick + delay
            due[agent] = from_seq
            self.counters.incr("range_retries")

        frames: List[bytes] = []
        # New history (ours AND merged — peers beyond two hop through us).
        txns = export_txns_since(self.doc, self._bcast_order)
        self._bcast_order = self.doc.get_next_order()
        for i in range(0, len(txns), self._txns_per_frame):
            frame = self._encode_txns(txns[i:i + self._txns_per_frame])
            frames.append(frame)
            self.counters.incr("frames_sent")
            self.counters.incr("wire_txn_bytes_sent", len(frame))

        if self._tick % self.digest_every == 0:
            frames.append(codec.encode_digest(
                agent_watermarks(self.doc), self._state_digest()))
            self.counters.incr("frames_sent")

        if due:
            frames.append(codec.encode_request(due))
            self.counters.incr("frames_sent")
            if self.tracer is not None:
                self.tracer.set_tick(self._tick)
                self.tracer.event("resync.round", wants=len(due))

        self.counters.hiwater("buffer_high_water", self.buffer.high_water)
        return frames

    def receive(self, data: bytes) -> List[bytes]:
        """Ingest one delivered frame; return response frames (if any).

        Corrupt bytes are rejected with a counted ``CodecError`` — never
        an uncaught exception — and the loss is re-covered by the
        digest/request cycle.
        """
        self._sync_watermarks()
        try:
            kind, value, _ = codec.decode_frame(data)
        except CodecError:
            self.counters.incr("frames_rejected")
            return []
        self.counters.incr("frames_received")

        if kind == codec.KIND_TXNS:
            released = self.buffer.add_all(value)
            self._apply_released(released)
            self.counters.hiwater("buffer_high_water", self.buffer.high_water)
            return []

        if kind == codec.KIND_REQUEST:
            txns = export_txns_for_wants(self.doc, value)
            out: List[bytes] = []
            for i in range(0, len(txns), self._txns_per_frame):
                frame = self._encode_txns(txns[i:i + self._txns_per_frame])
                out.append(frame)
                self.counters.incr("frames_sent")
                self.counters.incr("wire_txn_bytes_sent", len(frame))
            self.counters.incr("requests_served")
            return out

        # KIND_DIGEST
        marks, digest = value
        self._peer_marks = dict(marks)
        mine = agent_watermarks(self.doc)
        if marks == mine and digest != self._state_digest():
            # Same op sets, different states: the CRDT convergence
            # contract broke (or local state corrupted). Surface loudly;
            # serving reads from this replica would be silently wrong.
            self.divergence_detected = True
            self.counters.incr("divergence_detected")
        return []

    # -- readback -----------------------------------------------------------

    @property
    def device_doc(self):
        """The serving document for device-accelerated reads: the mirror
        while healthy, the host oracle once degraded (graceful fallback,
        never an assert)."""
        if self.mirror is not None and not self.mirror.degraded:
            return self.mirror.doc
        return self.doc

"""Fault-tolerant replication: wire codec, fault injection, resync sessions.

The reference defines the peer-portable structs but leaves the wire
unfinished ("wire encoding is out of scope", SURVEY §2 L4) and punts on
out-of-order delivery (`doc.rs:246-247` TODO). This package finishes the
peer boundary for the production story (ROADMAP north star): history
crosses the wire as *bytes*, and any byte stream a peer accepts either
converges bit-identically or is rejected with a precise, typed error —
never a crash, never a silent divergence.

- ``codec``   — length-prefixed binary frames for ``RemoteTxn`` batches
  (varint framing, agent-name string table, per-frame CRC32C, format
  version byte) plus the session control frames (REQUEST / DIGEST).
- ``columnar`` — the version-2 TXNS body: per-column delta+RLE LEB128
  chunks with predictive transforms (the automerge binary-format gear);
  ``decode_frame`` negotiates row/columnar on the version byte.
- ``faults``  — deterministic seeded fault injection (drop, duplicate,
  reorder, truncate, bit-flip) for fuzzing the whole stack.
- ``session`` — anti-entropy resync: per-agent watermarks + state
  digests detect gaps and divergence, missing ranges are re-requested
  with capped exponential backoff, the causal buffer is bounded, and
  device-engine overflow degrades to the host oracle instead of
  asserting.
"""
from .codec import (
    CodecError,
    FRAME_VERSION,
    FRAME_VERSION_COLUMNAR,
    KIND_TXNS_MUX,
    WIRE_FORMATS,
    crc32c,
    decode_frame,
    decode_frames,
    encode_digest,
    encode_request,
    encode_txns,
    txns_encoder,
)
from .columnar import (
    encode_mux,
    encode_mux_stream,
    encode_txns_stream,
)
from .columnar import encode_txns as encode_txns_columnar
from .faults import FaultSpec, FaultyChannel
from .session import CausalGapError, DeviceMirror, ResyncSession

__all__ = [
    "CodecError",
    "CausalGapError",
    "DeviceMirror",
    "FaultSpec",
    "FaultyChannel",
    "FRAME_VERSION",
    "FRAME_VERSION_COLUMNAR",
    "KIND_TXNS_MUX",
    "ResyncSession",
    "WIRE_FORMATS",
    "crc32c",
    "decode_frame",
    "decode_frames",
    "encode_digest",
    "encode_mux",
    "encode_mux_stream",
    "encode_request",
    "encode_txns",
    "encode_txns_columnar",
    "encode_txns_stream",
    "txns_encoder",
]
